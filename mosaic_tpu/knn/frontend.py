"""Bucketed ring-expansion KNN frontend with a Voronoi convex fast path.

The batch model (`models/knn.SpatialKNN`, reference
`models/knn/SpatialKNN.scala:28-331`) re-tessellates and re-jits per
call; this frontend holds a :class:`~mosaic_tpu.knn.index.KNNIndex`
resident and answers queries with the serving discipline of
`dispatch.DispatchCore`:

- **Shape discipline.** Every device entry runs at a `BucketLadder`
  rung: cell assignment pads query rows to the row ladder, distance
  evaluation pads (query, candidate) pairs to the pair ladder (oversize
  batches CHUNK at the top rung — they never escalate, so the compile
  signature set is closed under any traffic). Candidate caps don't
  exist here at all: a pair batch is exact by construction, the full
  bucket IS the cap.
- **Compile accounting.** Signatures are `("knn", kind, bucket, mesh,
  index fingerprint)`; :meth:`KNNFrontend.warmup` touches every rung and
  freezes the set, after which any new signature counts as a cold
  compile and fires ``on_cold_compile`` (the serve engine turns that
  into a ``serve_compile`` event — the bench asserts zero).
- **AOT persistence.** With a program store bound, each rung's cell and
  pair executables export via `dispatch.programs` (keys
  ``knn_cells``/``knn_pairs`` under the index fingerprint) and reload on
  relaunch, so a store-backed restart replays with zero compiles.
  Meshed executables bind a device topology the store does not model —
  a meshed frontend refuses the store exactly like the core.
- **Failure domains.** ``knn.expand`` (ring/walk candidate generation),
  ``knn.distance`` (the device pair batch), and ``knn.scatter`` (top-k
  merge) run under `dispatch.guarded_call`: watchdog deadline, transient
  retry, fault-plan injection. Past the retry budget the distance batch
  degrades to the exact f64 host oracle (`knn.oracle`) and the answer is
  flagged :class:`~mosaic_tpu.runtime.errors.DegradedResult` — never
  wrong, never dropped. Expand and scatter are pure functions whose
  results commit only after the guarded call returns, so retries are
  idempotent.

Lanes
-----
``ring`` is the exact iterative lane: grow k-ring(1) then k-loop(i)
shells per query (the batch model's loop, same stop rule: a query rests
once the grid-guaranteed radius ``(it-1)*cell_width`` covers its current
kth distance). ``voronoi`` collapses the loop: walk the precomputed
Voronoi adjacency of convex chip sites (`sql.join.VoronoiTables`) to a
near-nearest site, read ~k exact host distances to bound the kth
neighbour, and dispatch ONE ring cover of grid radius
``ceil(bound/w)+1`` — same pair program, same rungs, same exact answer,
no iteration. Queries the walk cannot bound (fewer than k reachable
convex geoms) fall back to the ring lane per query. Lane choice is the
``knn_lane`` tune knob, routed by the profiler's convex-share statistic
(`tune/recommend`).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..dispatch import (
    BucketLadder,
    ProgramFingerprintMismatch,
    ProgramStoreCorrupt,
    backend_compiles,
    bounded_cache,
    cells_prog,
    guarded_call,
    mesh_key,
    program_key,
    resolve_mesh,
    resolve_program_store,
)
from ..dispatch.programs import deserialize_compiled, serialize_compiled
from ..obs import trace as _trace
from ..runtime import telemetry as _telemetry
from ..runtime.errors import DegradedResult
from ..utils import get_logger
from .index import KNNIndex
from .oracle import host_pair_distances

logger = get_logger(__name__)

#: default pair ladder: 256 covers a handful of interactive queries'
#: first rings, 16k is one comfortable cover dispatch; bigger pair sets
#: chunk at the top rung (signature set stays closed)
DEFAULT_PAIR_LADDER = BucketLadder(min_bucket=256, max_bucket=16384)
#: default row ladder for query cell assignment
DEFAULT_ROW_LADDER = BucketLadder(min_bucket=64, max_bucket=4096)


@dataclasses.dataclass
class KNNAnswer:
    """Served neighbours (`-1`/`inf` pad unfilled slots when the index
    holds fewer than k candidates). :meth:`KNNFrontend.query` returns
    one per row with (k,) arrays; the serve engine's ``submit_knn``
    future resolves to a single batched answer with (n, k) arrays."""

    ids: np.ndarray  # (..., k) int64 candidate rows, rank order
    distance: np.ndarray  # (..., k) f64
    degraded: bool = False
    reason: "str | None" = None


def decode_knn(out: np.ndarray, k: int):
    """Split the wire encoding ``[distances ‖ ids]`` (rows of width 2k,
    the shape KNN answers travel through the mixed-traffic batcher in)
    back into ``(ids int64, dist f64)``."""
    out = np.asarray(out, dtype=np.float64)
    dist = out[..., :k]
    ids = out[..., k : 2 * k].astype(np.int64)
    return ids, dist


# ------------------------------------------------------- device programs


def _point_column(qxy, shift):
    """Synthesize a POINT DeviceGeometry column from (P, 2) coords
    INSIDE the jit — one vertex per ring, closed form (the vertex
    repeated at index ``ring_len``), so the pair kernel sees the exact
    column `pack_to_device` would build for these points and the compile
    signature depends only on P, never on the query values."""
    import jax.numpy as jnp

    from ..core.geometry.device import DeviceGeometry
    from ..core.types import GeometryType

    n = qxy.shape[0]
    verts = jnp.broadcast_to(qxy[:, None, None, :], (n, 1, 2, 2))
    return DeviceGeometry(
        verts=verts,
        ring_len=jnp.ones((n, 1), dtype=jnp.int32),
        ring_is_hole=jnp.zeros((n, 1), dtype=bool),
        n_rings=jnp.ones((n,), dtype=jnp.int32),
        geom_type=jnp.full((n,), int(GeometryType.POINT), dtype=jnp.int32),
        shift=shift,
    )


@bounded_cache("knn_point_pairs", 1)
def _point_pair_prog():
    """The ONE jitted (query point, candidate row) distance program all
    frontends share — jax's own trace cache keys the pair-bucket shapes,
    the frontend's ladder bounds how many there are. Lives in the
    dispatch cache registry (name ``knn_point_pairs``) so
    `cache_stats`/`clear_caches` cover it."""
    import jax

    from ..core.geometry.device import take_rows
    from ..functions.geometry import _distance_dense, _vmap_pair

    def run(dcs, qxy, crows):
        dq = _point_column(qxy, dcs.shift)
        return _vmap_pair(_distance_dense, dq, take_rows(dcs, crows))

    return jax.jit(run)


@bounded_cache("knn_point_pairs_sharded", 8)
def _sharded_point_pairs(mesh):
    """Meshed variant: candidate column replicated, query coords and
    candidate rows sharded over the pair axis (the `parallel/dist_knn`
    layout — embarrassingly parallel, no collectives)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..core.geometry.device import take_rows
    from ..functions.geometry import _distance_dense, _vmap_pair
    from ..parallel._compat import shard_map as _shard_map
    from ..parallel.dist_overlay import geom_specs

    row = P(mesh.axis_names)
    rep = geom_specs(P())

    def step(dcs, qxy, crows):
        dq = _point_column(qxy, dcs.shift)
        return _vmap_pair(_distance_dense, dq, take_rows(dcs, crows))

    return jax.jit(
        _shard_map(
            step, mesh=mesh, in_specs=(rep, row, row), out_specs=row
        )
    )


def _merge_topk(dist, cid, qi, ci, d, k):
    """Pure top-k merge: fold (query, candidate, distance) triples into
    the running (dist, cid) state, ranked lexicographically by
    ``(distance, candidate_id)`` — the oracle's tie rule, and equal to
    the batch model's insertion merge on tie-free data. Pairs are
    deduplicated upstream (``seen`` sets), so a candidate can never
    appear twice in one row."""
    dist = dist.copy()
    cid = cid.copy()
    for i in np.unique(qi):
        m = qi == i
        cd = np.concatenate([dist[i], d[m]])
        cc = np.concatenate([cid[i], ci[m]])
        take = np.lexsort((cc, cd))[:k]
        dist[i] = cd[take]
        cid[i] = cc[take]
    return dist, cid


class KNNFrontend:
    """Online KNN over a resident :class:`KNNIndex` (see module doc)."""

    def __init__(
        self,
        kx: KNNIndex,
        *,
        lane: str = "ring",
        pair_ladder: "BucketLadder | None" = None,
        row_ladder: "BucketLadder | None" = None,
        max_iterations: int = 64,
        mesh=None,
        program_store=None,
        on_cold_compile=None,
    ):
        if lane not in ("ring", "voronoi"):
            raise ValueError(f"unknown knn lane {lane!r}")
        if kx.n == 0:
            raise ValueError(
                "KNNFrontend needs a non-empty candidate index (warmup "
                "dispatches pair batches against candidate row 0)"
            )
        self.kx = kx
        self.lane = lane
        self.pair_ladder = pair_ladder or DEFAULT_PAIR_LADDER
        self.row_ladder = row_ladder or DEFAULT_ROW_LADDER
        self.max_iterations = int(max_iterations)
        self.mesh = resolve_mesh(mesh)
        if self.mesh is not None:
            for b in self.pair_ladder.buckets:
                if b % self.mesh.size:
                    raise ValueError(
                        f"pair bucket {b} does not divide over the "
                        f"{self.mesh.size}-device mesh"
                    )
        self._dtype = np.dtype(kx.dc.verts.dtype)
        self._signatures: set = set()
        self._warmed: "frozenset | None" = None
        self._cold_compiles = 0
        self._on_cold_compile = on_cold_compile
        # AOT persistence mirrors DispatchCore: explicit arg beats the
        # MOSAIC_PROGRAM_STORE env knob; a meshed frontend refuses the
        # store (sharded executables bind the device topology).
        self._programs = resolve_program_store(program_store)
        if self._programs is not None and self.mesh is not None:
            _telemetry.record(
                "program_store_refused", reason="mesh",
                devices=self.mesh.size,
            )
            self._programs = None
        self._aot: dict = {}  # (kind, bucket) -> compiled | None
        self.aot_stats = {"loaded": 0, "exported": 0, "fallback": 0}
        self.stats = {
            "queries": 0,
            "pairs": 0,
            "pairs_padded": 0,
            "iterations": 0,
            "degraded": 0,
            "lane_ring": 0,
            "lane_voronoi": 0,
            "voronoi_fallback": 0,
        }

    # ------------------------------------------------------- accounting

    @property
    def cold_compiles(self) -> int:
        """Signatures first seen AFTER :meth:`warmup` froze the set."""
        return self._cold_compiles

    def signature_count(self) -> int:
        return len(self._signatures)

    def freeze(self) -> None:
        self._warmed = frozenset(self._signatures)

    def _note(self, kind: str, bucket: int) -> bool:
        sig = (
            "knn", kind, int(bucket), mesh_key(self.mesh),
            self.kx.fingerprint,
        )
        if sig in self._signatures:
            return False
        self._signatures.add(sig)
        if self._warmed is not None:
            self._cold_compiles += 1
            if self._on_cold_compile is not None:
                self._on_cold_compile(bucket, len(self._signatures))
            else:
                _telemetry.record(
                    "knn_compile", kind=kind, bucket=bucket,
                    signatures=len(self._signatures),
                )
        return True

    # ----------------------------------------------------- AOT programs

    def _aot_program(self, kind: str, bucket: int):
        key = (kind, bucket)
        if key in self._aot:
            return self._aot[key]
        with _trace.span("knn.aot", kind=kind, bucket=bucket):
            try:
                fn = self._load_or_export(kind, bucket)
            except Exception as e:  # lint: broad-except-ok (AOT is an optimization: ANY serialization failure must degrade to plain compilation, not take down the frontend)
                _telemetry.record(
                    "program_store_fallback", bucket=bucket,
                    error=repr(e)[:200],
                )
                self.aot_stats["fallback"] += 1
                fn = None
        self._aot[key] = fn
        return fn

    def _load_or_export(self, kind: str, bucket: int):
        import jax as _jax

        fp = self.kx.fingerprint
        if kind == "cells":
            in_dtype = _jax.dtypes.canonicalize_dtype(np.float64)
            proto = _jax.ShapeDtypeStruct((bucket, 2), in_dtype)
            cfn = cells_prog(
                self.kx.index_system, self.kx.resolution, "cells"
            )
            aval = _jax.eval_shape(cfn, proto)
            return self._one_program(
                program_key(
                    fp, "knn_cells", bucket=bucket,
                    resolution=int(self.kx.resolution),
                ),
                lambda: cfn.lower(proto).compile(),
                (proto,), aval,
                meta={"kind": "knn_cells", "bucket": bucket},
            )
        qproto = _jax.ShapeDtypeStruct((bucket, 2), self._dtype)
        rdtype = _jax.dtypes.canonicalize_dtype(np.int64)
        rproto = _jax.ShapeDtypeStruct((bucket,), rdtype)
        prog = _point_pair_prog()
        aval = _jax.eval_shape(prog, self.kx.dc, qproto, rproto)
        return self._one_program(
            program_key(
                fp, "knn_pairs", bucket=bucket, dtype=str(self._dtype),
            ),
            lambda: prog.lower(self.kx.dc, qproto, rproto).compile(),
            (self.kx.dc, qproto, rproto), aval,
            meta={"kind": "knn_pairs", "bucket": bucket},
        )

    def _one_program(self, key, compile_fn, example_args, out_aval, meta):
        payload = None
        try:
            payload = self._programs.load(key)
        except (ProgramStoreCorrupt, ProgramFingerprintMismatch):
            pass  # typed telemetry already recorded by the store
        if payload is not None:
            fn = deserialize_compiled(payload, example_args, out_aval)
            self.aot_stats["loaded"] += 1
            return fn
        compiled = compile_fn()
        self._programs.save(key, serialize_compiled(compiled), meta=meta)
        self.aot_stats["exported"] += 1
        return compiled

    # ---------------------------------------------------- device entries

    def _cells_bucket(self, padded: np.ndarray) -> np.ndarray:
        """One full-bucket cell assignment (the shared `cells_prog`
        executable, AOT-loaded when a store is bound)."""
        import jax.numpy as jnp

        b = padded.shape[0]
        self._note("cells", b)
        dev = jnp.asarray(padded)
        fn = None
        if self._programs is not None:
            fn = self._aot_program("cells", b)
        if fn is None:
            fn = cells_prog(
                self.kx.index_system, self.kx.resolution, "cells"
            )
        return np.asarray(fn(dev))

    def _assign_cells(self, pts: np.ndarray) -> np.ndarray:
        """(n, 2) raw query coords -> (n,) int64 seed cells, chunked
        through the row ladder."""
        n = pts.shape[0]
        out = np.empty(n, dtype=np.int64)
        step = self.row_ladder.max_bucket
        for c0 in range(0, n, step):
            chunk = pts[c0 : c0 + step]
            m = chunk.shape[0]
            padded, _ = self.row_ladder.pad(chunk)
            cells = self._cells_bucket(padded)
            out[c0 : c0 + m] = cells[:m].astype(np.int64)
        return out

    def _pair_bucket(self, qxy: np.ndarray, rows: np.ndarray) -> np.ndarray:
        """One padded pair dispatch: (m, 2) shifted device-dtype query
        coords × (m,) candidate rows -> (m,) f64 distances."""
        import jax.numpy as jnp

        m = qxy.shape[0]
        b = self.pair_ladder.bucket_for(m)
        if b > m:
            # pad pairs repeat the first pair (inert, sliced off below)
            qxy = np.concatenate(
                [qxy, np.broadcast_to(qxy[:1], (b - m, 2))]
            )
            rows = np.concatenate(
                [rows, np.broadcast_to(rows[:1], (b - m,))]
            )
        self._note("pairs", b)
        self.stats["pairs"] += m
        self.stats["pairs_padded"] += b
        with _trace.span("knn.pairs", bucket=b, pairs=m):
            qdev = jnp.asarray(np.ascontiguousarray(qxy), dtype=self._dtype)
            rdev = jnp.asarray(np.ascontiguousarray(rows, dtype=np.int64))
            if self.mesh is not None:
                vals = _sharded_point_pairs(self.mesh)(
                    self.kx.dc, qdev, rdev
                )
            else:
                fn = None
                if self._programs is not None:
                    fn = self._aot_program("pairs", b)
                if fn is None:
                    fn = _point_pair_prog()
                vals = fn(self.kx.dc, qdev, rdev)
        return np.asarray(vals, dtype=np.float64)[:m]

    def _pair_values(self, qsd, qi, ci) -> np.ndarray:
        """All (query, candidate) pair distances, chunked at the top
        pair rung (chunking keeps the signature set closed — an
        arbitrarily large cover never invents a new shape)."""
        total = qi.shape[0]
        out = np.empty(total, dtype=np.float64)
        step = self.pair_ladder.max_bucket
        for c0 in range(0, total, step):
            c1 = min(total, c0 + step)
            out[c0:c1] = self._pair_bucket(qsd[qi[c0:c1]], ci[c0:c1])
        return out

    def _distances(self, qs64, qsd, qi, ci, default_s):
        """The ``knn.distance`` failure domain: device pair batch with
        watchdog + retry; past the budget the batch degrades to the
        exact f64 host oracle (`DegradedResult`, never dropped)."""
        if not qi.size:
            return np.zeros(0)
        return guarded_call(
            "knn.distance",
            lambda: self._pair_values(qsd, qi, ci),
            default_s=default_s,
            fallback=lambda: host_pair_distances(qs64, self.kx, qi, ci),
        )

    # ------------------------------------------------------- ring lane

    def _ring_lane(self, pts, k, default_s):
        """Exact iterative lane — the batch model's loop
        (`models/knn.SpatialKNN.transform`) with serve discipline."""
        kx = self.kx
        n = pts.shape[0]
        qs64 = pts - kx.shift
        qsd = qs64.astype(self._dtype, copy=False)
        dist = np.full((n, k), np.inf)
        cid = np.full((n, k), -1, dtype=np.int64)
        seen: list = [set() for _ in range(n)]
        seeds = self._assign_cells(pts)
        w = kx.cell_width
        degraded = None
        for it in range(1, self.max_iterations + 1):
            # the batch model's rest criterion: a query rests once it
            # holds k matches AND the grid-guaranteed covered radius
            # (it-1)*w reaches its kth distance; candidate exhaustion
            # rests it early (pure optimization — no candidates remain)
            active = [
                i
                for i in range(n)
                if len(seen[i]) < kx.n
                and (
                    int((cid[i] >= 0).sum()) < k
                    or (it - 1) * w < dist[i, k - 1]
                )
            ]
            if not active:
                break
            self.stats["iterations"] += 1

            def expand():
                # pure: fresh (query, sorted candidate rows) pairs; the
                # ``seen`` commit happens AFTER the guarded call returns
                # so a transient-fault retry re-reads identical state
                found = []
                for i in active:
                    if it == 1:
                        cells = np.asarray(
                            kx.index_system.k_ring(seeds[i : i + 1], 1)
                        )
                    else:
                        cells = np.asarray(
                            kx.index_system.k_loop(seeds[i : i + 1], it)
                        )
                    cells = np.unique(cells[cells >= 0])
                    rows = kx.candidate_rows(cells)
                    fresh = sorted(set(rows.tolist()) - seen[i])
                    if fresh:
                        found.append((i, fresh))
                return found

            with _telemetry.timed(
                "knn_stage", stage="expand", iteration=it,
                queries=len(active),
            ):
                found = guarded_call("knn.expand", expand)
            qi_l, ci_l = [], []
            for i, fresh in found:
                seen[i].update(fresh)
                qi_l.extend([i] * len(fresh))
                ci_l.extend(fresh)
            qi = np.asarray(qi_l, dtype=np.int64)
            ci = np.asarray(ci_l, dtype=np.int64)
            if not qi.size:
                continue
            with _telemetry.timed(
                "knn_stage", stage="distance", pairs=int(qi.size),
            ):
                d = self._distances(qs64, qsd, qi, ci, default_s)
            if isinstance(d, DegradedResult):
                degraded = degraded or d
                d = np.asarray(d)
            with _telemetry.timed(
                "knn_stage", stage="scatter", pairs=int(qi.size),
            ):
                dist, cid = guarded_call(
                    "knn.scatter",
                    lambda: _merge_topk(dist, cid, qi, ci, d, k),
                )
        return dist, cid, degraded

    # ---------------------------------------------------- voronoi lane

    def _walk_rows(self, qv: np.ndarray, k: int):
        """Greedy walk on the Voronoi adjacency to a locally nearest
        convex site, then breadth-first neighbour collection until k
        distinct candidate geoms are reachable. Returns (rows, ok)."""
        vt = self.kx.voronoi
        sites, adj = vt.sites, vt.adjacency
        cv = sites.shape[0]
        stride = max(1, cv // 64)
        probe = np.arange(0, cv, stride)
        d2 = np.sum((sites[probe] - qv) ** 2, axis=1)
        cur = int(probe[int(np.argmin(d2))])
        curd = float(np.sum((sites[cur] - qv) ** 2))
        while True:
            nbrs = adj[cur]
            nbrs = nbrs[nbrs >= 0]
            if not nbrs.size:
                break
            nd = np.sum((sites[nbrs] - qv) ** 2, axis=1)
            j = int(np.argmin(nd))
            if nd[j] < curd:
                cur, curd = int(nbrs[j]), float(nd[j])
            else:
                break
        rows = {int(vt.geom[cur])}
        seen_sites = {cur}
        frontier = [cur]
        while frontier and len(rows) < k:
            nxt = []
            for s in frontier:
                for t in adj[s]:
                    t = int(t)
                    if t < 0 or t in seen_sites:
                        continue
                    seen_sites.add(t)
                    nxt.append(t)
                    rows.add(int(vt.geom[t]))
            frontier = nxt
        return np.fromiter(sorted(rows), dtype=np.int64), len(rows) >= k

    def _voronoi_lane(self, pts, k, default_s):
        """One-shot exact lane: the walk's kth-distance bound collapses
        ring iteration into a single guaranteed cover dispatch (grid
        radius r satisfies (r-1)*w >= bound, the same guarantee the
        iterative stop rule relies on — so the answer is the ring
        lane's answer, computed in one device round-trip)."""
        kx = self.kx
        vt = kx.voronoi
        n = pts.shape[0]
        qs64 = pts - kx.shift
        qsd = qs64.astype(self._dtype, copy=False)
        qv = pts - vt.shift
        w = kx.cell_width
        dist = np.full((n, k), np.inf)
        cid = np.full((n, k), -1, dtype=np.int64)
        degraded = None

        def expand():
            # pure: per-query cover pairs + the indices the walk could
            # not bound (they take the iterative lane below)
            seeds = self._assign_cells(pts)
            pairs, fallback = [], []
            for i in range(n):
                rows, ok = self._walk_rows(qv[i], k)
                if not ok:
                    fallback.append(i)
                    continue
                ds = host_pair_distances(
                    qs64, kx, np.full(rows.shape[0], i, np.int64), rows
                )
                bound = float(np.partition(ds, k - 1)[k - 1])
                r = int(np.ceil(bound / w)) + 1 if bound > 0 else 1
                if r > self.max_iterations:
                    fallback.append(i)
                    continue
                cells = np.asarray(
                    kx.index_system.k_ring(seeds[i : i + 1], r)
                )
                cells = np.unique(cells[cells >= 0])
                cover = kx.candidate_rows(cells)
                pairs.append((i, np.sort(cover)))
            return pairs, fallback

        with _telemetry.timed(
            "knn_stage", stage="expand", lane="voronoi", queries=n,
        ):
            pairs, fallback = guarded_call("knn.expand", expand)
        self.stats["voronoi_fallback"] += len(fallback)
        qi = np.concatenate(
            [np.full(r.shape[0], i, np.int64) for i, r in pairs]
        ) if pairs else np.zeros(0, dtype=np.int64)
        ci = np.concatenate([r for _, r in pairs]) if pairs else np.zeros(
            0, dtype=np.int64
        )
        if qi.size:
            with _telemetry.timed(
                "knn_stage", stage="distance", lane="voronoi",
                pairs=int(qi.size),
            ):
                d = self._distances(qs64, qsd, qi, ci, default_s)
            if isinstance(d, DegradedResult):
                degraded = d
                d = np.asarray(d)
            with _telemetry.timed(
                "knn_stage", stage="scatter", lane="voronoi",
                pairs=int(qi.size),
            ):
                dist, cid = guarded_call(
                    "knn.scatter",
                    lambda: _merge_topk(dist, cid, qi, ci, d, k),
                )
        if fallback:
            sub = np.asarray(fallback, dtype=np.int64)
            fdist, fcid, fdeg = self._ring_lane(
                pts[sub], k, default_s
            )
            dist[sub] = fdist
            cid[sub] = fcid
            degraded = degraded or fdeg
        return dist, cid, degraded

    # --------------------------------------------------------- serving

    def dispatch(self, points: np.ndarray, k: int, default_s=None):
        """Answer a batch: (n, 2) raw query coords -> ((n, 2k) f64 wire
        rows ``[distances ‖ ids]``, pair-occupancy). Degraded batches
        come back as :class:`DegradedResult` (values exact — the host
        oracle computed them)."""
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        pts = np.asarray(points, dtype=np.float64)
        n = pts.shape[0]
        if n == 0:
            return np.zeros((0, 2 * k)), 1.0
        p0, b0 = self.stats["pairs"], self.stats["pairs_padded"]
        lane = (
            "voronoi"
            if self.lane == "voronoi" and self.kx.voronoi is not None
            else "ring"
        )
        with _trace.span("knn.dispatch", rows=n, k=k, lane=lane):
            if lane == "voronoi":
                dist, cid, deg = self._voronoi_lane(pts, k, default_s)
            else:
                dist, cid, deg = self._ring_lane(pts, k, default_s)
        self.stats["queries"] += n
        self.stats[f"lane_{lane}"] += n
        out = np.empty((n, 2 * k))
        out[:, :k] = dist
        out[:, k:] = cid.astype(np.float64)
        padded = self.stats["pairs_padded"] - b0
        occupancy = (self.stats["pairs"] - p0) / padded if padded else 1.0
        if deg is not None:
            self.stats["degraded"] += n
            return (
                DegradedResult.wrap(
                    out, reason=deg.reason, attempts=deg.attempts
                ),
                occupancy,
            )
        return out, occupancy

    def query(self, points: np.ndarray, k: int) -> "list[KNNAnswer]":
        """Direct (engine-less) entry: one :class:`KNNAnswer` per row."""
        out, _ = self.dispatch(points, k)
        degraded = isinstance(out, DegradedResult)
        reason = out.reason if degraded else None
        ids, dist = decode_knn(np.asarray(out), k)
        return [
            KNNAnswer(
                ids=ids[i], distance=dist[i], degraded=degraded,
                reason=reason,
            )
            for i in range(ids.shape[0])
        ]

    def warmup(self) -> dict:
        """Touch every (kind, rung) pair so serving can only replay:
        compiles (or AOT loads) every cell and pair program, then
        freezes the signature set — any later signature is a cold
        compile and fires ``on_cold_compile``."""
        c0 = backend_compiles()
        with _trace.span("knn.warmup"):
            for b in self.row_ladder.buckets:
                with _telemetry.timed(
                    "knn_stage", stage="warmup", kind="cells", bucket=b,
                ):
                    self._cells_bucket(np.zeros((b, 2)))
            for b in self.pair_ladder.buckets:
                with _telemetry.timed(
                    "knn_stage", stage="warmup", kind="pairs", bucket=b,
                ):
                    self._pair_bucket(
                        np.zeros((b, 2), dtype=self._dtype),
                        np.zeros(b, dtype=np.int64),
                    )
        self.freeze()
        c1 = backend_compiles()
        report = {
            "signatures": len(self._signatures),
            "row_buckets": len(self.row_ladder.buckets),
            "pair_buckets": len(self.pair_ladder.buckets),
            "backend_compiles": (
                c1 - c0 if c0 is not None and c1 is not None else None
            ),
            "aot": dict(self.aot_stats),
        }
        _telemetry.record("knn_warmup", **report)
        return report

    def metrics(self) -> dict:
        return {
            "knn_queries": self.stats["queries"],
            "knn_pairs": self.stats["pairs"],
            "knn_pair_occupancy": (
                self.stats["pairs"] / self.stats["pairs_padded"]
                if self.stats["pairs_padded"]
                else None
            ),
            "knn_iterations": self.stats["iterations"],
            "knn_degraded": self.stats["degraded"],
            "knn_lane_ring": self.stats["lane_ring"],
            "knn_lane_voronoi": self.stats["lane_voronoi"],
            "knn_voronoi_fallback": self.stats["voronoi_fallback"],
            "knn_signatures": len(self._signatures),
            "knn_cold_compiles": self._cold_compiles,
            "knn_aot": dict(self.aot_stats),
        }
