"""Brute-force f64 host oracle for served KNN — bit-identical to the
device path by construction.

The device program evaluates `functions.geometry._distance_dense` on the
shifted candidate column: three masked squared-distance terms
(vertex→segment both ways, vertex→vertex), ONE ``sqrt`` at the end, and
a containment override to 0 via even-odd ray crossing
(`core/geometry/predicates.py:137-211`). This module mirrors those exact
expressions in numpy f64 over the :class:`~mosaic_tpu.knn.index.
HostCandidates` twin — same shifted frame, same operation order — the
`sql.join.HostRecheck` idiom that lets serve tests assert
``assert_array_equal`` (not allclose) against the oracle.

A query is a POINT column row on device: its ring contributes no edges
(`device.edges` type mask), so only the vertex(query)→segment(candidate)
and vertex→vertex terms are live, and containment reduces to the parity
test of the query point against the candidate's closed polygon rings.
"""

from __future__ import annotations

import numpy as np

_BIG = 1e30


def _point_seg_dist2(p: np.ndarray, a: np.ndarray, b: np.ndarray):
    """numpy twin of `predicates._point_seg_dist2` (squared distance
    from point ``p`` (2,) to segments (a, b) (E, 2))."""
    ab = b - a
    ap = p - a
    denom = np.sum(ab * ab, axis=-1)
    t = np.sum(ap * ab, axis=-1) / np.where(denom == 0, 1.0, denom)
    t = np.clip(t, 0.0, 1.0)
    proj = a + t[..., None] * ab
    d = p - proj
    return np.sum(d * d, axis=-1)


def _contains(p: np.ndarray, poly_edges) -> bool:
    """numpy twin of `predicates.crossing_number` parity (even-odd)."""
    if poly_edges is None:
        return False
    a, b = poly_edges
    if not a.shape[0]:
        return False
    px, py = p[0], p[1]
    ay, by = a[:, 1], b[:, 1]
    ax, bx = a[:, 0], b[:, 0]
    straddle = (ay > py) != (by > py)
    denom = by - ay
    denom = np.where(denom == 0, 1.0, denom)
    xcross = ax + (py - ay) * (bx - ax) / denom
    hit = straddle & (px < xcross)
    return (int(hit.sum()) & 1) == 1


def host_distance(qs: np.ndarray, host, g: int) -> float:
    """Exact f64 distance from ONE shifted query point to candidate
    ``g`` — the same value (same bits) the device pair program
    computes."""
    ea, eb = host.edges[g]
    if ea.shape[0]:
        d_ab = float(np.min(_point_seg_dist2(qs, ea, eb)))
    else:
        d_ab = _BIG
    v = host.verts[g]
    if v.shape[0]:
        dv = float(np.min(np.sum((qs - v) ** 2, axis=-1)))
    else:
        dv = _BIG
    d = np.sqrt(min(d_ab, dv))
    if _contains(qs, host.poly_edges[g]):
        return 0.0
    return float(d)


def host_pair_distances(
    qs: np.ndarray, kx, qi: np.ndarray, ci: np.ndarray
) -> np.ndarray:
    """(P,) exact f64 distances for (query, candidate) pairs —
    ``qs`` are SHIFTED query coordinates (``raw - kx.shift``). The
    frontend's degradation fallback and the walk-bound evaluator."""
    out = np.empty(qi.shape[0], dtype=np.float64)
    for p in range(qi.shape[0]):
        out[p] = host_distance(qs[qi[p]], kx.host, int(ci[p]))
    return out


def brute_force_knn(queries: np.ndarray, kx, k: int):
    """Exhaustive exact top-k over ALL candidates per query.

    Returns ``(ids (n, k) int64, dist (n, k) f64)`` ranked by
    ``(distance, candidate_id)`` lexicographically — the tie rule the
    served merge uses, so on tie-free data this equals batch
    `SpatialKNN` bit-for-bit. Unfilled slots (k > candidates) hold
    ``-1`` / ``inf``.
    """
    q = np.asarray(queries, dtype=np.float64)
    n, m = q.shape[0], kx.n
    qs = q - kx.shift
    ids = np.full((n, k), -1, dtype=np.int64)
    dist = np.full((n, k), np.inf)
    kk = min(k, m)
    for i in range(n):
        d = np.array(
            [host_distance(qs[i], kx.host, g) for g in range(m)]
        )
        order = np.lexsort((np.arange(m), d))[:kk]
        ids[i, :kk] = order
        dist[i, :kk] = d[order]
    return ids, dist
