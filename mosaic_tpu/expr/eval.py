"""Execute compiled expression trees through the dispatch core's
guarded path.

`map_zonal` is the fused twin of `ZonalEngine.zones`/`grid`: per tile
the zone segments come from the SAME probe machinery (device PIP probe
+ epsilon-band exact host re-join — pixels exactly on zone edges are
patched before the fold), then ONE fused program reads the raw band
stack and emits per-segment stats. Each tile dispatch runs under
``guarded_call("expr.map", ...)`` so watchdog, transient retry, and f64
host-oracle degradation (`expr.host_oracle.host_expr_tile_partial`,
bit-identical by construction) come for free — the composition the
lint rule ``dispatch-adoption`` pins to `dispatch/core.py`.

The fold lane is always the f64 segment fold regardless of the
engine's ``lane`` — the expression layer's contract is bit-identity
with the numpy-f64 interpreter, which the f32 Pallas lane cannot hold
on arbitrary band math.
"""

from __future__ import annotations

import time

import numpy as np

from ..dispatch import core as _dispatch
from ..obs import trace as _trace
from ..raster.tiles import plan_tiles, stack_tiles
from ..raster.zonal import ZonalResult, _result_from_dict, host_tile_centers
from ..runtime import telemetry as _telemetry
from ..runtime.errors import RetryExhausted
from . import ast, compile as _compile

__all__ = ["map_join", "map_pixels", "map_zonal", "warmup_expr"]


def _stack_bands(raster, plan, bands):
    """((T, B, P) f64 values, (T, B, P) bool mask) — per-band
    `stack_tiles` (pad ∧ not-nodata ∧ not-NaN mask, zeros at invalid)
    stacked in sorted band order, the layout the programs consume."""
    th, tw = plan.shape
    p = th * tw
    vals = np.zeros((plan.ntiles, len(bands), p), np.float64)
    mask = np.zeros((plan.ntiles, len(bands), p), bool)
    for r, b in enumerate(bands):
        v, m = stack_tiles(raster, plan, b, dtype=np.float64)
        vals[:, r, :] = v.reshape(plan.ntiles, p)
        mask[:, r, :] = m.reshape(plan.ntiles, p)
    return vals, mask


def _acc_name(engine) -> str:
    return str(np.dtype(engine.acc_dtype).name)


def map_zonal(
    engine, expr: ast.Expr, raster, *,
    tile=None, by: "str | None" = None,
    watchdog_default_s: float = 600.0, retry_policy=None,
) -> ZonalResult:
    """Fold an expression into vector zones or grid cells: one fused
    device program per tile bucket, per-zone results bit-identical to
    the staged `rst_*`/zonal sequence AND the f64 host oracle."""
    value, kind, term_by, _stats = ast.terminal_of(expr)
    if kind != "zonal":
        raise ValueError(
            "map_zonal needs a zonal terminal (or a bare value tree) — "
            "use map_join for join terminals"
        )
    by = by or term_by
    has_zones = engine.chip_index is not None
    if by == "zones" and not has_zones:
        raise ValueError(
            "ZonalEngine was built without a chip_index — zones folds "
            "need the vector side"
        )
    ast.validate(expr, raster.num_bands, has_zones=has_zones, by=by)
    plan = plan_tiles(raster, tile)
    th, tw = plan.shape
    gt6 = np.asarray(plan.gt, np.float64)
    bands = ast.bands_of(value)
    vals, mask = _stack_bands(raster, plan, bands)
    acc = _acc_name(engine)
    num_segments = engine.num_zones if by == "zones" else th * tw
    prog = _compile.zonal_program(
        value, th, tw, num_segments, acc,
        engine.index_system, engine.resolution,
    )
    sig = _compile.signature_of(
        value, th, tw, num_segments, acc,
        engine.index_system, engine.resolution, engine.mesh,
    )
    host = getattr(engine, "_host", None)

    g = engine.num_zones
    cnt_acc = np.zeros(g, np.int64)
    sum_acc = np.zeros(g, np.float64)
    min_acc = np.full(g, np.inf)
    max_acc = np.full(g, -np.inf)
    merged: dict = {}
    degraded = 0
    t0 = time.perf_counter()
    with _trace.span(
        "expr.map", mode=by, ntiles=plan.ntiles, bands=len(bands),
        segments=num_segments,
    ):
        for t in range(plan.ntiles):
            uniq = None
            if by == "zones":
                geom = engine._tile_zone_rows(plan, t)
                seg = np.where(geom >= 0, geom, -1).astype(np.int32)
            else:
                cells = np.asarray(
                    engine._assign(gt6, plan.origins[t], th, tw)
                )
                uniq, inv = np.unique(cells, return_inverse=True)
                seg = inv.astype(np.int32)

            def dispatch(ti=t, seg_t=seg):
                return _compile.run_zonal(
                    prog, sig, gt6, plan.origins[ti],
                    vals[ti], mask[ti], seg_t,
                )

            try:
                cnt, s, mn, mx = _dispatch.guarded_call(
                    "expr.map", dispatch,
                    default_s=watchdog_default_s, policy=retry_policy,
                )
            except RetryExhausted as e:
                _telemetry.record(
                    "degraded", label="expr.map", tile=t,
                    error=type(e).__name__,
                )
                degraded += 1
                pts = host_tile_centers(plan, t)
                part = _compile_host_partial(
                    value, vals[t], mask[t], pts, engine, by,
                    num_segments,
                )
                if by == "zones":
                    cnt, s, mn, mx = part
                else:
                    for k, row in part.items():
                        _merge_row(merged, int(k), row)
                    continue
            if by == "zones":
                cnt = np.asarray(cnt).astype(np.int64)
                live = cnt > 0
                cnt_acc += cnt
                sum_acc = sum_acc + np.asarray(s)  # tile-order left fold
                mn = np.asarray(mn, np.float64)
                mx = np.asarray(mx, np.float64)
                min_acc[live] = np.minimum(min_acc[live], mn[live])
                max_acc[live] = np.maximum(max_acc[live], mx[live])
            else:
                cnt = np.asarray(cnt)[: uniq.size]
                s = np.asarray(s)[: uniq.size]
                mn = np.asarray(mn)[: uniq.size]
                mx = np.asarray(mx)[: uniq.size]
                for k, c, sv, mnv, mxv in zip(uniq, cnt, s, mn, mx):
                    if int(c) == 0:
                        continue  # only invalid pixels touched the cell
                    _merge_row(merged, int(k), [int(c), sv, mnv, mxv])
    seconds = time.perf_counter() - t0
    _telemetry.record(
        "expr_stage", stage="map", seconds=round(seconds, 6),
        mode=by, ntiles=plan.ntiles, bands=len(bands),
        segments=num_segments, pixels=plan.pixels,
        pixels_per_sec=round(plan.pixels / max(seconds, 1e-9), 1),
        degraded=degraded,
    )
    if by == "grid":
        return _result_from_dict(merged, band=0)
    live = cnt_acc > 0
    return ZonalResult(
        keys=np.nonzero(live)[0].astype(np.int64),
        count=cnt_acc[live],
        sum=sum_acc[live].astype(np.float64),
        min=min_acc[live],
        max=max_acc[live],
        band=0,
        pixels=int(cnt_acc.sum()),
    )


def _merge_row(merged: dict, k: int, row):
    have = merged.get(k)
    if have is None:
        merged[k] = [int(row[0]), row[1], row[2], row[3]]
    else:
        have[0] += int(row[0])
        have[1] += row[1]  # left fold in tile order
        have[2] = min(have[2], row[2])
        have[3] = max(have[3], row[3])


def _compile_host_partial(value, vals_t, mask_t, pts, engine, by,
                          num_segments):
    from .host_oracle import host_expr_tile_partial

    return host_expr_tile_partial(
        value, vals_t, mask_t, pts,
        index_system=engine.index_system,
        resolution=engine.resolution,
        host=getattr(engine, "_host", None),
        num_segments=num_segments, by=by,
    )


def map_pixels(
    expr: ast.Expr, raster, *, tile=None,
    index_system=None, resolution=None, seg_of=None,
) -> "tuple[np.ndarray, np.ndarray]":
    """Evaluate a bare value tree per pixel: ((H, W) f64 values,
    (H, W) bool valid) stitched from one fused per-pixel program per
    tile bucket. Zone nodes need ``seg_of`` (tile → (P,) zone rows);
    `CellOf` needs (index_system, resolution)."""
    if isinstance(expr, ast.Zonal):
        raise ValueError(
            "map_pixels evaluates value trees — the zonal terminal "
            "belongs to ZonalEngine.map"
        )
    value, _kind, _by, _stats = ast.terminal_of(expr)
    if ast.uses_cells(value) and (index_system is None or resolution is None):
        raise ValueError(
            "cell_of() needs index_system and resolution (session "
            "context for rst_mapbands)"
        )
    ast.validate(
        value, raster.num_bands, has_zones=seg_of is not None,
    )
    plan = plan_tiles(raster, tile)
    th, tw = plan.shape
    p = th * tw
    gt6 = np.asarray(plan.gt, np.float64)
    bands = ast.bands_of(value)
    vals, mask = _stack_bands(raster, plan, bands)
    res = -1 if resolution is None else int(resolution)
    prog = _compile.pixel_program(value, th, tw, index_system, res)
    sig = _compile.signature_of(
        value, th, tw, 0, "float64", index_system, res,
    )
    h, w = plan.raster_shape
    out = np.full((h, w), np.nan, np.float64)
    valid = np.zeros((h, w), bool)
    seg0 = np.full(p, -1, np.int32)
    t0 = time.perf_counter()
    with _trace.span("expr.map", mode="pixels", ntiles=plan.ntiles,
                     bands=len(bands)):
        for t in range(plan.ntiles):
            seg = seg0 if seg_of is None else np.asarray(
                seg_of(t), np.int32
            )
            v, m = _compile.run_pixels(
                prog, sig, gt6, plan.origins[t], vals[t], mask[t], seg
            )
            r0, c0 = (int(x) for x in plan.origins[t])
            r1 = min(r0 + th, h)
            c1 = min(c0 + tw, w)
            out[r0:r1, c0:c1] = v.reshape(th, tw)[: r1 - r0, : c1 - c0]
            valid[r0:r1, c0:c1] = m.reshape(th, tw)[
                : r1 - r0, : c1 - c0
            ]
    seconds = time.perf_counter() - t0
    _telemetry.record(
        "expr_stage", stage="pixels", seconds=round(seconds, 6),
        ntiles=plan.ntiles, bands=len(bands), pixels=plan.pixels,
        pixels_per_sec=round(plan.pixels / max(seconds, 1e-9), 1),
    )
    out[~valid] = np.nan
    return out, valid


def map_join(
    engine, expr: ast.Expr, raster, *, tile=None,
) -> "tuple[np.ndarray, np.ndarray, np.ndarray]":
    """Join terminal: ((H, W) int32 zone row or -1, (H, W) f64 value,
    (H, W) bool valid) — the raster side of a raster×vector join,
    zone membership epsilon-band exact."""
    value, kind, _by, _stats = ast.terminal_of(expr)
    if kind != "join":
        raise ValueError("map_join needs a .join() terminal")
    if engine.chip_index is None:
        raise ValueError("map_join needs the vector side (chip_index)")
    ast.validate(value, raster.num_bands, has_zones=True, by="zones")
    plan = plan_tiles(raster, tile)
    th, tw = plan.shape
    h, w = plan.raster_shape
    zones = np.full((h, w), -1, np.int32)
    segs: dict = {}

    def seg_of(t):
        geom = engine._tile_zone_rows(plan, t)
        s = np.where(geom >= 0, geom, -1).astype(np.int32)
        segs[t] = s
        return s

    vals, valid = map_pixels(
        value, raster, tile=tile,
        index_system=engine.index_system, resolution=engine.resolution,
        seg_of=seg_of,
    )
    for t in range(plan.ntiles):
        r0, c0 = (int(x) for x in plan.origins[t])
        r1 = min(r0 + th, h)
        c1 = min(c0 + tw, w)
        zones[r0:r1, c0:c1] = segs[t].reshape(th, tw)[
            : r1 - r0, : c1 - c0
        ]
    zones[~valid] = -1
    return zones, vals, valid


def warmup_expr(
    engine, expr: ast.Expr, raster, *, tile=None,
    by: "str | None" = None,
) -> tuple:
    """Precompile everything one `map_zonal` call will dispatch — the
    fused expression program (executed on zero tiles) and, for zones
    mode, the FULL per-tile membership path: probe plus epsilon-band
    host patch for every tile of the plan. The patch's ``point_to_cell``
    runs eagerly on the near-edge pixel set, whose size differs per
    tile, so each tile's primitive shapes only become warm by walking
    that tile — probing tile 0 alone leaves the rest cold. Returns the
    registered signature; after ``expr.freeze()``, a novel tree or
    bucket trips the cold-compile counter."""
    value, _kind, term_by, _stats = ast.terminal_of(expr)
    by = by or term_by
    plan = plan_tiles(raster, tile)
    th, tw = plan.shape
    gt6 = np.asarray(plan.gt, np.float64)
    num_segments = engine.num_zones if by == "zones" else th * tw
    if by == "zones":
        for t in range(plan.ntiles):
            engine._tile_zone_rows(plan, t)
    else:
        np.asarray(engine._assign(gt6, plan.origins[0], th, tw))
    return _compile.warmup_zonal(
        value, th, tw, num_segments, _acc_name(engine),
        engine.index_system, engine.resolution, engine.mesh,
    )
