"""numpy-f64 interpreter of expression trees — the bit-identity bar.

Every fused device result is required to match this interpreter bit for
bit (the project's standing oracle contract): :func:`interpret` walks
the SAME tree with the SAME operation order and the SAME mask-
propagation rule as the device lowering in `expr.compile`, in plain
numpy f64 — elementwise IEEE ops agree bit-exactly between XLA CPU and
numpy, and the affine center/cell/membership machinery reuses the
existing per-layer oracles (`raster.zonal.host_tile_centers`,
``index_system.point_to_cell``, `sql.join.host_join`) that the zonal
tests already pin against the device.

Two consumers:

- :func:`host_expr_zonal_oracle` — the full unfused twin of
  `expr.eval.map_zonal` (same tile decomposition, per-tile sequential
  f64 fold, row-major left-fold merge).
- :func:`host_expr_tile_partial` — ONE tile's partial, the degradation
  twin `eval` substitutes when a tile's device dispatch exhausts its
  retry budget; being bit-identical, a degraded tile does not perturb
  the fold.
"""

from __future__ import annotations

import numpy as np

from ..raster.tiles import plan_tiles
from ..raster.zonal import (
    ZonalResult,
    _oracle_fold,
    _result_from_dict,
    host_tile_centers,
)
from ..sql.join import host_join
from . import ast

__all__ = [
    "host_expr_tile_partial",
    "host_expr_zonal_oracle",
    "host_fold_partial",
    "host_overlay_measures",
    "host_pair_override",
    "interpret",
    "interpret_pair",
    "splice_override",
]

_BIN = {
    "add": np.add,
    "sub": np.subtract,
    "mul": np.multiply,
    "div": np.divide,
    "min": np.minimum,
    "max": np.maximum,
}
_CMP = {
    "lt": np.less,
    "le": np.less_equal,
    "gt": np.greater,
    "ge": np.greater_equal,
    "eq": np.equal,
    "ne": np.not_equal,
}


class HostCtx:
    """Interpretation context for one tile: ``vals``/``mask`` are the
    (B, P) f64/bool stack rows (row order = sorted band indices, same
    layout the device programs consume), ``cells`` the (P,) i64 cell
    ids, ``seg`` the (P,) zone row per pixel (-1 outside every zone)."""

    def __init__(self, vals, mask, rows, cells=None, seg=None):
        self.vals = vals
        self.mask = mask
        self.rows = rows
        self.cells = cells
        self.seg = seg


def interpret(node: ast.Expr, ctx: HostCtx):
    """→ (value, valid) numpy arrays — the f64 mirror of the device
    lowering, op for op (div by zero runs under errstate-ignore so the
    oracle reaches the same inf/NaN bits the device produces)."""
    true = np.True_
    if isinstance(node, ast.Band):
        r = ctx.rows[node.index]
        return ctx.vals[r], ctx.mask[r]
    if isinstance(node, ast.Const):
        return np.float64(node.value), true
    if isinstance(node, (ast.BinOp, ast.Compare)):
        av, am = interpret(node.a, ctx)
        bv, bm = interpret(node.b, ctx)
        fn = _BIN[node.op] if isinstance(node, ast.BinOp) else _CMP[node.op]
        with np.errstate(divide="ignore", invalid="ignore"):
            return fn(av, bv), am & bm
    if isinstance(node, ast.BoolOp):
        av, am = interpret(node.a, ctx)
        bv, bm = interpret(node.b, ctx)
        return (av & bv) if node.op == "and" else (av | bv), am & bm
    if isinstance(node, ast.Not):
        av, am = interpret(node.a, ctx)
        return ~av, am
    if isinstance(node, ast.Where):
        cv, cm = interpret(node.cond, ctx)
        av, am = interpret(node.a, ctx)
        bv, bm = interpret(node.b, ctx)
        return np.where(cv, av, bv), cm & np.where(cv, am, bm)
    if isinstance(node, ast.MaskWhere):
        vv, vm = interpret(node.value, ctx)
        cv, cm = interpret(node.cond, ctx)
        return vv, vm & cm & cv
    if isinstance(node, ast.CellOf):
        return ctx.cells, true
    if isinstance(node, ast.InZone):
        return ctx.seg >= 0, true
    if isinstance(node, ast.ZoneData):
        table = np.asarray(node.values, np.float64)
        inside = ctx.seg >= 0
        idx = np.where(inside, ctx.seg, 0)
        return np.where(
            inside, table[idx], np.float64(node.fill)
        ), true
    raise TypeError(
        f"cannot interpret {type(node).__name__} — peel the terminal "
        "first"
    )


def _stack_band_views(raster, plan, bands):
    """Per-tile generator of the multi-band twin of
    `raster.zonal._host_tile_views`: yields (t, (B, P) f64 values,
    (B, P) bool mask, (P, 2) f64 centers) in row-major tile order."""
    th, tw = plan.shape
    h, w = plan.raster_shape
    full = [
        (raster.band(b).values.astype(np.float64), raster.band(b).mask)
        for b in bands
    ]
    for t, (r0, c0) in enumerate(plan.origins):
        vals = np.zeros((len(bands), th, tw), np.float64)
        mask = np.zeros((len(bands), th, tw), bool)
        r1 = min(int(r0) + th, h)
        c1 = min(int(c0) + tw, w)
        for i, (vf, mf) in enumerate(full):
            sub = vf[int(r0):r1, int(c0):c1]
            vals[i, : sub.shape[0], : sub.shape[1]] = sub
            mask[i, : sub.shape[0], : sub.shape[1]] = mf[
                int(r0):r1, int(c0):c1
            ]
        vals[~mask] = 0
        yield (
            t,
            vals.reshape(len(bands), -1),
            mask.reshape(len(bands), -1),
            host_tile_centers(plan, t),
        )


def host_fold_partial(vals, valid, seg, num_segments: int):
    """One tile's sequential f64 fold into dense (S,) partials — the
    host twin of the fused program's masked segment fold, row-major
    pixel order (the order XLA's CPU scatter applies updates in)."""
    g = int(num_segments)
    cnt = np.zeros(g, np.int64)
    s = np.zeros(g, np.float64)
    mn = np.full(g, np.inf)
    mx = np.full(g, -np.inf)
    seg = np.asarray(seg)
    valid = np.asarray(valid, bool)
    for gg, ok, v in zip(seg, valid, np.asarray(vals, np.float64)):
        if ok and gg >= 0:
            cnt[gg] += 1
            s[gg] += v
            mn[gg] = min(mn[gg], v)
            mx[gg] = max(mx[gg], v)
    return cnt, s, mn, mx


def _tile_ctx(raster_ctx, value, pts, index_system, resolution, host):
    """Fill the cells/seg members a tree actually uses — membership via
    the exact f64 host join, cells via the host-side point_to_cell."""
    import jax.numpy as jnp

    cells = None
    seg = None
    if ast.uses_cells(value):
        cells = np.asarray(
            index_system.point_to_cell(jnp.asarray(pts), resolution)
        ).astype(np.int64)
    if host is not None:
        seg = np.asarray(
            host_join(pts, host, index_system, resolution)
        )
    raster_ctx.cells = cells
    raster_ctx.seg = seg
    return raster_ctx


def host_expr_tile_partial(
    value: ast.Expr, vals, mask, pts, *,
    index_system, resolution, host, num_segments: int, by: str,
):
    """ONE tile's zone/grid partial on the host — the degradation twin
    of the fused device tile dispatch. ``vals``/``mask`` are the (B, P)
    stack; returns dense (S,) (count, sum, min, max) for ``by="zones"``
    (S = num_zones) or a {cell_id: [c, s, mn, mx]} dict for grid."""
    import jax.numpy as jnp

    rows = _band_rows(value)
    ctx = HostCtx(np.asarray(vals, np.float64), np.asarray(mask, bool),
                  rows)
    _tile_ctx(ctx, value, pts, index_system, resolution, host)
    v, m = interpret(value, ctx)
    p = ctx.mask.shape[-1] if ctx.mask.size else len(pts)
    v = np.broadcast_to(np.asarray(v, np.float64), (p,))
    m = np.broadcast_to(np.asarray(m, bool), (p,))
    if by == "zones":
        seg = ctx.seg
        if seg is None:
            seg = np.asarray(
                host_join(pts, host, index_system, resolution)
            )
        return host_fold_partial(v, m, seg, num_segments)
    cells = np.asarray(
        index_system.point_to_cell(jnp.asarray(pts), resolution)
    ).astype(np.int64)
    acc: dict = {}
    seg = np.where(m, cells, -1)
    _oracle_fold(acc, seg, v)
    return acc


def _band_rows(value: ast.Expr) -> dict:
    return {b: r for r, b in enumerate(ast.bands_of(value))}


def interpret_pair(node: ast.Expr, area, larea, rarea):
    """→ (value, valid) numpy arrays over per-pair tables — the f64
    mirror of `expr.compile._lower_pair`, op for op (div by zero under
    errstate-ignore so the oracle reaches the same inf/NaN bits)."""
    true = np.True_
    if isinstance(node, ast.Const):
        return np.float64(node.value), true
    if isinstance(node, ast.OverlapArea):
        return area, true
    if isinstance(node, ast.LeftArea):
        return larea, true
    if isinstance(node, ast.RightArea):
        return rarea, true
    if isinstance(node, (ast.BinOp, ast.Compare)):
        av, am = interpret_pair(node.a, area, larea, rarea)
        bv, bm = interpret_pair(node.b, area, larea, rarea)
        fn = _BIN[node.op] if isinstance(node, ast.BinOp) else _CMP[node.op]
        with np.errstate(divide="ignore", invalid="ignore"):
            return fn(av, bv), am & bm
    if isinstance(node, ast.BoolOp):
        av, am = interpret_pair(node.a, area, larea, rarea)
        bv, bm = interpret_pair(node.b, area, larea, rarea)
        return (av & bv) if node.op == "and" else (av | bv), am & bm
    if isinstance(node, ast.Not):
        av, am = interpret_pair(node.a, area, larea, rarea)
        return ~av, am
    if isinstance(node, ast.Where):
        cv, cm = interpret_pair(node.cond, area, larea, rarea)
        av, am = interpret_pair(node.a, area, larea, rarea)
        bv, bm = interpret_pair(node.b, area, larea, rarea)
        return np.where(cv, av, bv), cm & np.where(cv, am, bm)
    if isinstance(node, ast.MaskWhere):
        vv, vm = interpret_pair(node.value, area, larea, rarea)
        cv, cm = interpret_pair(node.cond, area, larea, rarea)
        return vv, vm & cm & cv
    raise TypeError(
        f"cannot interpret {type(node).__name__} in an overlay pair tree"
    )


def _general_pair_area(prep, lk: int, rk: int) -> float:
    """Exact f64 chip∩chip area through the native boolean-op engine —
    the catch-all for shapes the convex clip cannot answer (multi-ring,
    holed, over-pad, spilled)."""
    from ..core.geometry import hostops as _hostops
    from ..sql.overlay import _csr_geom_areas

    L, R = prep.left, prep.right
    ga = L.table.chips.take(np.asarray([int(L.rows[lk])]))
    gb = R.table.chips.take(np.asarray([int(R.rows[rk])]))
    inter = _hostops.intersection(ga, gb)
    return float(_csr_geom_areas(inter, prep.shift)[0])


def host_pair_override(prep, li, ri, valid, seg, flagged):
    """Whole-pair f64 re-answer for the flagged geometry pairs.

    For every candidate row of a flagged pair, recompute its area in
    pure f64 (cell/chip area tables for core kinds, the numpy twin of
    the convex clip for clippable border pairs, the native boolean-op
    engine otherwise) and accumulate per pair IN EMISSION ORDER — the
    same stream order both fold lanes use. Returns (len(flagged),) f64
    sums aligned with ``flagged``."""
    from ..kernels import overlay as _k

    flagged = np.asarray(flagged, np.int64)
    out = np.zeros(flagged.shape[0], np.float64)
    L, R = prep.left, prep.right
    seg = np.asarray(seg)
    mask = np.asarray(valid, bool) & (seg >= 0) & np.isin(seg, flagged)
    rows = np.nonzero(mask)[0]
    if not rows.size:
        return out
    lk = np.asarray(li, np.int64)[rows]
    rk = np.asarray(ri, np.int64)[rows]
    # ``flagged`` comes out of np.unique (sorted), so searchsorted maps
    # each row to its pair slot; np.add.at over ascending ``rows`` then
    # accumulates each pair's rows in emission order, the same order a
    # per-row python loop (and both fold lanes) would use
    pos = np.searchsorted(flagged, seg[rows])
    lcore, rcore = L.core[lk], R.core[rk]
    areas = np.zeros(rows.shape[0], np.float64)
    cc = lcore & rcore
    areas[cc] = L.cell_area[lk[cc]]
    cb = lcore & ~rcore
    areas[cb] = R.chip_area[rk[cb]]
    bc = ~lcore & rcore
    areas[bc] = L.chip_area[lk[bc]]
    bb = ~lcore & ~rcore
    ok = bb & L.ok_subj[lk] & R.ok_win[rk]
    general = np.nonzero(bb & ~ok)[0]
    if ok.any():
        # one batched numpy clip over every clippable row — elementwise
        # per row, so bit-identical to clipping them one at a time
        ar, _, sp = _k.clip_area_convex(
            L.verts[lk[ok]], L.vlen[lk[ok]],
            R.verts[rk[ok]], R.vlen[rk[ok]], xp=np,
        )
        areas[ok] = ar
        spilled = np.nonzero(ok)[0][np.asarray(sp, bool)]
        general = np.concatenate([general, spilled])
    for idx in general.tolist():
        # the rare catch-all: multi-ring / holed / over-pad shapes go
        # through the native boolean-op engine one pair at a time
        areas[idx] = _general_pair_area(prep, int(lk[idx]), int(rk[idx]))
    np.add.at(out, pos, areas)
    return out


def splice_override(prep, value, li, ri, valid, seg, host_needed,
                    seg_l64, seg_r64, val, vok, area64):
    """Replace every host-flagged pair's folded area AND evaluated value
    with the pure-f64 re-answer (shared by the device lane and its numpy
    twin, so both lanes splice identically). Returns ``(val, vok,
    area64, n_overridden)``."""
    seg = np.asarray(seg)
    flag_rows = (
        np.asarray(valid, bool) & (seg >= 0) & np.asarray(host_needed)
    )
    flagged = np.unique(seg[flag_rows])
    if not flagged.size:
        return val, vok, area64, 0
    over = host_pair_override(prep, li, ri, valid, seg, flagged)
    area64[flagged] = over
    fv, fm = interpret_pair(
        value, over, seg_l64[flagged], seg_r64[flagged]
    )
    val[flagged] = np.broadcast_to(
        np.asarray(fv, np.float64), flagged.shape
    )
    vok[flagged] = np.broadcast_to(np.asarray(fm, bool), flagged.shape)
    return val, vok, area64, int(flagged.size)


def host_overlay_measures(prep, value: ast.Expr, *, pair_cap=None):
    """Pure-host overlay measure lane: the numpy twin (``xp=np``) of the
    device pipeline, stage for stage — equi-join count/emission, kind-
    routed clip areas in the prep's accelerated dtype (so the host-
    recheck flags match), the sequential pair fold, the pair-tree
    interpretation, and the same f64 override splice. Under x64 this IS
    the pure-f64 oracle the device lane must match bit for bit; it is
    also the degradation target when the device path fails. Returns the
    lane-output dict `sql.overlay.overlay_measures` packages."""
    from ..kernels import overlay as _k
    from ..sql import overlay as _ov

    L, R = prep.left, prep.right
    total = int(_k.pair_count(L.cells, R.cells, L.n, xp=np))
    Pb, emit_limit, overflow = _ov.pair_plan(total, pair_cap)
    li, ri, valid = _k.emit_pairs(
        L.cells, R.cells, L.n, emit_limit, Pb, xp=np
    )
    uniq, seg, sure, Sb, seg_l64, seg_r64 = _ov.pair_glue(
        prep, li, ri, valid
    )
    acc = np.dtype(prep.acc_name)
    area, host_needed = _k.pair_areas(
        L.core[li], R.core[ri], L.ok_subj[li], R.ok_win[ri],
        L.verts.astype(acc)[li], L.vlen[li],
        R.verts.astype(acc)[ri], R.vlen[ri],
        L.chip_area.astype(acc)[li], R.chip_area.astype(acc)[ri],
        L.cell_area.astype(acc)[li], acc.type(prep.band), xp=np,
    )
    _cnt, s = _k.host_pair_fold(area, valid, seg, Sb, acc_dtype=acc)
    fv, fm = interpret_pair(
        value, s, seg_l64.astype(acc), seg_r64.astype(acc)
    )
    val = np.broadcast_to(
        np.asarray(fv, np.float64), (Sb,)
    ).astype(np.float64).copy()
    vok = np.broadcast_to(np.asarray(fm, bool), (Sb,)).copy()
    area64 = s.astype(np.float64).copy()
    val, vok, area64, overridden = splice_override(
        prep, value, li, ri, valid, seg, host_needed,
        seg_l64, seg_r64, val, vok, area64,
    )
    U = uniq.shape[0]
    return {
        "pairs": uniq, "value": val[:U], "valid": vok[:U],
        "area": area64[:U], "sure": sure, "overflow": overflow,
        "host_overridden": overridden,
    }


def host_expr_zonal_oracle(
    raster, expr: ast.Expr, *, index_system, resolution,
    chip_index=None, tile=None, by: "str | None" = None,
) -> ZonalResult:
    """Pure-host f64 twin of `expr.eval.map_zonal`: interpret the same
    tree per tile, resolve membership through the exact f64 host join
    (zones) or point_to_cell (grid), fold sequentially per tile, merge
    with the same row-major left fold. Device results must match this
    bit for bit."""
    value, kind, term_by, _stats = ast.terminal_of(expr)
    if kind != "zonal":
        raise ValueError("host_expr_zonal_oracle folds zonal terminals")
    by = by or term_by
    host = None
    if chip_index is not None:
        host = getattr(chip_index, "host", None)
        if host is None and by == "zones":
            raise ValueError("chip_index carries no HostRecheck tables")
    ast.validate(
        expr, raster.num_bands, has_zones=chip_index is not None, by=by,
    )
    plan = plan_tiles(raster, tile)
    bands = ast.bands_of(value)
    rows = _band_rows(value)
    acc: dict = {}
    for _t, vals, mask, pts in _stack_band_views(raster, plan, bands):
        ctx = HostCtx(vals, mask, rows)
        _tile_ctx(ctx, value, pts, index_system, resolution,
                  host if by == "zones" else None)
        if by == "zones" and ctx.seg is None:
            ctx.seg = np.asarray(
                host_join(pts, host, index_system, resolution)
            )
        v, m = interpret(value, ctx)
        p = pts.shape[0]
        v = np.broadcast_to(np.asarray(v, np.float64), (p,))
        m = np.broadcast_to(np.asarray(m, bool), (p,))
        if by == "zones":
            key = ctx.seg
        else:
            import jax.numpy as jnp

            key = np.asarray(
                index_system.point_to_cell(jnp.asarray(pts), resolution)
            ).astype(np.int64)
        seg = np.where(m & (key >= 0), key, -1)
        _oracle_fold(acc, seg, v)
    return _result_from_dict(acc, band=0)
