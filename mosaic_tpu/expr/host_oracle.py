"""numpy-f64 interpreter of expression trees — the bit-identity bar.

Every fused device result is required to match this interpreter bit for
bit (the project's standing oracle contract): :func:`interpret` walks
the SAME tree with the SAME operation order and the SAME mask-
propagation rule as the device lowering in `expr.compile`, in plain
numpy f64 — elementwise IEEE ops agree bit-exactly between XLA CPU and
numpy, and the affine center/cell/membership machinery reuses the
existing per-layer oracles (`raster.zonal.host_tile_centers`,
``index_system.point_to_cell``, `sql.join.host_join`) that the zonal
tests already pin against the device.

Two consumers:

- :func:`host_expr_zonal_oracle` — the full unfused twin of
  `expr.eval.map_zonal` (same tile decomposition, per-tile sequential
  f64 fold, row-major left-fold merge).
- :func:`host_expr_tile_partial` — ONE tile's partial, the degradation
  twin `eval` substitutes when a tile's device dispatch exhausts its
  retry budget; being bit-identical, a degraded tile does not perturb
  the fold.
"""

from __future__ import annotations

import numpy as np

from ..raster.tiles import plan_tiles
from ..raster.zonal import (
    ZonalResult,
    _oracle_fold,
    _result_from_dict,
    host_tile_centers,
)
from ..sql.join import host_join
from . import ast

__all__ = [
    "host_expr_tile_partial",
    "host_expr_zonal_oracle",
    "host_fold_partial",
    "interpret",
]

_BIN = {
    "add": np.add,
    "sub": np.subtract,
    "mul": np.multiply,
    "div": np.divide,
    "min": np.minimum,
    "max": np.maximum,
}
_CMP = {
    "lt": np.less,
    "le": np.less_equal,
    "gt": np.greater,
    "ge": np.greater_equal,
    "eq": np.equal,
    "ne": np.not_equal,
}


class HostCtx:
    """Interpretation context for one tile: ``vals``/``mask`` are the
    (B, P) f64/bool stack rows (row order = sorted band indices, same
    layout the device programs consume), ``cells`` the (P,) i64 cell
    ids, ``seg`` the (P,) zone row per pixel (-1 outside every zone)."""

    def __init__(self, vals, mask, rows, cells=None, seg=None):
        self.vals = vals
        self.mask = mask
        self.rows = rows
        self.cells = cells
        self.seg = seg


def interpret(node: ast.Expr, ctx: HostCtx):
    """→ (value, valid) numpy arrays — the f64 mirror of the device
    lowering, op for op (div by zero runs under errstate-ignore so the
    oracle reaches the same inf/NaN bits the device produces)."""
    true = np.True_
    if isinstance(node, ast.Band):
        r = ctx.rows[node.index]
        return ctx.vals[r], ctx.mask[r]
    if isinstance(node, ast.Const):
        return np.float64(node.value), true
    if isinstance(node, (ast.BinOp, ast.Compare)):
        av, am = interpret(node.a, ctx)
        bv, bm = interpret(node.b, ctx)
        fn = _BIN[node.op] if isinstance(node, ast.BinOp) else _CMP[node.op]
        with np.errstate(divide="ignore", invalid="ignore"):
            return fn(av, bv), am & bm
    if isinstance(node, ast.BoolOp):
        av, am = interpret(node.a, ctx)
        bv, bm = interpret(node.b, ctx)
        return (av & bv) if node.op == "and" else (av | bv), am & bm
    if isinstance(node, ast.Not):
        av, am = interpret(node.a, ctx)
        return ~av, am
    if isinstance(node, ast.Where):
        cv, cm = interpret(node.cond, ctx)
        av, am = interpret(node.a, ctx)
        bv, bm = interpret(node.b, ctx)
        return np.where(cv, av, bv), cm & np.where(cv, am, bm)
    if isinstance(node, ast.MaskWhere):
        vv, vm = interpret(node.value, ctx)
        cv, cm = interpret(node.cond, ctx)
        return vv, vm & cm & cv
    if isinstance(node, ast.CellOf):
        return ctx.cells, true
    if isinstance(node, ast.InZone):
        return ctx.seg >= 0, true
    if isinstance(node, ast.ZoneData):
        table = np.asarray(node.values, np.float64)
        inside = ctx.seg >= 0
        idx = np.where(inside, ctx.seg, 0)
        return np.where(
            inside, table[idx], np.float64(node.fill)
        ), true
    raise TypeError(
        f"cannot interpret {type(node).__name__} — peel the terminal "
        "first"
    )


def _stack_band_views(raster, plan, bands):
    """Per-tile generator of the multi-band twin of
    `raster.zonal._host_tile_views`: yields (t, (B, P) f64 values,
    (B, P) bool mask, (P, 2) f64 centers) in row-major tile order."""
    th, tw = plan.shape
    h, w = plan.raster_shape
    full = [
        (raster.band(b).values.astype(np.float64), raster.band(b).mask)
        for b in bands
    ]
    for t, (r0, c0) in enumerate(plan.origins):
        vals = np.zeros((len(bands), th, tw), np.float64)
        mask = np.zeros((len(bands), th, tw), bool)
        r1 = min(int(r0) + th, h)
        c1 = min(int(c0) + tw, w)
        for i, (vf, mf) in enumerate(full):
            sub = vf[int(r0):r1, int(c0):c1]
            vals[i, : sub.shape[0], : sub.shape[1]] = sub
            mask[i, : sub.shape[0], : sub.shape[1]] = mf[
                int(r0):r1, int(c0):c1
            ]
        vals[~mask] = 0
        yield (
            t,
            vals.reshape(len(bands), -1),
            mask.reshape(len(bands), -1),
            host_tile_centers(plan, t),
        )


def host_fold_partial(vals, valid, seg, num_segments: int):
    """One tile's sequential f64 fold into dense (S,) partials — the
    host twin of the fused program's masked segment fold, row-major
    pixel order (the order XLA's CPU scatter applies updates in)."""
    g = int(num_segments)
    cnt = np.zeros(g, np.int64)
    s = np.zeros(g, np.float64)
    mn = np.full(g, np.inf)
    mx = np.full(g, -np.inf)
    seg = np.asarray(seg)
    valid = np.asarray(valid, bool)
    for gg, ok, v in zip(seg, valid, np.asarray(vals, np.float64)):
        if ok and gg >= 0:
            cnt[gg] += 1
            s[gg] += v
            mn[gg] = min(mn[gg], v)
            mx[gg] = max(mx[gg], v)
    return cnt, s, mn, mx


def _tile_ctx(raster_ctx, value, pts, index_system, resolution, host):
    """Fill the cells/seg members a tree actually uses — membership via
    the exact f64 host join, cells via the host-side point_to_cell."""
    import jax.numpy as jnp

    cells = None
    seg = None
    if ast.uses_cells(value):
        cells = np.asarray(
            index_system.point_to_cell(jnp.asarray(pts), resolution)
        ).astype(np.int64)
    if host is not None:
        seg = np.asarray(
            host_join(pts, host, index_system, resolution)
        )
    raster_ctx.cells = cells
    raster_ctx.seg = seg
    return raster_ctx


def host_expr_tile_partial(
    value: ast.Expr, vals, mask, pts, *,
    index_system, resolution, host, num_segments: int, by: str,
):
    """ONE tile's zone/grid partial on the host — the degradation twin
    of the fused device tile dispatch. ``vals``/``mask`` are the (B, P)
    stack; returns dense (S,) (count, sum, min, max) for ``by="zones"``
    (S = num_zones) or a {cell_id: [c, s, mn, mx]} dict for grid."""
    import jax.numpy as jnp

    rows = _band_rows(value)
    ctx = HostCtx(np.asarray(vals, np.float64), np.asarray(mask, bool),
                  rows)
    _tile_ctx(ctx, value, pts, index_system, resolution, host)
    v, m = interpret(value, ctx)
    p = ctx.mask.shape[-1] if ctx.mask.size else len(pts)
    v = np.broadcast_to(np.asarray(v, np.float64), (p,))
    m = np.broadcast_to(np.asarray(m, bool), (p,))
    if by == "zones":
        seg = ctx.seg
        if seg is None:
            seg = np.asarray(
                host_join(pts, host, index_system, resolution)
            )
        return host_fold_partial(v, m, seg, num_segments)
    cells = np.asarray(
        index_system.point_to_cell(jnp.asarray(pts), resolution)
    ).astype(np.int64)
    acc: dict = {}
    seg = np.where(m, cells, -1)
    _oracle_fold(acc, seg, v)
    return acc


def _band_rows(value: ast.Expr) -> dict:
    return {b: r for r, b in enumerate(ast.bands_of(value))}


def host_expr_zonal_oracle(
    raster, expr: ast.Expr, *, index_system, resolution,
    chip_index=None, tile=None, by: "str | None" = None,
) -> ZonalResult:
    """Pure-host f64 twin of `expr.eval.map_zonal`: interpret the same
    tree per tile, resolve membership through the exact f64 host join
    (zones) or point_to_cell (grid), fold sequentially per tile, merge
    with the same row-major left fold. Device results must match this
    bit for bit."""
    value, kind, term_by, _stats = ast.terminal_of(expr)
    if kind != "zonal":
        raise ValueError("host_expr_zonal_oracle folds zonal terminals")
    by = by or term_by
    host = None
    if chip_index is not None:
        host = getattr(chip_index, "host", None)
        if host is None and by == "zones":
            raise ValueError("chip_index carries no HostRecheck tables")
    ast.validate(
        expr, raster.num_bands, has_zones=chip_index is not None, by=by,
    )
    plan = plan_tiles(raster, tile)
    bands = ast.bands_of(value)
    rows = _band_rows(value)
    acc: dict = {}
    for _t, vals, mask, pts in _stack_band_views(raster, plan, bands):
        ctx = HostCtx(vals, mask, rows)
        _tile_ctx(ctx, value, pts, index_system, resolution,
                  host if by == "zones" else None)
        if by == "zones" and ctx.seg is None:
            ctx.seg = np.asarray(
                host_join(pts, host, index_system, resolution)
            )
        v, m = interpret(value, ctx)
        p = pts.shape[0]
        v = np.broadcast_to(np.asarray(v, np.float64), (p,))
        m = np.broadcast_to(np.asarray(m, bool), (p,))
        if by == "zones":
            key = ctx.seg
        else:
            import jax.numpy as jnp

            key = np.asarray(
                index_system.point_to_cell(jnp.asarray(pts), resolution)
            ).astype(np.int64)
        seg = np.where(m & (key >= 0), key, -1)
        _oracle_fold(acc, seg, v)
    return _result_from_dict(acc, band=0)
