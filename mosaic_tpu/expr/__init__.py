"""Geo-expression compiler: typed op-trees fused into one device
program per dispatch signature.

The reference surface is ~120 Catalyst expressions run through Spark's
whole-stage codegen; this package is the same move at our scale — a
small algebra over per-pixel band values (`expr.ast`), a lowering that
fuses the whole tree INTO the segment-reduced zonal fold so "NDVI, mask
clouds, zonal-mean by district" is a single launch per tile bucket
(`expr.compile`), execution through the dispatch core's guarded path so
watchdog/retry/host-oracle degradation come for free (`expr.eval`), and
a numpy-f64 interpreter of the same tree that device results must match
bit for bit (`expr.host_oracle`).

Entry points most callers want::

    from mosaic_tpu import expr

    e = expr.ndvi(nir=2, red=1).mask_where(expr.band(3) < 0.5)
    result = engine.map(e.zonal(by="zones"), raster)
"""

from .ast import (  # noqa: F401
    Band,
    BinOp,
    BoolOp,
    CellOf,
    Compare,
    Const,
    Expr,
    InZone,
    Join,
    MaskWhere,
    Not,
    Where,
    Zonal,
    ZoneData,
    band,
    bands_of,
    cell_of,
    const,
    in_zone,
    left_area,
    mask_where,
    ndvi,
    norm_diff,
    overlap_area,
    overlap_fraction,
    right_area,
    structure_key,
    terminal_of,
    tree_hash,
    uses_cells,
    uses_zones,
    validate,
    validate_pair,
    where,
    zone_data,
)
from .compile import (  # noqa: F401
    cold_compiles,
    freeze,
    pixel_program,
    run_zonal,
    signature_of,
    signatures,
    zonal_program,
)
from .eval import map_join, map_pixels, map_zonal, warmup_expr  # noqa: F401
from .host_oracle import (  # noqa: F401
    host_expr_tile_partial,
    host_expr_zonal_oracle,
    interpret,
)

__all__ = [
    "Band",
    "BinOp",
    "BoolOp",
    "CellOf",
    "Compare",
    "Const",
    "Expr",
    "InZone",
    "Join",
    "MaskWhere",
    "Not",
    "Where",
    "Zonal",
    "ZoneData",
    "band",
    "bands_of",
    "cell_of",
    "cold_compiles",
    "const",
    "freeze",
    "host_expr_tile_partial",
    "host_expr_zonal_oracle",
    "in_zone",
    "interpret",
    "map_join",
    "map_pixels",
    "map_zonal",
    "mask_where",
    "ndvi",
    "norm_diff",
    "pixel_program",
    "run_zonal",
    "signature_of",
    "signatures",
    "structure_key",
    "terminal_of",
    "tree_hash",
    "uses_cells",
    "uses_zones",
    "validate",
    "warmup_expr",
    "where",
    "zonal_program",
    "zone_data",
]
