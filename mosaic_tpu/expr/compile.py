"""Lower expression trees into ONE jitted device program per dispatch
signature.

Reference analog: Spark's whole-stage codegen collapsing a Catalyst
expression pipeline into one generated function — here the whole tree
(band reads, arithmetic, masking, grid/zone predicates, the terminal
zonal fold) lowers into a single closed jax function, so a 3-op
"NDVI → cloud mask → zonal mean" pipeline is one launch per tile
instead of N staged host→device round trips.

Programs live in the dispatch core's named-cache registry
(:func:`mosaic_tpu.dispatch.core.bounded_cache`, cache name
``expr_programs``) keyed on the tree ITSELF plus the bucket — nodes are
frozen dataclasses with structural equality, so two independently-built
but equal trees share one compiled program. The public execution
signature (:func:`signature_of`) is ``(tree-structure-hash, bucket,
index, mesh)``: :func:`run_zonal` opens a ``dispatch.compile`` span
(site=``expr``) with a ``backend_compiles()`` delta the first time a
signature executes — timeline attribution classifies expr cold-compiles
as *compile*, not *device* — and after :func:`freeze` a novel signature
trips the cold-compile counter plus an ``expr_compile`` telemetry
event, mirroring ``DispatchCore``'s tripwire.

jit purity: the fused body touches only jnp ops and the traceable
:func:`~mosaic_tpu.raster.tiles.assign_tile_cells`; spans, telemetry,
and signature bookkeeping all live OUTSIDE the jitted function.

Warmup is by EXECUTION, not AOT lowering — on this jax version
``jitted.lower(...).compile()`` does not populate the jit dispatch
cache, so :func:`warmup_zonal` runs the program on zero tiles through
the same :func:`run_zonal` wrapper the real path uses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..dispatch import core as _dispatch
from ..kernels.zonal import zonal_fold_masked
from ..obs import trace as _trace
from ..raster.tiles import assign_tile_cells
from ..runtime import telemetry as _telemetry
from . import ast

__all__ = [
    "PairCtx",
    "cold_compiles",
    "freeze",
    "overlay_program",
    "overlay_signature_of",
    "pixel_program",
    "run_pixels",
    "run_tracked",
    "run_zonal",
    "signature_of",
    "signatures",
    "warmup_zonal",
    "zonal_program",
]


# ------------------------------------------------------------- lowering

_BIN = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "min": jnp.minimum,
    "max": jnp.maximum,
}
_CMP = {
    "lt": jnp.less,
    "le": jnp.less_equal,
    "gt": jnp.greater,
    "ge": jnp.greater_equal,
    "eq": jnp.equal,
    "ne": jnp.not_equal,
}


class _Ctx:
    """Per-trace lowering context: band rows of the (B, P) tile stack,
    lazily-computed cell ids, and the zone segment vector."""

    def __init__(self, vals, mask, gt, origin, th, tw, rows,
                 index_system, resolution, seg):
        self.vals = vals
        self.mask = mask
        self.gt = gt
        self.origin = origin
        self.th = th
        self.tw = tw
        self.rows = rows  # band index (1-based) -> stack row
        self.index_system = index_system
        self.resolution = resolution
        self.seg = seg
        self._cells = None

    def cells(self):
        if self._cells is None:
            self._cells = assign_tile_cells(
                self.gt, self.origin, (self.th, self.tw),
                self.index_system, self.resolution,
            ).reshape(-1)
        return self._cells


def _lower(node: ast.Expr, ctx: _Ctx):
    """→ (value, valid) jnp arrays, implementing the mask-propagation
    rule documented in `expr.ast` — the f64 host oracle mirrors this
    function shape for shape."""
    true = jnp.ones((), bool)
    if isinstance(node, ast.Band):
        r = ctx.rows[node.index]
        return ctx.vals[r], ctx.mask[r]
    if isinstance(node, ast.Const):
        return jnp.asarray(node.value, jnp.float64), true
    if isinstance(node, (ast.BinOp, ast.Compare)):
        av, am = _lower(node.a, ctx)
        bv, bm = _lower(node.b, ctx)
        fn = _BIN[node.op] if isinstance(node, ast.BinOp) else _CMP[node.op]
        return fn(av, bv), am & bm
    if isinstance(node, ast.BoolOp):
        av, am = _lower(node.a, ctx)
        bv, bm = _lower(node.b, ctx)
        return (av & bv) if node.op == "and" else (av | bv), am & bm
    if isinstance(node, ast.Not):
        av, am = _lower(node.a, ctx)
        return ~av, am
    if isinstance(node, ast.Where):
        cv, cm = _lower(node.cond, ctx)
        av, am = _lower(node.a, ctx)
        bv, bm = _lower(node.b, ctx)
        return jnp.where(cv, av, bv), cm & jnp.where(cv, am, bm)
    if isinstance(node, ast.MaskWhere):
        vv, vm = _lower(node.value, ctx)
        cv, cm = _lower(node.cond, ctx)
        return vv, vm & cm & cv
    if isinstance(node, ast.CellOf):
        return ctx.cells(), true
    if isinstance(node, ast.InZone):
        return ctx.seg >= 0, true
    if isinstance(node, ast.ZoneData):
        table = jnp.asarray(node.values, jnp.float64)
        inside = ctx.seg >= 0
        idx = jnp.where(inside, ctx.seg, 0)
        return jnp.where(
            inside, table[idx], jnp.asarray(node.fill, jnp.float64)
        ), true
    raise TypeError(
        f"cannot lower {type(node).__name__} — terminals are peeled by "
        "eval before lowering"
    )


def _band_rows(value: ast.Expr) -> dict:
    """Band index (1-based) → row of the (B, P) stack, rows sorted by
    band index — the layout `eval` stacks and both programs consume."""
    return {b: r for r, b in enumerate(ast.bands_of(value))}


class PairCtx:
    """Lowering context for overlay PAIR trees: per-unique-pair (S,)
    tables — the folded intersection area and the two geometry total
    areas. Shared shape with `expr.host_oracle.interpret_pair`."""

    def __init__(self, area, larea, rarea):
        self.area = area
        self.larea = larea
        self.rarea = rarea


def _lower_pair(node: ast.Expr, ctx: PairCtx):
    """→ (value, valid) jnp arrays over the per-pair tables — the pair-
    tree twin of :func:`_lower`, with the same operator maps so the f64
    host oracle (`interpret_pair`) mirrors it op for op."""
    true = jnp.ones((), bool)
    if isinstance(node, ast.Const):
        return jnp.asarray(node.value, jnp.float64), true
    if isinstance(node, ast.OverlapArea):
        return ctx.area, true
    if isinstance(node, ast.LeftArea):
        return ctx.larea, true
    if isinstance(node, ast.RightArea):
        return ctx.rarea, true
    if isinstance(node, (ast.BinOp, ast.Compare)):
        av, am = _lower_pair(node.a, ctx)
        bv, bm = _lower_pair(node.b, ctx)
        fn = _BIN[node.op] if isinstance(node, ast.BinOp) else _CMP[node.op]
        return fn(av, bv), am & bm
    if isinstance(node, ast.BoolOp):
        av, am = _lower_pair(node.a, ctx)
        bv, bm = _lower_pair(node.b, ctx)
        return (av & bv) if node.op == "and" else (av | bv), am & bm
    if isinstance(node, ast.Not):
        av, am = _lower_pair(node.a, ctx)
        return ~av, am
    if isinstance(node, ast.Where):
        cv, cm = _lower_pair(node.cond, ctx)
        av, am = _lower_pair(node.a, ctx)
        bv, bm = _lower_pair(node.b, ctx)
        return jnp.where(cv, av, bv), cm & jnp.where(cv, am, bm)
    if isinstance(node, ast.MaskWhere):
        vv, vm = _lower_pair(node.value, ctx)
        cv, cm = _lower_pair(node.cond, ctx)
        return vv, vm & cm & cv
    raise TypeError(
        f"cannot lower {type(node).__name__} in an overlay pair tree"
    )


# ------------------------------------------------------------- programs


@_dispatch.bounded_cache("expr_programs", 64)
def zonal_program(
    value: ast.Expr, th: int, tw: int, num_segments: int,
    acc_name: str, index_system, resolution: int,
):
    """The fused program: ``(gt, origin, vals (B, P), mask (B, P),
    seg (P,)) → ((S,) count, sum, min, max)``. One launch reads raw
    bands and emits per-segment stats — the per-pixel expression is
    fused INTO the segment-reduced fold. Cached on the tree itself
    (structural equality), so equal trees share one entry."""
    rows = _band_rows(value)
    acc_dt = jnp.dtype(acc_name)
    p = th * tw

    def fused(gt, origin, vals, mask, seg):
        ctx = _Ctx(vals, mask, gt, origin, th, tw, rows,
                   index_system, resolution, seg)
        v, m = _lower(value, ctx)
        v = jnp.broadcast_to(v, (p,)).astype(acc_dt)
        m = jnp.broadcast_to(m, (p,))
        return zonal_fold_masked(
            v, m, seg, num_segments, acc_dtype=acc_dt
        )

    return jax.jit(fused)


@_dispatch.bounded_cache("expr_pixel_programs", 64)
def pixel_program(
    value: ast.Expr, th: int, tw: int, index_system, resolution,
):
    """Per-pixel program for `rst_mapbands`/join values: ``(gt, origin,
    vals, mask, seg) → ((P,) value, (P,) valid)`` — no fold; callers
    without a vector side pass an all ``-1`` segment vector (zone nodes
    are rejected by validation there)."""
    rows = _band_rows(value)
    p = th * tw

    def pixels(gt, origin, vals, mask, seg):
        ctx = _Ctx(vals, mask, gt, origin, th, tw, rows,
                   index_system, resolution, seg)
        v, m = _lower(value, ctx)
        return (
            jnp.broadcast_to(v, (p,)).astype(jnp.float64),
            jnp.broadcast_to(m, (p,)),
        )

    return jax.jit(pixels)


@_dispatch.bounded_cache("overlay_programs", 64)
def overlay_program(
    value: ast.Expr, Lb: int, Rb: int, Pb: int, Sb: int, vpad: int,
    acc_name: str, mesh=None,
):
    """The fused overlay measure program: gather candidate chip pairs
    from the two sorted side tables, compute per-pair intersection areas
    (kind routing + convex clip, `kernels.overlay.pair_areas`), fold
    them into per-geometry-pair totals, and evaluate the pair tree over
    the folded tables — ONE launch per ``(tree, buckets, mesh)``
    signature. Under ``mesh`` the per-pair stage runs data-parallel over
    the pair axis (side tables replicated, candidates sharded) — the
    stage is pointwise in the pair axis and the fold runs on the
    gathered output, so a sharded run is bit-identical to single-device
    by construction."""
    acc_dt = jnp.dtype(acc_name)
    from ..kernels import overlay as _ko

    def per_pair(li, ri, lcore, lok, lverts, lvlen, larea, lcell,
                 rcore, rok, rverts, rvlen, rarea, band):
        return _ko.pair_areas(
            lcore[li], rcore[ri], lok[li], rok[ri],
            lverts[li], lvlen[li], rverts[ri], rvlen[ri],
            larea[li], rarea[ri], lcell[li], band, xp=jnp,
        )

    stage = per_pair
    regather = None
    if mesh is not None:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ..parallel._compat import shard_map as _shard_map

        p, r = P(mesh.axis_names), P()
        stage = _shard_map(
            per_pair, mesh=mesh,
            in_specs=(p, p, r, r, r, r, r, r, r, r, r, r, r, r),
            out_specs=(p, p), check_rep=False,
        )
        # replicate the per-pair outputs before the fold: left sharded,
        # GSPMD would split the segment sum into per-shard partials plus
        # a cross-shard combine — a different f64 accumulation order
        # (1-ulp reassociation drift vs single-device)
        regather = NamedSharding(mesh, r)

    def fused(li, ri, valid, seg, lcore, lok, lverts, lvlen, larea,
              lcell, rcore, rok, rverts, rvlen, rarea, seg_larea,
              seg_rarea, band):
        area, host_needed = stage(
            li, ri, lcore, lok, lverts, lvlen, larea, lcell,
            rcore, rok, rverts, rvlen, rarea, band,
        )
        if regather is not None:
            area = jax.lax.with_sharding_constraint(area, regather)
            host_needed = jax.lax.with_sharding_constraint(
                host_needed, regather
            )
        cnt, s, _mn, _mx = zonal_fold_masked(
            area, valid, seg, Sb, acc_dtype=acc_dt
        )
        val, vok = _lower_pair(value, PairCtx(s, seg_larea, seg_rarea))
        return (
            jnp.broadcast_to(val, (Sb,)).astype(jnp.float64),
            jnp.broadcast_to(vok, (Sb,)),
            s, cnt, host_needed,
        )

    return jax.jit(fused)


def overlay_signature_of(
    value: ast.Expr, Lb: int, Rb: int, Pb: int, Sb: int, vpad: int,
    acc_name: str, index_system, resolution, mesh=None,
) -> tuple:
    """The dispatch signature an overlay measure execution is tracked
    under: ``(tree-hash, buckets, index, mesh)`` — the overlay twin of
    :func:`signature_of`."""
    return (
        "overlay:" + ast.tree_hash(value)[:16],
        (int(Lb), int(Rb), int(Pb), int(Sb), int(vpad), str(acc_name)),
        (type(index_system).__name__, int(resolution)),
        _dispatch.mesh_key(mesh),
    )


# ------------------------------------- signature tracking (the tripwire)

_signatures: set = set()
_frozen: "frozenset | None" = None
_cold_compiles = 0


def signature_of(
    value: ast.Expr, th: int, tw: int, num_segments: int,
    acc_name: str, index_system, resolution, mesh=None,
) -> tuple:
    """The dispatch signature a fused execution is tracked under:
    ``(tree-structure-hash, bucket, index, mesh)``."""
    return (
        ast.tree_hash(value)[:16],
        (int(th), int(tw), int(num_segments), str(acc_name)),
        (type(index_system).__name__, int(resolution)),
        _dispatch.mesh_key(mesh),
    )


def signatures() -> "frozenset":
    return frozenset(_signatures)


def freeze() -> "frozenset":
    """Snapshot the signature set after warmup — a NEW signature
    executing later is a cold compile in production, counted and
    telemetered (`DispatchCore.freeze` discipline)."""
    global _frozen
    _frozen = frozenset(_signatures)
    return _frozen


def cold_compiles() -> int:
    return _cold_compiles


def _reset_for_tests():
    global _frozen, _cold_compiles
    _signatures.clear()
    _frozen = None
    _cold_compiles = 0


def _track(sig: tuple):
    """First sight of ``sig`` → open a ``dispatch.compile`` span
    (site=expr) so timeline attribution books the build as *compile*;
    post-freeze novelty additionally trips the cold counter. Returns
    (span, compiles_before) — (None, None) for warm signatures."""
    global _cold_compiles
    if sig in _signatures:
        return None, None
    _signatures.add(sig)
    if _frozen is not None and sig not in _frozen:
        _cold_compiles += 1
        _telemetry.record(
            "expr_compile", signature=repr(sig), after_freeze=True,
            cold_compiles=_cold_compiles,
        )
    c0 = _dispatch.backend_compiles()
    span = _trace.start_span(
        "dispatch.compile", site="expr", signature=repr(sig)
    )
    return span, c0


def _untrack(span, c0):
    if span is None:
        return
    c1 = _dispatch.backend_compiles()
    if c0 is not None and c1 is not None:
        span.set(backend_compiles=c1 - c0)
    span.end()


def run_tracked(sig: tuple, fn, *args):
    """Execute any compiled program under expr signature tracking — the
    public wrapper overlay dispatch uses so its cold compiles land in
    the same `dispatch.compile` span / post-freeze tripwire as the
    raster programs."""
    span, c0 = _track(sig)
    try:
        return fn(*args)
    finally:
        _untrack(span, c0)


def run_zonal_async(prog, sig: tuple, gt, origin, vals, mask, seg):
    """Execute a fused program under signature tracking, returning the
    four partials as DEVICE arrays (async dispatch — the caller owns
    the blocking pull). Tracing/compilation is synchronous inside the
    jit call, so compile counts still land inside the span; only the
    device execution escapes it."""
    span, c0 = _track(sig)
    try:
        return prog(
            jnp.asarray(gt), jnp.asarray(origin),
            jnp.asarray(vals), jnp.asarray(mask), jnp.asarray(seg),
        )
    finally:
        _untrack(span, c0)


def run_zonal(prog, sig: tuple, gt, origin, vals, mask, seg):
    """Execute a fused program under signature tracking; returns the
    four partials as numpy arrays (blocking pulls, so a compile is
    fully inside the span)."""
    cnt, s, mn, mx = run_zonal_async(
        prog, sig, gt, origin, vals, mask, seg
    )
    return (
        np.asarray(cnt), np.asarray(s), np.asarray(mn),
        np.asarray(mx),
    )


def run_pixels(prog, sig: tuple, gt, origin, vals, mask, seg):
    """Execute a per-pixel program under the same signature tracking."""
    span, c0 = _track(sig)
    try:
        v, m = prog(
            jnp.asarray(gt), jnp.asarray(origin),
            jnp.asarray(vals), jnp.asarray(mask), jnp.asarray(seg),
        )
        return np.asarray(v), np.asarray(m)
    finally:
        _untrack(span, c0)


def warmup_zonal(
    value: ast.Expr, th: int, tw: int, num_segments: int,
    acc_name: str, index_system, resolution, mesh=None,
) -> tuple:
    """Precompile one fused signature by EXECUTING it on a zero tile
    (AOT lowering does not populate the jit dispatch cache on this jax
    version). Returns the signature, now registered for `freeze`."""
    prog = zonal_program(
        value, int(th), int(tw), int(num_segments), acc_name,
        index_system, int(resolution),
    )
    sig = signature_of(
        value, th, tw, num_segments, acc_name, index_system,
        resolution, mesh,
    )
    b = len(ast.bands_of(value))
    p = int(th) * int(tw)
    run_zonal(
        prog, sig,
        np.zeros(6, np.float64), np.zeros(2, np.int32),
        np.zeros((b, p), np.float64), np.zeros((b, p), bool),
        np.full(p, -1, np.int32),
    )
    return sig
