"""Typed expression trees over raster tile stacks.

Reference analog: Catalyst's expression nodes — the reference compiles
~120 ST_/RST_ expressions through Spark's whole-stage codegen; here the
tree is a small algebra over per-pixel band values that
`expr/compile.py` lowers into ONE jitted device program per dispatch
signature (tree structure × tile bucket × segment count), so an
"NDVI, mask clouds, zonal-mean" pipeline is a single launch per tile
instead of N staged host→device round trips.

Nodes are frozen dataclasses: structural equality and hashability come
for free, which is what lets two independently-built but equal trees
share one compiled program in the dispatch core's named cache
(`expr_programs`), and what makes :func:`tree_hash` a stable durable-
scan fingerprint.

The algebra (all per-pixel, over the (B, P) tile stack):

===============  =========  ====================================
node             dtype      meaning
===============  =========  ====================================
``Band(i)``      f64        band *i* (1-based) of the tile stack
``Const(v)``     f64        scalar broadcast
``BinOp``        f64        ``+ - * /`` (also ``min``/``max``)
``Compare``      bool       ``< <= > >= == !=`` (methods ``eq``/``ne``)
``BoolOp``/Not   bool       ``& |`` / ``~`` over bool operands
``Where``        promote    ``cond ? a : b``
``MaskWhere``    value's    keep value where cond; else INVALID
``CellOf``       i64        grid cell id of the pixel center
``InZone``       bool       pixel center inside some vector zone
``ZoneData``     f64        per-zone scalar broadcast to pixels
``Zonal``        terminal   fold into per-zone/per-cell stats
``Join``         terminal   per-pixel (zone row, value) output
===============  =========  ====================================

Mask propagation (the validity rule both the device lowering and the
f64 host oracle implement, over the tile stack's pad ∧ not-nodata ∧
not-NaN mask):

- ``Band(i)`` → band *i*'s tile mask;
- ``Const``/``CellOf``/``InZone``/``ZoneData`` → all-valid;
- ``BinOp``/``Compare``/``BoolOp`` → AND of the operand masks;
- ``Where(c, a, b)`` → ``c.mask ∧ (c ? a.mask : b.mask)`` (only the
  taken branch's validity matters);
- ``MaskWhere(v, c)`` → ``v.mask ∧ c.mask ∧ c`` — the cloud/nodata
  masking primitive: where the condition is False the pixel becomes
  invalid and folds nowhere.

NaN caveat: a NaN *produced on a valid pixel* (e.g. ``0/0`` on real
data) is outside the bit-identity contract — mask such pixels with
:class:`MaskWhere` first. NaN arriving via nodata/speckle is already
invalid in the tile mask and never reaches the fold.
"""

from __future__ import annotations

import dataclasses
import hashlib

__all__ = [
    "Band",
    "BinOp",
    "BoolOp",
    "Compare",
    "CellOf",
    "Const",
    "Expr",
    "InZone",
    "Join",
    "LeftArea",
    "MaskWhere",
    "Not",
    "OverlapArea",
    "RightArea",
    "Where",
    "ZoneData",
    "Zonal",
    "band",
    "bands_of",
    "cell_of",
    "const",
    "in_zone",
    "left_area",
    "mask_where",
    "ndvi",
    "norm_diff",
    "overlap_area",
    "overlap_fraction",
    "right_area",
    "structure_key",
    "terminal_of",
    "tree_hash",
    "uses_cells",
    "uses_zones",
    "validate",
    "validate_pair",
    "walk",
    "where",
    "zone_data",
]

_ARITH = ("add", "sub", "mul", "div", "min", "max")
_CMP = ("lt", "le", "gt", "ge", "eq", "ne")
_BOOL = ("and", "or")
_STATS = ("count", "sum", "min", "max", "mean")


def _as_expr(v) -> "Expr":
    if isinstance(v, Expr):
        return v
    if isinstance(v, (int, float)):
        return Const(float(v))
    raise TypeError(f"cannot coerce {type(v).__name__} into an Expr")


@dataclasses.dataclass(frozen=True)
class Expr:
    """Base node: operator sugar + the terminal constructors. Equality
    is structural (dataclass), so equal trees share compiled programs."""

    # -- arithmetic (numbers coerce to Const) --------------------------
    def __add__(self, o):
        return BinOp("add", self, _as_expr(o))

    def __radd__(self, o):
        return BinOp("add", _as_expr(o), self)

    def __sub__(self, o):
        return BinOp("sub", self, _as_expr(o))

    def __rsub__(self, o):
        return BinOp("sub", _as_expr(o), self)

    def __mul__(self, o):
        return BinOp("mul", self, _as_expr(o))

    def __rmul__(self, o):
        return BinOp("mul", _as_expr(o), self)

    def __truediv__(self, o):
        return BinOp("div", self, _as_expr(o))

    def __rtruediv__(self, o):
        return BinOp("div", _as_expr(o), self)

    # -- comparisons (``==``/``!=`` stay structural equality; use the
    #    ``eq``/``ne`` methods for pixel comparison nodes) -------------
    def __lt__(self, o):
        return Compare("lt", self, _as_expr(o))

    def __le__(self, o):
        return Compare("le", self, _as_expr(o))

    def __gt__(self, o):
        return Compare("gt", self, _as_expr(o))

    def __ge__(self, o):
        return Compare("ge", self, _as_expr(o))

    def eq(self, o):
        return Compare("eq", self, _as_expr(o))

    def ne(self, o):
        return Compare("ne", self, _as_expr(o))

    def __and__(self, o):
        return BoolOp("and", self, _as_expr(o))

    def __or__(self, o):
        return BoolOp("or", self, _as_expr(o))

    def __invert__(self):
        return Not(self)

    # -- masking + terminals -------------------------------------------
    def mask_where(self, cond) -> "MaskWhere":
        """Keep this value where ``cond`` holds; else the pixel becomes
        invalid (folds nowhere) — the cloud/nodata masking primitive."""
        return MaskWhere(self, _as_expr(cond))

    def zonal(self, stats=_STATS, *, by: str = "zones") -> "Zonal":
        """Terminal: fold into per-zone (``by="zones"``) or per-grid-
        cell (``by="grid"``) statistics."""
        if isinstance(stats, str):
            stats = (stats,)
        return Zonal(self, by=by, stats=tuple(stats))

    def join(self) -> "Join":
        """Terminal: per-pixel (zone row, value) output — the raster
        side of a raster×vector join without a reduction."""
        return Join(self)

    # dtype of the node's per-pixel value: "f64" | "i64" | "bool"
    def dtype(self) -> str:  # pragma: no cover - overridden everywhere
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Band(Expr):
    """Band ``index`` (1-based, GDAL-style) of the tile stack."""

    index: int

    def dtype(self) -> str:
        return "f64"


@dataclasses.dataclass(frozen=True)
class Const(Expr):
    value: float

    def dtype(self) -> str:
        return "f64"


@dataclasses.dataclass(frozen=True)
class BinOp(Expr):
    op: str
    a: Expr
    b: Expr

    def dtype(self) -> str:
        return "f64"


@dataclasses.dataclass(frozen=True)
class Compare(Expr):
    op: str
    a: Expr
    b: Expr

    def dtype(self) -> str:
        return "bool"


@dataclasses.dataclass(frozen=True)
class BoolOp(Expr):
    op: str
    a: Expr
    b: Expr

    def dtype(self) -> str:
        return "bool"


@dataclasses.dataclass(frozen=True)
class Not(Expr):
    a: Expr

    def dtype(self) -> str:
        return "bool"


@dataclasses.dataclass(frozen=True)
class Where(Expr):
    cond: Expr
    a: Expr
    b: Expr

    def dtype(self) -> str:
        da, db = self.a.dtype(), self.b.dtype()
        if da == db:
            return da
        return "f64"


@dataclasses.dataclass(frozen=True)
class MaskWhere(Expr):
    value: Expr
    cond: Expr

    def dtype(self) -> str:
        return self.value.dtype()


@dataclasses.dataclass(frozen=True)
class CellOf(Expr):
    """Grid cell id of each pixel center at the engine's
    (index_system, resolution) — usable in comparisons and ``Where``."""

    def dtype(self) -> str:
        return "i64"


@dataclasses.dataclass(frozen=True)
class InZone(Expr):
    """True where the pixel center lies inside some vector zone —
    the PIP-probe membership (epsilon-band-exact), as a predicate."""

    def dtype(self) -> str:
        return "bool"


@dataclasses.dataclass(frozen=True)
class ZoneData(Expr):
    """A per-zone f64 scalar (row ``g`` of ``values``) broadcast to
    every pixel of zone ``g``; ``fill`` outside every zone. Build from
    PackedGeometry measures with :func:`zone_data`. The values are part
    of the tree structure, so different tables compile different
    programs — keep tables small (zone counts, not pixel counts)."""

    values: tuple
    fill: float = 0.0

    def dtype(self) -> str:
        return "f64"


@dataclasses.dataclass(frozen=True)
class OverlapArea(Expr):
    """Overlay-join leaf: the intersection area of the candidate
    geometry pair (summed over its shared-cell chip pairs by the device
    fold). Only valid in PAIR trees (`sql.overlay.overlay_measures`),
    never in raster trees — :func:`validate` rejects it there and
    :func:`validate_pair` accepts it."""

    def dtype(self) -> str:
        return "f64"


@dataclasses.dataclass(frozen=True)
class LeftArea(Expr):
    """Overlay-join leaf: the LEFT geometry's total area (pair trees
    only) — the denominator of ``st_overlap_fraction``."""

    def dtype(self) -> str:
        return "f64"


@dataclasses.dataclass(frozen=True)
class RightArea(Expr):
    """Overlay-join leaf: the RIGHT geometry's total area (pair trees
    only)."""

    def dtype(self) -> str:
        return "f64"


@dataclasses.dataclass(frozen=True)
class Zonal(Expr):
    """Terminal: fold ``value`` into per-key (count, sum, min, max)."""

    value: Expr
    by: str = "zones"
    stats: tuple = _STATS

    def dtype(self) -> str:
        return self.value.dtype()


@dataclasses.dataclass(frozen=True)
class Join(Expr):
    """Terminal: per-pixel (zone row, value, valid) — no reduction."""

    value: Expr

    def dtype(self) -> str:
        return self.value.dtype()


# ------------------------------------------------------------- builders


def band(i: int) -> Band:
    return Band(int(i))


def const(v: float) -> Const:
    return Const(float(v))


def where(cond, a, b) -> Where:
    return Where(_as_expr(cond), _as_expr(a), _as_expr(b))


def mask_where(value, cond) -> MaskWhere:
    return MaskWhere(_as_expr(value), _as_expr(cond))


def norm_diff(a, b) -> BinOp:
    """The normalized difference ``(a - b) / (a + b)`` — one fixed
    operation order, shared by the device lowering and the host oracle
    so both compute bit-identical f64."""
    a, b = _as_expr(a), _as_expr(b)
    return BinOp("div", BinOp("sub", a, b), BinOp("add", a, b))


def ndvi(nir: int = 2, red: int = 1) -> BinOp:
    """NDVI over band indices: ``(nir - red) / (nir + red)``."""
    return norm_diff(Band(int(nir)), Band(int(red)))


def cell_of() -> CellOf:
    return CellOf()


def in_zone() -> InZone:
    return InZone()


def overlap_area() -> OverlapArea:
    return OverlapArea()


def left_area() -> LeftArea:
    return LeftArea()


def right_area() -> RightArea:
    return RightArea()


def overlap_fraction() -> BinOp:
    """``intersection_area / left_area`` — the overlay fraction measure,
    one fixed operation order shared by the device lowering and the f64
    host oracle (like :func:`norm_diff`)."""
    return BinOp("div", OverlapArea(), LeftArea())


def zone_data(values, fill: float = 0.0) -> ZoneData:
    """Per-zone auxiliary data as an expression leaf. ``values`` may be
    a sequence of floats (row g = zone g) or a PackedGeometry-measure
    array, e.g. ``zone_data(measures.area(zones_device))``."""
    import numpy as np

    vals = tuple(float(v) for v in np.asarray(values, dtype=np.float64))
    return ZoneData(vals, float(fill))


# ----------------------------------------------------------- inspection


def _children(node: Expr) -> tuple:
    if isinstance(node, (BinOp, Compare, BoolOp)):
        return (node.a, node.b)
    if isinstance(node, Not):
        return (node.a,)
    if isinstance(node, Where):
        return (node.cond, node.a, node.b)
    if isinstance(node, MaskWhere):
        return (node.value, node.cond)
    if isinstance(node, (Zonal, Join)):
        return (node.value,)
    return ()


def walk(node: Expr):
    yield node
    for c in _children(node):
        yield from walk(c)


def bands_of(node: Expr) -> list[int]:
    """Sorted distinct band indices the tree reads."""
    return sorted({n.index for n in walk(node) if isinstance(n, Band)})


def uses_cells(node: Expr) -> bool:
    return any(isinstance(n, CellOf) for n in walk(node))


def uses_zones(node: Expr) -> bool:
    return any(isinstance(n, (InZone, ZoneData)) for n in walk(node))


def terminal_of(node: Expr) -> tuple[Expr, str, str, tuple]:
    """(value tree, kind, by, stats) with the terminal peeled: bare
    value trees default to a full-stats zones fold."""
    if isinstance(node, Zonal):
        return node.value, "zonal", node.by, node.stats
    if isinstance(node, Join):
        return node.value, "join", "zones", ()
    return node, "zonal", "zones", _STATS


def structure_key(node: Expr):
    """The canonical nested-tuple spelling of the tree — the structural
    identity programs are cached on and :func:`tree_hash` digests."""
    if isinstance(node, Band):
        return ("band", node.index)
    if isinstance(node, Const):
        return ("const", repr(node.value))
    if isinstance(node, (BinOp, Compare, BoolOp)):
        tag = {"BinOp": "bin", "Compare": "cmp", "BoolOp": "bool"}[
            type(node).__name__
        ]
        return (tag, node.op, structure_key(node.a), structure_key(node.b))
    if isinstance(node, Not):
        return ("not", structure_key(node.a))
    if isinstance(node, Where):
        return (
            "where", structure_key(node.cond),
            structure_key(node.a), structure_key(node.b),
        )
    if isinstance(node, MaskWhere):
        return (
            "mask_where", structure_key(node.value),
            structure_key(node.cond),
        )
    if isinstance(node, CellOf):
        return ("cell_of",)
    if isinstance(node, InZone):
        return ("in_zone",)
    if isinstance(node, OverlapArea):
        return ("overlap_area",)
    if isinstance(node, LeftArea):
        return ("left_area",)
    if isinstance(node, RightArea):
        return ("right_area",)
    if isinstance(node, ZoneData):
        return (
            "zone_data",
            tuple(repr(v) for v in node.values),
            repr(node.fill),
        )
    if isinstance(node, Zonal):
        return ("zonal", node.by, node.stats, structure_key(node.value))
    if isinstance(node, Join):
        return ("join", structure_key(node.value))
    raise TypeError(f"unknown expression node {type(node).__name__}")


def tree_hash(node: Expr) -> str:
    """Process-stable sha256 of the tree structure (``repr`` of floats
    round-trips f64 exactly) — the durable-scan snapshot fingerprint:
    a resume against a structurally different expression must refuse."""
    return hashlib.sha256(
        repr(structure_key(node)).encode()
    ).hexdigest()


# ----------------------------------------------------------- validation


def validate(
    node: Expr,
    num_bands: int,
    *,
    has_zones: bool = True,
    by: str = "zones",
) -> Expr:
    """Type/shape-check the tree against a raster: band indices in
    range, bool conditions, numeric arithmetic, zone nodes only where a
    vector side exists. Returns the node (for chaining); raises
    ``ValueError``/``TypeError`` with the offending node spelled out."""
    value, kind, term_by, stats = terminal_of(node)
    if kind == "zonal":
        if term_by not in ("zones", "grid"):
            raise ValueError(
                f"zonal(by={term_by!r}): expected 'zones' or 'grid'"
            )
        bad = [s for s in stats if s not in _STATS]
        if bad:
            raise ValueError(
                f"unknown zonal stats {bad} (have {list(_STATS)})"
            )
        by = term_by
    for n in walk(value):
        if isinstance(n, (Zonal, Join)):
            raise ValueError(
                f"{type(n).__name__} is a terminal — it may only appear "
                "at the root of the tree"
            )
        if isinstance(n, Band) and not 1 <= n.index <= num_bands:
            raise ValueError(
                f"Band({n.index}) out of range — raster has "
                f"{num_bands} band(s), indices are 1-based"
            )
        if isinstance(n, (BinOp, Compare)):
            for side in (n.a, n.b):
                if side.dtype() == "bool":
                    raise TypeError(
                        f"{type(n).__name__}({n.op!r}) needs numeric "
                        "operands; got a bool tree — compare or Where "
                        "it first"
                    )
        if isinstance(n, BoolOp):
            for side in (n.a, n.b):
                if side.dtype() != "bool":
                    raise TypeError(
                        f"BoolOp({n.op!r}) needs bool operands; got "
                        f"{side.dtype()!r}"
                    )
        if isinstance(n, Not) and n.a.dtype() != "bool":
            raise TypeError("~ needs a bool operand")
        if isinstance(n, Where) and n.cond.dtype() != "bool":
            raise TypeError("Where condition must be bool")
        if isinstance(n, MaskWhere) and n.cond.dtype() != "bool":
            raise TypeError("mask_where condition must be bool")
        if isinstance(n, (OverlapArea, LeftArea, RightArea)):
            raise ValueError(
                f"{type(n).__name__} is an overlay-pair leaf — it only "
                "appears in pair trees (sql.overlay.overlay_measures)"
            )
        if isinstance(n, (InZone, ZoneData)):
            if not has_zones:
                raise ValueError(
                    f"{type(n).__name__} needs a vector side — the "
                    "engine was built without a chip_index"
                )
            if by == "grid":
                raise ValueError(
                    f"{type(n).__name__} is zone-keyed — it cannot "
                    "appear under zonal(by='grid')"
                )
    if value.dtype() == "bool" and kind == "zonal":
        raise TypeError(
            "a zonal fold needs a numeric value tree (fold bools via "
            "Where(cond, 1.0, 0.0))"
        )
    return node


#: node families allowed in an overlay PAIR tree: the three pair leaves
#: plus pure per-pair scalar algebra — no raster/zone machinery
_PAIR_NODES = (
    Const, BinOp, Compare, BoolOp, Not, Where, MaskWhere,
    OverlapArea, LeftArea, RightArea,
)


def validate_pair(node: Expr) -> Expr:
    """Check a tree for the overlay pair lane: pair leaves plus scalar
    algebra only, no terminal, numeric root (the per-pair value the
    measures result carries). Returns the node for chaining."""
    for n in walk(node):
        if not isinstance(n, _PAIR_NODES):
            raise ValueError(
                f"{type(n).__name__} cannot appear in an overlay pair "
                "tree — allowed: Const/BinOp/Compare/BoolOp/Not/Where/"
                "MaskWhere over OverlapArea/LeftArea/RightArea"
            )
        if isinstance(n, (BinOp, Compare)):
            for side in (n.a, n.b):
                if side.dtype() == "bool":
                    raise TypeError(
                        f"{type(n).__name__}({n.op!r}) needs numeric "
                        "operands; got a bool tree"
                    )
    if node.dtype() == "bool":
        raise TypeError(
            "an overlay pair tree must produce a numeric per-pair value "
            "(wrap predicates in Where(cond, 1.0, 0.0))"
        )
    return node
