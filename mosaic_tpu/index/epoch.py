"""Epochal mutable indexes: crash-consistent delta tessellation.

Every :class:`~mosaic_tpu.sql.join.ChipIndex` used to be build-once —
any zone edit meant a full re-tessellation plus ``hot_swap``, and a
crash mid-rebuild lost the work. :class:`EpochalIndex` makes mutation a
first-class, durable operation built from three pieces:

- **delta tessellation** (`core/tessellate.tessellate_subset`): only the
  changed geometries are tessellated. :func:`~mosaic_tpu.core.
  tessellate.tessellate` is per-geometry independent, so a delta's chip
  rows are bit-identical to the matching blocks of a from-scratch pass;
- an **epochal chip-table patch**: live chips are held as append-only
  blocks plus a tombstone array per block. An upsert tombstones the
  geometry's old rows and appends its fresh block; ``compact()`` folds
  tombstones out in the background. ``publish()`` materializes the live
  rows in column order — provably the same ``ChipTable`` a from-scratch
  ``tessellate`` of the current column would emit — and rebuilds the
  device index, swapping it in atomically (through
  ``ServeEngine.hot_swap`` when an engine is attached) so in-flight
  batches finish on the old epoch;
- a **checksummed, fingerprint-chained delta log** riding the
  `runtime/checkpoint.py` discipline: every record is an npz payload
  plus a JSON sidecar carrying the payload's SHA-256 and the previous
  record's chain hash, written temp-first and ``os.replace``\\ d,
  payload BEFORE sidecar. A kill at any byte boundary leaves either a
  fully-durable epoch or a truncatable tail — never a half-epoch.
  :meth:`EpochalIndex.replay` reconstructs the index bit-identically at
  the newest durable epoch; a corrupt *interior* record raises the
  typed :class:`~mosaic_tpu.runtime.errors.EpochLogCorrupt` and a
  broken chain raises
  :class:`~mosaic_tpu.runtime.errors.EpochFingerprintMismatch`.

Delta-log format v1 (documented in docs/ARCHITECTURE.md):

- ``base-00000000.npz/.json`` — the epoch-0 geometry column (CSR
  arrays + stable ids) and the build parameters; its chain hash is the
  **series** fingerprint every published index carries;
- ``delta-<epoch>.npz/.json`` — removed ids + upserted ids and their
  geometry column; sidecar ``prev`` is the predecessor's chain hash,
  ``chain = sha256(prev + ":" + sha256(payload))``;
- ``compact-<epoch>.npz/.json`` — the full current column with the
  truncated prefix's chain fingerprint sealed in as ``prev``, so replay
  after truncation still proves chain integrity: the next delta must
  chain from exactly that sealed value.

Fault sites: ``epoch.apply`` (pre-tessellate / pre-append /
post-append boundaries), ``epoch.publish`` (pre-build and the torn
boundary between index swap and epoch-counter bump), ``epoch.compact``
(pre-snapshot / post-snapshot-pre-truncate / post-truncate).

Knob: ``MOSAIC_EPOCH_LOG_MAX`` — when the log holds at least this many
delta records since the last compaction, ``apply`` triggers
compaction-and-truncate (explicit ``log_max=`` beats the env, per the
repo-wide precedence).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import threading
import time

import numpy as np

from ..core.tessellate import ChipTable, tessellate_subset
from ..core.types import GeometryBuilder, PackedGeometry, concat_packed
from ..obs import trace as _trace
from ..runtime import faults as _faults
from ..runtime import telemetry as _telemetry
from ..runtime.errors import EpochFingerprintMismatch, EpochLogCorrupt

LOG_VERSION = 1
_REC_RE = re.compile(r"^(base|delta|compact)-(\d{8})\.json$")


# ------------------------------------------------------------ column codec

_COL_KEYS = (
    "xy", "ring_offsets", "part_offsets", "geom_offsets", "geom_type",
    "srid", "geom_has_z",
)


def _empty_column() -> PackedGeometry:
    return PackedGeometry(
        xy=np.zeros((0, 2), dtype=np.float64),
        ring_offsets=np.zeros(1, dtype=np.int64),
        part_offsets=np.zeros(1, dtype=np.int64),
        geom_offsets=np.zeros(1, dtype=np.int64),
        geom_type=np.zeros(0, dtype=np.uint8),
        srid=np.zeros(0, dtype=np.int32),
    )


def _col_arrays(col: PackedGeometry, prefix: str = "") -> dict:
    out = {prefix + k: np.asarray(getattr(col, k)) for k in _COL_KEYS}
    out[prefix + "z_present"] = np.asarray(
        1 if col.z is not None else 0, dtype=np.int64
    )
    out[prefix + "z"] = (
        np.asarray(col.z)
        if col.z is not None
        else np.zeros(0, dtype=np.float64)
    )
    return out


def _col_from_arrays(arrays: dict, prefix: str = "") -> PackedGeometry:
    kw = {k: arrays[prefix + k] for k in _COL_KEYS}
    if int(arrays[prefix + "z_present"]):
        kw["z"] = arrays[prefix + "z"]
    return PackedGeometry(**kw)


def _concat_ranges(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """``concatenate([arange(s, s+l) for s, l in zip(starts, lens)])``
    without the Python loop."""
    starts = np.asarray(starts, np.int64)
    lens = np.asarray(lens, np.int64)
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, np.int64)
    ends = np.cumsum(lens)
    offs = np.repeat(starts - np.concatenate(([0], ends[:-1])), lens)
    return np.arange(total, dtype=np.int64) + offs


def _gather_packed(src: PackedGeometry, indices) -> PackedGeometry:
    """Vectorized ``PackedGeometry.take`` over the CSR arrays — the
    publish-path chip gather is O(rows) builder appends through
    ``take``, which dominates materialize at bench scale. Byte-for-byte
    the same column ``take`` builds (z-carrying columns fall back to
    it; chips are 2-D)."""
    idx = np.asarray(indices, np.int64).reshape(-1)
    if src.z is not None:
        return src.take([int(g) for g in idx])
    go = np.asarray(src.geom_offsets, np.int64)
    po = np.asarray(src.part_offsets, np.int64)
    ro = np.asarray(src.ring_offsets, np.int64)
    n_parts = go[idx + 1] - go[idx]
    parts = _concat_ranges(go[idx], n_parts)
    n_rings = po[parts + 1] - po[parts]
    rings = _concat_ranges(po[parts], n_rings)
    n_verts = ro[rings + 1] - ro[rings]
    verts = _concat_ranges(ro[rings], n_verts)
    return PackedGeometry(
        xy=np.asarray(src.xy)[verts],
        ring_offsets=np.concatenate(([0], np.cumsum(n_verts))),
        part_offsets=np.concatenate(([0], np.cumsum(n_rings))),
        geom_offsets=np.concatenate(([0], np.cumsum(n_parts))),
        geom_type=np.asarray(src.geom_type)[idx],
        srid=np.asarray(src.srid)[idx],
        geom_has_z=np.asarray(src.geom_has_z)[idx],
    )


def chip_index_equal(a, b) -> bool:
    """Bitwise identity of two ChipIndexes over every pytree leaf
    (shape, dtype and bytes) — the acceptance predicate of the epoch
    contract: a patched index must be indistinguishable from a
    from-scratch rebuild."""
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    if len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        if (
            x.shape != y.shape
            or x.dtype != y.dtype
            or x.tobytes() != y.tobytes()
        ):
            return False
    return True


# ------------------------------------------------------------- delta log

def _encode_record(arrays: dict, prev: str) -> tuple[bytes, str, str]:
    """(payload bytes, payload sha256, chain hash) of one record."""
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()
    sha = hashlib.sha256(payload).hexdigest()
    chain = hashlib.sha256(f"{prev}:{sha}".encode()).hexdigest()
    return payload, sha, chain


class _DeltaLog:
    """One directory of chained records (checkpoint discipline: atomic
    temp-write + replace, payload before sidecar)."""

    def __init__(self, root: str):
        self.root = str(root)

    def _paths(self, kind: str, epoch: int) -> tuple[str, str]:
        base = os.path.join(self.root, f"{kind}-{epoch:08d}")
        return base + ".npz", base + ".json"

    def write(
        self, kind: str, epoch: int, payload: bytes, sha: str,
        prev: str, chain: str, meta: dict,
    ) -> None:
        os.makedirs(self.root, exist_ok=True)
        npz_path, json_path = self._paths(kind, epoch)
        tmp = npz_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, npz_path)
        sidecar = {
            "version": LOG_VERSION, "kind": kind, "epoch": int(epoch),
            "sha256": sha, "prev": prev, "chain": chain, "meta": meta,
        }
        tmp = json_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(sidecar, f, sort_keys=True, indent=1)
        os.replace(tmp, json_path)

    def entries(self) -> list[tuple[str, int]]:
        """Sidecar-backed ``(kind, epoch)`` records, epoch-ordered."""
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        out = []
        for n in names:
            m = _REC_RE.match(n)
            if m:
                out.append((m.group(1), int(m.group(2))))
        return sorted(out, key=lambda ke: (ke[1], ke[0] != "compact"))

    def load(self, kind: str, epoch: int) -> tuple[dict, dict]:
        """(sidecar, arrays) of one VALID record; raises ValueError on
        any damage (the caller decides truncate-vs-refuse)."""
        npz_path, json_path = self._paths(kind, epoch)
        with open(json_path) as f:
            sidecar = json.load(f)
        if sidecar.get("version") != LOG_VERSION:
            raise ValueError(
                f"unknown log version {sidecar.get('version')!r}"
            )
        with open(npz_path, "rb") as f:
            payload = f.read()
        if hashlib.sha256(payload).hexdigest() != sidecar.get("sha256"):
            raise ValueError("payload checksum mismatch")
        expect = hashlib.sha256(
            f"{sidecar.get('prev')}:{sidecar.get('sha256')}".encode()
        ).hexdigest()
        if sidecar.get("chain") != expect:
            raise ValueError("chain hash does not bind prev+payload")
        with np.load(io.BytesIO(payload)) as z:
            arrays = {k: np.array(z[k]) for k in z.files}
        return sidecar, arrays

    def unlink(self, kind: str, epoch: int) -> None:
        for p in self._paths(kind, epoch):
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass


# ---------------------------------------------------------- epochal index

class EpochalIndex:
    """A mutable, durable chip index published in atomic epochs.

    ``apply`` mutates (delta-tessellate + durable log append + in-memory
    patch), ``publish`` builds and atomically swaps the device index,
    ``compact`` folds tombstones and truncates the log, ``replay``
    reconstructs from the log after a kill. The invariant everything
    here serves: at every epoch, the published index is **bit-identical**
    to ``build_chip_index(tessellate(current column))``.
    """

    def __init__(
        self,
        col: PackedGeometry | None,
        index_system,
        resolution: int,
        *,
        log_dir: str | None = None,
        keep_core_geoms: bool = True,
        dtype=None,
        max_chips_per_cell: int | None = None,
        recenter: bool = True,
        log_max: int | None = None,
        _defer_base: bool = False,
    ):
        import jax.numpy as jnp

        self.system = index_system
        self.resolution = int(resolution)
        self.keep_core_geoms = bool(keep_core_geoms)
        self.dtype = jnp.float32 if dtype is None else dtype
        self.max_chips_per_cell = max_chips_per_cell
        self.recenter = bool(recenter)
        self._log = _DeltaLog(log_dir) if log_dir else None
        self._log_max = log_max
        self._lock = threading.RLock()

        self._geoms: dict[int, PackedGeometry] = {}
        self._order: list[int] = []
        self._blocks: list[dict] = []  # {"table": ChipTable, "dead": bool[]}
        self._applied = 0   # durable epoch counter (count of deltas)
        self._epoch = -1    # last PUBLISHED epoch
        self._chain = ""    # chain hash through the last delta
        self._series = ""   # base record's chain hash
        self._deltas_since_compact = 0
        self._index = None
        if not _defer_base:
            self._init_base(col if col is not None else _empty_column())

    # ------------------------------------------------------------- base

    def _build_meta(self) -> dict:
        return {
            "system": type(self.system).__name__,
            "resolution": self.resolution,
            "keep_core_geoms": self.keep_core_geoms,
            "dtype": str(np.dtype(self.dtype)),
            "max_chips_per_cell": self.max_chips_per_cell,
            "recenter": self.recenter,
        }

    def _init_base(self, col: PackedGeometry) -> None:
        gids = list(range(len(col.geom_type)))
        arrays = dict(_col_arrays(col), ids=np.asarray(gids, np.int64))
        payload, sha, chain = _encode_record(arrays, "")
        if self._log is not None:
            self._log.write(
                "base", 0, payload, sha, "", chain, self._build_meta()
            )
        self._series = self._chain = chain
        for i, g in enumerate(gids):
            self._geoms[g] = col.take([i])
        self._order = gids
        if gids:
            table = tessellate_subset(
                col, np.arange(len(gids)), self.system, self.resolution,
                self.keep_core_geoms, geom_ids=np.asarray(gids, np.int64),
            )
            self._blocks = [
                {"table": table, "dead": np.zeros(len(table), dtype=bool)}
            ]

    # ------------------------------------------------------- properties

    @property
    def epoch(self) -> int:
        """The last PUBLISHED epoch (-1 before the first publish)."""
        return self._epoch

    @property
    def applied_epoch(self) -> int:
        """The newest DURABLE epoch (count of applied deltas)."""
        return self._applied

    @property
    def index(self):
        """The published ChipIndex (None before the first publish)."""
        return self._index

    @property
    def series(self) -> str:
        """The base record's chain hash — stable across every epoch of
        this index's life, distinct across indexes."""
        return self._series

    @property
    def chain(self) -> str:
        return self._chain

    def epoch_token(self, epoch: int | None = None) -> str:
        e = self._applied if epoch is None else int(epoch)
        return f"{self._series[:12]}:{e}:{self._chain[:12]}"

    def __len__(self) -> int:
        return len(self._order)

    def column(self) -> PackedGeometry:
        """The current geometry column, in stable column order — the
        from-scratch oracle's input."""
        with self._lock:
            order = list(self._order)
            geoms = {g: self._geoms[g] for g in order}
        b = GeometryBuilder()
        for g in order:
            b.append_from(geoms[g], 0)
        return b.build()

    # ------------------------------------------------------------ apply

    def apply(
        self,
        *,
        upsert: PackedGeometry | None = None,
        ids=None,
        remove=(),
    ) -> dict:
        """One durable delta: replace/insert ``upsert`` geometries under
        stable ``ids``, drop ``remove`` ids. Tessellates only the
        changed geometries, appends the delta to the log (the durable
        point — a kill before it loses only this call's work, a kill
        after it replays to the new epoch), then patches the in-memory
        chip table (tombstone + append). Publish separately.
        """
        upsert = upsert if upsert is not None else _empty_column()
        n_up = len(upsert.geom_type)
        ids = np.asarray(
            ids if ids is not None else np.zeros(0, np.int64), np.int64
        ).reshape(-1)
        remove = np.asarray(list(remove), np.int64).reshape(-1)
        if ids.shape[0] != n_up:
            raise ValueError(
                f"{ids.shape[0]} ids for {n_up} upsert geometries"
            )
        if np.intersect1d(ids, remove).size:
            raise ValueError("an id cannot be both upserted and removed")
        unknown = [int(g) for g in remove if int(g) not in self._geoms]
        if unknown:
            raise KeyError(f"cannot remove unknown geometry ids {unknown}")
        epoch = self._applied + 1
        stats = {"epoch": epoch, "upserts": n_up,
                 "removed": int(remove.size), "seconds": {}}
        with _trace.span("epoch.apply", epoch=epoch, upserts=n_up,
                         removed=int(remove.size)):
            _faults.maybe_fail("epoch.apply")  # pre-tessellate boundary
            t0 = time.perf_counter()
            with _telemetry.timed("epoch_stage", stage="tessellate"):
                if n_up:
                    delta = tessellate_subset(
                        upsert, np.arange(n_up), self.system,
                        self.resolution, self.keep_core_geoms,
                        geom_ids=ids,
                    )
                else:
                    delta = None
            stats["seconds"]["tessellate"] = round(
                time.perf_counter() - t0, 6
            )
            stats["chip_rows"] = 0 if delta is None else len(delta)

            _faults.maybe_fail("epoch.apply")  # pre-append boundary
            t0 = time.perf_counter()
            with _telemetry.timed("epoch_stage", stage="append"):
                arrays = dict(
                    _col_arrays(upsert),
                    ids=ids, removed=remove,
                )
                payload, sha, chain = _encode_record(arrays, self._chain)
                if self._log is not None:
                    self._log.write(
                        "delta", epoch, payload, sha, self._chain, chain,
                        {"upserts": n_up, "removed": int(remove.size)},
                    )
            stats["seconds"]["append"] = round(time.perf_counter() - t0, 6)

            _faults.maybe_fail("epoch.apply")  # post-append boundary
            with self._lock:
                self._patch(upsert, ids, remove, delta)
                self._chain = chain
                self._applied = epoch
                self._deltas_since_compact += 1
        _telemetry.record("epoch_applied", **{
            k: v for k, v in stats.items() if k != "seconds"
        })
        limit = self._log_max
        if limit is None:
            limit = int(os.environ.get("MOSAIC_EPOCH_LOG_MAX", "0") or "0")
        if (
            self._log is not None and limit
            and self._deltas_since_compact >= int(limit)
        ):
            stats["compacted"] = self.compact()
        return stats

    def _patch(self, upsert, ids, remove, delta) -> None:
        """In-memory chip-table patch (caller holds the lock)."""
        gone = np.concatenate([ids, remove])
        if gone.size:
            for blk in self._blocks:
                blk["dead"] |= np.isin(blk["table"].geom_id, gone)
        for g in remove:
            del self._geoms[int(g)]
            self._order.remove(int(g))
        for i, g in enumerate(ids):
            g = int(g)
            if g not in self._geoms:
                self._order.append(g)
            self._geoms[g] = upsert.take([i])
        if delta is not None and len(delta):
            self._blocks.append(
                {"table": delta, "dead": np.zeros(len(delta), dtype=bool)}
            )

    # ------------------------------------------------------ materialize

    def _live(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(block_idx, local_row, gid) of every live chip row, in final
        column order: stable-sorted by geometry position so the rows
        line up with a from-scratch tessellation of ``column()``."""
        with self._lock:
            blocks = list(self._blocks)
            pos = {g: p for p, g in enumerate(self._order)}
        bi, loc, gid = [], [], []
        for i, blk in enumerate(blocks):
            keep = np.nonzero(~blk["dead"])[0]
            if keep.size:
                bi.append(np.full(keep.size, i, dtype=np.int64))
                loc.append(keep.astype(np.int64))
                gid.append(blk["table"].geom_id[keep])
        if not bi:
            z = np.zeros(0, np.int64)
            return z, z, z
        bi = np.concatenate(bi)
        loc = np.concatenate(loc)
        gid = np.concatenate(gid)
        p = np.asarray([pos[int(g)] for g in gid], dtype=np.int64)
        order = np.argsort(p, kind="stable")
        return bi[order], loc[order], gid[order]

    def _materialize(self, labels: str = "pos") -> ChipTable:
        """The live chip table in column order; ``labels`` picks the
        ``geom_id`` column: dense positions (``pos`` — what
        ``build_chip_index`` needs) or stable ids (``gid`` — what a
        compacted base block stores)."""
        bi, loc, gid = self._live()
        with self._lock:
            blocks = list(self._blocks)
            pos = {g: p for p, g in enumerate(self._order)}
        cell = np.zeros(bi.size, np.int64)
        core = np.zeros(bi.size, bool)
        has = np.zeros(bi.size, bool)
        for b in np.unique(bi):
            m = bi == b
            t = blocks[int(b)]["table"]
            cell[m] = t.cell_id[loc[m]]
            core[m] = t.is_core[loc[m]]
            has[m] = t.has_geom[loc[m]]
        if bi.size:
            lens = np.asarray(
                [len(blk["table"].chips) for blk in blocks], np.int64
            )
            base = np.concatenate(([0], np.cumsum(lens)[:-1]))
            chips = _gather_packed(
                concat_packed([blk["table"].chips for blk in blocks]),
                base[bi] + loc,
            )
        else:
            chips = GeometryBuilder().build()
        geom_id = (
            np.asarray([pos[int(g)] for g in gid], dtype=np.int64)
            if labels == "pos"
            else gid
        )
        return ChipTable(
            geom_id=geom_id, cell_id=cell, is_core=core,
            chips=chips, has_geom=has,
        )

    # ---------------------------------------------------------- publish

    def publish(self, engine=None, *, reprofile: bool = False,
                **hot_swap_kw) -> dict:
        """Build the device index for the newest applied epoch and swap
        it in atomically. With ``engine`` (anything exposing
        ``hot_swap(index, profile=...)`` — a ``ServeEngine`` or the
        router's guarded proxy) the new epoch is built and warmed ASIDE
        while in-flight batches keep finishing on the old one; a failed
        swap leaves BOTH the engine and this index on the old epoch.
        ``reprofile=True`` re-profiles the mutated workload through
        `tune` and hands the refreshed profile to ``hot_swap`` so knobs
        re-resolve live on the epoch boundary."""
        from ..sql.join import build_chip_index

        epoch = self._applied
        stats = {"epoch": epoch, "seconds": {}}
        with _trace.span("epoch.publish", epoch=epoch):
            _faults.maybe_fail("epoch.publish")  # pre-build boundary
            t0 = time.perf_counter()
            with _telemetry.timed("epoch_stage", stage="materialize"):
                table = self._materialize()
            stats["seconds"]["materialize"] = round(
                time.perf_counter() - t0, 6
            )
            stats["chips"] = len(table)
            t0 = time.perf_counter()
            with _telemetry.timed("epoch_stage", stage="build"):
                idx = build_chip_index(
                    table, dtype=self.dtype,
                    max_chips_per_cell=self.max_chips_per_cell,
                    recenter=self.recenter,
                )
                idx.epoch = epoch
                idx.epoch_series = self._series
                idx.epoch_token = self.epoch_token(epoch)
            stats["seconds"]["build"] = round(time.perf_counter() - t0, 6)
            profile = None
            if reprofile:
                profile = self.reprofile()
                stats["reprofiled"] = True
            if engine is not None:
                t0 = time.perf_counter()
                swap = engine.hot_swap(idx, profile=profile, **hot_swap_kw)
                stats["seconds"]["swap"] = round(
                    time.perf_counter() - t0, 6
                )
                if isinstance(swap, dict):
                    stats["swap"] = {
                        k: swap[k]
                        for k in ("seconds", "backend_compiles")
                        if k in swap
                    }
            with self._lock:
                self._index = idx
                # the torn-publish boundary: index swapped, counter not
                # yet bumped — a kill here must replay to a clean epoch
                _faults.maybe_fail("epoch.publish")
                self._epoch = epoch
        _telemetry.record(
            "epoch_published", epoch=epoch, chips=stats["chips"],
            token=idx.epoch_token,
        )
        return stats

    def reprofile(self):
        """Re-profile the CURRENT column through `tune` (the ROADMAP
        rule: re-adapt knobs as the data mutates, on epoch boundaries)."""
        from ..tune import profile_polygons, recommend

        prof = profile_polygons(self.column(), self.system)
        tuning = recommend(prof)
        _telemetry.record(
            "epoch_reprofile", epoch=self._applied,
            geoms=len(self._order),
        )
        return tuning

    # ---------------------------------------------------------- compact

    def compact(self, *, background: bool = False):
        """Fold tombstones into a fresh base block and, when a log is
        bound, write a compacted snapshot sealing the truncated prefix's
        chain fingerprint (sidecar ``prev``), then truncate every older
        record. The delta chain itself is untouched — the next delta
        still chains from the last delta's hash — so a kill at ANY
        compaction boundary leaves replay consistent: before the
        snapshot is durable the old records still replay; after it, the
        snapshot wins and the leftovers are ignored.

        ``background=True`` runs on a worker thread (telemetry sinks,
        trace context and fault plans adopted) and returns the thread.
        """
        if background:
            sinks = _telemetry.current_sinks()
            ctx = _telemetry.current_trace()
            plans = _faults.current_plans()

            def work():
                _telemetry.adopt_sinks(sinks)
                _telemetry.adopt_trace(ctx)
                _faults.adopt_plans(plans)
                try:
                    self.compact()
                except Exception as e:  # lint: broad-except-ok (a failed background compaction degrades to a bigger log, never takes down serving; the telemetry event is the signal)
                    _telemetry.record(
                        "epoch_compact_failed", error=repr(e)[:200]
                    )

            t = threading.Thread(
                target=work, name="epoch-compact", daemon=True
            )
            t.start()
            return t

        stats = {"epoch": self._applied, "seconds": 0.0, "truncated": 0}
        with _trace.span("epoch.compact", epoch=self._applied):
            t0 = time.perf_counter()
            with _telemetry.timed("epoch_stage", stage="compact"):
                _faults.maybe_fail("epoch.compact")  # pre-snapshot
                with self._lock:
                    epoch = self._applied
                    sealed = self._chain
                table = self._materialize(labels="gid")
                column = self.column()
                with self._lock:
                    gids = np.asarray(self._order, np.int64)
                if self._log is not None:
                    arrays = dict(_col_arrays(column), ids=gids)
                    payload, sha, chain = _encode_record(arrays, sealed)
                    meta = dict(
                        self._build_meta(), sealed=sealed, epoch=epoch,
                        series=self._series,
                    )
                    self._log.write(
                        "compact", epoch, payload, sha, sealed, chain,
                        meta,
                    )
                    # post-snapshot, pre-truncation boundary: both the
                    # snapshot and the prefix exist — replay prefers the
                    # snapshot, the leftovers are dead weight
                    _faults.maybe_fail("epoch.compact")
                    for kind, e in self._log.entries():
                        if e <= epoch and not (
                            kind == "compact" and e == epoch
                        ):
                            self._log.unlink(kind, e)
                            stats["truncated"] += 1
                _faults.maybe_fail("epoch.compact")  # post-truncation
                with self._lock:
                    if self._applied == epoch:
                        self._blocks = [{
                            "table": table,
                            "dead": np.zeros(len(table), dtype=bool),
                        }]
                        self._deltas_since_compact = 0
            stats["seconds"] = round(time.perf_counter() - t0, 6)
            stats["rows"] = len(table)
        _telemetry.record("epoch_compacted", **stats)
        return stats

    # ----------------------------------------------------------- replay

    @classmethod
    def replay(
        cls,
        log_dir: str,
        index_system,
        *,
        engine=None,
        publish: bool = True,
        upto: int | None = None,
        log_max: int | None = None,
    ) -> "EpochalIndex":
        """Reconstruct the index from its delta log after a kill.

        Starts from the newest VALID compacted snapshot (falling back to
        the base record while a half-written compaction is just tail
        residue), verifies every subsequent delta's checksum and chain
        hash, truncates a corrupt TAIL (the kill-mid-write residue,
        ``epoch_log_truncated`` telemetry), and refuses typed on
        anything worse: a damaged interior record raises
        :class:`EpochLogCorrupt`, a chain that does not bind raises
        :class:`EpochFingerprintMismatch`. The result is bit-identical
        to a from-scratch rebuild of the surviving epoch — ``upto``
        stops early at a historical epoch for audits."""
        log = _DeltaLog(log_dir)
        entries = log.entries()
        if not entries:
            raise EpochLogCorrupt(
                f"no delta log under {log_dir!r}", log_dir=log_dir
            )
        with _trace.span("epoch.replay", log_dir=log_dir), \
                _telemetry.timed("epoch_stage", stage="replay"):
            # newest valid compact wins; an invalid one is kill residue
            # as long as older records can still replay past it
            start = None
            compacts = sorted(
                (e for k, e in entries if k == "compact"), reverse=True
            )
            if upto is not None:
                compacts = [e for e in compacts if e <= upto]
            for e in compacts:
                try:
                    sidecar, arrays = log.load("compact", e)
                except (OSError, ValueError) as err:
                    _telemetry.record(
                        "epoch_log_truncated", log_dir=log_dir,
                        kind="compact", epoch=e, error=repr(err)[:200],
                    )
                    continue
                start = (e, sidecar, arrays)
                break
            if start is None:
                if not any(k == "base" for k, _ in entries):
                    raise EpochLogCorrupt(
                        f"no base record and no valid compacted "
                        f"snapshot under {log_dir!r}",
                        log_dir=log_dir,
                    )
                try:
                    sidecar, arrays = log.load("base", 0)
                except (OSError, ValueError) as err:
                    raise EpochLogCorrupt(
                        f"base record under {log_dir!r} failed "
                        f"validation: {err}", log_dir=log_dir, epoch=0,
                    ) from err
                start = (0, sidecar, arrays)

            start_epoch, sidecar, arrays = start
            meta = sidecar.get("meta", {})
            if meta.get("system") != type(index_system).__name__:
                raise EpochFingerprintMismatch(
                    f"log under {log_dir!r} was written for index "
                    f"system {meta.get('system')!r}, not "
                    f"{type(index_system).__name__!r}",
                    expected=str(meta.get("system")),
                    actual=type(index_system).__name__,
                )
            self = cls(
                None, index_system, int(meta["resolution"]),
                keep_core_geoms=bool(meta["keep_core_geoms"]),
                dtype=np.dtype(meta["dtype"]),
                max_chips_per_cell=meta.get("max_chips_per_cell"),
                recenter=bool(meta.get("recenter", True)),
                log_max=log_max,
                _defer_base=True,
            )
            self._log = log
            col = _col_from_arrays(arrays)
            gids = [int(g) for g in arrays["ids"]]
            for i, g in enumerate(gids):
                self._geoms[g] = col.take([i])
            self._order = gids
            if gids:
                table = tessellate_subset(
                    col, np.arange(len(gids)), self.system,
                    self.resolution, self.keep_core_geoms,
                    geom_ids=np.asarray(gids, np.int64),
                )
                self._blocks = [{
                    "table": table,
                    "dead": np.zeros(len(table), dtype=bool),
                }]
            self._applied = start_epoch
            self._chain = sidecar["prev"] if sidecar["kind"] == "compact" \
                else sidecar["chain"]
            self._series = (
                sidecar["chain"] if sidecar["kind"] == "base"
                else meta.get("series", sidecar["chain"])
            )

            deltas = sorted(e for k, e in entries if k == "delta")
            deltas = [e for e in deltas if e > start_epoch]
            if upto is not None:
                deltas = [e for e in deltas if e <= upto]
            expect = start_epoch + 1
            for i, e in enumerate(deltas):
                tail = i == len(deltas) - 1
                if e != expect:
                    raise EpochLogCorrupt(
                        f"delta epoch {expect} missing under "
                        f"{log_dir!r} (next present: {e})",
                        log_dir=log_dir, epoch=expect,
                    )
                try:
                    rec, arrays = log.load("delta", e)
                except (OSError, ValueError) as err:
                    if tail:
                        _telemetry.record(
                            "epoch_log_truncated", log_dir=log_dir,
                            kind="delta", epoch=e,
                            error=repr(err)[:200],
                        )
                        log.unlink("delta", e)
                        break
                    raise EpochLogCorrupt(
                        f"delta {e} under {log_dir!r} failed "
                        f"validation with valid successors: {err}",
                        log_dir=log_dir, epoch=e,
                    ) from err
                if rec.get("prev") != self._chain:
                    raise EpochFingerprintMismatch(
                        f"delta {e} under {log_dir!r} chains from "
                        f"{rec.get('prev')!r}, expected {self._chain!r}",
                        expected=self._chain,
                        actual=str(rec.get("prev")), epoch=e,
                    )
                upsert = _col_from_arrays(arrays)
                ids = np.asarray(arrays["ids"], np.int64)
                remove = np.asarray(arrays["removed"], np.int64)
                n_up = ids.shape[0]
                delta = (
                    tessellate_subset(
                        upsert, np.arange(n_up), self.system,
                        self.resolution, self.keep_core_geoms,
                        geom_ids=ids,
                    )
                    if n_up
                    else None
                )
                with self._lock:
                    self._patch(upsert, ids, remove, delta)
                    self._chain = rec["chain"]
                    self._applied = e
                    self._deltas_since_compact += 1
                expect += 1
        _telemetry.record(
            "epoch_replayed", log_dir=log_dir, epoch=self._applied,
            start=start_epoch,
        )
        if publish:
            self.publish(engine)
        return self
