"""Mutable-index layer: epochal, durable, crash-consistent chip
indexes (`epoch.py`). Not to be confused with `mosaic_tpu.core.index`,
the grid index *systems* (H3/BNG/custom) — this package owns index
*instances* that change over time.
"""

from __future__ import annotations

from ..runtime.errors import EpochFingerprintMismatch, EpochLogCorrupt
from .epoch import EpochalIndex, chip_index_equal

__all__ = [
    "EpochalIndex",
    "chip_index_equal",
    "EpochLogCorrupt",
    "EpochFingerprintMismatch",
]
