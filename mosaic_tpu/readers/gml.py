"""GML and GPX vector readers (stdlib XML, no GDAL).

Reference analog: the any-OGR-driver datasource reads GML and GPX through
GDAL (`datasource/OGRFileFormat.scala:26-473`); here the two formats are
parsed directly with ``xml.etree.ElementTree`` into the shared
:class:`~.vector.VectorTable`.

GML (2.1 ``coordinates`` and 3.x ``posList``/``pos`` forms): feature
members with Point / LineString / Polygon (exterior+interior) /
MultiPoint / MultiCurve / MultiSurface / MultiGeometry; non-geometry
child elements with text become attribute columns; ``srsName`` EPSG
codes are honored per geometry.

GPX 1.1: waypoints (``wpt``) as points, routes (``rte``) and track
segments (``trk``/``trkseg``) as linestrings, with name/ele/time
attributes. GPX is always WGS84 by spec.
"""

from __future__ import annotations

from xml.etree import ElementTree

import numpy as np

from ..core.crs import parse_crs_code
from ..core.types import GeometryBuilder, GeometryType, open_ring
from ._xml import find as _find, local as _local


def _srid_of(el, default: int) -> int:
    name = el.get("srsName")
    if not name:
        return default
    try:
        return parse_crs_code(name.rsplit(":", 1)[-1])
    except (ValueError, TypeError):
        return default


_GML_GEOMS = (
    "Point", "LineString", "LinearRing", "Polygon", "MultiPoint",
    "MultiCurve", "MultiSurface", "MultiGeometry", "MultiLineString",
    "MultiPolygon", "Curve", "Surface",
)


def _seg_coords(el, dim_hint: int) -> tuple[np.ndarray, np.ndarray | None]:
    """Coordinates of one posList/pos/coordinates carrier element."""
    pl = _find(el, "posList")
    if pl is not None:
        vals = np.asarray((pl.text or "").split(), dtype=np.float64)
        attr = pl.get("srsDimension", el.get("srsDimension"))
        if attr is not None:
            dim = int(attr)
        else:
            # real-world GML omits srsDimension on 3-D posLists; prefer a
            # dimension that actually divides the token count over blindly
            # assuming the hint. Token counts divisible by both 2 and 3
            # stay genuinely ambiguous — the hint wins those (a 3-D list
            # with an even point count still parses as 2-D).
            cands = [d for d in (dim_hint, 2, 3) if len(vals) % d == 0]
            if not cands:
                raise ValueError(
                    f"posList has {len(vals)} values, divisible by "
                    "neither 2 nor 3"
                )
            dim = cands[0]
        vals = vals.reshape(-1, dim)
        z = vals[:, 2].copy() if dim >= 3 else None
        return np.ascontiguousarray(vals[:, :2]), z
    pos = [c for c in el.iter() if _local(c.tag) == "pos"]
    if pos:
        rows = [np.asarray((p.text or "").split(), dtype=np.float64) for p in pos]
        dim = min(len(r) for r in rows)
        vals = np.stack([r[:dim] for r in rows])
        z = vals[:, 2].copy() if dim >= 3 else None
        return np.ascontiguousarray(vals[:, :2]), z
    co = _find(el, "coordinates")
    if co is not None:
        rows = [
            [float(v) for v in t.split(",") if v]
            for t in (co.text or "").split()
        ]
        if rows:
            dim = min(len(r) for r in rows)
            vals = np.asarray([r[:dim] for r in rows])
            z = vals[:, 2].copy() if dim >= 3 else None
            return np.ascontiguousarray(vals[:, :2]), z
    return np.zeros((0, 2)), None


def _gml_coords(el, dim_hint: int = 2) -> tuple[np.ndarray, np.ndarray | None]:
    """All coordinates of one GML geometry node. A multi-segment Curve
    concatenates its LineStringSegments (dropping each segment's repeated
    joint vertex); everything else is a single coordinate carrier."""
    segs = [c for c in el.iter() if _local(c.tag) == "LineStringSegment"]
    if segs:
        xs, zs, has_z = [], [], False
        for k, s in enumerate(segs):
            xy, z = _seg_coords(s, dim_hint)
            if k and xs and xy.shape[0] and np.array_equal(xs[-1][-1:], xy[:1]):
                xy = xy[1:]
                z = None if z is None else z[1:]
            if xy.shape[0]:
                xs.append(xy)
                zs.append(z)
                has_z = has_z or z is not None
        if not xs:
            return np.zeros((0, 2)), None
        xy = np.concatenate(xs)
        z = (
            np.concatenate([
                z if z is not None else np.full(x.shape[0], np.nan)
                for x, z in zip(xs, zs)
            ])
            if has_z
            else None
        )
        return xy, z
    return _seg_coords(el, dim_hint)


def _gml_rings(poly, dim_hint: int) -> list[tuple[np.ndarray, np.ndarray | None]]:
    """exterior ring then interiors (2.1 outer/innerBoundaryIs too)."""
    dim_hint = int(poly.get("srsDimension", dim_hint))
    rings = []
    for role in ("exterior", "outerBoundaryIs"):
        r = _find(poly, role)
        if r is not None:
            rings.append(open_ring(*_gml_coords(r, dim_hint)))
    for c in poly.iter():
        if _local(c.tag) in ("interior", "innerBoundaryIs"):
            rings.append(open_ring(*_gml_coords(c, dim_hint)))
    return rings


_POINTISH = ("Point",)
_LINEISH = ("LineString", "LinearRing", "Curve")
_POLYISH = ("Polygon", "Surface")


def _append_gml(b: GeometryBuilder, el, srid: int) -> "GeometryType | None":
    """Parse one GML geometry into ``b``; returns the appended type.

    Mixed-member MultiGeometry resolves with the first-polygonal
    collection rule the codecs share (`core/geometry/collection.py`)."""
    kind = _local(el.tag)
    srid = _srid_of(el, srid)
    dim = int(el.get("srsDimension", "2"))
    if kind in _POINTISH:
        xy, z = _gml_coords(el, dim)
        b.add_ring(xy[:1], None if z is None else z[:1])
        b.end_part()
        b.end_geom(GeometryType.POINT, srid)
        return GeometryType.POINT
    if kind in _LINEISH:
        b.add_ring(*_gml_coords(el, dim))
        b.end_part()
        b.end_geom(GeometryType.LINESTRING, srid)
        return GeometryType.LINESTRING
    if kind in _POLYISH:
        for xy, z in _gml_rings(el, dim):
            b.add_ring(xy, z)
        b.end_part()
        b.end_geom(GeometryType.POLYGON, srid)
        return GeometryType.POLYGON
    if kind == "MultiPoint":
        for m in el.iter():
            if _local(m.tag) == "Point":
                xy, z = _gml_coords(m, dim)
                b.add_ring(xy[:1], None if z is None else z[:1])
                b.end_part()
        b.end_geom(GeometryType.MULTIPOINT, srid)
        return GeometryType.MULTIPOINT
    if kind in ("MultiCurve", "MultiLineString"):
        for m in el.iter():
            if _local(m.tag) in ("LineString", "Curve"):
                b.add_ring(*_gml_coords(m, dim))
                b.end_part()
        b.end_geom(GeometryType.MULTILINESTRING, srid)
        return GeometryType.MULTILINESTRING
    if kind in ("MultiSurface", "MultiPolygon"):
        n = 0
        for m in el.iter():
            if _local(m.tag) == "Polygon":
                for xy, z in _gml_rings(m, dim):
                    b.add_ring(xy, z)
                b.end_part()
                n += 1
        if not n:
            b.end_part()
        b.end_geom(
            GeometryType.MULTIPOLYGON if n else GeometryType.POLYGON, srid
        )
        return GeometryType.MULTIPOLYGON
    if kind == "MultiGeometry":
        # members may mix types: parse each top-level member geometry and
        # resolve with the shared collection rule
        from ..core.geometry.collection import end_collection

        members = []
        for wrap in el:  # geometryMember wrappers or direct members
            cand = (
                wrap
                if _local(wrap.tag) in _GML_GEOMS
                else next(
                    (c for c in wrap if _local(c.tag) in _GML_GEOMS), None
                )
            )
            if cand is None:
                continue
            sub = GeometryBuilder()
            declared = _append_gml(sub, cand, srid)
            if declared is not None:
                members.append((declared, sub.build()))
        if not members:
            b.end_part()
            b.end_geom(GeometryType.GEOMETRYCOLLECTION, srid)
            return GeometryType.GEOMETRYCOLLECTION
        kinds = {d.base for d, _ in members}
        if len(kinds) == 1 and GeometryType.GEOMETRYCOLLECTION not in kinds:
            base = kinds.pop()
            for _, m in members:
                hz = m.has_z(0)
                for p in m.geom_parts(0):
                    for r in m.part_rings(p):
                        b.add_ring(
                            m.ring_xy(r), m.ring_z(r) if hz else None
                        )
                    b.end_part()
            b.end_geom(GeometryType(int(base) + 3), srid)
            return GeometryType(int(base) + 3)
        end_collection(b, members, srid)
        return GeometryType.GEOMETRYCOLLECTION
    return None


def read_gml(path, srid: int = 4326):
    """Parse a GML feature collection into a VectorTable."""
    from .vector import VectorTable

    root = ElementTree.parse(str(path)).getroot()
    b = GeometryBuilder()
    rows: list[dict[str, str]] = []
    members = [
        c
        for m in root.iter()
        if _local(m.tag) in ("featureMember", "featureMembers", "member")
        for c in m
    ] or [root]
    for feat in members:
        geom = None
        attrs: dict[str, str] = {}
        # a feature's properties are its direct children: one holds a GML
        # geometry descendant (the geometry column), text leaves are
        # attributes
        for prop in feat:
            ln = _local(prop.tag)
            if ln in _GML_GEOMS:
                geom = geom or prop
                continue
            g = next(
                (c for c in prop.iter() if _local(c.tag) in _GML_GEOMS),
                None,
            )
            if g is not None:
                geom = geom or g
            elif len(prop) == 0 and prop.text and prop.text.strip():
                attrs[ln] = prop.text.strip()
        if geom is not None and _append_gml(b, geom, srid) is not None:
            rows.append(attrs)
    col = b.build()
    keys = sorted({k for r in rows for k in r})
    return VectorTable(
        geometry=col,
        columns={
            k: np.asarray([r.get(k, "") for r in rows], dtype=object)
            for k in keys
        },
    )


# ------------------------------------------------------------------- GPX


def read_gpx(path):
    """Parse a GPX 1.1 file: wpt -> POINT, rte/trkseg -> LINESTRING."""
    from .vector import VectorTable

    root = ElementTree.parse(str(path)).getroot()
    b = GeometryBuilder()
    rows: list[dict[str, str]] = []

    def pt_of(el):
        return float(el.get("lon")), float(el.get("lat"))

    def attrs_of(el, kind):
        a = {"kind": kind}
        for c in el:
            if _local(c.tag) in ("name", "time", "ele", "desc") and c.text:
                a[_local(c.tag)] = c.text.strip()
        return a

    for el in root.iter():
        ln = _local(el.tag)
        if ln == "wpt":
            x, y = pt_of(el)
            ele = _find(el, "ele")
            z = (
                np.asarray([float(ele.text)])
                if ele is not None and ele.text
                else None
            )
            b.add_ring(np.asarray([[x, y]]), z)
            b.end_part()
            b.end_geom(GeometryType.POINT, 4326)
            rows.append(attrs_of(el, "wpt"))
        elif ln == "rte":
            xy = np.asarray(
                [pt_of(p) for p in el if _local(p.tag) == "rtept"]
            ).reshape(-1, 2)
            b.add_ring(xy, None)
            b.end_part()
            b.end_geom(GeometryType.LINESTRING, 4326)
            rows.append(attrs_of(el, "rte"))
        elif ln == "trk":
            # segments become rows carrying the enclosing track's
            # name/time attributes
            trk_attrs = attrs_of(el, "trkseg")
            for seg in el.iter():
                if _local(seg.tag) != "trkseg":
                    continue
                xy = np.asarray(
                    [pt_of(p) for p in seg if _local(p.tag) == "trkpt"]
                ).reshape(-1, 2)
                b.add_ring(xy, None)
                b.end_part()
                b.end_geom(GeometryType.LINESTRING, 4326)
                rows.append(dict(trk_attrs))
    col = b.build()
    keys = sorted({k for r in rows for k in r})
    return VectorTable(
        geometry=col,
        columns={
            k: np.asarray([r.get(k, "") for r in rows], dtype=object)
            for k in keys
        },
    )
