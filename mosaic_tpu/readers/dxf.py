"""AutoCAD DXF (ASCII) entity reader.

Reference analog: another slice of `OGRFileFormat`'s any-driver breadth
(`datasource/OGRFileFormat.scala:26-47` — OGR ships a DXF driver); CAD
site plans routinely arrive as DXF in geospatial pipelines.

Reads the ENTITIES section's 2-D geometry, mapping like OGR's driver:
POINT → POINT, LINE → LINESTRING, LWPOLYLINE / POLYLINE+VERTEX →
LINESTRING (closed flag 70 bit 1 → POLYGON), CIRCLE → POLYGON
(64-gon, OGR's tessellated analog). Each entity carries its layer
(code 8) as the ``layer`` column. 3-D codes (30/38) are ignored —
the column contract is 2-D like every other reader here.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from ..core.types import GeometryBuilder, GeometryType
from .vector import VectorTable


def _pairs(path: Path):
    """DXF is (group-code, value) line pairs."""
    lines = path.read_text(errors="replace").splitlines()
    for k in range(0, len(lines) - 1, 2):
        try:
            yield int(lines[k].strip()), lines[k + 1].strip()
        except ValueError:
            continue


def read_dxf(path: str) -> VectorTable:
    """Read `path` (.dxf) into a VectorTable with a ``layer`` column."""
    b = GeometryBuilder()
    layers: list[str] = []

    in_entities = False
    ent: str | None = None
    layer = ""
    data: dict[int, list[float]] = {}
    poly_pts: list[list[float]] = []  # POLYLINE ... VERTEX ... SEQEND
    poly_closed = False
    poly_layer = ""
    in_poly = False

    def emit(kind: str, lay: str, d: dict[int, list[float]]):
        # incomplete entities (missing paired codes) are skipped, not
        # fatal — a truncated CAD export should not lose the whole file
        xs, ys = d.get(10, []), d.get(20, [])
        if kind == "POINT" and xs and ys:
            b.add_geometry(
                GeometryType.POINT, [[np.asarray([[xs[0], ys[0]]])]], 0
            )
            layers.append(lay)
        elif kind == "LINE" and xs and ys and d.get(11) and d.get(21):
            xy = np.asarray(
                [[xs[0], ys[0]], [d[11][0], d[21][0]]]
            )
            b.add_geometry(GeometryType.LINESTRING, [[xy]], 0)
            layers.append(lay)
        elif kind == "LWPOLYLINE" and min(len(xs), len(ys)) >= 2:
            k = min(len(xs), len(ys))
            xy = np.stack([xs[:k], ys[:k]], axis=-1)
            closed = int(d.get(70, [0])[0]) & 1
            if closed and k >= 3:
                b.add_geometry(GeometryType.POLYGON, [[xy]], 0)
            else:
                b.add_geometry(GeometryType.LINESTRING, [[xy]], 0)
            layers.append(lay)
        elif kind == "CIRCLE" and xs and ys and d.get(40):
            t = np.linspace(0.0, 2 * np.pi, 65)[:-1]
            xy = np.stack(
                [xs[0] + d[40][0] * np.cos(t), ys[0] + d[40][0] * np.sin(t)],
                axis=-1,
            )
            b.add_geometry(GeometryType.POLYGON, [[xy]], 0)
            layers.append(lay)

    for code, val in _pairs(Path(path)):
        if code == 0:
            # close out the pending simple entity
            if ent in ("POINT", "LINE", "LWPOLYLINE", "CIRCLE") and in_entities:
                emit(ent, layer, data)
            if val == "SECTION":
                ent = "SECTION"
            elif val == "ENDSEC":
                in_entities = False
                ent = None
            elif val == "EOF":
                break
            elif in_entities:
                if val == "POLYLINE":
                    in_poly = True
                    poly_pts = []
                    poly_closed = False
                    poly_layer = ""
                    ent = "POLYLINE"
                elif val == "VERTEX" and in_poly:
                    ent = "VERTEX"
                elif val == "SEQEND" and in_poly:
                    if len(poly_pts) >= 2:
                        xy = np.asarray(poly_pts)
                        if poly_closed and len(poly_pts) >= 3:
                            b.add_geometry(GeometryType.POLYGON, [[xy]], 0)
                        else:
                            b.add_geometry(
                                GeometryType.LINESTRING, [[xy]], 0
                            )
                        layers.append(poly_layer)
                    in_poly = False
                    ent = None
                else:
                    ent = val
            data = {}
            layer = ""
            continue
        if ent == "SECTION" and code == 2:
            in_entities = val.upper() == "ENTITIES"
        elif in_entities:
            if code == 8:
                if ent == "POLYLINE":
                    poly_layer = val
                else:
                    layer = val
            elif code == 70 and ent == "POLYLINE":
                poly_closed = bool(int(val) & 1)
            elif code in (10, 20, 11, 21, 40, 70):
                try:
                    v = float(val)
                except ValueError:
                    continue
                if ent == "VERTEX" and code in (10, 20):
                    if code == 10:
                        poly_pts.append([v, 0.0])
                    elif poly_pts:
                        poly_pts[-1][1] = v
                else:
                    data.setdefault(code, []).append(v)

    return VectorTable(
        geometry=b.build(),
        columns={"layer": np.asarray(layers)} if layers else {},
    )
