"""Minimal HDF5 reader for NetCDF-4 ingestion (pure host decode, no GDAL).

Reference analog: GDAL's netCDF driver behind `MosaicRasterGDAL.readRaster`
(`core/raster/MosaicRasterGDAL.scala:182-187`; the reference's
`binary/netcdf-coral` fixtures exercise it). This is NOT a general HDF5
implementation — it supports exactly the structures netCDF-4 writes for
gridded products, verified against those fixtures:

- superblock v2/v3 (v0 accepted when the root group uses v2 object headers)
- version-2 object headers (OHDR) + OCHK continuation blocks
- compact Link messages (dense/fractal-heap groups are rejected clearly)
- dataspace v1/v2; fixed-point and IEEE-float datatypes; fill values
- data layout v3: contiguous and chunked (v1 B-tree chunk index)
- filter pipeline v1/v2: shuffle + deflate (fletcher32 checksums stripped)
- compact Attribute messages (v1/v3); densely stored attributes are
  skipped (netCDF-4 stores them densely when creation order is tracked —
  callers must not rely on attrs being complete)
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

_UNDEF = 0xFFFFFFFFFFFFFFFF


class H5Lite:
    def __init__(self, path: str):
        self.path = path
        self._d = open(path, "rb").read()
        d = self._d
        if d[:8] != b"\x89HDF\r\n\x1a\n":
            raise ValueError(f"{path!r} is not an HDF5 file")
        ver = d[8]
        if ver in (2, 3):
            if d[9] != 8 or d[10] != 8:
                raise ValueError("only 8-byte offsets/lengths supported")
            # sig(8) ver(1) szoff(1) szlen(1) flags(1) base(8) ext(8)
            # eof(8) root(8)
            root = struct.unpack("<Q", d[36:44])[0]
        elif ver == 0:
            # v0: prefix(24) base(8) freespace(8) eof(8) driverinfo(8),
            # then the root symbol-table entry: linkname(8) + OHDR addr.
            # The object header may still be v1 (unsupported) — probed and
            # rejected in _messages with a clear error.
            if d[13] != 8 or d[14] != 8:
                raise ValueError("only 8-byte offsets/lengths supported")
            root = struct.unpack("<Q", d[64:72])[0]
        else:
            raise ValueError(f"HDF5 superblock v{ver} unsupported")
        self._vars: dict[str, int] = {}
        self._info_cache: dict[str, dict] = {}
        self._walk_group(root, "")

    # ------------------------------------------------------------ messages
    def _messages(self, off: int):
        d = self._d
        if d[off : off + 4] != b"OHDR":
            raise ValueError(
                "version-1 object headers unsupported (netCDF-4 files "
                "written with format=NETCDF4 use version 2)"
            )
        flags = d[off + 5]
        p = off + 6
        if flags & 0x20:
            p += 16  # four 4-byte timestamps
        if flags & 0x10:
            p += 4
        sb = 1 << (flags & 0x3)
        size = int.from_bytes(d[p : p + sb], "little")
        p += sb
        blocks = [(p, p + size)]
        out = []
        while blocks:
            q, e = blocks.pop()
            while q < e - 3:
                mt = d[q]
                ms = struct.unpack("<H", d[q + 1 : q + 3])[0]
                q += 4
                if flags & 0x04:
                    q += 2
                if mt == 16:  # continuation
                    addr, ln = struct.unpack("<QQ", d[q : q + 16])
                    if d[addr : addr + 4] == b"OCHK":
                        blocks.append((addr + 4, addr + ln - 4))
                else:
                    out.append((mt, q, ms))
                q += ms
        return out

    def _walk_group(self, off: int, prefix: str):
        for mt, mp, ms in self._messages(off):
            if mt != 6:
                continue
            name, addr = self._parse_link(mp)
            full = f"{prefix}/{name}" if prefix else name
            kinds = {m[0] for m in self._messages(addr)}
            if 8 in kinds or 3 in kinds:  # layout/datatype => dataset
                self._vars[full] = addr
            else:
                self._walk_group(addr, full)

    def _parse_link(self, mp: int):
        d = self._d
        lflags = d[mp + 1]
        q = mp + 2
        if lflags & 0x08:
            if d[q] != 0:  # 0 = hard link; soft/external have a path body
                raise ValueError("soft/external HDF5 links unsupported")
            q += 1
        if lflags & 0x04:
            q += 8
        if lflags & 0x10:
            q += 1
        lsz = 1 << (lflags & 0x3)
        nlen = int.from_bytes(d[q : q + lsz], "little")
        q += lsz
        name = d[q : q + nlen].decode("utf-8", "replace")
        addr = struct.unpack("<Q", d[q + nlen : q + nlen + 8])[0]
        return name, addr

    # ------------------------------------------------------------ datasets
    def datasets(self) -> list[str]:
        return sorted(self._vars)

    def _dataset_info(self, name: str) -> dict:
        if name not in self._vars:
            raise ValueError(f"no dataset {name!r} in {self.path!r}")
        d = self._d
        info: dict = {"filters": [], "fill": None, "attrs": {}}
        for mt, mp, ms in self._messages(self._vars[name]):
            if mt == 1:  # dataspace
                ver, rank = d[mp], d[mp + 1]
                base = mp + 4 if ver == 2 else mp + 8
                info["shape"] = struct.unpack(
                    f"<{rank}Q", d[base : base + 8 * rank]
                )
            elif mt == 3:  # datatype
                info["dtype"] = self._parse_dtype(mp)
            elif mt == 5:  # fill value (v2/v3)
                ver = d[mp]
                if ver == 3:
                    flags = d[mp + 1]
                    if flags & 0x20:
                        n = struct.unpack("<I", d[mp + 2 : mp + 6])[0]
                        info["fill_raw"] = d[mp + 6 : mp + 6 + n]
                elif ver == 2 and d[mp + 3]:
                    n = struct.unpack("<I", d[mp + 4 : mp + 8])[0]
                    info["fill_raw"] = d[mp + 8 : mp + 8 + n]
            elif mt == 8:  # layout
                ver = d[mp]
                if ver != 3:
                    raise ValueError(f"data layout v{ver} unsupported")
                cls = d[mp + 1]
                if cls == 1:  # contiguous
                    addr, sz = struct.unpack("<QQ", d[mp + 2 : mp + 18])
                    info["layout"] = ("contiguous", addr, sz)
                elif cls == 2:  # chunked: rank includes the element-size dim
                    rank = d[mp + 2]
                    addr = struct.unpack("<Q", d[mp + 3 : mp + 11])[0]
                    cdims = struct.unpack(
                        f"<{rank}I", d[mp + 11 : mp + 11 + 4 * rank]
                    )
                    info["layout"] = ("chunked", addr, cdims[:-1])
                elif cls == 0:  # compact
                    sz = struct.unpack("<H", d[mp + 2 : mp + 4])[0]
                    info["layout"] = ("compact", mp + 4, sz)
                else:
                    raise ValueError(f"layout class {cls} unsupported")
            elif mt == 11:  # filter pipeline
                info["filters"] = self._parse_filters(mp)
            elif mt == 12:  # compact attribute
                try:
                    k, v = self._parse_attr(mp)
                    info["attrs"][k] = v
                except Exception:  # lint: broad-except-ok (attrs are best-effort; densely stored ones skip)
                    pass
        if "shape" not in info or "dtype" not in info:
            raise ValueError(f"dataset {name!r} missing dataspace/datatype")
        return info

    def _parse_dtype(self, mp: int) -> np.dtype:
        d = self._d
        cls = d[mp] & 0x0F
        bits0 = d[mp + 1]
        size = struct.unpack("<I", d[mp + 4 : mp + 8])[0]
        if cls == 0:  # fixed point
            signed = bool(bits0 & 0x08)
            return np.dtype(f"{'<' if not (bits0 & 1) else '>'}{'i' if signed else 'u'}{size}")
        if cls == 1:  # float (assume IEEE)
            return np.dtype(f"{'<' if not (bits0 & 1) else '>'}f{size}")
        raise ValueError(f"datatype class {cls} unsupported")

    def _parse_filters(self, mp: int):
        d = self._d
        ver, nf = d[mp], d[mp + 1]
        q = mp + (8 if ver == 1 else 2)
        out = []
        for _ in range(nf):
            fid = struct.unpack("<H", d[q : q + 2])[0]
            if ver == 1 or fid >= 256:
                # fid(2) namelen(2) flags(2) ncv(2) name[padded for v1]
                nlen = struct.unpack("<H", d[q + 2 : q + 4])[0]
                ncv = struct.unpack("<H", d[q + 6 : q + 8])[0]
                q += 8 + nlen + ((-nlen) % 8 if ver == 1 else 0)
            else:
                # v2, known filter: fid(2) flags(2) ncv(2) — no name field
                ncv = struct.unpack("<H", d[q + 4 : q + 6])[0]
                q += 6
            cvals = struct.unpack(f"<{ncv}I", d[q : q + 4 * ncv])
            q += 4 * ncv
            if ver == 1 and ncv % 2:
                q += 4
            out.append((fid, cvals))
        return out

    def _parse_attr(self, mp: int):
        d = self._d
        ver = d[mp]
        if ver == 3:
            nsz, dsz, ssz = struct.unpack("<HHH", d[mp + 2 : mp + 8])
            q = mp + 9  # + name charset byte
            name = d[q : q + nsz].split(b"\0")[0].decode()
            q += nsz
            dt = self._parse_dtype(q)
            q += dsz
            rank = d[q + 1]
            dver = d[q]
            base = q + (4 if dver == 2 else 8)
            shape = struct.unpack(f"<{rank}Q", d[base : base + 8 * rank])
            q += ssz
            n = int(np.prod(shape)) if rank else 1
            val = np.frombuffer(d[q : q + n * dt.itemsize], dtype=dt)
            return name, (val[0] if n == 1 else val)
        raise ValueError(f"attribute v{ver} unsupported")

    # ---------------------------------------------------------------- read
    def attrs(self, name: str) -> dict:
        return self._info_cached(name)["attrs"]

    def _info_cached(self, name: str) -> dict:
        if name not in self._info_cache:
            self._info_cache[name] = self._dataset_info(name)
        return self._info_cache[name]

    def fill_value(self, name: str):
        info = self._info_cached(name)
        raw = info.get("fill_raw")
        if not raw:
            return None
        return np.frombuffer(raw[: info["dtype"].itemsize], dtype=info["dtype"])[0]

    def read(self, name: str) -> np.ndarray:
        info = self._info_cached(name)
        shape = tuple(int(s) for s in info["shape"])
        dt = info["dtype"]
        kind, addr, extra = info["layout"]
        d = self._d
        if kind == "contiguous":
            if addr == _UNDEF:
                return np.full(shape, self.fill_value(name) or 0, dtype=dt)
            n = int(np.prod(shape)) if shape else 1
            return (
                np.frombuffer(d[addr : addr + n * dt.itemsize], dtype=dt)
                .reshape(shape)
                .copy()
            )
        if kind == "compact":
            n = int(np.prod(shape)) if shape else 1
            return (
                np.frombuffer(d[addr : addr + n * dt.itemsize], dtype=dt)
                .reshape(shape)
                .copy()
            )
        chunk = tuple(int(c) for c in extra)
        fill = self.fill_value(name)
        out = np.full(shape, 0 if fill is None else fill, dtype=dt)
        if addr != _UNDEF:
            for coff, csize, fmask, caddr in self._btree_chunks(addr, len(chunk)):
                raw = d[caddr : caddr + csize]
                block = self._defilter(raw, info["filters"], fmask, dt, chunk)
                sl = tuple(
                    slice(o, min(o + c, s))
                    for o, c, s in zip(coff, chunk, shape)
                )
                out[sl] = block[tuple(slice(0, q.stop - q.start) for q in sl)]
        return out

    def _btree_chunks(self, addr: int, rank: int):
        """Walk a v1 B-tree of chunked raw data; yield
        (offsets, nbytes, filter_mask, address)."""
        d = self._d
        stack = [addr]
        while stack:
            node = stack.pop()
            if node == _UNDEF or d[node : node + 4] != b"TREE":
                continue
            level = d[node + 5]
            used = struct.unpack("<H", d[node + 6 : node + 8])[0]
            q = node + 8 + 16  # skip siblings
            key_sz = 8 + (rank + 1) * 8
            for i in range(used):
                nbytes, fmask = struct.unpack("<II", d[q : q + 8])
                offs = struct.unpack(
                    f"<{rank + 1}Q", d[q + 8 : q + 8 + (rank + 1) * 8]
                )[:-1]
                child = struct.unpack(
                    "<Q", d[q + key_sz : q + key_sz + 8]
                )[0]
                if level == 0:
                    yield offs, nbytes, fmask, child
                else:
                    stack.append(child)
                q += key_sz + 8
        return

    def _defilter(self, raw: bytes, filters, fmask, dt, chunk):
        n = int(np.prod(chunk))
        for i, (fid, cvals) in enumerate(reversed(filters)):
            if fmask & (1 << (len(filters) - 1 - i)):
                continue
            if fid == 1:  # deflate
                raw = zlib.decompress(raw)
            elif fid == 2:  # shuffle
                es = cvals[0] if cvals else dt.itemsize
                arr = np.frombuffer(raw, dtype=np.uint8)
                m = arr.size // es
                raw = (
                    arr[: m * es].reshape(es, m).T.reshape(-1).tobytes()
                )
            elif fid == 3:  # fletcher32: strip the trailing checksum
                raw = raw[:-4]
            else:
                raise ValueError(f"HDF5 filter {fid} unsupported")
        return np.frombuffer(raw[: n * dt.itemsize], dtype=dt).reshape(chunk)


def read_netcdf(path: str, variable: str | None = None):
    """NetCDF-4 grid -> Raster (lat/lon coordinate variables define the
    geotransform; 2-D+ variables become bands)."""
    from ..raster.core import Raster

    h5 = H5Lite(path)
    names = h5.datasets()
    candidates = []
    for n in names:
        shape = h5._info_cached(n)["shape"]
        if (
            len(shape) >= 2
            and int(np.prod(shape)) > 1
            and not n.split("/")[-1].endswith(("_bnds", "_bounds"))
        ):
            candidates.append(n)
    # CF files carry auxiliary 2-D variables (bounds, char arrays): keep
    # only the variables sharing the DOMINANT trailing 2-D shape
    from collections import Counter

    tails = Counter(
        tuple(h5._info_cached(n)["shape"][-2:]) for n in candidates
    )
    grids = []
    if tails:
        # largest grid wins (aux char arrays / station tables are small);
        # count only breaks ties between equal-sized grids
        best = max(tails.items(), key=lambda kv: (kv[0][0] * kv[0][1], kv[1]))[0]
        grids = [
            n
            for n in candidates
            if tuple(h5._info_cached(n)["shape"][-2:]) == best
        ]
    if variable is not None:
        if variable not in names:
            raise ValueError(f"no variable {variable!r}; have {names}")
        if len(h5._info_cached(variable)["shape"]) < 2:
            raise ValueError(f"variable {variable!r} is not gridded")
        grids = [variable]
    if not grids:
        raise ValueError(f"no gridded variables in {path!r}; have {names}")
    lat = next((n for n in names if n.split("/")[-1] in ("lat", "latitude")), None)
    lon = next((n for n in names if n.split("/")[-1] in ("lon", "longitude")), None)
    bands = []
    fills = set()
    for g in grids:
        arr = h5.read(g)
        # leading (time/level) dims become extra bands
        arr3 = arr.reshape(-1, arr.shape[-2], arr.shape[-1])
        f = h5.fill_value(g)
        for sl in arr3:
            a = sl.astype(np.float64)
            if f is not None:
                a[sl == f] = np.nan
                fills.add(float(f))
            bands.append(a)
    shapes = {b.shape for b in bands}
    if len(shapes) > 1:
        raise ValueError(f"variables have different grids: {shapes}")
    h, w = bands[0].shape
    if lat is not None and lon is not None:
        la = h5.read(lat).astype(np.float64)
        lo = h5.read(lon).astype(np.float64)
        dy = (la[-1] - la[0]) / max(la.size - 1, 1)
        dx = (lo[-1] - lo[0]) / max(lo.size - 1, 1)
        north_up = dy < 0
        top = la[0] if north_up else la[-1]
        gt = (lo[0] - dx / 2, dx, 0.0, top + abs(dy) / 2, 0.0, -abs(dy))
        flip = not north_up
    else:
        gt = (0.0, 1.0, 0.0, 0.0, 0.0, -1.0)
        flip = False
    data = np.stack([(b[::-1] if flip else b) for b in bands])
    return Raster(
        data=data,
        gt=gt,
        srid=4326,
        nodata=float("nan") if fills else None,
        meta_xml="",
        path=path,
    )
