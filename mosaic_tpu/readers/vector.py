"""Vector file readers: Shapefile (.shp/.dbf), GeoJSON, CSV points.

Reference analog: `datasource/OGRFileFormat.scala:26-473` (any OGR driver ->
rows with WKB + attribute columns, schema inferred by scanning features) and
the pinned-driver subclasses (`ShapefileFileFormat.scala:11-47`). Without
GDAL, the two formats the reference's test-suite exercises most — ESRI
Shapefile and GeoJSON — are decoded natively here; both produce a
:class:`VectorTable` (PackedGeometry column + numpy attribute columns), the
columnar analog of the OGR feature rows.

The ESRI shapefile main/dBASE formats are public specs; this decoder is
written to the spec, not to any other implementation.
"""

from __future__ import annotations

import dataclasses
import struct
from pathlib import Path

import numpy as np

from ..core.geometry.geojson import read_feature_collection
from ..core.types import GeometryBuilder, GeometryType, PackedGeometry


@dataclasses.dataclass
class VectorTable:
    """Geometry column + attribute columns (the OGR feature table analog)."""

    geometry: PackedGeometry
    columns: dict[str, np.ndarray]

    def __len__(self) -> int:
        return len(self.geometry)

    def slice(self, start: int, stop: int) -> "VectorTable":
        idx = list(range(start, min(stop, len(self))))
        return VectorTable(
            geometry=self.geometry.take(idx),
            columns={k: v[start:stop] for k, v in self.columns.items()},
        )


# ------------------------------------------------------------- shapefile

_SHP_NULL = 0
_SHP_POINT = 1
_SHP_POLYLINE = 3
_SHP_POLYGON = 5
_SHP_MULTIPOINT = 8
# Z/M variants share geometry layout with extra coordinate blocks
_SHP_Z = {11: 1, 13: 3, 15: 5, 18: 8, 21: 1, 23: 3, 25: 5, 28: 8}


def _read_shp(path: Path, srid: int) -> PackedGeometry:
    d = path.read_bytes()
    if len(d) < 100 or struct.unpack(">i", d[:4])[0] != 9994:
        raise ValueError(f"not a shapefile: {path}")
    b = GeometryBuilder()
    o = 100
    n = len(d)
    while o + 8 <= n:
        (_recno, clen) = struct.unpack(">ii", d[o : o + 8])
        o += 8
        rec = d[o : o + 2 * clen]
        o += 2 * clen
        if len(rec) < 4:
            break
        (stype,) = struct.unpack("<i", rec[:4])
        base = _SHP_Z.get(stype, stype)
        if stype == _SHP_NULL:
            b.add_geometry(GeometryType.POINT, [[np.zeros((0, 2))]], srid)
        elif base == _SHP_POINT:
            x, y = struct.unpack("<dd", rec[4:20])
            b.add_geometry(GeometryType.POINT, [[np.array([[x, y]])]], srid)
        elif base == _SHP_MULTIPOINT:
            (npts,) = struct.unpack("<i", rec[36:40])
            pts = np.frombuffer(rec, "<f8", 2 * npts, 40).reshape(-1, 2)
            b.add_geometry(
                GeometryType.MULTIPOINT, [[p[None, :]] for p in pts], srid
            )
        elif base in (_SHP_POLYLINE, _SHP_POLYGON):
            nparts, npts = struct.unpack("<ii", rec[36:44])
            parts = np.frombuffer(rec, "<i4", nparts, 44)
            pts = np.frombuffer(
                rec, "<f8", 2 * npts, 44 + 4 * nparts
            ).reshape(-1, 2)
            rings = [
                np.array(pts[parts[i] : (parts[i + 1] if i + 1 < nparts else npts)])
                for i in range(nparts)
            ]
            if base == _SHP_POLYLINE:
                b.add_geometry(
                    GeometryType.MULTILINESTRING if len(rings) > 1 else GeometryType.LINESTRING,
                    [[r] for r in rings],
                    srid,
                )
            else:
                _emit_shp_polygon(b, rings, srid)
        else:
            raise ValueError(f"unsupported shape type {stype}")
    return b.build()


def _emit_shp_polygon(b: GeometryBuilder, rings: list[np.ndarray], srid: int):
    """Shapefile polygons: CW rings are shells, CCW are holes; holes belong
    to the preceding shell (spec ordering). Drop the closing vertex."""
    from ..core.types import open_ring, ring_signed_area

    polys: list[list[np.ndarray]] = []
    for r in rings:
        xy, _ = open_ring(r)
        if xy.shape[0] < 3:
            continue
        if ring_signed_area(xy) <= 0 or not polys:  # CW in shp = shell
            polys.append([xy])
        else:
            polys[-1].append(xy)
    if not polys:
        b.add_geometry(GeometryType.POLYGON, [[np.zeros((0, 2))]], srid)
    elif len(polys) == 1:
        b.add_geometry(GeometryType.POLYGON, [polys[0]], srid)
    else:
        b.add_geometry(GeometryType.MULTIPOLYGON, polys, srid)


def _read_dbf(path: Path) -> dict[str, np.ndarray]:
    """dBASE III attribute table -> typed numpy columns (the OGR field
    type-coercion analog, `OGRFileFormat.scala:156-203`)."""
    if not path.exists():
        return {}
    d = path.read_bytes()
    if len(d) < 32:
        return {}
    nrec = struct.unpack("<I", d[4:8])[0]
    hdr_len, rec_len = struct.unpack("<HH", d[8:12])
    fields = []
    o = 32
    while o + 32 <= hdr_len - 1 and d[o] != 0x0D:
        raw = d[o : o + 32]
        name = raw[:11].split(b"\0")[0].decode("ascii", "replace")
        ftype = chr(raw[11])
        flen = raw[16]
        fdec = raw[17]
        fields.append((name, ftype, flen, fdec))
        o += 32
    cols: dict[str, list] = {f[0]: [] for f in fields}
    o = hdr_len
    for _ in range(nrec):
        if o + rec_len > len(d):
            break
        rec = d[o : o + rec_len]
        o += rec_len
        p = 1  # skip deletion flag
        for name, ftype, flen, fdec in fields:
            raw = rec[p : p + flen]
            p += flen
            s = raw.decode("latin-1").strip()
            if ftype in ("N", "F"):
                try:
                    cols[name].append(float(s) if (fdec or "." in s) else int(s))
                except ValueError:
                    cols[name].append(np.nan if (fdec or "." in s) else 0)
            elif ftype == "L":
                cols[name].append(s.upper() in ("T", "Y"))
            else:
                cols[name].append(s)
    out: dict[str, np.ndarray] = {}
    for name, ftype, flen, fdec in fields:
        vals = cols[name]
        if ftype in ("N", "F"):
            want_int = not fdec and ftype == "N"
            try:
                out[name] = np.asarray(
                    vals, dtype=np.int64 if want_int else np.float64
                )
            except (ValueError, OverflowError):
                # malformed cells fell back to NaN: keep the column as float
                out[name] = np.asarray(vals, dtype=np.float64)
        elif ftype == "L":
            out[name] = np.asarray(vals, dtype=bool)
        else:
            out[name] = np.asarray(vals, dtype=object)
    return out


def _read_prj_srid(path: Path) -> int:
    """srid from the .prj WKT — fully parsed when possible.

    `core.crs_wkt.register_prj_text` lowers the WKT1 tree to a PROJ
    string and registers it (declared EPSG code, or a stable synthetic
    code), so `st_transform` works for ANY projection family the CRS
    engine implements, not just a recognized-name allowlist. Malformed
    or exotic WKT falls back to the old substring heuristic."""
    if not path.exists():
        return 4326
    text = path.read_text(errors="replace")
    try:
        from ..core.crs_wkt import register_prj_text

        return register_prj_text(text)
    except Exception:  # lint: broad-except-ok (WKT registry miss falls back to the keyword heuristic)
        up = text.upper()
        if "OSGB" in up or "27700" in up:
            return 27700
        if "PSEUDO-MERCATOR" in up or "3857" in up:
            return 3857
        return 4326


def read_shapefile(path: str) -> VectorTable:
    """ESRI Shapefile (+ sidecar .dbf attributes, .prj CRS hint)."""
    p = Path(path)
    srid = _read_prj_srid(p.with_suffix(".prj"))
    geom = _read_shp(p, srid)
    cols = _read_dbf(p.with_suffix(".dbf"))
    cols = {k: v[: len(geom)] for k, v in cols.items()}
    return VectorTable(geometry=geom, columns=cols)


# --------------------------------------------------------------- geojson


def props_to_columns(props: "list[dict | None]") -> dict[str, np.ndarray]:
    """Feature properties -> typed columns: all-numeric keys become float
    arrays (None -> NaN), everything else an object array. Shared by the
    GeoJSON and TopoJSON readers so both type columns identically."""
    keys: list[str] = []
    for pr in props:
        for k in pr or {}:
            if k not in keys:
                keys.append(k)
    cols: dict[str, np.ndarray] = {}
    for k in keys:
        vals = [(pr or {}).get(k) for pr in props]
        if all(isinstance(v, (int, float, type(None))) and not isinstance(v, bool) for v in vals):
            cols[k] = np.asarray(
                [np.nan if v is None else float(v) for v in vals]
            )
        else:
            cols[k] = np.asarray(vals, dtype=object)
    return cols


def read_geojson(path_or_obj) -> VectorTable:
    """GeoJSON FeatureCollection -> VectorTable (properties as columns)."""
    geom, props = read_feature_collection(path_or_obj)
    return VectorTable(geometry=geom, columns=props_to_columns(props))


# ------------------------------------------------------------ CSV points


def read_points_csv(
    path: str,
    lon_col: str,
    lat_col: str,
    max_rows: "int | None" = None,
) -> VectorTable:
    """Point table from CSV (the NYC-taxi trips ingestion path)."""
    import csv

    lons: list[float] = []
    lats: list[float] = []
    with open(path, newline="") as f:
        rd = csv.DictReader(f)
        for i, row in enumerate(rd):
            if max_rows is not None and i >= max_rows:
                break
            try:
                lons.append(float(row[lon_col]))
                lats.append(float(row[lat_col]))
            except (ValueError, KeyError):
                lons.append(np.nan)
                lats.append(np.nan)
    from ..functions.formats import st_point

    geom = st_point(np.asarray(lons), np.asarray(lats))
    return VectorTable(
        geometry=geom,
        columns={lon_col: np.asarray(lons), lat_col: np.asarray(lats)},
    )


def read_wkt_csv(
    path: str,
    wkt_col: str = "wkt",
    srid: int = 4326,
    max_rows: "int | None" = None,
) -> VectorTable:
    """CSV with a WKT geometry column (OGR "CSV" driver semantics: the
    GEOM_POSSIBLE_NAMES field parses as WKT, other columns ride along)."""
    import csv

    from ..core.geometry.wkt import from_wkt

    wkts: list[str] = []
    rows: list[dict] = []
    with open(path, newline="") as f:
        rd = csv.DictReader(f)
        if rd.fieldnames is None or wkt_col not in rd.fieldnames:
            raise ValueError(
                f"no column {wkt_col!r} in {path}; have {rd.fieldnames}"
            )
        for i, row in enumerate(rd):
            if max_rows is not None and i >= max_rows:
                break
            wkts.append(row.pop(wkt_col) or "GEOMETRYCOLLECTION EMPTY")
            rows.append(row)
    geom = from_wkt(wkts, srid=srid)
    keys = rd.fieldnames or []
    cols = {
        k: np.asarray([r.get(k) for r in rows], dtype=object)
        for k in keys
        if k != wkt_col
    }
    return VectorTable(geometry=geom, columns=cols)


# ------------------------------------------------- multiread (chunked)


def multiread(
    paths: "list[str] | str",
    reader=None,
    chunk_size: int = 5000,
    workers: int = 8,
) -> VectorTable:
    """Parallel chunked reads: partition = file x chunk (reference:
    `OGRMultiReadDataFrameReader.load:25-77` computes
    partitionCount = 1 + featureCount/chunkSize). Thread pool stands in for
    Spark tasks; chunk tables are concatenated columnar."""
    from concurrent.futures import ThreadPoolExecutor

    if isinstance(paths, str):
        paths = [paths]
    if reader is None:
        reader = open_any

    def load(p):
        return reader(p)

    with ThreadPoolExecutor(max_workers=workers) as ex:
        tables = list(ex.map(load, paths))
    # chunked re-partition of each table (parallelism seam for downstream)
    chunks: list[VectorTable] = []
    for t in tables:
        for s in range(0, max(len(t), 1), chunk_size):
            chunks.append(t.slice(s, s + chunk_size))
    return concat_tables(chunks)


def concat_tables(tables: "list[VectorTable]") -> VectorTable:
    tables = [t for t in tables if len(t)]
    if not tables:
        raise ValueError("no rows")
    b = GeometryBuilder()
    for t in tables:
        for g in range(len(t.geometry)):
            b.append_from(t.geometry, g)
    keys = {k for t in tables for k in t.columns}
    cols = {}
    for k in keys:
        parts = [
            t.columns.get(k, np.full(len(t), np.nan)) for t in tables
        ]
        try:
            cols[k] = np.concatenate(parts)
        except (TypeError, ValueError):
            cols[k] = np.concatenate([np.asarray(p, dtype=object) for p in parts])
    return VectorTable(geometry=b.build(), columns=cols)


def open_any(path: str) -> VectorTable:
    s = str(path).lower()
    if s.endswith(".shp"):
        return read_shapefile(path)
    if s.endswith((".json", ".geojson")):
        return read_geojson(path)
    if s.endswith(".kml"):
        from .kml import read_kml

        return read_kml(path)
    if s.endswith(".gml"):
        from .gml import read_gml

        return read_gml(path)
    if s.endswith(".gpx"):
        from .gml import read_gpx

        return read_gpx(path)
    if s.endswith(".mif"):
        from .mif import read_mif

        return read_mif(path)
    if s.endswith(".dxf"):
        from .dxf import read_dxf

        return read_dxf(path)
    if s.endswith(".gpkg"):
        from .geopackage import read_geopackage

        return read_geopackage(path)
    if s.endswith(".topojson"):
        from .topojson import read_topojson

        return read_topojson(path)
    if s.endswith(".fgb"):
        from .flatgeobuf import read_flatgeobuf

        return read_flatgeobuf(path)
    if s.endswith(".osm"):
        from .osm import read_osm

        return read_osm(path)
    if s.endswith((".geojsonl", ".ndjson", ".geojsons")):
        return read_geojson(path)  # newline-delimited handled natively
    raise ValueError(f"no reader for {path}")


# --------------------------------------------------------------- writers


def _feature_props(table: VectorTable, i: int) -> dict:
    """Row ``i``'s columns as JSON-safe properties (NaN -> null)."""
    props: dict = {}
    for k, col in table.columns.items():
        v = col[i]
        if isinstance(v, (np.floating, float)):
            props[k] = None if np.isnan(v) else float(v)
        elif isinstance(v, (np.integer, int)):
            props[k] = int(v)
        elif isinstance(v, (np.bool_, bool)):
            props[k] = bool(v)
        elif v is None:
            props[k] = None
        else:
            props[k] = str(v)
    return props


def write_geojson(path: str, table: VectorTable, seq: bool = False) -> None:
    """Write a :class:`VectorTable` as a GeoJSON FeatureCollection, or —
    with ``seq`` — as newline-delimited GeoJSONSeq (one feature per
    line, the OGR GeoJSONSeq driver's format). Round-trips through
    :func:`read_geojson` / ``read("geojsonseq")``.

    Reference analog: writing vector output through OGR drivers
    (`datasource/OGRFileFormat.scala:26-47`); the reference's write side
    goes through Spark writers, so this columnar writer is the native
    equivalent surface.
    """
    import json as _json

    from ..core.geometry.geojson import to_geojson_obj

    geoms = to_geojson_obj(table.geometry)
    feats = [
        {
            "type": "Feature",
            "geometry": g,
            "properties": _feature_props(table, i),
        }
        for i, g in enumerate(geoms)
    ]
    with open(path, "w") as f:
        if seq:
            for ft in feats:
                f.write(_json.dumps(ft) + "\n")
        else:
            _json.dump({"type": "FeatureCollection", "features": feats}, f)


def write_shapefile(path: str, table: VectorTable, srid: int = 4326) -> None:
    """Write a :class:`VectorTable` as an ESRI Shapefile (.shp/.shx/.dbf,
    plus a minimal .prj). One shape type per file (the format's rule):
    the type is taken from the first non-empty geometry; empties become
    NULL shapes. Rings are written in shapefile orientation (shells CW,
    holes CCW — the packed column stores the opposite, so each closed
    ring is emitted reversed). Round-trips through
    :func:`read_shapefile`.

    Reference analog: OGR's "ESRI Shapefile" driver on the write side
    (`datasource/OGRFileFormat.scala:26-47` names the driver; the
    reference writes through Spark/OGR, this is the native equivalent).
    """
    from ..core.types import GeometryType

    p = Path(path)
    col = table.geometry
    G = len(col)

    def base_type(g):
        gt = col.geometry_type(g).base
        if gt == GeometryType.POINT and col.geometry_type(g) == (
            GeometryType.MULTIPOINT
        ):
            return _SHP_MULTIPOINT
        return {
            GeometryType.POINT: _SHP_POINT,
            GeometryType.MULTIPOINT: _SHP_MULTIPOINT,
            GeometryType.LINESTRING: _SHP_POLYLINE,
            GeometryType.POLYGON: _SHP_POLYGON,
        }[gt]

    shape_type = _SHP_NULL
    for g in range(G):
        if col.geom_xy(g).shape[0]:
            t = base_type(g)
            if shape_type == _SHP_NULL:
                shape_type = t
            elif shape_type != t:
                raise ValueError(
                    "shapefiles hold ONE shape type; got both "
                    f"{shape_type} and {t}"
                )

    def rings_of(g):
        out = []
        for pt in col.geom_parts(g):
            for r in col.part_rings(pt):
                xy = col.ring_xy(r)
                if xy.shape[0]:
                    out.append(np.asarray(xy, dtype=np.float64))
        return out

    recs: list[bytes] = []
    for g in range(G):
        gt = col.geometry_type(g)
        xy = np.asarray(col.geom_xy(g), dtype=np.float64)
        if xy.shape[0] == 0:
            recs.append(struct.pack("<i", _SHP_NULL))
            continue
        if shape_type == _SHP_POINT:
            recs.append(struct.pack("<idd", 1, xy[0, 0], xy[0, 1]))
        elif shape_type == _SHP_MULTIPOINT:
            bb = (xy[:, 0].min(), xy[:, 1].min(), xy[:, 0].max(), xy[:, 1].max())
            recs.append(
                struct.pack("<i4di", 8, *bb, xy.shape[0]) + xy.tobytes()
            )
        else:
            rings = rings_of(g)
            if shape_type == _SHP_POLYGON and gt.base == GeometryType.POLYGON:
                # packed shells are CCW / holes CW; shp wants the reverse
                rings = [r[::-1] for r in rings]
            pts = np.concatenate(rings, axis=0)
            parts, off = [], 0
            for r in rings:
                parts.append(off)
                off += r.shape[0]
            bb = (
                pts[:, 0].min(), pts[:, 1].min(),
                pts[:, 0].max(), pts[:, 1].max(),
            )
            recs.append(
                struct.pack("<i4dii", shape_type, *bb, len(rings), off)
                + np.asarray(parts, "<i4").tobytes()
                + np.ascontiguousarray(pts).tobytes()
            )

    vb = [col.geom_xy(g) for g in range(G) if col.geom_xy(g).shape[0]]
    allv = np.concatenate(vb, axis=0) if vb else np.zeros((1, 2))
    bbox = (
        float(allv[:, 0].min()), float(allv[:, 1].min()),
        float(allv[:, 0].max()), float(allv[:, 1].max()),
    )

    def header(total_words: int) -> bytes:
        return (
            struct.pack(">i5i i", 9994, 0, 0, 0, 0, 0, total_words)
            + struct.pack("<ii", 1000, shape_type)
            + struct.pack("<4d", *bbox)
            + struct.pack("<4d", 0, 0, 0, 0)
        )

    shp = bytearray()
    shx = bytearray()
    off_words = 50
    for i, rec in enumerate(recs):
        clen = len(rec) // 2
        shp += struct.pack(">ii", i + 1, clen) + rec
        shx += struct.pack(">ii", off_words, clen)
        off_words += 4 + clen
    p.with_suffix(".shp").write_bytes(header(off_words) + shp)
    p.with_suffix(".shx").write_bytes(header(50 + 4 * G) + shx)

    # DBF: N for numerics, L for bools, C otherwise
    fields = []
    for k, v in table.columns.items():
        a = np.asarray(v)
        if np.issubdtype(a.dtype, np.floating):
            fields.append((k[:10], "N", 19, 7))
        elif np.issubdtype(a.dtype, np.integer):
            fields.append((k[:10], "N", 18, 0))
        elif a.dtype == bool:
            fields.append((k[:10], "L", 1, 0))
        else:
            w = max([1] + [len(str(x).encode("latin-1", "replace"))
                           for x in a])
            fields.append((k[:10], "C", min(254, w), 0))
    rec_len = 1 + sum(f[2] for f in fields)
    hdr_len = 33 + 32 * len(fields)
    dbf = bytearray(
        struct.pack("<BBBBIHH20x", 3, 26, 7, 31, G, hdr_len, rec_len)
    )
    for name, ft, fl, fd in fields:
        dbf += struct.pack(
            "<11sc4xBB14x", name.encode("ascii", "replace"), ft.encode(),
            fl, fd,
        )
    dbf += b"\x0d"
    names = list(table.columns)
    for g in range(G):
        dbf += b" "
        for (name, ft, fl, fd), k in zip(fields, names):
            v = table.columns[k][g]
            if ft == "N":
                s = (f"{v:.{fd}f}" if fd else str(int(v))) if not (
                    isinstance(v, float) and np.isnan(v)
                ) else ""
                dbf += s.rjust(fl)[:fl].encode("ascii", "replace")
            elif ft == "L":
                dbf += b"T" if v else b"F"
            else:
                dbf += str(v).encode("latin-1", "replace")[:fl].ljust(fl)
    dbf += b"\x1a"
    p.with_suffix(".dbf").write_bytes(dbf)

    prj = {
        4326: 'GEOGCS["GCS_WGS_1984",DATUM["D_WGS_1984",SPHEROID'
              '["WGS_1984",6378137.0,298.257223563]],PRIMEM["Greenwich",0.0],'
              'UNIT["Degree",0.0174532925199433],'
              'AUTHORITY["EPSG","4326"]]',
        27700: 'PROJCS["British_National_Grid_OSGB",GEOGCS["GCS_OSGB_1936",'
               'DATUM["D_OSGB_1936",SPHEROID["Airy_1830",6377563.396,'
               '299.3249646]],PRIMEM["Greenwich",0.0],UNIT["Degree",'
               '0.0174532925199433]],PROJECTION["Transverse_Mercator"],'
               'PARAMETER["latitude_of_origin",49],'
               'PARAMETER["central_meridian",-2],'
               'PARAMETER["scale_factor",0.9996012717],'
               'PARAMETER["false_easting",400000],'
               'PARAMETER["false_northing",-100000],UNIT["metre",1],'
               'AUTHORITY["EPSG","27700"]]',
        3857: 'PROJCS["WGS_1984_Web_Mercator_Auxiliary_Sphere(Pseudo-Mercator)"'
              ',GEOGCS["GCS_WGS_1984",DATUM["D_WGS_1984",SPHEROID["WGS_1984",'
              '6378137.0,298.257223563]],PRIMEM["Greenwich",0.0],'
              'UNIT["Degree",0.0174532925199433]],'
              'PROJECTION["Mercator_Auxiliary_Sphere"],'
              'UNIT["Meter",1.0],AUTHORITY["EPSG","3857"]]',
    }.get(srid)
    if prj:
        p.with_suffix(".prj").write_text(prj)
