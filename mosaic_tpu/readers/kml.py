"""KML vector reader (stdlib XML, no GDAL).

Reference analog: the any-OGR-driver datasource reads KML through GDAL's
LIBKML driver (`datasource/OGRFileFormat.scala:26-473`, driver picked by
extension); here OGC KML 2.2 is parsed directly with
``xml.etree.ElementTree`` into the same :class:`VectorTable` the other
vector readers produce. Handled: ``Document``/``Folder`` nesting,
``Placemark`` with Point / LineString / LinearRing / Polygon
(outer+inner boundaries) / MultiGeometry, 2D/3D ``coordinates`` tuples,
``name`` and ``ExtendedData`` (both ``Data/value`` and
``SchemaData/SimpleData`` forms) as attribute columns. KML coordinates
are always lon/lat WGS84 (EPSG:4326) by spec.
"""

from __future__ import annotations

from xml.etree import ElementTree

import numpy as np

from ..core.types import GeometryBuilder, GeometryType, open_ring
from ._xml import children as _children, find as _find, local as _local


def _coords(el) -> tuple[np.ndarray, np.ndarray | None]:
    """Parse a <coordinates> text block: 'lon,lat[,alt]' whitespace-
    separated tuples."""
    text = (el.text or "").strip()
    if not text:
        return np.zeros((0, 2)), None
    # drop empty tokens: trailing commas ("lon,lat,") are common in
    # hand-written KML and must not count as a dimension
    rows = [[v for v in t.split(",") if v] for t in text.split()]
    dims = min(len(r) for r in rows)
    vals = np.asarray(
        [[float(v) for v in r[:dims]] for r in rows], dtype=np.float64
    )
    z = vals[:, 2].copy() if dims >= 3 else None
    return np.ascontiguousarray(vals[:, :2]), z


def _append_geometry(b: GeometryBuilder, el) -> "GeometryType | None":
    """Parse one KML geometry element into ``b``.

    Returns the DECLARED type (the role the element plays in collection
    resolution): a mixed-member MultiGeometry reports
    GEOMETRYCOLLECTION even though its content coerces, so an enclosing
    MultiGeometry's first-polygonal rule never selects it — the same
    nested-collection contract as the WKT/WKB/GeoJSON codecs.
    """
    kind = _local(el.tag)
    if kind == "Point":
        c = _find(el, "coordinates")
        xy, z = _coords(c) if c is not None else (np.zeros((0, 2)), None)
        b.add_ring(xy[:1], None if z is None else z[:1])
        b.end_part()
        b.end_geom(GeometryType.POINT, 4326)
        return GeometryType.POINT
    if kind in ("LineString", "LinearRing"):
        c = _find(el, "coordinates")
        xy, z = _coords(c) if c is not None else (np.zeros((0, 2)), None)
        b.add_ring(xy, z)
        b.end_part()
        b.end_geom(GeometryType.LINESTRING, 4326)
        return GeometryType.LINESTRING
    if kind == "Polygon":
        for boundary in ("outerBoundaryIs", "innerBoundaryIs"):
            for bnd in _children(el, boundary):
                ring = _find(bnd, "coordinates")
                if ring is None:
                    continue
                xy, z = open_ring(*_coords(ring))
                b.add_ring(xy, z)
        b.end_part()
        b.end_geom(GeometryType.POLYGON, 4326)
        return GeometryType.POLYGON
    if kind == "MultiGeometry":
        # homogeneous members collapse to the matching MULTI type; mixed
        # members resolve with the collection rule the codecs share
        members: list[tuple[GeometryType, object]] = []
        kinds: set[str] = set()
        for g_el in el:
            if not _is_geometry_tag(g_el):
                continue
            sub = GeometryBuilder()
            declared = _append_geometry(sub, g_el)
            members.append((declared, sub.build()))
            kinds.add(_local(g_el.tag))
        if not members:
            b.end_part()
            b.end_geom(GeometryType.GEOMETRYCOLLECTION, 4326)
            return GeometryType.GEOMETRYCOLLECTION
        if kinds <= {"Point"}:
            gt = GeometryType.MULTIPOINT
        elif kinds <= {"LineString", "LinearRing"}:
            gt = GeometryType.MULTILINESTRING
        elif kinds <= {"Polygon"}:
            gt = GeometryType.MULTIPOLYGON
        else:
            from ..core.geometry.collection import end_collection

            end_collection(b, members, 4326)
            return GeometryType.GEOMETRYCOLLECTION
        # copy every member's rings as parts of one multi-geometry
        for _, m in members:
            hz = m.has_z(0)
            for p in m.geom_parts(0):
                for r in m.part_rings(p):
                    b.add_ring(m.ring_xy(r), m.ring_z(r) if hz else None)
                b.end_part()
        b.end_geom(gt, 4326)
        return gt
    return None


def _is_geometry_tag(el) -> bool:
    return _local(el.tag) in (
        "Point", "LineString", "LinearRing", "Polygon", "MultiGeometry"
    )


def _placemark_attrs(pm) -> dict[str, str]:
    attrs: dict[str, str] = {}
    for c in pm:
        if _local(c.tag) == "name":
            attrs["name"] = (c.text or "").strip()
        elif _local(c.tag) == "ExtendedData":
            for d in c.iter():
                ln = _local(d.tag)
                if ln == "Data":
                    v = _find(d, "value")
                    attrs[d.get("name", "")] = (
                        (v.text or "").strip() if v is not None else ""
                    )
                elif ln == "SimpleData":
                    attrs[d.get("name", "")] = (d.text or "").strip()
    attrs.pop("", None)
    return attrs


def read_kml(path):
    """Parse a KML file into a :class:`~.vector.VectorTable`."""
    from .vector import VectorTable

    root = ElementTree.parse(str(path)).getroot()
    b = GeometryBuilder()
    rows: list[dict[str, str]] = []
    for pm in root.iter():
        if _local(pm.tag) != "Placemark":
            continue
        geom = next((g for g in pm if _is_geometry_tag(g)), None)
        if geom is None:
            continue
        if _append_geometry(b, geom) is not None:
            rows.append(_placemark_attrs(pm))
    col = b.build()
    keys = sorted({k for r in rows for k in r})
    columns = {
        k: np.asarray([r.get(k, "") for r in rows], dtype=object)
        for k in keys
    }
    return VectorTable(geometry=col, columns=columns)


def write_kml(path: str, table, name_col: "str | None" = None) -> None:
    """Write a VectorTable as KML Placemarks (round-trips through
    :func:`read_kml`): Point / LineString / Polygon (outer+inner
    boundaries) / MultiGeometry, attributes as ExtendedData/Data values.

    Reference analog: OGR's KML driver write side
    (`datasource/OGRFileFormat.scala:26-47` names the driver family)."""
    import numpy as np

    from ..core.types import GeometryType

    col = table.geometry

    def coords(xy):
        return " ".join(
            f"{float(x)!r},{float(y)!r}" for x, y in np.asarray(xy)
        )

    def polygon(rings):
        out = ["<Polygon>"]
        for k, r in enumerate(rings):
            r = np.asarray(r)
            if r.shape[0] and not np.array_equal(r[0], r[-1]):
                r = np.concatenate([r, r[:1]])
            tag = "outerBoundaryIs" if k == 0 else "innerBoundaryIs"
            out.append(
                f"<{tag}><LinearRing><coordinates>{coords(r)}"
                f"</coordinates></LinearRing></{tag}>"
            )
        out.append("</Polygon>")
        return "".join(out)

    def geometry(g):
        gt = col.geometry_type(g)
        base = gt.base
        if base == GeometryType.POINT and gt == GeometryType.MULTIPOINT:
            pts = np.asarray(col.geom_xy(g))
            return (
                "<MultiGeometry>"
                + "".join(
                    f"<Point><coordinates>{coords(p[None])}"
                    "</coordinates></Point>"
                    for p in pts
                )
                + "</MultiGeometry>"
            )
        if base == GeometryType.POINT:
            return (
                f"<Point><coordinates>{coords(col.geom_xy(g))}"
                "</coordinates></Point>"
            )
        if base == GeometryType.LINESTRING:
            parts = [
                f"<LineString><coordinates>{coords(col.ring_xy(r))}"
                "</coordinates></LineString>"
                for p in col.geom_parts(g)
                for r in col.part_rings(p)
            ]
            if len(parts) == 1:
                return parts[0]
            return "<MultiGeometry>" + "".join(parts) + "</MultiGeometry>"
        # polygons: one <Polygon> per part (shell + holes)
        polys = [
            polygon([col.ring_xy(r) for r in col.part_rings(p)])
            for p in col.geom_parts(g)
        ]
        if len(polys) == 1:
            return polys[0]
        return "<MultiGeometry>" + "".join(polys) + "</MultiGeometry>"

    def esc(s):
        return (
            str(s)
            .replace("&", "&amp;")
            .replace("<", "&lt;")
            .replace(">", "&gt;")
        )

    def esc_attr(s):
        # attribute values additionally need quote escaping (the
        # xml.sax.saxutils.quoteattr contract): a column name carrying
        # '"' would otherwise terminate the name="..." attribute early
        return esc(s).replace('"', "&quot;").replace("'", "&apos;")

    rows = []
    for g in range(len(col)):
        nm = (
            f"<name>{esc(table.columns[name_col][g])}</name>"
            if name_col and name_col in table.columns
            else ""
        )
        data = "".join(
            f'<Data name="{esc_attr(k)}"><value>{esc(v[g])}</value></Data>'
            for k, v in table.columns.items()
            if k != name_col
        )
        ext = f"<ExtendedData>{data}</ExtendedData>" if data else ""
        rows.append(f"<Placemark>{nm}{ext}{geometry(g)}</Placemark>")
    doc = (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        '<kml xmlns="http://www.opengis.net/kml/2.2"><Document>'
        + "".join(rows)
        + "</Document></kml>"
    )
    with open(path, "w") as f:
        f.write(doc)
