"""GeoPackage (OGC .gpkg) vector reader — stdlib sqlite3, no GDAL.

Reference analog: the OGR "GPKG" driver behind `OGRFileFormat`
(`datasource/OGRFileFormat.scala:26-473`): feature tables are discovered
through `gpkg_contents`/`gpkg_geometry_columns`, attribute columns become
typed arrays, and geometries are decoded from the GeoPackage geometry blob
(GP magic + envelope-flagged header, then standard WKB) into the packed
columnar layout.
"""

from __future__ import annotations

import sqlite3
import struct

import numpy as np

from ..core.geometry import wkb as _wkb
from .vector import VectorTable


def _parse_gpkg_blob(blob: bytes) -> tuple[bytes, int]:
    """GeoPackage geometry blob -> (wkb bytes, srid).

    Header: magic 'GP', version, flags (envelope size bits 1-3, empty bit
    4, byte-order bit 0), int32 srs_id, optional envelope of 0/32/48/64
    bytes, then WKB.
    """
    if len(blob) < 8 or blob[:2] != b"GP":
        raise ValueError("not a GeoPackage geometry blob")
    flags = blob[3]
    bo = "<" if (flags & 0x01) else ">"
    srid = struct.unpack(bo + "i", blob[4:8])[0]
    env_code = (flags >> 1) & 0x07
    env_len = {0: 0, 1: 32, 2: 48, 3: 48, 4: 64}.get(env_code)
    if env_len is None:
        raise ValueError(f"invalid GeoPackage envelope code {env_code}")
    return blob[8 + env_len :], srid


def list_layers(path: str) -> list[str]:
    """Feature-table names declared in gpkg_contents."""
    con = sqlite3.connect(path)
    try:
        rows = con.execute(
            "SELECT table_name FROM gpkg_contents WHERE data_type='features'"
        ).fetchall()
        return [r[0] for r in rows]
    finally:
        con.close()


def read_geopackage(path: str, layer: str | None = None) -> VectorTable:
    """One feature table -> VectorTable (attributes as typed columns)."""
    con = sqlite3.connect(path)
    try:
        layers = [
            r[0]
            for r in con.execute(
                "SELECT table_name FROM gpkg_contents WHERE data_type='features'"
            )
        ]
        if not layers:
            raise ValueError(f"{path!r} declares no feature tables")
        if layer is None:
            layer = layers[0]
        elif layer not in layers:
            raise ValueError(f"layer {layer!r} not in {layers}")
        row = con.execute(
            "SELECT column_name, srs_id FROM gpkg_geometry_columns "
            "WHERE table_name=?",
            (layer,),
        ).fetchone()
        if row is None:
            raise ValueError(
                f"layer {layer!r} has no gpkg_geometry_columns entry"
            )
        geom_col, srid = row
        cols_info = con.execute(f'PRAGMA table_info("{layer}")').fetchall()
        attr_cols = [c[1] for c in cols_info if c[1] != geom_col]
        sel = ", ".join(f'"{c}"' for c in [geom_col, *attr_cols])
        rows = con.execute(f'SELECT {sel} FROM "{layer}"').fetchall()
    finally:
        con.close()
    # GeoPackage allows NULL geometries: keep row alignment by dropping
    # those rows from both the geometry column and the attributes
    rows = [r for r in rows if r[0] is not None]
    blobs = [_parse_gpkg_blob(r[0])[0] for r in rows]
    geom = _wkb.from_wkb(blobs, srid=int(srid) if srid and srid > 0 else 4326)
    columns: dict[str, np.ndarray] = {}
    for i, name in enumerate(attr_cols, start=1):
        vals = [r[i] for r in rows]
        if all(isinstance(v, (int, float, type(None))) for v in vals) and any(
            v is not None for v in vals
        ):
            columns[name] = np.asarray(
                [np.nan if v is None else float(v) for v in vals]
            )
        else:
            columns[name] = np.asarray(vals, dtype=object)
    return VectorTable(geometry=geom, columns=columns)


def write_geopackage(
    path: str, table: VectorTable, layer: str = "features", srid: int = 4326
) -> None:
    """Minimal writer (tests + interchange): one feature table."""
    con = sqlite3.connect(path)
    try:
        con.executescript(
            """
            CREATE TABLE gpkg_spatial_ref_sys (
              srs_name TEXT, srs_id INTEGER PRIMARY KEY, organization TEXT,
              organization_coordsys_id INTEGER, definition TEXT, description TEXT);
            CREATE TABLE gpkg_contents (
              table_name TEXT PRIMARY KEY, data_type TEXT, identifier TEXT,
              description TEXT, last_change TEXT, min_x REAL, min_y REAL,
              max_x REAL, max_y REAL, srs_id INTEGER);
            CREATE TABLE gpkg_geometry_columns (
              table_name TEXT PRIMARY KEY, column_name TEXT,
              geometry_type_name TEXT, srs_id INTEGER, z TINYINT, m TINYINT);
            """
        )
        con.execute(
            "INSERT INTO gpkg_spatial_ref_sys VALUES (?,?,?,?,?,?)",
            (f"EPSG:{srid}", srid, "EPSG", srid, "", ""),
        )
        b = table.geometry.bounds()
        con.execute(
            "INSERT INTO gpkg_contents VALUES (?,?,?,?,?,?,?,?,?,?)",
            (
                layer,
                "features",
                layer,
                "",
                "",
                float(np.nanmin(b[:, 0])),
                float(np.nanmin(b[:, 1])),
                float(np.nanmax(b[:, 2])),
                float(np.nanmax(b[:, 3])),
                srid,
            ),
        )
        con.execute(
            "INSERT INTO gpkg_geometry_columns VALUES (?,?,?,?,?,?)",
            (layer, "geom", "GEOMETRY", srid, 0, 0),
        )
        names = list(table.columns)
        numeric = {
            c: np.issubdtype(np.asarray(table.columns[c]).dtype, np.number)
            for c in names
        }
        col_defs = "".join(
            f', "{c}" {"REAL" if numeric[c] else "TEXT"}' for c in names
        )
        con.execute(
            f'CREATE TABLE "{layer}" (fid INTEGER PRIMARY KEY, geom BLOB{col_defs})'
        )
        blobs = _wkb.to_wkb(table.geometry)
        header = b"GP\x00\x01" + struct.pack("<i", srid)  # LE, no envelope
        ph = ",".join("?" * (2 + len(names)))
        for i, w in enumerate(blobs):
            vals = [
                float(table.columns[c][i])
                if numeric[c]
                else (
                    None
                    if table.columns[c][i] is None
                    else str(table.columns[c][i])
                )
                for c in names
            ]
            con.execute(
                f'INSERT INTO "{layer}" VALUES ({ph})', (i + 1, header + w, *vals)
            )
        con.commit()
    finally:
        con.close()
