"""MapInfo Interchange Format (MIF/MID) reader.

Reference analog: the reference's `OGRFileFormat` accepts any OGR driver
name including "MapInfo File"
(`datasource/OGRFileFormat.scala:26-47,441-473`); this is the TAB/MIF
half of that breadth implemented from the published MIF spec — the ASCII
interchange form (binary .tab is MapInfo-internal and OGR itself
recommends MIF for exchange).

Supported objects: POINT, MULTIPOINT, LINE, PLINE [MULTIPLE], REGION
(ring nesting resolved by containment — MIF does not mark holes), NONE.
Attributes come from the .mid file typed by the COLUMNS block; DELIMITER
is honored. PEN/BRUSH/SYMBOL/CENTER styling clauses are skipped.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from ..core.geometry.hostops import _emit_polygon, _nest_contours
from ..core.types import GeometryBuilder, GeometryType
from .vector import VectorTable


def _emit_region(b: GeometryBuilder, rings: list[np.ndarray], srid: int):
    """MIF regions carry no hole flags: a ring is a hole of the ring that
    contains it (even-odd). Nesting rides hostops' boundary-robust
    machinery (`_nest_contours` probes a point clear of shared vertices —
    MIF holes routinely touch their shells)."""
    rings = [r for r in rings if r.shape[0] >= 3]
    _emit_polygon(b, _nest_contours(rings), srid)


def _parse_mid(path: Path, names: list[str], types: list[str], delim: str):
    import csv
    import io

    cols: dict[str, list] = {n: [] for n in names}
    if not path.exists() or not names:
        return cols
    # stdlib csv handles quoted delimiters and MID's doubled-quote escape
    # (the same pattern as readers/vector.py's csv_points)
    text = path.read_text(errors="replace")
    for vals in csv.reader(io.StringIO(text), delimiter=delim):
        if not vals:
            continue
        # a short row (trailing empty field with no delimiter) must not
        # truncate the zip and silently drop whole columns
        vals += [""] * (len(names) - len(vals))
        for n, t, v in zip(names, types, vals):
            v = v.strip()
            if t in ("integer", "smallint"):
                cols[n].append(int(v) if v else 0)
            elif t in ("float", "decimal"):
                cols[n].append(float(v) if v else np.nan)
            else:
                cols[n].append(v)
    return cols


def read_mif(path: str) -> VectorTable:
    """Read `path` (.mif, with its .mid sidecar) into a VectorTable."""
    p = Path(path)
    text = p.read_text(errors="replace")
    lines = [ln.strip() for ln in text.splitlines()]
    delim = "\t"
    names: list[str] = []
    types: list[str] = []
    i = 0
    # ------------------------------------------------------------ header
    while i < len(lines):
        ln = lines[i]
        up = ln.upper()
        if up.startswith("DELIMITER"):
            q = ln.split('"')
            if len(q) >= 2 and q[1]:
                delim = q[1]
        elif up.startswith("COLUMNS"):
            n = int(ln.split()[1])
            for k in range(n):
                i += 1
                parts = lines[i].split()
                names.append(parts[0])
                types.append(parts[1].split("(")[0].lower())
        elif up.startswith("DATA"):
            i += 1
            break
        i += 1
    # ------------------------------------------------------- object list
    b = GeometryBuilder()
    count = 0

    def floats(ln: str) -> list[float]:
        return [float(t) for t in ln.replace(",", " ").split()]

    def read_ring(k: int) -> np.ndarray:
        nonlocal i
        out = np.empty((k, 2))
        for v in range(k):
            out[v] = floats(lines[i])[:2]
            i += 1
        return out

    n_lines = len(lines)
    while i < n_lines:
        ln = lines[i]
        if not ln:
            i += 1
            continue
        tok = ln.split()
        kw = tok[0].upper()
        i += 1
        if kw in ("PEN", "BRUSH", "SYMBOL", "SMOOTH", "CENTER"):
            continue  # styling clauses attached to the previous object
        if kw == "NONE":
            b.add_geometry(GeometryType.POINT, [[np.zeros((0, 2))]], 0)
        elif kw == "POINT":
            xy = np.asarray([[float(tok[1]), float(tok[2])]])
            b.add_geometry(GeometryType.POINT, [[xy]], 0)
        elif kw == "MULTIPOINT":
            k = int(tok[1])
            pts = read_ring(k)
            b.add_geometry(
                GeometryType.MULTIPOINT, [[row[None, :]] for row in pts], 0
            )
        elif kw == "LINE":
            xy = np.asarray(
                [[float(tok[1]), float(tok[2])], [float(tok[3]), float(tok[4])]]
            )
            b.add_geometry(GeometryType.LINESTRING, [[xy]], 0)
        elif kw == "PLINE":
            if len(tok) >= 3 and tok[1].upper() == "MULTIPLE":
                parts = []
                for _ in range(int(tok[2])):
                    k = int(lines[i])
                    i += 1
                    parts.append([read_ring(k)])
                b.add_geometry(GeometryType.MULTILINESTRING, parts, 0)
            else:
                k = int(tok[1]) if len(tok) > 1 else int(lines[i])
                if len(tok) == 1:
                    i += 1
                b.add_geometry(GeometryType.LINESTRING, [[read_ring(k)]], 0)
        elif kw == "REGION":
            rings = []
            for _ in range(int(tok[1])):
                k = int(lines[i])
                i += 1
                r = read_ring(k)
                # MIF rings repeat the first vertex; drop the closure
                if r.shape[0] > 1 and np.allclose(r[0], r[-1]):
                    r = r[:-1]
                rings.append(r)
            _emit_region(b, rings, 0)
        else:
            # TEXT/RECT/ELLIPSE/ARC/... : consume the object's body (lines
            # up to the next keyword) and emit an EMPTY row so .mid
            # attribute rows stay aligned — OGR's skip-unsupported analog
            known = {
                "NONE", "POINT", "MULTIPOINT", "LINE", "PLINE", "REGION",
                "PEN", "BRUSH", "SYMBOL", "SMOOTH", "CENTER", "TEXT",
                "RECT", "ROUNDRECT", "ELLIPSE", "ARC", "COLLECTION",
                "FONT", "ANGLE", "JUSTIFY", "SPACING", "LABEL",
            }
            while i < n_lines:
                nxt = lines[i].split()
                first = nxt[0].upper() if nxt else ""
                if first in known and first not in (
                    "FONT", "ANGLE", "JUSTIFY", "SPACING", "LABEL"
                ):
                    break
                i += 1
            b.add_geometry(GeometryType.POINT, [[np.zeros((0, 2))]], 0)
        count += 1

    cols = _parse_mid(p.with_suffix(".mid"), names, types, delim)
    np_cols = {
        n: np.asarray(v)
        for n, v in cols.items()
        if len(v) == count  # tolerate missing/short .mid
    }
    return VectorTable(geometry=b.build(), columns=np_cols)
