"""ESRI FileGDB (.gdb) vector reader — pure host decode, no GDAL.

Reference analog: the OpenFileGDB/FileGDB OGR drivers behind the
reference's `GeoDBFileFormat`/`OpenGeoDBFileFormat`
(`datasource/GeoDBFileFormat.scala:11-37`; fixture
`binary/geodb/bridges.gdb.zip`). Implements the reverse-engineered v10
`.gdbtable`/`.gdbtablx` layout:

- field descriptors (int16/32, float32/64, string, datetime, objectid,
  geometry with origin/scale quantization parameters)
- row store with nullable-field bitmasks and varuint-length strings
- geometry blobs: point, multipoint, polyline, polygon — bbox varuints +
  zigzag-delta-packed integer coordinates, dequantized via the layer's
  origin/scale
- layer discovery through the GDB_SystemCatalog table (a00000001)

Validated against the fixture's own LATITUDE/LONGITUDE attribute columns
(geometry decoded from UTM 18N agrees after `crs.to_wgs84`).
"""

from __future__ import annotations

import os
import struct

import numpy as np

from ..core.types import GeometryBuilder, GeometryType
from .vector import VectorTable


def _varuint(d: bytes, q: int) -> tuple[int, int]:
    v = 0
    s = 0
    while True:
        b = d[q]
        q += 1
        v |= (b & 0x7F) << s
        if not (b & 0x80):
            return v, q
        s += 7


def _varint(d: bytes, q: int) -> tuple[int, int]:
    """FileGDB signed varint: bit6 of the first byte is the sign."""
    b = d[q]
    q += 1
    neg = bool(b & 0x40)
    v = b & 0x3F
    s = 6
    while b & 0x80:
        b = d[q]
        q += 1
        v |= (b & 0x7F) << s
        s += 7
    return (-v if neg else v), q


class _Field:
    __slots__ = ("name", "ftype", "nullable")

    def __init__(self, name, ftype, nullable):
        self.name = name
        self.ftype = ftype
        self.nullable = nullable


class GdbTable:
    """One .gdbtable/.gdbtablx pair."""

    def __init__(self, base: str):
        self.base = base
        self._d = open(base + ".gdbtable", "rb").read()
        self._x = open(base + ".gdbtablx", "rb").read()
        d = self._d
        if struct.unpack("<I", d[0:4])[0] != 3:
            raise ValueError(f"{base}.gdbtable: bad magic")
        self.n_valid = struct.unpack("<I", d[4:8])[0]
        fdo = struct.unpack("<Q", d[32:40])[0]
        self._parse_fields(fdo)
        # tablx header: magic, n-1024-row-blocks, row counter, offset size
        _magic, n1024, _rowctr, osz = struct.unpack("<4I", self._x[:16])
        raw = np.frombuffer(
            self._x[16 : 16 + n1024 * 1024 * osz], dtype=np.uint8
        )
        raw = raw[: (raw.size // osz) * osz].reshape(-1, osz)
        offs = raw[:, 0].astype(np.int64)
        for i in range(1, osz):
            offs |= raw[:, i].astype(np.int64) << (8 * i)
        live = offs > 0
        self.row_offsets = offs[live]
        # object IDs are the 1-based tablx slot positions (deleted rows
        # leave zero-offset gaps but keep their slots)
        self.row_ids = np.nonzero(live)[0] + 1

    def _parse_fields(self, fdo: int):
        d = self._d
        nfields = struct.unpack("<H", d[fdo + 12 : fdo + 14])[0]
        q = fdo + 14
        self.fields: list[_Field] = []
        self.geom_field: str | None = None
        self.xyorigin = (0.0, 0.0)
        self.xyscale = 1.0
        self.zscale = 1.0
        self.srs_wkt = ""
        for _ in range(nfields):
            nlen = d[q]
            q += 1
            name = d[q : q + 2 * nlen].decode("utf-16-le")
            q += 2 * nlen
            alen = d[q]
            q += 1 + 2 * alen
            ftype = d[q]
            q += 1
            nullable = True
            if ftype in (0, 1, 2, 3, 5):  # numeric / datetime
                flag = d[q + 1]
                nullable = bool(flag & 1)
                q += 2
                if flag & 4:
                    q += 1 + d[q]  # default value
            elif ftype == 4 or ftype == 12:  # string / xml
                flag = d[q + 4]
                nullable = bool(flag & 1)
                q += 5
                if flag & 4:
                    dl, q2 = _varuint(d, q)
                    q = q2 + dl
            elif ftype == 6:  # objectid (not stored in rows)
                nullable = False
                q += 2
            elif ftype == 7:  # geometry
                flag = d[q + 1]
                nullable = bool(flag & 1)
                q += 2
                srlen = struct.unpack("<H", d[q : q + 2])[0]
                q += 2
                self.srs_wkt = d[q : q + srlen].decode("utf-16-le", "replace")
                q += srlen
                gflags = d[q]
                q += 1
                has_m = bool(gflags & 2)
                has_z = bool(gflags & 4)
                xo, yo, xys = struct.unpack("<3d", d[q : q + 24])
                q += 24
                if has_m:
                    q += 16
                if has_z:
                    zo, zs = struct.unpack("<2d", d[q : q + 16])
                    self.zscale = zs
                    q += 16
                q += 8  # xytolerance
                if has_m:
                    q += 8
                if has_z:
                    q += 8
                q += 32  # extent
                q += 1  # trailing byte
                (ngrids,) = struct.unpack("<I", d[q : q + 4])
                q += 4 + 8 * ngrids
                self.xyorigin = (xo, yo)
                self.xyscale = xys
                self.geom_field = name
                self.has_z = has_z
            elif ftype == 8:  # binary
                flag = d[q + 1]
                nullable = bool(flag & 1)
                q += 2
            elif ftype in (10, 11):  # UUID
                flag = d[q + 1]
                nullable = bool(flag & 1)
                q += 2
            else:
                raise ValueError(f"FileGDB field type {ftype} unsupported")
            self.fields.append(_Field(name, ftype, nullable))

    # ----------------------------------------------------------------- rows
    def rows(self):
        """Yield dicts of field values (geometry as raw blob bytes)."""
        d = self._d
        nullable_fields = [f for f in self.fields if f.nullable]
        nmask = (len(nullable_fields) + 7) // 8
        for ro in self.row_offsets:
            ro = int(ro)
            q = ro + 4
            mask = d[q : q + nmask]
            q += nmask
            ni = 0
            row = {}
            for f in self.fields:
                if f.ftype == 6:  # objectid: derived, not stored
                    continue
                if f.nullable:
                    is_null = bool(mask[ni >> 3] & (1 << (ni & 7)))
                    ni += 1
                    if is_null:
                        row[f.name] = None
                        continue
                if f.ftype == 0:
                    row[f.name] = struct.unpack("<h", d[q : q + 2])[0]
                    q += 2
                elif f.ftype == 1:
                    row[f.name] = struct.unpack("<i", d[q : q + 4])[0]
                    q += 4
                elif f.ftype == 2:
                    row[f.name] = struct.unpack("<f", d[q : q + 4])[0]
                    q += 4
                elif f.ftype in (3, 5):
                    row[f.name] = struct.unpack("<d", d[q : q + 8])[0]
                    q += 8
                elif f.ftype in (4, 12):
                    n, q = _varuint(d, q)
                    row[f.name] = d[q : q + n].decode("utf-8", "replace")
                    q += n
                elif f.ftype == 7:
                    n, q = _varuint(d, q)
                    row[f.name] = d[q : q + n]
                    q += n
                elif f.ftype == 8:
                    n, q = _varuint(d, q)
                    row[f.name] = d[q : q + n]
                    q += n
                elif f.ftype in (10, 11):
                    row[f.name] = d[q : q + 16].hex()
                    q += 16
            yield row

    # ------------------------------------------------------------- geometry
    def decode_geometry(self, blob: bytes, builder: GeometryBuilder, srid: int):
        """One geometry blob -> appended to the builder."""
        xo, yo = self.xyorigin
        sc = self.xyscale
        gt, q = _varuint(blob, 0)
        kind = gt & 0xFF
        if kind in (1, 9, 11, 21):  # point variants
            vx, q = _varuint(blob, q)
            vy, q = _varuint(blob, q)
            if vx == 0 and vy == 0:
                builder.add_geometry(GeometryType.POINT, [[np.zeros((0, 2))]], srid)
                return
            x = (vx - 1) / sc + xo
            y = (vy - 1) / sc + yo
            builder.add_geometry(
                GeometryType.POINT, [[np.asarray([[x, y]])]], srid
            )
            return
        if kind in (2, 8, 20):  # multipoint
            n, q = _varuint(blob, q)
            q = _skip_bbox(blob, q)
            xs, ys, q = _delta_coords(blob, q, n)
            pts = np.stack([xs / sc + xo, ys / sc + yo], axis=-1)
            builder.add_geometry(
                GeometryType.MULTIPOINT, [[p[None, :]] for p in pts], srid
            )
            return
        if kind in (3, 10, 13, 23, 25, 50, 51):  # polyline
            n, q = _varuint(blob, q)
            nparts, q = _varuint(blob, q)
            q = _skip_bbox(blob, q)
            counts, q = _part_counts(blob, q, n, nparts)
            xs, ys, q = _delta_coords(blob, q, n)
            pts = np.stack([xs / sc + xo, ys / sc + yo], axis=-1)
            parts = []
            s = 0
            for c in counts:
                parts.append([pts[s : s + c]])
                s += c
            builder.add_geometry(GeometryType.MULTILINESTRING, parts, srid)
            return
        if kind in (4, 5, 12, 15, 19, 24, 26, 27, 54):  # polygon
            n, q = _varuint(blob, q)
            nparts, q = _varuint(blob, q)
            q = _skip_bbox(blob, q)
            counts, q = _part_counts(blob, q, n, nparts)
            xs, ys, q = _delta_coords(blob, q, n)
            pts = np.stack([xs / sc + xo, ys / sc + yo], axis=-1)
            rings = []
            s = 0
            for c in counts:
                rings.append(pts[s : s + c])
                s += c
            # FileGDB stores all rings flat; ring orientation separates
            # shells (CW in ESRI) from holes — group holes with the
            # preceding shell
            parts = []
            for r in rings:
                area2 = float(
                    np.sum(r[:-1, 0] * r[1:, 1] - r[1:, 0] * r[:-1, 1])
                )
                if area2 <= 0 or not parts:  # ESRI shells are clockwise
                    parts.append([r])
                else:
                    parts[-1].append(r)
            builder.add_geometry(GeometryType.MULTIPOLYGON, parts, srid)
            return
        raise ValueError(f"FileGDB geometry type {kind} unsupported")


def _skip_bbox(blob: bytes, q: int) -> int:
    for _ in range(4):
        _, q = _varuint(blob, q)
    return q


def _part_counts(blob, q, n, nparts):
    counts = []
    rem = n
    for _ in range(max(nparts - 1, 0)):
        c, q = _varuint(blob, q)
        counts.append(c)
        rem -= c
    counts.append(rem)
    return counts, q


def _delta_coords(blob, q, n):
    xs = np.empty(n, dtype=np.float64)
    ys = np.empty(n, dtype=np.float64)
    x = y = 0
    for i in range(n):
        dx, q = _varint(blob, q)
        x += dx
        xs[i] = x
    for i in range(n):
        dy, q = _varint(blob, q)
        y += dy
        ys[i] = y
    return xs, ys, q


_SRS_SRIDS = {
    "NAD_1983_UTM_Zone_18N": 26918,
    "WGS_1984_UTM_Zone_18N": 32618,
    "GCS_WGS_1984": 4326,
    "GCS_North_American_1983": 4269,
}


def _srid_of(wkt: str) -> int:
    for name, srid in _SRS_SRIDS.items():
        if wkt.startswith(f'PROJCS["{name}"') or wkt.startswith(f'GEOGCS["{name}"'):
            return srid
    return 0


def list_gdb_layers(gdb_dir: str) -> dict[str, str]:
    """Layer name -> table file base, via the GDB_SystemCatalog (a1)."""
    catalog = os.path.join(gdb_dir, "a00000001")
    if not os.path.exists(catalog + ".gdbtable"):
        raise ValueError(
            f"{gdb_dir!r} is not a FileGDB directory (no GDB_SystemCatalog)"
        )
    cat = GdbTable(catalog)
    out = {}
    for oid, row in zip(cat.row_ids, cat.rows()):
        name = row.get("Name")
        if not name or name.startswith("GDB_"):
            continue
        base = os.path.join(gdb_dir, f"a{int(oid):08x}")
        if os.path.exists(base + ".gdbtable"):
            out[name] = base
    return out


def read_filegdb(path: str, layer: str | None = None) -> VectorTable:
    """A .gdb directory (or .zip of one) -> VectorTable of one layer."""
    import shutil
    import tempfile
    import zipfile

    tmp = None
    if path.endswith(".zip"):
        tmp = tempfile.mkdtemp(prefix="gdb_")
        with zipfile.ZipFile(path) as z:
            z.extractall(tmp)
        inner = [f for f in os.listdir(tmp) if f.endswith(".gdb")]
        if not inner:
            shutil.rmtree(tmp, ignore_errors=True)
            raise ValueError(f"no .gdb directory inside {path!r}")
        path = os.path.join(tmp, inner[0])
    try:
        return _read_gdb_dir(path, layer)
    finally:
        if tmp is not None:  # tables are fully in memory once read
            shutil.rmtree(tmp, ignore_errors=True)


def _read_gdb_dir(path: str, layer: "str | None") -> VectorTable:
    layers = list_gdb_layers(path)
    if not layers:
        raise ValueError(f"no feature layers in {path!r}")
    if layer is None:
        layer = next(iter(layers))
    elif layer not in layers:
        raise ValueError(f"layer {layer!r} not in {sorted(layers)}")
    t = GdbTable(layers[layer])
    srid = _srid_of(t.srs_wkt)
    b = GeometryBuilder()
    cols: dict[str, list] = {
        f.name: [] for f in t.fields if f.ftype not in (6, 7)
    }
    for row in t.rows():
        blob = row.get(t.geom_field) if t.geom_field else None
        if blob:
            t.decode_geometry(blob, b, srid or 0)
        else:
            b.add_geometry(GeometryType.POINT, [[np.zeros((0, 2))]], srid or 0)
        for name in cols:
            cols[name].append(row.get(name))
    columns: dict[str, np.ndarray] = {}
    for name, vals in cols.items():
        if all(isinstance(v, (int, float, type(None))) for v in vals) and any(
            v is not None for v in vals
        ):
            columns[name] = np.asarray(
                [np.nan if v is None else float(v) for v in vals]
            )
        else:
            columns[name] = np.asarray(vals, dtype=object)
    return VectorTable(geometry=b.build(), columns=columns)
