"""Namespace-agnostic XML helpers shared by the KML/GML/GPX readers."""

from __future__ import annotations


def local(tag) -> str:
    """Element local name ('{ns}Polygon' -> 'Polygon')."""
    return str(tag).rsplit("}", 1)[-1]


def find(el, name: str):
    """First descendant (or self) with the given local name."""
    for c in el.iter():
        if local(c.tag) == name:
            return c
    return None


def children(el, name: str):
    """Direct children with the given local name."""
    return [c for c in el if local(c.tag) == name]
