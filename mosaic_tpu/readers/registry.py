"""`read(fmt)` — the `MosaicContext.read.format(...)` analog.

Reference: `MosaicDataFrameReader` dispatching on format name
(`datasource/multiread/MosaicDataFrameReader.scala`), service-loader
registration of the six datasources (META-INF DataSourceRegister).
"""

from __future__ import annotations

from typing import Callable


class _Reader:
    def __init__(self, fmt: str):
        self.fmt = fmt
        self.options: dict = {}

    def option(self, key: str, value) -> "_Reader":
        self.options[key] = value
        return self

    def load(self, path, **kwargs):
        merged = {**self.options, **kwargs}
        return _FORMATS[self.fmt](path, **merged)


def _fmt_shapefile(path, **kw):
    from .vector import read_shapefile

    return read_shapefile(path)


def _fmt_geojson(path, **kw):
    from .vector import read_geojson

    return read_geojson(path)


def _fmt_multiread(path, **kw):
    from .vector import multiread

    return multiread(path, chunk_size=int(kw.get("chunkSize", 5000)))


def _fmt_gdal(path, **kw):
    from .raster_grid import read_gdal_metadata

    return read_gdal_metadata(path, ext=kw.get("extensions", ".TIF"))


def _fmt_raster_to_grid(path, **kw):
    from .raster_grid import raster_to_grid

    return raster_to_grid(
        path,
        resolution=int(kw.get("resolution", 0)),
        combiner=kw.get("combiner", "avg"),
        index=kw.get("index"),
        raster_srid=kw.get("rasterSrid"),
        tile_size=int(kw.get("retileSize", 512)),
        k_ring_interpolate=int(kw.get("kRingInterpolate", 0)),
        ext=kw.get("extensions", ".TIF"),
    )


def _fmt_csv_points(path, **kw):
    from .vector import read_points_csv

    mr = kw.get("maxRows")
    return read_points_csv(
        path,
        lon_col=kw.get("lonCol", "pickup_longitude"),
        lat_col=kw.get("latCol", "pickup_latitude"),
        max_rows=None if mr is None else int(mr),
    )


def _fmt_geopackage(path, **kw):
    from .geopackage import read_geopackage

    return read_geopackage(path, layer=kw.get("layer"))


def _fmt_geodb(path, **kw):
    from .filegdb import read_filegdb

    return read_filegdb(path, layer=kw.get("layer"))


def _fmt_grib(path, **kw):
    from .grib2 import read_grib2

    return read_grib2(path)


def _fmt_zarr(path, **kw):
    from .zarr_store import read_zarr

    return read_zarr(path, array=kw.get("array"))


def _fmt_netcdf(path, **kw):
    from .hdf5_lite import read_netcdf

    return read_netcdf(path, variable=kw.get("variable"))


def _fmt_kml(path, **kw):
    from .kml import read_kml

    return read_kml(path)


def _fmt_gml(path, **kw):
    from .gml import read_gml

    return read_gml(path, srid=int(kw.get("srid", 4326)))


def _fmt_mif(path, **kw):
    from .mif import read_mif

    return read_mif(path)


def _fmt_dxf(path, **kw):
    from .dxf import read_dxf

    return read_dxf(path)


def _fmt_gpx(path, **kw):
    from .gml import read_gpx

    return read_gpx(path)


def _fmt_topojson(path, **kw):
    from .topojson import read_topojson

    return read_topojson(path, layer=kw.get("layer"))


def _fmt_flatgeobuf(path, **kw):
    from .flatgeobuf import read_flatgeobuf

    return read_flatgeobuf(path)


def _fmt_csv_wkt(path, **kw):
    from .vector import read_wkt_csv

    mr = kw.get("maxRows")
    return read_wkt_csv(
        path,
        wkt_col=kw.get("wktCol", "wkt"),
        srid=int(kw.get("srid", 4326)),
        max_rows=None if mr is None else int(mr),
    )


def _fmt_osm(path, **kw):
    from .osm import read_osm

    return read_osm(path)


_FORMATS: dict[str, Callable] = {
    "kml": _fmt_kml,
    "gml": _fmt_gml,
    "gpx": _fmt_gpx,
    "shapefile": _fmt_shapefile,
    "geojson": _fmt_geojson,
    "geopackage": _fmt_geopackage,
    "geodb": _fmt_geodb,
    "multi_read_ogr": _fmt_multiread,
    "gdal": _fmt_gdal,
    "grib": _fmt_grib,
    "netcdf": _fmt_netcdf,
    "zarr": _fmt_zarr,
    "raster_to_grid": _fmt_raster_to_grid,
    "csv_points": _fmt_csv_points,
    "mapinfo": _fmt_mif,  # OGR "MapInfo File" driver name analog
    "mif": _fmt_mif,
    "dxf": _fmt_dxf,
    "topojson": _fmt_topojson,
    "csv_wkt": _fmt_csv_wkt,  # OGR "CSV" driver with a WKT geometry field
    "flatgeobuf": _fmt_flatgeobuf,
    "geojsonseq": _fmt_geojson,  # NDJSON / RFC 8142 both handled
    "osm": _fmt_osm,
}


def read(fmt: str) -> _Reader:
    """`read("raster_to_grid").option("resolution", 6).load(path)`."""
    if fmt not in _FORMATS:
        raise ValueError(f"unknown format {fmt!r}; have {sorted(_FORMATS)}")
    return _Reader(fmt)


# ----------------------------------------------------------------- write


class _Writer:
    """`write(fmt).option(...).save(path, table)` — the write-side mirror
    of :func:`read` (the reference writes through Spark's
    `df.write.format(...)` + OGR drivers; these are the native columnar
    writers)."""

    def __init__(self, fmt: str):
        self.fmt = fmt
        self.options: dict = {}

    def option(self, key: str, value) -> "_Writer":
        self.options[key] = value
        return self

    def save(self, path, table, **kwargs) -> None:
        merged = {**self.options, **kwargs}
        _WRITE_FORMATS[self.fmt](path, table, **merged)


def _wfmt_geojson(path, table, **kw):
    from .vector import write_geojson

    write_geojson(path, table, seq=bool(kw.get("seq", False)))


def _wfmt_geojsonseq(path, table, **kw):
    from .vector import write_geojson

    write_geojson(path, table, seq=True)


def _wfmt_shapefile(path, table, **kw):
    from .vector import write_shapefile

    write_shapefile(path, table, srid=int(kw.get("srid", 4326)))


def _wfmt_flatgeobuf(path, table, **kw):
    from .flatgeobuf import write_flatgeobuf

    write_flatgeobuf(
        path, table, name=kw.get("name", "layer"),
        srid=int(kw.get("srid", 4326)),
    )


def _wfmt_geopackage(path, table, **kw):
    from .geopackage import write_geopackage

    write_geopackage(path, table, **kw)


def _wfmt_kml(path, table, **kw):
    from .kml import write_kml

    write_kml(path, table, name_col=kw.get("name_col"))


_WRITE_FORMATS: dict[str, Callable] = {
    "geojson": _wfmt_geojson,
    "kml": _wfmt_kml,
    "geojsonseq": _wfmt_geojsonseq,
    "shapefile": _wfmt_shapefile,
    "flatgeobuf": _wfmt_flatgeobuf,
    "geopackage": _wfmt_geopackage,
}


def write(fmt: str) -> _Writer:
    """`write("shapefile").option("srid", 27700).save(path, table)`."""
    if fmt not in _WRITE_FORMATS:
        raise ValueError(
            f"unknown write format {fmt!r}; have {sorted(_WRITE_FORMATS)}"
        )
    return _Writer(fmt)
