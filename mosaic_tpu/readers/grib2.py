"""GRIB raster reader (editions 1 and 2; pure host decode, no GDAL).

Reference analog: GDAL's GRIB driver behind `MosaicRasterGDAL.readRaster`
(`core/raster/MosaicRasterGDAL.scala:182-187`; the reference's
`binary/grib-cams` fixtures exercise it — those files interleave GRIB2 and
GRIB1 messages in one file and GDAL exposes all of them as bands).

Supported: edition 2 with grid definition template 3.0 (regular lat/lon),
data representation template 5.0 (simple packing), bitmap section present
or absent; edition 1 with grid representation 0 (regular lat/lon), simple
packing, IBM-370 reference floats, optional bitmap. Any number of messages
per file (one band each).

Decoded fields become :class:`mosaic_tpu.raster.Raster` objects with a
GDAL-style geotransform, so the whole raster expression surface
(`rst_*`, `raster_to_grid`) applies unchanged.
"""

from __future__ import annotations

import struct

import numpy as np

from ..raster.core import Raster


def _sm16(buf: bytes, off: int) -> int:
    """GRIB2 signed 16-bit: sign bit + magnitude (NOT two's complement)."""
    v = struct.unpack(">H", buf[off : off + 2])[0]
    return -(v & 0x7FFF) if v & 0x8000 else v


def _sm32(buf: bytes, off: int) -> int:
    v = struct.unpack(">I", buf[off : off + 4])[0]
    return -(v & 0x7FFFFFFF) if v & 0x80000000 else v


def _unpack_simple(
    payload: bytes,
    n: int,
    R: float,
    E: int,
    D: int,
    nbits: int,
    single: bool = True,
):
    """Simple packing: value = (R + X * 2^E) / 10^D.

    ``single=True`` does the arithmetic in float32, reproducing GDAL's
    g2clib GRIB2 decode bit-for-bit; GRIB1 passes ``single=False`` because
    GDAL's GRIB1 path computes in double (both verified against the
    fixture's GDAL-generated .aux.xml statistics)."""
    f = np.float32 if single else np.float64
    if nbits == 0:
        return np.full(n, f(R) / f(10.0**D), dtype=np.float64)
    raw = np.frombuffer(payload, dtype=np.uint8)
    bits = np.unpackbits(raw)[: n * nbits].reshape(n, nbits)
    weights = (1 << np.arange(nbits - 1, -1, -1)).astype(np.int64)
    x = bits.astype(np.int64) @ weights
    v = (f(R) + x.astype(f) * f(2.0**E)) / f(10.0**D)
    return v.astype(np.float64)


def _ibm32(b: bytes) -> float:
    """IBM System/370 32-bit float (GRIB1 reference values)."""
    w = struct.unpack(">I", b)[0]
    sign = -1.0 if w >> 31 else 1.0
    exp = (w >> 24) & 0x7F
    frac = w & 0xFFFFFF
    return sign * (frac / float(1 << 24)) * 16.0 ** (exp - 64)


def _sm24(buf: bytes, off: int) -> int:
    """GRIB1 signed 24-bit: sign bit + magnitude."""
    v = (buf[off] << 16) | (buf[off + 1] << 8) | buf[off + 2]
    return -(v & 0x7FFFFF) if v & 0x800000 else v


def _u24(buf: bytes, off: int) -> int:
    return (buf[off] << 16) | (buf[off + 1] << 8) | buf[off + 2]


def _decode_grib1(buf: bytes, idx: int, msg_len: int):
    """One GRIB1 message -> (grid (nj, ni) float32, gt, meta string)."""
    off = idx + 8  # past IS (8 octets)
    pds_len = _u24(buf, off)
    flags = buf[off + 7]
    param = buf[off + 8]
    D = _sm16(buf, off + 26)
    has_gds = bool(flags & 0x80)
    has_bms = bool(flags & 0x40)
    off += pds_len
    if not has_gds:
        raise ValueError("GRIB1 message without GDS unsupported")
    gds_len = _u24(buf, off)
    rep = buf[off + 5]
    if rep != 0:
        raise ValueError(f"GRIB1 grid representation {rep} unsupported")
    ni = struct.unpack(">H", buf[off + 6 : off + 8])[0]
    nj = struct.unpack(">H", buf[off + 8 : off + 10])[0]
    la1 = _sm24(buf, off + 10) / 1e3
    lo1 = _sm24(buf, off + 13) / 1e3
    la2 = _sm24(buf, off + 17) / 1e3
    lo2 = _sm24(buf, off + 20) / 1e3
    if struct.unpack(">H", buf[off + 23 : off + 25])[0] == 0xFFFF:
        # increments marked missing: derive from the corner coordinates
        di = (lo2 - lo1) / max(ni - 1, 1)
        dj = (la2 - la1) / max(nj - 1, 1)
    else:
        di = _sm16(buf, off + 23) / 1e3
        dj = _sm16(buf, off + 25) / 1e3
    scan = buf[off + 27]
    if scan & 0x20:
        raise ValueError("GRIB1 j-consecutive scanning (0x20) unsupported")
    off += gds_len
    bitmap = None
    if has_bms:
        bms_len = _u24(buf, off)
        unused = buf[off + 3]
        bm_raw = np.frombuffer(buf[off + 6 : off + bms_len], dtype=np.uint8)
        bits = np.unpackbits(bm_raw)
        if bits.size - unused < ni * nj:
            raise ValueError(
                f"GRIB1 bitmap holds {bits.size - unused} bits for "
                f"{ni * nj} grid points"
            )
        bitmap = bits[: ni * nj].astype(bool)
        off += bms_len
    bds_len = _u24(buf, off)
    bds_flags = buf[off + 3] >> 4
    if bds_flags & 0x4:  # complex packing
        raise ValueError("GRIB1 complex packing unsupported")
    E = _sm16(buf, off + 4)
    R = _ibm32(buf[off + 6 : off + 10])
    nbits = buf[off + 10]
    payload = buf[off + 11 : off + bds_len]
    n_data = int(bitmap.sum()) if bitmap is not None else ni * nj
    vals = _unpack_simple(payload, n_data, R, E, D, nbits, single=False)
    if bitmap is not None:
        full = np.full(ni * nj, np.nan)
        full[bitmap] = vals
        vals = full
    grid = np.asarray(vals).reshape(nj, ni)
    if scan & 0x40:
        grid = grid[::-1]
    if scan & 0x80:
        grid = grid[:, ::-1]
    gt = _grib_gt(la1, lo1, ni, nj, abs(di), abs(dj), scan)
    return grid.astype(np.float64), gt, f"GRIB1_PARAM={param}"


def _grib_gt(la1, lo1, ni, nj, di, dj, scan):
    """North-up geotransform from the first grid point + scanning mode.

    la1/lo1 are the CENTER of the first transmitted point: northernmost
    row unless +j scanning (0x40), westernmost column unless -i scanning
    (0x80) — the grid arrays are flipped to north-up/west-east to match.
    """
    lat_top = la1 + (nj - 1) * dj if scan & 0x40 else la1
    lon_west = lo1 - (ni - 1) * di if scan & 0x80 else lo1
    return (lon_west - di / 2, di, 0.0, lat_top + dj / 2, 0.0, -dj)


def _sections(buf: bytes, start: int, msg_len: int):
    """Yield (number, offset, length) for one message's sections 1..7."""
    off = start + 16
    end = start + msg_len
    while off < end - 4:
        if buf[off : off + 4] == b"7777":
            return
        slen = struct.unpack(">I", buf[off : off + 4])[0]
        if slen == 0:
            raise ValueError("zero-length GRIB2 section")
        yield buf[off + 4], off, slen
        off += slen


def read_grib2(path: str) -> Raster:
    """All messages of a GRIB2 file -> one multi-band Raster."""
    buf = open(path, "rb").read()
    bands = []
    gts = []
    meta_rows = []
    pos = 0
    while pos < len(buf) - 16:
        idx = buf.find(b"GRIB", pos)
        if idx < 0 or idx + 16 > len(buf):
            break
        # "GRIB" can occur inside message payloads: require a coherent
        # message (known edition, sane length, '7777' trailer)
        edition = buf[idx + 7]
        if edition == 1:
            msg1 = _u24(buf, idx + 4)
            if (
                32 <= msg1 <= len(buf) - idx
                and buf[idx + msg1 - 4 : idx + msg1] == b"7777"
            ):
                grid, gt1, m = _decode_grib1(buf, idx, msg1)
                bands.append(grid)
                meta_rows.append(m)
                gts.append(gt1)
                pos = idx + msg1
            else:
                pos = idx + 4
            continue
        msg_len = struct.unpack(">Q", buf[idx + 8 : idx + 16])[0]
        valid = (
            edition == 2
            and 32 <= msg_len <= len(buf) - idx
            and buf[idx + msg_len - 4 : idx + msg_len] == b"7777"
        )
        if not valid:
            pos = idx + 4
            continue
        ni = nj = None
        la1 = lo1 = di = dj = None
        scan = 0
        drs = None
        bitmap = None
        data = None
        n_pts = 0
        discipline = buf[idx + 6]
        cat = num = None
        for snum, off, slen in _sections(buf, idx, msg_len):
            if snum == 3:
                tmpl = struct.unpack(">H", buf[off + 12 : off + 14])[0]
                if tmpl != 0:
                    raise ValueError(
                        f"GRIB2 grid template 3.{tmpl} unsupported "
                        "(regular lat/lon only)"
                    )
                n_pts = struct.unpack(">I", buf[off + 6 : off + 10])[0]
                ni = struct.unpack(">I", buf[off + 30 : off + 34])[0]
                nj = struct.unpack(">I", buf[off + 34 : off + 38])[0]
                la1 = _sm32(buf, off + 46) / 1e6
                lo1 = _sm32(buf, off + 50) / 1e6
                di = _sm32(buf, off + 63) / 1e6
                dj = _sm32(buf, off + 67) / 1e6
                scan = buf[off + 71]
                if scan & 0x20:
                    raise ValueError(
                        "GRIB2 j-consecutive scanning (0x20) unsupported"
                    )
            elif snum == 4:
                cat, num = buf[off + 9], buf[off + 10]
            elif snum == 5:
                tmpl = struct.unpack(">H", buf[off + 9 : off + 11])[0]
                if tmpl != 0:
                    raise ValueError(
                        f"GRIB2 data template 5.{tmpl} unsupported "
                        "(simple packing only)"
                    )
                R = struct.unpack(">f", buf[off + 11 : off + 15])[0]
                E = _sm16(buf, off + 15)
                D = _sm16(buf, off + 17)
                nbits = buf[off + 19]
                drs = (R, E, D, nbits)
            elif snum == 6:
                indicator = buf[off + 5]
                if indicator == 0:
                    nbm = -(-n_pts // 8)
                    bm_raw = np.frombuffer(
                        buf[off + 6 : off + 6 + nbm], dtype=np.uint8
                    )
                    bitmap = np.unpackbits(bm_raw)[:n_pts].astype(bool)
                elif indicator != 255:
                    raise ValueError(
                        f"GRIB2 bitmap indicator {indicator} unsupported"
                    )
            elif snum == 7:
                data = buf[off + 5 : off + slen]
        if drs is None or ni is None or data is None:
            raise ValueError("incomplete GRIB2 message")
        n_data = int(bitmap.sum()) if bitmap is not None else ni * nj
        vals = _unpack_simple(data, n_data, *drs)
        if bitmap is not None:
            full = np.full(ni * nj, np.nan)
            full[bitmap] = vals
            vals = full
        grid = vals.reshape(nj, ni)
        if scan & 0x40:  # +j scan: rows south->north; flip to north-up
            grid = grid[::-1]
        if scan & 0x80:  # -i scan: columns east->west
            grid = grid[:, ::-1]
        bands.append(grid.astype(np.float64))
        meta_rows.append(f"GRIB_DISCIPLINE={discipline};CAT={cat};NUM={num}")
        gts.append(_grib_gt(la1, lo1, ni, nj, abs(di), abs(dj), scan))
        pos = idx + msg_len
    if not bands:
        raise ValueError(f"no decodable GRIB messages in {path!r}")
    shapes = {b.shape for b in bands}
    uniq_gt = {tuple(round(v, 9) for v in g) for g in gts}
    if len(shapes) > 1 or len(uniq_gt) > 1:
        raise ValueError(
            f"GRIB messages define different grids (shapes {sorted(shapes)}, "
            f"{len(uniq_gt)} geotransforms); read them as separate rasters"
        )
    gt = gts[0]
    meta = "".join(
        f'<Item name="BAND_{i + 1}">{m}</Item>' for i, m in enumerate(meta_rows)
    )
    return Raster(
        data=np.stack(bands),
        gt=gt,
        srid=4326,
        nodata=float("nan") if any(np.isnan(b).any() for b in bands) else None,
        meta_xml=f"<GDALMetadata>{meta}</GDALMetadata>",
        path=path,
    )
