"""Datasource readers (the reference's FileFormat/multiread layer).

Reference analogs: `datasource/OGRFileFormat.scala` (vector files ->
DataFrame), `datasource/GDALFileFormat.scala` (raster metadata datasource),
`datasource/multiread/OGRMultiReadDataFrameReader.scala` (parallel chunked
vector reads), `datasource/multiread/RasterAsGridReader.scala` (the full
raster->grid pipeline). `read(fmt)` mirrors `MosaicContext.read.format(...)`
(`functions/MosaicContext.scala:802`).
"""

from .registry import read, write  # noqa: F401
from .vector import (  # noqa: F401
    read_geojson,
    read_points_csv,
    read_shapefile,
    write_geojson,
    write_shapefile,
)
from .raster_grid import raster_to_grid, read_gdal_metadata  # noqa: F401
from .geopackage import read_geopackage, write_geopackage  # noqa: F401
from .filegdb import read_filegdb  # noqa: F401
from .grib2 import read_grib2  # noqa: F401
from .osm import read_osm  # noqa: F401
from .hdf5_lite import H5Lite, read_netcdf  # noqa: F401
from .zarr_store import ZarrStore, read_zarr  # noqa: F401

__all__ = [
    "read",
    "write",
    "read_geojson",
    "read_shapefile",
    "read_points_csv",
    "write_geojson",
    "write_shapefile",
    "read_geopackage",
    "write_geopackage",
    "read_filegdb",
    "read_grib2",
    "read_osm",
    "read_netcdf",
    "H5Lite",
    "read_zarr",
    "ZarrStore",
    "raster_to_grid",
    "read_gdal_metadata",
]
