"""Datasource readers (the reference's FileFormat/multiread layer).

Reference analogs: `datasource/OGRFileFormat.scala` (vector files ->
DataFrame), `datasource/GDALFileFormat.scala` (raster metadata datasource),
`datasource/multiread/OGRMultiReadDataFrameReader.scala` (parallel chunked
vector reads), `datasource/multiread/RasterAsGridReader.scala` (the full
raster->grid pipeline). `read(fmt)` mirrors `MosaicContext.read.format(...)`
(`functions/MosaicContext.scala:802`).
"""

from .registry import read  # noqa: F401
from .vector import read_geojson, read_shapefile, read_points_csv  # noqa: F401
from .raster_grid import raster_to_grid, read_gdal_metadata  # noqa: F401

__all__ = [
    "read",
    "read_geojson",
    "read_shapefile",
    "read_points_csv",
    "raster_to_grid",
    "read_gdal_metadata",
]
