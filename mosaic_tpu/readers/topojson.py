"""TopoJSON vector reader (from scratch, to the public spec).

Reference analog: the OGR "TopoJSON" driver reachable through
``format("ogr").option("driverName", ...)`` (`datasource/OGRFileFormat.scala:
26-47` accepts any driver name). TopoJSON stores shared borders once as
*arcs*; geometries reference arcs by index (ones'-complement for reversed
traversal). Quantized topologies delta-encode arc vertices against a
``transform`` (scale + translate).

Decoding goes TopoJSON -> GeoJSON coordinate structures -> the shared
:func:`_append_geojson` packer, so every geometry type and the properties
contract behave exactly like the GeoJSON reader.
"""

from __future__ import annotations

import json

import numpy as np

from ..core.geometry.geojson import _append_geojson, _crs_srid
from ..core.types import GeometryBuilder


def _decode_arcs(topo: dict) -> list[np.ndarray]:
    """All arcs as absolute-coordinate float arrays [n, 2]."""
    tr = topo.get("transform")
    if tr:
        scale = np.asarray(tr.get("scale", [1.0, 1.0]), dtype=np.float64)
        shift = np.asarray(tr.get("translate", [0.0, 0.0]), dtype=np.float64)
    arcs = []
    for arc in topo.get("arcs", []):
        a = np.asarray(arc, dtype=np.float64).reshape(-1, 2)
        if tr:  # quantized: delta-encoded from the first position
            a = np.cumsum(a, axis=0) * scale + shift
        arcs.append(a)
    return arcs


def _point(topo: dict, pos) -> list:
    tr = topo.get("transform")
    p = list(map(float, pos))
    if tr:  # point positions are absolute quantized counts, not deltas
        sx, sy = tr.get("scale", [1.0, 1.0])
        tx, ty = tr.get("translate", [0.0, 0.0])
        p[0] = p[0] * sx + tx
        p[1] = p[1] * sy + ty
    return p


def _line(arcs: list[np.ndarray], idxs) -> list:
    """Stitch one arc chain into a coordinate list. A negative index ~i
    traverses arc i backwards; the shared junction point between
    consecutive arcs appears only once."""
    pts: list[list[float]] = []
    for k in idxs:
        a = arcs[~k][::-1] if k < 0 else arcs[k]
        seg = a.tolist()
        if pts:
            seg = seg[1:]
        pts.extend(seg)
    return pts


def _geometry(topo: dict, arcs: list[np.ndarray], obj: dict) -> dict | None:
    t = obj.get("type")
    if t is None:  # null geometry
        return None
    if t == "Point":
        return {"type": t, "coordinates": _point(topo, obj["coordinates"])}
    if t == "MultiPoint":
        return {
            "type": t,
            "coordinates": [_point(topo, p) for p in obj["coordinates"]],
        }
    if t == "LineString":
        return {"type": t, "coordinates": _line(arcs, obj["arcs"])}
    if t == "MultiLineString":
        return {
            "type": t,
            "coordinates": [_line(arcs, ix) for ix in obj["arcs"]],
        }
    if t == "Polygon":
        return {
            "type": t,
            "coordinates": [_line(arcs, ring) for ring in obj["arcs"]],
        }
    if t == "MultiPolygon":
        return {
            "type": t,
            "coordinates": [
                [_line(arcs, ring) for ring in poly] for poly in obj["arcs"]
            ],
        }
    if t == "GeometryCollection":
        return {
            "type": t,
            "geometries": [
                g
                for g in (
                    _geometry(topo, arcs, s)
                    for s in obj.get("geometries", [])
                )
                if g is not None
            ],
        }
    raise ValueError(f"unsupported TopoJSON geometry type: {t}")


def read_topojson(path_or_obj, layer: "str | None" = None):
    """TopoJSON Topology -> :class:`VectorTable`.

    One row per geometry object; the originating named object lands in a
    ``layer`` column (OGR maps each top-level object to a layer — passing
    ``layer=`` restricts to one, like OGR's layer selection).
    """
    from .vector import VectorTable

    if isinstance(path_or_obj, str) and not path_or_obj.lstrip().startswith("{"):
        with open(path_or_obj) as f:
            topo = json.load(f)
    elif isinstance(path_or_obj, str):
        topo = json.loads(path_or_obj)
    else:
        topo = path_or_obj
    if topo.get("type") != "Topology":
        raise ValueError("not a TopoJSON Topology document")
    objects = topo.get("objects", {})
    if layer is not None:
        if layer not in objects:
            raise ValueError(
                f"no such TopoJSON object {layer!r}; have {sorted(objects)}"
            )
        objects = {layer: objects[layer]}
    arcs = _decode_arcs(topo)
    srid = _crs_srid(topo)

    builder = GeometryBuilder()
    layers: list[str] = []
    props: list[dict] = []

    def emit(name: str, obj: dict) -> None:
        _append_geojson(builder, _geometry(topo, arcs, obj), srid)
        layers.append(name)
        props.append(obj.get("properties") or {})

    for name, obj in objects.items():
        # a top-level GeometryCollection is a layer: its members are the
        # features (OGR semantics); nested collections stay one geometry
        if obj.get("type") == "GeometryCollection":
            for sub in obj.get("geometries", []):
                emit(name, sub)
        else:
            emit(name, obj)

    from .vector import props_to_columns

    cols: dict[str, np.ndarray] = {
        "layer": np.asarray(layers, dtype=object)
    }
    cols.update(props_to_columns(props))
    return VectorTable(geometry=builder.build(), columns=cols)
