"""Zarr v2 store reader (directory or zip), pure host decode.

Reference analog: GDAL's Zarr driver (the reference ships a
`binary/zarr-example` fixture for it). Supports zarr_format 2 arrays with
C or F chunk order, '.'- or '/'-separated chunk keys, missing chunks
(fill_value), zlib/gzip compressor or none; nested groups with `.zattrs`
metadata.
"""

from __future__ import annotations

import json
import os
import zipfile
import zlib

import numpy as np


class ZarrStore:
    """Read-only key/value view over a directory tree or .zip store."""

    def __init__(self, path: str):
        self.path = path
        if os.path.isfile(path) and path.endswith(".zip"):
            self._zip = zipfile.ZipFile(path)
            self._keys = set(self._zip.namelist())
        else:
            self._zip = None
            self._keys = set()
            for root, _dirs, files in os.walk(path):
                for f in files:
                    rel = os.path.relpath(os.path.join(root, f), path)
                    self._keys.add(rel.replace(os.sep, "/"))

    def get(self, key: str) -> bytes | None:
        if key not in self._keys:
            return None
        if self._zip is not None:
            return self._zip.read(key)
        return open(os.path.join(self.path, key.replace("/", os.sep)), "rb").read()

    def arrays(self) -> list[str]:
        """Paths of every array in the store (keys ending in .zarray)."""
        out = []
        for k in self._keys:
            if k.endswith(".zarray"):
                out.append(k[: -len(".zarray")].rstrip("/"))
        return sorted(out)

    def attrs(self, prefix: str = "") -> dict:
        key = f"{prefix}/.zattrs" if prefix else ".zattrs"
        raw = self.get(key)
        return json.loads(raw) if raw else {}

    def read_array(self, name: str) -> np.ndarray:
        meta_raw = self.get(f"{name}/.zarray" if name else ".zarray")
        if meta_raw is None:
            raise ValueError(f"no array {name!r} in {self.path!r}")
        meta = json.loads(meta_raw)
        if meta.get("zarr_format") != 2:
            raise ValueError(f"zarr_format {meta.get('zarr_format')} unsupported")
        if meta.get("filters"):
            raise ValueError("zarr filters unsupported")
        comp = meta.get("compressor")
        if comp is not None and comp.get("id") not in ("zlib", "gzip"):
            raise ValueError(f"zarr compressor {comp.get('id')!r} unsupported")
        shape = tuple(meta["shape"])
        chunks = tuple(meta["chunks"])
        order = meta.get("order", "C")
        dtype = np.dtype(meta["dtype"])
        fill = meta.get("fill_value", 0)
        if fill is None:  # v2 allows null = undefined fill
            fill = np.nan if dtype.kind == "f" else 0
        sep = meta.get("dimension_separator", ".")
        out = np.full(shape, fill, dtype=dtype)
        n_chunks = [-(-s // c) for s, c in zip(shape, chunks)]
        for idx in np.ndindex(*n_chunks):
            key_name = sep.join(str(i) for i in idx)
            key = f"{name}/{key_name}" if name else key_name
            raw = self.get(key)
            if raw is None:
                continue  # missing chunk = fill_value
            if comp is not None:
                raw = zlib.decompress(raw, 47)  # auto-detect zlib/gzip header
            block = np.frombuffer(raw, dtype=dtype).reshape(chunks, order=order)
            sl = tuple(
                slice(i * c, min((i + 1) * c, s))
                for i, c, s in zip(idx, chunks, shape)
            )
            out[sl] = block[tuple(slice(0, q.stop - q.start) for q in sl)]
        return out


def read_zarr(path: str, array: str | None = None):
    """One array (or the store listing) from a Zarr v2 store.

    Returns (np.ndarray, attrs) for a named (or the only) array.
    """
    store = ZarrStore(path)
    names = store.arrays()
    if array is None:
        if len(names) != 1:
            raise ValueError(
                f"store has {len(names)} arrays — pass array=...: {names}"
            )
        array = names[0]
    return store.read_array(array), store.attrs(array)
