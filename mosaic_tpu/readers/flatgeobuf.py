"""FlatGeobuf (.fgb) vector reader/writer — hand-decoded flatbuffers.

Reference analog: the OGR "FlatGeobuf" driver reachable through
`datasource/OGRFileFormat.scala:26-47` (any driver name). FlatGeobuf is a
flatbuffers-framed columnar format: magic, a Header table (schema columns,
geometry type, CRS, feature count, spatial-index node size), an optional
packed Hilbert R-tree, then length-prefixed Feature tables whose Geometry
carries coordinates as flat ``xy`` vectors with ``ends`` part splits.

No flatbuffers library exists in this environment, so both directions
speak the wire format directly: a ~60-line table decoder (vtable-indirect
field access) and a tiny prepend-style builder for the writer. The writer
emits no spatial index (``index_node_size = 0``) — legal per spec, and
the reader skips any index it finds by the published node-count formula.
"""

from __future__ import annotations

import struct

import numpy as np

from ..core.types import GeometryBuilder, GeometryType, close_ring, open_ring
from .vector import VectorTable

MAGIC = b"fgb\x03fgb\x00"

# feature.fbs GeometryType -> packed GeometryType
_GEOM_TYPES = {
    1: GeometryType.POINT,
    2: GeometryType.LINESTRING,
    3: GeometryType.POLYGON,
    4: GeometryType.MULTIPOINT,
    5: GeometryType.MULTILINESTRING,
    6: GeometryType.MULTIPOLYGON,
    7: GeometryType.GEOMETRYCOLLECTION,
}

# header.fbs ColumnType ordinals
_COL_BYTE, _COL_UBYTE, _COL_BOOL = 0, 1, 2
_COL_SHORT, _COL_USHORT, _COL_INT, _COL_UINT = 3, 4, 5, 6
_COL_LONG, _COL_ULONG, _COL_FLOAT, _COL_DOUBLE = 7, 8, 9, 10
_COL_STRING, _COL_JSON, _COL_DATETIME, _COL_BINARY = 11, 12, 13, 14

_FIXED_FMT = {
    _COL_BYTE: "b", _COL_UBYTE: "B", _COL_BOOL: "?",
    _COL_SHORT: "h", _COL_USHORT: "H", _COL_INT: "i", _COL_UINT: "I",
    _COL_LONG: "q", _COL_ULONG: "Q", _COL_FLOAT: "f", _COL_DOUBLE: "d",
}


# --------------------------------------------------------------------------
# flatbuffers table decoding
# --------------------------------------------------------------------------


class _Table:
    """One flatbuffers table: vtable-indirect access to its fields."""

    __slots__ = ("buf", "pos", "vt", "vt_len")

    def __init__(self, buf: bytes, pos: int):
        self.buf = buf
        self.pos = pos
        soff = struct.unpack_from("<i", buf, pos)[0]
        self.vt = pos - soff
        self.vt_len = struct.unpack_from("<H", buf, self.vt)[0]

    def _field(self, slot: int) -> int:
        """Absolute position of field ``slot``, or 0 when absent."""
        vo = 4 + 2 * slot
        if vo >= self.vt_len:
            return 0
        off = struct.unpack_from("<H", self.buf, self.vt + vo)[0]
        return self.pos + off if off else 0

    def scalar(self, slot: int, fmt: str, default=0):
        p = self._field(slot)
        return struct.unpack_from("<" + fmt, self.buf, p)[0] if p else default

    def _indirect(self, p: int) -> int:
        return p + struct.unpack_from("<I", self.buf, p)[0]

    def string(self, slot: int) -> str | None:
        p = self._field(slot)
        if not p:
            return None
        v = self._indirect(p)
        n = struct.unpack_from("<I", self.buf, v)[0]
        return self.buf[v + 4 : v + 4 + n].decode("utf-8")

    def vector(self, slot: int, dtype) -> np.ndarray | None:
        p = self._field(slot)
        if not p:
            return None
        v = self._indirect(p)
        n = struct.unpack_from("<I", self.buf, v)[0]
        return np.frombuffer(self.buf, dtype=dtype, count=n, offset=v + 4)

    def table(self, slot: int) -> "_Table | None":
        p = self._field(slot)
        return _Table(self.buf, self._indirect(p)) if p else None

    def table_vector(self, slot: int) -> "list[_Table]":
        p = self._field(slot)
        if not p:
            return []
        v = self._indirect(p)
        n = struct.unpack_from("<I", self.buf, v)[0]
        return [
            _Table(self.buf, self._indirect(v + 4 + 4 * i)) for i in range(n)
        ]

    def bytes_vector(self, slot: int) -> bytes:
        p = self._field(slot)
        if not p:
            return b""
        v = self._indirect(p)
        n = struct.unpack_from("<I", self.buf, v)[0]
        return bytes(self.buf[v + 4 : v + 4 + n])


def _root(buf: bytes) -> _Table:
    return _Table(buf, struct.unpack_from("<I", buf, 0)[0])


# --------------------------------------------------------------------------
# reader
# --------------------------------------------------------------------------


def _index_bytes(num_items: int, node_size: int) -> int:
    """Size of the packed Hilbert R-tree (40-byte nodes), per the spec's
    level-count recurrence."""
    if node_size < 2 or num_items == 0:
        return 0
    n, total = num_items, num_items
    while n != 1:
        n = (n + node_size - 1) // node_size
        total += n
    return total * 40


def _emit_geometry(b: GeometryBuilder, g: _Table | None, gtype: int,
                   srid: int, has_z: bool) -> None:
    """Append one Feature geometry (possibly nested parts) to the builder."""
    t = _GEOM_TYPES.get(gtype)
    if g is None:  # null geometry row -> empty collection, as GeoJSON path
        b.end_part()
        b.end_geom(GeometryType.GEOMETRYCOLLECTION, srid)
        return
    if t is None:
        raise ValueError(f"unsupported FlatGeobuf geometry type {gtype}")
    if t in (GeometryType.MULTIPOLYGON, GeometryType.GEOMETRYCOLLECTION):
        parts = g.table_vector(7)
        if t == GeometryType.GEOMETRYCOLLECTION:
            from ..core.geometry.collection import end_collection

            members = []
            for pt in parts:
                sub = GeometryBuilder()
                ptype = pt.scalar(6, "B", 0)
                _emit_geometry(sub, pt, ptype, srid, has_z)
                members.append((_GEOM_TYPES[ptype], sub.build()))
            end_collection(b, members, srid)
            return
        for pt in parts:  # each part: one Polygon table
            _polygon_rings(b, pt, has_z)
        b.end_geom(t, srid)
        return
    xy = g.vector(1, "<f8")
    xy = (
        np.asarray(xy, dtype=np.float64).reshape(-1, 2)
        if xy is not None
        else np.zeros((0, 2))
    )
    z = g.vector(2, "<f8") if has_z else None
    if t == GeometryType.POINT or t == GeometryType.LINESTRING:
        b.add_ring(xy, None if z is None else np.asarray(z))
        b.end_part()
    elif t == GeometryType.MULTIPOINT:
        for i in range(xy.shape[0]):
            b.add_ring(xy[i : i + 1], None if z is None else z[i : i + 1])
            b.end_part()
    elif t == GeometryType.MULTILINESTRING:
        for s, e in _part_slices(g, xy.shape[0]):
            b.add_ring(xy[s:e], None if z is None else z[s:e])
            b.end_part()
    elif t == GeometryType.POLYGON:
        _polygon_rings(b, g, has_z)
    b.end_geom(t, srid)


def _part_slices(g: _Table, n_coords: int):
    ends = g.vector(0, "<u4")
    if ends is None or len(ends) == 0:
        return [(0, n_coords)]
    out, s = [], 0
    for e in ends.tolist():
        out.append((s, int(e)))
        s = int(e)
    return out


def _polygon_rings(b: GeometryBuilder, g: _Table, has_z: bool) -> None:
    """One polygon (outer + holes): rings arrive closed (WKB convention),
    stored open in the packed layout."""
    xy = g.vector(1, "<f8")
    xy = (
        np.asarray(xy, dtype=np.float64).reshape(-1, 2)
        if xy is not None
        else np.zeros((0, 2))
    )
    z = g.vector(2, "<f8") if has_z else None
    for s, e in _part_slices(g, xy.shape[0]):
        rxy, rz = open_ring(xy[s:e], None if z is None else np.asarray(z[s:e]))
        b.add_ring(rxy, rz)
    b.end_part()


def _decode_properties(buf: bytes, cols: list[tuple[str, int]]) -> dict:
    out: dict = {}
    p, n = 0, len(buf)
    while p + 2 <= n:
        (ci,) = struct.unpack_from("<H", buf, p)
        p += 2
        if ci >= len(cols):
            raise ValueError(f"properties reference unknown column {ci}")
        name, ct = cols[ci]
        fmt = _FIXED_FMT.get(ct)
        if fmt is not None:
            (val,) = struct.unpack_from("<" + fmt, buf, p)
            p += struct.calcsize(fmt)
            out[name] = val
        elif ct in (_COL_STRING, _COL_JSON, _COL_DATETIME, _COL_BINARY):
            (ln,) = struct.unpack_from("<I", buf, p)
            p += 4
            raw = buf[p : p + ln]
            p += ln
            out[name] = raw if ct == _COL_BINARY else raw.decode("utf-8")
        else:
            raise ValueError(f"unsupported FlatGeobuf column type {ct}")
    return out


def read_flatgeobuf(path: str) -> VectorTable:
    """FlatGeobuf file -> :class:`VectorTable` (typed attribute columns)."""
    from .vector import props_to_columns

    with open(path, "rb") as f:
        data = f.read()
    if data[:8] != MAGIC[:8]:
        # verify the 'fgb' magic but accept any patch level (byte 7)
        if data[:4] != MAGIC[:4] or data[4:7] != MAGIC[4:7]:
            raise ValueError(f"not a FlatGeobuf file: {path}")
    p = 8
    (hlen,) = struct.unpack_from("<I", data, p)
    p += 4
    header = _root(data[p : p + hlen])
    p += hlen
    gtype = header.scalar(2, "B", 0)
    has_z = bool(header.scalar(3, "?", False))
    cols = [
        (c.string(0) or f"col{i}", c.scalar(1, "B", 0))
        for i, c in enumerate(header.table_vector(7))
    ]
    n_feat = header.scalar(8, "Q", 0)
    node_size = header.scalar(9, "H", 16)
    crs = header.table(10)
    srid = crs.scalar(1, "i", 0) if crs is not None else 0
    if srid <= 0:
        srid = 4326  # FGB default CRS is OGC:CRS84 (lon/lat)
    p += _index_bytes(n_feat, node_size)

    b = GeometryBuilder()
    props: list[dict] = []
    # bound by the promised count when the header carries one: trailing
    # bytes after the last feature must not be misread as a frame
    while p + 4 <= len(data) and (n_feat == 0 or len(props) < n_feat):
        (flen,) = struct.unpack_from("<I", data, p)
        p += 4
        if p + flen > len(data):
            raise ValueError(
                f"FlatGeobuf feature frame at byte {p - 4} overruns the file"
            )
        feat = _root(data[p : p + flen])
        p += flen
        g = feat.table(0)
        # per-feature type wins for heterogeneous (Unknown) collections
        ftype = g.scalar(6, "B", 0) if g is not None else 0
        _emit_geometry(b, g, ftype or gtype, srid, has_z)
        props.append(_decode_properties(feat.bytes_vector(1), cols))
    if n_feat and len(props) != n_feat:
        raise ValueError(
            f"FlatGeobuf header promises {n_feat} features, found {len(props)}"
        )
    return VectorTable(geometry=b.build(), columns=props_to_columns(props))


# --------------------------------------------------------------------------
# writer (fixture-grade: no spatial index)
# --------------------------------------------------------------------------


class _Builder:
    """Tiny prepend-style flatbuffers builder.

    Offsets are tracked as distances from the END of the buffer (the file
    grows by prepending), so a stored UOffset is simply
    ``field_distance - target_distance``. O(n^2) appends — fine for the
    fixture/writer scale this supports."""

    def __init__(self):
        self.buf = bytearray()

    @property
    def dist(self) -> int:
        return len(self.buf)

    def _prepend(self, raw: bytes) -> None:
        self.buf[:0] = raw

    def _align(self, size: int, extra: int = 0) -> None:
        while (len(self.buf) + extra) % size:
            self._prepend(b"\x00")

    def string(self, s: str) -> int:
        # file order [u32 len][bytes][NUL][pad]: padding is prepended
        # FIRST (prepends land at lower addresses, so earlier prepends sit
        # closer to the file end) to keep the length adjacent to the bytes
        raw = s.encode("utf-8") + b"\x00"
        self._align(4, extra=len(raw))
        self._prepend(raw)
        self._prepend(struct.pack("<I", len(raw) - 1))
        return self.dist

    def vector_scalar(self, fmt: str, vals) -> int:
        raw = b"".join(struct.pack("<" + fmt, v) for v in vals)
        self._align(max(4, struct.calcsize(fmt)), extra=len(raw))
        self._prepend(raw)
        self._prepend(struct.pack("<I", len(vals)))
        return self.dist

    def vector_offsets(self, offs: list[int]) -> int:
        self._align(4, extra=4 * len(offs))
        for o in reversed(offs):
            self._prepend(struct.pack("<I", self.dist + 4 - o))
        self._prepend(struct.pack("<I", len(offs)))
        return self.dist

    def table(self, fields: "dict[int, tuple]") -> int:
        """fields: slot -> ("scalar", fmt, value) | ("offset", target_dist).

        Layout: [soffset32][fields in slot order, aligned]; the vtable is
        prepended immediately before the table, so soffset == len(vtable).
        """
        slots = sorted(fields)
        n_slots = (max(slots) + 1) if slots else 0
        vt_len = 4 + 2 * n_slots
        # lay out field positions within the table (after the 4B soffset)
        pos: dict[int, int] = {}
        cur = 4
        blobs: dict[int, bytes] = {}
        for s in slots:
            kind = fields[s]
            if kind[0] == "scalar":
                raw = struct.pack("<" + kind[1], kind[2])
            else:
                raw = b"\x00\x00\x00\x00"  # patched below
            size = len(raw)
            align = min(size, 8) or 1
            cur = (cur + align - 1) // align * align
            pos[s] = cur
            blobs[s] = raw
            cur += size
        t_len = (cur + 3) // 4 * 4
        table = bytearray(t_len)
        struct.pack_into("<i", table, 0, vt_len)  # soffset -> vtable
        self._align(8, extra=t_len)  # 8-byte scalars inside stay aligned
        table_dist = self.dist + t_len  # distance of table start, once laid
        for s in slots:
            kind = fields[s]
            if kind[0] == "offset":
                field_dist = table_dist - pos[s]
                struct.pack_into(
                    "<I", table, pos[s], field_dist - kind[1]
                )
            else:
                table[pos[s] : pos[s] + len(blobs[s])] = blobs[s]
        self._prepend(bytes(table))
        vt = struct.pack("<HH", vt_len, t_len) + b"".join(
            struct.pack("<H", pos.get(s, 0)) for s in range(n_slots)
        )
        self._prepend(vt)
        return table_dist

    def finish(self, root_dist: int) -> bytes:
        # final length ≡ 0 mod 8 makes every dist-aligned object
        # address-aligned (addr = total_len - dist)
        self._align(8, extra=4)
        self._prepend(struct.pack("<I", self.dist + 4 - root_dist))
        return bytes(self.buf)


def _geometry_fields(b: _Builder, col, g: int, gtype: GeometryType):
    """Build the Geometry table contents for geometry ``g``; returns the
    table's field dict (coordinates closed back up for polygon rings,
    Z riding the parallel slot-2 vector when the geometry carries it)."""
    fields: dict[int, tuple] = {}
    t = gtype
    with_z = col.has_z(g)
    if t == GeometryType.MULTIPOLYGON:
        parts = []
        for p in col.geom_parts(g):
            sub: dict[int, tuple] = {}
            _rings_into(b, col, [p], sub, with_z)
            parts.append(b.table(sub))
        fields[7] = ("offset", b.vector_offsets(parts))
        fields[6] = ("scalar", "B", 6)
        return fields
    if t == GeometryType.GEOMETRYCOLLECTION:
        # packed columns never hold multi-member collections (parse
        # collapses them, core/geometry/collection.py); only the EMPTY
        # marker survives, which the caller writes as a null geometry
        raise ValueError("GEOMETRYCOLLECTION has no FlatGeobuf geometry")
    if t == GeometryType.POLYGON:
        _rings_into(b, col, list(col.geom_parts(g)), fields, with_z)
    else:
        xy = col.geom_xy(g)
        if t == GeometryType.MULTILINESTRING:
            ends, n = [], 0
            for p in col.geom_parts(g):
                for r in col.part_rings(p):
                    n += col.ring_xy(r).shape[0]
                    ends.append(n)
            if len(ends) > 1:
                fields[0] = ("offset", b.vector_scalar("I", ends))
        fields[1] = ("offset", b.vector_scalar("d", xy.reshape(-1).tolist()))
        if with_z:
            z = col.z[col.geom_vertex_slice(g)]
            fields[2] = ("offset", b.vector_scalar("d", z.tolist()))
    fields[6] = ("scalar", "B", int(_WKB_OF[t]))
    return fields


def _rings_into(b: _Builder, col, parts, fields, with_z: bool) -> None:
    """Closed-ring xy (+z) and ends vectors for one polygon's parts."""
    chunks, zchunks, ends, n = [], [], [], 0
    for p in parts:
        for r in col.part_rings(p):
            xy, z = close_ring(
                col.ring_xy(r), col.ring_z(r) if with_z else None
            )
            chunks.append(xy)
            if with_z:
                zchunks.append(z)
            n += xy.shape[0]
            ends.append(n)
    xy_all = np.vstack(chunks) if chunks else np.zeros((0, 2))
    if len(ends) > 1:
        fields[0] = ("offset", b.vector_scalar("I", ends))
    fields[1] = ("offset", b.vector_scalar("d", xy_all.reshape(-1).tolist()))
    if with_z:
        z_all = np.concatenate(zchunks) if zchunks else np.zeros(0)
        fields[2] = ("offset", b.vector_scalar("d", z_all.tolist()))


_WKB_OF = {
    GeometryType.POINT: 1,
    GeometryType.LINESTRING: 2,
    GeometryType.POLYGON: 3,
    GeometryType.MULTIPOINT: 4,
    GeometryType.MULTILINESTRING: 5,
    GeometryType.MULTIPOLYGON: 6,
    GeometryType.GEOMETRYCOLLECTION: 7,
}


def write_flatgeobuf(path: str, table: VectorTable, name: str = "layer",
                     srid: int = 4326) -> None:
    """Write a VectorTable as FlatGeobuf (no spatial index; string and
    float columns — the writer exists to round-trip fixtures and exports,
    not to replace a full OGR writer)."""
    col = table.geometry
    types = {col.geometry_type(g) for g in range(len(col))}
    gtype = _WKB_OF[next(iter(types))] if len(types) == 1 else 0

    cols: list[tuple[str, int]] = []
    for k, v in table.columns.items():
        ct = _COL_DOUBLE if np.issubdtype(
            np.asarray(v).dtype, np.floating
        ) else _COL_STRING
        cols.append((k, ct))

    out = bytearray(MAGIC)

    hb = _Builder()
    col_offs = [
        hb.table({0: ("offset", hb.string(k)), 1: ("scalar", "B", ct)})
        for k, ct in cols
    ]
    hfields: dict[int, tuple] = {
        0: ("offset", hb.string(name)),
        2: ("scalar", "B", gtype),
        8: ("scalar", "Q", len(col)),
    }
    if any(col.has_z(g) for g in range(len(col))):
        hfields[3] = ("scalar", "?", True)
    hfields.update({
        9: ("scalar", "H", 0),  # no spatial index
        10: ("offset", hb.table({
            0: ("offset", hb.string("EPSG")),
            1: ("scalar", "i", int(srid)),
        })),
    })
    if col_offs:
        hfields[7] = ("offset", hb.vector_offsets(col_offs))
    hdr = hb.finish(hb.table(hfields))
    out += struct.pack("<I", len(hdr)) + hdr

    for g in range(len(col)):
        fb = _Builder()
        gt = col.geometry_type(g)
        if gt == GeometryType.GEOMETRYCOLLECTION:
            geom_off = None  # empty collection == null-geometry feature
        else:
            geom_off = fb.table(_geometry_fields(fb, col, g, gt))
        props = bytearray()
        for ci, (k, ct) in enumerate(cols):
            v = table.columns[k][g]
            props += struct.pack("<H", ci)
            if ct == _COL_DOUBLE:
                props += struct.pack("<d", float(v))
            else:
                raw = str(v).encode("utf-8")
                props += struct.pack("<I", len(raw)) + raw
        ffields: dict[int, tuple] = (
            {} if geom_off is None else {0: ("offset", geom_off)}
        )
        if props:
            ffields[1] = ("offset", fb.vector_scalar("B", list(props)))
        feat = fb.finish(fb.table(ffields))
        out += struct.pack("<I", len(feat)) + feat

    with open(path, "wb") as f:
        f.write(out)
