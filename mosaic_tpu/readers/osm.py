"""OpenStreetMap XML (.osm) reader -> VectorTable.

Reference analog: OGR's OSM driver behind `OGRFileFormat`
(`datasource/OGRFileFormat.scala:26-47` accepts any driver name). OGR
splits OSM into per-type layers; this columnar reader keeps one table
with a ``kind`` column instead (point / line / polygon / multipolygon),
which filters to the same subsets.

Feature rules (OGR-compatible in spirit):
- tagged nodes -> POINT features;
- ways -> LINESTRING, or POLYGON when the way is closed and carries an
  area-ish tag (``area=yes``, ``building``, ``landuse``, ``natural``,
  ``leisure``, ``amenity`` ...) — highways stay lines even when closed
  (roundabouts);
- ``type=multipolygon``/``boundary`` relations -> (MULTI)POLYGON from
  their outer/inner member ways (members missing from the extract are
  skipped, like OGR's incomplete-relation handling).

Tags land as object columns via the shared ``props_to_columns`` typing;
``osm_id`` and ``kind`` are always present.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

import numpy as np

from ..core.types import GeometryBuilder, GeometryType
from .vector import VectorTable, props_to_columns

#: closed ways with any of these tag keys become polygons
_AREA_KEYS = {
    "building", "landuse", "natural", "leisure", "amenity", "area",
    "shop", "tourism", "waterway", "place",
}


def _is_area(tags: dict) -> bool:
    if tags.get("area") == "no":
        return False
    if tags.get("area") == "yes":
        return True
    if "highway" in tags or "barrier" in tags:
        return False
    return any(k in tags for k in _AREA_KEYS)


def _ring_from_way_refs(refs, nodes) -> "np.ndarray | None":
    pts = [nodes[r] for r in refs if r in nodes]
    if len(pts) < 2 or len(pts) != len(refs):
        return None
    return np.asarray(pts, dtype=np.float64)


def _assemble_rings(ways: "list[np.ndarray]") -> "list[np.ndarray]":
    """Chain open member ways into closed rings (endpoint matching)."""
    segs = [w for w in ways if w is not None and w.shape[0] >= 2]
    rings: list[np.ndarray] = []
    while segs:
        cur = segs.pop()
        # already closed?
        while not np.array_equal(cur[0], cur[-1]):
            for i, s in enumerate(segs):
                if np.array_equal(s[0], cur[-1]):
                    cur = np.concatenate([cur, s[1:]])
                    segs.pop(i)
                    break
                if np.array_equal(s[-1], cur[-1]):
                    cur = np.concatenate([cur, s[::-1][1:]])
                    segs.pop(i)
                    break
            else:
                cur = None  # incomplete ring: drop (OGR skips too)
                break
        if cur is not None and cur.shape[0] >= 4:
            rings.append(cur)
    return rings


def read_osm(path: str) -> VectorTable:
    """Parse an OSM XML extract into a single VectorTable."""
    nodes: dict[str, tuple[float, float]] = {}
    node_tags: dict[str, dict] = {}
    ways: dict[str, list] = {}
    way_tags: dict[str, dict] = {}
    relations: list[tuple[str, dict, list]] = []

    for _ev, el in ET.iterparse(path, events=("end",)):
        if el.tag == "node":
            nid = el.get("id")
            nodes[nid] = (float(el.get("lon")), float(el.get("lat")))
            tags = {t.get("k"): t.get("v") for t in el.findall("tag")}
            if tags:
                node_tags[nid] = tags
            el.clear()
        elif el.tag == "way":
            wid = el.get("id")
            ways[wid] = [nd.get("ref") for nd in el.findall("nd")]
            way_tags[wid] = {
                t.get("k"): t.get("v") for t in el.findall("tag")
            }
            el.clear()
        elif el.tag == "relation":
            tags = {t.get("k"): t.get("v") for t in el.findall("tag")}
            members = [
                (m.get("type"), m.get("ref"), m.get("role") or "outer")
                for m in el.findall("member")
            ]
            relations.append((el.get("id"), tags, members))
            el.clear()

    b = GeometryBuilder()
    props: list[dict] = []

    def emit(gtype, parts, osm_id, kind, tags):
        b.add_geometry(gtype, parts, 4326)
        props.append({"osm_id": osm_id, "kind": kind, **tags})

    for nid, tags in node_tags.items():
        xy = np.asarray([nodes[nid]], dtype=np.float64)
        emit(GeometryType.POINT, [[xy]], nid, "point", tags)

    ways_in_relations: set[str] = set()
    for _rid, tags, members in relations:
        if tags.get("type") in ("multipolygon", "boundary"):
            for mtype, ref, _role in members:
                if mtype == "way":
                    ways_in_relations.add(ref)

    for wid, refs in ways.items():
        tags = way_tags.get(wid, {})
        if not tags and wid in ways_in_relations:
            continue  # pure relation-member way: geometry only
        ring = _ring_from_way_refs(refs, nodes)
        if ring is None:
            continue
        closed = ring.shape[0] >= 4 and np.array_equal(ring[0], ring[-1])
        if closed and _is_area(tags):
            emit(GeometryType.POLYGON, [[ring[:-1]]], wid, "polygon", tags)
        else:
            emit(GeometryType.LINESTRING, [[ring]], wid, "line", tags)

    for rid, tags, members in relations:
        if tags.get("type") not in ("multipolygon", "boundary"):
            continue
        outers = _assemble_rings(
            [
                _ring_from_way_refs(ways.get(ref, []), nodes)
                for mtype, ref, role in members
                if mtype == "way" and role in ("outer", "")
            ]
        )
        inners = _assemble_rings(
            [
                _ring_from_way_refs(ways.get(ref, []), nodes)
                for mtype, ref, role in members
                if mtype == "way" and role == "inner"
            ]
        )
        if not outers:
            continue
        if len(outers) == 1:
            rings = [outers[0][:-1]] + [r[:-1] for r in inners]
            emit(GeometryType.POLYGON, [rings], rid, "multipolygon", tags)
        else:
            # holes attach to the first outer that bbox-contains them
            polys = [[o[:-1]] for o in outers]
            for hole in inners:
                hb = hole.min(0), hole.max(0)
                for poly in polys:
                    ob = poly[0].min(0), poly[0].max(0)
                    if (ob[0] <= hb[0]).all() and (hb[1] <= ob[1]).all():
                        poly.append(hole[:-1])
                        break
            emit(GeometryType.MULTIPOLYGON, polys, rid, "multipolygon", tags)

    if not props:
        raise ValueError(f"no features found in {path}")
    cols = props_to_columns(props)
    # osm ids are numeric strings: keep them int64 for joins
    cols["osm_id"] = np.asarray(
        [int(p["osm_id"]) for p in props], dtype=np.int64
    )
    return VectorTable(geometry=b.build(), columns=cols)
