"""raster_to_grid pipeline + raster metadata datasource.

Reference analog: `RasterAsGridReader`
(`datasource/multiread/RasterAsGridReader.scala:18-221`): binaryFile listing
-> subdataset resolve -> retile -> rst_rastertogrid<combiner> -> explode ->
group-by cell -> k-ring inverse-distance interpolation (`kRingResample:
164-181`); and `GDALFileFormat` (`datasource/GDALFileFormat.scala:94-111`)
whose fixed metadata schema becomes :func:`read_gdal_metadata`.
"""

from __future__ import annotations

import glob as _glob
from pathlib import Path

import numpy as np

from ..raster import read_raster


def _list_paths(path: "str | list[str]", ext: "str | None") -> list[str]:
    if isinstance(path, (list, tuple)):
        return [str(p) for p in path]
    p = Path(path)
    if p.is_dir():
        suffix = (ext or "").lower()
        return sorted(
            str(q)
            for q in p.iterdir()
            if q.is_file() and q.name.lower().endswith(suffix)
        )
    if any(c in str(path) for c in "*?["):
        return sorted(_glob.glob(str(path)))
    return [str(path)]


def read_gdal_metadata(path, ext: "str | None" = ".TIF") -> list[dict]:
    """Raster metadata table: one dict per file (reference: GDALFileFormat
    fixed schema — path, sizes, band count, metadata, subdatasets, srid)."""
    out = []
    for p in _list_paths(path, ext):
        r = read_raster(p)
        out.append(
            {
                "path": p,
                "ySize": r.height,
                "xSize": r.width,
                "bandCount": r.num_bands,
                "metadata": r.metadata(),
                "subdatasets": r.subdatasets(),
                "srid": r.srid,
                "proj4Str": "",
            }
        )
    return out


def raster_to_grid(
    path,
    resolution: int,
    combiner: str = "avg",
    index=None,
    raster_srid: "int | None" = None,
    tile_size: int = 512,
    k_ring_interpolate: int = 0,
    ext: "str | None" = ".TIF",
) -> dict[int, dict[int, float]]:
    """Full pipeline: files -> retile -> pixel->cell combine -> merge ->
    optional k-ring inverse-distance resample.

    Returns {band (1-based): {cell_id: value}} merged over all input files.
    """
    from ..context import current_context
    from ..functions import raster as RF

    if index is None:
        index = current_context().index_system
    resolution = index.resolution_arg(resolution)

    if combiner not in ("avg", "min", "max", "median", "count"):
        raise ValueError(f"unknown combiner {combiner!r}")

    # Per-cell accumulation across tiles and files (the reference's final
    # group-by(band, cell) combine, `RasterAsGridReader.scala:61-76`).
    # avg is merged pixel-weighted (sum of avg*count / sum of count) so cells
    # straddling tile boundaries combine exactly; median is not mergeable
    # from per-tile medians, so median skips retiling and runs whole-raster.
    per_band_acc: dict[int, dict[int, list]] = {}
    fn = getattr(RF, f"rst_rastertogrid{combiner}")
    for p in _list_paths(path, ext):
        r = read_raster(p)
        can_tile = combiner != "median"
        tiles = r.retile(tile_size, tile_size) if can_tile and (
            r.width > tile_size or r.height > tile_size
        ) else [r]
        for t in tiles:
            res = fn([t], resolution, index=index, raster_srid=raster_srid)[0]
            if combiner == "avg":
                cnt = RF.rst_rastertogridcount(
                    [t], resolution, index=index, raster_srid=raster_srid
                )[0]
            for b, cellmap in enumerate(res, start=1):
                acc = per_band_acc.setdefault(b, {})
                for cell, val in cellmap.items():
                    if combiner == "avg":
                        acc.setdefault(cell, []).append(
                            (val * cnt[b - 1][cell], cnt[b - 1][cell])
                        )
                    else:
                        acc.setdefault(cell, []).append(val)

    merged: dict[int, dict[int, float]] = {}
    for b, acc in per_band_acc.items():
        cells = {}
        for cell, vals in acc.items():
            if combiner == "avg":
                s = sum(v[0] for v in vals)
                c = sum(v[1] for v in vals)
                cells[cell] = float(s / c) if c else float("nan")
                continue
            v = np.asarray(vals, dtype=np.float64)
            if combiner == "min":
                cells[cell] = float(v.min())
            elif combiner == "max":
                cells[cell] = float(v.max())
            elif combiner == "median":
                cells[cell] = float(v[0]) if v.size == 1 else float(np.median(v))
            elif combiner == "count":
                cells[cell] = float(v.sum())
        merged[b] = cells

    if k_ring_interpolate > 0:
        for b in merged:
            merged[b] = k_ring_resample(
                merged[b], k_ring_interpolate, index
            )
    return merged


def k_ring_resample(
    cellmap: dict[int, float], k: int, index
) -> dict[int, float]:
    """Inverse-grid-distance weighted smoothing over each cell's k-ring
    (reference: `kRingResample` / `gridDistanceInverse` weighting,
    `RasterAsGridReader.scala:164-181`). Cells with no measured neighbor
    keep no value (like the reference's inner join on the ring)."""
    if not cellmap:
        return cellmap
    cells = np.fromiter(cellmap.keys(), dtype=np.int64)
    vals = np.fromiter(cellmap.values(), dtype=np.float64)
    rings = np.asarray(index.k_ring(cells, int(k)))  # (N, M)
    lut = {int(c): float(v) for c, v in zip(cells, vals)}
    out: dict[int, float] = {}
    # every ring member becomes a target; weight = 1/(1+grid_distance)
    targets: dict[int, list[tuple[float, float]]] = {}
    for i in range(cells.shape[0]):
        ring = rings[i]
        ring = ring[ring >= 0]
        dist = np.asarray(
            index.grid_distance(np.full(ring.shape, cells[i]), ring)
        ).astype(np.float64)
        w = 1.0 / (1.0 + dist)
        for c, wi in zip(ring, w):
            targets.setdefault(int(c), []).append((wi * vals[i], wi))
    for c, pairs in targets.items():
        num = sum(p[0] for p in pairs)
        den = sum(p[1] for p in pairs)
        out[c] = lut.get(c, num / den if den else np.nan)
        # measured cells keep their measurement; unmeasured get the IDW blend
    return out
