"""Models & services (reference: `models/` — SpatialKNN + core transformers)."""

from .core import (  # noqa: F401
    BinaryTransformer,
    CheckpointManager,
    IterativeTransformer,
)
from .knn import GridRingNeighbours, SpatialKNN  # noqa: F401

__all__ = [
    "CheckpointManager",
    "IterativeTransformer",
    "BinaryTransformer",
    "GridRingNeighbours",
    "SpatialKNN",
]
