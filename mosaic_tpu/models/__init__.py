"""Models & services (reference: `models/` — SpatialKNN + core transformers)."""

from .core import CheckpointManager, IterativeTransformer  # noqa: F401
from .knn import GridRingNeighbours, SpatialKNN  # noqa: F401

__all__ = [
    "CheckpointManager",
    "IterativeTransformer",
    "GridRingNeighbours",
    "SpatialKNN",
]
