"""Model-support primitives: checkpoints + iterate-until-converged.

Reference analogs: `models/util/CheckpointManager.scala:12-103` (Delta-backed
append/overwrite/load used as the per-iteration durability barrier) and
`models/core/IterativeTransformer.scala:49-87` (the generic fold with
early-stopping). Delta tables become directories of ``.npz`` array bundles —
the natural durable format for a columnar host runtime; each iteration's
arrays are one file, append = add file, load = concatenate.
"""

from __future__ import annotations

import json
import shutil
from pathlib import Path
from typing import Callable

import numpy as np


class CheckpointManager:
    """Durable array-table checkpoints (append/overwrite/load).

    A "table" is a dict[str, np.ndarray] of equal-length columns; each
    append writes ``part-<n>.npz``. Mirrors the reference's isTable=false
    directory mode (`CheckpointManager.scala`).
    """

    def __init__(self, location: str, overwrite: bool = False):
        self.dir = Path(location)
        if overwrite and self.dir.exists():
            shutil.rmtree(self.dir)
        self.dir.mkdir(parents=True, exist_ok=True)

    def _parts(self) -> list[Path]:
        return sorted(self.dir.glob("part-*.npz"))

    def append(self, table: dict[str, np.ndarray]) -> None:
        """Write one part (call `load()` to materialize the union —
        appending used to return it, which made every append re-read all
        prior parts: O(n^2) I/O over an iteration loop)."""
        n = len(self._parts())
        np.savez(self.dir / f"part-{n:05d}.npz", **table)

    def overwrite(self, table: dict[str, np.ndarray]) -> None:
        for p in self._parts():
            p.unlink()
        np.savez(self.dir / "part-00000.npz", **table)

    def load(self) -> dict[str, np.ndarray]:
        parts = self._parts()
        if not parts:
            return {}
        loaded = [dict(np.load(p, allow_pickle=True)) for p in parts]
        keys = loaded[0].keys()
        return {k: np.concatenate([d[k] for d in loaded]) for k in keys}

    def write_meta(self, meta: dict) -> None:
        (self.dir / "meta.json").write_text(json.dumps(meta, default=str))

    def read_meta(self) -> dict:
        p = self.dir / "meta.json"
        return json.loads(p.read_text()) if p.exists() else {}

    def delete(self) -> None:
        if self.dir.exists():
            shutil.rmtree(self.dir)


class IterativeTransformer:
    """Iterate ``step`` until ``should_stop`` or ``max_iterations``
    (reference: `IterativeTransformer.iterate:49-87`). State is whatever the
    caller threads through; each iteration may persist via a
    CheckpointManager (the Spark `.checkpoint(true)` barrier analog)."""

    def __init__(
        self,
        step: Callable,
        should_stop: Callable,
        max_iterations: int,
    ):
        self.step = step
        self.should_stop = should_stop
        self.max_iterations = max_iterations
        self.iterations_run = 0

    def iterate(self, state):
        prev = state
        for i in range(1, self.max_iterations + 1):
            self.iterations_run = i
            state = self.step(prev, i)
            if self.should_stop(prev, state):
                break
            prev = state
        return state


class BinaryTransformer(IterativeTransformer):
    """Left/right two-table iterative transformer (reference:
    `models/core/BinaryTransformer.scala` — the skeleton `SpatialKNN`-style
    models build on: a fixed RIGHT table joined against an evolving LEFT
    state each iteration).

    ``join_step(left_state, right, iteration)`` produces the next left
    state; the right side is threaded unchanged (and may live on device —
    e.g. a replicated :class:`~mosaic_tpu.sql.join.ChipIndex`)."""

    def __init__(
        self,
        join_step: Callable,
        should_stop: Callable,
        max_iterations: int,
        right=None,
        checkpoint: "CheckpointManager | None" = None,
    ):
        self.right = right
        self.checkpoint = checkpoint

        def step(left, i):
            out = join_step(left, self.right, i)
            if self.checkpoint is not None:
                # np.asarray also pulls device (jax.Array) states to host so
                # the checkpoint really is recoverable, not counter-only;
                # atleast_1d because load() concatenates columns and 0-d
                # arrays (scalar states) cannot be concatenated
                def _col(name, v):
                    try:
                        return np.atleast_1d(np.asarray(v))
                    except Exception as e:
                        # a dropped column would make parts key-inconsistent
                        # and break (or silently thin) load() on restore
                        raise TypeError(
                            f"checkpointed state {name!r} is not "
                            f"array-convertible: {e}"
                        ) from e

                if isinstance(out, dict):
                    part = {
                        k: _col(k, v)
                        for k, v in out.items()
                        if k != "iteration"
                    }
                else:
                    part = {"left": _col("left", out)}
                part["iteration"] = np.asarray([i])
                self.checkpoint.append(part)
            return out

        super().__init__(step, should_stop, max_iterations)

    def transform(self, left):
        """Run the iteration from an initial left state (ML-style verb)."""
        return self.iterate(left)
