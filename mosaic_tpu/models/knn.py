"""SpatialKNN: distributed approximate/exact K nearest neighbours.

Reference analog: `models/knn/SpatialKNN.scala:28-331` +
`models/knn/GridRingNeighbours.scala:28-206` — iterative grid-ring expansion:
iteration 1 joins each landmark's cell cover k-ring(1) against the
tessellated candidate chips, iteration i>1 joins only the k-loop(i) shell,
so every candidate is inspected once; per-iteration results append to a
checkpoint; early stopping fires when the unmatched count and the total
match count are stable (`earlyStoppingCheck:109-121`); a final exactness
pass widens rings until the grid-guaranteed radius covers each landmark's
current kth-neighbour distance (the reference's buffer-by-kth-distance
final ring, `resultTransform:176-189`).

TPU-native shape: the ring/cell bookkeeping stays on host (sets of int64
cells), while ALL geometry distance evaluation is batched per iteration into
one padded device call (pairs gathered from two DeviceGeometry columns that
share one f64 recenter shift).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..core.index.base import IndexSystem
from ..core.tessellate import tessellate
from ..functions._coerce import to_packed
from ..dispatch import core as _dispatch
from ..runtime.errors import DegradedResult
from .core import CheckpointManager


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


@_dispatch.bounded_cache("knn_pair_distance", 1)
def _pair_distance_prog():
    """The process-wide jitted pairwise-distance program: gather both
    DeviceGeometry columns by row, evaluate `_distance_dense` per pair.
    ONE wrapper whose internal executable cache keys on the padded pair
    width — registered in the dispatch cache registry so
    ``cache_stats()``/``clear_caches()`` govern it like every other
    compiled-program cache."""
    import jax

    from ..core.geometry.device import take_rows
    from ..functions.geometry import _distance_dense, _vmap_pair

    def run(dls, dcs, lrows, crows):
        da = take_rows(dls, lrows)
        db = take_rows(dcs, crows)
        return _vmap_pair(_distance_dense, da, db)

    return jax.jit(run)


class GridRingNeighbours:
    """One iteration's candidate generation + distance evaluation
    (reference: GridRingNeighbours.transform / leftTransform:76-99).

    With ``mesh`` set, each iteration's pair batch shards over the mesh
    devices (`parallel/dist_knn.py`) — the reference's distributed
    join+distance step (`SpatialKNN.scala:202-235`)."""

    def __init__(self, index: IndexSystem, resolution: int, mesh=None):
        self.index = index
        self.resolution = resolution
        self.mesh = mesh

    # ------------------------------------------------------------ cells
    def ring_cells(self, cover: list[np.ndarray], iteration: int) -> list[np.ndarray]:
        """Iteration 1: k-ring(1) of the cover; i>1: k-loop(i) shell only
        (`GridRingNeighbours.leftTransform`: kring for i==1 else kloop)."""
        out = []
        for seed in cover:
            if not seed.size:
                out.append(seed)
                continue
            if iteration == 1:
                cells = np.asarray(self.index.k_ring(seed, 1))
            else:
                cells = np.asarray(self.index.k_loop(seed, iteration))
            out.append(np.unique(cells[cells >= 0]))
        return out

    # --------------------------------------------------------- distances
    def pair_distances(
        self, dl, dc, li: np.ndarray, ci: np.ndarray
    ) -> np.ndarray:
        """Batched geometry distance for (landmark, candidate) row pairs.

        Pads the pair axis to a power of two so iterations share compiled
        kernels, then evaluates `_distance_dense` pairwise on device.
        """
        import jax.numpy as jnp

        P = li.shape[0]
        if P == 0:
            return np.zeros(0)
        if self.mesh is not None:
            from ..parallel.dist_knn import distributed_pair_distances

            return distributed_pair_distances(self.mesh, dl, dc, li, ci)
        Ppad = _pow2(P)
        lip = np.concatenate([li, np.zeros(Ppad - P, dtype=li.dtype)])
        cip = np.concatenate([ci, np.zeros(Ppad - P, dtype=ci.dtype)])

        # the registered program cache (`_pair_distance_prog`) replaces
        # the old per-instance dict: jit's executable cache keys on the
        # padded width, so iterations still share compiles, but the
        # cache is observable and clearable through dispatch.cache_stats
        prog = _pair_distance_prog()
        out = prog(dl, dc, jnp.asarray(lip), jnp.asarray(cip))
        return np.asarray(out, dtype=np.float64)[:P]


@dataclasses.dataclass
class KNNResult:
    """Flat match table (the reference's transformed DataFrame rows)."""

    landmark_id: np.ndarray  # (M,)
    candidate_id: np.ndarray  # (M,)
    distance: np.ndarray  # (M,)
    rank: np.ndarray  # (M,) 1-based neighbour rank per landmark
    metrics: dict


class SpatialKNN:
    """Reference: `SpatialKNN.transform:202-235` params
    (`SpatialKNNParams.scala`): kNeighbours, maxIterations,
    earlyStopIterations, distanceThreshold, approximate, checkpoint dir."""

    def __init__(
        self,
        index: "IndexSystem | None" = None,
        resolution: "int | None" = None,
        k_neighbours: int = 5,
        max_iterations: int = 10,
        early_stop_iterations: int = 3,
        distance_threshold: "float | None" = None,
        approximate: bool = True,
        checkpoint_dir: "str | None" = None,
        mesh=None,
    ):
        if index is None:
            from ..context import current_context

            index = current_context().index_system
        self.index = index
        self.resolution = resolution
        self.k = int(k_neighbours)
        self.max_iterations = int(max_iterations)
        self.early_stop = int(early_stop_iterations)
        self.distance_threshold = distance_threshold
        self.approximate = approximate
        self.checkpoint_dir = checkpoint_dir
        #: optional jax.sharding.Mesh: shards every iteration's pair
        #: batch over its devices (parallel/dist_knn.py)
        self.mesh = mesh
        self.metrics: dict = {}
        #: GridRingNeighbours per resolution — MUST survive across
        #: transform() calls: its _dist_cache holds the jitted distance
        #: kernels, and rebuilding it each call recompiled them every
        #: time (~27 s per transform over the axon tunnel)
        self._ring_cache: dict = {}

    # ------------------------------------------------------------ helpers
    def _cover_cells(self, col, res: int) -> list[np.ndarray]:
        table = tessellate(col, self.index, res, keep_core_geoms=False)
        return [
            np.unique(table.cell_id[table.geom_id == g])
            for g in range(len(col))
        ]

    def _cell_width(self, res: int) -> float:
        # conservative per-ring growth of the guaranteed-covered radius:
        # one ring adds at least the cell in-diameter ~ sqrt(area)/1.5
        return float(np.sqrt(self.index.cell_area_approx(res)) / 1.5)

    # ----------------------------------------------------------- transform
    def transform(self, landmarks, candidates) -> KNNResult:
        land = to_packed(landmarks)
        cand = to_packed(candidates)
        res = (
            self.index.resolution_arg(self.resolution)
            if self.resolution is not None
            else _default_resolution(self.index, cand)
        )
        L = len(land)

        # right side: chip cells -> candidate rows (tessellate once,
        # `SpatialKNN.transform:205-211` candidates tessellation)
        ctable = tessellate(cand, self.index, res, keep_core_geoms=False)
        order = np.argsort(ctable.cell_id, kind="stable")
        ccells = ctable.cell_id[order]
        crows = ctable.geom_id[order].astype(np.int64)

        # left cover + shared-shift device columns for distance evaluation
        cover = self._cover_cells(land, res)
        from ..functions.geometry import _pair_pack

        dl, dc = _pair_pack(land, cand)
        ring = self._ring_cache.get(res)
        if ring is None or ring.mesh is not self.mesh:
            ring = GridRingNeighbours(self.index, res, mesh=self.mesh)
            self._ring_cache[res] = ring

        ckpt = (
            CheckpointManager(self.checkpoint_dir, overwrite=True)
            if self.checkpoint_dir
            else None
        )

        # state
        dist = np.full((L, self.k), np.inf)
        cid = np.full((L, self.k), -1, dtype=np.int64)
        seen: list[set] = [set() for _ in range(L)]
        stable_rounds = 0
        prev_unfinished = L
        prev_matches = 0
        w = self._cell_width(res)
        iterations = 0
        degraded = False

        def matched(i: int) -> int:
            return int((cid[i] >= 0).sum())

        for it in range(1, self.max_iterations + 1):
            iterations = it
            # guarantee radius after ring r: (r-1) rings fully covered
            need = np.array(
                [
                    matched(i) < self.k
                    or (
                        not self.approximate
                        and (it - 1) * w < dist[i, self.k - 1]
                    )
                    for i in range(L)
                ]
            )
            if not need.any():
                break
            shells = ring.ring_cells(
                [c if need[i] else np.zeros(0, np.int64) for i, c in enumerate(cover)],
                it,
            )
            li_list: list[int] = []
            ci_list: list[int] = []
            for i in range(L):
                cells = shells[i]
                if not cells.size:
                    continue
                lo = np.searchsorted(ccells, cells, side="left")
                hi = np.searchsorted(ccells, cells, side="right")
                rows: set = set()
                for a, b in zip(lo, hi):
                    rows.update(crows[a:b].tolist())
                rows -= seen[i]
                seen[i].update(rows)
                for rr in rows:
                    li_list.append(i)
                    ci_list.append(rr)
            li = np.asarray(li_list, dtype=np.int64)
            ci = np.asarray(ci_list, dtype=np.int64)
            d = _resilient_distances(ring, dl, dc, li, ci, land, cand)
            if isinstance(d, DegradedResult):
                degraded = True
                d = np.asarray(d)
            if self.distance_threshold is not None:
                keep = d <= self.distance_threshold
                li, ci, d = li[keep], ci[keep], d[keep]
            # merge into running top-k per landmark
            for i, c, dd in zip(li, ci, d):
                row_d = dist[i]
                if dd < row_d[-1]:
                    j = int(np.searchsorted(row_d, dd))
                    dist[i] = np.insert(row_d, j, dd)[: self.k]
                    cid[i] = np.insert(cid[i], j, c)[: self.k]
            if ckpt is not None:
                ckpt.append(
                    {"iteration": np.full(li.shape, it), "landmark": li,
                     "candidate": ci, "distance": d}
                )
            # early stopping (`earlyStoppingCheck`): unmatched count and
            # total match count both stable
            unfinished = int(sum(matched(i) < self.k for i in range(L)))
            total_matches = int((cid >= 0).sum())
            if unfinished == prev_unfinished and total_matches == prev_matches:
                stable_rounds += 1
                if stable_rounds >= self.early_stop:
                    break
            else:
                stable_rounds = 0
            prev_unfinished, prev_matches = unfinished, total_matches

        # flatten result
        li_out, ci_out, d_out, rank_out = [], [], [], []
        for i in range(L):
            for r in range(self.k):
                if cid[i, r] >= 0:
                    li_out.append(i)
                    ci_out.append(int(cid[i, r]))
                    d_out.append(float(dist[i, r]))
                    rank_out.append(r + 1)
        self.metrics = {
            "match_count": len(li_out),
            "iterations": iterations,
            "landmarks": L,
            "candidates": len(cand),
            "complete_landmarks": int(
                sum(matched(i) >= self.k for i in range(L))
            ),
            "max_kth_distance": float(
                np.nanmax(np.where(np.isinf(dist), np.nan, dist), initial=0.0)
            ),
            "resolution": res,
            "approximate": self.approximate,
            # True when any iteration's distances came from the f64 host
            # oracle after the device path failed past its retry budget
            "degraded": degraded,
        }
        if ckpt is not None:
            ckpt.write_meta(self.metrics)
        return KNNResult(
            landmark_id=np.asarray(li_out, dtype=np.int64),
            candidate_id=np.asarray(ci_out, dtype=np.int64),
            distance=np.asarray(d_out),
            rank=np.asarray(rank_out, dtype=np.int64),
            metrics=dict(self.metrics),
        )

    def get_metrics(self) -> dict:
        """Reference: `SpatialKNN.getMetrics:280-318` (MLflow loggables)."""
        return dict(self.metrics)


def _resilient_distances(ring, dl, dc, li, ci, land, cand):
    """Device pair distances with transient-failure retry; past the
    budget the batch degrades to the exact f64 oracle `st_distance`
    (flagged :class:`DegradedResult` — the model records it in metrics
    rather than crashing mid-iteration or dropping pairs)."""
    if not li.size:
        return np.zeros(0)

    def device_eval():
        # the "knn.pair_distances" fault plan trips inside guarded_call
        return ring.pair_distances(dl, dc, li, ci)

    def oracle_eval():
        from ..functions.geometry import st_distance

        return np.asarray(
            st_distance(land.take(li), cand.take(ci), backend="oracle"),
            dtype=np.float64,
        )

    return _dispatch.guarded_call(
        "knn.pair_distances", device_eval, fallback=oracle_eval
    )


def _default_resolution(index: IndexSystem, col) -> int:
    from ..sql.analyzer import MosaicAnalyzer

    return MosaicAnalyzer(index).get_optimal_resolution(col)
