"""Arrow / pandas interop: the host-engine exchange boundary.

Reference analog: the reference's language boundary is Spark rows — WKB
geometry columns plus attributes crossing the JVM↔Python py4j seam
(`python/mosaic/core/mosaic_context.py:58-60`), with Arrow as Spark's
columnar interchange for `mapInArrow` UDFs (SURVEY §7.6). Here the same
boundary is explicit: :class:`~.readers.vector.VectorTable` ⇄
``pyarrow.Table`` (geometry serialized as WKB or WKT) and a
``map_in_arrow`` adapter that wraps any VectorTable→VectorTable function
as a RecordBatch-iterator transform — exactly the contract
``DataFrame.mapInArrow`` expects, so the same callable plugs into a real
Spark session without this package importing Spark.

pyarrow/pandas are optional: importing this module without them raises
``ImportError`` at call time, not package-import time.
"""

from __future__ import annotations

import numpy as np

from .core.geometry import wkb as _wkb
from .core.geometry import wkt as _wkt
from .core.types import PackedGeometry


def _pa():
    import pyarrow

    return pyarrow


def _as_vector_table(obj) -> "object":
    from .readers.vector import VectorTable

    if isinstance(obj, VectorTable):
        return obj
    if isinstance(obj, PackedGeometry):
        return VectorTable(geometry=obj, columns={})
    raise TypeError(f"expected VectorTable or PackedGeometry, got {type(obj)}")


def to_arrow(obj, geometry_format: str = "wkb", geometry_col: str = "geometry"):
    """VectorTable / PackedGeometry -> ``pyarrow.Table``.

    The geometry column serializes to WKB (binary) or WKT (string);
    attribute columns pass through as Arrow arrays.
    """
    pa = _pa()
    vt = _as_vector_table(obj)
    if geometry_format == "wkb":
        geom = pa.array(_wkb.to_wkb(vt.geometry), type=pa.binary())
    elif geometry_format == "wkt":
        geom = pa.array(_wkt.to_wkt(vt.geometry), type=pa.string())
    else:
        raise ValueError(f"geometry_format must be wkb|wkt, got {geometry_format!r}")
    names = [geometry_col]
    arrays = [geom]
    for k, v in vt.columns.items():
        names.append(k)
        arrays.append(pa.array(v.tolist() if v.dtype == object else v))
    return pa.Table.from_arrays(arrays, names=names)


def from_arrow(table, geometry_col: "str | None" = None, srid: int = 4326):
    """``pyarrow.Table`` (or RecordBatch) -> VectorTable.

    ``geometry_col`` defaults to the first binary (WKB) or
    geometry-looking string (WKT) column.
    """
    pa = _pa()
    from .readers.vector import VectorTable

    if isinstance(table, pa.RecordBatch):
        table = pa.Table.from_batches([table])
    col = geometry_col
    if col is None:
        for name in table.column_names:
            t = table.column(name).type
            if pa.types.is_binary(t) or pa.types.is_large_binary(t):
                col = name
                break
            if (
                pa.types.is_string(t) or pa.types.is_large_string(t)
            ) and name.lower() in ("geometry", "geom", "wkt"):
                col = name
                break
        if col is None:
            raise ValueError(
                f"no geometry column found in {table.column_names}"
            )
    vals = table.column(col).to_pylist()
    if any(v is None for v in vals):
        raise ValueError(
            f"geometry column {col!r} contains nulls; filter or fill them "
            "before the interop boundary (e.g. WKB of POLYGON EMPTY)"
        )
    t = table.column(col).type
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        geom = _wkb.from_wkb([bytes(v) for v in vals], srid=srid)
    else:
        geom = _wkt.from_wkt([str(v) for v in vals], srid=srid)
    columns = {
        name: np.asarray(table.column(name).to_pylist())
        for name in table.column_names
        if name != col
    }
    return VectorTable(geometry=geom, columns=columns)


def map_in_arrow(
    fn, geometry_col: str = "geometry", geometry_format: str = "wkb",
    srid: int = 4326,
):
    """Wrap ``fn(VectorTable) -> VectorTable`` as a RecordBatch-iterator
    transform — directly usable as ``df.mapInArrow(map_in_arrow(fn),
    schema)`` on a Spark DataFrame, and testable standalone on any
    iterator of batches."""

    def _transform(batches):
        for batch in batches:
            vt = from_arrow(batch, geometry_col=geometry_col, srid=srid)
            out = _as_vector_table(fn(vt))
            yield from to_arrow(
                out, geometry_format=geometry_format,
                geometry_col=geometry_col,
            ).to_batches()

    return _transform


def to_pandas(obj, geometry_format: str = "wkt", geometry_col: str = "geometry"):
    """VectorTable / PackedGeometry -> pandas DataFrame (WKT default —
    readable; pass 'wkb' for lossless binary)."""
    return to_arrow(
        obj, geometry_format=geometry_format, geometry_col=geometry_col
    ).to_pandas()


def from_pandas(df, geometry_col: "str | None" = None, srid: int = 4326):
    """pandas DataFrame -> VectorTable (via Arrow)."""
    pa = _pa()
    return from_arrow(
        pa.Table.from_pandas(df, preserve_index=False),
        geometry_col=geometry_col,
        srid=srid,
    )
