"""Admission control: bounded queue, per-request deadlines, poison
diversion — the engine's contract that load NEVER turns into unbounded
memory, unbounded latency, or a corrupted shared batch.

Three decisions happen at the submit boundary, in order:

1. **Fault hooks** — ``serve.admit`` is a fault-injection site:
   `runtime/faults.maybe_fail` can raise a synthetic transient here and
   `maybe_corrupt` can poison the request's rows (the adversarial-input
   model the quarantine tests drive).
2. **Quarantine** — rows that are non-finite or outside the declared
   domain bounds are *parked* (`runtime/quarantine.py`): replaced by a
   coordinate proven to hit no indexed cell, so they answer -1 without
   special-casing the kernel and cannot perturb batchmates. The request
   still gets a result; the report rides on it.
3. **Backpressure** — the queue is a bounded deque. At capacity the
   request is REFUSED with a typed
   :class:`~mosaic_tpu.runtime.errors.Overloaded` (``reason=
   "queue_full"``) instead of queueing: an overloaded engine must shed
   at the door, where the caller can retry elsewhere, not time out
   silently after occupying memory for seconds.

Deadlines are stamped here (monotonic clock) and enforced by the
batcher at both batch formation and scatter-back — a request that
cannot make its deadline is shed with ``reason="deadline"``, and ONLY
that request: batchmates keep their results.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..obs import metrics as _metrics, trace as _trace
from ..runtime import (
    faults as _faults,
    quarantine as _quarantine,
    telemetry as _telemetry,
)
from ..runtime.errors import Overloaded


@dataclasses.dataclass
class Request:
    """One admitted request queued for dispatch."""

    points: np.ndarray  # (n, 2) f64, poison rows already parked
    future: Future
    n: int
    t_submit: float  # monotonic
    deadline: float | None  # monotonic instant, None = no deadline
    #: request family sharing this queue: "pip" (point-in-polygon rows,
    #: (n,) int32 answers) or "knn" ((n, 2k) f64 wire rows) — one
    #: admission/deadline/shed budget covers both, the engine's dispatch
    #: splits a mixed batch by kind
    kind: str = "pip"
    k: int = 0  # neighbour count, kind == "knn" only
    parked: int = 0  # rows diverted to quarantine
    quarantine: "_quarantine.QuarantineReport | None" = None
    #: caller-thread context the dispatch worker adopts (both are
    #: thread-local in their modules)
    sinks: list = dataclasses.field(default_factory=list)
    plans: list = dataclasses.field(default_factory=list)
    #: the request's trace — ``span`` is the ``serve.request`` root
    #: (begun at admission, ended at scatter-back/shed), ``ctx`` its
    #: :class:`~mosaic_tpu.obs.trace.SpanContext` the batcher thread
    #: adopts so dispatch-side spans join the submitter's trace
    span: "_trace.Span | None" = None
    ctx: "_trace.SpanContext | None" = None

    def remaining(self, now: float | None = None) -> float:
        if self.deadline is None:
            return float("inf")
        return self.deadline - (time.monotonic() if now is None else now)


class AdmissionController:
    """Bounded request queue with deadline stamping and poison parking.

    ``capacity`` bounds QUEUED requests (in-flight batches are bounded
    separately by the batcher's window); ``default_deadline_s`` applies
    when a submit passes none; ``bounds`` is the (xmin, ymin, xmax,
    ymax) valid domain for quarantine scrubbing (None: non-finite rows
    only). ``park_point`` short-circuits the park search; otherwise the
    first poisoned admit derives one from ``find_park`` (the engine
    wires the index-aware search in).
    """

    def __init__(
        self,
        *,
        capacity: int = 256,
        default_deadline_s: float | None = None,
        bounds: tuple | None = None,
        park_point: np.ndarray | None = None,
        find_park=None,
    ):
        self.capacity = int(capacity)
        self.default_deadline_s = default_deadline_s
        self.bounds = bounds
        self._park = (
            None
            if park_point is None
            else np.asarray(park_point, dtype=np.float64)
        )
        self._find_park = find_park
        self._queue: list[Request] = []
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self.metrics = {
            "submitted": 0,
            "admitted": 0,
            "shed_queue_full": 0,
            "quarantined_rows": 0,
            "poisoned_requests": 0,
        }

    # ------------------------------------------------------ submit side

    def admit(
        self,
        points: np.ndarray,
        *,
        deadline_s: float | None = None,
        kind: str = "pip",
        k: int = 0,
    ) -> Request:
        """Scrub, stamp, and enqueue one request; returns it with its
        future. Raises :class:`Overloaded` when the queue is full.
        ``kind``/``k`` route the request family (KNN requests co-batch
        with PIP traffic under this same queue, deadline budget, and
        shed taxonomy)."""
        _faults.maybe_fail("serve.admit")
        raw = np.asarray(
            _faults.maybe_corrupt("serve.admit", points), dtype=np.float64
        )
        if raw.ndim != 2 or raw.shape[1] != 2:
            raise ValueError(f"expected (n, 2) points, got {raw.shape}")
        self.metrics["submitted"] += 1

        # the request's trace root: begun here on the submit thread,
        # ended at scatter-back (or shed) on the batcher thread — the
        # request's whole lifecycle is ONE span, its stages children
        root = _trace.start_span(
            "serve.request", detached=True, rows=int(raw.shape[0]),
            kind=kind,
        )
        try:
            with _trace.span(
                "serve.admit", parent=root.context, rows=int(raw.shape[0]),
            ):
                return self._admit_scrubbed(raw, deadline_s, root, kind, k)
        except BaseException as e:  # noqa: BLE001 — span closed, re-raised
            root.end(error=type(e).__name__)
            raise

    def _admit_scrubbed(
        self, raw: np.ndarray, deadline_s: float | None, root,
        kind: str = "pip", k: int = 0,
    ) -> Request:
        report = None
        parked = 0
        bad, reasons = _quarantine.scrub_points(raw, bounds=self.bounds)
        if bad.any():
            report = _quarantine.QuarantineReport()
            report.merge_batch(0, raw, bad, reasons)
            clean = raw.copy()
            clean[bad] = self._park_point(raw)
            parked = int(bad.sum())
            self.metrics["quarantined_rows"] += parked
            self.metrics["poisoned_requests"] += 1
            _telemetry.record(
                "serve_quarantine", rows=parked, of=int(raw.shape[0]),
                reasons={k: v for k, v in reasons.items() if v},
            )
            raw = clean

        now = time.monotonic()
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        req = Request(
            points=raw,
            future=Future(),
            n=int(raw.shape[0]),
            t_submit=now,
            deadline=None if deadline_s is None else now + float(deadline_s),
            kind=kind,
            k=int(k),
            parked=parked,
            quarantine=report,
            sinks=_telemetry.current_sinks(),
            plans=_faults.current_plans(),
            span=root,
            ctx=root.context,
        )
        with self._not_empty:
            depth = len(self._queue)
            if depth >= self.capacity:
                self.metrics["shed_queue_full"] += 1
                _telemetry.record(
                    "serve_shed", reason="queue_full", queue_depth=depth,
                    capacity=self.capacity,
                )
                raise Overloaded(
                    f"serve queue full ({depth}/{self.capacity} requests) "
                    f"— shedding at admission",
                    reason="queue_full",
                    queue_depth=depth,
                    capacity=self.capacity,
                )
            self._queue.append(req)
            self.metrics["admitted"] += 1
            _metrics.gauge("serve.queue_depth").set(len(self._queue))
            self._not_empty.notify()
        return req

    def _park_point(self, raw: np.ndarray) -> np.ndarray:
        if self._park is None:
            if self._find_park is None:
                raise ValueError(
                    "admission needs a park_point or find_park to divert "
                    "poisoned rows"
                )
            self._park = np.asarray(
                self._find_park(raw), dtype=np.float64
            )
        return self._park

    # ---------------------------------------------------- consumer side

    def take(self, timeout: float | None) -> Request | None:
        """Pop the oldest request, waiting up to ``timeout``; None on
        timeout (the batcher's idle tick)."""
        with self._not_empty:
            if not self._queue:
                self._not_empty.wait(timeout)
            if not self._queue:
                return None
            req = self._queue.pop(0)
            _metrics.gauge("serve.queue_depth").set(len(self._queue))
            return req

    def put_back(self, req: Request) -> None:
        """Return a request to the queue HEAD (the batcher overshot its
        row budget — this request leads the next batch)."""
        with self._not_empty:
            self._queue.insert(0, req)
            self._not_empty.notify()

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def drain(self) -> list[Request]:
        """Remove and return every queued request (shutdown path)."""
        with self._lock:
            out, self._queue = self._queue, []
            return out
