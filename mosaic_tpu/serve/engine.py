"""The request lifecycle: submit -> future -> padded, shape-bucketed
device dispatch with the full runtime resilience stack wired in.

One :class:`ServeEngine` owns a resident
:class:`~mosaic_tpu.sql.join.ChipIndex` and turns concurrent
point-in-polygon requests into micro-batched dispatches on the unified
dispatch core (`mosaic_tpu/dispatch` — the same executable cache
`pip_join` and the stream/raster frontends use, so a server and a batch
job in one process share compiles). The pipeline per batch:

    admit (quarantine + backpressure, `serve/admission.py`)
      -> coalesce (max-batch / max-wait window, `serve/batcher.py`)
      -> pad to bucket + execute through `dispatch.DispatchCore` under
         the ``serve.dispatch`` watchdog/fault site, transient retry,
         host-oracle degradation (`dispatch.guarded_call` owns the
         composition; the engine only names the site and the deadline)
      -> scatter back per request, shedding only deadline-expired ones

- `runtime/faults.py` sites ``serve.admit`` / ``serve.batch`` /
  ``serve.dispatch`` make every failure mode injectable from tests.
- every stage emits ``serve_stage`` `telemetry.timed` events; per-request
  latency lands in ``serve_request`` events (`telemetry.summarize` turns
  them into the bench's p50/p99).

Compile discipline is the core's: caps fixed at the full (per-shard)
bucket, one signature per `(bucket, index, mesh)`, :meth:`warmup`
precompiling every rung. After warmup the signature set is frozen — a
dispatch introducing a new signature emits a ``serve_compile`` event and
counts in ``metrics()["cold_compiles"]`` (the serve tests pin this at
zero). Pass ``mesh=`` (or set ``MOSAIC_MESH``) to place every dispatch
data-parallel over a device mesh with the index replicated —
bit-identical results at any device count.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future

import numpy as np

from ..dispatch import (
    BucketLadder,
    DispatchCore,
    backend_compiles,
    resolve_program_store,
)
from ..obs import trace as _trace
from ..runtime import telemetry as _telemetry
from ..runtime.errors import DegradedResult
from ..tune.resolve import resolve_knobs
from .admission import AdmissionController
from .batcher import MicroBatcher

import jax.numpy as jnp


class _MixedOut:
    """Result view for a mixed-kind batch: per-request answer segments
    keyed by their ``(start, stop)`` row interval in the concatenated
    batch. Requests are never split across batches, so the batcher's
    scatter-back slices ``out[off : off + req.n]`` land exactly on these
    keys — each request reads its own wire shape ((n,) int32 for PIP,
    (n, 2k) f64 for KNN) with no common dtype forced on the batch.

    ``degraded`` is batch-level and conservative: if ANY segment fell
    back to the host oracle, every request in the batch is flagged
    (values are exact either way — degradation changes provenance, not
    answers)."""

    def __init__(self, segments, *, degraded=False, reason=None, attempts=0):
        self._segments = segments
        self.degraded = bool(degraded)
        self.reason = reason
        self.attempts = attempts

    def __getitem__(self, sl: slice):
        seg = self._segments.get((sl.start, sl.stop))
        if seg is None:
            raise KeyError(
                f"no batch segment at rows [{sl.start}, {sl.stop})"
            )
        return seg


class ServeEngine:
    """Online serving engine over a resident chip index.

    >>> engine = ServeEngine(index, h3, 9, bounds=bbox)
    >>> engine.warmup()
    >>> fut = engine.submit(points)          # (n, 2) -> Future
    >>> rows = fut.result(timeout=1.0)       # (n,) int32, -1 = no match
    """

    def __init__(
        self,
        index,
        index_system,
        resolution: int,
        *,
        ladder: BucketLadder | None = None,
        max_batch_rows: int | None = None,
        max_wait_s: float = 0.002,
        queue_capacity: int = 256,
        default_deadline_s: float | None = 1.0,
        bounds: tuple | None = None,
        park_point: np.ndarray | None = None,
        writeback: str | None = None,
        lookup: str | None = None,
        cell_dtype=None,
        watchdog_grace_s: float = 0.5,
        probe: str | None = None,
        mesh=None,
        profile=None,
        program_store=None,
        knn=None,
        knn_lane: str | None = None,
    ):
        self.index = index
        self.index_system = index_system
        self.resolution = index_system.resolution_arg(resolution)
        # profile-consumed knobs resolve HERE, at the host entry point,
        # with the one documented precedence: explicit arg > env knob >
        # TuningProfile > built-in default (mosaic_tpu/tune/resolve.py)
        knobs = resolve_knobs(
            "serve_engine", profile,
            explicit={
                "probe": probe, "writeback": writeback, "lookup": lookup,
                "bucket_min": None, "bucket_max": None,
                "knn_lane": knn_lane,
            },
            defaults={
                "probe": "scatter", "writeback": "scatter", "lookup": None,
                "bucket_min": None, "bucket_max": None,
                "knn_lane": None,
            },
        )
        probe, writeback, lookup = (
            knobs["probe"], knobs["writeback"], knobs["lookup"]
        )
        if ladder is None and (knobs["bucket_min"] or knobs["bucket_max"]):
            ladder = BucketLadder(
                min_bucket=int(knobs["bucket_min"] or 64),
                max_bucket=int(knobs["bucket_max"] or 65536),
            )
        self.ladder = ladder or BucketLadder()
        self.writeback = writeback
        self.cell_dtype = cell_dtype
        self.watchdog_grace_s = float(watchdog_grace_s)
        # a hot_swap rebinds (ladder, core, index) as one unit; the lock
        # only guards the rebind and the dispatch-side snapshot of the
        # pair, never the dispatch itself
        self._swap_lock = threading.Lock()
        # the core owns probe/lookup resolution (force-lane env folds
        # once, so the compile-cache signature stays honest), caps,
        # signature accounting, the guarded execute path, and (when a
        # store is bound — explicit arg or MOSAIC_PROGRAM_STORE) the
        # AOT program persistence that makes warmup a load, not a
        # compile storm
        self.program_store = resolve_program_store(program_store)
        self.core = DispatchCore(
            index, index_system, resolution, ladder=self.ladder,
            writeback=writeback, lookup=lookup, probe=probe,
            cell_dtype=cell_dtype, mesh=mesh,
            on_cold_compile=self._on_cold_compile,
            program_store=self.program_store,
        )
        self.probe = self.core.probe
        self.lookup = self.core.lookup
        self.mesh = self.core.mesh
        # optional KNN frontend riding the same queue/batcher: a
        # KNNIndex builds a fresh frontend sharing the engine's mesh,
        # program store, and cold-compile tripwire; an existing
        # KNNFrontend is adopted as-is (tests pre-warm one)
        self.knn_lane = knobs["knn_lane"]
        self.knn = self._build_knn(knn, self.knn_lane)

        self.admission = AdmissionController(
            capacity=queue_capacity,
            default_deadline_s=default_deadline_s,
            bounds=bounds,
            park_point=park_point,
            find_park=self._derive_park,
        )
        self.batcher = MicroBatcher(
            self.admission,
            self._dispatch,
            max_batch_rows=(
                min(self.ladder.max_bucket, 16384)
                if max_batch_rows is None
                else int(max_batch_rows)
            ),
            max_wait_s=max_wait_s,
        )
        if self.batcher.max_batch_rows > self.ladder.max_bucket:
            raise ValueError(
                f"max_batch_rows {self.batcher.max_batch_rows} exceeds the "
                f"top bucket {self.ladder.max_bucket}"
            )
        self._closed = False
        self.batcher.start()

    # ----------------------------------------------------------- public

    def submit(self, points, *, deadline_s: float | None = None):
        """Enqueue one request; returns its ``concurrent.futures.Future``
        resolving to the (n,) int32 matches (:class:`Overloaded` when
        shed). Raises :class:`Overloaded` at admission when the queue is
        full."""
        if self._closed:
            raise RuntimeError("engine is closed")
        pts = np.asarray(points, dtype=np.float64)
        if pts.ndim != 2 or pts.shape[1] != 2:
            raise ValueError(f"expected (n, 2) points, got {pts.shape}")
        if pts.shape[0] > self.ladder.max_bucket:
            raise ValueError(
                f"request of {pts.shape[0]} rows exceeds the top bucket "
                f"{self.ladder.max_bucket} — split it upstream"
            )
        return self.admission.admit(pts, deadline_s=deadline_s).future

    def join(self, points, *, deadline_s: float | None = None, timeout=None):
        """Synchronous convenience wrapper: submit and wait."""
        return self.submit(points, deadline_s=deadline_s).result(timeout)

    def submit_knn(self, points, k: int, *, deadline_s: float | None = None):
        """Enqueue one k-nearest-neighbour request; returns a Future
        resolving to a :class:`~mosaic_tpu.knn.frontend.KNNAnswer` with
        (n, k) ``ids``/``distance`` arrays (:class:`Overloaded` when
        shed). KNN requests ride the SAME admission queue, deadline
        budget, micro-batch window, and shed taxonomy as PIP traffic —
        the dispatch splits a mixed batch by ``Request.kind`` and each
        family keeps its exact answers. Quarantined (non-finite /
        out-of-bounds) rows answer ``ids=-1, distance=inf``."""
        if self._closed:
            raise RuntimeError("engine is closed")
        if self.knn is None:
            raise RuntimeError(
                "engine has no KNN frontend — pass knn= at construction "
                "or hot_swap(knn=...)"
            )
        k = int(k)
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        pts = np.asarray(points, dtype=np.float64)
        if pts.ndim != 2 or pts.shape[1] != 2:
            raise ValueError(f"expected (n, 2) points, got {pts.shape}")
        if pts.shape[0] > self.ladder.max_bucket:
            raise ValueError(
                f"request of {pts.shape[0]} rows exceeds the top bucket "
                f"{self.ladder.max_bucket} — split it upstream"
            )
        req = self.admission.admit(pts, deadline_s=deadline_s, kind="knn", k=k)
        return _decode_knn_future(req, k)

    def join_knn(
        self, points, k: int, *, deadline_s: float | None = None, timeout=None
    ):
        """Synchronous convenience wrapper: submit_knn and wait."""
        return self.submit_knn(points, k, deadline_s=deadline_s).result(
            timeout
        )

    def warmup(self) -> dict:
        """Precompile every ladder bucket against the resident index.

        Runs the exact dispatch path (cell assignment + jitted probe) on
        an inert full-bucket batch per rung, so the first real request
        at any admitted shape replays a cached executable. Returns
        ``{"buckets": ..., "seconds": ..., "signatures": ...}``; after
        this, any dispatch that still introduces a new compile signature
        is counted in ``metrics()["cold_compiles"]`` (and emits a
        ``serve_compile`` event) — the bounded-compile contract's
        tripwire."""
        t0 = backend_compiles()
        total = 0.0
        with _telemetry.capture() as events, _trace.span(
            "serve.warmup", buckets=len(self.ladder.buckets)
        ):
            for b in self.ladder.buckets:
                pts = np.zeros((b, 2), dtype=np.float64)
                with _telemetry.timed(
                    "serve_stage", stage="warmup", bucket=b
                ):
                    self.core.execute_padded(pts)
            if self.knn is not None:
                knn_stats = self.knn.warmup()
        total = sum(
            e["seconds"]
            for e in events
            if e.get("stage") == "warmup" and "seconds" in e
        )
        self.core.freeze()
        t1 = backend_compiles()
        out = {
            "buckets": len(self.ladder.buckets),
            "seconds": round(total, 4),
            "signatures": len(self.core.signatures),
        }
        if t0 is not None and t1 is not None:
            out["backend_compiles"] = t1 - t0
        if self.program_store is not None:
            out["aot"] = dict(self.core.aot_stats)
        if self.knn is not None:
            out["knn"] = knn_stats
        _telemetry.record("serve_warmup", **out)
        return out

    def hot_swap(
        self,
        index=None,
        *,
        profile=None,
        resolution: int | None = None,
        probe: str | None = None,
        writeback: str | None = None,
        lookup: str | None = None,
        ladder: BucketLadder | None = None,
        knn=None,
        knn_lane: str | None = None,
    ) -> dict:
        """Swap in a new index and/or `TuningProfile` without dropping
        the engine: a NEW dispatch core is built off to the side, its
        ladder rungs precompiled and its signature set frozen
        (`DispatchCore.warmup`), and only then is ``(ladder, core,
        index)`` rebound as one unit — requests in flight finish on the
        old core, requests after the swap replay cached executables.
        Zero cold compiles after the swap is enforced by the existing
        ``freeze()`` tripwire: any post-swap dispatch that still compiles
        counts in ``metrics()["cold_compiles"]``.

        Knob precedence matches the constructor (explicit > env > profile
        > default), with the engine's CURRENT settings as the defaults —
        a profile-less ``hot_swap(index)`` swaps the index and keeps the
        tuning. Returns the new core's warmup stats."""
        index = self.index if index is None else index
        knobs = resolve_knobs(
            "serve_engine.hot_swap", profile,
            explicit={
                "resolution": resolution,
                "probe": probe, "writeback": writeback, "lookup": lookup,
                "bucket_min": None, "bucket_max": None,
            },
            defaults={
                "resolution": self.resolution,
                "probe": self.core.probe, "writeback": self.writeback,
                "lookup": self.core.lookup,
                "bucket_min": None, "bucket_max": None,
            },
        )
        new_resolution = self.index_system.resolution_arg(knobs["resolution"])
        if ladder is None:
            if knobs["bucket_min"] or knobs["bucket_max"]:
                ladder = BucketLadder(
                    min_bucket=int(knobs["bucket_min"] or 64),
                    max_bucket=int(knobs["bucket_max"] or 65536),
                )
            else:
                ladder = self.ladder
        with _trace.span(
            "serve.hot_swap", buckets=len(ladder.buckets),
            profiled=profile is not None,
        ), _telemetry.timed("serve_stage", stage="hot_swap"):
            core = DispatchCore(
                index, self.index_system, new_resolution, ladder=ladder,
                writeback=knobs["writeback"], lookup=knobs["lookup"],
                probe=knobs["probe"], cell_dtype=self.cell_dtype,
                mesh=self.mesh, on_cold_compile=self._on_cold_compile,
                program_store=self.program_store,
            )
            stats = core.warmup()  # precompiles every rung, then freezes
            # a new KNN index swaps the same way: frontend built and
            # warmed off to the side, rebound atomically with the core
            # (in-flight mixed batches already hold their snapshot)
            new_knn = self.knn
            if knn is not None:
                new_knn = self._build_knn(
                    knn, knn_lane or self.knn_lane
                )
                stats["knn"] = new_knn.warmup()
            with self._swap_lock:
                self.index = index
                self.resolution = new_resolution
                self.ladder = ladder
                self.core = core
                self.knn = new_knn
                self.writeback = knobs["writeback"]
                self.probe = core.probe
                self.lookup = core.lookup
                # keep the coalescing window inside the new ladder's span
                self.batcher.max_batch_rows = min(
                    self.batcher.max_batch_rows, ladder.max_bucket
                )
        _telemetry.record("serve_swap", **stats)
        return stats

    def metrics(self) -> dict:
        a, b = self.admission.metrics, self.batcher.metrics
        out = dict(a)
        out.update(b)
        out["shed"] = a["shed_queue_full"] + b["shed_deadline"]
        out["quarantined"] = a["quarantined_rows"]
        out["queue_depth"] = self.admission.depth()
        out["compile_signatures"] = len(self.core.signatures)
        out["cold_compiles"] = self.core.cold_compiles
        if self.knn is not None:
            out.update(self.knn.metrics())
            out["cold_compiles"] += self.knn.cold_compiles
        out["occupancy_mean"] = round(
            b["occupancy_sum"] / b["batches"], 4
        ) if b["batches"] else 0.0
        return out

    def close(self, timeout: float = 5.0) -> None:
        """Stop the batcher; queued requests are shed
        (``reason="shutdown"``)."""
        if not self._closed:
            self._closed = True
            self.batcher.stop(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # --------------------------------------------------------- dispatch

    def _build_knn(self, knn, lane):
        """Wrap a KNNIndex in a frontend sharing the engine's mesh,
        program store, and cold-compile tripwire; pass a ready-made
        frontend through unchanged; None stays None."""
        if knn is None:
            return None
        from ..knn.frontend import KNNFrontend

        if isinstance(knn, KNNFrontend):
            return knn
        return KNNFrontend(
            knn,
            lane=lane or "ring",
            mesh=self.mesh,
            program_store=self.program_store,
            on_cold_compile=self._on_cold_compile,
        )

    def _dispatch(self, points: np.ndarray, deadline_hint=None, reqs=None):
        """Batcher callback: pad, dispatch with resilience, unpad.
        Returns ``(results, occupancy)`` — a plain (n,) array for a
        uniform PIP batch, a :class:`_MixedOut` segment view when the
        batch carries KNN requests."""
        # snapshot the swap unit so a concurrent hot_swap can never pad
        # with one ladder and execute on the other core
        with self._swap_lock:
            ladder, core, knn = self.ladder, self.core, self.knn
        if reqs is not None and any(r.kind == "knn" for r in reqs):
            return self._dispatch_mixed(
                ladder, core, knn, points, deadline_hint, reqs
            )
        padded, n = ladder.pad(points)
        bucket = padded.shape[0]
        with _trace.span(
            "serve.dispatch", bucket=bucket, rows=n,
        ), _telemetry.timed(
            "serve_stage", stage="dispatch", bucket=bucket, rows=n,
        ):
            out = self._dispatch_resilient(core, padded, deadline_hint)
        occupancy = n / bucket
        return out[:n], occupancy

    def _dispatch_mixed(self, ladder, core, knn, points, deadline_hint, reqs):
        """Split a mixed batch by request kind: ALL PIP rows go through
        one padded core dispatch (their co-batching benefit is
        unchanged), KNN rows group by k into one frontend dispatch each.
        Answers come back as a :class:`_MixedOut` keyed by each request's
        row interval; occupancy is the rows-weighted mean over the
        device dispatches actually issued."""
        bounds, off = [], 0
        for r in reqs:
            bounds.append((r, off, off + r.n))
            off += r.n
        segs = {}
        degraded, reason, attempts = False, None, 0
        occ_rows, rows_total = 0.0, 0

        pip = [(r, a, b) for (r, a, b) in bounds if r.kind != "knn"]
        if pip:
            pts = np.concatenate([points[a:b] for (_r, a, b) in pip])
            padded, n = ladder.pad(pts)
            bucket = padded.shape[0]
            with _trace.span(
                "serve.dispatch", bucket=bucket, rows=n,
            ), _telemetry.timed(
                "serve_stage", stage="dispatch", bucket=bucket, rows=n,
            ):
                out = self._dispatch_resilient(core, padded, deadline_hint)
            if isinstance(out, DegradedResult):
                degraded, reason, attempts = True, out.reason, out.attempts
            vals = np.asarray(out[:n])
            o = 0
            for (r, a, b) in pip:
                segs[(a, b)] = vals[o : o + r.n]
                o += r.n
            occ_rows += (n / bucket) * n
            rows_total += n

        knn_reqs = [(r, a, b) for (r, a, b) in bounds if r.kind == "knn"]
        if knn_reqs:
            if knn is None:
                raise RuntimeError(
                    "KNN request admitted but the engine has no KNN frontend"
                )
            default_s = (
                None
                if deadline_hint is None
                else max(float(deadline_hint), 0.05) + self.watchdog_grace_s
            )
            by_k: dict[int, list] = {}
            for item in knn_reqs:
                by_k.setdefault(item[0].k, []).append(item)
            for k, group in sorted(by_k.items()):
                pts = np.concatenate([points[a:b] for (_r, a, b) in group])
                n = int(pts.shape[0])
                with _trace.span(
                    "serve.dispatch", rows=n, kind="knn", k=k,
                ), _telemetry.timed(
                    "serve_stage", stage="dispatch", rows=n,
                    kind="knn", k=k,
                ):
                    out, occ = knn.dispatch(pts, k, default_s=default_s)
                if isinstance(out, DegradedResult):
                    degraded, reason, attempts = (
                        True, out.reason, out.attempts
                    )
                vals = np.asarray(out)
                o = 0
                for (r, a, b) in group:
                    segs[(a, b)] = vals[o : o + r.n]
                    o += r.n
                occ_rows += float(occ) * n
                rows_total += n

        occupancy = occ_rows / rows_total if rows_total else 1.0
        view = _MixedOut(
            segs, degraded=degraded, reason=reason, attempts=attempts
        )
        return view, occupancy

    def _on_cold_compile(self, bucket: int, signatures: int) -> None:
        """Core callback: a post-warmup dispatch introduced a new
        compile signature — the bounded-compile contract's tripwire."""
        _telemetry.record(
            "serve_compile", bucket=bucket, signatures=signatures,
        )

    def _dispatch_resilient(self, core, padded, deadline_hint) -> np.ndarray:
        """The core's guarded execute under the batch's deadline: the
        ``serve.dispatch`` watchdog site, transient retry, and exact-f64
        host-oracle degradation — all composed by the dispatch core."""
        default_s = (
            None
            if deadline_hint is None
            else max(float(deadline_hint), 0.05) + self.watchdog_grace_s
        )
        return core.execute_resilient(
            "serve.dispatch", padded, default_s=default_s
        )

    # ------------------------------------------------------- quarantine

    def _derive_park(self, raw: np.ndarray) -> np.ndarray:
        """Index-aware park point for poisoned rows: walk outward from
        the request's own finite bounding box until a cell NOT in the
        resident index answers (`runtime/quarantine.find_park_point`)."""
        from ..runtime import quarantine as _quarantine

        finite = raw[np.isfinite(raw).all(axis=1)]
        if finite.size:
            bounds = (
                float(finite[:, 0].min()), float(finite[:, 1].min()),
                float(finite[:, 0].max()), float(finite[:, 1].max()),
            )
        else:
            bounds = (0.0, 0.0, 1.0, 1.0)
        if self.admission.bounds is not None:
            bounds = self.admission.bounds

        def assign(pts):
            dev = jnp.asarray(np.asarray(pts, dtype=np.float64))
            if self.cell_dtype is not None:
                dev = dev.astype(self.cell_dtype)
            return self.index_system.point_to_cell(dev, self.resolution)

        return _quarantine.find_park_point(
            assign, np.asarray(self.index.cells), bounds
        )


def _decode_knn_future(req, k: int) -> Future:
    """Chain the request's raw wire future ((n, 2k) f64 rows) into one
    resolving to a batched :class:`~mosaic_tpu.knn.frontend.KNNAnswer`.
    Quarantined rows were answered at the park point — mask them back to
    the sentinel (``ids=-1, distance=inf``) so a poisoned coordinate can
    never surface a real neighbour. Exceptions (Overloaded sheds,
    injected faults) pass through untranslated."""
    from ..knn.frontend import KNNAnswer, decode_knn

    fut: Future = Future()

    def _done(raw: Future) -> None:
        if raw.cancelled():
            fut.cancel()
            return
        exc = raw.exception()
        if exc is not None:
            fut.set_exception(exc)
            return
        try:
            out = raw.result()
            degraded = isinstance(out, DegradedResult) or bool(
                getattr(out, "degraded", False)
            )
            reason = getattr(out, "reason", None) if degraded else None
            ids, dist = decode_knn(np.asarray(out), k)
            if req.quarantine is not None:
                dist = dist.copy()
                bad = [r for (_b, r) in req.quarantine.rows]
                ids[bad] = -1
                dist[bad] = np.inf
            fut.set_result(
                KNNAnswer(
                    ids=ids, distance=dist,
                    degraded=degraded, reason=reason,
                )
            )
        except BaseException as e:  # noqa: BLE001 — delivered via future
            fut.set_exception(e)

    req.future.add_done_callback(_done)
    return fut
