"""The request lifecycle: submit -> future -> padded, shape-bucketed
device dispatch with the full runtime resilience stack wired in.

One :class:`ServeEngine` owns a resident
:class:`~mosaic_tpu.sql.join.ChipIndex` and turns concurrent
point-in-polygon requests into micro-batched dispatches on the
module-level jitted join (`sql/join._JIT_JOIN` — the same executable
cache `pip_join` uses, so a server and a batch job in one process share
compiles). The pipeline per batch:

    admit (quarantine + backpressure, `serve/admission.py`)
      -> coalesce (max-batch / max-wait window, `serve/batcher.py`)
      -> pad to bucket (`serve/bucket.py`)
      -> assign cells + probe under the ``serve.dispatch``
         watchdog/fault site, transient retry, host-oracle degradation
      -> scatter back per request, shedding only deadline-expired ones

Resilience wiring (all reused, none reimplemented):

- `runtime/watchdog.py` guards the blocking dispatch; its default
  deadline is the batch's largest remaining request deadline (plus
  grace), so a hung device surfaces as a typed ``StalledDeviceError``
  while the requests still have budget to retry or degrade.
- `runtime/retry.py` retries transient failures with backoff; past the
  budget the batch degrades to the exact f64 host oracle and every
  result is flagged :class:`DegradedResult` — callers get exact values
  and the truth about how they were computed.
- `runtime/faults.py` sites ``serve.admit`` / ``serve.batch`` /
  ``serve.dispatch`` make every failure mode injectable from tests.
- every stage emits ``serve_stage`` `telemetry.timed` events; per-request
  latency lands in ``serve_request`` events (`telemetry.summarize` turns
  them into the bench's p50/p99).

Compile discipline: caps are fixed at the full bucket (overflow is
structurally impossible, so no escalation can change a static argument
at runtime), and :meth:`warmup` precompiles every bucket against the
resident index. After warmup the signature set is frozen — a dispatch
introducing a new signature emits a ``serve_compile`` event and counts
in ``metrics()["cold_compiles"]`` (the serve tests pin this at zero).
"""

from __future__ import annotations

import numpy as np

from ..obs import trace as _trace
from ..runtime import telemetry as _telemetry, watchdog as _watchdog
from ..runtime.retry import call_with_retry
from ..sql import join as _join
from .admission import AdmissionController
from .batcher import MicroBatcher
from .bucket import BucketLadder, backend_compiles, dispatch_signature

import jax
import jax.numpy as jnp


class ServeEngine:
    """Online serving engine over a resident chip index.

    >>> engine = ServeEngine(index, h3, 9, bounds=bbox)
    >>> engine.warmup()
    >>> fut = engine.submit(points)          # (n, 2) -> Future
    >>> rows = fut.result(timeout=1.0)       # (n,) int32, -1 = no match
    """

    def __init__(
        self,
        index,
        index_system,
        resolution: int,
        *,
        ladder: BucketLadder | None = None,
        max_batch_rows: int | None = None,
        max_wait_s: float = 0.002,
        queue_capacity: int = 256,
        default_deadline_s: float | None = 1.0,
        bounds: tuple | None = None,
        park_point: np.ndarray | None = None,
        writeback: str = "scatter",
        lookup: str | None = None,
        cell_dtype=None,
        watchdog_grace_s: float = 0.5,
        probe: str = "scatter",
    ):
        self.index = index
        self.index_system = index_system
        self.resolution = index_system.resolution_arg(resolution)
        self.ladder = ladder or BucketLadder()
        self.writeback = writeback
        # force-lane env resolution happens once, here — dispatch uses
        # the pinned value so the compile-cache signature stays honest
        self.probe = _join.resolve_probe_mode(probe)
        if self.probe != "scatter" and writeback == "direct":
            raise ValueError(
                "probe='adaptive' requires writeback scatter|gather"
            )
        self.cell_dtype = cell_dtype
        self.watchdog_grace_s = float(watchdog_grace_s)
        dtype = index.border.verts.dtype
        if lookup is None:
            lookup = (
                "mxu"
                if jax.devices()[0].platform != "cpu"
                and dtype == jnp.float32
                else "gather"
            )
        self.lookup = lookup
        self._dtype = dtype
        host = getattr(index, "host", None)
        self._host = host
        self._shift = (
            host.shift
            if host is not None
            else np.asarray(index.border.shift, dtype=np.float64)
        )
        self._signatures: set = set()
        self._warmed: frozenset | None = None
        self._cold_compiles = 0

        self.admission = AdmissionController(
            capacity=queue_capacity,
            default_deadline_s=default_deadline_s,
            bounds=bounds,
            park_point=park_point,
            find_park=self._derive_park,
        )
        self.batcher = MicroBatcher(
            self.admission,
            self._dispatch,
            max_batch_rows=(
                min(self.ladder.max_bucket, 16384)
                if max_batch_rows is None
                else int(max_batch_rows)
            ),
            max_wait_s=max_wait_s,
        )
        if self.batcher.max_batch_rows > self.ladder.max_bucket:
            raise ValueError(
                f"max_batch_rows {self.batcher.max_batch_rows} exceeds the "
                f"top bucket {self.ladder.max_bucket}"
            )
        self._closed = False
        self.batcher.start()

    # ----------------------------------------------------------- public

    def submit(self, points, *, deadline_s: float | None = None):
        """Enqueue one request; returns its ``concurrent.futures.Future``
        resolving to the (n,) int32 matches (:class:`Overloaded` when
        shed). Raises :class:`Overloaded` at admission when the queue is
        full."""
        if self._closed:
            raise RuntimeError("engine is closed")
        pts = np.asarray(points, dtype=np.float64)
        if pts.ndim != 2 or pts.shape[1] != 2:
            raise ValueError(f"expected (n, 2) points, got {pts.shape}")
        if pts.shape[0] > self.ladder.max_bucket:
            raise ValueError(
                f"request of {pts.shape[0]} rows exceeds the top bucket "
                f"{self.ladder.max_bucket} — split it upstream"
            )
        return self.admission.admit(pts, deadline_s=deadline_s).future

    def join(self, points, *, deadline_s: float | None = None, timeout=None):
        """Synchronous convenience wrapper: submit and wait."""
        return self.submit(points, deadline_s=deadline_s).result(timeout)

    def warmup(self) -> dict:
        """Precompile every ladder bucket against the resident index.

        Runs the exact dispatch path (cell assignment + jitted probe) on
        an inert full-bucket batch per rung, so the first real request
        at any admitted shape replays a cached executable. Returns
        ``{"buckets": ..., "seconds": ..., "signatures": ...}``; after
        this, any dispatch that still introduces a new compile signature
        is counted in ``metrics()["cold_compiles"]`` (and emits a
        ``serve_compile`` event) — the bounded-compile contract's
        tripwire."""
        t0 = backend_compiles()
        total = 0.0
        with _telemetry.capture() as events, _trace.span(
            "serve.warmup", buckets=len(self.ladder.buckets)
        ):
            for b in self.ladder.buckets:
                pts = np.zeros((b, 2), dtype=np.float64)
                with _telemetry.timed(
                    "serve_stage", stage="warmup", bucket=b
                ):
                    self._dispatch_device(pts)
        total = sum(
            e["seconds"]
            for e in events
            if e.get("stage") == "warmup" and "seconds" in e
        )
        self._warmed = frozenset(self._signatures)
        t1 = backend_compiles()
        out = {
            "buckets": len(self.ladder.buckets),
            "seconds": round(total, 4),
            "signatures": len(self._signatures),
        }
        if t0 is not None and t1 is not None:
            out["backend_compiles"] = t1 - t0
        _telemetry.record("serve_warmup", **out)
        return out

    def metrics(self) -> dict:
        a, b = self.admission.metrics, self.batcher.metrics
        out = dict(a)
        out.update(b)
        out["shed"] = a["shed_queue_full"] + b["shed_deadline"]
        out["quarantined"] = a["quarantined_rows"]
        out["queue_depth"] = self.admission.depth()
        out["compile_signatures"] = len(self._signatures)
        out["cold_compiles"] = self._cold_compiles
        out["occupancy_mean"] = round(
            b["occupancy_sum"] / b["batches"], 4
        ) if b["batches"] else 0.0
        return out

    def close(self, timeout: float = 5.0) -> None:
        """Stop the batcher; queued requests are shed
        (``reason="shutdown"``)."""
        if not self._closed:
            self._closed = True
            self.batcher.stop(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # --------------------------------------------------------- dispatch

    def _dispatch(self, points: np.ndarray, deadline_hint=None):
        """Batcher callback: pad, dispatch with resilience, unpad.
        Returns ``(results (n,), occupancy)``."""
        padded, n = self.ladder.pad(points)
        bucket = padded.shape[0]
        with _trace.span(
            "serve.dispatch", bucket=bucket, rows=n,
        ), _telemetry.timed(
            "serve_stage", stage="dispatch", bucket=bucket, rows=n,
        ):
            out = self._dispatch_resilient(padded, deadline_hint)
        occupancy = n / bucket
        return out[:n], occupancy

    def _caps(self, bucket: int):
        """Full-bucket caps: overflow structurally impossible, so the
        static-arg set per bucket never changes at runtime."""
        fcap = None if self.writeback == "direct" else bucket
        hcap = bucket if self.index.num_heavy_cells else None
        ccap = (
            bucket
            if self.probe != "scatter" and self.index.num_convex_cells
            else None
        )
        return fcap, hcap, ccap

    def _dispatch_device(self, padded: np.ndarray) -> np.ndarray:
        """One exact device join of a full-bucket batch (the compile
        unit warmup precompiles and dispatch replays)."""
        bucket = padded.shape[0]
        fcap, hcap, ccap = self._caps(bucket)
        sig = dispatch_signature(
            bucket, self.index, writeback=self.writeback,
            lookup=self.lookup, found_cap=fcap, heavy_cap=hcap,
            probe=self.probe, convex_cap=ccap,
        )
        if sig not in self._signatures:
            self._signatures.add(sig)
            if self._warmed is not None:
                self._cold_compiles += 1
                _telemetry.record(
                    "serve_compile", bucket=bucket,
                    signatures=len(self._signatures),
                )
        dev = jnp.asarray(padded)
        if self.cell_dtype is not None:
            dev = dev.astype(self.cell_dtype)
        # always the JITTED cell program (shared `_cells_prog` lru, one
        # compile per bucket, precompiled by warmup): the batch-path
        # heuristic of going eager below 64k rows on CPU trades a
        # one-off compile for a ~1000x slower dispatch — the right trade
        # for a single cold batch, the wrong one on a serving hot path
        cells = _join._cells_prog(
            self.index_system, self.resolution, "cells"
        )(dev)
        shifted = jnp.asarray(padded - self._shift, dtype=self._dtype)
        return np.asarray(
            _join._JIT_JOIN(
                shifted, cells, self.index,
                heavy_cap=hcap, found_cap=fcap,
                writeback=self.writeback, lookup=self.lookup,
                probe=self.probe, convex_cap=ccap,
            )
        )

    def _dispatch_resilient(self, padded, deadline_hint) -> np.ndarray:
        """`_dispatch_device` under the watchdog deadline, transient
        retry, and host-oracle degradation."""
        default_s = (
            None
            if deadline_hint is None
            else max(float(deadline_hint), 0.05) + self.watchdog_grace_s
        )

        def attempt():
            return _watchdog.guard(
                "serve.dispatch", self._dispatch_device, padded,
                default_s=default_s,
            )

        fallback = None
        if self._host is not None:
            fallback = lambda: _join.host_join(  # noqa: E731
                padded, self._host, self.index_system, self.resolution
            )
        return call_with_retry(
            attempt, label="serve.dispatch", fallback=fallback
        )

    # ------------------------------------------------------- quarantine

    def _derive_park(self, raw: np.ndarray) -> np.ndarray:
        """Index-aware park point for poisoned rows: walk outward from
        the request's own finite bounding box until a cell NOT in the
        resident index answers (`runtime/quarantine.find_park_point`)."""
        from ..runtime import quarantine as _quarantine

        finite = raw[np.isfinite(raw).all(axis=1)]
        if finite.size:
            bounds = (
                float(finite[:, 0].min()), float(finite[:, 1].min()),
                float(finite[:, 0].max()), float(finite[:, 1].max()),
            )
        else:
            bounds = (0.0, 0.0, 1.0, 1.0)
        if self.admission.bounds is not None:
            bounds = self.admission.bounds

        def assign(pts):
            dev = jnp.asarray(np.asarray(pts, dtype=np.float64))
            if self.cell_dtype is not None:
                dev = dev.astype(self.cell_dtype)
            return self.index_system.point_to_cell(dev, self.resolution)

        return _quarantine.find_park_point(
            assign, np.asarray(self.index.cells), bounds
        )
