"""Multi-tenant serve router: N resident engines, hard isolation.

One :class:`~mosaic_tpu.serve.engine.ServeEngine` owns one index and
ONE admission queue — which means one overloaded caller fills the
shared queue for everyone behind it. The router is the fleet answer:
each tenant gets its OWN engine (own bounded queue, own micro-batcher
thread, own deadline budget, own ``DispatchCore``), so tenant A's
overload structurally cannot occupy a single slot of tenant B's
admission quota — isolation by construction, not by scheduling policy.
`Overloaded(reason=...)` shed accounting is therefore per-tenant for
free, and the router folds it onto the obs spine
(``serve.router_shed{tenant, reason}``).

Residency is bounded: at most ``max_resident`` engines (explicit arg >
``MOSAIC_SERVE_TENANTS`` env knob > 4) hold warmed cores at once.
Registering or reviving a tenant past the bound evicts one resident
tenant's engine under the ``router.evict`` fault/watchdog site —
health-aware: unhealthy tenants (per `obs/health.py`'s per-tenant
state machine) lose residency first, then cold — never-warmed —
engines (matching `_CoreCache`'s occupancy-aware order), then LRU; the
evicted tenant stays registered and is revived transparently on its
next submit. With a
:class:`~mosaic_tpu.dispatch.programs.ProgramStore` bound, a revival's
warmup is an AOT load, not a compile storm — eviction costs
milliseconds, which is what makes bounded residency viable at all.

Per-tenant `TuningProfile`\\ s load from the tenant's `ProfileStore`
(``profile_root=``) with the store's own typed refusals degraded to
"serve untuned" — a corrupt or mismatched profile must never keep a
tenant from serving.

Fault sites: ``router.admit`` (submit path), ``router.evict``,
``router.swap`` — all riding the existing faults/watchdog machinery
(`dispatch.guarded_call` for the two slow ops, `faults.maybe_fail` at
admission, same as `serve.admit`).
"""

from __future__ import annotations

import os
import threading
import time

from ..dispatch import guarded_call, resolve_program_store
from ..obs import health as _health
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..runtime import faults as _faults
from ..runtime import telemetry as _telemetry
from ..runtime.errors import Overloaded
from .engine import ServeEngine

#: resident-engine bound when neither the argument nor the env knob
#: says otherwise — sized to the repo's CPU smoke lanes; a real fleet
#: sets MOSAIC_SERVE_TENANTS to its HBM budget
DEFAULT_MAX_RESIDENT = 4


def resolve_max_resident(max_resident) -> int:
    """Host-side residency-bound resolution: explicit argument >
    ``MOSAIC_SERVE_TENANTS`` env knob > built-in default."""
    if max_resident is not None:
        n = int(max_resident)
    else:
        raw = os.environ.get("MOSAIC_SERVE_TENANTS", "").strip()
        n = int(raw) if raw else DEFAULT_MAX_RESIDENT
    if n < 1:
        raise ValueError(f"max_resident must be >= 1, got {n}")
    return n


class _Tenant:
    """One registered tenant: the config needed to (re)build its
    engine, the live engine when resident, and the router-side
    accounting that survives eviction."""

    __slots__ = (
        "name", "index", "resolution", "profile", "engine_kw",
        "engine", "last_used", "submitted", "shed_admit", "revivals",
        "last_metrics", "epoch", "epoch_advances",
    )

    def __init__(self, name, index, resolution, profile, engine_kw):
        self.name = name
        self.index = index
        self.resolution = resolution
        self.profile = profile
        self.engine_kw = engine_kw
        self.engine: "ServeEngine | None" = None
        self.last_used = 0.0
        self.submitted = 0
        self.shed_admit = 0
        self.revivals = 0
        self.last_metrics: dict = {}
        self.epoch = getattr(index, "epoch", None)
        self.epoch_advances = 0


class ServeRouter:
    """Tenant-keyed front door over per-tenant :class:`ServeEngine`\\ s.

    >>> router = ServeRouter(h3, program_store="/data/programs")
    >>> router.add_tenant("acme", acme_index, 9, profile_root="/data/acme")
    >>> fut = router.submit("acme", points)
    """

    def __init__(
        self,
        index_system,
        *,
        max_resident: int | None = None,
        program_store=None,
        default_deadline_s: float | None = 1.0,
        queue_capacity: int = 256,
        engine_defaults: dict | None = None,
        health_monitor=None,
    ):
        self.index_system = index_system
        self.max_resident = resolve_max_resident(max_resident)
        self.program_store = resolve_program_store(program_store)
        self.default_deadline_s = default_deadline_s
        self.queue_capacity = queue_capacity
        self.engine_defaults = dict(engine_defaults or {})
        #: the health state machine consulted by the eviction order —
        #: the process monitor unless a test injects its own
        self.health_monitor = (
            _health.MONITOR if health_monitor is None else health_monitor
        )
        self._tenants: dict[str, _Tenant] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._evictions = 0

    # ---------------------------------------------------------- tenants

    def add_tenant(
        self,
        tenant: str,
        index,
        resolution: int,
        *,
        profile=None,
        profile_root: str | None = None,
        deadline_s: float | None = None,
        queue_capacity: int | None = None,
        warm: bool = True,
        **engine_kw,
    ) -> dict:
        """Register ``tenant`` and (by default) bring its engine
        resident and warmed. ``deadline_s`` / ``queue_capacity`` are
        the tenant's deadline budget and admission quota; unset values
        inherit the router defaults. ``profile_root`` loads the
        tenant's newest valid `TuningProfile` bound to this index's
        tessellation — store refusals degrade to serving untuned."""
        if tenant in self._tenants:
            raise ValueError(f"tenant {tenant!r} already registered")
        if profile is None and profile_root is not None:
            profile = self._load_profile(tenant, index, profile_root)
        kw = dict(self.engine_defaults)
        kw.update(engine_kw)
        kw.setdefault("queue_capacity", queue_capacity or self.queue_capacity)
        kw.setdefault(
            "default_deadline_s",
            self.default_deadline_s if deadline_s is None else deadline_s,
        )
        t = _Tenant(tenant, index, resolution, profile, kw)
        with self._lock:
            self._tenants[tenant] = t
            stats = self._revive(t) if warm else {}
        _telemetry.record(
            "router_tenant_added", tenant=tenant, warm=warm,
            profiled=profile is not None,
        )
        return stats

    def _load_profile(self, tenant: str, index, profile_root: str):
        from ..tune import (
            ProfileFingerprintMismatch,
            ProfileStore,
            ProfileStoreCorrupt,
            index_fingerprint,
        )

        try:
            profile, _ = ProfileStore(profile_root).load_latest(
                expect_fingerprint=index_fingerprint(index)
            )
            return profile
        except (ProfileStoreCorrupt, ProfileFingerprintMismatch) as e:
            # the store already recorded its typed telemetry; the router
            # adds the tenant-scoped view and serves untuned
            _telemetry.record(
                "router_profile_fallback", tenant=tenant,
                error=repr(e)[:200],
            )
            return None

    def _revive(self, t: _Tenant) -> dict:
        """Build + warm ``t``'s engine (caller holds the lock), evicting
        LRU tenants as needed to respect the residency bound."""
        while self._resident_count() >= self.max_resident:
            victim = self._eviction_victim(exclude=t.name)
            if victim is None:
                break
            self._evict(victim)
        with _trace.span("router.revive", tenant=t.name), _telemetry.timed(
            "router_stage", stage="revive", tenant=t.name
        ):
            t.engine = ServeEngine(
                t.index, self.index_system, t.resolution,
                profile=t.profile, program_store=self.program_store,
                **t.engine_kw,
            )
            stats = t.engine.warmup()
        t.revivals += 1
        t.last_used = time.monotonic()
        _metrics.gauge(
            "serve.router_resident", "resident tenant engines",
        ).set(self._resident_count())
        return stats

    def _resident_count(self) -> int:
        return sum(1 for t in self._tenants.values() if t.engine is not None)

    def _eviction_victim(self, exclude: str) -> "_Tenant | None":
        """Health-aware occupancy-aware LRU: among resident tenants,
        sickest first (an unhealthy tenant's residency is the cheapest
        thing the fleet can shed — it is mostly shedding anyway), then
        never-warmed engines (nothing of value to drop), then oldest
        ``last_used``."""
        resident = [
            t for t in self._tenants.values()
            if t.engine is not None and t.name != exclude
        ]
        if not resident:
            return None
        rank = _health.RANK
        state = self.health_monitor.tenant_state
        return min(
            resident,
            key=lambda t: (
                -rank[state(t.name)], t.engine.core.warmed, t.last_used,
            ),
        )

    def _evict(self, t: _Tenant) -> None:
        """Close one tenant's engine under the ``router.evict``
        fault/watchdog site (queued requests shed with
        ``reason="shutdown"``); the tenant stays registered."""
        engine = t.engine
        with _trace.span("router.evict", tenant=t.name), _telemetry.timed(
            "router_stage", stage="evict", tenant=t.name
        ):
            # guarded_call's watchdog evaluates the router.evict fault
            # plan on this thread before dispatching
            guarded_call("router.evict", engine.close, retry=False)
        t.last_metrics = engine.metrics()
        t.engine = None
        self._evictions += 1
        _telemetry.record("router_evicted", tenant=t.name)
        _metrics.counter(
            "serve.router_evictions", "tenant engines evicted (LRU)",
        ).inc(tenant=t.name)
        _metrics.gauge(
            "serve.router_resident", "resident tenant engines",
        ).set(self._resident_count())

    def evict(self, tenant: str) -> None:
        """Explicitly release one tenant's engine (it revives on next
        submit)."""
        with self._lock:
            t = self._require(tenant)
            if t.engine is not None:
                self._evict(t)

    # ----------------------------------------------------------- serve

    def submit(self, tenant: str, points, *, deadline_s: float | None = None):
        """Admit one request for ``tenant``; returns its Future.

        Raises the engine's typed :class:`Overloaded` when the
        TENANT'S OWN quota is exhausted — other tenants' queues are
        untouchable by construction. A cold (evicted) tenant is revived
        first; ``router.admit`` is the injectable fault site."""
        if self._closed:
            raise RuntimeError("router is closed")
        with _telemetry.timed("router_stage", stage="admit", tenant=tenant):
            _faults.maybe_fail("router.admit")
            with self._lock:
                t = self._require(tenant)
                if t.engine is None:
                    self._revive(t)
                t.last_used = time.monotonic()
                t.submitted += 1
                engine = t.engine
        try:
            return engine.submit(points, deadline_s=deadline_s)
        except Overloaded as e:
            t.shed_admit += 1
            # a typed EVENT, not a direct counter inc: the obs bridge
            # folds it into serve.router_shed{tenant, reason}, and the
            # SLO/health monitors see the same shed the metric counts
            _telemetry.record("router_shed", tenant=tenant, reason=e.reason)
            raise

    def join(self, tenant, points, *, deadline_s=None, timeout=None):
        """Synchronous convenience wrapper: submit and wait."""
        return self.submit(
            tenant, points, deadline_s=deadline_s
        ).result(timeout)

    def submit_knn(
        self, tenant: str, points, k: int,
        *, deadline_s: float | None = None,
    ):
        """Admit one KNN request for ``tenant`` (engine configured with
        ``knn=``); same quota/revival/shed semantics as :meth:`submit`,
        future resolves to a batched
        :class:`~mosaic_tpu.knn.frontend.KNNAnswer`."""
        if self._closed:
            raise RuntimeError("router is closed")
        with _telemetry.timed(
            "router_stage", stage="admit", tenant=tenant, kind="knn",
        ):
            _faults.maybe_fail("router.admit")
            with self._lock:
                t = self._require(tenant)
                if t.engine is None:
                    self._revive(t)
                t.last_used = time.monotonic()
                t.submitted += 1
                engine = t.engine
        try:
            return engine.submit_knn(points, k, deadline_s=deadline_s)
        except Overloaded as e:
            t.shed_admit += 1
            _telemetry.record("router_shed", tenant=tenant, reason=e.reason)
            raise

    def join_knn(self, tenant, points, k, *, deadline_s=None, timeout=None):
        """Synchronous convenience wrapper: submit_knn and wait."""
        return self.submit_knn(
            tenant, points, k, deadline_s=deadline_s
        ).result(timeout)

    def swap(self, tenant: str, index=None, **hot_swap_kw) -> dict:
        """Hot-swap one tenant's index/profile under the
        ``router.swap`` fault/watchdog site — the engine's swap
        discipline (build aside, warm, rebind atomically) applies
        unchanged, so in-flight requests answer from the old snapshot
        bit-identically."""
        with self._lock:
            t = self._require(tenant)
            if t.engine is None:
                self._revive(t)
            engine = t.engine
            if index is not None:
                t.index = index
        with _trace.span("router.swap", tenant=tenant), _telemetry.timed(
            "router_stage", stage="swap", tenant=tenant
        ):
            stats = guarded_call(
                "router.swap", engine.hot_swap, index,
                retry=False, **hot_swap_kw,
            )
        _telemetry.record("router_swapped", tenant=tenant, **stats)
        return stats

    def advance_epoch(
        self, tenant: str, epochal, *, reprofile: bool = False,
        **hot_swap_kw,
    ) -> dict:
        """Publish an :class:`~mosaic_tpu.index.epoch.EpochalIndex`'s
        newest applied epoch into one tenant's engine, through the
        ``router.swap`` guarded site.

        The old-snapshot-keeps-serving contract: the new epoch's core is
        built and warmed ASIDE (``hot_swap``'s discipline) — if the swap
        fails, the guarded site raises, the tenant's engine keeps
        answering from its current snapshot, the tenant's accounting is
        untouched, AND the epochal index stays on its previous published
        epoch (the delta log is already durable, so a later retry
        publishes the same epoch). ``reprofile=True`` re-profiles the
        mutated column through `tune` on the boundary."""
        with self._lock:
            t = self._require(tenant)
            if t.engine is None:
                self._revive(t)
            engine = t.engine

        class _Guarded:
            """hot_swap proxied through the router's fault site."""

            @staticmethod
            def hot_swap(index, **kw):
                return guarded_call(
                    "router.swap", engine.hot_swap, index,
                    retry=False, **kw,
                )

        stats = epochal.publish(
            _Guarded, reprofile=reprofile, **hot_swap_kw
        )
        with self._lock:
            t.index = epochal.index
            t.epoch = epochal.epoch
            t.epoch_advances += 1
        _telemetry.record(
            "router_epoch_advanced", tenant=tenant,
            epoch=int(epochal.epoch), chips=stats.get("chips", 0),
        )
        return stats

    # ------------------------------------------------------- accounting

    def _require(self, tenant: str) -> _Tenant:
        t = self._tenants.get(tenant)
        if t is None:
            raise KeyError(
                f"unknown tenant {tenant!r} — register it with add_tenant"
            )
        return t

    def tenants(self) -> list[str]:
        return sorted(self._tenants)

    def metrics(self) -> dict:
        """Per-tenant engine metrics (live, or last-known for evicted
        tenants) plus the router-level residency story."""
        with self._lock:
            per = {}
            for name, t in self._tenants.items():
                m = (
                    t.engine.metrics()
                    if t.engine is not None
                    else dict(t.last_metrics)
                )
                m.update(
                    resident=t.engine is not None,
                    submitted_router=t.submitted,
                    shed_admit_router=t.shed_admit,
                    revivals=t.revivals,
                    epoch=t.epoch,
                    epoch_advances=t.epoch_advances,
                    health=self.health_monitor.tenant_state(name),
                )
                per[name] = m
            return {
                "tenants": per,
                "registered": len(self._tenants),
                "resident": self._resident_count(),
                "max_resident": self.max_resident,
                "evictions": self._evictions,
            }

    def close(self, timeout: float = 5.0) -> None:
        """Close every resident engine (queued requests shed with
        ``reason="shutdown"``)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for t in self._tenants.values():
                if t.engine is not None:
                    t.last_metrics = t.engine.metrics()
                    t.engine.close(timeout)
                    t.engine = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
