"""Compatibility shim: the shape-bucketing contract moved to
`mosaic_tpu.dispatch.bucket` when the dispatch core unified the four
frontend execution paths — serve was its first owner, every frontend
now shares it. Import from `mosaic_tpu.dispatch` in new code."""

from ..dispatch.bucket import (  # noqa: F401
    DEFAULT_MAX_BUCKET,
    DEFAULT_MIN_BUCKET,
    BucketLadder,
    backend_compiles,
    dispatch_signature,
    mesh_key,
)

__all__ = [
    "BucketLadder",
    "DEFAULT_MAX_BUCKET",
    "DEFAULT_MIN_BUCKET",
    "backend_compiles",
    "dispatch_signature",
    "mesh_key",
]
