"""Online query serving: dynamic micro-batching over a resident index.

The reference engine serves its ~120 expressions from resident Spark
executors; this package is the TPU-native analog for the request-facing
path — many small concurrent point-in-polygon queries coalesced into
padded, shape-bucketed device dispatches on the module-level jitted
join, with admission control in front and the PR-1..3 resilience stack
(watchdog, retry, degradation, quarantine, fault injection) underneath.

    from mosaic_tpu.serve import ServeEngine

    engine = ServeEngine(chip_index, h3, resolution=9, bounds=bbox)
    engine.warmup()                 # precompile every bucket
    fut = engine.submit(points)     # -> concurrent.futures.Future
    rows = fut.result(timeout=1.0)  # (n,) int32, -1 = no polygon

KNN-as-a-service rides the same queue: ``engine = ServeEngine(...,
knn=build_knn_index(...))`` lets ``engine.submit_knn(points, k)``
co-batch k-nearest-neighbour requests with PIP traffic under one
admission/deadline/shed budget (`mosaic_tpu/knn` owns the bucketed
ring-expansion frontend and its Voronoi convex fast path).

Component map: `bucket.py` (pad-to-bucket ladder + compile accounting),
`admission.py` (bounded queue, deadlines, poison parking, typed
``Overloaded``), `batcher.py` (max-batch/max-wait coalescing with
per-request deadline shedding), `engine.py` (lifecycle + resilience
wiring), `router.py` (multi-tenant front door: per-tenant engines with
hard isolation, bounded residency with occupancy-aware LRU eviction,
per-tenant tuning profiles and shed accounting). Benches:
`tools/serve_bench.py` (single- and multi-tenant),
`tools/restart_bench.py` (zero-cold-start restart storm over the AOT
program store).
"""

from .admission import AdmissionController, Request
from .batcher import MicroBatcher
from .bucket import BucketLadder, backend_compiles, dispatch_signature
from .engine import ServeEngine
from .router import ServeRouter, resolve_max_resident

__all__ = [
    "AdmissionController",
    "BucketLadder",
    "MicroBatcher",
    "Request",
    "ServeEngine",
    "ServeRouter",
    "backend_compiles",
    "dispatch_signature",
    "resolve_max_resident",
]
