"""Dynamic micro-batching: many small requests -> one well-shaped device
dispatch.

The batching policy is the classic (max batch size, max wait window)
pair: the worker takes the oldest queued request, then keeps coalescing
while the summed rows stay within ``max_batch_rows`` AND the window
(``max_wait_s``, counted from the FIRST request in the batch) has not
expired. A request that would overshoot the row budget goes back to the
queue head and leads the next batch — requests are never split, so each
request's rows are contiguous in the concatenated batch and scatter-back
is one slice per request.

Correctness contract (pinned by tests/test_serve.py): co-batched results
are BIT-IDENTICAL to solo execution. This is structural, not
approximate — cell assignment is pointwise, the probe evaluates each row
independently, and caps at the full bucket cannot overflow — so
coalescing changes scheduling, never values.

Deadline enforcement happens at the two batcher touchpoints:

- **formation**: a request already past its deadline is shed before any
  device work is spent on it (``Overloaded(reason="deadline")``);
- **scatter-back**: after the dispatch returns (possibly delayed by a
  stall the watchdog/retry stack absorbed), each request's deadline is
  re-checked; late requests are shed — and ONLY they: batchmates with
  remaining budget keep their results. A stall therefore degrades the
  engine request-by-request, never batch-by-batch.

``serve.batch`` is the batch-formation fault site; the dispatch itself
runs under the ``serve.dispatch`` watchdog/fault site inside the
engine's dispatch function.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..obs import trace as _trace
from ..runtime import faults as _faults, telemetry as _telemetry
from ..runtime.errors import DegradedResult, Overloaded
from .admission import AdmissionController, Request


class MicroBatcher:
    """Background coalescing loop over an :class:`AdmissionController`.

    ``dispatch(points, deadline_hint, reqs)`` is the engine-supplied
    function mapping a concatenated ``(n, 2)`` f64 array to
    ``(results, occupancy)`` (padding, bucketing, retry, and degradation
    live there; the hint — the batch's largest remaining request budget
    in seconds — becomes the watchdog default; ``reqs`` is the live
    request list in concatenation order, which lets the engine split a
    mixed PIP/KNN batch by ``Request.kind`` and answer each segment in
    its own wire shape). The result only needs ``out[off : off + n]``
    slicing at the request boundaries — a plain (n,) array for uniform
    batches, the engine's segment view for mixed ones. The batcher owns
    request lifecycle: coalescing, deadline shedding, scatter-back, and
    future resolution.
    """

    def __init__(
        self,
        admission: AdmissionController,
        dispatch,
        *,
        max_batch_rows: int = 16384,
        max_wait_s: float = 0.002,
        idle_tick_s: float = 0.05,
    ):
        self.admission = admission
        self.dispatch = dispatch
        self.max_batch_rows = int(max_batch_rows)
        self.max_wait_s = float(max_wait_s)
        self.idle_tick_s = float(idle_tick_s)
        self.metrics = {
            "batches": 0,
            "batched_rows": 0,
            "batched_requests": 0,
            "shed_deadline": 0,
            "completed": 0,
            "failed": 0,
            "degraded": 0,
            "occupancy_sum": 0.0,
        }
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="mosaic-serve-batcher", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(timeout)
        for req in self.admission.drain():
            self._shed(req, "shutdown")

    # ------------------------------------------------------------ loop

    def _loop(self) -> None:
        while not self._stop.is_set():
            first = self.admission.take(self.idle_tick_s)
            if first is None:
                continue
            batch = self._form_batch(first)
            if batch:
                self._process(batch)

    def _form_batch(self, first: Request) -> list[Request]:
        """Coalesce from the queue until the row budget or the window
        (measured from ``first``'s arrival at the batcher) is spent."""
        batch = [first]
        rows = first.n
        window_end = time.monotonic() + self.max_wait_s
        while rows < self.max_batch_rows:
            remaining = window_end - time.monotonic()
            if remaining <= 0:
                break
            nxt = self.admission.take(remaining)
            if nxt is None:
                break
            if rows + nxt.n > self.max_batch_rows:
                self.admission.put_back(nxt)
                break
            batch.append(nxt)
            rows += nxt.n
        return batch

    def _process(self, batch: list[Request]) -> None:
        # the dispatch worker adopts the FIRST request's caller context:
        # fault plans, capture sinks, and span context are thread-local,
        # and tests install them on the submitting thread (batchmates
        # from other traces keep their OWN root spans; only the shared
        # batch/dispatch spans parent to the first request's trace)
        _telemetry.adopt_sinks(batch[0].sinks)
        _faults.adopt_plans(batch[0].plans)
        _trace.adopt_context(batch[0].ctx)

        now = time.monotonic()
        live = []
        for req in batch:
            if req.remaining(now) <= 0:
                self._shed(req, "deadline")
            else:
                live.append(req)
        if not live:
            return

        # queue-wait interval per admitted request: submit stamp →
        # batch formation (this instant); recorded flat (ts_mono -
        # seconds recovers the interval) and stamped with the request's
        # own trace ids so the wait lands inside its serve.request root
        for req in live:
            _telemetry.record(
                "serve_stage", stage="queue_wait",
                seconds=round(max(now - req.t_submit, 0.0), 6),
                rows=req.n, **_req_ids(req),
            )

        rows = sum(r.n for r in live)
        self.metrics["batches"] += 1
        self.metrics["batched_rows"] += rows
        self.metrics["batched_requests"] += len(live)
        try:
            with _trace.span(
                "serve.batch", requests=len(live), rows=rows,
            ), _telemetry.timed(
                "serve_stage", stage="batch", requests=len(live), rows=rows,
            ):
                _faults.maybe_fail("serve.batch")
                points = (
                    live[0].points
                    if len(live) == 1
                    else np.concatenate([r.points for r in live])
                )
                # the watchdog default for this dispatch: the batch's
                # largest remaining request budget (None = no deadline)
                rem = [r.remaining(now) for r in live]
                hint = max(rem) if all(np.isfinite(rem)) else None
                out, occupancy = self.dispatch(points, hint, live)
            self.metrics["occupancy_sum"] += float(occupancy)
        except BaseException as e:  # noqa: BLE001 — delivered per-future
            for req in live:
                self._fail(req, e)
            return

        # mixed-batch segment views flag degradation via a plain
        # attribute (they are not ndarray subclasses)
        degraded = isinstance(out, DegradedResult) or bool(
            getattr(out, "degraded", False)
        )
        now = time.monotonic()
        off = 0
        for req in live:
            sl = np.asarray(out[off : off + req.n])
            off += req.n
            if req.remaining(now) <= 0:
                self._shed(req, "deadline")
                continue
            if degraded:
                sl = DegradedResult.wrap(
                    sl, reason=out.reason, attempts=out.attempts
                )
                self.metrics["degraded"] += 1
            self.metrics["completed"] += 1
            # the event and the root-span close both carry the REQUEST's
            # own trace ids — the ambient context here is batch[0]'s
            _telemetry.record(
                "serve_request",
                seconds=round(now - req.t_submit, 6),
                rows=req.n,
                parked=req.parked,
                degraded=bool(degraded),
                **_req_ids(req),
            )
            if req.span is not None:
                req.span.end(degraded=bool(degraded), parked=req.parked)
            req.future.set_result(sl)

    def _shed(self, req: Request, reason: str) -> None:
        self.metrics["shed_deadline"] += reason == "deadline"
        elapsed = time.monotonic() - req.t_submit
        _telemetry.record(
            "serve_shed", reason=reason, rows=req.n,
            elapsed_s=round(elapsed, 6),
            **_req_ids(req),
        )
        if req.span is not None:
            req.span.end(error="Overloaded", reason=reason)
        req.future.set_exception(
            Overloaded(
                f"request shed ({reason}) after {elapsed:.3f}s",
                reason=reason,
                elapsed_s=elapsed,
                deadline_s=(
                    0.0
                    if req.deadline is None
                    else req.deadline - req.t_submit
                ),
            )
        )

    def _fail(self, req: Request, exc: BaseException) -> None:
        self.metrics["failed"] += 1
        if req.span is not None:
            req.span.end(error=type(exc).__name__)
        req.future.set_exception(exc)


def _req_ids(req: Request) -> dict:
    """Explicit trace stamps for per-request events recorded while the
    thread's ambient context belongs to another batchmate."""
    if req.ctx is None:
        return {}
    return {"trace_id": req.ctx.trace_id, "span_id": req.ctx.span_id}
