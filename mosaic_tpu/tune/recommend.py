"""Map a `WorkloadProfile` to a `TuningProfile` with auditable rules.

PAPERS.md's *Adaptive Geospatial Joins for Modern Hardware* picks the join
strategy from measured data statistics; this module is that idea over our
knob surface. Every rule is measurement-backed — either by the profile
statistic it reads or by the committed bench history (`TREND.json`,
``BENCH_*``/``STREAM_*``/``RASTER_*`` artifacts) loaded as priors — and
every recommendation carries a machine-checkable rationale entry
``{knob, value, rule, evidence}`` so a reviewer (or a test) can replay the
decision from the profile alone. A knob the rules have no evidence for
stays None, which the resolver reads as "keep the built-in default" — the
optimizer never guesses.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

from ..runtime import telemetry as _telemetry
from .profiler import WorkloadProfile

#: class-share threshold above which the per-cell router pays for itself —
#: the round-7 probe bench (BENCH_r07) showed adaptive winning once dense
#: cells carry >~25% of the points and losing (router overhead) below it
ADAPTIVE_DENSE_SHARE = 0.25

#: tile occupancy below which halving the tile shape wins — raster_bench
#: round 6 (RASTER_r06): sparse coverage wastes pad compute in big tiles
SPARSE_TILE_OCCUPANCY = 0.5

#: border-pair share above which an overlay join is predicate-bound and
#: one step finer tessellation pays: smaller cells convert border chips
#: to core chips, and core pairs are decided WITHOUT the exact
#: ``st_intersects`` predicate (`sql/overlay.py` accepts them outright),
#: so past an even split the predicate batch shrinks faster than the
#: candidate list grows
OVERLAY_BORDER_SHARE = 0.5

#: candidate-pair count above which the device overlay lane amortizes its
#: fixed costs (prep transfer + one fused launch) over enough pairs to beat
#: the host numpy twin — the OVERLAY_r17 bench lane crosses over well below
#: this, so the threshold is conservative; below it the host oracle lane is
#: both exact and cheaper
OVERLAY_DEVICE_CANDIDATES = 4096

#: convex-candidate share above which the KNN Voronoi fast path pays: the
#: one-shot cover dispatch needs the Voronoi walk's strict-descent
#: guarantee, which only convex chip sites give, so its fallback-to-ring
#: fraction tracks (1 - convex share). The KNN_r19 bench lane measured the
#: Voronoi lane well above parity on an all-convex fixture (see
#: ``detail.voronoi_speedup_vs_ring``); at half-convex the saved ring
#: iterations still dominate the wasted walk on the non-convex half
KNN_CONVEX_SHARE = 0.5


@dataclasses.dataclass
class TuningProfile:
    """A set of knob recommendations. None = no recommendation: the
    resolver falls through to the built-in default. ``rationale`` is the
    machine-checkable audit trail, ``source`` summarizes the inputs."""

    resolution: "int | None" = None
    probe: "str | None" = None
    writeback: "str | None" = None
    lookup: "str | None" = None
    batch_size: "int | None" = None
    bucket_min: "int | None" = None
    bucket_max: "int | None" = None
    stream_window: "int | None" = None
    stream_pipeline: "bool | None" = None
    raster_tile: "tuple | None" = None
    zonal_lane: "str | None" = None
    overlay_lane: "str | None" = None
    knn_lane: "str | None" = None
    rationale: list = dataclasses.field(default_factory=list)
    source: dict = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if d.get("raster_tile") is not None:
            d["raster_tile"] = list(d["raster_tile"])
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TuningProfile":
        names = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in names}
        if kw.get("raster_tile") is not None:
            kw["raster_tile"] = tuple(int(v) for v in kw["raster_tile"])
        return cls(**kw)

    @classmethod
    def merged(cls, *profiles: "TuningProfile") -> "TuningProfile":
        """Combine recommendations from complementary workload profiles
        (e.g. the polygon side's resolution with the point side's probe
        and batch knobs). First non-None wins per knob; rationales
        concatenate in the same order so the audit trail survives."""
        out = cls()
        for p in profiles:
            for f in dataclasses.fields(cls):
                if f.name in ("rationale", "source"):
                    continue
                if getattr(out, f.name) is None:
                    setattr(out, f.name, getattr(p, f.name))
            out.rationale.extend(p.rationale)
            out.source.setdefault("merged", []).append(p.source)
        return out


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


def load_priors(root: "str | Path | None" = None) -> dict:
    """Best-effort read of the committed bench history: ``TREND.json``
    plus any ``BENCH_*``/``STREAM_*``/``RASTER_*`` round artifacts under
    ``root`` (default: the repository root, found relative to this file).
    Missing or unreadable files are skipped — priors sharpen rules, they
    never gate them."""
    if root is None:
        root = Path(__file__).resolve().parents[2]
    root = Path(root)
    priors: dict = {"artifacts": {}}
    for pattern in (
        "TREND.json",
        "BENCH_*.json",
        "STREAM_*.json",
        "RASTER_*.json",
        "OVERLAY_*.json",
        "KNN_*.json",
    ):
        for path in sorted(root.glob(pattern)):
            try:
                priors["artifacts"][path.name] = json.loads(path.read_text())
            except (OSError, ValueError):
                continue
    return priors


def recommend(profile: WorkloadProfile, priors: "dict | None" = None) -> TuningProfile:
    """The rule table. Each branch appends one rationale entry; the
    returned profile's ``source`` echoes the statistics it read."""
    if priors is None:
        priors = load_priors()
    with _telemetry.timed("tune_stage", stage="recommend", kind=profile.kind):
        return _recommend(profile, priors)


def _recommend(profile: WorkloadProfile, priors: dict) -> TuningProfile:
    out = TuningProfile()
    why = out.rationale

    def set_knob(knob, value, rule, evidence):
        setattr(out, knob, value)
        why.append({"knob": knob, "value": value if not isinstance(value, tuple)
                    else list(value), "rule": rule, "evidence": evidence})

    if profile.kind == "polygons" and profile.optimal_resolution is not None:
        set_knob(
            "resolution", int(profile.optimal_resolution),
            "analyzer-target-cells",
            {"cells_per_geom": profile.cells_per_geom,
             "optimal_resolution": profile.optimal_resolution},
        )

    if (
        profile.kind == "overlay"
        and profile.border_fraction is not None
        and profile.resolution is not None
        and profile.border_fraction > OVERLAY_BORDER_SHARE
    ):
        # consumed from the overlay.candidates span stats the profiler
        # captures (sql/overlay.py emits them on every candidate pass)
        set_knob(
            "resolution", int(profile.resolution) + 1,
            "border-dominated-finer-tessellation",
            {"border_fraction": profile.border_fraction,
             "sure_fraction": profile.sure_fraction,
             "candidates": profile.n_sampled,
             "threshold": OVERLAY_BORDER_SHARE},
        )

    if profile.kind == "overlay" and profile.n_sampled:
        speedup, artifact = _overlay_lane_prior(priors)
        evidence = {
            "candidates": profile.n_sampled,
            "threshold": OVERLAY_DEVICE_CANDIDATES,
            "artifact": artifact,
            "speedup_vs_host": speedup,
        }
        if profile.n_sampled >= OVERLAY_DEVICE_CANDIDATES and (
            speedup is None or speedup >= 1.0
        ):
            # the fused device lane wins once the fixed prep/launch cost is
            # spread over enough pairs, provided the committed bench did not
            # measure it losing to the host twin on this hardware
            set_knob("overlay_lane", "device",
                     "device-lane-amortized-candidates", evidence)
        else:
            set_knob("overlay_lane", "host",
                     "small-candidate-host-lane", evidence)

    shares = profile.class_shares or {}
    dense = float(shares.get("heavy", 0.0)) + float(shares.get("convex", 0.0))
    if profile.kind == "points" and shares:
        if dense > ADAPTIVE_DENSE_SHARE:
            set_knob(
                "probe", "adaptive", "dense-share-router",
                {"heavy": shares.get("heavy"), "convex": shares.get("convex"),
                 "threshold": ADAPTIVE_DENSE_SHARE},
            )
        else:
            set_knob(
                "probe", "scatter", "light-dominated-single-lane",
                {"light": shares.get("light"),
                 "threshold": ADAPTIVE_DENSE_SHARE},
            )

    if profile.kind == "points" and shares:
        speedup, artifact = _knn_lane_prior(priors)
        convex = float(shares.get("convex", 0.0))
        evidence = {
            "convex": convex,
            "threshold": KNN_CONVEX_SHARE,
            "artifact": artifact,
            "voronoi_speedup_vs_ring": speedup,
        }
        if convex > KNN_CONVEX_SHARE and (speedup is None or speedup >= 1.0):
            # mostly-convex candidates: the Voronoi walk's one-shot cover
            # replaces the iterative ring loop, and the committed bench
            # did not measure it losing to ring on this hardware
            set_knob("knn_lane", "voronoi",
                     "convex-share-voronoi-lane", evidence)
        else:
            set_knob("knn_lane", "ring",
                     "mixed-share-ring-lane", evidence)

    n_total = profile.n_total or profile.n_sampled
    if profile.kind == "points" and n_total:
        # batch at a pow2 that amortizes dispatch overhead but keeps the
        # probe intermediates bounded — sized from the FULL workload (the
        # profiling sample is capped; chunking a large stream at the
        # sample size would multiply dispatches ~50x)
        batch = min(65536, max(1024, _next_pow2(n_total // 8)))
        set_knob(
            "batch_size", batch, "pow2-amortized-chunks",
            {"n_total": n_total},
        )
        set_knob(
            "bucket_min", max(64, batch // 16), "ladder-spans-batch",
            {"batch_size": batch},
        )
        set_knob(
            "bucket_max", batch, "ladder-spans-batch",
            {"batch_size": batch},
        )

    if profile.band_fraction is not None and profile.band_fraction > 0.05:
        # a fat epsilon band means the f64 recheck dominates — the exact
        # fold lane keeps zonal answers bit-identical without a recheck
        set_knob(
            "zonal_lane", "fold", "band-fraction-exactness",
            {"band_fraction": profile.band_fraction},
        )

    if profile.kind == "raster" and profile.tile_occupancy is not None:
        if profile.tile_occupancy < SPARSE_TILE_OCCUPANCY:
            set_knob(
                "raster_tile", (128, 128), "sparse-raster-small-tiles",
                {"tile_occupancy": profile.tile_occupancy,
                 "threshold": SPARSE_TILE_OCCUPANCY},
            )
        else:
            set_knob(
                "raster_tile", (256, 256), "dense-raster-default-tiles",
                {"tile_occupancy": profile.tile_occupancy,
                 "threshold": SPARSE_TILE_OCCUPANCY},
            )

    stream = _stream_pipeline_prior(priors)
    if stream is not None:
        window, speedup, name = stream
        set_knob(
            "stream_window", window, "bench-history-window",
            {"artifact": name, "speedup_vs_sync": speedup},
        )
        if speedup is not None:
            set_knob(
                "stream_pipeline", bool(speedup >= 1.0),
                "bench-history-pipeline-speedup",
                {"artifact": name, "speedup_vs_sync": speedup},
            )

    out.source = {
        "profile": profile.as_dict(),
        "priors": sorted(priors.get("artifacts", {})),
    }
    _telemetry.record(
        "tune_recommend",
        kind=profile.kind,
        knobs=",".join(sorted(r["knob"] for r in why)),
        rules=",".join(sorted({r["rule"] for r in why})),
    )
    return out


def _overlay_lane_prior(priors: dict):
    """The committed overlay bench's device-vs-host measurement, when one
    exists: ``(speedup_vs_host, artifact)``. A measured speedup < 1.0 means
    the fused device lane lost to the host numpy twin on this hardware, so
    the router should keep candidates on the host lane regardless of size."""
    speedup, artifact = None, None
    for name, art in sorted(priors.get("artifacts", {}).items()):
        if not name.startswith("OVERLAY_") or not isinstance(art, dict):
            continue
        detail = art.get("detail")
        if not isinstance(detail, dict):
            continue
        s = detail.get("speedup_vs_host")
        if isinstance(s, (int, float)):
            # newest round wins (names sort by round suffix)
            speedup, artifact = float(s), name
    return speedup, artifact


def _knn_lane_prior(priors: dict):
    """The committed KNN bench's Voronoi-vs-ring measurement, when one
    exists: ``(voronoi_speedup_vs_ring, artifact)``. A measured speedup
    < 1.0 means the one-shot Voronoi cover lost to iterative ring
    expansion on this hardware, so the router keeps the ring lane even
    for convex-dominated candidates."""
    speedup, artifact = None, None
    for name, art in sorted(priors.get("artifacts", {}).items()):
        if not name.startswith("KNN_") or not isinstance(art, dict):
            continue
        detail = art.get("detail")
        if not isinstance(detail, dict):
            continue
        s = detail.get("voronoi_speedup_vs_ring")
        if isinstance(s, (int, float)):
            # newest round wins (names sort by round suffix)
            speedup, artifact = float(s), name
    return speedup, artifact


def _stream_pipeline_prior(priors: dict):
    """The committed stream bench's pipelined-executor measurement, when
    one exists: ``(window, speedup_vs_sync, artifact)``. The measured-good
    window depth beats the hardcoded default, and the measured speedup
    decides whether the pipelined lane is worth turning on at all."""
    best = None
    for name, art in sorted(priors.get("artifacts", {}).items()):
        if not name.startswith("STREAM_") or not isinstance(art, dict):
            continue
        detail = art.get("detail")
        pipe = detail.get("pipeline") if isinstance(detail, dict) else None
        if not isinstance(pipe, dict):
            continue
        win = pipe.get("window")
        if isinstance(win, (int, float)) and int(win) >= 1:
            speedup = pipe.get("speedup_vs_sync")
            cand = (
                int(win),
                float(speedup) if isinstance(speedup, (int, float)) else None,
                name,
            )
            # newest round wins (names sort by round suffix)
            best = cand
    return best
