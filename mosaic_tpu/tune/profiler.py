"""Workload profiling: sample a workload into a typed `WorkloadProfile`.

The profile is the optimizer's input contract — the same statistics the
bench ``detail`` blocks already collect (`bench.py caps_for` presample,
`tools/raster_bench.py` occupancy), computed once on a capped host-side
sample and recorded under a ``tune.profile`` span so profiling shows up in
trails like any other stage:

- **match rate / class shares** — fraction of sampled points whose cell is
  in the index, split light/heavy/convex by the index's own density
  classes (``cell_heavy`` / ``cell_convex``), because the shares decide
  probe-lane routing.
- **chip-density histogram** — chips-per-cell percentiles over the cells
  the sample actually hits; dense cells push toward the adaptive probe.
- **epsilon-band fraction** — fraction of matched sample points within
  ``EDGE_BAND_K * eps(f32) * coord_scale`` of a chip edge (the exact
  recheck band, computed against the f64 `HostRecheck` companion); high
  band fractions mean recheck cost dominates and finer resolutions pay.
- **cells-per-geometry percentiles** — `sql.analyzer.MosaicAnalyzer`'s
  metrics at its recommended resolution (polygon workloads).
- **tile occupancy / nodata fraction** — valid-pixel share per
  `raster.tiles.stack_tiles` mask (raster workloads); sparse tiles favor
  smaller tile shapes so empty tiles are skipped, not padded.

Everything here is host-side numpy on a deterministic capped sample —
nothing is traced, nothing touches the jit cache.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..obs import trace as _trace
from ..runtime import telemetry as _telemetry

#: deterministic profiling sample cap — large enough for stable shares
#: (binomial std < 1% at 4096), small enough that the f64 edge-distance
#: scan stays in the milliseconds
DEFAULT_SAMPLE = 4096


@dataclasses.dataclass
class WorkloadProfile:
    """One workload, summarized. ``kind`` is ``points`` / ``polygons`` /
    ``raster``; fields that a given kind does not measure stay None."""

    kind: str
    n_sampled: int
    n_total: "int | None" = None  # full workload size (sampling excluded)
    resolution: "int | None" = None  # resolution the sample was probed at
    match_rate: "float | None" = None
    class_shares: "dict | None" = None  # {"light","heavy","convex"} of matches
    chip_density: "dict | None" = None  # chips-per-cell p50/p90/max over hit cells
    band_fraction: "float | None" = None
    cells_per_geom: "dict | None" = None  # analyzer mean/p25/p50/p75
    optimal_resolution: "int | None" = None
    tile_occupancy: "float | None" = None
    nodata_fraction: "float | None" = None
    sure_fraction: "float | None" = None  # overlay pairs decided core-free
    border_fraction: "float | None" = None  # overlay pairs paying the predicate

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "WorkloadProfile":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})


def _sample_rows(n: int, sample: int, seed: int) -> np.ndarray:
    if n <= sample:
        return np.arange(n)
    return np.random.default_rng(seed).choice(n, size=sample, replace=False)


def _seg_dist2(px, py, edges):
    """(n,) min squared point-to-segment distance over (n, E, 4) f64
    edges; zero-padded edge rows are masked out."""
    ax, ay, bx, by = (edges[..., i] for i in range(4))
    live = (np.abs(edges).sum(axis=-1) > 0.0)
    dx, dy = bx - ax, by - ay
    den = np.maximum(dx * dx + dy * dy, 1e-300)
    t = np.clip(((px[:, None] - ax) * dx + (py[:, None] - ay) * dy) / den, 0.0, 1.0)
    qx, qy = ax + t * dx - px[:, None], ay + t * dy - py[:, None]
    d2 = qx * qx + qy * qy
    return np.where(live, d2, np.inf).min(axis=1)


def profile_points(
    points,
    chip_index,
    index_system,
    resolution: int,
    *,
    sample: int = DEFAULT_SAMPLE,
    seed: int = 0,
) -> WorkloadProfile:
    """Profile a point workload against a resident index: match rate,
    light/heavy/convex shares, chip-density histogram of the hit cells,
    and the epsilon-band fraction (when the index carries its f64 host
    companion)."""
    from ..sql.join import EDGE_BAND_K

    raw = np.asarray(points, dtype=np.float64)
    with _trace.span(
        "tune.profile", kind="points", n=int(raw.shape[0]), sample=sample
    ), _telemetry.timed("tune_stage", stage="profile", kind="points"):
        rows = _sample_rows(raw.shape[0], sample, seed)
        pts = raw[rows]
        cells = np.asarray(
            index_system.point_to_cell(pts, resolution)
        ).astype(np.int64)
        index_cells = np.asarray(chip_index.cells)
        U = index_cells.shape[0]
        if U:
            u = np.clip(np.searchsorted(index_cells, cells), 0, U - 1)
            matched = index_cells[u] == cells
        else:
            u = np.zeros(pts.shape[0], dtype=np.int64)
            matched = np.zeros(pts.shape[0], dtype=bool)
        n = max(1, pts.shape[0])
        match_rate = float(matched.sum()) / n
        um = u[matched]
        heavy = np.asarray(chip_index.cell_heavy)[um] >= 0
        convex = np.asarray(chip_index.cell_convex)[um] >= 0
        m = max(1, int(matched.sum()))
        shares = {
            "heavy": float(heavy.sum()) / m,
            "convex": float(convex.sum()) / m,
            "light": float((~heavy & ~convex).sum()) / m,
        }
        # chip_rows keeps every chip of every cell (heavy cells divert
        # their chips OUT of cell_slot_geom, which would undercount)
        chip_rows = np.asarray(chip_index.chip_rows)
        chips = (chip_rows[um] >= 0).sum(axis=1) if um.size else np.zeros(0)
        density = {
            "p50": float(np.percentile(chips, 50)) if chips.size else 0.0,
            "p90": float(np.percentile(chips, 90)) if chips.size else 0.0,
            "max": float(chips.max()) if chips.size else 0.0,
        }
        host = getattr(chip_index, "host", None)
        band_fraction = None
        if host is not None and matched.any():
            p = pts[matched] - host.shift
            d2 = _seg_dist2(p[:, 0], p[:, 1], host.cell_edges[um])
            thr = EDGE_BAND_K * float(np.finfo(np.float32).eps) * host.coord_scale
            band_fraction = float((d2 < thr * thr).sum()) / m
        prof = WorkloadProfile(
            kind="points",
            n_sampled=int(pts.shape[0]),
            n_total=int(raw.shape[0]),
            resolution=int(resolution),
            match_rate=match_rate,
            class_shares=shares,
            chip_density=density,
            band_fraction=band_fraction,
        )
        _telemetry.record("tune_profile", **_flat(prof))
        return prof


def profile_polygons(
    polygons,
    index_system,
    *,
    target_cells: float = 64.0,
    fraction: float = 1.0,
    limit: "int | None" = None,
) -> WorkloadProfile:
    """Profile a polygon set with `sql.analyzer.MosaicAnalyzer`: the
    data-driven resolution plus cells-per-geometry percentiles at that
    resolution."""
    from ..functions._coerce import to_packed
    from ..sql.analyzer import MosaicAnalyzer, SampleStrategy

    packed = to_packed(polygons)
    with _trace.span(
        "tune.profile", kind="polygons", n=len(packed)
    ), _telemetry.timed("tune_stage", stage="profile", kind="polygons"):
        analyzer = MosaicAnalyzer(index_system, target_cells=target_cells)
        strategy = SampleStrategy(fraction=fraction, limit=limit)
        res = analyzer.get_optimal_resolution(packed, strategy)
        at = analyzer.get_resolution_metrics(packed, strategy).get(res, {})
        prof = WorkloadProfile(
            kind="polygons",
            n_sampled=len(packed),
            n_total=len(packed),
            optimal_resolution=int(res),
            # analyzer keys are "<stat>_cells"; store the bare stat names
            cells_per_geom={
                k.rsplit("_", 1)[0]: float(v) for k, v in at.items()
            } or None,
        )
        _telemetry.record("tune_profile", **_flat(prof))
        return prof


def profile_overlay(
    left,
    right,
    index_system,
    resolution: int,
    *,
    left_chips=None,
    right_chips=None,
) -> WorkloadProfile:
    """Profile a polygon-polygon overlay join by CONSUMING the statistics
    `sql.overlay.candidate_pairs` already emits on its
    ``overlay.candidates`` span: the candidate count, the sure-fraction
    (pairs a core chip decides predicate-free), and the border-fraction
    (pairs that pay the exact ``st_intersects`` predicate). Border-heavy
    overlays are predicate-bound, and the recommender turns that into a
    finer-tessellation recommendation (`recommend.OVERLAY_BORDER_SHARE`).

    Pass prebuilt chip tables to amortize tessellation, exactly as
    `intersects_join` does."""
    from ..core.tessellate import tessellate
    from ..sql.overlay import candidate_pairs

    with _trace.span(
        "tune.profile", kind="overlay", resolution=int(resolution)
    ), _telemetry.timed("tune_stage", stage="profile", kind="overlay"):
        lt = (
            left_chips
            if left_chips is not None
            else tessellate(left, index_system, resolution)
        )
        rt = (
            right_chips
            if right_chips is not None
            else tessellate(right, index_system, resolution)
        )
        with _telemetry.capture() as events:
            candidate_pairs(lt, rt)
        stats = next(
            e for e in reversed(events)
            if e.get("event") == "overlay_candidates"
        )
        prof = WorkloadProfile(
            kind="overlay",
            n_sampled=int(stats["candidates"]),
            n_total=int(stats["candidates"]),
            resolution=int(resolution),
            sure_fraction=float(stats["sure_fraction"]),
            border_fraction=float(stats["border_fraction"]),
        )
        _telemetry.record("tune_profile", **_flat(prof))
        return prof


def profile_raster(
    raster,
    *,
    band: int = 1,
    tile: "tuple[int, int] | None" = None,
) -> WorkloadProfile:
    """Profile a raster: tile occupancy (mean valid-pixel share per tile)
    and the overall nodata fraction, from the same `stack_tiles` mask the
    zonal fold uses."""
    from ..raster.tiles import plan_tiles, stack_tiles

    with _trace.span(
        "tune.profile", kind="raster", band=int(band)
    ), _telemetry.timed("tune_stage", stage="profile", kind="raster"):
        plan = plan_tiles(raster, tile)
        _, mask = stack_tiles(raster, plan, band=band)
        per_tile = mask.reshape(mask.shape[0], -1).mean(axis=1)
        bm = raster.band(band).mask
        prof = WorkloadProfile(
            kind="raster",
            n_sampled=int(mask.shape[0]),
            n_total=int(mask.shape[0]),
            tile_occupancy=float(per_tile.mean()) if per_tile.size else 0.0,
            nodata_fraction=float(1.0 - bm.mean()) if bm.size else 1.0,
        )
        _telemetry.record("tune_profile", **_flat(prof))
        return prof


def _flat(prof: WorkloadProfile) -> dict:
    """Profile as flat telemetry fields (nested dicts stay readable)."""
    out = {}
    for k, v in prof.as_dict().items():
        if isinstance(v, dict):
            out.update({f"{k}_{kk}": vv for kk, vv in v.items()})
        else:
            out[k] = v
    return out
