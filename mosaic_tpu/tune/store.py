"""Versioned, checksummed `TuningProfile` persistence.

A profile lives NEXT TO the index artifacts it was tuned for — the pair
ships together, the pair hot-swaps together. The store is the
`runtime/checkpoint` discipline applied to a JSON document:

- one version = one ``profile-vNNNN.json`` written temp-first and
  ``os.replace``\\ d, with the payload's SHA-256 embedded over the
  canonical (sorted-keys) body — a kill mid-write leaves an orphaned temp
  file, never a half-written profile under the real name;
- :meth:`ProfileStore.load_latest` walks versions newest-first and skips
  corrupt entries with ``tune_profile_corrupt_skipped`` telemetry (same
  newest-valid-wins as snapshot resume); when EVERY version is damaged it
  raises the typed :class:`ProfileStoreCorrupt`;
- each profile records the **tessellation fingerprint** of the index it
  was tuned against (`runtime.checkpoint.fingerprint` over the sorted
  cell ids). Loading against a different index raises the typed
  :class:`ProfileFingerprintMismatch` — applying a profile tuned for
  another tessellation would silently mis-tune, so it is a refusal, not
  a skip.

Format (v1): ``{"version": 1, "profile_version": N, "sha256": hex,
"fingerprint": hex|None, "profile": TuningProfile.as_dict()}``. Readers
must reject a ``version`` they don't know.
"""

from __future__ import annotations

import hashlib
import json
import os
import re

import numpy as np

from ..runtime import checkpoint as _checkpoint
from ..runtime import telemetry as _telemetry
from ..runtime.errors import MosaicRuntimeError
from .recommend import TuningProfile

VERSION = 1
_PROFILE_RE = re.compile(r"^profile-v(\d{4})\.json$")


class ProfileStoreCorrupt(MosaicRuntimeError):
    """Every persisted profile version failed validation — the store
    cannot produce a profile. Rebuild with :meth:`ProfileStore.save`."""


class ProfileFingerprintMismatch(MosaicRuntimeError):
    """The newest valid profile was tuned for a DIFFERENT tessellation
    than the index being served — refusing to apply it. Re-profile the
    workload against the current index (or pass the matching index)."""


def index_fingerprint(chip_index) -> str:
    """The tessellation identity a profile binds to: the checkpoint
    fingerprint of the index's sorted cell-id column (resolution and
    geometry changes both change it)."""
    return _checkpoint.fingerprint(np.asarray(chip_index.cells))


def _body_sha256(payload: dict) -> str:
    body = {k: v for k, v in payload.items() if k != "sha256"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()
    ).hexdigest()


class ProfileStore:
    """Profile versions under one directory (conventionally the index
    artifact directory)."""

    def __init__(self, root: str):
        self.root = str(root)

    def _path(self, version: int) -> str:
        return os.path.join(self.root, f"profile-v{version:04d}.json")

    def versions(self) -> list[int]:
        """Persisted profile versions, ascending (validity unchecked)."""
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        return sorted(
            int(m.group(1))
            for m in (_PROFILE_RE.match(n) for n in names)
            if m
        )

    def save(
        self,
        profile: TuningProfile,
        *,
        fingerprint: "str | None" = None,
    ) -> str:
        """Persist ``profile`` as the next version; returns the path.
        ``fingerprint`` (from :func:`index_fingerprint`) binds the profile
        to its tessellation — pass it whenever the profile was tuned
        against a concrete index."""
        os.makedirs(self.root, exist_ok=True)
        version = (self.versions() or [0])[-1] + 1
        payload = {
            "version": VERSION,
            "profile_version": version,
            "fingerprint": fingerprint,
            "profile": profile.as_dict(),
        }
        payload["sha256"] = _body_sha256(payload)
        path = self._path(version)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True, indent=1)
        os.replace(tmp, path)
        _telemetry.record(
            "tune_profile_saved", root=self.root, profile_version=version,
            sha256=payload["sha256"][:12], fingerprint=(fingerprint or "")[:12],
        )
        return path

    def load_latest(
        self,
        *,
        expect_fingerprint: "str | None" = None,
    ) -> tuple[TuningProfile, dict]:
        """(profile, payload) of the newest VALID version.

        Corrupt versions (unparseable, unknown format version, checksum
        mismatch) are skipped with ``tune_profile_corrupt_skipped``
        telemetry; if nothing survives, :class:`ProfileStoreCorrupt`.
        When ``expect_fingerprint`` is given and the newest valid
        profile's recorded fingerprint differs,
        :class:`ProfileFingerprintMismatch` — a refusal, never a silent
        fallback to an older (potentially matching) version: versions are
        a history of ONE index's tuning, not a pool of candidates."""
        versions = self.versions()
        if not versions:
            raise ProfileStoreCorrupt(
                f"no tuning profile under {self.root!r} — save one with "
                f"ProfileStore.save"
            )
        for version in reversed(versions):
            path = self._path(version)
            try:
                with open(path) as f:
                    payload = json.load(f)
                if payload.get("version") != VERSION:
                    raise ValueError(
                        f"unknown profile format version "
                        f"{payload.get('version')!r}"
                    )
                if _body_sha256(payload) != payload.get("sha256"):
                    raise ValueError("content hash mismatch")
                profile = TuningProfile.from_dict(payload["profile"])
            except (OSError, ValueError, KeyError, TypeError) as e:
                _telemetry.record(
                    "tune_profile_corrupt_skipped", root=self.root,
                    profile_version=version, error=repr(e)[:200],
                )
                continue
            if (
                expect_fingerprint is not None
                and payload.get("fingerprint") != expect_fingerprint
            ):
                raise ProfileFingerprintMismatch(
                    f"profile v{version} under {self.root!r} was tuned for "
                    f"tessellation {str(payload.get('fingerprint'))[:12]}…, "
                    f"not the index being served "
                    f"({expect_fingerprint[:12]}…) — re-profile against "
                    f"the current index"
                )
            _telemetry.record(
                "tune_profile_loaded", root=self.root,
                profile_version=version,
            )
            return profile, payload
        raise ProfileStoreCorrupt(
            f"all {len(versions)} profile version(s) under {self.root!r} "
            f"failed validation — every candidate was skipped as corrupt"
        )
