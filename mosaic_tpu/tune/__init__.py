"""mosaic_tpu.tune — the self-tuning workload optimizer.

Profile a workload (`profiler.WorkloadProfile`), map it to knob
recommendations (`recommend.TuningProfile`), persist them next to the
index artifacts (`store.ProfileStore`), and hand the profile to any
frontend via ``profile=`` — resolved with one documented precedence
(`resolve`): explicit argument > env knob > profile > built-in default.

Import discipline: the frontends this package tunes import
``tune.resolve`` at module scope, so nothing here may import ``sql``/
``raster``/``serve`` back at module scope (the profiler pulls them
lazily inside its entry points).
"""

from __future__ import annotations

from .profiler import (
    WorkloadProfile,
    profile_overlay,
    profile_points,
    profile_polygons,
    profile_raster,
)
from .recommend import TuningProfile, load_priors, recommend
from .resolve import KNOBS, resolve_knob, resolve_knobs
from .store import (
    ProfileFingerprintMismatch,
    ProfileStore,
    ProfileStoreCorrupt,
    index_fingerprint,
)

__all__ = [
    "KNOBS",
    "ProfileFingerprintMismatch",
    "ProfileStore",
    "ProfileStoreCorrupt",
    "TuningProfile",
    "WorkloadProfile",
    "index_fingerprint",
    "load_priors",
    "profile_overlay",
    "profile_points",
    "profile_polygons",
    "profile_raster",
    "recommend",
    "resolve_knob",
    "resolve_knobs",
]
