"""Knob-precedence resolution: explicit arg > env knob > profile > default.

Every frontend that accepts ``profile=`` (`pip_join`, `StreamJoin`,
`ServeEngine`, `ZonalEngine`, `RasterStream`) funnels its profile-consumed
knobs through :func:`resolve_knobs` at the HOST entry point, before any
value is closed over by a jitted program — the same staging discipline as
`join.resolve_probe_mode` / `zonal.resolve_zonal_lane`, and the mosaic-lint
``env-read-after-staging`` rule keeps it machine-checked. The precedence is
the single documented order (ARCHITECTURE "Workload optimizer"):

    explicit argument  >  env knob  >  TuningProfile  >  built-in default

Knobs that already had an env spelling keep it (``MOSAIC_STREAM_WINDOW``,
``MOSAIC_STREAM_PIPELINE``, ``MOSAIC_RASTER_TILE``, ``MOSAIC_RASTER_LANE``);
tune-only knobs read the ``MOSAIC_TUNE_*`` family (``MOSAIC_TUNE_PROBE``,
``MOSAIC_TUNE_WRITEBACK``, ``MOSAIC_TUNE_LOOKUP``, ``MOSAIC_TUNE_BATCH``,
``MOSAIC_TUNE_BUCKET_MIN``, ``MOSAIC_TUNE_BUCKET_MAX``,
``MOSAIC_TUNE_KNN_LANE``). ``resolution`` has
deliberately NO env layer: it changes the tessellation artifact, not just
the execution schedule, so it only flows explicitly or via a profile.

Each entry-point call records ONE ``tune_resolve`` telemetry event naming
every resolved knob's value and source — the precedence tests assert on
that event, so the order is machine-checkable per frontend.
"""

from __future__ import annotations

import os

from ..runtime import telemetry as _telemetry


def _parse_bool(raw: str):
    return raw not in ("", "0")


def _parse_tile(raw: str):
    th, tw = (int(p) for p in raw.lower().split("x"))
    if th < 1 or tw < 1:
        raise ValueError(raw)
    return th, tw


#: tune-only knobs: profile field -> (MOSAIC_TUNE_ env suffix, parser)
_TUNE_ENV = {
    "probe": ("PROBE", str),
    "writeback": ("WRITEBACK", str),
    "lookup": ("LOOKUP", str),
    "batch_size": ("BATCH", int),
    "bucket_min": ("BUCKET_MIN", int),
    "bucket_max": ("BUCKET_MAX", int),
    "knn_lane": ("KNN_LANE", str),
}

#: knobs whose env spelling predates the tune subsystem (kept verbatim so
#: existing deployments keep working): profile field -> (reader, parser).
#: The readers keep the names as LITERAL os.environ.get calls so the
#: project-registry env scan (and hence the docs drift rule) still sees
#: every spelling.
_SHARED_ENV = {
    "stream_window": (
        lambda: os.environ.get("MOSAIC_STREAM_WINDOW"), int,
    ),
    "stream_pipeline": (
        lambda: os.environ.get("MOSAIC_STREAM_PIPELINE"), _parse_bool,
    ),
    "raster_tile": (
        lambda: os.environ.get("MOSAIC_RASTER_TILE"), _parse_tile,
    ),
    "zonal_lane": (
        lambda: os.environ.get("MOSAIC_RASTER_LANE"), str,
    ),
}

#: knobs with no env layer at all (artifact-changing, not schedule-changing)
_NO_ENV = frozenset({"resolution"})

KNOBS = tuple(sorted({*_TUNE_ENV, *_SHARED_ENV, *_NO_ENV}))


def _env_value(name: str):
    """The env layer's parsed value for one knob, or None when unset.
    Reads happen here — host resolution code, never traced — which is
    what keeps the ``env-read-after-staging`` lint rule green."""
    if name in _TUNE_ENV:
        suffix, parse = _TUNE_ENV[name]
        raw = os.environ.get(f"MOSAIC_TUNE_{suffix}")
    elif name in _SHARED_ENV:
        read, parse = _SHARED_ENV[name]
        raw = read()
    else:
        return None
    if raw is None or raw == "":
        return None
    try:
        return parse(raw)
    except (TypeError, ValueError) as exc:
        raise ValueError(
            f"malformed env value for tune knob {name!r}: {raw!r}"
        ) from exc


def resolve_knob(name: str, explicit, profile, default):
    """One knob through the precedence chain; returns ``(value, source)``
    with source in ``explicit|env|profile|default``. ``explicit=None``
    means "caller did not pass it" — frontends use None sentinels for
    exactly this reason."""
    if name not in KNOBS:
        raise KeyError(f"unknown tune knob {name!r} (expected one of {KNOBS})")
    if explicit is not None:
        return explicit, "explicit"
    env = _env_value(name)
    if env is not None:
        return env, "env"
    pval = getattr(profile, name, None) if profile is not None else None
    if pval is not None:
        return pval, "profile"
    return default, "default"


def resolve_knobs(entry: str, profile, *, explicit: dict, defaults: dict) -> dict:
    """Resolve every knob in ``explicit``/``defaults`` for one frontend
    entry point and record the single summarizing ``tune_resolve``
    telemetry event. Returns ``{knob: value}``."""
    values, sources = {}, {}
    for name, default in defaults.items():
        values[name], sources[name] = resolve_knob(
            name, explicit.get(name), profile, default
        )
    _telemetry.record(
        "tune_resolve",
        entry=entry,
        profiled=profile is not None,
        **{f"{k}_source": s for k, s in sources.items()},
        **{
            k: (v if isinstance(v, (int, float, bool, str, type(None))) else repr(v))
            for k, v in values.items()
        },
    )
    return values
