"""Durable raster scans: the tile twin of `sql/stream.py`.

A MODIS-scale zonal scan is minutes of device time over thousands of
tiles — long enough that device loss mid-scan is an operational
certainty, exactly the regime `StreamJoin.run_durable` was built for.
This module reuses that machinery for tiles: the scan runs in segments
of ``snapshot_every`` tiles, persisting the fold accumulators (count /
sum / min / max per zone, all f64-exact) and the tile cursor to a
checksummed snapshot (`runtime/checkpoint.py`) after each segment. Kill
the process anywhere and :meth:`RasterStream.resume` finishes the scan
— converging to a final fold bit-identical to the uninterrupted run,
because the accumulators snapshot exactly and the tile order (row-major
over the tile grid, the `raster/zonal.py` contract) is deterministic.

Resilience per segment matches the point stream: each device dispatch
sits under the ``raster.zonal`` watchdog deadline and the bounded
transient-retry budget; past the budget the segment's tiles degrade to
the f64 host twin (`host_zone_partial`), which is bit-identical to the
device partial, so degradation changes latency, never the answer.

Tracing: one ``raster.scan`` span per durable run, its context
persisted in every snapshot sidecar so a resume JOINS the killed run's
trace instead of starting a fresh one.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from ..dispatch import core as _dispatch, pipeline as _pipeline
from ..obs import trace as _trace
from ..runtime import (
    checkpoint as _checkpoint,
    telemetry as _telemetry,
)
from ..runtime.errors import RetryExhausted
from ..tune import resolve as _tune_resolve

__all__ = ["RasterScanResult", "RasterStream"]


def _zonal():
    """`raster/zonal.py`, imported lazily: that module composes on the
    join layer of THIS package, so a module-level import here would be
    a cycle (sql → raster_stream → raster.zonal → sql)."""
    from ..raster import tiles, zonal

    return tiles, zonal


@dataclasses.dataclass
class RasterScanResult:
    """One durable raster scan: the zonal fold + durability metrics
    (``snapshots`` written, ``degraded_tiles`` answered by the host
    twin, ``resumed_from`` tile cursor when this call was a resume)."""

    stats: "ZonalResult"  # noqa: F821 — resolved lazily, see _zonal()
    ntiles: int
    pixels: int
    wall_s: float
    pixels_per_sec: float
    metrics: dict = dataclasses.field(default_factory=dict)


class RasterStream:
    """Durable tiled zonal-statistics scans against one ChipIndex.

    Construction compiles nothing; the per-tile fold executables live
    in the wrapped :class:`~mosaic_tpu.raster.zonal.ZonalEngine` and are
    keyed by tile shape, so every raster with the same tile shape
    replays the same programs.
    """

    def __init__(
        self,
        chip_index,
        index_system,
        resolution: int,
        *,
        found_cap: "int | None" = None,
        heavy_cap: "int | None" = None,
        lookup: "str | None" = None,
        compaction: str = "scatter",
        probe: "str | None" = None,
        convex_cap: "int | None" = None,
        mesh=None,
        profile=None,
    ):
        # profile-consumed knobs fold at this host entry point: explicit
        # arg > env knob > profile > built-in default (tune/resolve.py);
        # the tile/window knobs resolve per scan, where they apply
        self._profile = profile
        knobs = _tune_resolve.resolve_knobs(
            "raster_stream", profile,
            explicit={"probe": probe, "lookup": lookup},
            defaults={"probe": "adaptive", "lookup": "gather"},
        )
        probe, lookup = knobs["probe"], knobs["lookup"]
        # the stream always folds on the f64-capable jnp lane — the
        # durable contract is bit-identity through kill/resume, and the
        # f32 Pallas lane only holds it on exact-summable data
        _tiles, zonal = _zonal()
        self.engine = zonal.ZonalEngine(
            index_system, resolution, chip_index=chip_index,
            found_cap=found_cap, heavy_cap=heavy_cap, lookup=lookup,
            compaction=compaction, probe=probe, convex_cap=convex_cap,
            lane="fold", mesh=mesh,
        )
        self.chip_index = chip_index
        self.index_system = index_system
        self.resolution = int(resolution)

    @property
    def num_zones(self) -> int:
        return self.engine.num_zones

    # -------------------------------------------------------------- API
    def scan(
        self,
        raster,
        *,
        band: int = 1,
        expr=None,
        tile: "tuple[int, int] | None" = None,
        run_dir: "str | None" = None,
        snapshot_every: int = 8,
        watchdog_default_s: float = 600.0,
        retry_policy=None,
        window: "int | None" = None,
    ) -> RasterScanResult:
        """Scan one band — or a fused expression tree over the band
        stack (``expr=``, `mosaic_tpu.expr`) — into per-zone (count,
        sum, min, max). With ``run_dir`` the scan is durable: interrupt
        anywhere and :meth:`resume` finishes it. Durable expression
        scans snapshot the tree's structural hash; resume refuses a
        different tree.

        Tiles ride the pipelined execution core
        (`dispatch/pipeline.py`): up to ``window`` tile folds are in
        flight at once (default: the ``MOSAIC_STREAM_WINDOW`` knob),
        so tile i's device fold overlaps tile i+1's host probe/patch —
        double-buffering for free. Accumulation and snapshots happen
        at the ordered drain, so the fold order (and therefore the
        result, bit for bit) is the synchronous loop's."""
        return self._run(
            raster, band=band, expr=expr, tile=tile, run_dir=run_dir,
            snapshot_every=int(snapshot_every), start_tile=0, acc0=None,
            resumed_from=None, watchdog_default_s=watchdog_default_s,
            retry_policy=retry_policy, trace_parent=None, window=window,
        )

    def resume(
        self,
        run_dir: str,
        raster,
        *,
        expr=None,
        watchdog_default_s: float = 600.0,
        retry_policy=None,
        window: "int | None" = None,
    ) -> RasterScanResult:
        """Restart an interrupted durable scan from the newest VALID
        snapshot under ``run_dir``. The snapshot's raster fingerprint,
        tile shape, band, zone count — and for expression scans the
        expression hash — must match: resuming a fold against different
        pixels OR a different tree would silently merge garbage."""
        loaded = _checkpoint.load_latest(run_dir)
        if loaded is None:
            raise FileNotFoundError(
                f"no valid snapshot under {run_dir!r} — nothing to resume"
            )
        step, arrays, meta = loaded
        want_fp = meta.get("raster_sha256")
        if want_fp and want_fp != _checkpoint.fingerprint(
            np.ascontiguousarray(raster.data)
        ):
            raise ValueError(
                "snapshot raster fingerprint mismatch — this is not "
                "the raster the interrupted scan was folding"
            )
        if int(meta.get("num_zones", self.num_zones)) != self.num_zones:
            raise ValueError(
                f"snapshot zone count {meta.get('num_zones')} != this "
                f"stream's {self.num_zones}"
            )
        want_expr = meta.get("expr_sha256")
        have_expr = None
        if expr is not None:
            from .. import expr as _expr  # lazy: see _zonal()

            have_expr = _expr.tree_hash(expr)
        if want_expr != have_expr:
            raise ValueError(
                "snapshot expression mismatch — the interrupted scan "
                f"folded tree {want_expr!r}, resume was given "
                f"{have_expr!r}; pass the same expression (structural "
                "equality) or none at all"
            )
        tile = tuple(meta["tile"]) if meta.get("tile") else None
        return self._run(
            raster, band=int(meta.get("band", 1)), expr=expr, tile=tile,
            run_dir=run_dir,
            snapshot_every=int(meta.get("snapshot_every", 8)),
            start_tile=int(step),
            acc0={k: np.asarray(v) for k, v in arrays.items()},
            resumed_from=int(step),
            watchdog_default_s=watchdog_default_s,
            retry_policy=retry_policy,
            trace_parent=_trace.SpanContext.from_dict(meta.get("trace")),
            window=window,
        )

    # ------------------------------------------------------------ engine
    def _run(
        self, raster, *, band, expr, tile, run_dir, snapshot_every,
        start_tile, acc0, resumed_from, watchdog_default_s,
        retry_policy, trace_parent, window=None,
    ) -> RasterScanResult:
        tiles, _zn = _zonal()
        # per-scan knobs: an explicit tile (or a resume's snapshot tile)
        # wins, then MOSAIC_RASTER_TILE / MOSAIC_STREAM_WINDOW, then the
        # constructor's TuningProfile, then the built-in defaults
        knobs = _tune_resolve.resolve_knobs(
            "raster_stream.scan", self._profile,
            explicit={"raster_tile": tile, "stream_window": window},
            defaults={"raster_tile": None, "stream_window": None},
        )
        tile, window = knobs["raster_tile"], knobs["stream_window"]
        plan = tiles.plan_tiles(raster, tile)
        th, tw = plan.shape
        g = self.num_zones
        snapshot_every = max(1, int(snapshot_every))
        root = _trace.start_span(
            "raster.scan",
            parent=trace_parent,
            ntiles=plan.ntiles, th=th, tw=tw, band=band,
            zones=g, resumed_from=resumed_from,
            fused=expr is not None,
        )
        try:
            return self._run_traced(
                raster, plan=plan, band=band, expr=expr,
                run_dir=run_dir,
                snapshot_every=snapshot_every, start_tile=start_tile,
                acc0=acc0, resumed_from=resumed_from,
                watchdog_default_s=watchdog_default_s,
                retry_policy=retry_policy, root=root, window=window,
            )
        except BaseException as e:  # noqa: BLE001 — stamped, re-raised
            root.set(error=type(e).__name__)
            raise
        finally:
            root.end()

    def _run_traced(
        self, raster, *, plan, band, expr, run_dir, snapshot_every,
        start_tile, acc0, resumed_from, watchdog_default_s,
        retry_policy, root, window=None,
    ) -> RasterScanResult:
        tiles, zonal = _zonal()
        th, tw = plan.shape
        g = self.num_zones
        eng = self.engine
        expr_sha = None
        if expr is None:
            vals, mask = tiles.stack_tiles(
                raster, plan, band, dtype=np.float64
            )
        else:
            # fused expression scan: stage the whole referenced band
            # stack; per tile ONE program computes the tree and folds it
            from .. import expr as _expr  # lazy: see _zonal()
            from ..expr import compile as _ec, eval as _ee

            value, kind, by, _stats = _expr.terminal_of(expr)
            if kind != "zonal" or (by or "zones") != "zones":
                raise ValueError(
                    "RasterStream.scan(expr=...) folds zones — use a "
                    "zones zonal terminal (or a bare value tree)"
                )
            _expr.validate(
                expr, raster.num_bands, has_zones=True, by="zones"
            )
            expr_sha = _expr.tree_hash(expr)
            expr_bands = _expr.bands_of(value)
            vals, mask = _ee._stack_bands(raster, plan, expr_bands)
            acc_name = str(np.dtype(eng.acc_dtype).name)
            expr_prog = _ec.zonal_program(
                value, th, tw, g, acc_name,
                eng.index_system, eng.resolution,
            )
            expr_sig = _ec.signature_of(
                value, th, tw, g, acc_name,
                eng.index_system, eng.resolution, eng.mesh,
            )
            band = 0  # snapshot meta: fused scans read the stack
        if acc0 is None:
            cnt_acc = np.zeros(g, np.int64)
            sum_acc = np.zeros(g, np.float64)
            min_acc = np.full(g, np.inf)
            max_acc = np.full(g, -np.inf)
        else:
            cnt_acc = np.asarray(acc0["count"], np.int64).copy()
            sum_acc = np.asarray(acc0["sum"], np.float64).copy()
            min_acc = np.asarray(acc0["min"], np.float64).copy()
            max_acc = np.asarray(acc0["max"], np.float64).copy()
        meta = None
        if run_dir is not None:
            meta = {
                "ntiles": plan.ntiles,
                "tile": [th, tw],
                "band": int(band),
                "num_zones": g,
                "snapshot_every": int(snapshot_every),
                "raster_sha256": _checkpoint.fingerprint(
                    np.ascontiguousarray(raster.data)
                ),
                "expr_sha256": expr_sha,
                "trace": root.context.as_dict(),
            }
        host = getattr(self.chip_index, "host", None)
        degraded = [0]
        counters = {"snapshots": 0}
        start = int(start_tile)
        win = _pipeline.resolve_window(window)

        # tiles ride the pipelined execution core: launch dispatches
        # tile t's fold WITHOUT the blocking pull (the probe's host
        # patch still completes here — it is host work by construction),
        # the ordered drain pulls the partials under the watchdog and
        # the caller-thread commit accumulates them, so the fold
        # order — and therefore the float result, bit for bit — is the
        # synchronous loop's. Fault plans trip inside the launch guard
        # (the watchdog runs maybe_fail under the retry wrapper):
        # transient errors retry/degrade, non-transient ones abort.
        def launch(i):
            t = start + i

            if expr is None:
                def dispatch(t=t):
                    return eng._tile_zone_stats_async(
                        plan, t, vals[t].reshape(-1),
                        mask[t].reshape(-1),
                    )
            else:
                def dispatch(t=t):
                    # probe + epsilon patch, then the fused
                    # expression+fold program — one launch
                    geom = eng._tile_zone_rows(plan, t)
                    seg = np.where(
                        geom >= 0, geom, -1
                    ).astype(np.int32)
                    return _ec.run_zonal_async(
                        expr_prog, expr_sig,
                        np.asarray(plan.gt, np.float64),
                        plan.origins[t], vals[t], mask[t], seg,
                    )

            with _trace.span(
                "raster.zonal", step=t, n=1, pipelined=True
            ):
                try:
                    return ("dev", _dispatch.guarded_call(
                        "raster.zonal", dispatch,
                        default_s=watchdog_default_s,
                        policy=retry_policy,
                    ))
                except RetryExhausted as e:
                    if host is None:
                        raise
                    _telemetry.record(
                        "degraded", label="raster.zonal", step=t,
                        attempts=e.attempts,
                        error=repr(e.last)[:200],
                    )
                    if expr is None:
                        return ("host", zonal.host_zone_partial(
                            zonal.host_tile_centers(plan, t),
                            vals[t].reshape(-1),
                            mask[t].reshape(-1),
                            host, self.index_system,
                            self.resolution, g,
                        ))
                    return ("host", _expr.host_expr_tile_partial(
                        value, vals[t], mask[t],
                        zonal.host_tile_centers(plan, t),
                        index_system=self.index_system,
                        resolution=self.resolution,
                        host=host, num_segments=g,
                        by="zones",
                    ))

        def land(i, handle):
            # runs under the drain watchdog, whose deadline ABANDONS
            # the worker thread — pull ALL four partials here and
            # mutate nothing, so a worker finishing late changes
            # nothing and a mid-pull transient replays a tile whose
            # effects were never applied
            kind, (cnt, s, mn, mx) = handle
            return (
                kind,
                np.asarray(cnt, np.int64),  # blocks: the drain's pull
                np.asarray(s, np.float64),
                np.asarray(mn, np.float64),
                np.asarray(mx, np.float64),
            )

        def commit(i, pulled):
            nonlocal cnt_acc, sum_acc
            kind, cnt, s, mn, mx = pulled
            if kind == "host":
                # degradation counts at materialization, not launch —
                # a degraded in-flight tile later discarded by a
                # transient is re-run (and counted once) by the replay
                degraded[0] += 1
            live = cnt > 0
            cnt_acc += cnt
            sum_acc = sum_acc + s
            min_acc[live] = np.minimum(min_acc[live], mn[live])
            max_acc[live] = np.maximum(max_acc[live], mx[live])
            se = start + i + 1
            # the snapshot write runs here on the caller thread —
            # outside the drain-watchdog deadline, like the
            # synchronous loop — and swallows its own failures, so
            # nothing after the accumulator fold can raise a
            # transient that would replay (and double-count) the tile
            if run_dir is not None and (
                (se - start) % snapshot_every == 0 or se == plan.ntiles
            ):
                payload = {
                    "count": cnt_acc, "sum": sum_acc,
                    "min": min_acc, "max": max_acc,
                }
                with _trace.span("raster.snapshot", step=se):
                    try:
                        _checkpoint.save_snapshot(
                            run_dir, se, payload, meta
                        )
                        counters["snapshots"] += 1
                    except Exception as e:  # lint: broad-except-ok (durability degrades — coarser resume point — but a sick disk must not kill the scan)
                        _telemetry.record(
                            "snapshot_skipped", run_dir=run_dir,
                            step=se, error=repr(e)[:200],
                        )

        def replay(lo, hi):
            # tiles carry no cross-tile device state, so the
            # synchronous path IS launch + pull + commit — the full
            # guarded retry/degradation budget applies per tile
            for j in range(lo, hi + 1):
                commit(j, land(j, launch(j)))

        t0 = time.perf_counter()
        pstats = _pipeline.execute_pipeline(
            plan.ntiles - start, launch, land,
            drain_site="raster.pipeline.drain", commit=commit,
            replay=replay, window=win,
            watchdog_default_s=watchdog_default_s,
        )
        degraded_tiles = degraded[0]
        snapshots = counters["snapshots"]
        wall = time.perf_counter() - t0
        n_run = plan.ntiles - int(start_tile)
        px_run = n_run * th * tw
        _telemetry.record(
            "raster_stage", stage="scan",
            seconds=round(wall, 6), ntiles=plan.ntiles,
            th=th, tw=tw, zones=g, snapshots=snapshots,
            degraded_tiles=degraded_tiles, resumed_from=resumed_from,
            window=pstats.window,
            pixels_per_sec=round(px_run / max(wall, 1e-9), 1),
        )
        live = cnt_acc > 0
        stats = zonal.ZonalResult(
            keys=np.nonzero(live)[0].astype(np.int64),
            count=cnt_acc[live],
            sum=sum_acc[live],
            min=min_acc[live],
            max=max_acc[live],
            band=band,
            pixels=int(cnt_acc.sum()),
        )
        return RasterScanResult(
            stats=stats,
            ntiles=plan.ntiles,
            pixels=plan.pixels,
            wall_s=wall,
            pixels_per_sec=px_run / max(wall, 1e-9),
            metrics={
                "degraded": degraded_tiles > 0,
                "degraded_tiles": degraded_tiles,
                "snapshots": snapshots,
                "resumed_from": resumed_from,
                "run_dir": run_dir,
                "pipeline": pstats.as_dict(),
            },
        )

