"""Polygon-polygon overlay join: device candidates + fused overlap measures.

Reference analog: the BNG overlay workload
(`notebooks/examples/python/BritishNationalGrid.py`) — both polygon tables
are tessellated into grid chips, the equi-join on cell id produces candidate
pairs, and the exact work runs only on pairs whose chips are both border
chips (a core chip covers its whole cell, so any other geometry touching
that cell intersects it by construction — the chip-table shortcut the
reference's `is_core || st_intersects` predicate expresses).

Two lanes share one contract:

- **Device lane** (:func:`overlay_measures`): both chip tables are sorted
  by int64 cell id once (:func:`prepare_overlay`, amortized like the chip
  index build), candidate generation runs on device as a sorted segment
  equi-join (`kernels.overlay.pair_count` / `emit_pairs`) against a static
  pair bucket, and the overlap measures — per-pair intersection area via
  batched Sutherland–Hodgman clip, folded per geometry pair, with an
  `expr/` pair tree evaluated over the folded tables — run as ONE fused
  program per ``(tree-hash, buckets, index, mesh)`` signature through
  `DispatchCore` (compile cache, warmup tripwire, watchdog/retry,
  ``mesh=`` sharding, graceful degradation). Near-degenerate clip areas
  (inside the ``EDGE_BAND_K·eps(acc)·scale²`` band), non-convex windows,
  multi-ring/over-pad chips and spills are re-answered by the f64 host
  lane per WHOLE geometry pair, so the accelerated dtype never decides a
  contact case.
- **Host lane** (`expr.host_oracle.host_overlay_measures`): the numpy twin
  of the same kernels (``xp=np``) — the pure-f64 oracle the device lane
  must match bitwise under x64, and the degradation target when the
  device path fails past its retry budget.

Caps are full-bucket and structural: when the candidate count exceeds
``pair_cap`` (or the top pair bucket), the emission truncates and the
result carries an OVERFLOW(-2) pair row — never a silent wrong answer,
never an escalation.

The boolean `ST_Intersects` join (:func:`intersects_join`) keeps its host
columnar candidate generator, now deduplicated by geometry pair
(core-beats-border precedence) so a pair sharing N cells is emitted once.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core.index.base import IndexSystem
from ..core.tessellate import ChipTable, _dedupe_boundaries_batch, tessellate
from ..core.types import GeometryType, PackedGeometry
from ..dispatch import core as _dispatch
from ..kernels import overlay as _k
from ..obs import trace as _trace
from ..runtime import telemetry as _telemetry
from ..runtime.errors import DegradedResult
from .join import EDGE_BAND_K, OVERFLOW

__all__ = [
    "MAX_CHIP_VERTS",
    "OverlayMeasures",
    "OverlayPrep",
    "OverlaySide",
    "candidate_pairs",
    "chip_candidate_rows",
    "intersects_join",
    "overlay_join",
    "overlay_measures",
    "pair_glue",
    "pair_plan",
    "prepare_overlay",
    "warmup_overlay",
]

#: vertex pad ceiling for device-clippable chips — a border chip whose
#: outer ring needs more vertices is routed to the f64 host lane (the
#: pad enters the program signature, so it must stay small and stable)
MAX_CHIP_VERTS = 32

#: candidate-pair bucket ladder: min 8 so tiny caps exercise OVERFLOW
#: semantics without a dedicated program population, top bucket 4M pairs
PAIR_LADDER = _dispatch.BucketLadder(min_bucket=8, max_bucket=1 << 22)

#: sorted side-table ladder (chip rows) and geometry-pair segment ladder
TABLE_LADDER = _dispatch.BucketLadder(min_bucket=64, max_bucket=1 << 21)
SEG_LADDER = _dispatch.BucketLadder(min_bucket=64, max_bucket=1 << 21)


def _acc_name() -> str:
    """Accelerated fold dtype — f64 under x64 (the CPU oracle contract),
    f32 on accelerators without it (the epsilon band covers the gap)."""
    return "float64" if jax.config.jax_enable_x64 else "float32"


def pair_plan(total: int, pair_cap: int | None = None):
    """``(Pb, emit_limit, overflow)`` for a candidate count — full-bucket
    cap semantics: emission truncates at ``min(total, pair_cap, top
    bucket)`` and the remainder is booked as structural OVERFLOW."""
    total = int(total)
    cap = PAIR_LADDER.max_bucket if pair_cap is None else int(pair_cap)
    emit_limit = min(total, cap, PAIR_LADDER.max_bucket)
    Pb = PAIR_LADDER.bucket_for(max(emit_limit, 1))
    return Pb, emit_limit, total - emit_limit


# ------------------------------------------------ host candidate columns


def _group_spans(cells_sorted: np.ndarray):
    """(uniq, start, stop) run-length spans of a sorted int64 array."""
    if not cells_sorted.shape[0]:
        return (
            np.zeros(0, np.int64),
            np.zeros(0, np.int64),
            np.zeros(0, np.int64),
        )
    change = np.nonzero(np.diff(cells_sorted))[0] + 1
    start = np.concatenate([[0], change])
    stop = np.concatenate([change, [cells_sorted.shape[0]]])
    return cells_sorted[start], start, stop


def chip_candidate_rows(
    left: ChipTable, right: ChipTable
) -> tuple[np.ndarray, np.ndarray]:
    """Raw chip-row candidate pairs sharing a cell (host columnar set
    algebra). A geometry pair sharing N cells appears N times here — the
    per-shared-cell stream the area fold consumes; use
    :func:`candidate_pairs` for the deduplicated geometry-pair view."""
    lc = np.asarray(left.cell_id)
    rc = np.asarray(right.cell_id)
    lo = np.argsort(lc, kind="stable")
    ro = np.argsort(rc, kind="stable")
    lu, ls, le_ = _group_spans(lc[lo])
    ru, rs, re_ = _group_spans(rc[ro])
    common, li, ri = np.intersect1d(lu, ru, return_indices=True)
    if not common.shape[0]:
        z = np.zeros(0, np.int64)
        return z, z
    # vectorized per-cell cross join: left rows repeat by the right
    # group size, right rows tile within each (cell, left-row) block
    ln = le_[li] - ls[li]  # left group size per common cell
    rn = re_[ri] - rs[ri]  # right group size per common cell
    pair_n = ln * rn
    cell_of = np.repeat(np.arange(common.shape[0]), pair_n)
    off = np.concatenate([[0], np.cumsum(pair_n)])[:-1]
    k = np.arange(int(pair_n.sum())) - off[cell_of]  # rank within cell
    lrows = lo[ls[li][cell_of] + k // rn[cell_of]]
    rrows = ro[rs[ri][cell_of] + k % rn[cell_of]]
    return lrows, rrows


def _dedup_pairs(left: ChipTable, right: ChipTable,
                 lrows: np.ndarray, rrows: np.ndarray):
    """Chip-row candidates → unique geometry pairs with core-beats-border
    precedence: ``sure[p]`` is True when ANY shared cell of pair ``p``
    has a core chip on either side (intersection certain there, no
    predicate needed anywhere for the pair)."""
    lgeom = np.asarray(left.geom_id)[lrows]
    rgeom = np.asarray(right.geom_id)[rrows]
    either = (
        np.asarray(left.is_core)[lrows] | np.asarray(right.is_core)[rrows]
    )
    uniq, pair_id = np.unique(
        np.stack([lgeom, rgeom], axis=-1), axis=0, return_inverse=True
    )
    sure = np.zeros(uniq.shape[0], bool)
    np.logical_or.at(sure, pair_id, either)
    return uniq, pair_id, either, sure


def _candidate_stats(span, sure: np.ndarray) -> None:
    """Record the profileable candidate statistics (deduplicated
    geometry-pair counts) on the span and the telemetry stream."""
    n = int(sure.shape[0])
    sure_fraction = float(sure.sum()) / max(1, n)
    stats = {
        "candidates": n,
        "sure_fraction": round(sure_fraction, 6),
        "border_fraction": round(1.0 - sure_fraction, 6),
    }
    span.set(**stats)
    _telemetry.record("overlay_candidates", **stats)


def candidate_pairs(
    left: ChipTable, right: ChipTable
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Deduplicated geometry-pair candidates sharing at least one cell.

    Returns ``(lgeom, rgeom, sure)`` — one row per (left geometry, right
    geometry) pair regardless of how many cells the pair shares, with
    ``sure`` True where some shared cell has a core chip on either side
    (core beats border: the pair is accepted without a predicate).

    Emits an ``overlay.candidates`` span (and matching
    ``overlay_candidates`` telemetry) with the candidate count, the
    sure-fraction (pairs accepted without a predicate), and the
    border-pair fraction (pairs that will pay exact work) — the
    statistics that make overlay workloads profileable like the point
    frontends.
    """
    with _trace.span(
        "overlay.candidates",
        left_chips=int(np.asarray(left.cell_id).shape[0]),
        right_chips=int(np.asarray(right.cell_id).shape[0]),
    ) as span:
        lrows, rrows = chip_candidate_rows(left, right)
        if not lrows.shape[0]:
            _candidate_stats(span, np.zeros(0, bool))
            z = np.zeros(0, np.int64)
            return z, z, np.zeros(0, bool)
        uniq, _, _, sure = _dedup_pairs(left, right, lrows, rrows)
        _candidate_stats(span, sure)
        return uniq[:, 0], uniq[:, 1], sure


def intersects_join(
    left: PackedGeometry,
    right: PackedGeometry,
    index_system: IndexSystem,
    resolution: int,
    left_chips: ChipTable | None = None,
    right_chips: ChipTable | None = None,
    backend: str = "oracle",
) -> np.ndarray:
    """(P, 2) int64 — distinct (left_row, right_row) pairs that intersect.

    Both sides tessellate at ``resolution`` (pass prebuilt chip tables to
    amortize); pairs sharing a cell where either chip is core are accepted
    without a predicate, the rest run one row-wise st_intersects over the
    border-chip geometry pairs (chips are clipped to their cell, so
    chip-level intersection within a shared cell is exact for the
    geometry-level predicate). Refinement defaults to the f64 ``oracle``
    backend — exact boundary touches (shared edges) are below f32
    resolution; pass ``backend="device"`` to trade that edge case for
    batched device evaluation of huge pair lists.

    Known degenerate case (cell-equality joins generally, including the
    reference's): a pair whose intersection has zero area and lies
    EXACTLY on a cell boundary of an axis-aligned grid (BNG/CUSTOM) can
    tessellate into disjoint cell sets and produce no candidate.
    """
    lt = (
        left_chips
        if left_chips is not None
        else tessellate(left, index_system, resolution)
    )
    rt = (
        right_chips
        if right_chips is not None
        else tessellate(right, index_system, resolution)
    )
    with _trace.span(
        "overlay.candidates",
        left_chips=int(np.asarray(lt.cell_id).shape[0]),
        right_chips=int(np.asarray(rt.cell_id).shape[0]),
    ) as span:
        lrows, rrows = chip_candidate_rows(lt, rt)
        if not lrows.shape[0]:
            _candidate_stats(span, np.zeros(0, bool))
            return np.zeros((0, 2), np.int64)
        uniq_pairs, pair_id, either, psure = _dedup_pairs(
            lt, rt, lrows, rrows
        )
        _candidate_stats(span, psure)
    hit = either.copy()
    # a geometry pair already accepted via a core chip in ANY shared cell
    # needs no predicate for its remaining border-border candidates
    need = np.nonzero(~either & ~psure[pair_id])[0]
    degraded: DegradedResult | None = None
    if need.shape[0]:
        from ..functions.geometry import st_intersects

        # every undecided candidate chip pair is evaluated: a geometry
        # pair intersects iff ANY of its shared-cell chip pairs does
        a = lt.chips.take(lrows[need])
        b = rt.chips.take(rrows[need])

        def predicate():
            return np.asarray(st_intersects(a, b, backend=backend))

        # transient device failures retry with backoff; past the budget a
        # non-oracle backend degrades to the exact f64 host oracle (result
        # flagged), an oracle run raises typed RetryExhausted — the
        # watchdog/retry composition (and the "overlay.predicate" fault
        # plan) lives in dispatch.guarded_call
        res = _dispatch.guarded_call(
            "overlay.predicate",
            predicate,
            fallback=(
                (lambda: np.asarray(st_intersects(a, b, backend="oracle")))
                if backend != "oracle"
                else None
            ),
        )
        if isinstance(res, DegradedResult):
            degraded = res
        hit[need] = np.asarray(res)
    pairs = uniq_pairs[np.unique(pair_id[hit])]
    if degraded is not None:
        return DegradedResult.wrap(
            pairs, reason=degraded.reason, attempts=degraded.attempts,
        )
    return pairs


#: the managed overlay entry point under its workload name (the BNG
#: overlay notebook's join) — same callable, resilience included
overlay_join = intersects_join


# ------------------------------------------------------- device-lane prep


@dataclass(frozen=True)
class OverlaySide:
    """One cell-sorted, bucket-padded side table of an overlay prep.

    All per-row arrays are in sorted-by-cell order, padded to ``bucket``
    rows (pad cells carry a per-side sentinel that sorts above every
    real cell and can never equi-join the other side's sentinel).
    ``rows`` maps sorted row → original chip row for the host override
    lane; ``geom_area`` is indexed by ORIGINAL geometry id.
    """

    table: ChipTable
    n: int
    bucket: int
    cells: np.ndarray      # (Lb,) i64 sorted ascending, sentinel tail
    geom: np.ndarray       # (Lb,) i64 geometry id, -1 pad
    core: np.ndarray       # (Lb,) bool
    ok_subj: np.ndarray    # (Lb,) bool device-clippable as clip SUBJECT
    ok_win: np.ndarray     # (Lb,) bool device-clippable as clip WINDOW
    verts: np.ndarray      # (Lb, V, 2) f64 shifted CCW open rings
    vlen: np.ndarray       # (Lb,) i32 left-packed vertex counts
    chip_area: np.ndarray  # (Lb,) f64 |chip| (core rows: the cell area)
    cell_area: np.ndarray  # (Lb,) f64 area of the row's cell
    rows: np.ndarray       # (n,) i64 sorted row -> original chip row
    geom_area: np.ndarray  # (G,) f64 |geometry| (shifted frame)


@dataclass(frozen=True)
class OverlayPrep:
    """Amortized overlay prep: both sorted side tables plus the shared
    coordinate frame (``shift``/``scale``), the accelerated fold dtype,
    the epsilon-band threshold in area units and the vertex pad — every
    static piece of the fused program's signature."""

    left: OverlaySide
    right: OverlaySide
    shift: np.ndarray
    scale: float
    index_system: IndexSystem
    resolution: int
    acc_name: str
    band: float
    vpad: int


def _csr_geom_areas(col: PackedGeometry, shift: np.ndarray) -> np.ndarray:
    """(G,) f64 polygon areas (|shells| − |holes|), vectorized over the
    CSR offsets — the columnar twin of `core.geometry.oracle.area`
    (shell = first ring of its part, open rings, wraparound shoelace).
    Non-polygon rows report 0.0; coordinates are shifted first so the
    table is computed in the same frame the clip kernels run in."""
    G = len(col)
    out = np.zeros(G, np.float64)
    nv = int(np.asarray(col.xy).shape[0])
    if not G or not nv:
        return out
    x = np.asarray(col.xy[:, 0], np.float64) - float(shift[0])
    y = np.asarray(col.xy[:, 1], np.float64) - float(shift[1])
    ro = np.asarray(col.ring_offsets, np.int64)
    po = np.asarray(col.part_offsets, np.int64)
    go = np.asarray(col.geom_offsets, np.int64)
    R = ro.shape[0] - 1
    ring_of = np.repeat(np.arange(R), np.diff(ro))
    nxt = np.arange(nv) + 1
    nxt = np.where(nxt == ro[1:][ring_of], ro[:-1][ring_of], nxt)
    ring_area = np.zeros(R, np.float64)
    np.add.at(ring_area, ring_of, x * y[nxt] - x[nxt] * y)
    ring_area *= 0.5
    part_of_ring = np.repeat(np.arange(po.shape[0] - 1), np.diff(po))
    is_shell = np.arange(R) == po[:-1][part_of_ring]
    signed = np.where(is_shell, np.abs(ring_area), -np.abs(ring_area))
    geom_of_part = np.repeat(np.arange(G), np.diff(go))
    np.add.at(out, geom_of_part[part_of_ring], signed)
    gt = np.asarray(col.geom_type, np.int64)
    base = np.where(gt > 3, gt - 3, gt)
    return np.where(base == int(GeometryType.POLYGON), out, 0.0)


def _masked_shoelace(verts: np.ndarray, vlen: np.ndarray) -> np.ndarray:
    """(N,) f64 signed shoelace areas of left-packed open rings."""
    x, y = verts[:, :, 0], verts[:, :, 1]
    j = np.arange(verts.shape[1])[None, :]
    nxt = np.where(j + 1 < vlen[:, None], j + 1, 0)
    xn = np.take_along_axis(x, nxt, axis=1)
    yn = np.take_along_axis(y, nxt, axis=1)
    contrib = np.where(j < vlen[:, None], x * yn - xn * y, 0.0)
    return 0.5 * contrib.sum(axis=1)


def _chip_analysis(table: ChipTable):
    """Per-chip-row CSR facts: ``(simple, r0s, r0l)`` — device-clippable
    shape class (single-part single-ring polygon with a stored geometry)
    plus its outer ring span."""
    ch = table.chips
    C = len(ch)
    if not C:
        z = np.zeros(0, np.int64)
        return np.zeros(0, bool), z, z
    has = np.asarray(table.has_geom, bool)
    go = np.asarray(ch.geom_offsets, np.int64)
    po = np.asarray(ch.part_offsets, np.int64)
    ro = np.asarray(ch.ring_offsets, np.int64)
    gt = np.asarray(ch.geom_type, np.int64)
    nparts = np.diff(go)
    nrings = po[go[1:]] - po[go[:-1]]
    fr = np.minimum(po[go[:-1]], max(ro.shape[0] - 2, 0))
    r0s = ro[fr]
    r0l = ro[fr + 1] - r0s
    base = np.where(gt > 3, gt - 3, gt)
    simple = (
        has
        & (base == int(GeometryType.POLYGON))
        & (nparts == 1)
        & (nrings == 1)
        & (r0l >= 3)
    )
    return simple, r0s, r0l


def _side_verts(table: ChipTable, simple, r0s, r0l, V: int,
                shift: np.ndarray, scale: float):
    """(eligible, ok_win, verts, vlen) in original chip-row order —
    left-packed CCW shifted outer rings padded by repeating the last
    vertex, plus the convex-window eligibility flag."""
    C = len(table.chips)
    if not C:
        return (
            np.zeros(0, bool), np.zeros(0, bool),
            np.zeros((0, V, 2), np.float64), np.zeros(0, np.int32),
        )
    eligible = simple & (r0l <= V)
    xy = np.asarray(table.chips.xy, np.float64)
    safe_len = np.maximum(r0l, 1)
    idx = r0s[:, None] + np.minimum(np.arange(V)[None, :],
                                    safe_len[:, None] - 1)
    idx = np.clip(idx, 0, max(xy.shape[0] - 1, 0))
    verts = xy[idx]
    vlen = np.where(eligible, r0l, 0).astype(np.int32)
    # orient CCW (reverse the valid prefix where the ring is CW)
    sa = _masked_shoelace(verts, vlen)
    j = np.arange(V)[None, :]
    rev = np.where(j < vlen[:, None],
                   np.maximum(vlen[:, None] - 1 - j, 0), j)
    flipped = np.take_along_axis(verts, rev[:, :, None], axis=1)
    verts = np.where((sa < 0)[:, None, None], flipped, verts)
    verts = verts - np.asarray(shift, np.float64)[None, None, :]
    # convex-window test on the oriented, shifted ring: every pair of
    # consecutive edges turns left (cross ≥ -tol), wraparound included
    nxt = np.where(j + 1 < vlen[:, None], j + 1, 0)
    nxy = np.take_along_axis(verts, nxt[:, :, None], axis=1)
    e = nxy - verts
    en = np.take_along_axis(e, nxt[:, :, None], axis=1)
    cross = e[:, :, 0] * en[:, :, 1] - e[:, :, 1] * en[:, :, 0]
    tol = _k.CLIP_EPS * scale * scale
    convex = np.all(
        np.where(j < vlen[:, None], cross, 0.0) >= -tol, axis=1
    )
    return eligible, eligible & convex, verts, vlen


def prepare_overlay(
    left_chips: ChipTable,
    right_chips: ChipTable,
    left: PackedGeometry,
    right: PackedGeometry,
    index_system: IndexSystem,
    resolution: int,
) -> OverlayPrep:
    """Build the amortized device-lane prep for an overlay table pair.

    One host pass per table pair: sort both chip tables by cell id, pad
    to ladder buckets with per-side sentinels, precompute the f64 area
    tables (chip, cell, whole-geometry — all in a shared shifted frame
    centered on the data so the f32 lane keeps maximal mantissa), pack
    the device-clippable outer rings to the vertex pad, and derive the
    epsilon-band threshold. Everything here is reused across measures,
    caps and meshes — only the fused program varies per signature.
    """
    with _trace.span(
        "overlay.prepare",
        left_chips=int(np.asarray(left_chips.cell_id).shape[0]),
        right_chips=int(np.asarray(right_chips.cell_id).shape[0]),
    ):
        lcells_raw = np.asarray(left_chips.cell_id, np.int64)
        rcells_raw = np.asarray(right_chips.cell_id, np.int64)
        ucells = np.unique(np.concatenate([lcells_raw, rcells_raw]))
        if ucells.shape[0]:
            bnds = np.asarray(
                index_system.cell_boundary(ucells), np.float64
            )
        else:
            bnds = np.zeros((0, 4, 2), np.float64)
        lxy = np.asarray(left_chips.chips.xy, np.float64).reshape(-1, 2)
        rxy = np.asarray(right_chips.chips.xy, np.float64).reshape(-1, 2)
        allxy = np.concatenate([lxy, rxy, bnds.reshape(-1, 2)], axis=0)
        if allxy.shape[0]:
            lo, hi = allxy.min(axis=0), allxy.max(axis=0)
            shift = 0.5 * (lo + hi)
            scale = float(max(1.0, float(np.max(np.abs(allxy - shift)))))
        else:
            shift = np.zeros(2, np.float64)
            scale = 1.0
        cell_polys, klen = _dedupe_boundaries_batch(bnds)
        ucell_area = np.abs(_masked_shoelace(
            cell_polys - shift[None, None, :], klen.astype(np.int64)
        ))

        lsimple, lr0s, lr0l = _chip_analysis(left_chips)
        rsimple, rr0s, rr0l = _chip_analysis(right_chips)

        def _border_max(table, simple, r0l):
            m = simple & ~np.asarray(table.is_core, bool)
            return int(r0l[m].max()) if m.any() else 0

        V = int(min(MAX_CHIP_VERTS, max(
            4,
            _border_max(left_chips, lsimple, lr0l),
            _border_max(right_chips, rsimple, rr0l),
        )))

        acc = _acc_name()
        band = (
            EDGE_BAND_K * float(np.finfo(np.dtype(acc)).eps)
            * scale * scale
        )

        def _side(table, col, cells_raw, simple, r0s, r0l, pad_cell):
            n = int(cells_raw.shape[0])
            order = np.argsort(cells_raw, kind="stable")
            Lb = TABLE_LADDER.bucket_for(max(n, 1))
            elig, ok_win, verts, vlen = _side_verts(
                table, simple, r0s, r0l, V, shift, scale
            )
            chip_area = _csr_geom_areas(table.chips, shift)
            pos = np.searchsorted(ucells, cells_raw)
            row_cell_area = (
                ucell_area[pos] if n else np.zeros(0, np.float64)
            )
            core = np.asarray(table.is_core, bool)
            # a core chip covers its cell exactly — use the cell table so
            # the core branches and the area tables agree bit-for-bit
            chip_area = np.where(core, row_cell_area, chip_area)

            def pad(a, fill=0):
                out = np.full((Lb,) + a.shape[1:], fill, a.dtype)
                out[:n] = a[order]
                return out

            return OverlaySide(
                table=table,
                n=n,
                bucket=Lb,
                cells=pad(cells_raw, pad_cell),
                geom=pad(np.asarray(table.geom_id, np.int64), -1),
                core=pad(core),
                ok_subj=pad(elig),
                ok_win=pad(ok_win),
                verts=pad(verts),
                vlen=pad(vlen),
                chip_area=pad(chip_area),
                cell_area=pad(row_cell_area),
                rows=order.astype(np.int64),
                geom_area=_csr_geom_areas(col, shift),
            )

        return OverlayPrep(
            left=_side(left_chips, left, lcells_raw, lsimple, lr0s,
                       lr0l, _k.LEFT_PAD_CELL),
            right=_side(right_chips, right, rcells_raw, rsimple, rr0s,
                        rr0l, _k.RIGHT_PAD_CELL),
            shift=np.asarray(shift, np.float64),
            scale=scale,
            index_system=index_system,
            resolution=resolution,
            acc_name=acc,
            band=float(band),
            vpad=V,
        )


def pair_glue(prep: OverlayPrep, li, ri, valid):
    """Candidate stream → geometry-pair segments (host glue, shared by
    the device lane and its numpy twin so both see identical segment
    ids): ``(uniq (U, 2) i64, seg (Pb,) i32 with -1 for dead slots,
    sure (U,), Sb, seg_larea (Sb,) f64, seg_rarea (Sb,) f64)``."""
    L, R = prep.left, prep.right
    li = np.asarray(li)
    ri = np.asarray(ri)
    valid = np.asarray(valid, bool)
    lg = L.geom[li]
    rg = R.geom[ri]
    valid = valid & (lg >= 0) & (rg >= 0)
    seg = np.full(li.shape[0], -1, np.int32)
    if valid.any():
        uniq, inv = np.unique(
            np.stack([lg[valid], rg[valid]], axis=-1),
            axis=0, return_inverse=True,
        )
        seg[valid] = inv.astype(np.int32)
    else:
        uniq = np.zeros((0, 2), np.int64)
    U = uniq.shape[0]
    sure = np.zeros(U, bool)
    either = L.core[li] | R.core[ri]
    if valid.any():
        np.logical_or.at(sure, seg[valid], either[valid])
    Sb = SEG_LADDER.bucket_for(max(U, 1))
    seg_larea = np.zeros(Sb, np.float64)
    seg_rarea = np.zeros(Sb, np.float64)
    if U:
        seg_larea[:U] = L.geom_area[uniq[:, 0]]
        seg_rarea[:U] = R.geom_area[uniq[:, 1]]
    return uniq, seg, sure, Sb, seg_larea, seg_rarea


# --------------------------------------------------- device-lane programs


@_dispatch.bounded_cache("overlay_count_programs", 8)
def _count_program():
    return jax.jit(partial(_k.pair_count, xp=jnp))


@_dispatch.bounded_cache("overlay_emit_programs", 32)
def _emit_program(pair_bucket: int):
    return jax.jit(
        partial(_k.emit_pairs, pair_bucket=pair_bucket, xp=jnp)
    )


@dataclass(frozen=True)
class OverlayMeasures:
    """Fused overlay measure result — one row per unique geometry pair
    sharing at least one cell (plus, when the candidate stream was
    capped, a trailing ``(OVERFLOW, OVERFLOW)`` row with NaN measures:
    structural truncation, never a silent wrong answer).

    ``value`` is the evaluated pair tree (f64), ``valid`` its mask lane,
    ``area`` the folded intersection area, ``sure`` the core-chip
    certainty flag, ``host_overridden`` how many pairs the f64 host lane
    re-answered (epsilon band / shape class), and ``lane`` which lane
    produced the numbers (``degraded`` True when the device lane failed
    past its retry budget and the host oracle answered instead)."""

    pairs: np.ndarray
    value: np.ndarray
    valid: np.ndarray
    area: np.ndarray
    sure: np.ndarray
    overflow: int
    lane: str
    host_overridden: int
    degraded: bool = False
    reason: str = ""


def _package(out: dict, lane: str, degraded: bool = False,
             reason: str = "") -> OverlayMeasures:
    """Lane output dict → :class:`OverlayMeasures`, appending the
    OVERFLOW(-2) row when the emission was capped."""
    pairs = out["pairs"]
    value = out["value"]
    vmask = out["valid"]
    area = out["area"]
    sure = out["sure"]
    overflow = int(out["overflow"])
    if overflow > 0:
        pairs = np.concatenate(
            [pairs, np.asarray([[OVERFLOW, OVERFLOW]], np.int64)]
        )
        value = np.concatenate([value, [np.nan]])
        area = np.concatenate([area, [np.nan]])
        vmask = np.concatenate([vmask, [False]])
        sure = np.concatenate([sure, [False]])
    return OverlayMeasures(
        pairs=pairs, value=value, valid=vmask, area=area, sure=sure,
        overflow=overflow, lane=lane,
        host_overridden=int(out["host_overridden"]),
        degraded=degraded, reason=reason,
    )


def overlay_measures(
    left: PackedGeometry,
    right: PackedGeometry,
    index_system: IndexSystem,
    resolution: int,
    value=None,
    *,
    left_chips: ChipTable | None = None,
    right_chips: ChipTable | None = None,
    prep: OverlayPrep | None = None,
    pair_cap: int | None = None,
    mesh=None,
    lane: str = "device",
) -> OverlayMeasures:
    """Fused overlap measures per intersecting geometry pair.

    ``value`` is an `expr/` PAIR tree over :func:`expr.ast.overlap_area`
    / ``left_area`` / ``right_area`` (default: the raw intersection
    area); ``st_intersection_area`` and ``st_overlap_fraction`` are the
    canned frontends. Candidate generation runs on device as a sorted
    segment equi-join over the prep's cell columns, the measures as ONE
    fused program per ``(tree-hash, buckets, index, mesh)`` signature —
    warm it with :func:`warmup_overlay` before `expr.compile.freeze`.

    ``lane="host"`` routes to the pure-f64 numpy twin (the oracle); the
    device lane degrades there automatically (result flagged) when the
    device path fails past its retry budget. ``pair_cap`` bounds the
    candidate emission — the excess is reported as an OVERFLOW(-2) row,
    never silently dropped.
    """
    from ..expr import ast as _ast
    from ..expr import compile as _compile
    from ..expr.host_oracle import host_overlay_measures, splice_override

    value = _ast.overlap_area() if value is None else value
    _ast.validate_pair(value)
    mesh = _dispatch.resolve_mesh(mesh)
    if prep is None:
        lt = (
            left_chips
            if left_chips is not None
            else tessellate(left, index_system, resolution)
        )
        rt = (
            right_chips
            if right_chips is not None
            else tessellate(right, index_system, resolution)
        )
        prep = prepare_overlay(
            lt, rt, left, right, index_system, resolution
        )
    if lane == "host":
        out = host_overlay_measures(prep, value, pair_cap=pair_cap)
        return _package(out, lane="host")
    if lane != "device":
        raise ValueError(f"unknown overlay lane {lane!r}")

    L, R = prep.left, prep.right
    acc = np.dtype(prep.acc_name)
    try:
        with _trace.span(
            "overlay.device_candidates",
            left_chips=L.n, right_chips=R.n,
        ) as span:
            with _telemetry.timed("overlay_stage", stage="candidates"):

                def device_candidates():
                    total = int(
                        _count_program()(L.cells, R.cells, L.n)
                    )
                    Pb, emit_limit, overflow = pair_plan(
                        total, pair_cap
                    )
                    li, ri, valid = _emit_program(Pb)(
                        L.cells, R.cells, L.n, emit_limit
                    )
                    return (
                        np.asarray(li), np.asarray(ri),
                        np.asarray(valid), total, Pb, emit_limit,
                        overflow,
                    )

                li, ri, valid, total, Pb, emit_limit, overflow = (
                    _dispatch.guarded_call(
                        "overlay.device_candidates", device_candidates
                    )
                )
                uniq, seg, sure, Sb, seg_l64, seg_r64 = pair_glue(
                    prep, li, ri, valid
                )
            span.set(
                raw_candidates=total, emitted=emit_limit,
                overflow=overflow,
            )
            _candidate_stats(span, sure)

        with _trace.span(
            "overlay.measures", pairs=int(uniq.shape[0]),
            candidates=total, mesh=_dispatch.mesh_key(mesh) is not None,
        ) as span:
            with _telemetry.timed("overlay_stage", stage="measures"):
                sig = _compile.overlay_signature_of(
                    value, L.bucket, R.bucket, Pb, Sb, prep.vpad,
                    prep.acc_name, index_system, resolution, mesh,
                )
                prog = _compile.overlay_program(
                    value, L.bucket, R.bucket, Pb, Sb, prep.vpad,
                    prep.acc_name, mesh,
                )
                raw = _dispatch.guarded_call(
                    "overlay.measures",
                    _compile.run_tracked, sig, prog,
                    li, ri, valid, seg,
                    L.core, L.ok_subj,
                    L.verts.astype(acc), L.vlen,
                    L.chip_area.astype(acc), L.cell_area.astype(acc),
                    R.core, R.ok_win,
                    R.verts.astype(acc), R.vlen,
                    R.chip_area.astype(acc),
                    seg_l64.astype(acc), seg_r64.astype(acc),
                    acc.type(prep.band),
                )
                val, vok, s, _cnt, host_needed = (
                    np.asarray(x) for x in raw
                )
                val = val.astype(np.float64).copy()
                vok = vok.astype(bool).copy()
                area64 = s.astype(np.float64).copy()
                val, vok, area64, overridden = splice_override(
                    prep, value, li, ri, valid, seg,
                    host_needed, seg_l64, seg_r64, val, vok, area64,
                )
            span.set(host_overridden=overridden)
        U = uniq.shape[0]
        return _package(
            {
                "pairs": uniq, "value": val[:U], "valid": vok[:U],
                "area": area64[:U], "sure": sure,
                "overflow": overflow, "host_overridden": overridden,
            },
            lane="device",
        )
    except Exception as e:  # lint: broad-except-ok (degradation seam: past the retry budget the f64 host oracle answers instead; the result is flagged, parity with every other DispatchCore frontend)
        _telemetry.record(
            "degraded", label="overlay.measures", error=repr(e)[:200]
        )
        out = host_overlay_measures(prep, value, pair_cap=pair_cap)
        return _package(
            out, lane="host", degraded=True,
            reason=f"overlay.measures: {e!r}"[:300],
        )


def warmup_overlay(
    left: PackedGeometry,
    right: PackedGeometry,
    index_system: IndexSystem,
    resolution: int,
    value=None,
    *,
    left_chips: ChipTable | None = None,
    right_chips: ChipTable | None = None,
    prep: OverlayPrep | None = None,
    pair_cap: int | None = None,
    mesh=None,
) -> OverlayPrep:
    """Execute the device overlay pipeline once so its signature joins
    the warm set (`expr.compile.freeze` afterwards arms the cold-compile
    tripwire) and return the prep for amortized reuse."""
    if prep is None:
        lt = (
            left_chips
            if left_chips is not None
            else tessellate(left, index_system, resolution)
        )
        rt = (
            right_chips
            if right_chips is not None
            else tessellate(right, index_system, resolution)
        )
        prep = prepare_overlay(
            lt, rt, left, right, index_system, resolution
        )
    overlay_measures(
        left, right, index_system, resolution, value,
        prep=prep, pair_cap=pair_cap, mesh=mesh,
    )
    return prep
