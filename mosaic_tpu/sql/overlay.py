"""Polygon-polygon ST_Intersects overlay join (cell-indexed).

Reference analog: the BNG overlay workload
(`notebooks/examples/python/BritishNationalGrid.py`) — both polygon tables
are tessellated into grid chips, the equi-join on cell id produces candidate
pairs, and the exact `ST_Intersects` predicate runs only on pairs whose
chips are both border chips (a core chip covers its whole cell, so any
other geometry touching that cell intersects it by construction — the
chip-table shortcut the reference's `is_core || st_intersects` predicate
expresses).

TPU-native shape: candidate generation is host columnar set algebra
(sort + group join on int64 cell ids); the surviving exact predicate runs
as one batched device `st_intersects` over the candidate chip pairs.
"""

from __future__ import annotations

import numpy as np

from ..core.index.base import IndexSystem
from ..core.tessellate import ChipTable, tessellate
from ..core.types import PackedGeometry
from ..dispatch import core as _dispatch
from ..obs import trace as _trace
from ..runtime import telemetry as _telemetry
from ..runtime.errors import DegradedResult


def _group_spans(cells_sorted: np.ndarray):
    """(uniq, start, stop) run-length spans of a sorted int64 array."""
    if not cells_sorted.shape[0]:
        return (
            np.zeros(0, np.int64),
            np.zeros(0, np.int64),
            np.zeros(0, np.int64),
        )
    change = np.nonzero(np.diff(cells_sorted))[0] + 1
    start = np.concatenate([[0], change])
    stop = np.concatenate([change, [cells_sorted.shape[0]]])
    return cells_sorted[start], start, stop


def candidate_pairs(
    left: ChipTable, right: ChipTable
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Chip-row candidate pairs sharing a cell.

    Returns (lrows, rrows, sure): chip-row index pairs, and ``sure`` True
    where at least one side's chip is core (intersection certain).

    Emits an ``overlay.candidates`` span (and matching
    ``overlay_candidates`` telemetry) with the candidate count, the
    sure-fraction (pairs accepted without a predicate), and the
    border-pair fraction (pairs that will pay the exact predicate) — the
    statistics that make overlay workloads profileable like the point
    frontends.
    """
    with _trace.span(
        "overlay.candidates",
        left_chips=int(np.asarray(left.cell_id).shape[0]),
        right_chips=int(np.asarray(right.cell_id).shape[0]),
    ) as span:
        lc = np.asarray(left.cell_id)
        rc = np.asarray(right.cell_id)
        lo = np.argsort(lc, kind="stable")
        ro = np.argsort(rc, kind="stable")
        lu, ls, le_ = _group_spans(lc[lo])
        ru, rs, re_ = _group_spans(rc[ro])
        common, li, ri = np.intersect1d(lu, ru, return_indices=True)
        if not common.shape[0]:
            z = np.zeros(0, np.int64)
            span.set(candidates=0, sure_fraction=0.0, border_fraction=0.0)
            _telemetry.record(
                "overlay_candidates", candidates=0,
                sure_fraction=0.0, border_fraction=0.0,
            )
            return z, z, np.zeros(0, bool)
        # vectorized per-cell cross join: left rows repeat by the right
        # group size, right rows tile within each (cell, left-row) block
        ln = le_[li] - ls[li]  # left group size per common cell
        rn = re_[ri] - rs[ri]  # right group size per common cell
        pair_n = ln * rn
        cell_of = np.repeat(np.arange(common.shape[0]), pair_n)
        off = np.concatenate([[0], np.cumsum(pair_n)])[:-1]
        k = np.arange(int(pair_n.sum())) - off[cell_of]  # rank within cell
        lrows = lo[ls[li][cell_of] + k // rn[cell_of]]
        rrows = ro[rs[ri][cell_of] + k % rn[cell_of]]
        sure = (
            np.asarray(left.is_core)[lrows] | np.asarray(right.is_core)[rrows]
        )
        n = int(sure.shape[0])
        sure_fraction = float(sure.sum()) / max(1, n)
        stats = {
            "candidates": n,
            "sure_fraction": round(sure_fraction, 6),
            "border_fraction": round(1.0 - sure_fraction, 6),
        }
        span.set(**stats)
        _telemetry.record("overlay_candidates", **stats)
        return lrows, rrows, sure


def intersects_join(
    left: PackedGeometry,
    right: PackedGeometry,
    index_system: IndexSystem,
    resolution: int,
    left_chips: ChipTable | None = None,
    right_chips: ChipTable | None = None,
    backend: str = "oracle",
) -> np.ndarray:
    """(P, 2) int64 — distinct (left_row, right_row) pairs that intersect.

    Both sides tessellate at ``resolution`` (pass prebuilt chip tables to
    amortize); pairs sharing a cell where either chip is core are accepted
    without a predicate, the rest run one row-wise st_intersects over the
    border-chip geometry pairs (chips are clipped to their cell, so
    chip-level intersection within a shared cell is exact for the
    geometry-level predicate). Refinement defaults to the f64 ``oracle``
    backend — exact boundary touches (shared edges) are below f32
    resolution; pass ``backend="device"`` to trade that edge case for
    batched device evaluation of huge pair lists.

    Known degenerate case (cell-equality joins generally, including the
    reference's): a pair whose intersection has zero area and lies
    EXACTLY on a cell boundary of an axis-aligned grid (BNG/CUSTOM) can
    tessellate into disjoint cell sets and produce no candidate.
    """
    lt = (
        left_chips
        if left_chips is not None
        else tessellate(left, index_system, resolution)
    )
    rt = (
        right_chips
        if right_chips is not None
        else tessellate(right, index_system, resolution)
    )
    lrows, rrows, sure = candidate_pairs(lt, rt)
    if not lrows.shape[0]:
        return np.zeros((0, 2), np.int64)

    lgeom = np.asarray(lt.geom_id)[lrows]
    rgeom = np.asarray(rt.geom_id)[rrows]
    hit = sure.copy()
    # a geometry pair already accepted via a core chip in ANY shared cell
    # needs no predicate for its remaining border-border candidates
    # (pair identity via unique-inverse on the 2-column array — exact for
    # any row-id width, no packed-key collisions)
    uniq_pairs, pair_id = np.unique(
        np.stack([lgeom, rgeom], axis=-1), axis=0, return_inverse=True
    )
    decided = np.zeros(uniq_pairs.shape[0], bool)
    decided[pair_id[sure]] = True
    need = np.nonzero(~sure & ~decided[pair_id])[0]
    degraded: DegradedResult | None = None
    if need.shape[0]:
        from ..functions.geometry import st_intersects

        # every undecided candidate chip pair is evaluated: a geometry
        # pair intersects iff ANY of its shared-cell chip pairs does
        a = lt.chips.take(lrows[need])
        b = rt.chips.take(rrows[need])

        def predicate():
            return np.asarray(st_intersects(a, b, backend=backend))

        # transient device failures retry with backoff; past the budget a
        # non-oracle backend degrades to the exact f64 host oracle (result
        # flagged), an oracle run raises typed RetryExhausted — the
        # watchdog/retry composition (and the "overlay.predicate" fault
        # plan) lives in dispatch.guarded_call
        res = _dispatch.guarded_call(
            "overlay.predicate",
            predicate,
            fallback=(
                (lambda: np.asarray(st_intersects(a, b, backend="oracle")))
                if backend != "oracle"
                else None
            ),
        )
        if isinstance(res, DegradedResult):
            degraded = res
        hit[need] = np.asarray(res)
    pairs = uniq_pairs[np.unique(pair_id[hit])]
    if degraded is not None:
        return DegradedResult.wrap(
            pairs, reason=degraded.reason, attempts=degraded.attempts,
        )
    return pairs


#: the managed overlay entry point under its workload name (the BNG
#: overlay notebook's join) — same callable, resilience included
overlay_join = intersects_join
