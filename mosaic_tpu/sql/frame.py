"""MosaicFrame: a geometry-aware columnar table.

Reference analog: `sql/MosaicFrame.scala:15-374` — a DataFrame subclass that
carries geometry-column roles, the chosen index resolution, and
exploded-or-array indexing state in column metadata, plus `Prettifier`
(`sql/Prettifier.scala:14-18`). Here the table is a plain dict of numpy
columns + a PackedGeometry, and the index state is explicit fields.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING

import numpy as np

if TYPE_CHECKING:  # string annotations only
    from ..core.tessellate import ChipTable  # noqa: F401

from ..core.types import PackedGeometry
from ..functions._coerce import to_packed


@dataclasses.dataclass
class MosaicFrame:
    """Geometry column + attributes + grid-index bookkeeping."""

    geometry: PackedGeometry
    columns: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    resolution: "int | None" = None
    chips: "ChipTable | None" = None  # set by set_index_resolution
    chips_index: "str | None" = None  # index-system name the chips used

    # ------------------------------------------------------------ builders
    @classmethod
    def from_table(cls, table) -> "MosaicFrame":
        """From a readers.VectorTable."""
        return cls(geometry=table.geometry, columns=dict(table.columns))

    @classmethod
    def from_geometry(cls, geom, **columns) -> "MosaicFrame":
        return cls(
            geometry=to_packed(geom),
            columns={k: np.asarray(v) for k, v in columns.items()},
        )

    def __len__(self) -> int:
        return len(self.geometry)

    # ------------------------------------------------------------ indexing
    def get_optimal_resolution(self, index=None, **kwargs) -> int:
        from .analyzer import MosaicAnalyzer

        if index is None:
            from ..context import current_context

            index = current_context().index_system
        return MosaicAnalyzer(index).get_optimal_resolution(
            self.geometry, **kwargs
        )

    def set_index_resolution(
        self, resolution: int, index=None, keep_core_geoms: bool = False
    ) -> "MosaicFrame":
        """Tessellate the geometry column and attach the chip table
        (reference: `setIndexResolution` + `applyIndex`)."""
        from ..functions.grid import grid_tessellate

        chips = grid_tessellate(
            self.geometry, resolution, keep_core_geoms=keep_core_geoms,
            index=index,
        )
        if index is None:
            from ..context import current_context

            index = current_context().index_system
        return dataclasses.replace(
            self,
            resolution=resolution,
            chips=chips,
            chips_index=getattr(index, "name", str(index)),
        )

    # --------------------------------------------------------------- joins
    def point_in_polygon_join(
        self, points: "MosaicFrame", index=None, resolution: "int | None" = None
    ) -> dict[str, np.ndarray]:
        """Managed PIP join: this frame = polygons, other = points
        (reference: `PointInPolygonJoin.join:15-37`). Returns the joined
        column dict (point columns + matched polygon row + polygon columns).
        """
        from ..sql.join import pip_join

        if index is None:
            from ..context import current_context

            index = current_context().index_system
        res = resolution if resolution is not None else self.resolution
        if res is None:
            res = self.get_optimal_resolution(index)
        pts = np.stack(
            [
                _point_coords(points.geometry, 0),
                _point_coords(points.geometry, 1),
            ],
            axis=-1,
        )
        match = pip_join(pts, self.geometry, index, res)
        out = {k: v.copy() for k, v in points.columns.items()}
        out["polygon_row"] = match
        ok = match >= 0
        safe = np.maximum(match, 0)
        for k, v in self.columns.items():
            col = np.asarray(v)[safe]
            if col.dtype.kind in "fiu":  # numeric -> NaN mask
                col = np.where(ok, col.astype(np.float64), np.nan)
            else:  # strings/objects -> None mask
                col = np.where(ok, col.astype(object), None)
            out[f"polygon_{k}"] = col
        return out

    def intersects_join(
        self,
        other: "MosaicFrame",
        index=None,
        resolution: "int | None" = None,
    ) -> np.ndarray:
        """Polygon-polygon ST_Intersects overlay join (reference: the BNG
        overlay workload). Returns distinct (this_row, other_row) pairs.
        Prebuilt chip tables (`set_index_resolution`) on either frame are
        reused."""
        from ..sql.overlay import intersects_join as _ov

        if index is None:
            from ..context import current_context

            index = current_context().index_system
        res = resolution if resolution is not None else self.resolution
        if res is None:
            res = self.get_optimal_resolution(index)
        # reuse prebuilt chips only when both resolution AND index system
        # match — joining BNG cell ids against H3 ids would silently fail
        iname = getattr(index, "name", str(index))

        def _reusable(frame):
            return (
                frame.chips
                if frame.resolution == res and frame.chips_index == iname
                else None
            )

        return _ov(
            self.geometry,
            other.geometry,
            index,
            res,
            left_chips=_reusable(self),
            right_chips=_reusable(other),
        )

    # ------------------------------------------------------------- display
    def prettified(self, n: int = 10) -> str:
        """Reference: `Prettifier.prettified` — compact preview."""
        from ..core.geometry.wkt import to_wkt

        rows = min(n, len(self))
        idx = list(range(rows))
        wkts = to_wkt(self.geometry.take(idx))
        lines = []
        header = ["geometry"] + list(self.columns)
        lines.append(" | ".join(header))
        for i in idx:
            w = wkts[i] if len(wkts[i]) < 60 else wkts[i][:57] + "..."
            vals = [w] + [str(self.columns[k][i]) for k in self.columns]
            lines.append(" | ".join(vals))
        return "\n".join(lines)


def _point_coords(col: PackedGeometry, axis: int) -> np.ndarray:
    out = np.full(len(col), np.nan)
    for g in range(len(col)):
        xy = col.geom_xy(g)
        if xy.shape[0]:
            out[g] = xy[0, axis]
    return out
