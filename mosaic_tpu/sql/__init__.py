"""High-level services (reference analog: `src/main/scala/.../sql/`)."""

from .join import ChipIndex, build_chip_index, pip_join, pip_join_points
from .overlay import intersects_join, overlay_join

__all__ = [
    "ChipIndex",
    "build_chip_index",
    "intersects_join",
    "overlay_join",
    "pip_join",
    "pip_join_points",
]
