"""High-level services (reference analog: `src/main/scala/.../sql/`)."""

from .join import ChipIndex, build_chip_index, pip_join, pip_join_points
from .overlay import (
    OverlayMeasures,
    OverlayPrep,
    candidate_pairs,
    intersects_join,
    overlay_join,
    overlay_measures,
    prepare_overlay,
    warmup_overlay,
)
from .raster_stream import RasterScanResult, RasterStream
from .stream import (
    StreamJoin,
    StreamResult,
    generator_rate,
    hbm_peak,
    ring_from_generator,
    ring_from_host,
)

__all__ = [
    "ChipIndex",
    "OverlayMeasures",
    "OverlayPrep",
    "RasterScanResult",
    "RasterStream",
    "StreamJoin",
    "StreamResult",
    "build_chip_index",
    "candidate_pairs",
    "generator_rate",
    "hbm_peak",
    "intersects_join",
    "overlay_join",
    "overlay_measures",
    "pip_join",
    "pip_join_points",
    "prepare_overlay",
    "ring_from_generator",
    "ring_from_host",
    "warmup_overlay",
]
