"""High-level services (reference analog: `src/main/scala/.../sql/`)."""

from .join import ChipIndex, build_chip_index, pip_join, pip_join_points
from .overlay import intersects_join, overlay_join
from .raster_stream import RasterScanResult, RasterStream
from .stream import (
    StreamJoin,
    StreamResult,
    generator_rate,
    hbm_peak,
    ring_from_generator,
    ring_from_host,
)

__all__ = [
    "ChipIndex",
    "RasterScanResult",
    "RasterStream",
    "StreamJoin",
    "StreamResult",
    "build_chip_index",
    "generator_rate",
    "hbm_peak",
    "intersects_join",
    "overlay_join",
    "pip_join",
    "pip_join_points",
    "ring_from_generator",
    "ring_from_host",
]
