"""Index-assisted point-in-polygon join — the north-star workload.

Reference analog: `sql/join/PointInPolygonJoin.scala:15-98` and the
Quickstart benchmark (`notebooks/examples/scala/QuickstartNotebook.scala:
204-216`): points get a cell id, polygons are tessellated into chips, the
join is an equi-join on cell id, and the exact `st_contains` predicate runs
only on border-chip matches (`is_core || st_contains(wkb, point)`).

TPU-native redesign: there is no shuffle. The chip table is compiled into a
device-resident :class:`ChipIndex` which is small enough to replicate
(all-gather over ICI) on every chip of a mesh, while the billion-point side
shards over devices.

The per-point probe is designed around TPU gather latency and HBM bandwidth:

    key  = (cell * A) >> (64 - log2 T)    multiply-shift hash, no search
    bkt  = table[key]                     1 gather: B candidate (cell, u)
    u    = bucket row whose cell matches  parallel compare, no loop
    edges= cell_edges[u]                  1 flat gather: the cell's chip
                                          edges, capped at EDGE_CAP
    par  = xor-reduce(crossing ? bit : 0) one parity bit per chip slot
    hit  = core | parity bit              fused vector math

The edge table is FLAT per cell (not per-chip padded): every cell row holds
at most ``EDGE_CAP`` edges, each tagged with the parity bit of the chip it
belongs to. This kills the max-verts padding blow-up that a per-chip
``(U, M, R, V, 2)`` layout suffers (one 309-vertex coastline chip would
force every cell row to carry V=309 — ~10 GB of gather per 1M points, which
made every >=1M batch fail TPU compilation in round 2). Cells whose chips
carry more than ``EDGE_CAP`` edges (<8% of NYC cells) divert to a HEAVY side
table: points landing in them are stream-compacted (cumsum + scatter, all
static shapes) and only that compacted subset pays the wide heavy gather.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..core.geometry.device import (
    DeviceGeometry,
    recenter_shift,
    to_device,
)
from ..core.index.base import IndexSystem
from ..core.tessellate import ChipTable, tessellate
from ..core.types import PackedGeometry
from ..dispatch import core as _dispatch
from ..obs import trace as _obs_trace
from ..runtime import (
    faults as _faults,
    telemetry as _telemetry,
)
from ..runtime.errors import DegradedResult, RetryExhausted
from ..runtime.escalate import run_escalating
from ..utils import get_logger

_SENTINEL = jnp.iinfo(jnp.int32).max
_I32_MAX = np.iinfo(np.int32).max
_OVF_MARK = _SENTINEL - 1  # in-probe marker: tier-2 capacity exceeded

#: per-cell flat edge capacity of the tier-1 probe; cells with more edges
#: divert to the heavy table (measured on NYC res-9: cap 32 keeps 93% of
#: cells in tier 1 and the heavy table holds <8k edges)
EDGE_CAP = 32

#: parity bits are uint32 — at most 32 chip slots per cell and per heavy row
MAX_SLOTS = 32

#: result code for points whose heavy-cell probe exceeded ``heavy_cap``
#: (unknown result; raise the cap — `pip_join` sizes it exactly)
OVERFLOW = -2

#: direct-mode tier-1 chunk rows (keeps the un-compacted (CH, E1, 4)
#: edge intermediate under XLA's 2 GB buffer limit); tests shrink it to
#: exercise the lax.map path on small inputs
_DIRECT_CHUNK = 1 << 20

#: epsilon-band multipliers (SURVEY §7 precision strategy): a point is
#: borderline when its cell-rounding margin (`IndexSystem.
#: point_to_cell_margin`) is below CELL_MARGIN_K·eps(dtype) — calibrated
#: against exhaustive f32-vs-f64 disagreement sets (max observed ≈ 2.8·eps
#: globally at res 5/9; tests/test_recheck.py pins the 2x headroom) — or
#: within EDGE_BAND_K·eps·coord_scale of a probed chip edge.
CELL_MARGIN_K = 6.0
EDGE_BAND_K = 16.0

#: convex-lane table shape (adaptive router): y-scanline buckets per
#: convex cell and the per-bucket edge capacity. A cell only qualifies
#: when every pad-inflated bucket fits CONVEX_EDGE_CAP edges, so the lane
#: reads at most EB edges/point against tier 1's full E1 row.
CONVEX_BUCKETS = 8
CONVEX_EDGE_CAP = 16


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ChipIndex:
    """Device-resident join index over a tessellated polygon table.

    Per-chip layout (kept for oracles, tests and host inspection):

    cells:     (U,) int64 — sorted unique cell ids present in the chip table.
    chip_rows: (U, M) int32 — chip-row ids per cell, -1 padded (M = max
               chips per cell, static).
    chip_geom: (C,) int32 — source polygon row per chip.
    chip_core: (C,) bool — core chips skip the predicate.
    border:    DeviceGeometry over all C chip rows (core rows are empty and
               never evaluated).

    Probe fast path (see module docstring):

    hash_mult:  (1,) uint64 — multiply-shift hash multiplier.
    table_cell: (T, B) int64 — bucketed hash table of cell ids (-1 empty);
                T is a power of two, B the max bucket occupancy.
    table_slot: (T, B) int32 — cell slot u for each bucket entry (-1 empty).
    table_pack: (T, B) int64 — slot-packed probe table: when every indexed
                cell shares its low ``k`` bits (H3 at a fixed resolution
                keeps the unused finer digits constant), entry =
                ``(cell & ~low) | (slot + 1)`` and the probe needs ONE
                gather instead of two. (0, 0) when packing is impossible
                (too few constant bits); probe falls back to the pair.
    pack_low:   (2,) int64 — [low-bit mask, constant low-bit value].

    Tier-1 flat edge probe (light cells):

    cell_edges:     (U, E1, 4) — ax, ay, bx, by per edge, zero pad (inert:
                    a zero-length edge never straddles any scanline).
    cell_ebits:     (U, E1) uint32 — parity bit ``1 << slot`` of the owning
                    chip, 0 for pad edges.
    cell_slot_geom: (U, M1) int32 — geom id per tier-1 chip slot, -1 pad.
    cell_slot_core: (U, M1) bool — core chips hit without any edge test.
    cell_heavy:     (U,) int32 — heavy-table row of this cell, -1 if light.

    Tier-2 heavy table (cells whose border chips exceed EDGE_CAP edges):

    heavy_edges:     (H, E2, 4); heavy_ebits: (H, E2) uint32.
    heavy_slot_geom: (H, M2) int32 — geom per heavy chip slot, -1 pad.
    H == 0 when no cell is heavy (tier 2 compiles away entirely).

    Convex lane (adaptive router, ``probe="adaptive"``): single-chip
    light cells whose border chip is one closed convex ring get a
    reduced-edge test — edges are binned by y into ``KB`` scanline
    buckets so a point touches only its bucket's ``EB`` edges instead of
    the cell's full E1 row:

    cell_convex: (U,) int32 — convex-table row of this cell, -1 otherwise.
    convex_edges: (Cv, KB, EB, 4) — y-bucketed edges (the same f32 values
                  as the cell's tier-1 row; zero pad is inert).
    convex_ebits: (Cv, KB, EB) uint32 — 1 for real edges, 0 pad.
    convex_geom:  (Cv,) int32 — the single chip's geom id.
    convex_ybin:  (Cv, 3) f32 — [y_min, buckets/height, band_guard²];
                  buckets overlap by a pad of 4·EDGE_BAND_K·eps·scale so
                  bucket-boundary rounding can never drop a straddling
                  edge, and the epsilon band stays exact while the
                  runtime eps² <= band_guard² (the router checks).
    Cv == 0 when no cell qualifies (the lane compiles away).

    Instances built by :func:`build_chip_index` additionally carry a
    ``host`` attribute (:class:`HostRecheck`, f64 host twin of the edge
    tables) — not a dataclass field, so it stays out of the pytree.
    """

    cells: jax.Array
    chip_rows: jax.Array
    chip_geom: jax.Array
    chip_core: jax.Array
    border: DeviceGeometry
    hash_mult: jax.Array
    table_cell: jax.Array
    table_slot: jax.Array
    table_pack: jax.Array
    pack_low: jax.Array
    cell_edges: jax.Array
    cell_ebits: jax.Array
    cell_slot_geom: jax.Array
    cell_slot_core: jax.Array
    cell_heavy: jax.Array
    heavy_edges: jax.Array
    heavy_ebits: jax.Array
    heavy_slot_geom: jax.Array
    cell_convex: jax.Array
    convex_edges: jax.Array
    convex_ebits: jax.Array
    convex_geom: jax.Array
    convex_ybin: jax.Array

    @property
    def num_cells(self) -> int:
        return int(self.cells.shape[0])

    @property
    def max_chips_per_cell(self) -> int:
        return int(self.chip_rows.shape[1])

    @property
    def num_heavy_cells(self) -> int:
        return int(self.heavy_edges.shape[0])

    @property
    def num_convex_cells(self) -> int:
        return int(self.convex_edges.shape[0])


@dataclasses.dataclass
class HostRecheck:
    """Host-side f64 companion of a :class:`ChipIndex`: the same flat
    edge layout in full precision and the same recentring shift, built
    from the pre-narrowing chip coordinates. This is the exact oracle the
    epsilon-band recheck evaluates borderline points against (and a
    standalone f64 reference join for tests/benchmarks via
    :func:`host_join_with_cells`). Not a pytree — never crosses to device.
    """

    cells: np.ndarray  # (U,) int64 sorted
    cell_edges: np.ndarray  # (U, E1, 4) float64
    cell_ebits: np.ndarray
    cell_slot_geom: np.ndarray
    cell_slot_core: np.ndarray
    cell_heavy: np.ndarray
    heavy_edges: np.ndarray  # (H, E2, 4) float64
    heavy_ebits: np.ndarray
    heavy_slot_geom: np.ndarray
    shift: np.ndarray  # (2,) float64
    coord_scale: float  # max |recentered edge coordinate|

    _FIELDS = (
        "cells", "cell_edges", "cell_ebits", "cell_slot_geom",
        "cell_slot_core", "cell_heavy", "heavy_edges", "heavy_ebits",
        "heavy_slot_geom", "shift",
    )

    def save_arrays(self) -> dict:
        """{name: array} for npz round-trips (bench index cache)."""
        d = {f"hr_{n}": getattr(self, n) for n in self._FIELDS}
        d["hr_coord_scale"] = np.asarray(self.coord_scale)
        return d

    @classmethod
    def from_arrays(cls, z) -> "HostRecheck":
        kw = {n: np.asarray(z[f"hr_{n}"]) for n in cls._FIELDS}
        return cls(coord_scale=float(z["hr_coord_scale"]), **kw)


def _np_parity(px, py, e, bits):
    """Host twin of :func:`_ray_parity` (float64 numpy)."""
    ax, ay, bx, by = e[..., 0], e[..., 1], e[..., 2], e[..., 3]
    st = (ay > py[:, None]) != (by > py[:, None])
    den = np.where(by == ay, 1.0, by - ay)
    xc = ax + (py[:, None] - ay) * (bx - ax) / den
    cr = st & (px[:, None] < xc)
    return np.bitwise_xor.reduce(
        np.where(cr, bits, np.uint32(0)).astype(np.uint32), axis=1
    )


def host_join_with_cells(
    points: np.ndarray, cells: np.ndarray, host: HostRecheck
) -> np.ndarray:
    """(N,) int32 — exact f64 host evaluation of the join contract for
    pre-assigned ``cells`` (raw, unshifted ``points``; same smallest-
    matching-row semantics as :func:`pip_join_points`)."""
    p = np.asarray(points, np.float64) - host.shift
    out = np.full(p.shape[0], -1, dtype=np.int32)
    U = host.cells.shape[0]
    if U == 0:
        return out
    u = np.clip(np.searchsorted(host.cells, cells), 0, U - 1)
    fi = np.nonzero(host.cells[u] == cells)[0]
    if fi.size == 0:
        return out
    uf = u[fi]
    px, py = p[fi, 0], p[fi, 1]
    par = _np_parity(px, py, host.cell_edges[uf], host.cell_ebits[uf])
    M = host.cell_slot_geom.shape[1]
    inside = ((par[:, None] >> np.arange(M, dtype=np.uint32)) & 1).astype(bool)
    g = host.cell_slot_geom[uf]
    hit = (g >= 0) & (host.cell_slot_core[uf] | inside)
    best = np.where(hit, g, _I32_MAX).min(axis=1)
    if host.heavy_edges.shape[0]:
        hrow = host.cell_heavy[uf]
        hi_ = np.nonzero(hrow >= 0)[0]
        if hi_.size:
            h = hrow[hi_]
            par2 = _np_parity(
                px[hi_], py[hi_], host.heavy_edges[h], host.heavy_ebits[h]
            )
            M2 = host.heavy_slot_geom.shape[1]
            in2 = (
                (par2[:, None] >> np.arange(M2, dtype=np.uint32)) & 1
            ).astype(bool)
            g2 = host.heavy_slot_geom[h]
            b2 = np.where((g2 >= 0) & in2, g2, _I32_MAX).min(axis=1)
            best[hi_] = np.minimum(best[hi_], b2)
    out[fi] = np.where(best == _I32_MAX, -1, best).astype(np.int32)
    return out


def host_join(
    points: np.ndarray,
    host: HostRecheck,
    index_system: IndexSystem,
    resolution: int,
) -> np.ndarray:
    """Exact f64 host join: f64 cell assignment (numpy host path, pentagon-
    exact) + f64 flat-edge probe. Ground truth for the epsilon-band
    recheck and the f32/f64 agreement metrics."""
    cells = np.asarray(
        index_system.point_to_cell(np.asarray(points, np.float64), resolution)
    )
    return host_join_with_cells(points, cells, host)


def _build_hash(cells: np.ndarray, max_bucket: int = 8):
    """Host: bucketed multiply-shift hash over the unique cell ids.

    Returns (mult, table_cell (T, B), table_slot (T, B)). T is sized ~4x the
    cell count (power of two); the multiplier is retried (growing the table
    each time) until the fullest bucket holds <= max_bucket entries, then B
    shrinks to the realized max. The fallback keeps ``keys`` consistent with
    the final ``bits`` even if every retry clusters: the last computed keys
    are used as-is with a (possibly larger) realized B.
    """
    U = cells.shape[0]
    bits = max(4, int(np.ceil(np.log2(max(4 * U, 16)))))
    bits_cap = bits + 6  # bound table growth (and host memory) at 64x
    rng = np.random.default_rng(0xC0FFEE)
    # NOTE: do not chase smaller B by growing T — measured on v5e, gather
    # cost is dominated by table footprint (a 262k-row table probes ~8x
    # slower per element than an 8k-row one), so T ~= 4U with a hard-won
    # small B beats a larger table. The probe gather cost is linear in B
    # ((N, B) rows fetched per batch: 16.6 ms/4M at B=3), so FIRST spend
    # host-side effort hunting a B<=2 multiplier at the SAME T — success
    # odds per multiplier are ~1% at T=4U (Poisson tail), so a few
    # hundred tries (microseconds each over U keys) usually land one.
    cells_u64 = cells.astype(np.uint64)
    counts = np.zeros(1, dtype=np.int64)
    mult = np.uint64(1)
    found = False
    for b2 in (bits, bits + 1):  # one doubling: T=8U at B=2 still beats
        # Poisson estimate of >=3-entry buckets: when e^-E(count) is
        # negligible the hunt cannot succeed — skip instead of burning
        # 400 futile tries (3.7 s at U=200k)
        lam = U / float(1 << b2)
        if (1 << b2) * lam**3 / 6.0 * np.exp(-lam) > 7.0:
            continue
        for _ in range(400):     # T=4U at B=4 (same bytes, half the rows)
            cand = np.uint64(
                rng.integers(0, 2**64, dtype=np.uint64) | np.uint64(1)
            )
            k = (cells_u64 * cand) >> np.uint64(64 - b2)
            c = np.bincount(k.astype(np.int64), minlength=1 << b2)
            if c.max() <= 2:
                mult, keys, counts, found = cand, k, c, True
                bits = b2
                break
        if found:
            break
    if not found:
        for attempt in range(32):
            mult = np.uint64(
                rng.integers(0, 2**64, dtype=np.uint64) | np.uint64(1)
            )
            keys = (cells_u64 * mult) >> np.uint64(64 - bits)
            counts = np.bincount(keys.astype(np.int64), minlength=1 << bits)
            if counts.max() <= max_bucket:
                break
            if attempt < 31 and bits < bits_cap:
                bits += 1  # grow the table if this multiplier clusters
    B = int(counts.max()) if U else 1
    T = 1 << bits
    table_cell = np.full((T, B), -1, dtype=np.int64)
    table_slot = np.full((T, B), -1, dtype=np.int32)
    fill = np.zeros(T, dtype=np.int64)
    for u, (c, k) in enumerate(zip(cells, keys.astype(np.int64))):
        table_cell[k, fill[k]] = c
        table_slot[k, fill[k]] = u
        fill[k] += 1

    # slot-packed variant: if all cells share their low k bits (H3 at a
    # fixed res keeps the unused finer digits constant) and slot+1 fits in
    # k bits, one int64 entry carries both the cell and the slot — the
    # device probe then needs a single (N, B) gather instead of two
    table_pack = np.zeros((0, 0), dtype=np.int64)
    pack_low = np.zeros(2, dtype=np.int64)
    if U:
        diff = np.bitwise_or.reduce(cells ^ cells[0])
        k_bits = int(diff & -diff).bit_length() - 1 if diff else 63
        k_bits = min(k_bits, 62)
        if k_bits > 0 and (U + 1) < (1 << k_bits):
            low = np.int64((1 << k_bits) - 1)
            table_pack = np.where(
                table_slot >= 0,
                (table_cell & ~low) | (table_slot.astype(np.int64) + 1),
                np.int64(0),
            )
            pack_low = np.asarray([low, cells[0] & low], dtype=np.int64)
    return mult, table_cell, table_slot, table_pack, pack_low


def _round8(n: int, lo: int = 8) -> int:
    return max(lo, (n + 7) // 8 * 8)


def build_chip_index(
    table: ChipTable,
    dtype=jnp.float32,
    max_chips_per_cell: int | None = None,
    recenter: bool = True,
    edge_cap: int = EDGE_CAP,
) -> ChipIndex:
    """Host: compile a ChipTable into the device join index."""
    C = len(table)
    if C == 0:
        raise ValueError("empty chip table")
    order = np.argsort(table.cell_id, kind="stable")
    sorted_cells = table.cell_id[order]
    uniq, starts, counts = np.unique(
        sorted_cells, return_index=True, return_counts=True
    )
    M = int(max_chips_per_cell or counts.max())
    if counts.max() > M:
        raise ValueError(
            f"cell with {counts.max()} chips exceeds max_chips_per_cell={M}"
        )
    U = uniq.size
    rows = np.full((U, M), -1, dtype=np.int32)
    chip_cell_slot = np.full(C, -1, dtype=np.int64)  # chip -> cell row u
    for i, (s, c) in enumerate(zip(starts, counts)):
        rows[i, :c] = order[s : s + c]
        chip_cell_slot[order[s : s + c]] = i
    # only border rows need vertices: blank core chip geometries before
    # padding so V is set by the clipped border chips, not the cell polygons
    chips = table.chips
    if table.is_core.any() and table.has_geom[table.is_core].any():
        # rebuild with empty geometry for core rows
        from ..core.types import GeometryBuilder, GeometryType

        b = GeometryBuilder()
        for g in range(C):
            if table.is_core[g]:
                b.add_geometry(GeometryType.POLYGON, [[np.zeros((0, 2))]], 0)
            else:
                b.append_from(chips, g)
        chips = b.build()
    # recenter: chips span a city/region, so subtracting the f64 midpoint
    # before narrowing to f32 shrinks the coordinate ulp by ~1e3 (the
    # SURVEY §7 precision strategy) — points are shifted to match in
    # pip_join before they are narrowed. The padded host f64 coordinates
    # are kept (HostRecheck) so the epsilon-band recheck evaluates against
    # the TRUE chips, not their narrowed images; the device tables below
    # narrow from these same host arrays (bitwise-identical to narrowing
    # on device, no device round-trip).
    padded = chips.to_padded(dtype=np.float64)
    shift64 = recenter_shift(padded) if recenter else np.zeros(2)
    bverts64 = np.where(
        (np.asarray(padded.ring_len)[:, :, None] > 0)[..., None],
        np.asarray(padded.verts, dtype=np.float64) - shift64,
        0.0,
    )
    border = to_device(
        padded, dtype=dtype, shifted_verts=bverts64, shift=shift64
    )

    # probe fast path: hash table + flat per-cell edge rows
    mult, table_cell, table_slot, table_pack, pack_low = _build_hash(uniq)

    from ..core.types import GeometryType

    bverts = bverts64.astype(np.dtype(dtype))  # (C, R, V, 2), recentered
    blen = np.asarray(padded.ring_len)  # (C, R)
    btype = np.asarray(padded.geom_type)
    is_poly = (btype == GeometryType.POLYGON) | (btype == GeometryType.MULTIPOLYGON)
    contributes = is_poly & ~table.is_core  # chips whose edges are probed

    # flat edge extraction: one (chip, ring, e) triple per real edge, in
    # chip-major order (closed rings: vertex ring_len repeats vertex 0)
    Rr, V = bverts.shape[1], bverts.shape[2]
    e_idx = np.arange(V - 1)
    emask = (
        contributes[:, None, None]
        & (e_idx[None, None, :] < blen[:, :, None])
    )  # (C, R, V-1)
    ec, er, ee = np.nonzero(emask)
    e_a = bverts[ec, er, ee]  # (E, 2)
    e_b = bverts[ec, er, ee + 1]
    edges_all = np.concatenate([e_a, e_b], axis=1).astype(bverts.dtype)  # (E,4)
    edges_all64 = np.concatenate(
        [bverts64[ec, er, ee], bverts64[ec, er, ee + 1]], axis=1
    )  # (E, 4) f64 twin, same row order
    e_cell = chip_cell_slot[ec]  # (E,) cell row u per edge

    # per-cell edge totals decide light vs heavy
    epc = np.bincount(e_cell, minlength=U)
    heavy_mask = epc > edge_cap
    heavy_u = np.nonzero(heavy_mask)[0]
    H = heavy_u.size
    cell_heavy = np.full(U, -1, dtype=np.int32)
    cell_heavy[heavy_u] = np.arange(H, dtype=np.int32)

    # chip slot assignment per tier: tier-1 keeps every chip of light cells
    # plus core/non-polygonal chips of heavy cells; heavy border chips get
    # tier-2 slots. Slot numbers are per-cell-local (parity bit positions).
    # Vectorized: per-tier rank within each cell via cumsum-of-flags minus
    # the cumsum at the cell's start (chips in `order` are cell-grouped).
    chip_heavy_tier = contributes & heavy_mask[chip_cell_slot]
    f2 = chip_heavy_tier[order]
    f1 = ~f2
    c1 = np.cumsum(f1)
    c2 = np.cumsum(f2)
    start_pos = np.repeat(starts, counts)  # sorted-pos of each chip's cell start
    base1 = np.concatenate([[0], c1])[start_pos]
    base2 = np.concatenate([[0], c2])[start_pos]
    rank1 = c1 - 1 - base1  # valid where f1
    rank2 = c2 - 1 - base2  # valid where f2
    t1_slot = np.full(C, -1, dtype=np.int64)
    t2_slot = np.full(C, -1, dtype=np.int64)
    t1_slot[order[f1]] = rank1[f1]
    t2_slot[order[f2]] = rank2[f2]
    n1_per_cell = np.bincount(chip_cell_slot[~chip_heavy_tier], minlength=U)
    n2_per_cell = np.bincount(chip_cell_slot[chip_heavy_tier], minlength=U)
    M1 = max(1, int(n1_per_cell.max(initial=0)))
    M2 = max(1, int(n2_per_cell.max(initial=0)))
    if M1 > MAX_SLOTS or M2 > MAX_SLOTS:
        raise ValueError(
            f"a cell holds more than {MAX_SLOTS} chips per probe tier "
            f"(M1={M1}, M2={M2}); parity bits are uint32 — merge chips or "
            "raise the tessellation resolution"
        )
    slot_geom = np.full((U, M1), -1, dtype=np.int32)
    slot_core = np.zeros((U, M1), dtype=bool)
    ch1 = np.nonzero(~chip_heavy_tier)[0]
    slot_geom[chip_cell_slot[ch1], t1_slot[ch1]] = table.geom_id[ch1].astype(
        np.int32
    )
    slot_core[chip_cell_slot[ch1], t1_slot[ch1]] = table.is_core[ch1]

    # pack tier-1 edges: light-tier edges only, grouped per cell
    t1_edge = t1_slot[ec] >= 0
    E1 = _round8(min(int(epc.max(initial=0)), edge_cap))
    cell_edges = np.zeros((U, E1, 4), dtype=bverts.dtype)
    cell_edges64 = np.zeros((U, E1, 4), dtype=np.float64)
    cell_ebits = np.zeros((U, E1), dtype=np.uint32)
    if t1_edge.any():
        cu = e_cell[t1_edge]
        ord1 = np.argsort(cu, kind="stable")
        cu = cu[ord1]
        ed = edges_all[t1_edge][ord1]
        bits = np.uint32(1) << t1_slot[ec][t1_edge][ord1].astype(np.uint32)
        pos = np.arange(cu.size) - np.searchsorted(cu, cu)
        cell_edges[cu, pos] = ed
        cell_edges64[cu, pos] = edges_all64[t1_edge][ord1]
        cell_ebits[cu, pos] = bits

    # pack tier-2 heavy rows
    if H:
        t2_edge = t2_slot[ec] >= 0
        hrow = cell_heavy[e_cell[t2_edge]].astype(np.int64)
        ord2 = np.argsort(hrow, kind="stable")
        hrow = hrow[ord2]
        ed2 = edges_all[t2_edge][ord2]
        bits2 = np.uint32(1) << t2_slot[ec][t2_edge][ord2].astype(np.uint32)
        eph = np.bincount(hrow, minlength=H)
        E2 = _round8(int(eph.max(initial=1)))
        heavy_edges = np.zeros((H, E2, 4), dtype=bverts.dtype)
        heavy_edges64 = np.zeros((H, E2, 4), dtype=np.float64)
        heavy_ebits = np.zeros((H, E2), dtype=np.uint32)
        pos2 = np.arange(hrow.size) - np.searchsorted(hrow, hrow)
        heavy_edges[hrow, pos2] = ed2
        heavy_edges64[hrow, pos2] = edges_all64[t2_edge][ord2]
        heavy_ebits[hrow, pos2] = bits2
        hgeom = np.full((H, M2), -1, dtype=np.int32)
        ch2 = np.nonzero(chip_heavy_tier)[0]
        hgeom[
            cell_heavy[chip_cell_slot[ch2]], t2_slot[ch2]
        ] = table.geom_id[ch2].astype(np.int32)
    else:
        heavy_edges = np.zeros((0, 8, 4), dtype=bverts.dtype)
        heavy_edges64 = np.zeros((0, 8, 4), dtype=np.float64)
        heavy_ebits = np.zeros((0, 8), dtype=np.uint32)
        hgeom = np.zeros((0, 1), dtype=np.int32)

    coord_scale = (
        float(np.abs(edges_all64).max()) if edges_all64.size else 1.0
    )
    (
        cell_convex, convex_edges, convex_ebits, convex_geom, convex_ybin,
    ) = _build_convex_tables(
        U, epc, heavy_mask, cell_edges, slot_geom, slot_core, coord_scale
    )

    idx = ChipIndex(
        cells=jnp.asarray(uniq, dtype=jnp.int64),
        chip_rows=jnp.asarray(rows),
        chip_geom=jnp.asarray(table.geom_id.astype(np.int32)),
        chip_core=jnp.asarray(table.is_core),
        border=border,
        hash_mult=jnp.asarray(np.asarray([mult], dtype=np.uint64)),
        table_cell=jnp.asarray(table_cell),
        table_slot=jnp.asarray(table_slot),
        table_pack=jnp.asarray(table_pack),
        pack_low=jnp.asarray(pack_low),
        cell_edges=jnp.asarray(cell_edges),
        cell_ebits=jnp.asarray(cell_ebits),
        cell_slot_geom=jnp.asarray(slot_geom),
        cell_slot_core=jnp.asarray(slot_core),
        cell_heavy=jnp.asarray(cell_heavy),
        heavy_edges=jnp.asarray(heavy_edges),
        heavy_ebits=jnp.asarray(heavy_ebits),
        heavy_slot_geom=jnp.asarray(hgeom),
        cell_convex=jnp.asarray(cell_convex),
        convex_edges=jnp.asarray(convex_edges),
        convex_ebits=jnp.asarray(convex_ebits),
        convex_geom=jnp.asarray(convex_geom),
        convex_ybin=jnp.asarray(convex_ybin),
    )
    # host f64 companion for the epsilon-band recheck — a plain attribute,
    # deliberately OUTSIDE the pytree (jit must never device-put it);
    # absent on indexes reconstructed from flattened pytrees or plain
    # deserialization (see HostRecheck.save_arrays for npz round-trips)
    idx.host = HostRecheck(
        cells=uniq.astype(np.int64),
        cell_edges=cell_edges64,
        cell_ebits=cell_ebits,
        cell_slot_geom=slot_geom,
        cell_slot_core=slot_core,
        cell_heavy=cell_heavy,
        heavy_edges=heavy_edges64,
        heavy_ebits=heavy_ebits,
        heavy_slot_geom=hgeom,
        shift=shift64,
        coord_scale=coord_scale,
    )
    # Voronoi adjacency of the convex chip sites — same non-pytree
    # discipline as ``host`` above; consumed by the KNN serve frontend's
    # convex fast path (mosaic_tpu/knn/frontend.py)
    idx.voronoi = _build_voronoi_tables(
        uniq, cell_convex, epc, cell_edges64, convex_geom, shift64
    )
    return idx


@dataclasses.dataclass
class VoronoiTables:
    """Host-side Voronoi adjacency of the convex chip sites (PAPERS.md:
    *A Novel Point Inclusion Test for Convex Polygons Based on Voronoi
    Tessellations*): one site per convex-lane cell (the single chip's
    vertex centroid), with the Delaunay-dual neighbour lists that make
    "move to the adjacent site closer to the query" walks possible.

    The KNN serve frontend (`mosaic_tpu/knn`) uses the walk twice: to
    order ring expansion by neighbour-of-current-nearest, and to derive
    a kth-distance upper bound that collapses the iterative ring loop
    into one guaranteed-cover dispatch. Correctness never depends on the
    adjacency (the ring cover guarantee is what is exact) — adjacency
    quality only affects how tight the bound is, which is why the
    scipy-less fallback (nearest-``DEG`` sites) is sound.

    Like :class:`HostRecheck` this is a plain attribute on the built
    index, deliberately OUTSIDE the pytree — the walk is host work.

    sites:    (Cv, 2) f64 — convex chip vertex centroids (recentred frame).
    adjacency:(Cv, DEG) int32 — neighbouring convex rows, -1 padded.
    geom:     (Cv,) int32 — the site's source polygon row (== convex_geom).
    cell:     (Cv,) int64 — the site's cell id.
    shift:    (2,) f64 — the recenter origin of ``sites`` (same frame as
              :class:`HostRecheck`); walks subtract it from raw queries.
    method:   "delaunay" | "nearest" — how adjacency was derived.
    """

    sites: np.ndarray
    adjacency: np.ndarray
    geom: np.ndarray
    cell: np.ndarray
    shift: np.ndarray
    method: str

    @property
    def num_sites(self) -> int:
        return int(self.sites.shape[0])


def _voronoi_adjacency(sites: np.ndarray):
    """(Cv, DEG) int32 neighbour lists. Prefers the true Delaunay dual
    (scipy, when the container has it); degrades to the nearest-DEG
    heuristic — a superset-free approximation that only loosens the
    walk's bound, never the exactness of the ring cover pass."""
    Cv = sites.shape[0]
    if Cv <= 1:
        return np.full((Cv, 1), -1, dtype=np.int32), "nearest"
    neigh = [set() for _ in range(Cv)]
    method = "nearest"
    if Cv >= 4:
        try:
            from scipy.spatial import Delaunay  # noqa: PLC0415

            tri = Delaunay(sites)
            for simplex in tri.simplices:
                for i in simplex:
                    for j in simplex:
                        if i != j:
                            neigh[i].add(int(j))
            method = "delaunay"
        except Exception:  # lint: broad-except-ok (scipy absent or degenerate site set — the nearest-neighbour fallback below is always available)
            method = "nearest"
    if method == "nearest":
        deg = min(8, Cv - 1)
        d2 = ((sites[:, None, :] - sites[None, :, :]) ** 2).sum(axis=-1)
        np.fill_diagonal(d2, np.inf)
        nearest = np.argsort(d2, axis=1, kind="stable")[:, :deg]
        for i in range(Cv):
            neigh[i].update(int(j) for j in nearest[i])
            # symmetrize so walks can traverse in both directions
            for j in nearest[i]:
                neigh[int(j)].add(i)
    deg = max(1, max(len(s) for s in neigh))
    adj = np.full((Cv, deg), -1, dtype=np.int32)
    for i, s in enumerate(neigh):
        row = sorted(s)
        adj[i, : len(row)] = row
    return adj, method


def _build_voronoi_tables(
    uniq, cell_convex, epc, cell_edges, convex_geom, shift
) -> VoronoiTables:
    """Host: site + adjacency tables over the convex-lane cells, built
    next to the y-bucketed convex tables from the same edge rows."""
    rows = np.nonzero(cell_convex >= 0)[0]
    Cv = rows.size
    sites = np.zeros((Cv, 2), dtype=np.float64)
    cell = np.zeros(Cv, dtype=np.int64)
    for u in rows:
        r = int(cell_convex[u])
        k = int(epc[u])
        # one closed convex ring: the edge 'a' endpoints enumerate the
        # ring's vertices exactly once
        sites[r] = cell_edges[u, :k, 0:2].astype(np.float64).mean(axis=0)
        cell[r] = uniq[u]
    adj, method = _voronoi_adjacency(sites)
    return VoronoiTables(
        sites=sites, adjacency=adj,
        geom=np.asarray(convex_geom, dtype=np.int32), cell=cell,
        shift=np.asarray(shift, dtype=np.float64), method=method,
    )


def _build_convex_tables(
    U, epc, heavy_mask, cell_edges, slot_geom, slot_core, coord_scale
):
    """Host: classify convex-eligible cells and y-bucket their edges.

    A cell qualifies when it is light, holds exactly one non-core chip
    whose edges form one closed convex ring, and every pad-inflated y
    bucket fits CONVEX_EDGE_CAP edges. The bucketed edges are the SAME
    f32 values as the cell's tier-1 row (bit-identity: the lane evaluates
    the identical crossing arithmetic on a subset of edges that provably
    contains every edge the point's scanline can straddle). Buckets are
    inflated by ``pad = 4·EDGE_BAND_K·eps(f32)·coord_scale``: f32
    bucket-index rounding moves a point across a boundary by at most a
    few ulps (< pad), and the epsilon band reaches at most sqrt(eps²)
    <= pad/2 beyond the straddle set while the runtime guard
    ``eps² <= band_guard² = (pad/2)²`` holds.
    """
    KB = CONVEX_BUCKETS
    pad = 4.0 * EDGE_BAND_K * float(np.finfo(np.float32).eps) * coord_scale
    cell_convex = np.full(U, -1, dtype=np.int32)
    picked = []  # (u, (KB, EB) edge-index lists, ymin, inv)
    n_slots = (slot_geom >= 0).sum(axis=1)
    cand = np.nonzero(
        (~heavy_mask)
        & (n_slots == 1)
        & (slot_geom[:, 0] >= 0)
        & (~slot_core[:, 0])
        & (epc >= 3)
    )[0]
    for u in cand:
        k = int(epc[u])
        ef = cell_edges[u, :k].astype(np.float64)  # the probed f32 values
        # one closed ring: each edge's b is the next edge's a (cyclic);
        # multi-ring chips (holes) break the chain and fall out here
        if not np.array_equal(ef[:, 2:4], np.roll(ef[:, 0:2], -1, axis=0)):
            continue
        d = ef[:, 2:4] - ef[:, 0:2]
        cr = d[:, 0] * np.roll(d[:, 1], -1) - d[:, 1] * np.roll(d[:, 0], -1)
        if not (np.all(cr >= 0) or np.all(cr <= 0)):
            continue
        ys = np.concatenate([ef[:, 1], ef[:, 3]])
        ymin, ymax = float(ys.min()), float(ys.max())
        height = ymax - ymin
        if not height > 4.0 * pad:  # degenerate: buckets would alias
            continue
        hb = height / KB
        elo = np.minimum(ef[:, 1], ef[:, 3])
        ehi = np.maximum(ef[:, 1], ef[:, 3])
        buckets = []
        for b in range(KB):
            blo = ymin + b * hb - pad
            bhi = ymin + (b + 1) * hb + pad
            sel = np.nonzero((ehi >= blo) & (elo <= bhi))[0]
            if sel.size > CONVEX_EDGE_CAP:
                buckets = None
                break
            buckets.append(sel)
        if buckets is None:
            continue
        picked.append((u, buckets, np.float32(ymin), np.float32(KB / height)))
    Cv = len(picked)
    if not Cv:
        return (
            cell_convex,
            np.zeros((0, KB, 8, 4), dtype=cell_edges.dtype),
            np.zeros((0, KB, 8), dtype=np.uint32),
            np.zeros((0,), dtype=np.int32),
            np.zeros((0, 3), dtype=np.float32),
        )
    EB = _round8(max(max(s.size for s in bk) for _, bk, _, _ in picked))
    convex_edges = np.zeros((Cv, KB, EB, 4), dtype=cell_edges.dtype)
    convex_ebits = np.zeros((Cv, KB, EB), dtype=np.uint32)
    convex_geom = np.zeros(Cv, dtype=np.int32)
    convex_ybin = np.zeros((Cv, 3), dtype=np.float32)
    for row, (u, buckets, ymin, inv) in enumerate(picked):
        cell_convex[u] = row
        convex_geom[row] = slot_geom[u, 0]
        convex_ybin[row] = (ymin, inv, np.float32((pad / 2.0) ** 2))
        for b, sel in enumerate(buckets):
            convex_edges[row, b, : sel.size] = cell_edges[u, sel]
            convex_ebits[row, b, : sel.size] = 1
    return cell_convex, convex_edges, convex_ebits, convex_geom, convex_ybin


def _probe_slot(pcells: jax.Array, index: ChipIndex) -> jax.Array:
    """(N,) cell ids -> (N,) cell row u, -1 on miss — the multiply-shift
    hash probe (one gather on the slot-packed table when available)."""
    T = index.table_cell.shape[0]
    shift_bits = jnp.uint64(64 - int(np.log2(T)))
    key = (
        (pcells.astype(jnp.uint64) * index.hash_mult[0]) >> shift_bits
    ).astype(jnp.int32)
    if index.table_pack.shape[0]:
        # slot-packed probe: one (N, B) gather carries cell + slot
        low = index.pack_low[0]
        ent = index.table_pack[key]  # (N, B)
        slotp = (ent & low).astype(jnp.int32)
        match = (
            (((ent ^ pcells[:, None]) & ~low) == 0)
            & (slotp > 0)
            & ((pcells[:, None] & low) == index.pack_low[1])
        )
        return jnp.max(jnp.where(match, slotp - 1, -1), axis=1)  # (N,)
    cand_cell = index.table_cell[key]  # (N, B)
    cand_slot = index.table_slot[key]  # (N, B)
    match = (cand_cell == pcells[:, None]) & (cand_slot >= 0)
    return jnp.max(jnp.where(match, cand_slot, -1), axis=1)  # (N,)


def _probe_counts(pcells: jax.Array, index: ChipIndex):
    """Device-side exact compaction-cap inputs: one (3,) array of (found
    count, heavy-cell count, convex-cell count) — `pip_join` pulls these
    ints in a single transfer instead of the whole cell column (32 MB at
    4M points over a ~10 MB/s tunnel)."""
    u = _probe_slot(pcells, index)
    found = u >= 0
    nf = found.sum()
    us = jnp.maximum(u, 0)
    if index.heavy_edges.shape[0]:
        nh = (jnp.where(found, index.cell_heavy[us], -1) >= 0).sum()
    else:
        nh = jnp.zeros((), nf.dtype)
    if index.convex_edges.shape[0]:
        nc = (jnp.where(found, index.cell_convex[us], -1) >= 0).sum()
    else:
        nc = jnp.zeros((), nf.dtype)
    return jnp.stack([nf, nh, nc])


def _ray_parity(px, py, edges, bits, eps2=None):
    """XOR-accumulated crossing parity bits.

    px, py: (...,); edges: (..., E, 4) ax/ay/bx/by; bits: (..., E) uint32
    (0 for pad edges — a zero edge has ay == by so it never straddles).
    Returns (...,) uint32 where bit m is the ray-crossing parity of chip
    slot m. With ``eps2`` (scalar, squared length), additionally returns
    the epsilon-band mask: True where the point lies within sqrt(eps2) of
    any real edge segment — the only geometry where the f32 crossing
    decision can disagree with f64 (fused into the same pass so the edge
    gather is paid once).
    """
    ax, ay = edges[..., 0], edges[..., 1]
    bx, by = edges[..., 2], edges[..., 3]
    pyb, pxb = py[..., None], px[..., None]
    straddle = (ay > pyb) != (by > pyb)
    denom = jnp.where(by == ay, jnp.ones_like(by), by - ay)
    xcross = ax + (pyb - ay) * (bx - ax) / denom
    crossed = straddle & (pxb < xcross)
    vals = jnp.where(crossed, bits, jnp.zeros_like(bits))
    par = jax.lax.reduce(
        vals, np.uint32(0), jax.lax.bitwise_xor, (vals.ndim - 1,)
    )
    if eps2 is None:
        return par
    ex, ey = bx - ax, by - ay
    qx, qy = pxb - ax, pyb - ay
    dd = ex * ex + ey * ey
    t = jnp.clip((qx * ex + qy * ey) / jnp.where(dd == 0, 1.0, dd), 0.0, 1.0)
    rx, ry = qx - t * ex, qy - t * ey
    near = jnp.any((rx * rx + ry * ry <= eps2) & (bits != 0), axis=-1)
    return par, near


def _slot_best(parity, geoms, cores=None):
    """Smallest geom id among hit slots (SENTINEL if none).

    parity: (...,) uint32; geoms: (..., M) int32 (-1 pad);
    cores: (..., M) bool or None.
    """
    Mn = geoms.shape[-1]
    m = jnp.arange(Mn, dtype=jnp.uint32)
    inside = ((parity[..., None] >> m) & jnp.uint32(1)).astype(bool)
    hit = inside if cores is None else (cores | inside)
    hit = hit & (geoms >= 0)
    return jnp.min(jnp.where(hit, geoms, _SENTINEL), axis=-1)


_SCAN_COLS = 2048


def _prefix_inclusive(flag_i32: jax.Array) -> jax.Array:
    """Inclusive prefix sum of (N,) 0/1 int32, N >= 1.

    `jnp.cumsum` lowers to an XLA reduce-window that costs ~22 ms for 4M
    elements on v5e; a row-reshaped prefix by upper-triangular-ones matmul
    runs on the MXU in ~2 ms. f32 HIGHEST keeps counts exact only below
    2^24, so batches that could overflow fall back to the exact cumsum
    (as do small batches, where the matmul setup dominates).
    """
    n = flag_i32.shape[0]
    if n < 4 * _SCAN_COLS or n >= (1 << 24):
        return jnp.cumsum(flag_i32)
    c = _SCAN_COLS
    r = (n + c - 1) // c
    # device-built mask: a module-level numpy constant would bake 16 MB
    # into every executable that traces this
    tri = (
        jax.lax.broadcasted_iota(jnp.int32, (c, c), 0)
        <= jax.lax.broadcasted_iota(jnp.int32, (c, c), 1)
    ).astype(jnp.float32)
    x = jnp.zeros(r * c, jnp.float32).at[: n].set(flag_i32.astype(jnp.float32))
    x2 = x.reshape(r, c)
    p = jax.lax.dot(x2, tri, precision=jax.lax.Precision.HIGHEST)
    rowsum = p[:, -1]
    rowoff = jnp.cumsum(rowsum) - rowsum
    return (p + rowoff[:, None]).reshape(-1)[:n].astype(jnp.int32)


def _compact(flag: jax.Array, cap: int):
    """Stream-compact: indices of up-to-``cap`` True rows (static shape).

    Returns (src (cap,) int32, valid (cap,) bool, overflow (N,) bool,
    pos (N,) int32): ``src`` lists the first ``cap`` flagged row ids
    (padded with 0, masked by ``valid``); ``overflow`` marks flagged rows
    beyond ``cap``; ``pos`` is each row's compacted slot (exclusive
    prefix — meaningful where ``flag``), which lets callers invert the
    compaction by GATHER instead of scatter.

    The scatter destinations are *globally unique*: flagged rows write
    their row id to their exclusive-prefix slot (all distinct, < cap);
    non-flagged rows aim at ``cap + (i - pos_i)`` — strictly increasing
    out-of-bounds slots that ``mode="drop"`` discards. A unique
    no-combiner scatter is the cheapest XLA can lower on TPU: 18.8 ms at
    4M points vs 35.2 ms for the previous sorted min-combiner
    formulation (the single largest op in the traced join step; the
    sorted-add variant also measures 35 ms).
    """
    n = flag.shape[0]
    incl = _prefix_inclusive(flag.astype(jnp.int32))
    pos = incl - flag.astype(jnp.int32)  # exclusive prefix
    iota = jnp.arange(n, dtype=jnp.int32)
    # flagged rows land on pos (<= n); non-flagged on cap+n+(i-pos_i),
    # strictly increasing from cap+n — the two ranges cannot collide, so
    # every index is globally unique even for dropped overflow rows
    dest = jnp.where(flag, pos, cap + n + (iota - pos))
    src = (
        jnp.zeros(cap, dtype=jnp.int32)
        .at[dest]
        .set(iota, unique_indices=True, mode="drop")
    )
    count = incl[-1]
    valid = jnp.arange(cap, dtype=jnp.int32) < count
    return src, valid, flag & (pos >= cap), pos


def _compact_mxu(
    flag: jax.Array,
    cap: int,
    s_cap: int = 256,
    vals: jax.Array | None = None,
):
    """Two-level stream compaction: block-local one-hot int8 matmuls on
    the MXU, then ONE small unique scatter.

    The single global scatter in :func:`_compact` costs ~5 ns per SOURCE
    row on v5e (21.5 ms at 4M — the largest op in the traced join step).
    Here each 2048-row block compacts locally: an (R, C, S) int8 one-hot
    of the block-local prefix positions contracts against the local row
    ids split into two 6-bit factors (exact in int8), yielding每 block's
    first ``s_cap`` flagged row ids; a block's s-th element owns global
    slot ``rowoff[r] + s`` DIRECTLY, so the second level is a unique
    no-combiner scatter of only R*S (~N/8) sources — no second prefix.

    Same contract as :func:`_compact`. Additionally, rows flagged beyond
    ``s_cap`` within one block are reported in the overflow mask (their
    output slots stay invalid), so results are never silently wrong —
    callers retry with a bigger ``s_cap`` exactly like a cap overflow.
    ``s_cap`` must be a multiple of 128 (lane width).

    ``vals`` (optional, (N,) int32 in [0, 2^24)) rides the SAME one-hot
    through one extra batched int8 dot (four 6-bit factors, exact) and
    comes back compacted as a fifth output — cheaper than gathering
    ``vals[src]`` afterwards (the (cap,) gather costs ~4.7 ms at 640k on
    v5e; the extra dot re-reads the already-resident one-hot).
    """
    n = flag.shape[0]
    C = 2048
    pad = (-n) % C
    f = jnp.pad(flag, (0, pad)).reshape(-1, C)  # (R, C)
    R = f.shape[0]
    fi = f.astype(jnp.float32)
    tri = (
        jax.lax.broadcasted_iota(jnp.int32, (C, C), 0)
        <= jax.lax.broadcasted_iota(jnp.int32, (C, C), 1)
    ).astype(jnp.float32)
    incl = jax.lax.dot(
        fi, tri, precision=jax.lax.Precision.HIGHEST
    )  # exact: counts < 2^24
    pos_local = (incl - fi).astype(jnp.int32)  # (R, C) block-local excl
    cnt = incl[:, -1].astype(jnp.int32)  # (R,)
    rowoff = jnp.cumsum(cnt) - cnt  # (R,) global exclusive offsets
    pos = (pos_local + rowoff[:, None]).reshape(-1)[:n]

    sidx = jnp.arange(s_cap, dtype=jnp.int32)
    oh = (
        (pos_local[..., None] == sidx[None, None, :]) & f[..., None]
    ).astype(jnp.int8)  # (R, C, S) — 1 GB at 4M/2048/256
    cloc = jnp.arange(C, dtype=jnp.int32)
    qr = jnp.stack([cloc >> 6, cloc & 63], axis=1).astype(jnp.int8)
    out = jax.lax.dot_general(
        oh, qr, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (R, S, 2) — exact integer accumulation
    lc = out[..., 0] * 64 + out[..., 1]  # block-local row ids
    src_b = lc + (jnp.arange(R, dtype=jnp.int32) * C)[:, None]

    valid_b = sidx[None, :] < jnp.minimum(cnt, s_cap)[:, None]  # (R, S)
    slot_b = rowoff[:, None] + sidx[None, :]  # global slot per (r, s)
    rs = R * s_cap
    # invalid slots start past n: valid slot_b values are <= n, so the
    # two classes stay disjoint even when count exceeds cap (both then
    # drop, but unique_indices must still hold globally)
    dest2 = jnp.where(
        valid_b,
        slot_b,
        cap + n + jnp.arange(rs, dtype=jnp.int32).reshape(R, -1),
    ).reshape(-1)
    src = (
        jnp.zeros(cap, dtype=jnp.int32)
        .at[dest2]
        .set(src_b.reshape(-1), unique_indices=True, mode="drop")
    )
    valid = (
        jnp.zeros(cap, dtype=bool)
        .at[dest2]
        .set(valid_b.reshape(-1), unique_indices=True, mode="drop")
    )
    over = flag & (pos >= cap)
    blk_over = (cnt > s_cap)[:, None] & (pos_local >= s_cap)
    over = over | (flag & blk_over.reshape(-1)[:n])
    if vals is None:
        return src, valid, over, pos
    v = jnp.pad(vals.astype(jnp.int32), (0, pad)).reshape(-1, C)
    v8 = jnp.stack(
        [
            v & 63,
            (v >> 6) & 63,
            (v >> 12) & 63,
            (v >> 18) & 63,
        ],
        axis=-1,
    ).astype(jnp.int8)  # (R, C, 4)
    vout = jax.lax.dot_general(
        oh, v8,
        (((1,), (1,)), ((0,), (0,))),  # contract c, batch r
        preferred_element_type=jnp.int32,
    )  # (R, S, 4)
    vloc = (
        vout[..., 0]
        + (vout[..., 1] << 6)
        + (vout[..., 2] << 12)
        + (vout[..., 3] << 18)
    )
    vals_c = (
        jnp.zeros(cap, dtype=jnp.int32)
        .at[dest2]
        .set(vloc.reshape(-1), unique_indices=True, mode="drop")
    )
    return src, valid, over, pos, vals_c


def _mm_rows(idx: jax.Array, table_f32: jax.Array) -> jax.Array:
    """``table_f32[idx]`` as a one-hot MXU matmul — bit-exact f32 row
    gather.

    Data-dependent row gathers serialize on TPU (~10 GB/s effective on
    the 512 B tier-1 edge rows, ~42 ms at a 640k-point cap); contracting
    a (K, U) one-hot against the (U, D) row table runs on the MXU
    instead. Exactness: each one-hot row has a single 1, and any f32
    value splits exactly into three bf16 terms (Sterbenz: the rounded
    high part is within a factor 2 of the remainder, so each residual
    subtraction is exact); each output element is therefore reassembled
    from <= 3 exact partial products in a f32 accumulator — a bit-exact
    gather, asserted against the real gather in tests.

    idx: (K,) int32 in [0, U); table_f32: (U, D) f32 -> (K, D) f32.
    """
    U = table_f32.shape[0]
    oh = (
        idx[:, None] == jnp.arange(U, dtype=idx.dtype)[None, :]
    ).astype(jnp.bfloat16)
    hi = table_f32.astype(jnp.bfloat16)
    r = table_f32 - hi.astype(jnp.float32)
    mid = r.astype(jnp.bfloat16)
    lo = (r - mid.astype(jnp.float32)).astype(jnp.bfloat16)
    dot = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dot(oh, hi) + dot(oh, mid) + dot(oh, lo)


def _tier1_rows_mxu(us: jax.Array, index: "ChipIndex"):
    """All tier-1 per-cell rows for slots ``us`` in ONE MXU lookup.

    Packs cell_edges / cell_ebits (split into exact 16-bit halves) /
    cell_slot_geom / cell_slot_core / cell_heavy into a single (U, D)
    f32 matrix so the one-hot operand is built and contracted once.
    Integer fields survive exactly: every value (parity-bit halves
    <= 65535, geom/heavy ids < 2^24, bools) is an integer exactly
    representable in f32. Returns (edges (K, E1, 4), ebits (K, E1) u32,
    geoms (K, M1) i32, cores (K, M1) bool, heavy (K,) i32).
    """
    U, E1 = index.cell_ebits.shape
    M1 = index.cell_slot_geom.shape[1]
    eb = index.cell_ebits
    tab = jnp.concatenate(
        [
            index.cell_edges.reshape(U, E1 * 4).astype(jnp.float32),
            (eb >> jnp.uint32(16)).astype(jnp.float32),
            (eb & jnp.uint32(0xFFFF)).astype(jnp.float32),
            index.cell_slot_geom.astype(jnp.float32),
            index.cell_slot_core.astype(jnp.float32),
            index.cell_heavy.astype(jnp.float32)[:, None],
        ],
        axis=1,
    )
    out = _mm_rows(us, tab)
    o = E1 * 4
    edges = out[:, :o].reshape(-1, E1, 4)
    hi16, lo16 = out[:, o : o + E1], out[:, o + E1 : o + 2 * E1]
    o += 2 * E1
    ebits = (hi16.astype(jnp.uint32) << jnp.uint32(16)) | lo16.astype(
        jnp.uint32
    )
    geoms = out[:, o : o + M1].astype(jnp.int32)
    cores = out[:, o + M1 : o + 2 * M1] > 0.5
    heavy = out[:, o + 2 * M1].astype(jnp.int32)
    return edges, ebits, geoms, cores, heavy


def _heavy_rows_mxu(h2: jax.Array, index: "ChipIndex"):
    """Heavy-table rows for slots ``h2`` via the one-hot MXU lookup —
    same exactness argument as :func:`_tier1_rows_mxu` (the heavy one-hot
    is tiny: (K2, H) with H typically < 128)."""
    H, E2 = index.heavy_ebits.shape
    M2 = index.heavy_slot_geom.shape[1]
    eb = index.heavy_ebits
    tab = jnp.concatenate(
        [
            index.heavy_edges.reshape(H, E2 * 4).astype(jnp.float32),
            (eb >> jnp.uint32(16)).astype(jnp.float32),
            (eb & jnp.uint32(0xFFFF)).astype(jnp.float32),
            index.heavy_slot_geom.astype(jnp.float32),
        ],
        axis=1,
    )
    out = _mm_rows(h2, tab)
    o = E2 * 4
    edges = out[:, :o].reshape(-1, E2, 4)
    hi16, lo16 = out[:, o : o + E2], out[:, o + E2 : o + 2 * E2]
    ebits = (hi16.astype(jnp.uint32) << jnp.uint32(16)) | lo16.astype(
        jnp.uint32
    )
    geoms = out[:, o + 2 * E2 : o + 2 * E2 + M2].astype(jnp.int32)
    return edges, ebits, geoms


def _heavy_tier(
    px, py, hs, index, heavy_cap, k2_default, out_len, eps2,
    lookup="gather", compaction="scatter", compact_block=256,
    engine="gather",
):
    """Tier 2, shared by every probe plumbing mode: compact the rows whose
    cell is heavy, probe the wide rows, scatter back to ``out_len``.

    ``engine="pallas"`` runs the probe through the tiled
    :func:`~mosaic_tpu.kernels.pip.pip_heavy_tiled` kernel (heavy tables
    pinned in VMEM, bit-identical crossing arithmetic) instead of the
    row-gather + `_ray_parity` pipeline; interpret mode is selected
    automatically off-TPU so CPU tests exercise the same kernel.

    Returns (best2 (out_len,), over2 (out_len,) overflow mask,
    near2 (out_len,) | None when ``eps2`` is None)."""
    with jax.named_scope("pip.tier2"):
        return _heavy_tier_impl(
            px, py, hs, index, heavy_cap, k2_default, out_len, eps2,
            lookup, compaction, compact_block, engine,
        )


def _heavy_tier_impl(
    px, py, hs, index, heavy_cap, k2_default, out_len, eps2,
    lookup, compaction, compact_block, engine,
):
    K2 = int(heavy_cap) if heavy_cap else k2_default
    K2 = max(8, min(K2, k2_default))
    if compaction == "mxu" and hs.shape[0] >= (1 << 16):
        src2, valid2, over2, _ = _compact_mxu(hs >= 0, K2, compact_block)
    else:
        src2, valid2, over2, _ = _compact(hs >= 0, K2)
    h2 = jnp.maximum(hs[src2], 0)
    # one (K2, 2) gather, not two serialized column gathers (see tier 1)
    pq2 = jnp.stack([px, py], axis=1)[src2]
    if engine == "pallas":
        from ..kernels.pip import pip_heavy_tiled

        rows2 = jnp.where(valid2, h2, -1)
        best2k, near2 = pip_heavy_tiled(
            pq2[:, 0], pq2[:, 1], rows2,
            index.heavy_edges, index.heavy_ebits, index.heavy_slot_geom,
            eps2=eps2, interpret=jax.default_backend() != "tpu",
        )
        if near2 is None and eps2 is not None:  # pragma: no cover
            near2 = jnp.zeros(pq2.shape[0], bool)
    else:
        if lookup == "mxu":
            hedges, hebits, hgeoms = _heavy_rows_mxu(h2, index)
        else:
            hedges, hebits = index.heavy_edges[h2], index.heavy_ebits[h2]
            hgeoms = index.heavy_slot_geom[h2]
        r2 = _ray_parity(pq2[:, 0], pq2[:, 1], hedges, hebits, eps2=eps2)
        par2, near2 = r2 if eps2 is not None else (r2, None)
        best2k = _slot_best(par2, hgeoms)  # invalid slots never land (drop)
    # unique no-combiner scatter back (see _compact): valid src2 row ids
    # are unique; invalid slots drop via distinct out-of-bounds dests
    dest2 = jnp.where(
        valid2, src2, out_len + jnp.arange(src2.shape[0], dtype=jnp.int32)
    )
    best2 = (
        jnp.full(out_len, _SENTINEL, dtype=jnp.int32)
        .at[dest2]
        .set(best2k, unique_indices=True, mode="drop")
    )
    near_sc = (
        jnp.zeros(out_len, bool)
        .at[dest2]
        .set(near2, unique_indices=True, mode="drop")
        if eps2 is not None
        else None
    )
    return best2, over2, near_sc


#: lanes a forced-adaptive probe can pin (MOSAIC_PROBE_FORCE_LANE)
_PROBE_LANES = ("light", "heavy", "convex")


def _probe_modes():
    return ("scatter", "adaptive") + tuple(
        f"adaptive-{ln}" for ln in _PROBE_LANES
    )


def resolve_probe_mode(probe: str) -> str:
    """Normalize a ``probe`` argument, folding in the force-lane env knob.

    ``MOSAIC_PROBE_FORCE_LANE=light|heavy|convex`` rewrites ``adaptive``
    to the pinned variant ``adaptive-<lane>`` HERE — before the value
    reaches any jit static argument — so the knob can never be baked
    stale into a compiled program's cache entry.
    """
    if probe not in _probe_modes():
        raise ValueError(
            f"probe must be one of {_probe_modes()}, got {probe!r}"
        )
    if probe == "adaptive":
        lane = os.environ.get("MOSAIC_PROBE_FORCE_LANE", "").strip().lower()
        if lane:
            if lane not in _PROBE_LANES:
                raise ValueError(
                    f"MOSAIC_PROBE_FORCE_LANE must be one of "
                    f"{_PROBE_LANES}, got {lane!r}"
                )
            return f"adaptive-{lane}"
    return probe


def pip_join_points(
    points: jax.Array,
    pcells: jax.Array,
    index: ChipIndex,
    heavy_cap: int | None = None,
    found_cap: int | None = None,
    edge_eps2: jax.Array | None = None,
    writeback: str = "scatter",
    lookup: str = "gather",
    compaction: str = "scatter",
    compact_block: int = 256,
    probe: str = "scatter",
    convex_cap: int | None = None,
) -> jax.Array:
    """(N,) int32 — smallest matching polygon row per point, -1 if none.

    Jittable (``heavy_cap``/``found_cap`` static); shard the point axis over
    a mesh and replicate ``index``. Probe = hash lookup (1 gather), then
    stream-compaction of the points whose cell exists in the index (misses
    skip all edge work — on sparse workloads most points stop here), then a
    flat bounded edge gather + XOR crossing parity; points in heavy cells
    are compacted once more for the tier-2 gather.

    ``found_cap`` bounds how many points per call may hit an indexed cell
    and ``heavy_cap`` how many may land in heavy cells. Both default to
    their exact upper bound (N / found_cap), so an uncapped call is always
    exact — tighter caps are a performance knob. If a cap is exceeded the
    excess points return :data:`OVERFLOW` (-2) instead of a wrong answer;
    `pip_join` sizes both caps exactly from device-side counts.

    ``edge_eps2`` (scalar array, squared length) switches on the epsilon
    band: returns ``(out, near)`` where ``near`` marks points within
    sqrt(edge_eps2) of any probed chip edge — the set whose f32 parity may
    disagree with f64 (`pip_join` rechecks them on the host oracle).

    ``compaction="mxu"`` (with ``compact_block``) switches stream
    compaction to block-local one-hot int8 matmuls (`_compact_mxu`):
    identical results while no 2048-point block holds more than
    ``compact_block`` found points; beyond that the affected points
    return :data:`OVERFLOW` (never a wrong answer) — size
    ``compact_block`` to ~6 sigma above the expected per-block found
    count (256 covers found rates up to ~9%).

    ``writeback`` picks the probe plumbing — identical results, a TPU
    autotuning knob the bench measures and picks the winner of:
    ``"scatter"`` compacts found points then returns results via a
    unique-destination set scatter; ``"gather"`` compacts but inverts by
    per-point gather of
    the prefix slot; ``"direct"`` skips tier-1 compaction entirely —
    every point gathers its own 512 B edge row (wasted gathers on misses,
    but no prefix scan, no point permutation and no writeback, which cost
    ~65 ms combined at 4M on v5e while the full row-gather runs ~30 ms;
    ``found_cap`` is ignored and tier-1 overflow is impossible).

    ``probe="adaptive"`` switches on per-cell density routing inside this
    one jitted program: light cells keep the tier-1 path above, heavy
    cells run tier 2 through the tiled Pallas kernel
    (:func:`~mosaic_tpu.kernels.pip.pip_heavy_tiled`, interpret mode off
    TPU), and convex single-chip cells divert to a y-bucketed
    reduced-edge test sized by ``convex_cap`` (default exact: N).
    Results are bit-identical to ``probe="scatter"`` — the kernel
    reproduces `_ray_parity`'s evaluation order and the convex tables
    hold the same f32 edge values as tier 1. ``adaptive-light`` /
    ``adaptive-heavy`` / ``adaptive-convex`` pin one lane for isolation
    (benchmarks, the CI probe-smoke gate); `resolve_probe_mode` folds
    the ``MOSAIC_PROBE_FORCE_LANE`` env knob into these pinned values
    before jit ever sees the argument. Convex-lane overflow returns
    :data:`OVERFLOW`, exactly like the other caps.
    """
    if writeback not in ("scatter", "gather", "direct"):
        raise ValueError(
            f"writeback must be scatter|gather|direct, got {writeback!r}"
        )
    if lookup not in ("gather", "mxu", "mxu2"):
        raise ValueError(f"lookup must be gather|mxu|mxu2, got {lookup!r}")
    if compaction not in ("scatter", "mxu"):
        raise ValueError(
            f"compaction must be scatter|mxu, got {compaction!r}"
        )
    if compact_block % 128:
        raise ValueError(
            f"compact_block must be a multiple of 128 (TPU lane width), "
            f"got {compact_block}"
        )
    # validate only — no env fold here: this function is jit-traced
    # (`dispatch.jit_join` keys its compile cache on the UNRESOLVED
    # `probe` static arg), so reading MOSAIC_PROBE_FORCE_LANE at this point
    # would bake the first-seen lane into the cached program. Host-side
    # entry points (pip_join, stream, serve, dist_join) fold the knob
    # via `resolve_probe_mode` before staging.
    if probe not in _probe_modes():
        raise ValueError(
            f"probe must be one of {_probe_modes()}, got {probe!r}"
        )
    adaptive = probe != "scatter"
    if adaptive and writeback == "direct":
        raise ValueError(
            "probe='adaptive' routes through compaction; it composes "
            "with writeback scatter|gather, not direct"
        )
    if lookup != "gather" and (
        writeback == "direct" or index.cell_edges.dtype != jnp.float32
    ):
        # direct mode probes ALL N points (a (N, U) one-hot would not
        # fit), and the 3-term bf16 split is exact only for f32 tables
        lookup = "gather"
    N = points.shape[0]
    # named scopes mark the probe stages in traces so the streaming
    # pipeline's overlap (cell assign vs these passes) is attributable
    with jax.named_scope("pip.hash_probe"):
        u = _probe_slot(pcells, index)
    found = u >= 0
    banded_d = edge_eps2 is not None
    H = int(index.heavy_edges.shape[0])
    CV = int(index.convex_edges.shape[0])
    # adaptive per-cell routing: the density class is a table lookup, so
    # the route costs one extra (N,) gather. Convex cells leave the light
    # lane; heavy POINTS stay in it (their tier-1 row holds the cell's
    # core/light chips — the Pallas lane replaces only the tier-2 probe).
    use_convex = adaptive and CV > 0 and probe in ("adaptive", "adaptive-convex")
    heavy_engine = (
        "pallas"
        if adaptive
        and probe in ("adaptive", "adaptive-heavy")
        and index.heavy_edges.dtype == jnp.float32
        else "gather"
    )
    if use_convex:
        with jax.named_scope("pip.route"):
            cvrow = jnp.where(
                found, index.cell_convex[jnp.maximum(u, 0)], -1
            )
            conv = cvrow >= 0
            if banded_d:
                # band exactness holds only while eps² fits under the
                # bucket pad guard; wider bands fall back to tier 1
                guard2 = index.convex_ybin[jnp.maximum(cvrow, 0), 2]
                conv = conv & (edge_eps2 <= guard2)
    else:
        conv = None

    if writeback == "direct":
        us = jnp.maximum(u, 0)

        def _direct_tier1(args):
            px_c, py_c, us_c = args
            r = _ray_parity(
                px_c, py_c,
                index.cell_edges[us_c], index.cell_ebits[us_c],
                eps2=edge_eps2,
            )
            par, near = r if banded_d else (r, None)
            b = _slot_best(
                par, index.cell_slot_geom[us_c], index.cell_slot_core[us_c]
            )
            return (b, near) if banded_d else b

        # the un-compacted (N, E1, 4) edge intermediate crosses XLA's
        # 2 GB buffer limit above ~2M points (tpu_compile_helper crash,
        # observed at 4M on v5e): chunk the tier-1 row work via lax.map
        CH = _DIRECT_CHUNK
        if N > CH:
            pad = (-N) % CH
            px_p = jnp.pad(points[:, 0], (0, pad))
            py_p = jnp.pad(points[:, 1], (0, pad))
            us_p = jnp.pad(us, (0, pad))
            n_ch = (N + pad) // CH
            res = jax.lax.map(
                _direct_tier1,
                (
                    px_p.reshape(n_ch, CH),
                    py_p.reshape(n_ch, CH),
                    us_p.reshape(n_ch, CH),
                ),
            )
            if banded_d:
                best = res[0].reshape(-1)[:N]
                near1 = res[1].reshape(-1)[:N]
            else:
                best = res.reshape(-1)[:N]
        else:
            r1 = _direct_tier1((points[:, 0], points[:, 1], us))
            best, near1 = r1 if banded_d else (r1, None)
        best = jnp.where(found, best, _SENTINEL)
        if H:
            hs = jnp.where(found, index.cell_heavy[us], -1)
            best2, over2, near_sc = _heavy_tier(
                points[:, 0], points[:, 1], hs, index, heavy_cap, N, N,
                edge_eps2,
            )
            best = jnp.minimum(best, best2)
            best = jnp.where(over2, _OVF_MARK, best)
            if banded_d:
                near1 = near1 | near_sc
        out = jnp.where(best == _SENTINEL, -1, best).astype(jnp.int32)
        out = jnp.where(best == _OVF_MARK, OVERFLOW, out)
        if banded_d:
            return out, near1 & found
        return out

    light = found if conv is None else (found & ~conv)
    K1 = int(found_cap) if found_cap else N
    K1 = max(8, min(K1, N))
    if compaction == "mxu" and N >= (1 << 16):
        # (the vals channel could also carry u through the one-hot, but
        # the extra batched dot re-reads the 1 GB one-hot and measured
        # SLOWER than the (K1,) gather below: 87.0 vs 84.2 ms/iter)
        src1, valid1, over1, pos1 = _compact_mxu(light, K1, compact_block)
    else:
        src1, valid1, over1, pos1 = _compact(light, K1)
    us = jnp.maximum(u[src1], 0)  # (K1,)
    # ONE (K1, 2) row gather: indexing the columns separately makes XLA
    # emit two serialized point gathers (traced at ~14 ms EACH at 4M/640k)
    pxy = points[src1]
    px, py = pxy[:, 0], pxy[:, 1]

    banded = edge_eps2 is not None
    with jax.named_scope("pip.tier1"):
        if lookup in ("mxu", "mxu2"):
            edges1, ebits1, geoms1, cores1, heavy1 = _tier1_rows_mxu(
                us, index
            )
        else:
            edges1, ebits1 = index.cell_edges[us], index.cell_ebits[us]
            geoms1 = index.cell_slot_geom[us]
            cores1 = index.cell_slot_core[us]
            heavy1 = index.cell_heavy[us]
        r1 = _ray_parity(px, py, edges1, ebits1, eps2=edge_eps2)
        parity, near1 = r1 if banded else (r1, None)
        best1 = _slot_best(parity, geoms1, cores1)
        best1 = jnp.where(valid1, best1, _SENTINEL)

    if H:
        # tier 2: compact again to the points whose cell is heavy
        hs = jnp.where(valid1, heavy1, -1)
        # measured on v5e/NYC: the MXU lookup wins tier 1 but not the
        # 6 KB heavy rows (gathers get efficient at that row size), so
        # "mxu" keeps tier 2 on the gather path and "mxu2" forces both
        best2, over2, near_sc = _heavy_tier(
            px, py, hs, index, heavy_cap, K1, K1, edge_eps2,
            lookup="mxu" if lookup == "mxu2" else "gather",
            compaction=compaction, compact_block=compact_block,
            engine=heavy_engine,
        )
        best1 = jnp.minimum(best1, best2)
        # an overflowed tier-2 point has an unknown answer even if tier 1
        # hit: mark it (each compacted row writes its own unique slot, so
        # the mark survives the writeback scatter verbatim)
        best1 = jnp.where(over2, _OVF_MARK, best1)
        if banded:
            near1 = near1 | near_sc

    if use_convex:
        # convex lane: compact, y-bucket, probe at most EB edges/point.
        # The single-chip eligibility contract makes `parity bit 0 set ->
        # that chip's geom` exactly _slot_best on the cell's tier-1 row.
        K3 = int(convex_cap) if convex_cap else N
        K3 = max(8, min(K3, N))
        with jax.named_scope("pip.convex"):
            if compaction == "mxu" and N >= (1 << 16):
                src3, valid3, over3, pos3 = _compact_mxu(
                    conv, K3, compact_block
                )
            else:
                src3, valid3, over3, pos3 = _compact(conv, K3)
            cv3 = jnp.maximum(cvrow[src3], 0)
            pq3 = points[src3]
            px3, py3 = pq3[:, 0], pq3[:, 1]
            yb = index.convex_ybin[cv3]
            KB = int(index.convex_edges.shape[1])
            EB = int(index.convex_edges.shape[2])
            b3 = jnp.clip(
                jnp.floor((py3 - yb[:, 0]) * yb[:, 1]).astype(jnp.int32),
                0, KB - 1,
            )
            flat3 = cv3 * KB + b3
            ce = index.convex_edges.reshape(CV * KB, EB, 4)[flat3]
            cb = index.convex_ebits.reshape(CV * KB, EB)[flat3]
            r3 = _ray_parity(px3, py3, ce, cb, eps2=edge_eps2)
            par3, near3 = r3 if banded else (r3, None)
            g3 = index.convex_geom[cv3]
            hit3 = ((par3 & jnp.uint32(1)) == 1) & (g3 >= 0) & valid3
            best3 = jnp.where(hit3, g3, _SENTINEL)
    else:
        best3 = near3 = over3 = None

    # return compacted results to the full point axis. Valid src1 row ids
    # are unique by construction; invalid slots divert to distinct
    # out-of-bounds destinations that mode="drop" discards — a unique
    # no-combiner scatter (see _compact for the measured win over
    # combiner scatters). The convex lane's rows are disjoint from the
    # light lane's, so its scatter chains onto the same buffer.
    if writeback == "gather":
        slot = jnp.clip(pos1, 0, K1 - 1)
        best = jnp.where(light, best1[slot], _SENTINEL)
        if use_convex:
            slot3 = jnp.clip(pos3, 0, K3 - 1)
            best = jnp.where(conv, best3[slot3], best)
    else:
        wdest = jnp.where(
            valid1, src1, N + jnp.arange(K1, dtype=jnp.int32)
        )
        best = (
            jnp.full(N, _SENTINEL, dtype=jnp.int32)
            .at[wdest]
            .set(best1, unique_indices=True, mode="drop")
        )
        if use_convex:
            wdest3 = jnp.where(
                valid3, src3, N + jnp.arange(K3, dtype=jnp.int32)
            )
            best = best.at[wdest3].set(
                best3, unique_indices=True, mode="drop"
            )
    out = jnp.where(best == _SENTINEL, -1, best).astype(jnp.int32)
    out = jnp.where(best == _OVF_MARK, OVERFLOW, out)
    out = jnp.where(over1, OVERFLOW, out)
    if use_convex:
        out = jnp.where(over3, OVERFLOW, out)
    if banded:
        if writeback == "gather":
            near = light & ~over1 & near1[slot]
            if use_convex:
                near = jnp.where(conv, ~over3 & near3[slot3], near)
        else:
            near = (
                jnp.zeros(N, bool)
                .at[wdest]
                .set(near1, unique_indices=True, mode="drop")
            )
            if use_convex:
                near = near.at[wdest3].set(
                    near3, unique_indices=True, mode="drop"
                )
        return out, near
    return out


# the jitted join/counts/compact executables and the cell-assignment
# program cache are owned by the dispatch core (`dispatch/core.py`) —
# one compile cache shared by batch, stream, serve, raster, and the
# sharded lane. `_dispatch.jit_join()` et al. hand back the process-wide
# wrappers; this module keeps only thin legacy views below.


def _next_pow2(n: int, lo: int = 16) -> int:
    return max(lo, 1 << int(np.ceil(np.log2(max(n, 1)))))


def join_cache_stats(emit: bool = True) -> dict:
    """Legacy view over the unified dispatch cache registry
    (`dispatch.cache_stats` is the full surface).

    ``{"cells_prog": {hits, misses, maxsize, currsize}, "jit_join":
    n_cached, "jit_compact": n_cached}`` — the `cells_prog` lru entry
    count is the number of live (index system, resolution, variant)
    program keys (each PINS its index-system object for the cache's
    lifetime), and the jit sizes count compiled (shape, static-args)
    specializations. Emits one ``join_cache_stats`` telemetry event
    (``emit=False`` reads silently) so long-running servers can chart
    growth and decide when to call :func:`clear_join_caches`.
    """
    stats = _dispatch.join_cache_view()
    if emit:
        _telemetry.record("join_cache_stats", **stats)
    return stats


def clear_join_caches() -> dict:
    """Release the join-owned slice of the dispatch caches (cell
    programs plus the shared join/compact compile caches — they regrow
    on next use; the next call per shape pays one recompile); returns
    the pre-clear :func:`join_cache_stats`. `dispatch.clear_caches`
    drops EVERY dispatch cache. Emits ``join_caches_cleared`` telemetry.
    """
    stats = join_cache_stats(emit=False)
    _dispatch.clear_caches(
        names=("cells_prog", "jit_join", "jit_counts", "jit_compact"),
        emit=False,
    )
    _telemetry.record("join_caches_cleared", **stats)
    return stats


#: below this batch size on CPU, eager per-op dispatch of the cell
#: pipeline beats its XLA compile (measured ~1 min+ for the unrolled H3
#: digit pipeline on CPU x64). On accelerators always jit: eager would pay
#: the ~28 ms tunnel RTT per op, and the compile caches across batches.
_JIT_CELLS_MIN = 65536


def _assign_cells(index_system, resolution: int, dev: jax.Array, variant: str):
    if (
        dev.shape[0] >= _JIT_CELLS_MIN
        or jax.devices()[0].platform != "cpu"
    ):
        return _dispatch.cells_prog(index_system, resolution, variant)(dev)
    if variant == "margin":
        return index_system.point_to_cell_margin(dev, resolution)
    if variant == "alt":
        return index_system.point_to_cell_alt(dev, resolution)
    return index_system.point_to_cell(dev, resolution)


def pip_join(
    points: np.ndarray | jax.Array,
    polygons: PackedGeometry | None,
    index_system: IndexSystem,
    resolution: "int | None" = None,
    chip_index: ChipIndex | None = None,
    batch_size: int | None = None,
    recheck: bool | None = None,
    cell_dtype=None,
    writeback: "str | None" = None,
    lookup: str | None = None,
    cell_margin_k: float | None = None,
    edge_band_k: float | None = None,
    probe: "str | None" = None,
    mesh=None,
    profile=None,
) -> np.ndarray:
    """Managed join (reference: `PointInPolygonJoin.join` auto-indexes both
    sides, `sql/join/PointInPolygonJoin.scala:86-97`).

    Tessellates ``polygons`` (unless a prebuilt ``chip_index`` is passed),
    assigns cells to ``points`` on device and returns the matched polygon
    row per point (-1 = no polygon). ``batch_size`` chunks the point axis
    to bound the probe intermediates. Compaction caps are sized exactly
    from two device-side scalar counts (no cell column ever crosses back
    to the host), so no point can overflow. Should a cap overflow anyway
    (shrunken by `runtime.faults` injection, or user-adversarial inputs),
    the bounded escalation engine (`runtime/escalate.py`) regrows every
    cap geometrically until the answer is exact or raises a typed
    :class:`~mosaic_tpu.runtime.CapacityOverflow` — :data:`OVERFLOW`
    rows never escape this API. Transient device failures retry with
    backoff (`runtime/retry.py`); past the budget the call degrades to
    the exact f64 host oracle and the result is flagged
    :class:`~mosaic_tpu.runtime.DegradedResult`.

    ``recheck`` (default: the ``exact_recheck`` config flag) switches on
    the epsilon-band borderline recheck — the SURVEY §7 precision
    contract: points whose cell-rounding margin or chip-edge distance is
    within a few ulps of flipping are re-evaluated exactly. Escalation is
    tiered so the exact host oracle only sees genuine ties: borderline
    cell assignments first re-join against the runner-up cell ON DEVICE
    (`IndexSystem.point_to_cell_alt`); only points where the two
    candidate answers differ — plus cell-corner neighborhoods, invalid
    alternates, and edge-band points — go to the f64 host path
    (:func:`host_join`). Requires the index's ``host`` companion (present
    on any `build_chip_index` product).

    ``cell_dtype`` forces the dtype cells are computed in (default: the
    input device array's dtype — f32 on TPU) — lets CPU/x64 tests
    reproduce TPU f32 behavior exactly.

    ``writeback`` selects the probe plumbing (``scatter``/``gather``/
    ``direct`` — see :func:`pip_join_points`); results are identical,
    the bench autotunes the winner per workload. ``lookup`` picks the
    tier-1 row access (``gather``/``mxu`` one-hot matmul); default None
    auto-selects ``mxu`` on accelerators for f32 indexes.

    ``cell_margin_k`` / ``edge_band_k`` override the calibrated band
    constants :data:`CELL_MARGIN_K` / :data:`EDGE_BAND_K` for this call —
    the `tools/calibrate_margins.py` sweep knob (wider bands stay exact
    but recheck more; narrower bands below the measured drift ceiling
    lose the exactness contract).

    ``probe="adaptive"`` turns on per-cell density routing (light cells
    on the tier-1 path, heavy cells through the tiled Pallas kernel,
    convex single-chip cells through the y-bucketed reduced-edge test) —
    bit-identical results, a throughput knob. ``adaptive-light`` /
    ``adaptive-heavy`` / ``adaptive-convex`` pin a single lane (also
    reachable via ``MOSAIC_PROBE_FORCE_LANE`` when ``probe="adaptive"``);
    requires a compaction writeback (not ``direct``).

    ``mesh`` routes each chunk through the dispatch core's bucketed
    data-parallel lane (`dispatch.DispatchCore`): points padded to the
    ladder bucket and sharded over a 1-D mesh with the ChipIndex
    replicated, full per-shard caps (no count sync, no escalation — the
    serve path's compile discipline), bit-identical to single-device.
    Accepts a device count, a 1-D `jax.sharding.Mesh`, or None (the
    ``MOSAIC_MESH`` env knob, resolved once per call). Requires
    ``recheck=False`` — the epsilon-band path stays single-device.

    ``profile`` takes a `tune.TuningProfile`; its knobs apply with the
    one documented precedence — explicit argument > env knob > profile >
    built-in default (`mosaic_tpu/tune/resolve.py`). Profile-consumed
    knobs here: ``resolution``, ``probe``, ``writeback``, ``lookup``,
    ``batch_size`` (pass ``batch_size=0`` to explicitly force the
    unbatched path past a profile's recommendation).
    """
    from ..tune.resolve import resolve_knobs

    # profile-consumed knobs fold HERE, at the host entry point, before
    # anything is staged (env-read-after-staging discipline)
    knobs = resolve_knobs(
        "pip_join", profile,
        explicit={
            "resolution": resolution, "probe": probe,
            "writeback": writeback, "lookup": lookup,
            "batch_size": batch_size,
        },
        defaults={
            "resolution": None, "probe": "scatter", "writeback": "scatter",
            "lookup": None, "batch_size": None,
        },
    )
    resolution, writeback, lookup = (
        knobs["resolution"], knobs["writeback"], knobs["lookup"]
    )
    batch_size = knobs["batch_size"] or None  # 0 = explicitly unbatched
    if resolution is None:
        raise ValueError(
            "pip_join needs a resolution — pass it explicitly or via a "
            "profile that recommends one"
        )
    resolution = index_system.resolution_arg(resolution)
    probe = resolve_probe_mode(knobs["probe"])
    if probe != "scatter" and writeback == "direct":
        raise ValueError(
            "probe='adaptive' requires writeback scatter|gather"
        )
    if chip_index is None:
        table = tessellate(polygons, index_system, resolution, keep_core_geoms=False)
        chip_index = build_chip_index(table)
    if recheck is None:
        from ..context import current_config

        recheck = current_config().exact_recheck
    mesh = _dispatch.resolve_mesh(mesh)
    if mesh is not None and recheck:
        raise ValueError(
            "pip_join(mesh=...) runs the bucketed sharded dispatch lane, "
            "which does not support the epsilon-band recheck yet — pass "
            "recheck=False (or drop the mesh for the exact-recheck path)"
        )
    host: HostRecheck | None = getattr(chip_index, "host", None)
    if recheck and host is None:
        raise ValueError(
            "exact_recheck needs the index's f64 host companion — present "
            "on build_chip_index products; rebuild the index in-process "
            "or restore it via HostRecheck.from_arrays"
        )
    raw = np.asarray(points, dtype=np.float64)
    # shift in f64 first, narrow after (keeps f32 ulp small near the data)
    shift = (
        host.shift
        if host is not None
        else np.asarray(chip_index.border.shift, dtype=np.float64)
    )
    dtype = chip_index.border.verts.dtype
    if lookup is None:
        lookup = (
            "mxu"
            if jax.devices()[0].platform != "cpu" and dtype == jnp.float32
            else "gather"
        )
    n = raw.shape[0]
    core = (
        None
        if mesh is None
        else _dispatch.core_for(
            chip_index, index_system, resolution,
            writeback=writeback, lookup=lookup, probe=probe,
            cell_dtype=cell_dtype, mesh=mesh,
        )
    )

    def run(chunk: np.ndarray) -> np.ndarray:
        if core is not None:
            # the sharded bucketed lane: pad to the ladder, dispatch
            # data-parallel with full per-shard caps (overflow
            # structurally impossible — no count sync, no escalation),
            # slice the pad off. RetryExhausted falls through to
            # `run_resilient`'s host-oracle degradation like every lane.
            padded, nn = core.ladder.pad(chunk)
            return _dispatch.guarded_call(
                "pip_join.device", core.execute_padded, padded
            )[:nn]
        dev = jnp.asarray(chunk)
        if cell_dtype is not None:
            dev = dev.astype(cell_dtype)
        if recheck:
            cells, margins = _assign_cells(
                index_system, resolution, dev, "margin"
            )
        else:
            cells = _assign_cells(index_system, resolution, dev, "cells")
            margins = None
        # exact cap sizing from two scalars (pow2-bucketed to bound the
        # number of distinct compiled programs) — overflow impossible.
        # Direct mode has no tier-1 compaction: found_cap is unused, so
        # None keeps the jit static key stable across batches (and with
        # no heavy cells the count sync is skipped entirely).
        if writeback == "direct":
            fcap = None
            hcap = (
                min(
                    _next_pow2(
                        int(np.asarray(
                            _dispatch.jit_counts()(cells, chip_index)
                        )[1]) + 1
                    ),
                    chunk.shape[0],
                )
                if chip_index.num_heavy_cells
                else None
            )
            caps = _faults.clamp_caps({"heavy_cap": hcap})
            hcap = caps["heavy_cap"]
            ccap = None
        else:
            nf, nh, nc = (
                int(v)
                for v in np.asarray(_dispatch.jit_counts()(cells, chip_index))
            )
            fcap = min(_next_pow2(nf + 1), chunk.shape[0])
            hcap = (
                min(_next_pow2(nh + 1), fcap)
                if chip_index.num_heavy_cells
                else None
            )
            ccap = (
                min(_next_pow2(nc + 1), chunk.shape[0])
                if probe != "scatter" and chip_index.num_convex_cells
                else None
            )
            # fault injection may clamp the exactly-sized caps (no-op
            # without an active plan); the escalation loop grows them back
            caps = _faults.clamp_caps(
                {"found_cap": fcap, "heavy_cap": hcap, "convex_cap": ccap}
            )
            fcap, hcap, ccap = (
                caps["found_cap"], caps["heavy_cap"], caps["convex_cap"]
            )
            if probe != "scatter":
                # lane populations for trails/dashboards: how the router
                # splits this chunk (convex leaves the light lane; heavy
                # points pay both tier 1 and the Pallas tier 2)
                _telemetry.record(
                    "probe_route", n=chunk.shape[0], probe=probe,
                    found=nf, heavy=nh, convex=nc,
                    light=nf - nc,
                )
        shifted = jnp.asarray(chunk - shift, dtype=dtype)
        # every cap that exists escalates together toward the row-count
        # ceiling, at which overflow is structurally impossible
        grow = {k: v for k, v in caps.items() if v is not None}
        ceilings = {k: chunk.shape[0] for k in grow}
        if not recheck:

            def attempt(c):
                return np.asarray(
                    _dispatch.jit_join()(
                        shifted, cells, chip_index,
                        heavy_cap=c.get("heavy_cap", hcap),
                        found_cap=c.get("found_cap", fcap),
                        writeback=writeback, lookup=lookup,
                        probe=probe,
                        convex_cap=c.get("convex_cap", ccap),
                    )
                )

            # `guarded_call` evaluates the fault hooks (maybe_fail +
            # planned stalls) on this thread, then runs the blocking
            # dispatch under the site's watchdog deadline with transient
            # retry: a hung device surfaces as a typed
            # StalledDeviceError on the same retry path as a tunnel
            # drop, never a silent hang
            out, _ = run_escalating(
                lambda c: _dispatch.guarded_call(
                    "pip_join.device", attempt, c
                ),
                grow, ceilings,
                overflow_count=lambda o: int((o == OVERFLOW).sum()),
                stage="pip_join",
            )
            return out

        # --- epsilon-band recheck (SURVEY §7) -------------------------
        ebk = EDGE_BAND_K if edge_band_k is None else float(edge_band_k)
        eps2 = jnp.asarray(
            (ebk * float(np.finfo(np.dtype(dtype)).eps)
             * host.coord_scale) ** 2,
            dtype=dtype,
        )

        def attempt_banded(c):
            o, nr = _dispatch.jit_join()(
                shifted, cells, chip_index,
                heavy_cap=c.get("heavy_cap", hcap),
                found_cap=c.get("found_cap", fcap), edge_eps2=eps2,
                writeback=writeback, lookup=lookup,
                probe=probe, convex_cap=c.get("convex_cap", ccap),
            )
            return np.array(o), np.array(nr)  # writable host copies

        (out, host_mask), _ = run_escalating(
            lambda c: _dispatch.guarded_call(
                "pip_join.device", attempt_banded, c
            ),
            grow, ceilings,
            overflow_count=lambda r: int((r[0] == OVERFLOW).sum()),
            stage="pip_join.recheck",
        )
        # PIP-boundary band -> host (host_mask)
        if margins is not None:
            meps = float(np.finfo(np.dtype(margins.dtype)).eps)
            cmk = (
                CELL_MARGIN_K if cell_margin_k is None
                else float(cell_margin_k)
            )
            km = cmk * meps
            t_rc = time.perf_counter()
            flagged = margins[..., 0] < km
            n_flag = int(flagged.sum())
            if n_flag:
                # band-compacted narrow re-join: the epsilon band is
                # compacted ONCE (the probe tiers' own `_compact`
                # machinery) and a single re-join over just the compacted
                # band — sized exactly from its own device-side counts,
                # on the caller's tier-1 lookup path — resolves the
                # runner-up cell. Only result TIES (plus cell corners and
                # invalid alternates) escalate to the host oracle; the
                # full point axis is never re-probed.
                cap = min(_next_pow2(n_flag), chunk.shape[0])
                src, _, _, _ = _dispatch.jit_compact()(flagged, cap=cap)
                alt = _assign_cells(
                    index_system, resolution, dev[src], "alt"
                )
                src_np = np.asarray(src)[:n_flag]
                if alt is None:  # system without alternate-rounding
                    host_mask[src_np] = True
                    _telemetry.record(
                        "recheck_narrow", n=chunk.shape[0], band=n_flag,
                        cap=cap, ties=n_flag, mode="host_all",
                        seconds=round(time.perf_counter() - t_rc, 6),
                    )
                else:
                    # exact caps for the narrow join from the band's own
                    # scalar counts (pad rows duplicate row 0, so the
                    # counts upper-bound the real band — still exact; the
                    # rejoin runs the scatter path, so the convex count
                    # is unused)
                    nf2, nh2, _ = (
                        int(v)
                        for v in np.asarray(
                            _dispatch.jit_counts()(alt, chip_index)
                        )
                    )
                    fcap2 = min(_next_pow2(nf2 + 1), cap)
                    hcap2 = (
                        min(_next_pow2(nh2 + 1), fcap2)
                        if chip_index.num_heavy_cells
                        else None
                    )
                    r_alt = np.asarray(
                        _dispatch.jit_join()(
                            shifted[src], alt, chip_index,
                            heavy_cap=hcap2, found_cap=fcap2,
                            lookup=lookup,
                        )
                    )[:n_flag]
                    vertex = np.asarray(margins[src, 1])[:n_flag] < km
                    alt_np = np.asarray(alt)[:n_flag]
                    tie = (
                        (r_alt != out[src_np]) | vertex | (alt_np < 0)
                    )
                    host_mask[src_np[tie]] = True
                    _telemetry.record(
                        "recheck_narrow", n=chunk.shape[0], band=n_flag,
                        cap=cap, caps=[fcap2, hcap2],
                        ties=int(tie.sum()), mode="alt_rejoin",
                        seconds=round(time.perf_counter() - t_rc, 6),
                    )
        rows = np.nonzero(host_mask)[0]
        if rows.size:
            out[rows] = host_join(chunk[rows], host, index_system, resolution)
        return out

    def run_resilient(chunk: np.ndarray) -> np.ndarray:
        """`run`, degrading to the exact f64 host oracle when the device
        path fails past the transient-retry budget (result flagged
        :class:`DegradedResult` — never a silent zero/wrong answer)."""
        try:
            return run(chunk)
        except RetryExhausted as e:
            if host is None:
                raise
            _telemetry.record(
                "degraded", label="pip_join", attempts=e.attempts,
                error=repr(e.last)[:200],
            )
            get_logger("mosaic_tpu.runtime").warning(
                "pip_join: device path failed %d times (%r); answering "
                "from the f64 host oracle", e.attempts, e.last,
            )
            return DegradedResult.wrap(
                host_join(chunk, host, index_system, resolution),
                reason=f"pip_join device retries exhausted ({e.last!r})"[:300],
                attempts=e.attempts,
            )

    def run_spanned(chunk: np.ndarray) -> np.ndarray:
        """One lane span per device dispatch when routing is pinned:
        `join.probe.<lane>` wraps the whole forced-lane dispatch so a
        trail attributes its wall clock to that lane (the fused
        `adaptive` program is one dispatch — its lane populations ride
        the `probe_route` event instead)."""
        if probe.startswith("adaptive-"):
            with _obs_trace.span(
                f"join.probe.{probe.removeprefix('adaptive-')}",
                n=chunk.shape[0],
            ):
                return run_resilient(chunk)
        return run_resilient(chunk)

    # one span per pip_join call: escalation/retry/degradation/recheck
    # events inside attach to it, so a trail shows WHICH join they hit
    with _obs_trace.span("join.pip", n=n, recheck=bool(recheck), probe=probe):
        if batch_size is None or n <= batch_size:
            return run_spanned(raw)
        out = np.empty(n, dtype=np.int32)
        degraded: list[DegradedResult] = []
        for s in range(0, n, batch_size):
            r = run_spanned(raw[s : s + batch_size])
            if isinstance(r, DegradedResult):
                degraded.append(r)
            out[s : s + batch_size] = r
        if degraded:
            return DegradedResult.wrap(
                out,
                reason=degraded[0].reason,
                attempts=max(d.attempts for d in degraded),
                detail={"degraded_batches": len(degraded)},
            )
        return out
