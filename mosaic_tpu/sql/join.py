"""Index-assisted point-in-polygon join — the north-star workload.

Reference analog: `sql/join/PointInPolygonJoin.scala:15-98` and the
Quickstart benchmark (`notebooks/examples/scala/QuickstartNotebook.scala:
204-216`): points get a cell id, polygons are tessellated into chips, the
join is an equi-join on cell id, and the exact `st_contains` predicate runs
only on border-chip matches (`is_core || st_contains(wkb, point)`).

TPU-native redesign: there is no shuffle. The chip table is compiled into a
device-resident :class:`ChipIndex` which is small enough to replicate
(all-gather over ICI) on every chip of a mesh, while the billion-point side
shards over devices.

The per-point probe is designed around TPU gather latency (random HBM row
gathers are latency-bound at ~tens of ns each, independent of row size):

    key = (cell * A) >> (64 - log2 T)      multiply-shift hash, no search
    bucket = table[key]                     1 gather: B candidate (cell, u)
    u      = bucket row whose cell matches  parallel compare, no loop
    chips  = cell_rows[u]                   1 WIDE gather: all M chips' edge
                                            data, core flags and geom ids
    hit    = core | ray_crossing(...)       fused vector math

Two parallel gathers per point, total — versus the 13 serially-dependent
gathers of a binary search (searchsorted) plus ~3M small per-chip gathers,
which measured ~10x slower on v5e. Everything is one fused XLA program: no
host round-trip, no dynamic shapes.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..core.geometry.device import DeviceGeometry, pack_to_device
from ..core.index.base import IndexSystem
from ..core.tessellate import ChipTable, tessellate
from ..core.types import PackedGeometry

_SENTINEL = jnp.iinfo(jnp.int32).max


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ChipIndex:
    """Device-resident join index over a tessellated polygon table.

    Per-chip layout (kept for oracles, tests and host inspection):

    cells:     (U,) int64 — sorted unique cell ids present in the chip table.
    chip_rows: (U, M) int32 — chip-row ids per cell, -1 padded (M = max
               chips per cell, static).
    chip_geom: (C,) int32 — source polygon row per chip.
    chip_core: (C,) bool — core chips skip the predicate.
    border:    DeviceGeometry over all C chip rows (core rows are empty and
               never evaluated).

    Probe fast path (see module docstring):

    hash_mult:  (1,) uint64 — multiply-shift hash multiplier.
    table_cell: (T, B) int64 — bucketed hash table of cell ids (-1 empty);
                T is a power of two, B the max bucket occupancy.
    table_slot: (T, B) int32 — cell slot u for each bucket entry (-1 empty).
    cell_verts: (U, M, R, V, 2) — every cell's M chip polygons, gathered
                into one row so the probe is a single wide gather.
    cell_elen:  (U, M, R) int32 — ring lengths (edge masks) per chip.
    cell_core:  (U, M) bool; cell_geom: (U, M) int32, -1 padded.
    """

    cells: jax.Array
    chip_rows: jax.Array
    chip_geom: jax.Array
    chip_core: jax.Array
    border: DeviceGeometry
    hash_mult: jax.Array
    table_cell: jax.Array
    table_slot: jax.Array
    cell_verts: jax.Array
    cell_elen: jax.Array
    cell_core: jax.Array
    cell_geom: jax.Array

    @property
    def num_cells(self) -> int:
        return int(self.cells.shape[0])

    @property
    def max_chips_per_cell(self) -> int:
        return int(self.chip_rows.shape[1])


def _build_hash(cells: np.ndarray, max_bucket: int = 8):
    """Host: bucketed multiply-shift hash over the unique cell ids.

    Returns (mult, table_cell (T, B), table_slot (T, B)). T is sized ~4x the
    cell count (power of two); the multiplier is retried until the fullest
    bucket holds <= max_bucket entries, then B shrinks to the realized max.
    """
    U = cells.shape[0]
    bits = max(4, int(np.ceil(np.log2(max(4 * U, 16)))))
    rng = np.random.default_rng(0xC0FFEE)
    for _ in range(32):
        mult = np.uint64(rng.integers(0, 2**64, dtype=np.uint64) | np.uint64(1))
        keys = (cells.astype(np.uint64) * mult) >> np.uint64(64 - bits)
        counts = np.bincount(keys.astype(np.int64), minlength=1 << bits)
        if counts.max() <= max_bucket:
            break
        bits += 1  # grow the table if this multiplier clusters
    B = int(counts.max())
    T = 1 << bits
    table_cell = np.full((T, B), -1, dtype=np.int64)
    table_slot = np.full((T, B), -1, dtype=np.int32)
    fill = np.zeros(T, dtype=np.int64)
    for u, (c, k) in enumerate(zip(cells, keys.astype(np.int64))):
        table_cell[k, fill[k]] = c
        table_slot[k, fill[k]] = u
        fill[k] += 1
    return mult, table_cell, table_slot


def build_chip_index(
    table: ChipTable,
    dtype=jnp.float32,
    max_chips_per_cell: int | None = None,
    recenter: bool = True,
) -> ChipIndex:
    """Host: compile a ChipTable into the device join index."""
    C = len(table)
    if C == 0:
        raise ValueError("empty chip table")
    order = np.argsort(table.cell_id, kind="stable")
    sorted_cells = table.cell_id[order]
    uniq, starts, counts = np.unique(
        sorted_cells, return_index=True, return_counts=True
    )
    M = int(max_chips_per_cell or counts.max())
    if counts.max() > M:
        raise ValueError(
            f"cell with {counts.max()} chips exceeds max_chips_per_cell={M}"
        )
    rows = np.full((uniq.size, M), -1, dtype=np.int32)
    for i, (s, c) in enumerate(zip(starts, counts)):
        rows[i, :c] = order[s : s + c]
    # only border rows need vertices: blank core chip geometries before
    # padding so V is set by the clipped border chips, not the cell polygons
    chips = table.chips
    if table.is_core.any() and table.has_geom[table.is_core].any():
        # rebuild with empty geometry for core rows
        from ..core.types import GeometryBuilder, GeometryType

        b = GeometryBuilder()
        for g in range(C):
            if table.is_core[g]:
                b.add_geometry(GeometryType.POLYGON, [[np.zeros((0, 2))]], 0)
            else:
                b.append_from(chips, g)
        chips = b.build()
    # recenter: chips span a city/region, so subtracting the f64 midpoint
    # before narrowing to f32 shrinks the coordinate ulp by ~1e3 (the
    # SURVEY §7 precision strategy) — points are shifted to match in
    # pip_join before they are narrowed.
    border = pack_to_device(chips, dtype=dtype, recenter=recenter)

    # probe fast path: hash table + per-cell packed chip rows
    mult, table_cell, table_slot = _build_hash(uniq)
    bverts = np.asarray(border.verts)
    blen = np.asarray(border.ring_len)
    U = uniq.size
    _, R, V, _ = bverts.shape
    cell_verts = np.zeros((U, M, R, V, 2), dtype=bverts.dtype)
    cell_elen = np.zeros((U, M, R), dtype=np.int32)
    cell_core = np.zeros((U, M), dtype=bool)
    cell_geom = np.full((U, M), -1, dtype=np.int32)
    valid = rows >= 0
    rs = np.maximum(rows, 0)
    cell_verts[:] = bverts[rs]
    cell_verts[~valid] = 0.0
    cell_elen[:] = blen[rs]
    cell_elen[~valid] = 0
    # non-polygonal chips (line/point tessellations) must contribute no
    # edges: their rings are open, so the closed-ring edge mask would admit
    # a phantom edge to the zero pad and flip crossing parity (same guard
    # as predicates._poly_edges). is_core still matches them exactly.
    from ..core.types import GeometryType

    btype = np.asarray(border.geom_type)
    poly = (btype[rs] == GeometryType.POLYGON) | (
        btype[rs] == GeometryType.MULTIPOLYGON
    )
    cell_elen[~poly] = 0
    cell_core[:] = table.is_core[rs] & valid
    cell_geom[valid] = table.geom_id[rs[valid]].astype(np.int32)

    return ChipIndex(
        cells=jnp.asarray(uniq, dtype=jnp.int64),
        chip_rows=jnp.asarray(rows),
        chip_geom=jnp.asarray(table.geom_id.astype(np.int32)),
        chip_core=jnp.asarray(table.is_core),
        border=border,
        hash_mult=jnp.asarray(np.asarray([mult], dtype=np.uint64)),
        table_cell=jnp.asarray(table_cell),
        table_slot=jnp.asarray(table_slot),
        cell_verts=jnp.asarray(cell_verts),
        cell_elen=jnp.asarray(cell_elen),
        cell_core=jnp.asarray(cell_core),
        cell_geom=jnp.asarray(cell_geom),
    )


def pip_join_points(
    points: jax.Array, pcells: jax.Array, index: ChipIndex
) -> jax.Array:
    """(N,) int32 — smallest matching polygon row per point, -1 if none.

    Jittable; shard the point axis over a mesh and replicate ``index``.
    Probe = hash lookup (1 gather) + packed cell row (1 wide gather) + fused
    ray crossing over (N, M, R, E) — see module docstring for why.
    """
    T = index.table_cell.shape[0]
    shift_bits = jnp.uint64(64 - int(np.log2(T)))
    key = (
        (pcells.astype(jnp.uint64) * index.hash_mult[0]) >> shift_bits
    ).astype(jnp.int32)
    cand_cell = index.table_cell[key]  # (N, B)
    cand_slot = index.table_slot[key]  # (N, B)
    match = (cand_cell == pcells[:, None]) & (cand_slot >= 0)
    u = jnp.max(jnp.where(match, cand_slot, -1), axis=1)  # (N,)
    found = u >= 0
    us = jnp.maximum(u, 0)

    verts = index.cell_verts[us]  # (N, M, R, V, 2) — the one wide gather
    elen = index.cell_elen[us]  # (N, M, R)
    core = index.cell_core[us]  # (N, M)
    geom = index.cell_geom[us]  # (N, M)

    a = verts[..., :-1, :]
    b = verts[..., 1:, :]
    px = points[:, 0][:, None, None, None]
    py = points[:, 1][:, None, None, None]
    ay, by = a[..., 1], b[..., 1]
    straddle = (ay > py) != (by > py)
    denom = by - ay
    denom = jnp.where(denom == 0, 1.0, denom)
    xcross = a[..., 0] + (py - ay) * (b[..., 0] - a[..., 0]) / denom
    emask = (
        jnp.arange(verts.shape[3] - 1, dtype=jnp.int32)[None, None, None, :]
        < elen[..., None]
    )
    crossings = jnp.sum(
        (straddle & (px < xcross) & emask).astype(jnp.int32), axis=(-2, -1)
    )  # (N, M)
    inside = (crossings & 1) == 1
    hit = found[:, None] & (geom >= 0) & (core | inside)
    best = jnp.min(jnp.where(hit, geom, _SENTINEL), axis=1)
    return jnp.where(best == _SENTINEL, -1, best).astype(jnp.int32)


# module-level jit so repeated pip_join calls share the compilation cache
_JIT_JOIN = jax.jit(pip_join_points)


def pip_join(
    points: np.ndarray | jax.Array,
    polygons: PackedGeometry,
    index_system: IndexSystem,
    resolution: int,
    chip_index: ChipIndex | None = None,
    batch_size: int | None = None,
) -> np.ndarray:
    """Managed join (reference: `PointInPolygonJoin.join` auto-indexes both
    sides, `sql/join/PointInPolygonJoin.scala:86-97`).

    Tessellates ``polygons`` (unless a prebuilt ``chip_index`` is passed),
    assigns cells to ``points`` and returns the matched polygon row per
    point (-1 = no polygon). ``batch_size`` chunks the point axis to bound
    the (N·M·E) predicate intermediate.
    """
    resolution = index_system.resolution_arg(resolution)
    if chip_index is None:
        table = tessellate(polygons, index_system, resolution, keep_core_geoms=False)
        chip_index = build_chip_index(table)
    raw = np.asarray(points, dtype=np.float64)
    # shift in f64 first, narrow after (keeps f32 ulp small near the data)
    shift = np.asarray(chip_index.border.shift, dtype=np.float64)
    dtype = chip_index.border.verts.dtype
    step = _JIT_JOIN
    n = raw.shape[0]

    def run(chunk: np.ndarray) -> np.ndarray:
        cells = index_system.point_to_cell(jnp.asarray(chunk), resolution)
        shifted = jnp.asarray(chunk - shift, dtype=dtype)
        return np.asarray(step(shifted, cells, chip_index))

    if batch_size is None or n <= batch_size:
        return run(raw)
    out = np.empty(n, dtype=np.int32)
    for s in range(0, n, batch_size):
        out[s : s + batch_size] = run(raw[s : s + batch_size])
    return out
