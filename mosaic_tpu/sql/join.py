"""Index-assisted point-in-polygon join — the north-star workload.

Reference analog: `sql/join/PointInPolygonJoin.scala:15-98` and the
Quickstart benchmark (`notebooks/examples/scala/QuickstartNotebook.scala:
204-216`): points get a cell id, polygons are tessellated into chips, the
join is an equi-join on cell id, and the exact `st_contains` predicate runs
only on border-chip matches (`is_core || st_contains(wkb, point)`).

TPU-native redesign: there is no shuffle. The chip table is compiled into a
device-resident :class:`ChipIndex` — a sorted cell-id vector plus a dense
``(U, M)`` slot table of chip rows — which is small enough to replicate
(all-gather over ICI) on every chip of a mesh, while the billion-point side
shards over devices. Per point the join is then:

    searchsorted(cells, point_cell) → slot row → M candidate chips
    hit = chip_is_core | ray_crossing(point, chip_polygon)

which is one fused XLA program: no host round-trip, no dynamic shapes.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..core.geometry.device import DeviceGeometry, pack_to_device
from ..core.geometry.predicates import contains_xy_gather
from ..core.index.base import IndexSystem
from ..core.tessellate import ChipTable, tessellate
from ..core.types import PackedGeometry

_SENTINEL = jnp.iinfo(jnp.int32).max


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ChipIndex:
    """Device-resident join index over a tessellated polygon table.

    cells:     (U,) int64 — sorted unique cell ids present in the chip table.
    chip_rows: (U, M) int32 — chip-row ids per cell, -1 padded (M = max
               chips per cell, static).
    chip_geom: (C,) int32 — source polygon row per chip.
    chip_core: (C,) bool — core chips skip the predicate.
    border:    DeviceGeometry over all C chip rows (core rows are empty and
               never evaluated).
    """

    cells: jax.Array
    chip_rows: jax.Array
    chip_geom: jax.Array
    chip_core: jax.Array
    border: DeviceGeometry

    @property
    def num_cells(self) -> int:
        return int(self.cells.shape[0])

    @property
    def max_chips_per_cell(self) -> int:
        return int(self.chip_rows.shape[1])


def build_chip_index(
    table: ChipTable,
    dtype=jnp.float32,
    max_chips_per_cell: int | None = None,
    recenter: bool = True,
) -> ChipIndex:
    """Host: compile a ChipTable into the device join index."""
    C = len(table)
    if C == 0:
        raise ValueError("empty chip table")
    order = np.argsort(table.cell_id, kind="stable")
    sorted_cells = table.cell_id[order]
    uniq, starts, counts = np.unique(
        sorted_cells, return_index=True, return_counts=True
    )
    M = int(max_chips_per_cell or counts.max())
    if counts.max() > M:
        raise ValueError(
            f"cell with {counts.max()} chips exceeds max_chips_per_cell={M}"
        )
    rows = np.full((uniq.size, M), -1, dtype=np.int32)
    for i, (s, c) in enumerate(zip(starts, counts)):
        rows[i, :c] = order[s : s + c]
    # only border rows need vertices: blank core chip geometries before
    # padding so V is set by the clipped border chips, not the cell polygons
    chips = table.chips
    if table.is_core.any() and table.has_geom[table.is_core].any():
        # rebuild with empty geometry for core rows
        from ..core.types import GeometryBuilder, GeometryType

        b = GeometryBuilder()
        for g in range(C):
            if table.is_core[g]:
                b.add_geometry(GeometryType.POLYGON, [[np.zeros((0, 2))]], 0)
            else:
                b.append_from(chips, g)
        chips = b.build()
    return ChipIndex(
        cells=jnp.asarray(uniq, dtype=jnp.int64),
        chip_rows=jnp.asarray(rows),
        chip_geom=jnp.asarray(table.geom_id.astype(np.int32)),
        chip_core=jnp.asarray(table.is_core),
        # recenter: chips span a city/region, so subtracting the f64 midpoint
        # before narrowing to f32 shrinks the coordinate ulp by ~1e3 (the
        # SURVEY §7 precision strategy) — points are shifted to match in
        # pip_join before they are narrowed.
        border=pack_to_device(chips, dtype=dtype, recenter=recenter),
    )


def pip_join_points(
    points: jax.Array, pcells: jax.Array, index: ChipIndex
) -> jax.Array:
    """(N,) int32 — smallest matching polygon row per point, -1 if none.

    Jittable; shard the point axis over a mesh and replicate ``index``.
    """
    U = index.cells.shape[0]
    u = jnp.clip(jnp.searchsorted(index.cells, pcells), 0, U - 1)
    cell_hit = index.cells[u] == pcells  # (N,)
    rows = index.chip_rows[u]  # (N, M)
    valid = cell_hit[:, None] & (rows >= 0)
    rows_safe = jnp.maximum(rows, 0)
    core = index.chip_core[rows_safe] & valid
    N, M = rows.shape
    flat_idx = rows_safe.reshape(-1)
    flat_pts = jnp.repeat(points, M, axis=0)
    inside = contains_xy_gather(flat_pts, flat_idx, index.border).reshape(N, M)
    hit = core | (inside & valid & ~index.chip_core[rows_safe])
    geoms = jnp.where(hit, index.chip_geom[rows_safe], _SENTINEL)
    best = jnp.min(geoms, axis=1)
    return jnp.where(best == _SENTINEL, -1, best).astype(jnp.int32)


# module-level jit so repeated pip_join calls share the compilation cache
_JIT_JOIN = jax.jit(pip_join_points)


def pip_join(
    points: np.ndarray | jax.Array,
    polygons: PackedGeometry,
    index_system: IndexSystem,
    resolution: int,
    chip_index: ChipIndex | None = None,
    batch_size: int | None = None,
) -> np.ndarray:
    """Managed join (reference: `PointInPolygonJoin.join` auto-indexes both
    sides, `sql/join/PointInPolygonJoin.scala:86-97`).

    Tessellates ``polygons`` (unless a prebuilt ``chip_index`` is passed),
    assigns cells to ``points`` and returns the matched polygon row per
    point (-1 = no polygon). ``batch_size`` chunks the point axis to bound
    the (N·M·E) predicate intermediate.
    """
    resolution = index_system.resolution_arg(resolution)
    if chip_index is None:
        table = tessellate(polygons, index_system, resolution, keep_core_geoms=False)
        chip_index = build_chip_index(table)
    raw = np.asarray(points, dtype=np.float64)
    # shift in f64 first, narrow after (keeps f32 ulp small near the data)
    shift = np.asarray(chip_index.border.shift, dtype=np.float64)
    dtype = chip_index.border.verts.dtype
    step = _JIT_JOIN
    n = raw.shape[0]

    def run(chunk: np.ndarray) -> np.ndarray:
        cells = index_system.point_to_cell(jnp.asarray(chunk), resolution)
        shifted = jnp.asarray(chunk - shift, dtype=dtype)
        return np.asarray(step(shifted, cells, chip_index))

    if batch_size is None or n <= batch_size:
        return run(raw)
    out = np.empty(n, dtype=np.int32)
    for s in range(0, n, batch_size):
        out[s : s + batch_size] = run(raw[s : s + batch_size])
    return out
