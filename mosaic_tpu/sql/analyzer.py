"""MosaicAnalyzer: optimal grid-resolution estimation.

Reference analog: `sql/MosaicAnalyzer.scala:28-129` — sample the geometry
column, compare area percentiles against the mean cell area per resolution,
and pick the resolution whose cells-per-geometry ratio falls inside a target
band. `SampleStrategy` (`sql/SampleStrategy.scala:5`) becomes a plain
(fraction, limit) pair.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..core.index.base import IndexSystem
from ..functions._coerce import to_packed


@dataclasses.dataclass
class SampleStrategy:
    fraction: float = 1.0
    limit: "int | None" = None

    def __post_init__(self):
        # fraction=0 would floor every sample to the max(1, ...) clamp and
        # silently analyze a single row; out-of-range fractions are always
        # a caller bug, so fail at construction, not deep in numpy
        if not 0.0 < self.fraction <= 1.0:
            raise ValueError(
                f"SampleStrategy fraction must be in (0, 1], got "
                f"{self.fraction!r} — pass fraction=1.0 with limit=K to "
                f"sample a fixed number of rows"
            )
        if self.limit is not None and self.limit < 1:
            raise ValueError(
                f"SampleStrategy limit must be >= 1, got {self.limit!r}"
            )

    def apply(self, n: int, rng: np.random.Generator) -> np.ndarray:
        if n <= 0:
            # without this, n=0 reaches rng.choice(0, size=1) and dies with
            # an opaque "a must be greater than 0" deep in numpy
            raise ValueError(
                "cannot sample an empty geometry column (0 rows) — the "
                "analyzer needs at least one geometry; check the upstream "
                "filter or load"
            )
        take = int(np.ceil(n * self.fraction))
        if self.limit is not None:
            take = min(take, self.limit)
        take = max(1, min(take, n))
        return rng.choice(n, size=take, replace=False)


class MosaicAnalyzer:
    """Pick the resolution where a typical geometry spans ``target_cells``
    grid cells (the reference defaults to ~16-256 cells per geometry)."""

    def __init__(self, index: IndexSystem, target_cells: float = 64.0):
        self.index = index
        self.target_cells = target_cells

    def _sampled(self, col, sample: SampleStrategy, seed: int):
        """(sampled PackedGeometry, finite positive areas) — the shared
        sampling/area/filter step of every analyzer entry point."""
        packed = to_packed(col)
        rng = np.random.default_rng(seed)
        rows = sample.apply(len(packed), rng)
        from ..core.geometry import oracle

        sub = packed.take(rows)
        areas = oracle.area(sub)
        areas = areas[np.isfinite(areas) & (areas > 0)]
        if areas.size == 0:
            raise ValueError("no polygonal geometries to analyze")
        return sub, areas

    def _geometry_areas(self, col, sample: SampleStrategy, seed: int) -> np.ndarray:
        return self._sampled(col, sample, seed)[1]

    def get_optimal_resolution(
        self,
        col,
        sample: "SampleStrategy | None" = None,
        percentile: float = 50.0,
        seed: int = 0,
    ) -> int:
        """Resolution whose mean cell area is closest to
        geometry_area(percentile) / target_cells
        (reference: `getOptimalResolution:28-39`)."""
        sample = sample or SampleStrategy()
        areas = self._geometry_areas(col, sample, seed)
        target_cell_area = np.percentile(areas, percentile) / self.target_cells
        best, best_err = None, np.inf
        for res in self.index.resolutions():
            try:
                ca = self.index.cell_area_approx(res)
            except NotImplementedError:
                continue
            err = abs(np.log(ca / target_cell_area))
            if err < best_err:
                best, best_err = res, err
        if best is None:
            raise ValueError("index system exposes no cell areas")
        return int(best)

    def get_optimal_resolution_reference(
        self,
        col,
        sample: "SampleStrategy | None" = None,
        lower: float = 1.0,
        upper: float = 100.0,
        seed: int = 0,
    ) -> int:
        """The reference's exact recipe (`MosaicAnalyzer.scala:28-39` +
        `:41-100`): per resolution, the mean cell area is measured from
        the boundary polygon of the cell containing each geometry's
        centroid; resolutions where ANY of the mean/p25/p50/p75
        cells-per-geometry ratios fall inside (lower, upper) survive, and
        the median-by-p50-ratio row wins. Golden-pinned on the NYC taxi
        fixture in tests/test_models_services.py (resolution 9)."""
        sample = sample or SampleStrategy()
        sub, areas = self._sampled(col, sample, seed)
        from ..core.geometry import oracle

        stats = (
            float(areas.mean()),
            *(float(v) for v in np.percentile(areas, [25, 50, 75])),
        )
        cents = oracle.centroid(sub)
        cents = cents[np.isfinite(cents).all(axis=1)]
        kept: list[tuple[float, int]] = []
        for res in self.index.resolutions():
            cells = np.asarray(self.index.point_to_cell(cents, res))
            bnd = np.asarray(self.index.cell_boundary(cells))
            x, y = bnd[..., 0], bnd[..., 1]
            a = 0.5 * np.abs(
                np.sum(
                    x * np.roll(y, -1, axis=-1) - np.roll(x, -1, axis=-1) * y,
                    axis=-1,
                )
            )
            ia = float(a.mean())
            if ia <= 0:
                continue
            ratios = [s / ia for s in stats]
            if any(lower < r < upper for r in ratios):
                kept.append((ratios[2], int(res)))
        if not kept:
            raise ValueError(
                "no resolution has cells-per-geometry inside "
                f"({lower}, {upper})"
            )
        kept.sort()
        return kept[(len(kept) - 1) // 2][1]

    def get_resolution_metrics(
        self,
        col,
        sample: "SampleStrategy | None" = None,
        seed: int = 0,
    ) -> dict[int, dict[str, float]]:
        """Per-resolution cells-per-geometry percentiles (reference:
        `getResolutionMetrics:41-100`)."""
        sample = sample or SampleStrategy()
        areas = self._geometry_areas(col, sample, seed)
        out: dict[int, dict[str, float]] = {}
        for res in self.index.resolutions():
            try:
                ca = self.index.cell_area_approx(res)
            except NotImplementedError:
                continue
            ratio = areas / ca
            out[int(res)] = {
                "mean_cells": float(ratio.mean()),
                "p25_cells": float(np.percentile(ratio, 25)),
                "p50_cells": float(np.percentile(ratio, 50)),
                "p75_cells": float(np.percentile(ratio, 75)),
            }
        return out
