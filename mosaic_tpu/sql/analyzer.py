"""MosaicAnalyzer: optimal grid-resolution estimation.

Reference analog: `sql/MosaicAnalyzer.scala:28-129` — sample the geometry
column, compare area percentiles against the mean cell area per resolution,
and pick the resolution whose cells-per-geometry ratio falls inside a target
band. `SampleStrategy` (`sql/SampleStrategy.scala:5`) becomes a plain
(fraction, limit) pair.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..core.index.base import IndexSystem
from ..functions._coerce import to_packed


@dataclasses.dataclass
class SampleStrategy:
    fraction: float = 1.0
    limit: "int | None" = None

    def apply(self, n: int, rng: np.random.Generator) -> np.ndarray:
        take = int(np.ceil(n * self.fraction))
        if self.limit is not None:
            take = min(take, self.limit)
        take = max(1, min(take, n))
        return rng.choice(n, size=take, replace=False)


class MosaicAnalyzer:
    """Pick the resolution where a typical geometry spans ``target_cells``
    grid cells (the reference defaults to ~16-256 cells per geometry)."""

    def __init__(self, index: IndexSystem, target_cells: float = 64.0):
        self.index = index
        self.target_cells = target_cells

    def _geometry_areas(self, col, sample: SampleStrategy, seed: int) -> np.ndarray:
        packed = to_packed(col)
        rng = np.random.default_rng(seed)
        rows = sample.apply(len(packed), rng)
        from ..core.geometry import oracle

        areas = oracle.area(packed)[rows]
        areas = areas[np.isfinite(areas) & (areas > 0)]
        if areas.size == 0:
            raise ValueError("no polygonal geometries to analyze")
        return areas

    def get_optimal_resolution(
        self,
        col,
        sample: "SampleStrategy | None" = None,
        percentile: float = 50.0,
        seed: int = 0,
    ) -> int:
        """Resolution whose mean cell area is closest to
        geometry_area(percentile) / target_cells
        (reference: `getOptimalResolution:28-39`)."""
        sample = sample or SampleStrategy()
        areas = self._geometry_areas(col, sample, seed)
        target_cell_area = np.percentile(areas, percentile) / self.target_cells
        best, best_err = None, np.inf
        for res in self.index.resolutions():
            try:
                ca = self.index.cell_area_approx(res)
            except NotImplementedError:
                continue
            err = abs(np.log(ca / target_cell_area))
            if err < best_err:
                best, best_err = res, err
        if best is None:
            raise ValueError("index system exposes no cell areas")
        return int(best)

    def get_resolution_metrics(
        self,
        col,
        sample: "SampleStrategy | None" = None,
        seed: int = 0,
    ) -> dict[int, dict[str, float]]:
        """Per-resolution cells-per-geometry percentiles (reference:
        `getResolutionMetrics:41-100`)."""
        sample = sample or SampleStrategy()
        areas = self._geometry_areas(col, sample, seed)
        out: dict[int, dict[str, float]] = {}
        for res in self.index.resolutions():
            try:
                ca = self.index.cell_area_approx(res)
            except NotImplementedError:
                continue
            ratio = areas / ca
            out[int(res)] = {
                "mean_cells": float(ratio.mean()),
                "p25_cells": float(np.percentile(ratio, 25)),
                "p50_cells": float(np.percentile(ratio, 50)),
                "p75_cells": float(np.percentile(ratio, 75)),
            }
        return out
