"""Streaming join pipeline: HBM-resident batch ring + double-buffered
cell-assignment prefetch.

Why this layer exists (round-5 measurement, `STREAM_1B_r05.json`): the
1B-point device-gen stream sustained 47.2M pts/s against a 132.2M pts/s
single-batch rate (0.357x) because the `fori_loop` folded point
*generation* into every iteration and nothing overlapped batch staging
with the join. The 3DPipe lesson (PAPERS.md) is that the fix is
structural: split the stream into pipelined stages and keep the next
batch's inputs resident before the current batch's compute needs them.

Three pieces, all CPU-testable and bit-identical to the per-batch path:

- **Ring** — K pre-generated point batches stacked into one (K, B, 2)
  HBM-resident array the loop cycles (`ring_from_host` /
  `ring_from_generator`). Generator cost moves OUT of the measured loop;
  `generator_rate` (an identical fori_loop running only `gen_batch`)
  prices it separately.
- **Prefetch** — inside the jitted scan, iteration i joins batch i with
  the cell ids computed in iteration i-1 and computes batch i+1's cell
  assignment in the same program. The two stages have no data dependency,
  so XLA overlaps the cell pipeline (one-hot MXU work) with the PIP
  probe's gather/scatter phases instead of serializing them.
- **Accounting** — every stage emits a `stream_stage` telemetry event
  (`runtime/telemetry.py`) with measured wall seconds, and
  :func:`hbm_peak` reports the loop's high-water device memory — from
  runtime memory stats when the backend exposes them, else a live-buffer
  census (the axon tunnel returns no stats: STREAM_1B_r05 recorded
  ``peak_hbm_bytes: 0``; that zero is the bug this closes).

Completion is always forced by :func:`fold_stats` — a device-side
(checksum, matches, overflow) fold so no per-point data crosses the
host link inside a measured region.

Durability layer (PR 3): the same stage boundaries that made the ring
fast make it checkpointable. :meth:`StreamJoin.run_durable` runs the
scan in segments of ``snapshot_every`` ring cycles, snapshotting the
scan carry (fold accumulators, ring cursor, prefetched cell ids,
optional generator key) to a checksummed run directory
(`runtime/checkpoint.py`) between segments; :meth:`StreamJoin.resume`
restarts from the last valid snapshot and converges to the SAME final
(checksum, matches, overflow) as an uninterrupted run — int32 fold
addition is exact and associative across segment boundaries, and cell
assignment is deterministic, so segmenting changes scheduling, never
values (pinned by tests/test_stream_faults.py). Every blocking device
operation sits under a `runtime/watchdog.py` deadline
(``MOSAIC_WATCHDOG_*``) with transient retry — composed by
`dispatch.guarded_call` at the ``stream.prefetch`` / ``stream.scan_step``
/ ``stream.snapshot`` sites — segment failures past the retry budget
degrade to the f64 host oracle (surfaced as ``metrics["degraded"]``,
never vanishing into the fold), and :meth:`StreamJoin.admit` diverts
poisoned input rows (NaN/Inf, out-of-CRS-bounds) into a quarantine
buffer (`runtime/quarantine.py`) instead of the device fold.

Dispatch-core unification (this PR's lane): the compiled program bundle
(assign / join / scan / durable-segment executables) is built by
:func:`build_stream_programs` and cached process-wide behind
`dispatch.stream_programs`, keyed on the static spec — two StreamJoins
over the same (system, resolution, caps, placement) share one set of
compiles, and `dispatch.cache_stats` audits the population. ``mesh=``
shards the scan data-parallel with the index replicated;
``donate_ring=True`` donates the HBM ring to the loop.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import os
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ..dispatch import core as _dispatch, pipeline as _pipeline
from ..obs import metrics as _metrics, trace as _trace
from ..tune import resolve as _tune_resolve
from ..runtime import (
    checkpoint as _checkpoint,
    faults as _faults,
    quarantine as _quarantine,
    telemetry as _telemetry,
)
from ..runtime.errors import EpochFingerprintMismatch, RetryExhausted
from .join import (
    ChipIndex,
    host_join_with_cells,
    pip_join_points,
    resolve_probe_mode,
)


def fold_stats(out: jax.Array) -> jax.Array:
    """(3,) int32 device-side completion fold of a join output: full-bit
    XOR-shift checksum (every result bit stays live — a masked sum lets
    XLA dead-code the high half), match count, overflow count."""
    return jnp.stack(
        [
            (out ^ (out >> 16)).sum().astype(jnp.int32),
            (out >= 0).sum().astype(jnp.int32),
            (out == -2).sum().astype(jnp.int32),
        ]
    )


def ring_from_host(batches) -> jax.Array:
    """Stack host point batches into one (K, B, 2) f64 device-resident
    ring. Blocks until the ring is staged (staging is not loop time).
    ``stream.prefetch`` is the fault/watchdog site: staging the next
    inputs is where a tunnel drop or hang surfaces in ring rebuilds."""
    with _trace.span(
        "stream.ring_build", source="host"
    ) as sp, _telemetry.timed(
        "stream_stage", stage="ring_build", source="host"
    ):

        def stage():
            ring = jnp.stack(
                [jnp.asarray(b, dtype=jnp.float64) for b in batches]
            )
            ring.block_until_ready()
            return ring

        # watchdog only — ring staging has no retry budget of its own;
        # the caller owns rebuild-vs-fail
        ring = _dispatch.guarded_call(
            "stream.prefetch", stage, retry=False
        )
        sp.set(nbytes=int(getattr(ring, "nbytes", 0)))
        return ring


def ring_from_generator(gen, key: jax.Array, k: int) -> jax.Array:
    """Device-generated ring: ``gen(fold_in(key, i)) -> (B, 2)`` for K
    distinct slots, stacked resident in HBM."""
    with _trace.span(
        "stream.ring_build", source="device_gen", k=k
    ) as sp, _telemetry.timed(
        "stream_stage", stage="ring_build", source="device_gen", k=k
    ):

        def stage():
            ring = jnp.stack(
                [gen(jax.random.fold_in(key, i)) for i in range(k)]
            )
            ring.block_until_ready()
            return ring

        ring = _dispatch.guarded_call(
            "stream.prefetch", stage, retry=False
        )
        sp.set(nbytes=int(getattr(ring, "nbytes", 0)))
        return ring


def hbm_peak(device=None, fallback_arrays=()) -> tuple[int, str]:
    """(peak_bytes, source) for ``device`` (default: first device).

    Prefers the runtime's ``memory_stats()`` high-water mark; when the
    backend reports none (CPU, and the axon TPU tunnel — the source of
    the ``peak_hbm_bytes: 0`` artifact bug), falls back to a census of
    live device buffers (ring + index + loop carries are resident at the
    high-water point, so this lower-bounds the true peak).
    """
    dev = device if device is not None else jax.devices()[0]
    try:
        st = dev.memory_stats() or {}
    except Exception:  # lint: broad-except-ok (memory_stats is optional backend introspection; census fallback below)
        st = {}
    for key in ("peak_bytes_in_use", "bytes_in_use", "bytes_used"):
        v = int(st.get(key, 0) or 0)
        if v > 0:
            _metrics.gauge("stream.hbm_peak_bytes").set(
                v, source=f"memory_stats.{key}"
            )
            return v, f"memory_stats.{key}"
    total = 0
    try:
        arrays = list(jax.live_arrays())
    except Exception:  # lint: broad-except-ok (live_arrays is version-dependent; fall back to the tracked arrays)
        arrays = list(fallback_arrays)
    for a in arrays:
        try:
            total += int(a.nbytes)
        except Exception:  # lint: broad-except-ok (deleted/donated buffers raise on nbytes; skip them)
            pass
    _metrics.gauge("stream.hbm_peak_bytes").set(
        total, source="live_buffer_census"
    )
    return total, "live_buffer_census"


@dataclasses.dataclass
class StreamResult:
    """One streamed run: device-fold stats + wall-clock accounting.

    ``metrics`` is the durability/quality side channel: ``degraded``
    (any segment answered by the host oracle), ``degraded_segments``,
    ``snapshots`` written, ``resumed_from`` (ring cursor a resume
    started at, else None), and the quarantine counters when admission
    ran (``quarantined``, ``quarantine_reasons``). Plain runs carry an
    empty dict — absence of a key is never a signal.
    """

    checksum: int
    matches: int
    overflow: int
    n_points: int
    n_batches: int
    batch: int
    wall_s: float
    points_per_sec: float
    prefetch: bool
    outs: np.ndarray | None = None  # (nb, B) per-batch rows (collect=True)
    metrics: dict = dataclasses.field(default_factory=dict)


@contextlib.contextmanager
def _quiet_donation():
    """Suppress the backend's not-donatable warning: on CPU donation is
    a silent no-op by design (the bench records whether it applied via
    ``ring.is_deleted()``), and the warning would fire once per run."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        yield


@dataclasses.dataclass(frozen=True)
class StreamPrograms:
    """The compiled-executable bundle behind one StreamJoin spec.

    Built by :func:`build_stream_programs` and cached process-wide by
    `dispatch.stream_programs` — two StreamJoins over the same (system,
    resolution, caps, placement) spec replay one compiled scan instead
    of tracing their own. Every callable takes the ChipIndex as an
    argument, so the bundle is index-agnostic (the compile signature is
    the spec, not the data)."""

    assign_eager: object  #: un-jitted assign for tiny host-side lookups
    assign: object  #: jitted cell assignment (pts) -> int64 cells
    join: object  #: jitted probe (pts, cells, index) -> rows
    step: object  #: fused assign+join (pts, index) -> rows
    step_stats: object  #: fused step, device-folded to (3,) stats
    loop: object  #: jitted scan (ring, index, nb=, collect=)
    donate_loop: object  #: ring-donating twin of ``loop`` (or None)
    seg_loop: object  #: durable-segment scan (absolute batch indices)


def build_stream_programs(
    index_system,
    resolution: int,
    *,
    dtype,
    cell_dtype,
    found_cap,
    heavy_cap,
    lookup,
    compaction,
    probe,
    convex_cap,
    prefetch,
    donate_ring,
    mesh,
) -> StreamPrograms:
    """Trace the full StreamJoin program set for one static spec.

    Called through the bounded `dispatch.stream_programs` cache — never
    directly. ``mesh`` (a 1-D ``dp`` mesh, or None) shards the probe
    data-parallel with the ChipIndex replicated inside the scan body;
    because each per-point result depends only on that point and the
    replicated index, the sharded scan is bit-identical to the
    single-device one. ``donate_ring`` additionally traces a donating
    twin of the scan (``donate_argnums`` on the ring) so a sustained run
    can release the K×B×2 HBM ring buffer to XLA instead of holding a
    second copy across the loop — the donating twin is a separate
    executable because warmup must not consume the caller's ring.
    """

    def assign(pts):
        c = index_system.point_to_cell(pts.astype(cell_dtype), resolution)
        return c.astype(jnp.int64)

    def join_one(pts, cells, chip_index):
        shifted = (pts - chip_index.border.shift).astype(dtype)
        return pip_join_points(
            shifted,
            cells,
            chip_index,
            heavy_cap=heavy_cap,
            found_cap=found_cap,
            lookup=lookup,
            compaction=compaction,
            probe=probe,
            convex_cap=convex_cap,
        )

    if mesh is None:
        join = join_one
    else:
        join = _dispatch.sharded_pointwise(
            join_one, mesh, check_rep=_dispatch.probe_check_rep(probe)
        )

    def loop(ring, chip_index, nb: int, collect: bool):
        k = ring.shape[0]

        def slot(i):
            return jax.lax.dynamic_index_in_dim(
                ring, i % k, axis=0, keepdims=False
            )

        if prefetch:

            def body(carry, i):
                acc, cells_cur = carry
                # join batch i against the cells prefetched at i-1;
                # assign batch i+1's cells in the SAME program so XLA
                # overlaps the cell pipeline with the probe
                out = join(slot(i), cells_cur, chip_index)
                cells_next = assign(slot(i + 1))
                return (acc + fold_stats(out), cells_next), (
                    out if collect else None
                )

            carry0 = (jnp.zeros(3, jnp.int32), assign(ring[0]))
        else:

            def body(carry, i):
                pts = slot(i)
                out = join(pts, assign(pts), chip_index)
                return carry + fold_stats(out), (
                    out if collect else None
                )

            carry0 = jnp.zeros(3, jnp.int32)
        carry, outs = jax.lax.scan(
            body, carry0, jnp.arange(nb, dtype=jnp.int32)
        )
        acc = carry[0] if prefetch else carry
        return acc, outs

    def seg(ring, chip_index, i0, acc, cells, nb: int, collect: bool):
        """One durable segment: the SAME scan body as ``loop`` over
        absolute batch indices [i0, i0+nb). The carry crosses segments
        through the host (snapshot), so the fold stays int32-add-exact
        and cell prefetch deterministic — segmenting is invisible in
        the final stats."""
        k = ring.shape[0]

        def slot(i):
            return jax.lax.dynamic_index_in_dim(
                ring, i % k, axis=0, keepdims=False
            )

        steps = i0 + jnp.arange(nb, dtype=jnp.int32)
        if prefetch:

            def body(carry, i):
                a, cells_cur = carry
                out = join(slot(i), cells_cur, chip_index)
                cells_next = assign(slot(i + 1))
                return (a + fold_stats(out), cells_next), (
                    out if collect else None
                )

            (acc, cells), outs = jax.lax.scan(body, (acc, cells), steps)
        else:

            def body(a, i):
                pts = slot(i)
                out = join(pts, assign(pts), chip_index)
                return a + fold_stats(out), (out if collect else None)

            acc, outs = jax.lax.scan(body, acc, steps)
        return acc, cells, outs

    return StreamPrograms(
        assign_eager=assign,
        assign=jax.jit(assign),
        join=jax.jit(join),
        step=jax.jit(lambda pts, ix: join(pts, assign(pts), ix)),
        # fused step + fold: benches time THIS (one (3,) pull forces
        # completion; pulling the (N,) rows would measure the tunnel)
        step_stats=jax.jit(
            lambda pts, ix: fold_stats(join(pts, assign(pts), ix))
        ),
        loop=jax.jit(loop, static_argnames=("nb", "collect")),
        donate_loop=(
            jax.jit(
                loop, static_argnames=("nb", "collect"), donate_argnums=(0,)
            )
            if donate_ring
            else None
        ),
        seg_loop=jax.jit(seg, static_argnames=("nb", "collect")),
    )


class StreamJoin:
    """Compiled streaming pip-join over a resident ring.

    Splits the fused bench step into its two stages — ``assign`` (grid
    cell ids) and ``join`` (the PIP probe) — and compiles one scan that
    cycles ring slots with optional double-buffered prefetch of the next
    batch's cell assignment. ``run`` (prefetch on) is bit-identical to
    ``run_batched`` (one call per batch, no pipeline): cell assignment is
    deterministic, so joining batch i against cells computed one
    iteration early changes scheduling, never values — pinned by
    tests/test_stream.py.

    The executables come from the unified dispatch core
    (`dispatch.stream_programs`): one traced program bundle per static
    spec, shared across StreamJoin instances and audited by
    `dispatch.cache_stats`. ``mesh=`` (or the ``MOSAIC_MESH`` knob)
    shards the probe data-parallel over a 1-D device mesh inside the
    scan with the ChipIndex replicated — bit-identical at any device
    count; the batch size must divide over the mesh. ``donate_ring=True``
    lets ``run`` donate the ring buffer to the loop (``metrics
    ["ring_donated"]`` reports whether the backend applied it — CPU
    declines donation and keeps the copy).
    """

    def __init__(
        self,
        index: ChipIndex,
        index_system,
        resolution: int,
        *,
        found_cap: int | None = None,
        heavy_cap: int | None = None,
        lookup: str | None = None,
        compaction: str | None = None,
        cell_dtype=jnp.float32,
        prefetch: bool = True,
        probe: "str | None" = None,
        convex_cap: int | None = None,
        donate_ring: bool = False,
        mesh=None,
        profile=None,
    ):
        self.index = index
        self.index_system = index_system
        self.resolution = resolution
        self.prefetch = bool(prefetch)
        self.donate_ring = bool(donate_ring)
        #: the TuningProfile consulted again at run_durable/resume time
        #: for the pipeline/window knobs (same precedence as here)
        self._profile = profile
        # profile-consumed knobs fold at this host entry point: explicit
        # arg > env knob > profile > built-in default (tune/resolve.py)
        knobs = _tune_resolve.resolve_knobs(
            "stream_join", profile,
            explicit={"probe": probe, "lookup": lookup},
            defaults={"probe": "scatter", "lookup": None},
        )
        probe, lookup = knobs["probe"], knobs["lookup"]
        #: (ring fingerprint, report) of the last admission, if any
        self._last_quarantine: tuple | None = None
        dtype = index.border.verts.dtype
        platform = jax.devices()[0].platform
        if lookup is None:
            lookup = (
                "mxu"
                if platform != "cpu" and dtype == jnp.float32
                else "gather"
            )
        if compaction is None:
            compaction = "scatter" if platform == "cpu" else "mxu"
        self.lookup, self.compaction = lookup, compaction
        self.found_cap, self.heavy_cap = found_cap, heavy_cap
        # resolve the adaptive/force-lane and mesh knobs HERE, before
        # the values are closed over by the jitted scan (env changes
        # cannot reach a compiled program; see join.resolve_probe_mode)
        probe = resolve_probe_mode(probe)
        self.probe, self.convex_cap = probe, convex_cap
        self.mesh = _dispatch.resolve_mesh(mesh)

        progs = _dispatch.stream_programs(
            index_system, resolution, dtype=dtype, cell_dtype=cell_dtype,
            found_cap=found_cap, heavy_cap=heavy_cap, lookup=lookup,
            compaction=compaction, probe=probe, convex_cap=convex_cap,
            prefetch=self.prefetch, donate_ring=self.donate_ring,
            mesh=self.mesh,
        )
        self._programs = progs
        # eager twin for tiny host-side lookups (park-point search): a
        # jitted call would recompile the whole cell pipeline per shape
        self._assign_eager = progs.assign_eager
        self.assign = progs.assign
        self.join = progs.join
        self._step = progs.step
        self._step_stats = progs.step_stats
        self._loop = progs.loop
        self._donate_loop = progs.donate_loop
        self._seg_loop = progs.seg_loop
        #: (ring shape+dtype, nb, collect) signatures this instance has
        #: warmed — the jit cache itself lives on the shared program
        #: bundle, this only stops repeat warm executions per stream
        self._seg_warm: set = set()

    def _check_batch(self, batch: int) -> None:
        if self.mesh is not None and int(batch) % self.mesh.size:
            raise ValueError(
                f"stream batch {int(batch)} does not divide over the "
                f"{self.mesh.size}-device mesh"
            )

    def step(self, pts: jax.Array) -> jax.Array:
        """Single fused batch (assign + join) — the single-batch-rate
        reference the sustained number is measured against."""
        self._check_batch(pts.shape[0])
        return self._step(pts, self.index)

    def step_stats(self, pts: jax.Array) -> jax.Array:
        """Single fused batch, device-folded to (3,) stats."""
        self._check_batch(pts.shape[0])
        return self._step_stats(pts, self.index)

    def compile(self, ring: jax.Array, n_batches: int, collect=False):
        """Warm the loop program (compile time must not pollute the
        sustained measurement); emits a ``stream_stage`` compile event.
        With ``donate_ring`` the donating twin is warmed on a scratch
        copy, so the caller's ring survives warmup intact."""
        self._check_batch(ring.shape[1])
        with _telemetry.timed(
            "stream_stage", stage="compile", n_batches=n_batches,
            prefetch=self.prefetch, donate_ring=self.donate_ring,
        ):
            if self.donate_ring:
                scratch = jnp.array(ring, copy=True)
                with _quiet_donation():
                    acc, outs = self._donate_loop(
                        scratch, self.index, n_batches, collect
                    )
            else:
                acc, outs = self._loop(ring, self.index, n_batches, collect)
            jax.block_until_ready(acc)
        return acc, outs

    def run(
        self, ring: jax.Array, n_batches: int, *, collect: bool = False
    ) -> StreamResult:
        """One timed streamed pass over ``n_batches`` ring cycles.

        The whole stream is ONE dispatch (per-batch python dispatch over
        the tunnel measured 146 ms/batch for a 63 ms device step in r05);
        completion is forced by pulling the (3,) fold. With
        ``donate_ring`` the ring buffer is donated to the loop —
        ``metrics["ring_donated"]`` records whether the backend applied
        the donation (CPU declines; the ring then stays live).
        """
        k, batch = int(ring.shape[0]), int(ring.shape[1])
        self._check_batch(batch)
        donation = {}
        ring_bytes = int(ring.nbytes)  # before the loop may delete it
        with _trace.span(
            "stream.run", n_batches=n_batches, batch=batch, ring_k=k,
        ):
            t0 = time.perf_counter()
            if self.donate_ring:
                with _quiet_donation():
                    acc, outs = self._donate_loop(
                        ring, self.index, n_batches, collect
                    )
            else:
                acc, outs = self._loop(ring, self.index, n_batches, collect)
            acc_np = np.asarray(acc)  # blocks: the loop's only host pull
            wall = time.perf_counter() - t0
            n_points = n_batches * batch
            if self.donate_ring:
                donation = {
                    "donate_ring": True,
                    "ring_donated": bool(ring.is_deleted()),
                    "ring_bytes": ring_bytes,
                }
            _telemetry.record(
                "stream_stage", stage="join_loop",
                seconds=round(wall, 6), n_batches=n_batches, batch=batch,
                ring_k=k, prefetch=self.prefetch,
                points_per_sec=round(n_points / max(wall, 1e-9), 1),
                **donation,
            )
        return StreamResult(
            checksum=int(acc_np[0]),
            matches=int(acc_np[1]),
            overflow=int(acc_np[2]),
            n_points=n_points,
            n_batches=n_batches,
            batch=batch,
            wall_s=wall,
            points_per_sec=n_points / max(wall, 1e-9),
            prefetch=self.prefetch,
            outs=np.asarray(outs) if collect else None,
            metrics=dict(donation),
        )

    def run_batched(self, ring: jax.Array, n_batches: int) -> StreamResult:
        """Per-batch reference path: one ``step`` call per ring slot, no
        pipeline, host-accumulated stats — the bit-identity oracle for
        the scanned loop (and the honest non-overlapped comparison)."""
        k, batch = int(ring.shape[0]), int(ring.shape[1])
        outs, acc = [], np.zeros(3, np.int64)
        t0 = time.perf_counter()
        for i in range(n_batches):
            out = self.step(ring[i % k])
            outs.append(np.asarray(out))
            acc += np.asarray(fold_stats(out), dtype=np.int64)
        wall = time.perf_counter() - t0
        n_points = n_batches * batch
        # int32 wraparound to match the device-side accumulator
        c = int(acc[0]) & 0xFFFFFFFF
        if c >= 1 << 31:
            c -= 1 << 32
        return StreamResult(
            checksum=c,
            matches=int(acc[1]),
            overflow=int(acc[2]),
            n_points=n_points,
            n_batches=n_batches,
            batch=batch,
            wall_s=wall,
            points_per_sec=n_points / max(wall, 1e-9),
            prefetch=False,
            outs=np.stack(outs),
        )

    # ------------------------------------------------------ durability

    def admit(
        self,
        batches,
        *,
        bounds: tuple | None = None,
        park: np.ndarray | None = None,
    ) -> "tuple[jax.Array, _quarantine.QuarantineReport]":
        """Validate and stage host batches into a ring; poisoned rows go
        to quarantine, never to the device fold.

        Each batch is scrubbed (`runtime/quarantine.py`): non-finite
        rows, and rows outside ``bounds`` (xmin, ymin, xmax, ymax) when
        given, are recorded in the returned
        :class:`~mosaic_tpu.runtime.quarantine.QuarantineReport` (their
        raw values land in ``report.buffer`` for triage) and replaced in
        the staged ring by the stream's *park point* — a coordinate
        proven here to hit no indexed cell, so every parked row returns
        -1 and contributes exactly zero to each fold statistic. Admitted
        rows are staged bit-identically; the ring is otherwise exactly
        :func:`ring_from_host`'s. The report's counters surface in
        ``metrics`` of subsequent :meth:`run_durable` calls.
        """
        batches = list(batches)  # materialize once (may be a generator)
        with _trace.span("stream.admit", batches=len(batches)):
            return self._admit_scrubbed(batches, bounds, park)

    def _admit_scrubbed(self, batches, bounds, park):
        raws = [
            np.asarray(
                _faults.maybe_corrupt("stream.admit", b), dtype=np.float64
            )
            for b in batches
        ]
        report = _quarantine.QuarantineReport()
        park_pt = (
            None if park is None else np.asarray(park, dtype=np.float64)
        )
        cleaned = []
        for bi, raw in enumerate(raws):
            bad, reasons = _quarantine.scrub_points(raw, bounds=bounds)
            report.merge_batch(bi, raw, bad, reasons)
            if bad.any():
                if park_pt is None:
                    park_pt = self._find_park(raws, bounds)
                clean = raw.copy()
                clean[bad] = park_pt
                cleaned.append(clean)
            else:
                cleaned.append(raw)
        ring = ring_from_host(cleaned)
        if report.n_quarantined:
            _telemetry.record("stream_quarantine", **report.metrics())
        # keyed by ring fingerprint: run_durable only surfaces this
        # report for the ring THIS admission staged, never a stale one
        self._last_quarantine = (_checkpoint.fingerprint(ring), report)
        return ring, report

    def _find_park(self, raws, bounds) -> np.ndarray:
        """The guaranteed-miss park coordinate (see ``admit``)."""
        if bounds is None:
            finite = [r[np.isfinite(r).all(axis=1)] for r in raws]
            finite = [f for f in finite if f.size]
            allp = (
                np.concatenate(finite)
                if finite
                else np.zeros((1, 2), np.float64)
            )
            bounds = (
                float(allp[:, 0].min()), float(allp[:, 1].min()),
                float(allp[:, 0].max()), float(allp[:, 1].max()),
            )
        return _quarantine.find_park_point(
            lambda p: self._assign_eager(jnp.asarray(p, jnp.float64)),
            np.asarray(self.index.cells),
            bounds,
        )

    def _warm_seg_loop(
        self, ring, cells, start_step: int, n_batches: int,
        snapshot_every: int, collect: bool,
    ) -> None:
        """Compile the durable-segment executables BEFORE the segment
        loop starts.

        Round-12 stall attribution (``STALL_r12.json``) put 1.95 s of a
        2.28 s durable run inside ``stream.segment[0]`` — almost all of
        it the seg_loop trace+compile, booked as *device* time because
        it happened under the segment span. Executing each distinct
        ``nb`` signature here (at most two: ``snapshot_every`` and the
        tail remainder; execution is required — AOT lowering does not
        populate the jit dispatch cache) moves that wall time under a
        ``dispatch.compile`` span, where timeline attribution classifies
        it as compile. Costs up to two warm segments of compute; set
        ``MOSAIC_STREAM_NO_SEG_WARMUP=1`` to skip and eat the
        segment[0] compile instead."""
        if os.environ.get("MOSAIC_STREAM_NO_SEG_WARMUP"):
            return
        sizes = sorted({
            min(snapshot_every, n_batches - s)
            for s in range(start_step, n_batches, snapshot_every)
        })
        key0 = (tuple(ring.shape), str(ring.dtype), bool(collect))
        sizes = [
            nb for nb in sizes if (key0, nb) not in self._seg_warm
        ]
        if not sizes:
            return
        c0 = _dispatch.backend_compiles()
        span = _trace.start_span(
            "dispatch.compile", site="stream.seg_loop",
            sizes=repr(sizes),
        )
        try:
            acc0 = jnp.zeros(3, jnp.int32)
            for nb in sizes:
                a, _c, _o = self._seg_loop(
                    ring, self.index, jnp.int32(int(start_step)),
                    acc0, cells, nb=nb, collect=collect,
                )
                jax.block_until_ready(a)
                self._seg_warm.add((key0, nb))
        finally:
            span.set(
                backend_compiles=_dispatch.backend_compiles() - c0
            )
            span.end()

    def _host_segment(self, ring_np, i0: int, nb: int, collect: bool):
        """f64 host-oracle evaluation of batches [i0, i0+nb) — the
        degradation fallback when a segment's device path fails past the
        retry budget. Returns ((3,) int64 fold delta, outs | None)."""
        host = self.index.host
        k = ring_np.shape[0]
        acc = np.zeros(3, np.int64)
        outs = []
        for i in range(i0, i0 + nb):
            pts = np.asarray(ring_np[i % k], np.float64)
            cells = np.asarray(
                self.index_system.point_to_cell(pts, self.resolution)
            )
            out = host_join_with_cells(pts, cells, host)
            acc += fold_stats_np(out)
            if collect:
                outs.append(out)
        return acc, (np.stack(outs) if collect else None)

    def run_durable(
        self,
        ring: jax.Array,
        n_batches: int,
        *,
        run_dir: str,
        snapshot_every: int = 8,
        collect: bool = False,
        extra_arrays: dict | None = None,
        watchdog_default_s: float = 600.0,
        retry_policy: "RetryPolicy | None" = None,
        pipeline: "bool | None" = None,
        window: "int | None" = None,
    ) -> StreamResult:
        """A streamed pass that survives device loss: the scan runs in
        segments of ``snapshot_every`` ring cycles, persisting the scan
        carry (fold accumulators, ring cursor, prefetched cell ids, any
        ``extra_arrays`` such as the generator key) to ``run_dir`` after
        each segment (`runtime/checkpoint.py`: checksummed, atomic).

        ``pipeline=True`` (default: the ``MOSAIC_STREAM_PIPELINE``
        knob) runs the segments through the asynchronous pipelined
        executor (`dispatch/pipeline.py`): the fold accumulator and
        prefetched cells stay device-resident across segments, up to
        ``window`` segments (``MOSAIC_STREAM_WINDOW``, default 4) are
        in flight at once, and snapshot I/O runs on a background writer
        thread off the device's critical path. Bit-identical to the
        synchronous loop — the carry chain is the same int32 fold — and
        the durability contract is unchanged: a snapshot is durable
        only once its background write completes, and resume replays
        from the last *completed* snapshot.

        Identical final (checksum, matches, overflow) to :meth:`run` —
        int32 fold addition segments exactly, cell prefetch is
        deterministic. Each segment dispatch sits under the
        ``stream.scan_step`` watchdog deadline and the transient-retry
        budget; past the budget the segment degrades to the f64 host
        oracle and ``metrics["degraded"]`` reports it. Snapshot failures
        never kill the run (``snapshot_skipped`` telemetry; resume
        granularity coarsens). Interrupt anywhere and
        :meth:`resume`\\ (``run_dir``, same ring) finishes the run.

        Tracing: the whole run is one ``stream.durable_run`` span with
        one child per segment and snapshot; the span's context is
        persisted in every snapshot sidecar, so a later :meth:`resume`
        JOINS the interrupted run's trace instead of starting a new one.
        """
        return self._run_segments(
            ring, int(n_batches), run_dir=run_dir,
            snapshot_every=int(snapshot_every), start_step=0,
            acc0=None, cells0=None, collect=collect,
            resumed_from=None, extra_arrays=extra_arrays,
            watchdog_default_s=watchdog_default_s,
            retry_policy=retry_policy,
            pipeline=pipeline, window=window,
        )

    def resume(
        self,
        run_dir: str,
        ring: jax.Array,
        *,
        collect: bool = False,
        watchdog_default_s: float = 600.0,
        retry_policy: "RetryPolicy | None" = None,
        pipeline: "bool | None" = None,
        window: "int | None" = None,
    ) -> StreamResult:
        """Restart an interrupted :meth:`run_durable` from the last
        VALID snapshot in ``run_dir`` (corrupt/truncated snapshots are
        skipped with telemetry) and run to completion.

        The snapshot's ring fingerprint, shape, and prefetch mode must
        match this stream — resuming against different data would
        silently fold garbage. Converges to the same final (checksum,
        matches, overflow) as the uninterrupted run; ``metrics
        ["resumed_from"]`` records the ring cursor resumed at. With
        ``collect=True``, ``outs`` covers only the batches run by THIS
        call (earlier rows are already folded into the snapshot).
        """
        loaded = _checkpoint.load_latest(run_dir)
        if loaded is None:
            raise FileNotFoundError(
                f"no valid snapshot under {run_dir!r} — nothing to resume"
            )
        step, arrays, meta = loaded
        k, batch = int(ring.shape[0]), int(ring.shape[1])
        if bool(meta.get("prefetch")) != self.prefetch:
            raise ValueError(
                f"snapshot prefetch={meta.get('prefetch')} != stream "
                f"prefetch={self.prefetch}"
            )
        if int(meta.get("ring_k", k)) != k or int(
            meta.get("batch", batch)
        ) != batch:
            raise ValueError(
                f"snapshot ring shape ({meta.get('ring_k')}, "
                f"{meta.get('batch')}) != resumed ring ({k}, {batch})"
            )
        want_fp = meta.get("ring_sha256")
        if want_fp and want_fp != _checkpoint.fingerprint(ring):
            raise ValueError(
                "snapshot ring fingerprint mismatch — this is not the "
                "ring the interrupted run was folding"
            )
        want_idx = meta.get("index_identity")
        have_idx = _checkpoint.index_identity(self.index)
        if want_idx and want_idx != have_idx:
            # the epoch-boundary refusal: a resume must finish on the
            # snapshot's epoch or not at all — folding batches joined
            # against one epoch into accumulators from another would be
            # a silent wrong answer (an epoch publish between the kill
            # and the resume is the expected way to land here)
            raise EpochFingerprintMismatch(
                f"snapshot under {run_dir!r} was taken against index "
                f"{want_idx[:24]}…, but this stream is bound to "
                f"{have_idx[:24]}… — rebuild the stream on the "
                "snapshot's epoch (EpochalIndex.replay of the matching "
                "epoch) to finish this run, or start a fresh run on "
                "the new epoch",
                expected=want_idx, actual=have_idx,
            )
        cells0 = (
            jnp.asarray(arrays["cells"]) if "cells" in arrays else None
        )
        return self._run_segments(
            ring, int(meta["n_batches"]), run_dir=run_dir,
            snapshot_every=int(meta.get("snapshot_every", 8)),
            start_step=int(step),
            acc0=np.asarray(arrays["acc"], np.int64),
            cells0=cells0, collect=collect, resumed_from=int(step),
            extra_arrays={
                key[2:]: val
                for key, val in arrays.items()
                if key.startswith("x_")
            } or None,
            watchdog_default_s=watchdog_default_s,
            retry_policy=retry_policy,
            trace_parent=_trace.SpanContext.from_dict(meta.get("trace")),
            pipeline=pipeline, window=window,
        )

    def _run_segments(
        self, ring, n_batches, *, run_dir, snapshot_every, start_step,
        acc0, cells0, collect, resumed_from, extra_arrays,
        watchdog_default_s, retry_policy, trace_parent=None,
        pipeline=None, window=None,
    ) -> StreamResult:
        k, batch = int(ring.shape[0]), int(ring.shape[1])
        self._check_batch(batch)
        snapshot_every = max(1, snapshot_every)
        # mode knobs resolved at call time, never inside traced code:
        # explicit arg > MOSAIC_STREAM_PIPELINE/_WINDOW > profile > default
        knobs = _tune_resolve.resolve_knobs(
            "stream_join.run_durable", self._profile,
            explicit={"stream_pipeline": pipeline, "stream_window": window},
            defaults={"stream_pipeline": False, "stream_window": None},
        )
        pipeline, window = knobs["stream_pipeline"], knobs["stream_window"]
        ring_np = np.asarray(ring)  # host twin: fingerprint + fallback
        ring_fp = _checkpoint.fingerprint(ring_np)
        # one root span per durable run; a resume parents to the
        # INTERRUPTED run's root (persisted in the snapshot sidecars),
        # so kill + resume reads as one trace end to end
        root = _trace.start_span(
            "stream.durable_run",
            parent=trace_parent,
            n_batches=int(n_batches),
            resumed_from=resumed_from,
            snapshot_every=int(snapshot_every),
            pipelined=bool(pipeline),
        )
        runner = (
            self._run_segments_pipelined if pipeline
            else self._run_segments_traced
        )
        kw = {"window": window} if pipeline else {}
        try:
            return runner(
                ring, n_batches, run_dir=run_dir,
                snapshot_every=snapshot_every, start_step=start_step,
                acc0=acc0, cells0=cells0, collect=collect,
                resumed_from=resumed_from, extra_arrays=extra_arrays,
                watchdog_default_s=watchdog_default_s,
                retry_policy=retry_policy, root=root,
                ring_np=ring_np, ring_fp=ring_fp, k=k, batch=batch,
                **kw,
            )
        except BaseException as e:  # noqa: BLE001 — stamped, re-raised
            root.set(error=type(e).__name__)
            raise
        finally:
            root.end()

    def _run_segments_traced(
        self, ring, n_batches, *, run_dir, snapshot_every, start_step,
        acc0, cells0, collect, resumed_from, extra_arrays,
        watchdog_default_s, retry_policy, root, ring_np, ring_fp,
        k, batch,
    ) -> StreamResult:
        acc = (
            np.zeros(3, np.int64) if acc0 is None
            else _wrap_i32(np.asarray(acc0, np.int64))
        )
        if self.prefetch:
            cells = (
                cells0 if cells0 is not None
                else self.assign(ring[start_step % k])
            )
        else:
            cells = jnp.zeros((0,), jnp.int64)  # inert placeholder carry
        meta = {
            "n_batches": int(n_batches),
            "batch": batch,
            "ring_k": k,
            "prefetch": self.prefetch,
            "snapshot_every": int(snapshot_every),
            "ring_sha256": ring_fp,
            "index_identity": _checkpoint.index_identity(self.index),
            "trace": root.context.as_dict(),
        }
        degraded_segments = 0
        snapshots = 0
        outs_list: list[np.ndarray] = []
        host = getattr(self.index, "host", None)
        # compile the segment executables up front, under a compile
        # span — NOT inside segment[0]'s device-attributed wall time
        self._warm_seg_loop(
            ring, cells, start_step, int(n_batches),
            int(snapshot_every), collect,
        )
        step = start_step
        t0 = time.perf_counter()
        while step < n_batches:
            seg_n = min(snapshot_every, n_batches - step)
            acc, cells, o_np, degr = self._segment_sync(
                ring, ring_np, step, seg_n, acc, cells,
                collect=collect, watchdog_default_s=watchdog_default_s,
                retry_policy=retry_policy, host=host,
            )
            degraded_segments += int(degr)
            if collect and o_np is not None:
                outs_list.append(o_np)
            step += seg_n

            def snap():
                payload = self._snapshot_payload(
                    acc, cells, extra_arrays
                )
                return _checkpoint.save_snapshot(
                    run_dir, step, payload, meta
                )

            with _trace.span("stream.snapshot", step=step):
                try:
                    _dispatch.guarded_call(
                        "stream.snapshot", snap,
                        default_s=watchdog_default_s,
                        policy=retry_policy,
                    )
                    snapshots += 1
                except RetryExhausted as e:
                    # durability degrades (coarser resume point), the
                    # run itself must not die for a sick disk
                    _telemetry.record(
                        "snapshot_skipped", run_dir=run_dir, step=step,
                        error=repr(e.last)[:200],
                    )
        wall = time.perf_counter() - t0
        acc_w = _wrap_i32(acc)
        n_run = n_batches - start_step
        n_points = n_batches * batch
        _telemetry.record(
            "stream_stage", stage="durable_loop",
            seconds=round(wall, 6), n_batches=n_batches,
            batch=batch, ring_k=k, prefetch=self.prefetch,
            snapshots=snapshots, degraded_segments=degraded_segments,
            resumed_from=resumed_from,
            points_per_sec=round(
                n_run * batch / max(wall, 1e-9), 1
            ),
        )
        metrics = {
            "degraded": degraded_segments > 0,
            "degraded_segments": degraded_segments,
            "snapshots": snapshots,
            "resumed_from": resumed_from,
            "run_dir": run_dir,
        }
        if (
            self._last_quarantine is not None
            and self._last_quarantine[0] == ring_fp
        ):
            metrics.update(self._last_quarantine[1].metrics())
        return StreamResult(
            checksum=int(acc_w[0]),
            matches=int(acc_w[1]),
            overflow=int(acc_w[2]),
            n_points=n_points,
            n_batches=n_batches,
            batch=batch,
            wall_s=wall,
            points_per_sec=n_run * batch / max(wall, 1e-9),
            prefetch=self.prefetch,
            outs=(
                np.concatenate(outs_list)
                if collect and outs_list
                else None
            ),
            metrics=metrics,
        )

    def _segment_sync(
        self, ring, ring_np, step, seg_n, acc, cells, *, collect,
        watchdog_default_s, retry_policy, host,
    ):
        """One synchronous durable segment: dispatch + blocking pull
        under the ``stream.scan_step`` guard, host-oracle degradation
        past the retry budget. Returns ``(acc int64, cells, outs |
        None, degraded)``. Shared by the synchronous loop and the
        pipelined executor's transient-replay path — replay IS the
        synchronous path, so its semantics cannot drift."""
        k = int(ring.shape[0])
        acc_i32 = jnp.asarray(_wrap_i32(acc).astype(np.int32))
        cells_arg = cells

        def dispatch():
            a, c, o = self._seg_loop(
                ring, self.index, jnp.int32(step), acc_i32,
                cells_arg, nb=seg_n, collect=collect,
            )
            # one host pull forces completion (and is what a real
            # stall would block on)
            return (
                np.asarray(a), c,
                np.asarray(o) if collect else None,
            )

        with _trace.span("stream.segment", step=step, n=seg_n):
            try:
                a_np, cells_new, o_np = _dispatch.guarded_call(
                    "stream.scan_step", dispatch,
                    default_s=watchdog_default_s,
                    policy=retry_policy,
                )
                return np.asarray(a_np, np.int64), cells_new, o_np, False
            except RetryExhausted as e:
                if host is None:
                    raise
                _telemetry.record(
                    "degraded", label="stream.scan_step", step=step,
                    attempts=e.attempts, error=repr(e.last)[:200],
                )
                delta, o_np = self._host_segment(
                    ring_np, step, seg_n, collect
                )
                acc = _wrap_i32(np.asarray(acc, np.int64) + delta)
                if self.prefetch:
                    cells = self.assign(ring[(step + seg_n) % k])
                return acc, cells, o_np, True

    def _snapshot_payload(self, acc, cells, extra_arrays) -> dict:
        """The snapshot carry arrays, every device pull under a
        ``dispatch.transfer.d2h`` span (``cells`` AND the ``x_<key>``
        passthroughs — timeline transfer accounting is complete)."""
        payload = {"acc": _wrap_i32(acc).astype(np.int32)}
        if self.prefetch and cells is not None:
            # a TRUE D2H interval: the segment's compute is already
            # forced complete by the acc pull, so this measures the
            # copy, not hidden device work
            with _trace.span(
                "dispatch.transfer.d2h", site="stream.snapshot",
                nbytes=int(getattr(cells, "nbytes", 0)),
            ):
                payload["cells"] = np.asarray(cells)
        for key, val in (extra_arrays or {}).items():
            with _trace.span(
                "dispatch.transfer.d2h", site="stream.snapshot",
                nbytes=int(getattr(val, "nbytes", 0)), key=key,
            ):
                payload[f"x_{key}"] = np.asarray(val)
        return payload

    def _run_segments_pipelined(
        self, ring, n_batches, *, run_dir, snapshot_every, start_step,
        acc0, cells0, collect, resumed_from, extra_arrays,
        watchdog_default_s, retry_policy, root, ring_np, ring_fp,
        k, batch, window=None,
    ) -> StreamResult:
        """The asynchronous pipelined durable loop.

        Segment i+1 is dispatched while segment i still executes: the
        int32 fold accumulator and prefetched cells chain device to
        device (no per-segment host round-trip — bit-identical, the
        device fold IS the int32 wraparound `_wrap_i32` emulates), the
        blocking pull happens at the bounded window's drain, and the
        snapshot write runs on a `dispatch.pipeline.SnapshotWriter`
        thread so checkpoint I/O overlaps the next segments' compute.
        Transient failures at the drain replay through
        :meth:`_segment_sync` from the last materialized carry;
        degradation/watchdog/fault-injection semantics are the
        synchronous loop's (same ``stream.scan_step`` /
        ``stream.snapshot`` sites)."""
        acc_host = (
            np.zeros(3, np.int64) if acc0 is None
            else _wrap_i32(np.asarray(acc0, np.int64))
        )
        if self.prefetch:
            cells_dev = (
                cells0 if cells0 is not None
                else self.assign(ring[start_step % k])
            )
        else:
            cells_dev = jnp.zeros((0,), jnp.int64)  # inert placeholder
        acc_dev = jnp.asarray(_wrap_i32(acc_host).astype(np.int32))
        meta = {
            "n_batches": int(n_batches),
            "batch": batch,
            "ring_k": k,
            "prefetch": self.prefetch,
            "snapshot_every": int(snapshot_every),
            "ring_sha256": ring_fp,
            "index_identity": _checkpoint.index_identity(self.index),
            "trace": root.context.as_dict(),
        }
        degraded = [0]
        counters = {"snapshots": 0}
        outs_list: list[np.ndarray] = []
        host = getattr(self.index, "host", None)
        self._warm_seg_loop(
            ring, cells_dev, start_step, int(n_batches),
            int(snapshot_every), collect,
        )
        bounds = [
            (s, min(snapshot_every, n_batches - s))
            for s in range(start_step, int(n_batches), snapshot_every)
        ]
        win = _pipeline.resolve_window(window)
        writer = _pipeline.SnapshotWriter(
            name="stream", maxsize=max(2, 2 * win)
        )
        # the replay anchor: last materialized (landed) host carry
        landed = {"acc": acc_host, "end": start_step}

        def submit_snapshot(se, acc, cells):
            def job(se=se, acc=np.asarray(acc, np.int64), cells=cells):
                def snap():
                    payload = self._snapshot_payload(
                        acc, cells, extra_arrays
                    )
                    return _checkpoint.save_snapshot(
                        run_dir, se, payload, meta
                    )

                with _trace.span("stream.snapshot", step=se, mode="async"):
                    try:
                        _dispatch.guarded_call(
                            "stream.snapshot", snap,
                            default_s=watchdog_default_s,
                            policy=retry_policy,
                        )
                        counters["snapshots"] += 1
                    except RetryExhausted as e:
                        _telemetry.record(
                            "snapshot_skipped", run_dir=run_dir,
                            step=se, error=repr(e.last)[:200],
                        )

            if cells is not None and hasattr(cells, "copy_to_host_async"):
                cells.copy_to_host_async()  # start the D2H now
            writer.submit(job)

        def launch(i):
            nonlocal acc_dev, cells_dev
            step, seg_n = bounds[i]
            a0, c0 = acc_dev, cells_dev

            def dispatch_async():
                # async dispatch: the returned arrays are futures; the
                # blocking pull happens at the window's drain
                return self._seg_loop(
                    ring, self.index, jnp.int32(step), a0, c0,
                    nb=seg_n, collect=collect,
                )

            with _trace.span(
                "stream.segment", step=step, n=seg_n, pipelined=True
            ):
                try:
                    a, c, o = _dispatch.guarded_call(
                        "stream.scan_step", dispatch_async,
                        default_s=watchdog_default_s,
                        policy=retry_policy,
                    )
                except RetryExhausted as e:
                    if host is None:
                        raise
                    _telemetry.record(
                        "degraded", label="stream.scan_step",
                        step=step, attempts=e.attempts,
                        error=repr(e.last)[:200],
                    )
                    # the carry chain is deterministic: pulling the
                    # in-flight acc blocks until upstream segments
                    # finish and yields the exact pre-segment fold
                    a_host = np.asarray(a0, np.int64)
                    delta, o_np = self._host_segment(
                        ring_np, step, seg_n, collect
                    )
                    acc_new = _wrap_i32(a_host + delta)
                    acc_dev = jnp.asarray(acc_new.astype(np.int32))
                    if self.prefetch:
                        cells_dev = self.assign(
                            ring[(step + seg_n) % k]
                        )
                    return ("host", acc_new, cells_dev, o_np)
                acc_dev, cells_dev = a, c
                return ("dev", a, c, o)

        def land(i, handle):
            # runs under the drain watchdog, whose deadline ABANDONS
            # the worker thread — pulls only, no state mutation (an
            # abandoned worker finishing late must change nothing)
            kind, a, c, o = handle
            if kind == "dev":
                a_np = np.asarray(a)  # blocks: the drain's one pull
                o_np = np.asarray(o) if collect else None
            else:
                a_np, o_np = a, o
            return (kind, a_np, o_np, c)

        def commit(i, pulled):
            kind, a_np, o_np, c = pulled
            step, seg_n = bounds[i]
            se = step + seg_n
            acc_w = _wrap_i32(np.asarray(a_np, np.int64))
            # submit before touching the anchor: copy_to_host_async or
            # a held writer error can raise here, and the replay must
            # then re-apply this segment from the PRE-segment carry
            submit_snapshot(se, acc_w, c if self.prefetch else None)
            if kind == "host":
                # degradation counts at materialization, not launch —
                # a degraded in-flight segment later discarded by a
                # transient is re-run (and counted once) by the replay
                degraded[0] += 1
            if collect and o_np is not None:
                outs_list.append(o_np)
            # anchor update is the final statement: nothing after the
            # submit can fail, so the anchor never runs ahead of the
            # effects it stands for
            landed["acc"] = acc_w
            landed["end"] = se

        def replay(lo, hi):
            nonlocal acc_dev, cells_dev
            acc = landed["acc"]
            step0 = bounds[lo][0]
            cells = (
                self.assign(ring[step0 % k]) if self.prefetch
                else jnp.zeros((0,), jnp.int64)
            )
            for j in range(lo, hi + 1):
                step, seg_n = bounds[j]
                acc, cells, o_np, degr = self._segment_sync(
                    ring, ring_np, step, seg_n, acc, cells,
                    collect=collect,
                    watchdog_default_s=watchdog_default_s,
                    retry_policy=retry_policy, host=host,
                )
                degraded[0] += int(degr)
                if collect and o_np is not None:
                    outs_list.append(o_np)
                landed["acc"] = _wrap_i32(np.asarray(acc, np.int64))
                landed["end"] = step + seg_n
                submit_snapshot(
                    landed["end"], landed["acc"],
                    cells if self.prefetch else None,
                )
            acc_dev = jnp.asarray(landed["acc"].astype(np.int32))
            cells_dev = cells

        t0 = time.perf_counter()
        try:
            pstats = _pipeline.execute_pipeline(
                len(bounds), launch, land,
                drain_site="stream.pipeline.drain", commit=commit,
                replay=replay, window=win,
                watchdog_default_s=watchdog_default_s,
            )
            # durability barrier: a snapshot exists only once its
            # background write completed
            with _trace.span(
                "stream.pipeline.flush", pending=writer.pending
            ), _telemetry.timed("stream_stage", stage="pipeline_flush"):
                writer.flush()
        except BaseException:
            # make completed snapshot writes durable, then let the
            # original failure win — resume replays from the last
            # COMPLETED snapshot, exactly as the synchronous loop
            with contextlib.suppress(BaseException):
                writer.close()
            raise
        writer.close()
        wall = time.perf_counter() - t0
        acc_w = _wrap_i32(landed["acc"])
        n_run = int(n_batches) - start_step
        n_points = int(n_batches) * batch
        _telemetry.record(
            "stream_stage", stage="durable_loop",
            seconds=round(wall, 6), n_batches=int(n_batches),
            batch=batch, ring_k=k, prefetch=self.prefetch,
            snapshots=counters["snapshots"],
            degraded_segments=degraded[0],
            resumed_from=resumed_from, pipelined=True,
            window=pstats.window,
            points_per_sec=round(n_run * batch / max(wall, 1e-9), 1),
        )
        metrics = {
            "degraded": degraded[0] > 0,
            "degraded_segments": degraded[0],
            "snapshots": counters["snapshots"],
            "resumed_from": resumed_from,
            "run_dir": run_dir,
            "pipeline": pstats.as_dict(),
        }
        if (
            self._last_quarantine is not None
            and self._last_quarantine[0] == ring_fp
        ):
            metrics.update(self._last_quarantine[1].metrics())
        return StreamResult(
            checksum=int(acc_w[0]),
            matches=int(acc_w[1]),
            overflow=int(acc_w[2]),
            n_points=n_points,
            n_batches=int(n_batches),
            batch=batch,
            wall_s=wall,
            points_per_sec=n_run * batch / max(wall, 1e-9),
            prefetch=self.prefetch,
            outs=(
                np.concatenate(outs_list)
                if collect and outs_list
                else None
            ),
            metrics=metrics,
        )


def _wrap_i32(v: np.ndarray) -> np.ndarray:
    """int64 -> the int32 two's-complement value (the device fold's
    wraparound semantics, applied on host so segment accumulation stays
    bit-identical to one uninterrupted int32 scan)."""
    return (
        (np.asarray(v, np.int64) + (1 << 31)) % (1 << 32) - (1 << 31)
    ).astype(np.int64)


def fold_stats_np(out: np.ndarray) -> np.ndarray:
    """(3,) int64 host twin of :func:`fold_stats` (checksum term exact
    mod 2^32; wrap with :func:`_wrap_i32` after accumulating)."""
    o = np.asarray(out, np.int32)
    return np.array(
        [
            int((o ^ (o >> 16)).astype(np.int64).sum()),
            int((o >= 0).sum()),
            int((o == -2).sum()),
        ],
        dtype=np.int64,
    )


def generator_rate(
    gen, key: jax.Array, n_batches: int, batch: int
) -> tuple[float, float]:
    """(points_per_sec, wall_s) of ``gen`` alone in a fori_loop identical
    in shape to the join loop — the generator cost the r05 stream silently
    folded into its sustained number. The full-array sum keeps every
    generated element live (a partial fold would let XLA skip most of the
    generation work)."""

    @functools.partial(jax.jit, static_argnames=("nb",))
    def gen_loop(k, nb):
        def body(i, acc):
            return acc + gen(jax.random.fold_in(k, i)).sum()

        return jax.lax.fori_loop(0, nb, body, jnp.zeros((), jnp.float64))

    with _telemetry.timed(
        "stream_stage", stage="gen_compile", n_batches=n_batches
    ):
        float(gen_loop(key, n_batches))
    t0 = time.perf_counter()
    float(gen_loop(key, n_batches))
    wall = max(time.perf_counter() - t0, 1e-9)
    rate = n_batches * batch / wall
    _telemetry.record(
        "stream_stage", stage="gen_loop", seconds=round(wall, 6),
        n_batches=n_batches, batch=batch, points_per_sec=round(rate, 1),
    )
    return rate, wall
