"""Streaming join pipeline: HBM-resident batch ring + double-buffered
cell-assignment prefetch.

Why this layer exists (round-5 measurement, `STREAM_1B_r05.json`): the
1B-point device-gen stream sustained 47.2M pts/s against a 132.2M pts/s
single-batch rate (0.357x) because the `fori_loop` folded point
*generation* into every iteration and nothing overlapped batch staging
with the join. The 3DPipe lesson (PAPERS.md) is that the fix is
structural: split the stream into pipelined stages and keep the next
batch's inputs resident before the current batch's compute needs them.

Three pieces, all CPU-testable and bit-identical to the per-batch path:

- **Ring** — K pre-generated point batches stacked into one (K, B, 2)
  HBM-resident array the loop cycles (`ring_from_host` /
  `ring_from_generator`). Generator cost moves OUT of the measured loop;
  `generator_rate` (an identical fori_loop running only `gen_batch`)
  prices it separately.
- **Prefetch** — inside the jitted scan, iteration i joins batch i with
  the cell ids computed in iteration i-1 and computes batch i+1's cell
  assignment in the same program. The two stages have no data dependency,
  so XLA overlaps the cell pipeline (one-hot MXU work) with the PIP
  probe's gather/scatter phases instead of serializing them.
- **Accounting** — every stage emits a `stream_stage` telemetry event
  (`runtime/telemetry.py`) with measured wall seconds, and
  :func:`hbm_peak` reports the loop's high-water device memory — from
  runtime memory stats when the backend exposes them, else a live-buffer
  census (the axon tunnel returns no stats: STREAM_1B_r05 recorded
  ``peak_hbm_bytes: 0``; that zero is the bug this closes).

Completion is always forced by :func:`fold_stats` — a device-side
(checksum, matches, overflow) fold so no per-point data crosses the
host link inside a measured region.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import telemetry as _telemetry
from .join import ChipIndex, pip_join_points


def fold_stats(out: jax.Array) -> jax.Array:
    """(3,) int32 device-side completion fold of a join output: full-bit
    XOR-shift checksum (every result bit stays live — a masked sum lets
    XLA dead-code the high half), match count, overflow count."""
    return jnp.stack(
        [
            (out ^ (out >> 16)).sum().astype(jnp.int32),
            (out >= 0).sum().astype(jnp.int32),
            (out == -2).sum().astype(jnp.int32),
        ]
    )


def ring_from_host(batches) -> jax.Array:
    """Stack host point batches into one (K, B, 2) f64 device-resident
    ring. Blocks until the ring is staged (staging is not loop time)."""
    with _telemetry.timed("stream_stage", stage="ring_build", source="host"):
        ring = jnp.stack([jnp.asarray(b, dtype=jnp.float64) for b in batches])
        ring.block_until_ready()
    return ring


def ring_from_generator(gen, key: jax.Array, k: int) -> jax.Array:
    """Device-generated ring: ``gen(fold_in(key, i)) -> (B, 2)`` for K
    distinct slots, stacked resident in HBM."""
    with _telemetry.timed(
        "stream_stage", stage="ring_build", source="device_gen", k=k
    ):
        ring = jnp.stack(
            [gen(jax.random.fold_in(key, i)) for i in range(k)]
        )
        ring.block_until_ready()
    return ring


def hbm_peak(device=None, fallback_arrays=()) -> tuple[int, str]:
    """(peak_bytes, source) for ``device`` (default: first device).

    Prefers the runtime's ``memory_stats()`` high-water mark; when the
    backend reports none (CPU, and the axon TPU tunnel — the source of
    the ``peak_hbm_bytes: 0`` artifact bug), falls back to a census of
    live device buffers (ring + index + loop carries are resident at the
    high-water point, so this lower-bounds the true peak).
    """
    dev = device if device is not None else jax.devices()[0]
    try:
        st = dev.memory_stats() or {}
    except Exception:
        st = {}
    for key in ("peak_bytes_in_use", "bytes_in_use", "bytes_used"):
        v = int(st.get(key, 0) or 0)
        if v > 0:
            return v, f"memory_stats.{key}"
    total = 0
    try:
        arrays = list(jax.live_arrays())
    except Exception:
        arrays = list(fallback_arrays)
    for a in arrays:
        try:
            total += int(a.nbytes)
        except Exception:
            pass
    return total, "live_buffer_census"


@dataclasses.dataclass
class StreamResult:
    """One streamed run: device-fold stats + wall-clock accounting."""

    checksum: int
    matches: int
    overflow: int
    n_points: int
    n_batches: int
    batch: int
    wall_s: float
    points_per_sec: float
    prefetch: bool
    outs: np.ndarray | None = None  # (nb, B) per-batch rows (collect=True)


class StreamJoin:
    """Compiled streaming pip-join over a resident ring.

    Splits the fused bench step into its two stages — ``assign`` (grid
    cell ids) and ``join`` (the PIP probe) — and compiles one scan that
    cycles ring slots with optional double-buffered prefetch of the next
    batch's cell assignment. ``run`` (prefetch on) is bit-identical to
    ``run_batched`` (one call per batch, no pipeline): cell assignment is
    deterministic, so joining batch i against cells computed one
    iteration early changes scheduling, never values — pinned by
    tests/test_stream.py.
    """

    def __init__(
        self,
        index: ChipIndex,
        index_system,
        resolution: int,
        *,
        found_cap: int | None = None,
        heavy_cap: int | None = None,
        lookup: str | None = None,
        compaction: str | None = None,
        cell_dtype=jnp.float32,
        prefetch: bool = True,
    ):
        self.index = index
        self.prefetch = bool(prefetch)
        dtype = index.border.verts.dtype
        platform = jax.devices()[0].platform
        if lookup is None:
            lookup = (
                "mxu"
                if platform != "cpu" and dtype == jnp.float32
                else "gather"
            )
        if compaction is None:
            compaction = "scatter" if platform == "cpu" else "mxu"
        self.lookup, self.compaction = lookup, compaction
        self.found_cap, self.heavy_cap = found_cap, heavy_cap

        def assign(pts):
            c = index_system.point_to_cell(
                pts.astype(cell_dtype), resolution
            )
            return c.astype(jnp.int64)

        def join(pts, cells, chip_index):
            shifted = (pts - chip_index.border.shift).astype(dtype)
            return pip_join_points(
                shifted,
                cells,
                chip_index,
                heavy_cap=heavy_cap,
                found_cap=found_cap,
                lookup=lookup,
                compaction=compaction,
            )

        self.assign = jax.jit(assign)
        self.join = jax.jit(join)
        self._step = jax.jit(lambda pts, ix: join(pts, assign(pts), ix))
        # fused step + fold: benches time THIS (one (3,) pull forces
        # completion; pulling the (N,) rows would measure the tunnel)
        self._step_stats = jax.jit(
            lambda pts, ix: fold_stats(join(pts, assign(pts), ix))
        )

        def loop(ring, chip_index, nb: int, collect: bool):
            k = ring.shape[0]

            def slot(i):
                return jax.lax.dynamic_index_in_dim(
                    ring, i % k, axis=0, keepdims=False
                )

            if self.prefetch:

                def body(carry, i):
                    acc, cells_cur = carry
                    # join batch i against the cells prefetched at i-1;
                    # assign batch i+1's cells in the SAME program so XLA
                    # overlaps the cell pipeline with the probe
                    out = join(slot(i), cells_cur, chip_index)
                    cells_next = assign(slot(i + 1))
                    return (acc + fold_stats(out), cells_next), (
                        out if collect else None
                    )

                carry0 = (jnp.zeros(3, jnp.int32), assign(ring[0]))
            else:

                def body(carry, i):
                    pts = slot(i)
                    out = join(pts, assign(pts), chip_index)
                    return carry + fold_stats(out), (
                        out if collect else None
                    )

                carry0 = jnp.zeros(3, jnp.int32)
            carry, outs = jax.lax.scan(
                body, carry0, jnp.arange(nb, dtype=jnp.int32)
            )
            acc = carry[0] if self.prefetch else carry
            return acc, outs

        self._loop = jax.jit(loop, static_argnames=("nb", "collect"))

    def step(self, pts: jax.Array) -> jax.Array:
        """Single fused batch (assign + join) — the single-batch-rate
        reference the sustained number is measured against."""
        return self._step(pts, self.index)

    def step_stats(self, pts: jax.Array) -> jax.Array:
        """Single fused batch, device-folded to (3,) stats."""
        return self._step_stats(pts, self.index)

    def compile(self, ring: jax.Array, n_batches: int, collect=False):
        """Warm the loop program (compile time must not pollute the
        sustained measurement); emits a ``stream_stage`` compile event."""
        with _telemetry.timed(
            "stream_stage", stage="compile", n_batches=n_batches,
            prefetch=self.prefetch,
        ):
            acc, outs = self._loop(ring, self.index, n_batches, collect)
            jax.block_until_ready(acc)
        return acc, outs

    def run(
        self, ring: jax.Array, n_batches: int, *, collect: bool = False
    ) -> StreamResult:
        """One timed streamed pass over ``n_batches`` ring cycles.

        The whole stream is ONE dispatch (per-batch python dispatch over
        the tunnel measured 146 ms/batch for a 63 ms device step in r05);
        completion is forced by pulling the (3,) fold.
        """
        k, batch = int(ring.shape[0]), int(ring.shape[1])
        t0 = time.perf_counter()
        acc, outs = self._loop(ring, self.index, n_batches, collect)
        acc_np = np.asarray(acc)  # blocks: the loop's only host pull
        wall = time.perf_counter() - t0
        n_points = n_batches * batch
        _telemetry.record(
            "stream_stage", stage="join_loop",
            seconds=round(wall, 6), n_batches=n_batches, batch=batch,
            ring_k=k, prefetch=self.prefetch,
            points_per_sec=round(n_points / max(wall, 1e-9), 1),
        )
        return StreamResult(
            checksum=int(acc_np[0]),
            matches=int(acc_np[1]),
            overflow=int(acc_np[2]),
            n_points=n_points,
            n_batches=n_batches,
            batch=batch,
            wall_s=wall,
            points_per_sec=n_points / max(wall, 1e-9),
            prefetch=self.prefetch,
            outs=np.asarray(outs) if collect else None,
        )

    def run_batched(self, ring: jax.Array, n_batches: int) -> StreamResult:
        """Per-batch reference path: one ``step`` call per ring slot, no
        pipeline, host-accumulated stats — the bit-identity oracle for
        the scanned loop (and the honest non-overlapped comparison)."""
        k, batch = int(ring.shape[0]), int(ring.shape[1])
        outs, acc = [], np.zeros(3, np.int64)
        t0 = time.perf_counter()
        for i in range(n_batches):
            out = self.step(ring[i % k])
            outs.append(np.asarray(out))
            acc += np.asarray(fold_stats(out), dtype=np.int64)
        wall = time.perf_counter() - t0
        n_points = n_batches * batch
        # int32 wraparound to match the device-side accumulator
        c = int(acc[0]) & 0xFFFFFFFF
        if c >= 1 << 31:
            c -= 1 << 32
        return StreamResult(
            checksum=c,
            matches=int(acc[1]),
            overflow=int(acc[2]),
            n_points=n_points,
            n_batches=n_batches,
            batch=batch,
            wall_s=wall,
            points_per_sec=n_points / max(wall, 1e-9),
            prefetch=False,
            outs=np.stack(outs),
        )


def generator_rate(
    gen, key: jax.Array, n_batches: int, batch: int
) -> tuple[float, float]:
    """(points_per_sec, wall_s) of ``gen`` alone in a fori_loop identical
    in shape to the join loop — the generator cost the r05 stream silently
    folded into its sustained number. The full-array sum keeps every
    generated element live (a partial fold would let XLA skip most of the
    generation work)."""

    @functools.partial(jax.jit, static_argnames=("nb",))
    def gen_loop(k, nb):
        def body(i, acc):
            return acc + gen(jax.random.fold_in(k, i)).sum()

        return jax.lax.fori_loop(0, nb, body, jnp.zeros((), jnp.float64))

    with _telemetry.timed(
        "stream_stage", stage="gen_compile", n_batches=n_batches
    ):
        float(gen_loop(key, n_batches))
    t0 = time.perf_counter()
    float(gen_loop(key, n_batches))
    wall = max(time.perf_counter() - t0, 1e-9)
    rate = n_batches * batch / wall
    _telemetry.record(
        "stream_stage", stage="gen_loop", seconds=round(wall, 6),
        n_batches=n_batches, batch=batch, points_per_sec=round(rate, 1),
    )
    return rate, wall
