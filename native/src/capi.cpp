// C ABI for the host geometry engine (ctypes-consumed from Python).
//
// Exchange format: a geometry is a flat contour list — double* xy (2*nv),
// int64* ring_off (nr+1) — even-odd semantics (shells and holes are both
// just contours). Shell/hole nesting is reconstructed on the Python side.
// All returned buffers are malloc'd and released via mg_free_result.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

#include "martinez.cpp"

namespace mg {

static std::vector<Contour> toContours(const double* xy, const int64_t* ro,
                                       int64_t nr) {
  std::vector<Contour> cs;
  cs.reserve((size_t)nr);
  for (int64_t r = 0; r < nr; ++r) {
    Contour c;
    for (int64_t v = ro[r]; v < ro[r + 1]; ++v)
      c.push_back({xy[2 * v], xy[2 * v + 1]});
    // drop explicit closing vertex
    if (c.size() >= 2 && c.front() == c.back()) c.pop_back();
    if (c.size() >= 3) cs.push_back(std::move(c));
  }
  return cs;
}

// open polyline chains: keep short runs, no closing-vertex strip
static std::vector<Contour> toChains(const double* xy, const int64_t* ro,
                                     int64_t nr) {
  std::vector<Contour> cs;
  cs.reserve((size_t)nr);
  for (int64_t r = 0; r < nr; ++r) {
    Contour c;
    for (int64_t v = ro[r]; v < ro[r + 1]; ++v)
      c.push_back({xy[2 * v], xy[2 * v + 1]});
    if (!c.empty()) cs.push_back(std::move(c));
  }
  return cs;
}

static double contourArea(const Contour& c) {
  double a = 0;
  for (size_t i = 0; i < c.size(); ++i) {
    const Pt& p = c[i];
    const Pt& q = c[(i + 1) % c.size()];
    a += p.x * q.y - q.x * p.y;
  }
  return 0.5 * a;
}

// Snap-round every coordinate onto one shared power-of-two lattice
// (~2^-40 of the coordinate magnitude, ~1e-12 relative). Rationale: chips
// produced by independent clipping passes share edges whose endpoints differ
// in the last few ulps; the sweep line handles *bit-identical* overlapping
// segments robustly but mis-resolves almost-coincident ones. A power-of-two
// quantum makes the snap exact in binary floating point.
static void snapLattice(std::vector<std::vector<Contour>*> groups) {
  double m = 0;
  for (auto* cs : groups)
    for (auto& c : *cs)
      for (auto& p : c) {
        m = std::max(m, std::abs(p.x));
        m = std::max(m, std::abs(p.y));
      }
  if (!(m > 0) || !std::isfinite(m)) return;
  double q = std::ldexp(1.0, (int)std::floor(std::log2(m)) - 40);
  for (auto* cs : groups) {
    for (auto& c : *cs)
      for (auto& p : c) {
        p.x = std::round(p.x / q) * q;
        p.y = std::round(p.y / q) * q;
      }
    // snapping can merge consecutive vertices; drop dups + degenerates
    for (auto& c : *cs) {
      Contour d;
      for (auto& p : c)
        if (d.empty() || !(d.back() == p)) d.push_back(p);
      if (d.size() >= 2 && d.front() == d.back()) d.pop_back();
      c.swap(d);
    }
    cs->erase(std::remove_if(cs->begin(), cs->end(),
                             [](const Contour& c) { return c.size() < 3; }),
              cs->end());
  }
}

static void dropSlivers(std::vector<Contour>& cs, double eps) {
  cs.erase(std::remove_if(cs.begin(), cs.end(),
                          [&](const Contour& c) {
                            return std::abs(contourArea(c)) <= eps;
                          }),
           cs.end());
}

static int emit(const std::vector<Contour>& cs, double** out_xy,
                int64_t** out_ro, int64_t* out_nv, int64_t* out_nr) {
  int64_t nv = 0;
  for (auto& c : cs) nv += (int64_t)c.size();
  double* xy = (double*)malloc(sizeof(double) * 2 * std::max<int64_t>(nv, 1));
  int64_t* ro = (int64_t*)malloc(sizeof(int64_t) * (cs.size() + 1));
  if (!xy || !ro) { free(xy); free(ro); return -1; }
  int64_t v = 0;
  ro[0] = 0;
  for (size_t r = 0; r < cs.size(); ++r) {
    for (auto& p : cs[r]) {
      xy[2 * v] = p.x;
      xy[2 * v + 1] = p.y;
      ++v;
    }
    ro[r + 1] = v;
  }
  *out_xy = xy;
  *out_ro = ro;
  *out_nv = nv;
  *out_nr = (int64_t)cs.size();
  return 0;
}

// union of many contour-sets by binary reduction (keeps operand sizes small)
static std::vector<Contour> unionMany(std::vector<std::vector<Contour>> items) {
  if (items.empty()) return {};
  while (items.size() > 1) {
    std::vector<std::vector<Contour>> next;
    for (size_t i = 0; i + 1 < items.size(); i += 2) {
      std::vector<Contour> out;
      boolOp(OP_UNION, items[i], items[i + 1], out);
      next.push_back(std::move(out));
    }
    if (items.size() & 1) next.push_back(std::move(items.back()));
    items.swap(next);
  }
  return std::move(items[0]);
}

static std::vector<Contour> capsules(const std::vector<Contour>& rings,
                                     bool closed, double r, int quadSegs) {
  // All arc vertices are sampled from ONE global angle lattice
  // (2*pi*j/N, j integer). Capsules of adjacent edges then share *bit-
  // identical* vertices on the arcs around their common endpoint, so the
  // sweep sees exactly-coincident overlapping segments (its robust path)
  // instead of segments that differ in the last ulp (its fragile path).
  std::vector<std::vector<Contour>> caps;
  int N = std::max(2, quadSegs) * 4;  // full-circle lattice resolution
  std::vector<double> ux(N), uy(N);
  for (int j = 0; j < N; ++j) {
    double t = 2.0 * M_PI * j / N;
    ux[j] = std::cos(t);
    uy[j] = std::sin(t);
  }
  auto at = [&](const Pt& c, int j) -> Pt {
    j = ((j % N) + N) % N;
    return {c.x + r * ux[j], c.y + r * uy[j]};
  };
  for (auto& ring : rings) {
    size_t n = ring.size();
    size_t nedges = closed ? n : (n > 0 ? n - 1 : 0);
    if (n == 1 && !closed) nedges = 1;  // lone point -> disc
    for (size_t i = 0; i < nedges; ++i) {
      Pt a = ring[i];
      Pt b = ring[(i + 1) % n];
      double dx = b.x - a.x, dy = b.y - a.y;
      double len = std::sqrt(dx * dx + dy * dy);
      Contour c;
      if (len < 1e-300) {  // disc
        for (int j = 0; j < N; ++j) c.push_back(at(a, j));
      } else {
        double base = std::atan2(dx, -dy);  // left-normal angle of the edge
        int j0 = (int)std::lround(base / (2.0 * M_PI / N));
        // CCW: arc around b from the +normal to the -normal (clockwise in
        // angle = through the edge's forward direction), then back around a
        for (int k = 0; k <= N / 2; ++k) c.push_back(at(b, j0 - k));
        for (int k = 0; k <= N / 2; ++k) c.push_back(at(a, j0 - N / 2 - k));
      }
      caps.push_back({std::move(c)});
    }
  }
  // union them here so callers get one flattened contour set
  return unionMany(std::move(caps));
}

}  // namespace mg

extern "C" {

// ops: 0=intersection 1=union 2=difference 3=xor
int mg_bool_op(int op, const double* axy, const int64_t* aro, int64_t anr,
               const double* bxy, const int64_t* bro, int64_t bnr,
               double** out_xy, int64_t** out_ro, int64_t* out_nv,
               int64_t* out_nr) {
  auto a = mg::toContours(axy, aro, anr);
  auto b = mg::toContours(bxy, bro, bnr);
  mg::snapLattice({&a, &b});
  std::vector<mg::Contour> out;
  mg::boolOp((mg::BoolOp)op, a, b, out);
  mg::dropSlivers(out, 0.0);
  return mg::emit(out, out_xy, out_ro, out_nv, out_nr);
}

// buffer a polygon (closed rings, even-odd) by dist (may be negative)
int mg_buffer(const double* axy, const int64_t* aro, int64_t anr, int closed,
              double dist, int quad_segs, double** out_xy, int64_t** out_ro,
              int64_t* out_nv, int64_t* out_nr) {
  auto rings = closed ? mg::toContours(axy, aro, anr)
                      : mg::toChains(axy, aro, anr);
  std::vector<mg::Contour> out;
  if (dist == 0.0) {
    if (closed) out = rings;  // zero-width buffer of lines/points is empty
  } else if (!closed) {
    // lines/points: buffer = union of edge capsules
    if (dist > 0) out = mg::capsules(rings, false, dist, quad_segs);
  } else if (dist > 0) {
    auto caps = mg::capsules(rings, true, dist, quad_segs);
    mg::boolOp(mg::OP_UNION, rings, caps, out);
  } else {
    auto caps = mg::capsules(rings, true, -dist, quad_segs);
    mg::boolOp(mg::OP_DIFFERENCE, rings, caps, out);
  }
  mg::dropSlivers(out, 0.0);
  return mg::emit(out, out_xy, out_ro, out_nv, out_nr);
}

// union of n geometries given as one flat contour list with a geometry
// partition go (n+1 entries into rings)
int mg_union_many(const double* xy, const int64_t* ro, int64_t nr,
                  const int64_t* go, int64_t ng, double** out_xy,
                  int64_t** out_ro, int64_t* out_nv, int64_t* out_nr) {
  (void)nr;
  std::vector<std::vector<mg::Contour>> items;
  {
    for (int64_t g = 0; g < ng; ++g) {
      std::vector<mg::Contour> item;
      for (int64_t r = go[g]; r < go[g + 1]; ++r) {
        mg::Contour c;
        for (int64_t v = ro[r]; v < ro[r + 1]; ++v)
          c.push_back({xy[2 * v], xy[2 * v + 1]});
        if (c.size() >= 2 && c.front() == c.back()) c.pop_back();
        if (c.size() >= 3) item.push_back(std::move(c));
      }
      if (!item.empty()) items.push_back(std::move(item));
    }
  }
  {
    std::vector<std::vector<mg::Contour>*> ptrs;
    for (auto& it : items) ptrs.push_back(&it);
    mg::snapLattice(ptrs);
  }
  auto out = mg::unionMany(std::move(items));
  mg::dropSlivers(out, 0.0);
  return mg::emit(out, out_xy, out_ro, out_nv, out_nr);
}

void mg_free_result(double* xy, int64_t* ro) {
  free(xy);
  free(ro);
}

// Andrew monotone chain; returns hull size, writes CCW hull into out (cap 2n)
int64_t mg_convex_hull(const double* xy, int64_t n, double* out) {
  std::vector<mg::Pt> pts(n);
  for (int64_t i = 0; i < n; ++i) pts[i] = {xy[2 * i], xy[2 * i + 1]};
  std::sort(pts.begin(), pts.end(), [](const mg::Pt& a, const mg::Pt& b) {
    return a.x < b.x || (a.x == b.x && a.y < b.y);
  });
  pts.erase(std::unique(pts.begin(), pts.end(),
                        [](const mg::Pt& a, const mg::Pt& b) {
                          return a.x == b.x && a.y == b.y;
                        }),
            pts.end());
  int64_t m = (int64_t)pts.size();
  if (m <= 2) {
    for (int64_t i = 0; i < m; ++i) { out[2 * i] = pts[i].x; out[2 * i + 1] = pts[i].y; }
    return m;
  }
  std::vector<mg::Pt> h(2 * m);
  int64_t k = 0;
  for (int64_t i = 0; i < m; ++i) {
    while (k >= 2 && mg::signedArea(h[k - 2], h[k - 1], pts[i]) <= 0) --k;
    h[k++] = pts[i];
  }
  int64_t lower = k + 1;
  for (int64_t i = m - 2; i >= 0; --i) {
    while (k >= lower && mg::signedArea(h[k - 2], h[k - 1], pts[i]) <= 0) --k;
    h[k++] = pts[i];
  }
  --k;  // last point equals first
  for (int64_t i = 0; i < k; ++i) { out[2 * i] = h[i].x; out[2 * i + 1] = h[i].y; }
  return k;
}

// Douglas-Peucker: writes 0/1 keep flags; closed rings anchor at 0 and the
// farthest-from-0 vertex
int64_t mg_simplify_mask(const double* xy, int64_t n, double tol, int closed,
                         uint8_t* keep) {
  if (n <= 2) {
    for (int64_t i = 0; i < n; ++i) keep[i] = 1;
    return n;
  }
  std::memset(keep, 0, (size_t)n);
  auto dist2seg = [&](int64_t i, int64_t a, int64_t b) {
    double ax = xy[2 * a], ay = xy[2 * a + 1];
    double bx = xy[2 * b], by = xy[2 * b + 1];
    double px = xy[2 * i], py = xy[2 * i + 1];
    double dx = bx - ax, dy = by - ay;
    double l2 = dx * dx + dy * dy;
    double t = l2 > 0 ? ((px - ax) * dx + (py - ay) * dy) / l2 : 0.0;
    t = std::max(0.0, std::min(1.0, t));
    double qx = ax + t * dx - px, qy = ay + t * dy - py;
    return qx * qx + qy * qy;
  };
  double tol2 = tol * tol;
  std::vector<std::pair<int64_t, int64_t>> stack;
  auto dp = [&](int64_t a, int64_t b) {
    stack.push_back({a, b});
    while (!stack.empty()) {
      auto [s, e] = stack.back();
      stack.pop_back();
      double dmax = -1.0;
      int64_t imax = -1;
      for (int64_t i = s + 1; i < e; ++i) {
        double d = dist2seg(i, s, e);
        if (d > dmax) { dmax = d; imax = i; }
      }
      if (imax >= 0 && dmax > tol2) {
        keep[imax] = 1;
        stack.push_back({s, imax});
        stack.push_back({imax, e});
      }
    }
  };
  if (closed) {
    // anchor: vertex 0 and the farthest vertex from it
    double dmax = -1;
    int64_t imax = n / 2;
    for (int64_t i = 1; i < n; ++i) {
      double dx = xy[2 * i] - xy[0], dy = xy[2 * i + 1] - xy[1];
      double d = dx * dx + dy * dy;
      if (d > dmax) { dmax = d; imax = i; }
    }
    keep[0] = keep[imax] = 1;
    dp(0, imax);
    dp(imax, n - 1);
    keep[n - 1] = 1;  // ring input arrives open; last vertex stays
  } else {
    keep[0] = keep[n - 1] = 1;
    dp(0, n - 1);
  }
  int64_t cnt = 0;
  for (int64_t i = 0; i < n; ++i) cnt += keep[i];
  return cnt;
}
}
