// Second geometry engine (C++, from scratch) — the ESRI-engine role of the
// reference's dual-engine contract (`core/geometry/api/GeometryAPI.scala`:
// the reference ships JTS *and* ESRI implementations of every geometry op
// and its tests cross-check them). Here the independent pair is: the
// jitted JAX device kernels / numpy oracle (same repo, same author, shared
// conventions) vs THIS file — separate language, separate algorithms,
// separate numerics (Kahan-compensated accumulation, half-open edge rule),
// consumed through the C ABI by `core/geometry/second.py` and cross-checked
// in `tests/test_second_engine.py`.
//
// Exchange format matches capi.cpp: flat contour lists (double* xy, 2*nv;
// int64* ring_off, nr+1). Holes are passed explicitly (uint8* is_hole) —
// membership tests ignore the flags (even-odd parity handles holes), the
// signed measures use them.

#include <cmath>
#include <cstdint>
#include <cstdlib>

namespace mgeval {

struct Kahan {
  double s = 0, c = 0;
  inline void add(double v) {
    double y = v - c;
    double t = s + y;
    c = (t - s) - y;
    s = t;
  }
};

// twice the signed area of one closed contour (last->first edge implied)
static double contourArea2(const double* xy, int64_t lo, int64_t hi) {
  Kahan k;
  int64_t n = hi - lo;
  if (n < 3) return 0.0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t j = (i + 1) % n;
    double x0 = xy[2 * (lo + i)], y0 = xy[2 * (lo + i) + 1];
    double x1 = xy[2 * (lo + j)], y1 = xy[2 * (lo + j) + 1];
    k.add(x0 * y1 - x1 * y0);
  }
  return k.s;
}

// even-odd crossing parity of one point against every contour, half-open
// edge rule (y0 <= py < y1) so shared vertices count once
static bool evenOddInside(const double* xy, const int64_t* ro, int64_t nr,
                          double px, double py) {
  bool in = false;
  for (int64_t r = 0; r < nr; ++r) {
    int64_t lo = ro[r], hi = ro[r + 1], n = hi - lo;
    if (n < 3) continue;
    for (int64_t i = 0; i < n; ++i) {
      int64_t j = (i + 1) % n;
      double x0 = xy[2 * (lo + i)], y0 = xy[2 * (lo + i) + 1];
      double x1 = xy[2 * (lo + j)], y1 = xy[2 * (lo + j) + 1];
      if ((y0 <= py) != (y1 <= py)) {
        double xc = x0 + (py - y0) / (y1 - y0) * (x1 - x0);
        if (px < xc) in = !in;
      }
    }
  }
  return in;
}

static double segDist2(double px, double py, double x0, double y0, double x1,
                       double y1) {
  double dx = x1 - x0, dy = y1 - y0;
  double L2 = dx * dx + dy * dy;
  double t = L2 > 0 ? ((px - x0) * dx + (py - y0) * dy) / L2 : 0.0;
  t = t < 0 ? 0 : (t > 1 ? 1 : t);
  double qx = x0 + t * dx - px, qy = y0 + t * dy - py;
  return qx * qx + qy * qy;
}

}  // namespace mgeval

extern "C" {

// area (holes negative), perimeter, and area-weighted centroid of one
// polygonal geometry. out = {area, perimeter, cx, cy}. rc 0 = ok.
int mg_eval_polygon(const double* xy, const int64_t* ro, int64_t nr,
                    const uint8_t* is_hole, double* out) {
  using mgeval::Kahan;
  Kahan area2, perim, cx6, cy6;
  for (int64_t r = 0; r < nr; ++r) {
    int64_t lo = ro[r], hi = ro[r + 1], n = hi - lo;
    if (n < 3) continue;
    double a2 = mgeval::contourArea2(xy, lo, hi);
    // normalize to positive, then sign by the hole flag — independent of
    // stored ring orientation
    double sgn = (is_hole && is_hole[r]) ? -1.0 : 1.0;
    double orient = a2 >= 0 ? 1.0 : -1.0;
    area2.add(sgn * orient * a2);
    Kahan mx, my;
    for (int64_t i = 0; i < n; ++i) {
      int64_t j = (i + 1) % n;
      double x0 = xy[2 * (lo + i)], y0 = xy[2 * (lo + i) + 1];
      double x1 = xy[2 * (lo + j)], y1 = xy[2 * (lo + j) + 1];
      double cross = x0 * y1 - x1 * y0;
      mx.add((x0 + x1) * cross);
      my.add((y0 + y1) * cross);
      perim.add(std::hypot(x1 - x0, y1 - y0));
    }
    cx6.add(sgn * orient * mx.s);
    cy6.add(sgn * orient * my.s);
  }
  double area = 0.5 * area2.s;
  out[0] = area;
  out[1] = perim.s;
  if (area != 0) {
    out[2] = cx6.s / (6.0 * area);
    out[3] = cy6.s / (6.0 * area);
  } else {
    out[2] = out[3] = NAN;
  }
  return 0;
}

// total polyline length of open chains
int mg_eval_length(const double* xy, const int64_t* ro, int64_t nr,
                   double* out) {
  mgeval::Kahan k;
  for (int64_t r = 0; r < nr; ++r) {
    for (int64_t i = ro[r]; i + 1 < ro[r + 1]; ++i)
      k.add(std::hypot(xy[2 * (i + 1)] - xy[2 * i],
                       xy[2 * (i + 1) + 1] - xy[2 * i + 1]));
  }
  *out = k.s;
  return 0;
}

int mg_eval_bounds(const double* xy, int64_t nv, double* out) {
  double xmin = INFINITY, ymin = INFINITY, xmax = -INFINITY, ymax = -INFINITY;
  for (int64_t i = 0; i < nv; ++i) {
    double x = xy[2 * i], y = xy[2 * i + 1];
    xmin = x < xmin ? x : xmin;
    xmax = x > xmax ? x : xmax;
    ymin = y < ymin ? y : ymin;
    ymax = y > ymax ? y : ymax;
  }
  out[0] = xmin;
  out[1] = ymin;
  out[2] = xmax;
  out[3] = ymax;
  return 0;
}

// even-odd point-in-polygon for npts points; out[i] in {0, 1}
int mg_eval_contains(const double* xy, const int64_t* ro, int64_t nr,
                     const double* pts, int64_t npts, uint8_t* out) {
  for (int64_t i = 0; i < npts; ++i)
    out[i] = mgeval::evenOddInside(xy, ro, nr, pts[2 * i], pts[2 * i + 1])
                 ? 1
                 : 0;
  return 0;
}

// point -> polygon distance: 0 inside, else min distance to any edge
int mg_eval_distance(const double* xy, const int64_t* ro, int64_t nr,
                     const double* pts, int64_t npts, double* out) {
  for (int64_t i = 0; i < npts; ++i) {
    double px = pts[2 * i], py = pts[2 * i + 1];
    if (mgeval::evenOddInside(xy, ro, nr, px, py)) {
      out[i] = 0.0;
      continue;
    }
    double d2 = INFINITY;
    for (int64_t r = 0; r < nr; ++r) {
      int64_t lo = ro[r], hi = ro[r + 1], n = hi - lo;
      for (int64_t k = 0; k < n; ++k) {
        int64_t j = (k + 1) % n;
        double v = mgeval::segDist2(px, py, xy[2 * (lo + k)],
                                    xy[2 * (lo + k) + 1], xy[2 * (lo + j)],
                                    xy[2 * (lo + j) + 1]);
        d2 = v < d2 ? v : d2;
      }
    }
    out[i] = std::isfinite(d2) ? std::sqrt(d2) : NAN;
  }
  return 0;
}

}  // extern "C"
