// Second geometry engine (C++, from scratch) — the ESRI-engine role of the
// reference's dual-engine contract (`core/geometry/api/GeometryAPI.scala`:
// the reference ships JTS *and* ESRI implementations of every geometry op
// and its tests cross-check them). Here the independent pair is: the
// jitted JAX device kernels / numpy oracle (same repo, same author, shared
// conventions) vs THIS file — separate language, separate algorithms,
// separate numerics (Kahan-compensated accumulation, half-open edge rule),
// consumed through the C ABI by `core/geometry/second.py` and cross-checked
// in `tests/test_second_engine.py`.
//
// Exchange format matches capi.cpp: flat contour lists (double* xy, 2*nv;
// int64* ring_off, nr+1). Holes are passed explicitly (uint8* is_hole) —
// membership tests ignore the flags (even-odd parity handles holes), the
// signed measures use them.

#include <algorithm>
#include <array>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace mgeval {

struct Kahan {
  double s = 0, c = 0;
  inline void add(double v) {
    double y = v - c;
    double t = s + y;
    c = (t - s) - y;
    s = t;
  }
};

// twice the signed area of one closed contour (last->first edge implied)
static double contourArea2(const double* xy, int64_t lo, int64_t hi) {
  Kahan k;
  int64_t n = hi - lo;
  if (n < 3) return 0.0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t j = (i + 1) % n;
    double x0 = xy[2 * (lo + i)], y0 = xy[2 * (lo + i) + 1];
    double x1 = xy[2 * (lo + j)], y1 = xy[2 * (lo + j) + 1];
    k.add(x0 * y1 - x1 * y0);
  }
  return k.s;
}

// even-odd crossing parity of one point against every contour, half-open
// edge rule (y0 <= py < y1) so shared vertices count once
static bool evenOddInside(const double* xy, const int64_t* ro, int64_t nr,
                          double px, double py) {
  bool in = false;
  for (int64_t r = 0; r < nr; ++r) {
    int64_t lo = ro[r], hi = ro[r + 1], n = hi - lo;
    if (n < 3) continue;
    for (int64_t i = 0; i < n; ++i) {
      int64_t j = (i + 1) % n;
      double x0 = xy[2 * (lo + i)], y0 = xy[2 * (lo + i) + 1];
      double x1 = xy[2 * (lo + j)], y1 = xy[2 * (lo + j) + 1];
      if ((y0 <= py) != (y1 <= py)) {
        double xc = x0 + (py - y0) / (y1 - y0) * (x1 - x0);
        if (px < xc) in = !in;
      }
    }
  }
  return in;
}

static double segDist2(double px, double py, double x0, double y0, double x1,
                       double y1) {
  double dx = x1 - x0, dy = y1 - y0;
  double L2 = dx * dx + dy * dy;
  double t = L2 > 0 ? ((px - x0) * dx + (py - y0) * dy) / L2 : 0.0;
  t = t < 0 ? 0 : (t > 1 ? 1 : t);
  double qx = x0 + t * dx - px, qy = y0 + t * dy - py;
  return qx * qx + qy * qy;
}

// ---------------------------------------------------------------------------
// Independent boolean operations — the second-engine witness for the
// Martinez sweep in martinez.cpp. Deliberately a different algorithm
// family: O(Ea*Eb) pairwise edge subdivision, then per-subedge SIDE
// MEMBERSHIP classification (a subedge belongs to the result boundary iff
// op(inA, inB) differs between the two sides of the edge), then greedy
// leftmost-turn stitching. No sweep line, no transition flags, no shared
// code with the primary engine — clipping bugs cannot cancel out.
// ---------------------------------------------------------------------------

struct ClipEdge {
  double x0, y0, x1, y1;
};

static void collectEdges(const double* xy, const int64_t* ro, int64_t nr,
                         std::vector<ClipEdge>& es) {
  for (int64_t r = 0; r < nr; ++r) {
    int64_t lo = ro[r], hi = ro[r + 1], n = hi - lo;
    if (n < 3) continue;
    for (int64_t i = 0; i < n; ++i) {
      int64_t j = (i + 1) % n;
      ClipEdge e{xy[2 * (lo + i)], xy[2 * (lo + i) + 1], xy[2 * (lo + j)],
                 xy[2 * (lo + j) + 1]};
      if (e.x0 == e.x1 && e.y0 == e.y1) continue;
      es.push_back(e);
    }
  }
}

static double coordScale(const double* xy, int64_t nv, double s) {
  for (int64_t i = 0; i < 2 * nv; ++i) {
    double v = std::fabs(xy[i]);
    s = v > s ? v : s;
  }
  return s;
}

// record intersection parameters of one edge pair (proper crossings,
// touches, collinear overlaps) into the two edges' split lists
static void splitPair(const ClipEdge& A, const ClipEdge& B, double scale,
                      std::vector<double>& tA, std::vector<double>& tB) {
  const double pe = 1e-12;  // parameter epsilon
  double ax = A.x0, ay = A.y0;
  double d1x = A.x1 - ax, d1y = A.y1 - ay;
  double L1 = std::hypot(d1x, d1y);
  double bx = B.x0, by = B.y0;
  double d2x = B.x1 - bx, d2y = B.y1 - by;
  double L2 = std::hypot(d2x, d2y);
  double denom = d1x * d2y - d1y * d2x;
  double ex = bx - ax, ey = by - ay;
  if (std::fabs(denom) > 1e-12 * L1 * L2) {
    double t = (ex * d2y - ey * d2x) / denom;
    double s = (ex * d1y - ey * d1x) / denom;
    if (t > -pe && t < 1 + pe && s > -pe && s < 1 + pe) {
      tA.push_back(t < 0 ? 0 : (t > 1 ? 1 : t));
      tB.push_back(s < 0 ? 0 : (s > 1 ? 1 : s));
    }
    return;
  }
  // parallel: collinear overlap splits both edges at the other's ends
  if (std::fabs(ex * d1y - ey * d1x) > 1e-12 * scale * L1) return;
  double La = d1x * d1x + d1y * d1y, Lb = d2x * d2x + d2y * d2y;
  double u0 = (ex * d1x + ey * d1y) / La;
  double u1 = ((B.x1 - ax) * d1x + (B.y1 - ay) * d1y) / La;
  if (u0 > pe && u0 < 1 - pe) tA.push_back(u0);
  if (u1 > pe && u1 < 1 - pe) tA.push_back(u1);
  double v0 = (-ex * d2x - ey * d2y) / Lb;
  double v1 = ((A.x1 - bx) * d2x + (A.y1 - by) * d2y) / Lb;
  if (v0 > pe && v0 < 1 - pe) tB.push_back(v0);
  if (v1 > pe && v1 < 1 - pe) tB.push_back(v1);
}

static void splitParams(const std::vector<ClipEdge>& ea,
                        const std::vector<ClipEdge>& eb, double scale,
                        std::vector<std::vector<double>>& ta,
                        std::vector<std::vector<double>>& tb) {
  for (size_t i = 0; i < ea.size(); ++i)
    for (size_t j = 0; j < eb.size(); ++j)
      splitPair(ea[i], eb[j], scale, ta[i], tb[j]);
}

// self-subdivision: even-odd inputs may have contours crossing their own
// polygon's other contours (e.g. a shell passing through a hole); every
// edge must also split at those crossings or midpoint classification
// flips mid-subedge
static void splitSelf(const std::vector<ClipEdge>& es, double scale,
                      std::vector<std::vector<double>>& ts) {
  for (size_t i = 0; i < es.size(); ++i)
    for (size_t j = i + 1; j < es.size(); ++j)
      splitPair(es[i], es[j], scale, ts[i], ts[j]);
}

static void subdivide(const std::vector<ClipEdge>& es,
                      std::vector<std::vector<double>>& ts,
                      std::vector<ClipEdge>& out) {
  for (size_t i = 0; i < es.size(); ++i) {
    auto& t = ts[i];
    t.push_back(0.0);
    t.push_back(1.0);
    std::sort(t.begin(), t.end());
    double prev = t.front();
    for (size_t k = 1; k < t.size(); ++k) {
      double v = t[k];
      // split points closer than 1e-9 to prev merge into the NEXT
      // emitted subedge (prev must stay at the last emitted parameter —
      // advancing it through a cluster would silently drop that span of
      // boundary and break the stitched ring)
      if (v - prev > 1e-9) {
        out.push_back({es[i].x0 + prev * (es[i].x1 - es[i].x0),
                       es[i].y0 + prev * (es[i].y1 - es[i].y0),
                       es[i].x0 + v * (es[i].x1 - es[i].x0),
                       es[i].y0 + v * (es[i].y1 - es[i].y0)});
        prev = v;
      }
    }
  }
}

static inline bool opMember(int op, bool a, bool b) {
  switch (op) {
    case 0: return a && b;   // intersection
    case 1: return a || b;   // union
    case 2: return a && !b;  // difference
    default: return a != b;  // xor
  }
}

struct QKey {
  int64_t x, y;
  bool operator==(const QKey& o) const { return x == o.x && y == o.y; }
};
struct QKeyHash {
  size_t operator()(const QKey& k) const {
    // unsigned arithmetic: the multiply wraps by definition (a signed
    // int64 product here would overflow, which is UB)
    uint64_t h = (uint64_t)k.x * 0x9E3779B97F4A7C15ull ^ (uint64_t)k.y;
    return std::hash<uint64_t>()(h);
  }
};

static inline QKey quant(double x, double y, double q) {
  return {(int64_t)std::llround(x / q), (int64_t)std::llround(y / q)};
}

struct EdgeKeyHash {
  size_t operator()(const std::array<int64_t, 4>& k) const {
    size_t h = 1469598103934665603ull;
    for (int64_t v : k) {
      h ^= (size_t)v;
      h *= 1099511628211ull;
    }
    return h;
  }
};

// selected, oriented subedges -> closed contours (leftmost-turn walk)
static void stitch(std::vector<ClipEdge>& kept, double q,
                   std::vector<std::vector<double>>& rings) {
  std::unordered_map<QKey, std::vector<size_t>, QKeyHash> at;
  for (size_t i = 0; i < kept.size(); ++i)
    at[quant(kept[i].x0, kept[i].y0, q)].push_back(i);
  std::vector<char> used(kept.size(), 0);
  for (size_t s = 0; s < kept.size(); ++s) {
    if (used[s]) continue;
    std::vector<double> ring;
    QKey startKey = quant(kept[s].x0, kept[s].y0, q);
    size_t cur = s;
    used[s] = 1;
    ring.push_back(kept[s].x0);
    ring.push_back(kept[s].y0);
    bool closed = false;
    for (size_t guard = 0; guard <= kept.size(); ++guard) {
      QKey end = quant(kept[cur].x1, kept[cur].y1, q);
      if (end == startKey) {
        closed = true;
        break;
      }
      // candidates at the end point (search the 3x3 quant neighborhood:
      // intersection points computed from the A- and B-side parameters
      // can straddle a lattice boundary)
      double dix = kept[cur].x1 - kept[cur].x0;
      double diy = kept[cur].y1 - kept[cur].y0;
      size_t best = SIZE_MAX;
      double bestAng = -1e18;
      for (int64_t ddx = -1; ddx <= 1; ++ddx)
        for (int64_t ddy = -1; ddy <= 1; ++ddy) {
          auto it = at.find({end.x + ddx, end.y + ddy});
          if (it == at.end()) continue;
          for (size_t c : it->second) {
            if (used[c]) continue;
            double dcx = kept[c].x1 - kept[c].x0;
            double dcy = kept[c].y1 - kept[c].y0;
            // leftmost turn keeps the tightest member-on-left region
            double ang =
                std::atan2(dix * dcy - diy * dcx, dix * dcx + diy * dcy);
            if (ang > bestAng) {
              bestAng = ang;
              best = c;
            }
          }
        }
      if (best == SIZE_MAX) break;  // open chain: numerical orphan, drop
      used[best] = 1;
      ring.push_back(kept[best].x0);
      ring.push_back(kept[best].y0);
      cur = best;
    }
    if (closed && ring.size() >= 6) rings.push_back(std::move(ring));
  }
}

}  // namespace mgeval

extern "C" {

// area (holes negative), perimeter, and area-weighted centroid of one
// polygonal geometry. out = {area, perimeter, cx, cy}. rc 0 = ok.
int mg_eval_polygon(const double* xy, const int64_t* ro, int64_t nr,
                    const uint8_t* is_hole, double* out) {
  using mgeval::Kahan;
  Kahan area2, perim, cx6, cy6;
  for (int64_t r = 0; r < nr; ++r) {
    int64_t lo = ro[r], hi = ro[r + 1], n = hi - lo;
    if (n < 3) continue;
    double a2 = mgeval::contourArea2(xy, lo, hi);
    // normalize to positive, then sign by the hole flag — independent of
    // stored ring orientation
    double sgn = (is_hole && is_hole[r]) ? -1.0 : 1.0;
    double orient = a2 >= 0 ? 1.0 : -1.0;
    area2.add(sgn * orient * a2);
    Kahan mx, my;
    for (int64_t i = 0; i < n; ++i) {
      int64_t j = (i + 1) % n;
      double x0 = xy[2 * (lo + i)], y0 = xy[2 * (lo + i) + 1];
      double x1 = xy[2 * (lo + j)], y1 = xy[2 * (lo + j) + 1];
      double cross = x0 * y1 - x1 * y0;
      mx.add((x0 + x1) * cross);
      my.add((y0 + y1) * cross);
      perim.add(std::hypot(x1 - x0, y1 - y0));
    }
    cx6.add(sgn * orient * mx.s);
    cy6.add(sgn * orient * my.s);
  }
  double area = 0.5 * area2.s;
  out[0] = area;
  out[1] = perim.s;
  if (area != 0) {
    out[2] = cx6.s / (6.0 * area);
    out[3] = cy6.s / (6.0 * area);
  } else {
    out[2] = out[3] = NAN;
  }
  return 0;
}

// total polyline length of open chains
int mg_eval_length(const double* xy, const int64_t* ro, int64_t nr,
                   double* out) {
  mgeval::Kahan k;
  for (int64_t r = 0; r < nr; ++r) {
    for (int64_t i = ro[r]; i + 1 < ro[r + 1]; ++i)
      k.add(std::hypot(xy[2 * (i + 1)] - xy[2 * i],
                       xy[2 * (i + 1) + 1] - xy[2 * i + 1]));
  }
  *out = k.s;
  return 0;
}

int mg_eval_bounds(const double* xy, int64_t nv, double* out) {
  double xmin = INFINITY, ymin = INFINITY, xmax = -INFINITY, ymax = -INFINITY;
  for (int64_t i = 0; i < nv; ++i) {
    double x = xy[2 * i], y = xy[2 * i + 1];
    xmin = x < xmin ? x : xmin;
    xmax = x > xmax ? x : xmax;
    ymin = y < ymin ? y : ymin;
    ymax = y > ymax ? y : ymax;
  }
  out[0] = xmin;
  out[1] = ymin;
  out[2] = xmax;
  out[3] = ymax;
  return 0;
}

// even-odd point-in-polygon for npts points; out[i] in {0, 1}
int mg_eval_contains(const double* xy, const int64_t* ro, int64_t nr,
                     const double* pts, int64_t npts, uint8_t* out) {
  for (int64_t i = 0; i < npts; ++i)
    out[i] = mgeval::evenOddInside(xy, ro, nr, pts[2 * i], pts[2 * i + 1])
                 ? 1
                 : 0;
  return 0;
}

// point -> polygon distance: 0 inside, else min distance to any edge
int mg_eval_distance(const double* xy, const int64_t* ro, int64_t nr,
                     const double* pts, int64_t npts, double* out) {
  for (int64_t i = 0; i < npts; ++i) {
    double px = pts[2 * i], py = pts[2 * i + 1];
    if (mgeval::evenOddInside(xy, ro, nr, px, py)) {
      out[i] = 0.0;
      continue;
    }
    double d2 = INFINITY;
    for (int64_t r = 0; r < nr; ++r) {
      int64_t lo = ro[r], hi = ro[r + 1], n = hi - lo;
      for (int64_t k = 0; k < n; ++k) {
        int64_t j = (k + 1) % n;
        double v = mgeval::segDist2(px, py, xy[2 * (lo + k)],
                                    xy[2 * (lo + k) + 1], xy[2 * (lo + j)],
                                    xy[2 * (lo + j) + 1]);
        d2 = v < d2 ? v : d2;
      }
    }
    out[i] = std::isfinite(d2) ? std::sqrt(d2) : NAN;
  }
  return 0;
}

// Single-thread reference-shaped PIP join — the bench's honest baseline
// lane (the closest runnable analog of the reference's JTS codegen row
// path, MosaicGeometryJTS.scala:101): binary-search the point's cell in
// the sorted index, then evaluate the cell's chips exactly the way the
// reference's generated row code does: `is_core || contains(chip, pt)`
// on the clipped chip polygon.
//
// Chips are CSR rings: chip c owns rings [cro[c], cro[c+1]) of (xy, ro);
// cell u's chip rows live in cell_rows[u*max_chips ..], -1 padded
// (trailing). Output: smallest matching geom id, -1 if none.
int mg_eval_pip_join(const double* xy, const int64_t* ro,
                     const int64_t* cro, int64_t nchips,
                     const uint8_t* chip_core, const int32_t* chip_geom,
                     const int64_t* cells, int64_t ncells,
                     const int32_t* cell_rows, int64_t max_chips,
                     const double* pts, const int64_t* pcells, int64_t npts,
                     int32_t* out) {
  (void)nchips;
  for (int64_t i = 0; i < npts; ++i) {
    int64_t c = pcells[i];
    int64_t lo = 0, hi = ncells;
    while (lo < hi) {
      int64_t mid = (lo + hi) >> 1;
      if (cells[mid] < c)
        lo = mid + 1;
      else
        hi = mid;
    }
    int32_t best = INT32_MAX;
    if (lo < ncells && cells[lo] == c) {
      const int32_t* rows = cell_rows + lo * max_chips;
      double px = pts[2 * i], py = pts[2 * i + 1];
      for (int64_t m = 0; m < max_chips; ++m) {
        int32_t chip = rows[m];
        if (chip < 0) break;
        int32_t g = chip_geom[chip];
        if (g >= best) continue;
        if (chip_core[chip]) {
          best = g;
          continue;
        }
        int64_t r0 = cro[chip], r1 = cro[chip + 1];
        if (r1 > r0 && mgeval::evenOddInside(xy, ro + r0, r1 - r0, px, py))
          best = g;
      }
    }
    out[i] = best == INT32_MAX ? -1 : best;
  }
  return 0;
}

// Independent polygon boolean op (see the block comment above): same ABI
// and output convention as capi.cpp's mg_bool_op (flat contours, malloc'd,
// released via mg_free_result); ops 0=inter 1=union 2=diff 3=xor.
int mg_eval_clip(int op, const double* axy, const int64_t* aro, int64_t anr,
                 const double* bxy, const int64_t* bro, int64_t bnr,
                 double** out_xy, int64_t** out_ro, int64_t* out_nv,
                 int64_t* out_nr) {
  using namespace mgeval;
  std::vector<ClipEdge> ea, eb;
  collectEdges(axy, aro, anr, ea);
  collectEdges(bxy, bro, bnr, eb);
  double scale = coordScale(axy, anr ? aro[anr] : 0, 1.0);
  scale = coordScale(bxy, bnr ? bro[bnr] : 0, scale);
  const double off = 1e-9 * scale;  // classification offset + quant grid

  std::vector<std::vector<double>> ta(ea.size()), tb(eb.size());
  splitParams(ea, eb, scale, ta, tb);
  splitSelf(ea, scale, ta);
  splitSelf(eb, scale, tb);
  std::vector<ClipEdge> subs;
  subdivide(ea, ta, subs);
  subdivide(eb, tb, subs);

  // keep a subedge iff result-membership differs across it; orient the
  // member side to the LEFT; dedup shared (collinear) copies
  std::vector<ClipEdge> kept;
  std::unordered_set<std::array<int64_t, 4>, EdgeKeyHash> seen;
  for (const ClipEdge& e : subs) {
    double mx = 0.5 * (e.x0 + e.x1), my = 0.5 * (e.y0 + e.y1);
    double dx = e.x1 - e.x0, dy = e.y1 - e.y0;
    double L = std::hypot(dx, dy);
    double nx = -dy / L * off, ny = dx / L * off;  // left normal
    bool inAl = evenOddInside(axy, aro, anr, mx + nx, my + ny);
    bool inBl = evenOddInside(bxy, bro, bnr, mx + nx, my + ny);
    bool inAr = evenOddInside(axy, aro, anr, mx - nx, my - ny);
    bool inBr = evenOddInside(bxy, bro, bnr, mx - nx, my - ny);
    bool ml = opMember(op, inAl, inBl), mr = opMember(op, inAr, inBr);
    if (ml == mr) continue;
    ClipEdge k = ml ? e : ClipEdge{e.x1, e.y1, e.x0, e.y0};
    QKey q0 = quant(k.x0, k.y0, off), q1 = quant(k.x1, k.y1, off);
    if (!seen.insert({q0.x, q0.y, q1.x, q1.y}).second) continue;
    kept.push_back(k);
  }

  std::vector<std::vector<double>> rings;
  stitch(kept, off, rings);

  int64_t nv = 0;
  for (auto& r : rings) nv += (int64_t)r.size() / 2;
  int64_t nr = (int64_t)rings.size();
  *out_nv = nv;
  *out_nr = nr;
  if (!nr) {
    *out_xy = nullptr;
    *out_ro = nullptr;
    return 0;
  }
  *out_xy = (double*)std::malloc(sizeof(double) * 2 * nv);
  *out_ro = (int64_t*)std::malloc(sizeof(int64_t) * (nr + 1));
  if (!*out_xy || !*out_ro) {
    std::free(*out_xy);
    std::free(*out_ro);
    *out_xy = nullptr;
    *out_ro = nullptr;
    return 1;
  }
  int64_t o = 0;
  (*out_ro)[0] = 0;
  for (int64_t r = 0; r < nr; ++r) {
    std::memcpy(*out_xy + 2 * o, rings[r].data(),
                sizeof(double) * rings[r].size());
    o += (int64_t)rings[r].size() / 2;
    (*out_ro)[r + 1] = o;
  }
  return 0;
}

}  // extern "C"
