// Native GeoTIFF reader: classic TIFF -> band-sequential arrays + geo tags.
//
// This is the TPU build's replacement for the reference's GDAL JNI raster
// ingest (`core/raster/MosaicRasterGDAL.scala:17-254`,
// `gdal/MosaicGDAL.scala:82-90` shared-object bootstrap): a small, dependency-
// light C++ decoder (zlib only) that feeds pixels straight into packed host
// buffers for device upload. Supported: classic little/big-endian TIFF,
// strips + tiles, PlanarConfig chunky/planar, compression none/deflate/
// LZW/PackBits, horizontal-differencing predictor, u8..f64 samples, and the
// GeoTIFF georeferencing tags (ModelPixelScale+Tiepoint, ModelTransformation,
// GeoKeyDirectory EPSG) plus GDAL's NODATA and metadata-XML tags.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <zlib.h>

namespace mtiff {

struct Reader {
  const uint8_t* d;
  size_t n;
  bool le;
  uint16_t u16(size_t o) const {
    if (o + 2 > n) return 0;
    return le ? (uint16_t)(d[o] | d[o + 1] << 8)
              : (uint16_t)(d[o] << 8 | d[o + 1]);
  }
  uint32_t u32(size_t o) const {
    if (o + 4 > n) return 0;
    return le ? ((uint32_t)d[o] | (uint32_t)d[o + 1] << 8 |
                 (uint32_t)d[o + 2] << 16 | (uint32_t)d[o + 3] << 24)
              : ((uint32_t)d[o] << 24 | (uint32_t)d[o + 1] << 16 |
                 (uint32_t)d[o + 2] << 8 | (uint32_t)d[o + 3]);
  }
  double f64(size_t o) const {
    uint8_t b[8];
    if (o + 8 > n) return 0;
    if (le)
      memcpy(b, d + o, 8);
    else
      for (int i = 0; i < 8; ++i) b[i] = d[o + 7 - i];
    double v;
    memcpy(&v, b, 8);
    return v;
  }
};

struct Entry {
  uint16_t tag, type;
  uint32_t count;
  size_t value_off;  // offset of the value bytes (inline or pointed-to)
};

static size_t typeSize(uint16_t t) {
  switch (t) {
    case 1: case 2: case 6: case 7: return 1;   // byte/ascii/sbyte/undef
    case 3: case 8: return 2;                   // short/sshort
    case 4: case 9: case 11: return 4;          // long/slong/float
    case 5: case 10: case 12: return 8;         // rational/srational/double
    default: return 1;
  }
}

struct IFD {
  std::vector<Entry> entries;
  const Entry* find(uint16_t tag) const {
    for (auto& e : entries)
      if (e.tag == tag) return &e;
    return nullptr;
  }
};

static bool parseIFD(const Reader& r, size_t off, IFD& out, size_t* next) {
  if (off + 2 > r.n) return false;
  uint16_t n = r.u16(off);
  size_t p = off + 2;
  if (p + 12 * (size_t)n + 4 > r.n) return false;
  for (uint16_t i = 0; i < n; ++i, p += 12) {
    Entry e;
    e.tag = r.u16(p);
    e.type = r.u16(p + 2);
    e.count = r.u32(p + 4);
    size_t bytes = typeSize(e.type) * (size_t)e.count;
    e.value_off = bytes <= 4 ? p + 8 : (size_t)r.u32(p + 8);
    // a truncated IFD (value bytes past EOF) must fail the parse rather
    // than silently decode zeros through the bounds-checked Reader
    if (e.value_off + bytes > r.n) return false;
    out.entries.push_back(e);
  }
  *next = r.u32(p);
  return true;
}

static uint32_t scalar(const Reader& r, const Entry* e, uint32_t dflt) {
  if (!e || e->count < 1) return dflt;
  if (e->type == 3) return r.u16(e->value_off);
  if (e->type == 4) return r.u32(e->value_off);
  return dflt;
}

static std::vector<uint64_t> longs(const Reader& r, const Entry* e) {
  std::vector<uint64_t> v;
  if (!e) return v;
  size_t ts = typeSize(e->type);
  for (uint32_t i = 0; i < e->count; ++i) {
    size_t o = e->value_off + ts * i;
    v.push_back(e->type == 3 ? r.u16(o) : r.u32(o));
  }
  return v;
}

static std::vector<double> doubles(const Reader& r, const Entry* e) {
  std::vector<double> v;
  if (!e) return v;
  for (uint32_t i = 0; i < e->count; ++i)
    v.push_back(r.f64(e->value_off + 8 * i));
  return v;
}

static std::string ascii(const Reader& r, const Entry* e) {
  if (!e) return "";
  size_t o = e->value_off, c = e->count;
  if (o + c > r.n) return "";
  std::string s((const char*)r.d + o, c);
  while (!s.empty() && s.back() == '\0') s.pop_back();
  return s;
}

// ----------------------------------------------------------- decompressors

static bool inflateBuf(const uint8_t* src, size_t sn, uint8_t* dst,
                       size_t dn) {
  uLongf outn = dn;
  return uncompress(dst, &outn, src, sn) == Z_OK;
}

static bool packbits(const uint8_t* src, size_t sn, uint8_t* dst, size_t dn) {
  size_t i = 0, o = 0;
  while (i < sn && o < dn) {
    int8_t h = (int8_t)src[i++];
    if (h >= 0) {
      size_t cnt = (size_t)h + 1;
      if (i + cnt > sn || o + cnt > dn) return false;
      memcpy(dst + o, src + i, cnt);
      i += cnt;
      o += cnt;
    } else if (h != -128) {
      size_t cnt = (size_t)(-h) + 1;
      if (i >= sn || o + cnt > dn) return false;
      memset(dst + o, src[i++], cnt);
      o += cnt;
    }
  }
  return o == dn;
}

// TIFF LZW (MSB-first codes, early change)
static bool lzw(const uint8_t* src, size_t sn, uint8_t* dst, size_t dn) {
  struct Str { int prev; uint8_t ch; };
  std::vector<Str> table(4096);
  std::vector<uint8_t> buf;
  auto emit = [&](int code, size_t& o) -> bool {
    buf.clear();
    while (code >= 0) {
      buf.push_back(table[code].ch);
      code = table[code].prev;
    }
    for (size_t k = buf.size(); k-- > 0;) {
      if (o >= dn) return false;
      dst[o++] = buf[k];
    }
    return true;
  };
  auto firstChar = [&](int code) -> uint8_t {
    while (table[code].prev >= 0) code = table[code].prev;
    return table[code].ch;
  };
  for (int i = 0; i < 256; ++i) table[i] = {-1, (uint8_t)i};
  int next = 258, bits = 9, old = -1;
  size_t o = 0;
  uint32_t acc = 0;
  int nbits = 0;
  size_t i = 0;
  while (true) {
    while (nbits < bits && i < sn) {
      acc = (acc << 8) | src[i++];
      nbits += 8;
    }
    if (nbits < bits) break;
    int code = (int)((acc >> (nbits - bits)) & ((1u << bits) - 1));
    nbits -= bits;
    if (code == 257) break;  // EOI
    if (code == 256) {       // clear
      next = 258;
      bits = 9;
      old = -1;
      continue;
    }
    if (old < 0) {
      if (code >= 256 || !emit(code, o)) return false;
      old = code;
      continue;
    }
    if (code < next) {
      if (!emit(code, o)) return false;
      if (next < 4096) table[next++] = {old, firstChar(code)};
    } else if (code == next) {
      if (next < 4096) table[next++] = {old, firstChar(old)};
      if (!emit(next - 1, o)) return false;
    } else {
      return false;
    }
    if (next == (1 << bits) - 1 && bits < 12) ++bits;  // early change
    old = code;
  }
  return o == dn;
}

// ------------------------------------------------------------ main decode

struct Info {
  int64_t width = 0, height = 0, bands = 1;
  int32_t dtype = 0;  // 1 u8, 2 u16, 3 u32, 4 i8, 5 i16, 6 i32, 7 f32, 8 f64
  double gt[6] = {0, 1, 0, 0, 0, 1};
  int32_t epsg = 0;
  double nodata = 0;
  int32_t has_nodata = 0;
  int32_t pages = 1;
  std::string meta;
};

static int32_t dtypeCode(uint16_t bits, uint16_t fmt) {
  if (fmt == 3) return bits == 64 ? 8 : 7;  // float
  if (fmt == 2) return bits == 8 ? 4 : bits == 16 ? 5 : 6;  // signed
  return bits == 8 ? 1 : bits == 16 ? 2 : 3;  // unsigned (fmt 1/4)
}

static size_t dtypeBytes(int32_t c) {
  switch (c) {
    case 1: case 4: return 1;
    case 2: case 5: return 2;
    case 8: return 8;
    default: return 4;
  }
}

// byte-swap + predictor fixup applied per decoded chunk row
static void fixRow(uint8_t* row, size_t npix, size_t spp, size_t bytes,
                   bool le, uint16_t predictor, int32_t dtype) {
  if (!le && bytes > 1) {
    for (size_t i = 0; i < npix * spp; ++i) {
      uint8_t* p = row + i * bytes;
      for (size_t a = 0, b = bytes - 1; a < b; ++a, --b) std::swap(p[a], p[b]);
    }
  }
  if (predictor == 2) {
    // horizontal differencing on integer samples
    if (bytes == 1) {
      for (size_t i = spp; i < npix * spp; ++i) row[i] = (uint8_t)(row[i] + row[i - spp]);
    } else if (bytes == 2) {
      uint16_t* r = (uint16_t*)row;
      for (size_t i = spp; i < npix * spp; ++i) r[i] = (uint16_t)(r[i] + r[i - spp]);
    } else if (bytes == 4 && (dtype == 3 || dtype == 6)) {
      uint32_t* r = (uint32_t*)row;
      for (size_t i = spp; i < npix * spp; ++i) r[i] += r[i - spp];
    }
  }
}

static bool decodeChunk(const Reader& r, size_t off, size_t clen,
                        uint16_t comp, uint8_t* dst, size_t rawn) {
  if (off + clen > r.n) return false;
  const uint8_t* src = r.d + off;
  switch (comp) {
    case 1:
      if (clen < rawn) return false;
      memcpy(dst, src, rawn);
      return true;
    case 5:
      return lzw(src, clen, dst, rawn);
    case 8:
    case 32946:
      return inflateBuf(src, clen, dst, rawn);
    case 32773:
      return packbits(src, clen, dst, rawn);
    default:
      return false;
  }
}

static int readTiff(const uint8_t* data, size_t n, Info& info,
                    uint8_t** out_pixels) {
  Reader r{data, n, true};
  if (n < 8) return -2;
  if (data[0] == 'I' && data[1] == 'I')
    r.le = true;
  else if (data[0] == 'M' && data[1] == 'M')
    r.le = false;
  else
    return -2;
  if (r.u16(2) != 42) return -3;  // BigTIFF (43) unsupported for now
  size_t off = r.u32(4), next = 0;
  IFD ifd;
  if (!parseIFD(r, off, ifd, &next)) return -4;
  // count pages (overviews/subdatasets in multi-IFD files)
  info.pages = 1;
  {
    size_t nx = next;
    int guard = 0;
    while (nx && guard++ < 64) {
      IFD tmp;
      size_t nn = 0;
      if (!parseIFD(r, nx, tmp, &nn)) break;
      info.pages++;
      nx = nn;
    }
  }

  info.width = scalar(r, ifd.find(256), 0);
  info.height = scalar(r, ifd.find(257), 0);
  if (info.width <= 0 || info.height <= 0) return -5;
  uint16_t spp = (uint16_t)scalar(r, ifd.find(277), 1);
  info.bands = spp;
  uint16_t bits = 8;
  if (const Entry* e = ifd.find(258)) bits = (uint16_t)r.u16(e->value_off);
  uint16_t fmt = 1;
  if (const Entry* e = ifd.find(339)) fmt = (uint16_t)r.u16(e->value_off);
  info.dtype = dtypeCode(bits, fmt);
  size_t bysz = dtypeBytes(info.dtype);
  if (bysz * 8 != bits && !(bits == 32 && bysz == 4)) {
    if (bits != 8 * bysz) return -6;  // odd bit depths unsupported
  }
  uint16_t comp = (uint16_t)scalar(r, ifd.find(259), 1);
  uint16_t planar = (uint16_t)scalar(r, ifd.find(284), 1);
  uint16_t predictor = (uint16_t)scalar(r, ifd.find(317), 1);
  if (predictor > 2) return -12;  // float predictor 3 unsupported: refuse
                                  // rather than return shuffled garbage

  // georeference
  auto scale = doubles(r, ifd.find(33550));
  auto tie = doubles(r, ifd.find(33922));
  auto xform = doubles(r, ifd.find(34264));
  if (xform.size() >= 8) {
    info.gt[1] = xform[0]; info.gt[2] = xform[1]; info.gt[0] = xform[3];
    info.gt[4] = xform[4]; info.gt[5] = xform[5]; info.gt[3] = xform[7];
  } else if (scale.size() >= 2 && tie.size() >= 6) {
    info.gt[1] = scale[0];
    info.gt[5] = -scale[1];
    info.gt[2] = info.gt[4] = 0;
    info.gt[0] = tie[3] - tie[0] * scale[0];
    info.gt[3] = tie[4] + tie[1] * scale[1];
  }
  // GeoKeyDirectory: short keys; 3072 projected EPSG, 2048 geographic
  if (const Entry* e = ifd.find(34735)) {
    auto keys = longs(r, e);
    for (size_t i = 4; i + 3 < keys.size(); i += 4) {
      uint64_t key = keys[i], loc = keys[i + 1], val = keys[i + 3];
      if ((key == 3072 || key == 2048) && loc == 0) {
        if (key == 3072 || info.epsg == 0) info.epsg = (int32_t)val;
      }
    }
  }
  std::string nod = ascii(r, ifd.find(42113));
  if (!nod.empty()) {
    info.nodata = atof(nod.c_str());
    info.has_nodata = 1;
  }
  info.meta = ascii(r, ifd.find(42112));

  // chunk geometry
  bool tiled = ifd.find(322) != nullptr;
  int64_t cw, ch;
  std::vector<uint64_t> offs, cnts;
  if (tiled) {
    cw = scalar(r, ifd.find(322), 0);
    ch = scalar(r, ifd.find(323), 0);
    offs = longs(r, ifd.find(324));
    cnts = longs(r, ifd.find(325));
  } else {
    cw = info.width;
    ch = scalar(r, ifd.find(278), 0xFFFFFFFF);
    if (ch > info.height) ch = info.height;
    offs = longs(r, ifd.find(273));
    cnts = longs(r, ifd.find(279));
  }
  if (cw <= 0 || ch <= 0 || offs.empty() || offs.size() != cnts.size())
    return -7;

  int64_t across = (info.width + cw - 1) / cw;
  int64_t down = (info.height + ch - 1) / ch;
  size_t chunkSpp = planar == 2 ? 1 : spp;
  size_t planeChunks = (size_t)(across * down);
  size_t needed = planar == 2 ? planeChunks * spp : planeChunks;
  if (offs.size() < needed) return -8;

  size_t total = (size_t)info.bands * info.width * info.height * bysz;
  uint8_t* out = (uint8_t*)malloc(std::max<size_t>(total, 1));
  if (!out) return -1;
  std::vector<uint8_t> chunk((size_t)cw * (size_t)ch * chunkSpp * bysz);

  for (size_t c = 0; c < needed; ++c) {
    size_t plane = planar == 2 ? c / planeChunks : 0;
    size_t ci = planar == 2 ? c % planeChunks : c;
    int64_t ty = (int64_t)(ci / across), tx = (int64_t)(ci % across);
    int64_t x0 = tx * cw, y0 = ty * ch;
    int64_t copyw = std::min(cw, info.width - x0);
    int64_t copyh = std::min(ch, info.height - y0);
    // tiles are padded to full size on disk; the FINAL strip of a striped
    // file is short (only the remaining rows are stored)
    int64_t rows = tiled ? ch : copyh;
    size_t rawn = (size_t)cw * (size_t)rows * chunkSpp * bysz;
    if (!decodeChunk(r, (size_t)offs[c], (size_t)cnts[c], comp, chunk.data(),
                     rawn)) {
      free(out);
      return -9;
    }
    // per-row fixups
    for (int64_t y = 0; y < rows; ++y)
      fixRow(chunk.data() + (size_t)y * cw * chunkSpp * bysz, (size_t)cw,
             chunkSpp, bysz, r.le, predictor, info.dtype);
    for (int64_t y = 0; y < copyh; ++y) {
      const uint8_t* srow = chunk.data() + (size_t)y * cw * chunkSpp * bysz;
      if (planar == 2 || spp == 1) {
        uint8_t* drow = out + ((plane * info.height + (y0 + y)) * info.width +
                               x0) * bysz;
        memcpy(drow, srow, (size_t)copyw * bysz);
      } else {
        // chunky -> band-sequential deinterleave
        for (int64_t x = 0; x < copyw; ++x)
          for (size_t s = 0; s < spp; ++s) {
            uint8_t* dpx = out + (((size_t)s * info.height + (y0 + y)) *
                                      info.width + (x0 + x)) * bysz;
            memcpy(dpx, srow + ((size_t)x * spp + s) * bysz, bysz);
          }
      }
    }
  }
  *out_pixels = out;
  return 0;
}

}  // namespace mtiff

extern "C" {

// Reads path; fills info arrays and returns 0 on success.
// iinfo: [width, height, bands, dtype, has_nodata, pages, meta_len]
// dinfo: [gt0..gt5, nodata, epsg]
// pixels: malloc'd band-sequential raster (free with mg_tiff_free)
// meta: malloc'd GDAL metadata XML (may be NULL)
int mg_tiff_read(const char* path, int64_t* iinfo, double* dinfo,
                 uint8_t** pixels, char** meta) {
  FILE* f = fopen(path, "rb");
  if (!f) return -10;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf((size_t)std::max(sz, 0L));
  if (sz > 0 && fread(buf.data(), 1, (size_t)sz, f) != (size_t)sz) {
    fclose(f);
    return -11;
  }
  fclose(f);
  mtiff::Info info;
  uint8_t* px = nullptr;
  int rc = mtiff::readTiff(buf.data(), buf.size(), info, &px);
  if (rc != 0) return rc;
  iinfo[0] = info.width;
  iinfo[1] = info.height;
  iinfo[2] = info.bands;
  iinfo[3] = info.dtype;
  iinfo[4] = info.has_nodata;
  iinfo[5] = info.pages;
  iinfo[6] = (int64_t)info.meta.size();
  for (int i = 0; i < 6; ++i) dinfo[i] = info.gt[i];
  dinfo[6] = info.nodata;
  dinfo[7] = (double)info.epsg;
  *pixels = px;
  if (meta) {
    if (!info.meta.empty()) {
      *meta = (char*)malloc(info.meta.size() + 1);
      memcpy(*meta, info.meta.c_str(), info.meta.size() + 1);
    } else {
      *meta = nullptr;
    }
  }
  return 0;
}

void mg_tiff_free(void* p) { free(p); }

}  // extern "C"
