// Martinez–Rueda–Feito sweep-line boolean operations on polygons.
//
// Role in the framework: the host-side exact-geometry engine. The reference
// delegates intersection/union/difference to JTS
// (core/geometry/MosaicGeometryJTS.scala:61-101); here the same capability is
// a from-scratch C++ implementation of the Martinez 2009 algorithm
// ("A new algorithm for computing Boolean operations on polygons"), the
// standard sweep approach: subdivide segments at intersections while
// annotating each with in/out transition flags for both operands, select the
// result edges per operation, then stitch them into closed contours.
//
// Input/output are flat contour lists (rings); shell/hole nesting is decided
// by the caller (even-odd containment), which keeps this file free of any
// polygon-with-holes bookkeeping.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <deque>
#include <queue>
#include <set>
#include <vector>

namespace mg {

struct Pt {
  double x, y;
  bool operator==(const Pt& o) const { return x == o.x && y == o.y; }
};

static inline double signedArea(const Pt& p0, const Pt& p1, const Pt& p2) {
  return (p0.x - p2.x) * (p1.y - p2.y) - (p1.x - p2.x) * (p0.y - p2.y);
}

enum BoolOp { OP_INTERSECTION = 0, OP_UNION = 1, OP_DIFFERENCE = 2, OP_XOR = 3 };
enum EdgeType { NORMAL, NON_CONTRIBUTING, SAME_TRANSITION, DIFFERENT_TRANSITION };

struct SweepEvent {
  Pt p;
  bool left = false;
  SweepEvent* other = nullptr;
  bool isSubject = false;
  EdgeType type = NORMAL;
  bool inOut = false;       // in-out transition for this event's own polygon
  bool otherInOut = false;  // ditto w.r.t. the other polygon
  SweepEvent* prevInResult = nullptr;
  bool inResult = false;
  int pos = 0;          // index into resultEvents during contour stitching
  int64_t id = 0;       // creation order; strict-weak-order tiebreak
  int contourId = 0;    // input contour (collinear tiebreak)

  bool isBelow(const Pt& q) const {
    return left ? signedArea(p, other->p, q) > 0
                : signedArea(other->p, p, q) > 0;
  }
  bool isAbove(const Pt& q) const { return !isBelow(q); }
  bool isVertical() const { return p.x == other->p.x; }
};

// Priority order for the event queue (and final result ordering): left-to-
// right, bottom-to-top, right endpoints before left, lower segment first.
static int compareEvents(const SweepEvent* e1, const SweepEvent* e2) {
  if (e1->p.x > e2->p.x) return 1;
  if (e1->p.x < e2->p.x) return -1;
  if (e1->p.y != e2->p.y) return e1->p.y > e2->p.y ? 1 : -1;
  if (e1->left != e2->left) return e1->left ? 1 : -1;
  if (signedArea(e1->p, e1->other->p, e2->other->p) != 0.0)
    return !e1->isBelow(e2->other->p) ? 1 : -1;
  return (!e1->isSubject && e2->isSubject) ? 1 : -1;
}

struct QueueCmp {
  // std::priority_queue is a max-heap: "less" = lower priority = later.
  bool operator()(const SweepEvent* a, const SweepEvent* b) const {
    int c = compareEvents(a, b);
    if (c != 0) return c > 0;
    return a->id > b->id;
  }
};

// Status-line (sweep-line) vertical order of segments.
struct SegmentCmp {
  bool operator()(const SweepEvent* le1, const SweepEvent* le2) const {
    if (le1 == le2) return false;
    if (signedArea(le1->p, le1->other->p, le2->p) != 0.0 ||
        signedArea(le1->p, le1->other->p, le2->other->p) != 0.0) {
      // not collinear
      if (le1->p == le2->p) return le1->isBelow(le2->other->p);
      if (le1->p.x == le2->p.x) return le1->p.y < le2->p.y;
      if (compareEvents(le1, le2) == 1) return le2->isAbove(le1->p);
      return le1->isBelow(le2->p);
    }
    // collinear segments
    if (le1->isSubject == le2->isSubject) {
      if (le1->p == le2->p) {
        if (le1->other->p == le2->other->p) return le1->id < le2->id;
        return le1->contourId < le2->contourId;
      }
    } else {
      return le1->isSubject;
    }
    return compareEvents(le1, le2) == -1;
  }
};

struct Sweeper {
  std::deque<SweepEvent> pool;  // stable addresses
  std::priority_queue<SweepEvent*, std::vector<SweepEvent*>, QueueCmp> queue;
  std::vector<SweepEvent*> sorted;
  int64_t nextId = 0;

  SweepEvent* make(const Pt& p, bool left, bool isSubject, int contourId) {
    pool.push_back(SweepEvent{});
    SweepEvent* e = &pool.back();
    e->p = p;
    e->left = left;
    e->isSubject = isSubject;
    e->id = nextId++;
    e->contourId = contourId;
    return e;
  }

  void addSegment(const Pt& a, const Pt& b, bool isSubject, int contourId) {
    if (a == b) return;  // zero-length edges contribute nothing
    SweepEvent* e1 = make(a, true, isSubject, contourId);
    SweepEvent* e2 = make(b, true, isSubject, contourId);
    e1->other = e2;
    e2->other = e1;
    if (compareEvents(e1, e2) < 0) e2->left = false;
    else e1->left = false;
    queue.push(e1);
    queue.push(e2);
  }

  void divideSegment(SweepEvent* le, const Pt& p) {
    SweepEvent* r = make(p, false, le->isSubject, le->contourId);
    SweepEvent* l = make(p, true, le->isSubject, le->contourId);
    r->other = le;
    l->other = le->other;
    if (compareEvents(l, le->other) > 0) {  // rounding produced a flip
      le->other->left = true;
      l->left = false;
    }
    le->other->other = l;
    le->other = r;
    queue.push(l);
    queue.push(r);
  }
};

// Segment intersection: returns number of intersection points (0, 1, or 2
// for collinear overlap), writing them to i0/i1.
static int findIntersection(const Pt& a0, const Pt& a1, const Pt& b0,
                            const Pt& b1, Pt& i0, Pt& i1) {
  double vax = a1.x - a0.x, vay = a1.y - a0.y;
  double vbx = b1.x - b0.x, vby = b1.y - b0.y;
  double ex = b0.x - a0.x, ey = b0.y - a0.y;
  double kross = vax * vby - vay * vbx;
  double sqrKross = kross * kross;
  double sqrLenA = vax * vax + vay * vay;
  double sqrLenB = vbx * vbx + vby * vby;
  const double sqrEps = 1e-24;
  if (sqrKross > sqrEps * sqrLenA * sqrLenB) {
    double s = (ex * vby - ey * vbx) / kross;
    if (s < 0 || s > 1) return 0;
    double t = (ex * vay - ey * vax) / kross;
    if (t < 0 || t > 1) return 0;
    i0 = {a0.x + s * vax, a0.y + s * vay};
    // snap to endpoints to avoid drift
    auto snap = [&](const Pt& q) {
      if (std::abs(i0.x - q.x) < 1e-15 && std::abs(i0.y - q.y) < 1e-15) i0 = q;
    };
    snap(a0); snap(a1); snap(b0); snap(b1);
    return 1;
  }
  double sqrLenE = ex * ex + ey * ey;
  double krossE = ex * vay - ey * vax;
  if (krossE * krossE > sqrEps * sqrLenA * sqrLenE) return 0;  // parallel apart
  // collinear: project b onto a's parameter space
  double s0 = (vax * ex + vay * ey) / sqrLenA;
  double s1 = s0 + (vax * vbx + vay * vby) / sqrLenA;
  double smin = std::min(s0, s1), smax = std::max(s0, s1);
  double lo = std::max(0.0, smin), hi = std::min(1.0, smax);
  if (lo > hi) return 0;
  auto at = [&](double s) -> Pt {
    if (s <= 0) return a0;
    if (s >= 1) return a1;
    return {a0.x + s * vax, a0.y + s * vay};
  };
  i0 = at(lo);
  if (lo == hi) return 1;
  i1 = at(hi);
  return 2;
}

static bool inResultFlag(const SweepEvent* ev, BoolOp op) {
  switch (ev->type) {
    case NORMAL:
      switch (op) {
        case OP_INTERSECTION: return !ev->otherInOut;
        case OP_UNION: return ev->otherInOut;
        case OP_DIFFERENCE:
          return (ev->isSubject && ev->otherInOut) ||
                 (!ev->isSubject && !ev->otherInOut);
        case OP_XOR: return true;
      }
      return false;
    case SAME_TRANSITION:
      return op == OP_INTERSECTION || op == OP_UNION;
    case DIFFERENT_TRANSITION:
      return op == OP_DIFFERENCE;
    case NON_CONTRIBUTING:
      return false;
  }
  return false;
}

static void computeFields(SweepEvent* ev, SweepEvent* prev, BoolOp op) {
  if (prev == nullptr) {
    ev->inOut = false;
    ev->otherInOut = true;
  } else if (ev->isSubject == prev->isSubject) {
    ev->inOut = !prev->inOut;
    ev->otherInOut = prev->otherInOut;
  } else {
    ev->inOut = !prev->otherInOut;
    ev->otherInOut = prev->isVertical() ? !prev->inOut : prev->inOut;
  }
  if (prev != nullptr) {
    ev->prevInResult =
        (!inResultFlag(prev, op) || prev->isVertical()) ? prev->prevInResult
                                                        : prev;
  }
  ev->inResult = inResultFlag(ev, op);
}

// returns 0 = no change, 2 = overlap (fields of both must be recomputed),
// 1/3 = segments divided
static int possibleIntersection(SweepEvent* se1, SweepEvent* se2, Sweeper& sw) {
  Pt i0{}, i1{};
  int n = findIntersection(se1->p, se1->other->p, se2->p, se2->other->p, i0, i1);
  if (n == 0) return 0;
  if (n == 1 && (se1->p == se2->p || se1->other->p == se2->other->p)) return 0;
  if (n == 2 && se1->isSubject == se2->isSubject) {
    // self-overlap within one operand: ignore (inputs may carry duplicate
    // edges from degenerate rings; treating them as non-contributing is safe)
    se2->type = NON_CONTRIBUTING;
    return 0;
  }
  if (n == 1) {
    if (!(se1->p == i0) && !(se1->other->p == i0)) sw.divideSegment(se1, i0);
    if (!(se2->p == i0) && !(se2->other->p == i0)) sw.divideSegment(se2, i0);
    return 1;
  }
  // the segments overlap
  std::vector<SweepEvent*> events;
  bool leftCoincide = (se1->p == se2->p);
  bool rightCoincide = (se1->other->p == se2->other->p);
  if (!leftCoincide) {
    if (compareEvents(se1, se2) > 0) { events.push_back(se2); events.push_back(se1); }
    else { events.push_back(se1); events.push_back(se2); }
  }
  if (!rightCoincide) {
    if (compareEvents(se1->other, se2->other) > 0) {
      events.push_back(se2->other); events.push_back(se1->other);
    } else {
      events.push_back(se1->other); events.push_back(se2->other);
    }
  }
  if ((leftCoincide && rightCoincide) || leftCoincide) {
    se2->type = NON_CONTRIBUTING;
    se1->type = (se2->inOut == se1->inOut) ? SAME_TRANSITION : DIFFERENT_TRANSITION;
    if (leftCoincide && !rightCoincide)
      sw.divideSegment(events[1]->other, events[0]->p);
    return 2;
  }
  if (rightCoincide) {
    sw.divideSegment(events[0], events[1]->p);
    return 3;
  }
  if (events[0] != events[3]->other) {
    sw.divideSegment(events[0], events[1]->p);
    sw.divideSegment(events[1], events[2]->p);
    return 3;
  }
  // one segment fully contains the other
  sw.divideSegment(events[0], events[1]->p);
  sw.divideSegment(events[3]->other, events[2]->p);
  return 3;
}

using Contour = std::vector<Pt>;

static void connectEdges(std::vector<SweepEvent*>& sorted, BoolOp op,
                         std::vector<Contour>& out) {
  std::vector<SweepEvent*> result;
  result.reserve(sorted.size());
  for (SweepEvent* ev : sorted) {
    if ((ev->left && ev->inResult) || (!ev->left && ev->other->inResult))
      result.push_back(ev);
  }
  // re-sort: divisions can leave the collected order slightly stale
  bool sortedFlag = false;
  while (!sortedFlag) {
    sortedFlag = true;
    for (size_t i = 0; i + 1 < result.size(); ++i) {
      if (compareEvents(result[i], result[i + 1]) == 1) {
        std::swap(result[i], result[i + 1]);
        sortedFlag = false;
      }
    }
  }
  for (size_t i = 0; i < result.size(); ++i) result[i]->pos = (int)i;
  for (size_t i = 0; i < result.size(); ++i) {
    SweepEvent* ev = result[i];
    if (!ev->left) {
      int tmp = ev->pos;
      ev->pos = ev->other->pos;
      ev->other->pos = tmp;
    }
  }
  std::vector<bool> processed(result.size(), false);
  for (size_t i = 0; i < result.size(); ++i) {
    if (processed[i]) continue;
    Contour contour;
    Pt initial = result[i]->p;
    contour.push_back(initial);
    size_t pos = i;
    while (true) {
      processed[pos] = true;
      pos = (size_t)result[pos]->pos;  // jump to the partner endpoint
      processed[pos] = true;
      if (result[pos]->p == initial) break;
      contour.push_back(result[pos]->p);
      // Choose the next unprocessed event sharing this point. With four
      // or more result edges at a vertex (every crossing under XOR; a
      // subject hole touching its shell under any op) first-found
      // pairing stitches chains that cross and drops their partners —
      // take the SHARPEST LEFT TURN from the incoming edge instead,
      // which pairs edges into non-crossing closed contours.
      Pt cur = result[pos]->p;
      Pt prevP = contour[contour.size() - 2];
      double dix = cur.x - prevP.x, diy = cur.y - prevP.y;
      size_t next = pos;
      bool found = false;
      double bestAng = -1e300;
      auto consider = [&](size_t j) {
        if (processed[j]) return;
        Pt q = result[j]->other->p;
        double dcx = q.x - cur.x, dcy = q.y - cur.y;
        double ang = std::atan2(dix * dcy - diy * dcx, dix * dcx + diy * dcy);
        if (!found || ang > bestAng) {
          bestAng = ang;
          next = j;
          found = true;
        }
      };
      for (size_t j = pos + 1; j < result.size() && result[j]->p == cur; ++j)
        consider(j);
      for (size_t j = pos; j-- > 0 && result[j]->p == cur;) consider(j);
      if (!found) break;  // open chain (degenerate); emit what we have
      pos = next;
    }
    if (contour.size() >= 3) out.push_back(std::move(contour));
  }
}

// rings: flat array of contours for subject (ns rings) then clipping.
void boolOp(BoolOp op, const std::vector<Contour>& subject,
            const std::vector<Contour>& clipping, std::vector<Contour>& out) {
  // trivial cases
  bool subjEmpty = subject.empty(), clipEmpty = clipping.empty();
  if (subjEmpty || clipEmpty) {
    if (op == OP_INTERSECTION) return;
    if (op == OP_DIFFERENCE) { out = subject; return; }
    out = subjEmpty ? clipping : subject;
    return;
  }
  double sxmin = 1e300, sxmax = -1e300, symin = 1e300, symax = -1e300;
  double cxmin = 1e300, cxmax = -1e300, cymin = 1e300, cymax = -1e300;
  for (auto& c : subject)
    for (auto& p : c) {
      sxmin = std::min(sxmin, p.x); sxmax = std::max(sxmax, p.x);
      symin = std::min(symin, p.y); symax = std::max(symax, p.y);
    }
  for (auto& c : clipping)
    for (auto& p : c) {
      cxmin = std::min(cxmin, p.x); cxmax = std::max(cxmax, p.x);
      cymin = std::min(cymin, p.y); cymax = std::max(cymax, p.y);
    }
  if (sxmin > cxmax || cxmin > sxmax || symin > cymax || cymin > symax) {
    if (op == OP_INTERSECTION) return;
    if (op == OP_DIFFERENCE) { out = subject; return; }
    out = subject;
    out.insert(out.end(), clipping.begin(), clipping.end());
    return;
  }
  double rightbound = std::min(sxmax, cxmax);

  Sweeper sw;
  int cid = 0;
  for (auto& c : subject) {
    ++cid;
    for (size_t k = 0; k < c.size(); ++k)
      sw.addSegment(c[k], c[(k + 1) % c.size()], true, cid);
  }
  for (auto& c : clipping) {
    ++cid;
    for (size_t k = 0; k < c.size(); ++k)
      sw.addSegment(c[k], c[(k + 1) % c.size()], false, cid);
  }

  std::set<SweepEvent*, SegmentCmp> sl;
  while (!sw.queue.empty()) {
    SweepEvent* ev = sw.queue.top();
    sw.queue.pop();
    sw.sorted.push_back(ev);
    // optimization: beyond the overlap zone nothing can change the result
    if ((op == OP_INTERSECTION && ev->p.x > rightbound) ||
        (op == OP_DIFFERENCE && ev->p.x > sxmax))
      break;
    if (ev->left) {
      auto ins = sl.insert(ev);
      auto it = ins.first;
      auto prev = it, next = it;
      SweepEvent* prevEv = (it == sl.begin()) ? nullptr : *(--prev);
      computeFields(ev, prevEv, op);
      ++next;
      if (next != sl.end()) {
        if (possibleIntersection(ev, *next, sw) == 2) {
          computeFields(ev, prevEv, op);
          computeFields(*next, ev, op);
        }
      }
      if (prevEv != nullptr) {
        if (possibleIntersection(prevEv, ev, sw) == 2) {
          auto pprev = prev;
          SweepEvent* prevPrevEv = (prev == sl.begin()) ? nullptr : *(--pprev);
          computeFields(prevEv, prevPrevEv, op);
          computeFields(ev, prevEv, op);
        }
      }
    } else {
      SweepEvent* le = ev->other;
      auto it = sl.find(le);
      if (it == sl.end()) continue;  // robustness: comparator drift
      auto prev = it, next = it;
      SweepEvent* prevEv = (it == sl.begin()) ? nullptr : *(--prev);
      ++next;
      SweepEvent* nextEv = (next == sl.end()) ? nullptr : *next;
      sl.erase(it);
      if (prevEv && nextEv) possibleIntersection(prevEv, nextEv, sw);
    }
  }
  connectEdges(sw.sorted, op, out);
}

}  // namespace mg
