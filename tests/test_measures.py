"""Device measures vs host oracle (the eval-vs-compiled equivalence matrix)."""

import jax
import numpy as np
import pytest

from mosaic_tpu.core.geometry import measures, oracle, wkt
from mosaic_tpu.core.geometry.device import pack_to_device

import fixtures as fx


@pytest.fixture(scope="module")
def col():
    return wkt.from_wkt(fx.ALL_WKT)


@pytest.fixture(scope="module", params=["f32", "f64"])
def dev(request, col):
    import jax.numpy as jnp

    dtype = jnp.float32 if request.param == "f32" else jnp.float64
    return pack_to_device(col, dtype=dtype)


def tol(dev):
    return 1e-4 if dev.verts.dtype == np.float32 else 1e-9


def test_area_matches_oracle(col, dev):
    got = np.asarray(jax.jit(measures.area)(dev))
    want = oracle.area(col)
    np.testing.assert_allclose(got, want, rtol=tol(dev), atol=tol(dev))


def test_area_values(col):
    dev = pack_to_device(col, dtype=np.float64)
    a = np.asarray(measures.area(dev))
    # square 4x4 = 16; 10x10 minus 2x2 hole = 96
    assert a[5] == pytest.approx(16.0)
    assert a[6] == pytest.approx(96.0)


def test_length_matches_oracle(col, dev):
    got = np.asarray(jax.jit(measures.length)(dev))
    want = oracle.length(col)
    np.testing.assert_allclose(got, want, rtol=tol(dev), atol=tol(dev))


def test_centroid_matches_oracle(col, dev):
    got = np.asarray(jax.jit(measures.centroid)(dev))
    want = oracle.centroid(col)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=tol(dev) * 10)


def test_bounds_matches_host(col, dev):
    got = np.asarray(jax.jit(measures.bounds)(dev))
    want = col.bounds()
    np.testing.assert_allclose(got, want, rtol=tol(dev), atol=tol(dev))


def test_num_points(col, dev):
    got = np.asarray(measures.num_points(dev))
    # square: 5 with closing vertex (JTS semantics)
    assert got[5] == 5
    assert got[6] == 10  # 5 + 5 hole
    assert got[0] == 1  # point


def test_centroid_square(col):
    dev = pack_to_device(col, dtype=np.float64)
    c = np.asarray(measures.centroid(dev))
    np.testing.assert_allclose(c[5], [2.0, 2.0], atol=1e-12)
