"""Runtime resilience layer: capacity escalation, transient retry,
graceful degradation, fault injection (`mosaic_tpu/runtime/`).

The acceptance contract (ISSUE 1): under injected faults — forced
tier-2 overflow with shrunken caps, synthetic transient device errors on
the first N calls — `pip_join`, `overlay_join`, and `dist_pip_join`
return results bit-identical to the clean run with the escalation/retry
trail visible in structured telemetry; a fault that exhausts the bounded
budget raises a typed error or returns an explicitly ``degraded``
host-oracle result. Never a silent ``-2``/zeroed output.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.datasets import random_points, synthetic_zones
from mosaic_tpu.parallel import dist_pip_join, make_mesh
from mosaic_tpu.runtime import (
    CapacityOverflow,
    DegradedResult,
    EscalationPolicy,
    RetryExhausted,
    RetryPolicy,
    TransientDeviceError,
    backoff_delays,
    call_with_retry,
    faults,
    is_transient,
    run_escalating,
    telemetry,
)
from mosaic_tpu.sql.join import OVERFLOW, build_chip_index, pip_join
from mosaic_tpu.sql.overlay import overlay_join
from mosaic_tpu.sql import pip_join_points

RES = 7
BBOX = (-74.05, 40.60, -73.85, 40.78)
N_POINTS = 1200


@pytest.fixture(scope="module")
def problem():
    """Zones + a chip index built with a tiny edge_cap so heavy (tier-2)
    cells genuinely exist, points, and the clean join result."""
    h3 = H3IndexSystem()
    zones = synthetic_zones(3, 3, bbox=BBOX)
    table = tessellate(zones, h3, RES, keep_core_geoms=False)
    index = build_chip_index(table, edge_cap=8)
    assert index.num_heavy_cells > 0  # tier 2 must be exercised
    pts = random_points(N_POINTS, bbox=BBOX, seed=5)
    clean = np.asarray(
        pip_join(pts, None, h3, RES, chip_index=index, recheck=False)
    )
    assert (clean >= 0).any() and (clean != OVERFLOW).all()
    return h3, zones, index, pts, clean


# ------------------------------------------------------------ primitives


def test_backoff_delays_grow_and_cap():
    pol = RetryPolicy(base_delay_s=1.0, max_delay_s=4.0, jitter=0.0)
    d = backoff_delays(pol)
    assert [next(d) for _ in range(4)] == [1.0, 2.0, 4.0, 4.0]


def test_is_transient_classification():
    assert is_transient(TransientDeviceError("x"))
    assert is_transient(RuntimeError("remote_compile: HTTP 500"))
    assert not is_transient(ValueError("bad argument"))
    assert not is_transient(RuntimeError("shape mismatch"))
    assert not is_transient(TypeError("nope"))


def test_call_with_retry_recovers_and_telemetry():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientDeviceError("boom")
        return 42

    with telemetry.capture() as ev:
        out = call_with_retry(
            flaky, policy=RetryPolicy(base_delay_s=0.0), label="t"
        )
    assert out == 42 and calls["n"] == 3
    assert [e["event"] for e in ev] == ["transient_retry", "transient_retry"]
    assert ev[0]["attempt"] == 1 and ev[1]["attempt"] == 2


def test_call_with_retry_nontransient_raises_immediately():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("programming error")

    with pytest.raises(ValueError):
        call_with_retry(bad, policy=RetryPolicy(base_delay_s=0.0))
    assert calls["n"] == 1


def test_call_with_retry_exhausts_typed():
    def always():
        raise TransientDeviceError("down")

    with pytest.raises(RetryExhausted) as ei:
        call_with_retry(
            always, policy=RetryPolicy(max_attempts=2, base_delay_s=0.0)
        )
    assert ei.value.attempts == 2
    assert isinstance(ei.value.last, TransientDeviceError)


def test_call_with_retry_fallback_is_degraded():
    def always():
        raise TransientDeviceError("down")

    out = call_with_retry(
        always,
        policy=RetryPolicy(max_attempts=2, base_delay_s=0.0),
        fallback=lambda: np.arange(4),
    )
    assert isinstance(out, DegradedResult) and out.degraded
    np.testing.assert_array_equal(np.asarray(out), np.arange(4))
    # a DegradedResult behaves like its base array everywhere else
    assert int(out.sum()) == 6


def test_run_escalating_grows_to_exact():
    seen = []

    def attempt(caps):
        seen.append(dict(caps))
        return caps["cap"]

    with telemetry.capture() as ev:
        out, caps = run_escalating(
            attempt, {"cap": 8}, {"cap": 1024},
            overflow_count=lambda c: 0 if c >= 32 else 32 - c,
            stage="unit",
        )
    assert out == 32 and caps["cap"] == 32
    assert [c["cap"] for c in seen] == [8, 16, 32]
    kinds = [e["event"] for e in ev]
    assert kinds.count("capacity_overflow") == 2
    assert kinds[-1] == "escalation_resolved"


def test_run_escalating_ceiling_raises_typed():
    with pytest.raises(CapacityOverflow) as ei:
        run_escalating(
            lambda caps: caps["cap"], {"cap": 8}, {"cap": 16},
            overflow_count=lambda c: 1, stage="unit",
        )
    assert ei.value.stage == "unit" and ei.value.overflow_count == 1


def test_run_escalating_attempt_budget_raises_typed():
    with pytest.raises(CapacityOverflow):
        run_escalating(
            lambda caps: caps["cap"], {"cap": 8}, {"cap": 1 << 40},
            overflow_count=lambda c: 1,
            policy=EscalationPolicy(max_attempts=3),
        )


def test_faults_site_filtering():
    with faults.transient_errors(5, sites=("other.site",)):
        faults.maybe_fail("this.site")  # no match: must not raise
    with faults.transient_errors(1, sites=("knn.*",)):
        with pytest.raises(TransientDeviceError):
            faults.maybe_fail("knn.pair_distances")
        faults.maybe_fail("knn.pair_distances")  # budget of 1 spent


def test_faults_clamp_caps_noop_without_plan():
    caps = {"found_cap": 512, "heavy_cap": None}
    assert faults.clamp_caps(caps) == caps
    with faults.shrink_caps(found_cap=8, heavy_cap=8):
        out = faults.clamp_caps(caps)
    assert out == {"found_cap": 8, "heavy_cap": 8}


# ------------------------------------------------- pip_join under faults


def test_pip_join_forced_overflow_escalates_bit_identical(problem):
    h3, zones, index, pts, clean = problem
    with telemetry.capture() as ev:
        with faults.shrink_caps(found_cap=128, heavy_cap=32):
            out = pip_join(
                pts, None, h3, RES, chip_index=index, recheck=False
            )
    out = np.asarray(out)
    np.testing.assert_array_equal(out, clean)
    assert (out != OVERFLOW).all()
    # ignore span events (obs tracing closes the join.pip span after the
    # escalation trail) — the resilience trail itself ends resolved
    kinds = [e["event"] for e in ev if e["event"] != "span"]
    assert "capacity_overflow" in kinds  # the trail is visible
    assert kinds[-1] == "escalation_resolved"


def test_pip_join_forced_tier2_overflow_bit_identical(problem):
    h3, zones, index, pts, clean = problem
    with telemetry.capture() as ev:
        with faults.force_tier2_overflow(heavy_cap=8):
            out = pip_join(
                pts, None, h3, RES, chip_index=index, recheck=False
            )
    np.testing.assert_array_equal(np.asarray(out), clean)
    over = [e for e in ev if e["event"] == "capacity_overflow"]
    assert over and all(e["caps"]["heavy_cap"] >= 8 for e in over)


def test_pip_join_transient_faults_retry_bit_identical(problem):
    h3, zones, index, pts, clean = problem
    with telemetry.capture() as ev:
        with faults.transient_errors(2, sites=("pip_join.device",)):
            out = pip_join(
                pts, None, h3, RES, chip_index=index, recheck=False
            )
    assert not isinstance(out, DegradedResult)  # retries recovered
    np.testing.assert_array_equal(np.asarray(out), clean)
    assert [e["event"] for e in ev].count("transient_retry") == 2


def test_pip_join_retry_exhausted_degrades_to_host_oracle(problem):
    h3, zones, index, pts, clean = problem
    from mosaic_tpu.sql.join import host_join

    with telemetry.capture() as ev:
        with faults.transient_errors(50, sites=("pip_join.device",)):
            out = pip_join(
                pts, None, h3, RES, chip_index=index, recheck=False
            )
    assert isinstance(out, DegradedResult) and out.degraded
    assert out.attempts >= 3 and "exhausted" in out.reason
    # the degraded answer is the exact f64 host oracle, not zeros
    expect = host_join(pts, index.host, h3, RES)
    np.testing.assert_array_equal(np.asarray(out), expect)
    assert any(e["event"] == "degraded" for e in ev)


def test_pip_join_points_still_reports_overflow_at_low_level(problem):
    """The LOW-level jittable API keeps the documented -2 contract; only
    the managed wrappers escalate. This pins that the sentinel survives
    for callers that size caps themselves."""
    h3, zones, index, pts, clean = problem
    shift = index.host.shift
    dt = np.asarray(index.border.verts).dtype
    cells = np.asarray(h3.point_to_cell(jnp.asarray(pts), RES))
    out = np.asarray(
        pip_join_points(
            jnp.asarray((pts - shift).astype(dt)), jnp.asarray(cells),
            index, found_cap=8,
        )
    )
    assert (out == OVERFLOW).any()


def test_compact_block_must_be_multiple_of_128(problem):
    h3, zones, index, pts, clean = problem
    cells = np.asarray(h3.point_to_cell(jnp.asarray(pts), RES))
    shift = index.host.shift
    dt = np.asarray(index.border.verts).dtype
    with pytest.raises(
        ValueError, match=r"compact_block must be a multiple of 128"
    ):
        pip_join_points(
            jnp.asarray((pts - shift).astype(dt)), jnp.asarray(cells),
            index, compaction="mxu", compact_block=200,
        )


# --------------------------------------------- overlay_join under faults


@pytest.fixture(scope="module")
def overlay_problem():
    h3 = H3IndexSystem()
    left = synthetic_zones(3, 3, bbox=BBOX)
    right = synthetic_zones(2, 2, bbox=BBOX)
    clean = np.asarray(overlay_join(left, right, h3, RES))
    assert clean.shape[0] > 0
    return h3, left, right, clean


def test_overlay_transient_retry_bit_identical(overlay_problem):
    h3, left, right, clean = overlay_problem
    with telemetry.capture() as ev:
        with faults.transient_errors(2, sites=("overlay.predicate",)):
            out = overlay_join(left, right, h3, RES)
    assert not isinstance(out, DegradedResult)
    np.testing.assert_array_equal(np.asarray(out), clean)
    assert [e["event"] for e in ev].count("transient_retry") == 2


def test_overlay_oracle_exhaustion_raises_typed(overlay_problem):
    h3, left, right, clean = overlay_problem
    with faults.transient_errors(99, sites=("overlay.predicate",)):
        with pytest.raises(RetryExhausted):
            overlay_join(left, right, h3, RES)


def test_overlay_device_backend_degrades_to_oracle(overlay_problem):
    h3, left, right, clean = overlay_problem
    with faults.transient_errors(99, sites=("overlay.predicate",)):
        out = overlay_join(left, right, h3, RES, backend="device")
    assert isinstance(out, DegradedResult) and out.degraded
    np.testing.assert_array_equal(np.asarray(out), clean)


# -------------------------------------------- dist_pip_join under faults


def test_dist_pip_join_clean_matches_pip_join(problem, devices):
    h3, zones, index, pts, clean = problem
    mesh = make_mesh(8, cell_axis=2)
    cells = np.asarray(h3.point_to_cell(jnp.asarray(pts), RES))
    match, counts = dist_pip_join(pts, cells, index, mesh, len(zones))
    np.testing.assert_array_equal(match, clean)
    expect = np.bincount(clean[clean >= 0], minlength=len(zones))
    np.testing.assert_array_equal(counts, expect)


def test_dist_pip_join_faults_bit_identical(problem, devices):
    """The headline acceptance: shrunken caps AND two transient failures
    — the distributed join still converges to the clean answer."""
    h3, zones, index, pts, clean = problem
    mesh = make_mesh(8, cell_axis=2)
    cells = np.asarray(h3.point_to_cell(jnp.asarray(pts), RES))
    with telemetry.capture() as ev:
        with faults.shrink_caps(found_cap=16, heavy_cap=16):
            with faults.transient_errors(2, sites=("dist_join.step",)):
                match, counts = dist_pip_join(
                    pts, cells, index, mesh, len(zones)
                )
    np.testing.assert_array_equal(match, clean)
    assert (match != OVERFLOW).all()
    kinds = [e["event"] for e in ev]
    assert kinds.count("transient_retry") == 2
    assert "capacity_overflow" in kinds and "escalation_resolved" in kinds


def test_dist_pip_join_exhaustion_degrades(problem, devices):
    h3, zones, index, pts, clean = problem
    mesh = make_mesh(8, cell_axis=2)
    cells = np.asarray(h3.point_to_cell(jnp.asarray(pts), RES))
    with faults.transient_errors(99, sites=("dist_join.step",)):
        match, counts = dist_pip_join(pts, cells, index, mesh, len(zones))
    assert isinstance(match, DegradedResult) and match.degraded
    from mosaic_tpu.sql.join import host_join_with_cells

    expect = host_join_with_cells(pts, cells, index.host)
    np.testing.assert_array_equal(np.asarray(match), expect)
    np.testing.assert_array_equal(
        counts, np.bincount(expect[expect >= 0], minlength=len(zones))
    )


# ------------------------------------------------------ KNN under faults


def test_knn_degrades_to_oracle_distances(problem):
    from mosaic_tpu.models import SpatialKNN

    h3, zones, index, pts, clean = problem
    lands = synthetic_zones(2, 2, bbox=(-74.0, 40.62, -73.9, 40.7))
    knn = SpatialKNN(index=h3, resolution=RES, k_neighbours=2)
    ref = knn.transform(lands, zones)
    assert ref.metrics["degraded"] is False
    knn2 = SpatialKNN(index=h3, resolution=RES, k_neighbours=2)
    with faults.transient_errors(999, sites=("knn.pair_distances",)):
        out = knn2.transform(lands, zones)
    assert out.metrics["degraded"] is True
    np.testing.assert_array_equal(out.candidate_id, ref.candidate_id)
    np.testing.assert_allclose(out.distance, ref.distance, rtol=1e-9)
