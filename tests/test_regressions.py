"""Regression tests for round-2 advisor findings.

(a) `_build_hash` must stay self-consistent even when every multiplier
    retry clusters (the fallback path);
(b) float rasters with NaN nodata must mask NaN pixels (`v != NaN` is
    always True);
(c) a GeoTIFF whose IFD value bytes are truncated must fail the read with
    an error code instead of silently decoding zeros.
"""

import numpy as np
import pytest

from mosaic_tpu.raster import Raster, read_raster, write_geotiff
from mosaic_tpu.sql.join import _build_hash


def test_build_hash_exhausted_retries_stay_consistent():
    """max_bucket=0 forces every retry to 'fail': the returned (mult, T)
    must still locate every cell (the round-2 bug desynced keys from T)."""
    cells = np.sort(np.unique(np.random.default_rng(1).integers(
        1, 2**60, 500, dtype=np.int64
    )))
    mult, table_cell, table_slot, _, _ = _build_hash(cells, max_bucket=0)
    T = table_cell.shape[0]
    bits = int(np.log2(T))
    keys = (cells.astype(np.uint64) * mult) >> np.uint64(64 - bits)
    for u, (c, k) in enumerate(zip(cells, keys.astype(np.int64))):
        row = table_cell[k]
        hit = np.nonzero(row == c)[0]
        assert hit.size == 1, f"cell {c} not findable under returned hash"
        assert table_slot[k, hit[0]] == u


def test_nan_nodata_masked():
    data = np.full((1, 4, 5), 1.5, dtype=np.float32)
    data[0, 0, 0] = np.nan
    data[0, 1, 2] = np.nan
    r = Raster(
        data=data,
        gt=(0.0, 1.0, 0.0, 0.0, 0.0, -1.0),
        srid=4326,
        nodata=float("nan"),
    )
    m = r.band(1).mask
    assert not m[0, 0] and not m[1, 2]
    assert m.sum() == 18
    assert r.band(1).min() == 1.5  # NaN pixels excluded from stats


def test_truncated_ifd_errors(tmp_path):
    data = (np.arange(200, dtype=np.float64)).reshape(1, 10, 20)
    r = Raster(
        data=data.astype(np.float32),
        gt=(0.0, 1.0, 0.0, 0.0, 0.0, -1.0),
        srid=4326,
        nodata=None,
    )
    p = tmp_path / "full.tif"
    write_geotiff(str(p), r)
    raw = p.read_bytes()
    # truncate into the out-of-line IFD value area: offsets now point past
    # EOF, which must be a hard read error, not a zero-filled success
    for frac in (0.35, 0.6):
        q = tmp_path / f"trunc_{frac}.tif"
        q.write_bytes(raw[: int(len(raw) * frac)])
        with pytest.raises(ValueError):
            read_raster(str(q))
