"""Synthetic MODIS-like GeoTIFF fixture writer.

The real MODIS tile the reference ships
(``MCD43A4...B01.TIF``: 2400x2400 int16, tiled + deflate + horizontal
predictor 2, sinusoidal ~463.31 m pixels, nodata 32767, mostly ocean)
lives in ``/root/reference``, which most environments don't have. This
module writes a file with the SAME on-disk shape — tiled layout (tags
322-325), zlib deflate (compression 8), predictor 2 (tag 317), int16,
band-sequential planes (planar 2), GDAL nodata + metadata tags — so the
MODIS decode tests exercise the native engine's tiled/compressed/
predicted path for real instead of xfailing, and fall through to the
reference file when it is present.

The pixel field is "sinusoidal-ish": an elliptical land blob of smooth
non-negative reflectance values in an ocean of nodata, tuned so the
valid fraction lands in the (0.05, 0.2) window the decode test asserts.
Deliberately NOT written through `raster/core.py`'s writer (which emits
uncompressed strips): a fixture produced by the code under test would
prove nothing about the decoder's compressed lanes.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

#: MODIS sinusoidal pixel pitch (meters) — the decode test asserts
#: gt[1] to 1e-3, so the fixture uses the real constant
MODIS_PIXEL = 463.3127165279165

#: upper-left corner of sinusoidal tile h10v07 (meters)
MODIS_UL = (-7783653.637667, 2223901.039333)


def modis_like_field(
    width: int = 2400, height: int = 2400, bands: int = 1,
    nodata: int = 32767, seed: int = 7,
) -> np.ndarray:
    """(bands, H, W) int16: smooth non-negative "reflectance" inside an
    elliptical blob (~10% of pixels), ``nodata`` ocean elsewhere."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:height, 0:width]
    cy, cx = height * 0.62, width * 0.31
    # ellipse sized for ~10% coverage: pi*a*b = 0.10*H*W
    a, b = width * 0.26, height * 0.125
    inside = ((xx - cx) / a) ** 2 + ((yy - cy) / b) ** 2 < 1.0
    out = np.full((bands, height, width), nodata, dtype=np.int16)
    for bi in range(bands):
        phase = rng.uniform(0, 2 * np.pi)
        field = (
            2000.0
            + 1500.0 * np.sin(xx / width * 9.0 + phase)
            * np.cos(yy / height * 7.0)
            + 800.0 * np.cos((xx + 2 * yy) / width * 5.0)
        )
        vals = np.clip(field, 0, 32000).astype(np.int16)
        out[bi][inside] = vals[inside]
    return out


def write_tiled_geotiff(
    path: str,
    data: np.ndarray,
    *,
    gt=None,
    nodata: "float | None" = None,
    meta_xml: str = "",
    tile: int = 256,
) -> None:
    """Write (bands, H, W) int16/uint16 as a tiled + deflate +
    predictor-2 little-endian classic TIFF, planar configuration 2
    (plane-major tile order), edge tiles padded to full size — the
    MODIS on-disk shape."""
    data = np.ascontiguousarray(data)
    if data.dtype not in (np.dtype(np.int16), np.dtype(np.uint16)):
        raise ValueError(
            f"fixture writer is int16/uint16-only, got {data.dtype}"
        )
    bands, h, w = data.shape
    fmt = 2 if data.dtype == np.dtype(np.int16) else 1
    ta = -(-w // tile)
    td = -(-h // tile)
    if gt is None:
        gt = (
            MODIS_UL[0], MODIS_PIXEL, 0.0,
            MODIS_UL[1], 0.0, -MODIS_PIXEL,
        )
    x0, sx, rx, y0, ry, sy = gt

    blobs: list[bytes] = []
    for bi in range(bands):  # plane-major: all of band 0's tiles first
        plane = data[bi]
        for ty in range(td):
            for tx in range(ta):
                chunk = np.zeros((tile, tile), data.dtype)
                sub = plane[
                    ty * tile : min((ty + 1) * tile, h),
                    tx * tile : min((tx + 1) * tile, w),
                ]
                chunk[: sub.shape[0], : sub.shape[1]] = sub
                # horizontal differencing (predictor 2), per tile row,
                # int16 wraparound — the decoder re-integrates per row
                diffed = chunk.copy()
                diffed[:, 1:] = chunk[:, 1:] - chunk[:, :-1]
                blobs.append(
                    zlib.compress(diffed.astype("<" + data.dtype.str[1:]).tobytes(), 6)
                )

    entries: list[tuple[int, int, int, bytes]] = []

    def e_short(tag, *vals):
        entries.append(
            (tag, 3, len(vals), struct.pack(f"<{len(vals)}H", *vals))
        )

    def e_long(tag, *vals):
        entries.append(
            (tag, 4, len(vals), struct.pack(f"<{len(vals)}I", *vals))
        )

    def e_dbl(tag, *vals):
        entries.append(
            (tag, 12, len(vals), struct.pack(f"<{len(vals)}d", *vals))
        )

    def e_ascii(tag, s):
        b = s.encode() + b"\0"
        entries.append((tag, 2, len(b), b))

    e_long(256, w)
    e_long(257, h)
    e_short(258, *([16] * bands))
    e_short(259, 8)  # Adobe deflate (zlib)
    e_short(262, 1)
    e_short(277, bands)
    e_short(284, 2)  # planar: band-sequential tile planes
    e_short(317, 2)  # horizontal differencing
    e_long(322, tile)
    e_long(323, tile)
    e_long(324, *([0] * len(blobs)))  # patched after layout
    e_long(325, *[len(b) for b in blobs])
    e_short(339, *([fmt] * bands))
    e_dbl(33550, sx, -sy, 0.0)
    e_dbl(33922, 0.0, 0.0, 0.0, x0, y0, 0.0)
    if nodata is not None:
        e_ascii(42113, repr(float(nodata)))
    if meta_xml:
        e_ascii(42112, meta_xml)

    entries.sort(key=lambda t: t[0])
    n = len(entries)
    ifd_off = 8
    val_off = ifd_off + 2 + 12 * n + 4
    fixed = []
    out_blobs = []
    for tag, typ, cnt, val in entries:
        if len(val) <= 4:
            fixed.append((tag, typ, cnt, val.ljust(4, b"\0"), None))
        else:
            fixed.append((tag, typ, cnt, None, val_off))
            out_blobs.append((tag, val))
            val_off += len(val) + (len(val) & 1)
    data_off = val_off
    # tile payload layout, then patch the offsets array (tag 324)
    offs = []
    cursor = data_off
    for b in blobs:
        offs.append(cursor)
        cursor += len(b) + (len(b) & 1)
    for i, (tag, val) in enumerate(out_blobs):
        if tag == 324:
            out_blobs[i] = (tag, struct.pack(f"<{len(offs)}I", *offs))
    out = bytearray()
    out += b"II*\0" + struct.pack("<I", ifd_off)
    out += struct.pack("<H", n)
    for tag, typ, cnt, inline, off in fixed:
        out += struct.pack("<HHI", tag, typ, cnt)
        if inline is not None:
            if tag == 324 and cnt == 1:
                out += struct.pack("<I", offs[0])
            else:
                out += inline
        else:
            out += struct.pack("<I", off)
    out += struct.pack("<I", 0)
    for _tag, val in out_blobs:
        out += val
        if len(val) & 1:
            out += b"\0"
    for b in blobs:
        out += b
        if len(b) & 1:
            out += b"\0"
    with open(path, "wb") as f:
        f.write(bytes(out))


def write_modis_like(
    path: str,
    *,
    width: int = 2400,
    height: int = 2400,
    bands: int = 1,
    nodata: int = 32767,
    tile: int = 256,
    seed: int = 7,
) -> str:
    """Write the full MODIS-like fixture (field + tags + metadata XML
    with a dataset-level ``_FillValue``) and return ``path``."""
    data = modis_like_field(width, height, bands, nodata, seed)
    meta = (
        "<GDALMetadata>\n"
        f'  <Item name="_FillValue">{nodata}</Item>\n'
        '  <Item name="PRODUCT">SYNTHETIC_MCD43A4</Item>\n'
        "</GDALMetadata>"
    )
    write_tiled_geotiff(
        path, data, nodata=float(nodata), meta_xml=meta, tile=tile
    )
    return path
