"""Unit contracts for the pipelined execution core
(`dispatch/pipeline.py`): bounded-window ordering, the replay-from-
materialized-carry rule, best-effort drain on fatal errors, and the
SnapshotWriter's context adoption + held-error/durability barriers.

Frontend-level semantics (bit-identity, kill/resume, degradation) are
pinned where they live: tests/test_stream_faults.py and
tests/test_raster_zonal.py. This file pins the core's mechanics with
synthetic launch/land callbacks, so a regression points at the
pipeline, not at a frontend.
"""

from __future__ import annotations

import threading
import time

import pytest

from mosaic_tpu.dispatch import (
    SnapshotWriter,
    execute_pipeline,
    resolve_window,
)
from mosaic_tpu.runtime import faults, telemetry
from mosaic_tpu.runtime.errors import TransientDeviceError


class TestResolveWindow:
    def test_default(self, monkeypatch):
        monkeypatch.delenv("MOSAIC_STREAM_WINDOW", raising=False)
        assert resolve_window() == 4

    def test_env_knob(self, monkeypatch):
        monkeypatch.setenv("MOSAIC_STREAM_WINDOW", "7")
        assert resolve_window() == 7

    def test_explicit_beats_env(self, monkeypatch):
        monkeypatch.setenv("MOSAIC_STREAM_WINDOW", "7")
        assert resolve_window(2) == 2

    def test_clamped_to_one(self):
        assert resolve_window(0) == 1
        assert resolve_window(-3) == 1

    def test_garbage_env_falls_back(self, monkeypatch):
        monkeypatch.setenv("MOSAIC_STREAM_WINDOW", "many")
        assert resolve_window() == 4


class TestExecutePipeline:
    def test_lands_in_order_and_counts(self):
        landed = []
        stats = execute_pipeline(
            10, lambda i: i * i,
            lambda i, h: landed.append((i, h)),
            drain_site="t.drain", window=3,
        )
        assert landed == [(i, i * i) for i in range(10)]
        assert stats.launched == 10 and stats.landed == 10
        assert stats.max_inflight == 3
        assert stats.replays == 0 and stats.replayed == 0

    def test_window_bounds_inflight(self):
        live = set()
        high = [0]

        def launch(i):
            live.add(i)
            high[0] = max(high[0], len(live))
            return i

        stats = execute_pipeline(
            12, launch, lambda i, h: live.discard(i),
            drain_site="t.drain", window=2,
        )
        assert high[0] == 2
        assert stats.max_inflight == 2

    def test_window_one_is_the_synchronous_loop(self):
        order = []
        execute_pipeline(
            4, lambda i: order.append(("launch", i)),
            lambda i, h: order.append(("land", i)),
            drain_site="t.drain", window=1,
        )
        assert order == [
            (op, i) for i in range(4) for op in ("launch", "land")
        ]

    def test_transient_drain_replays_from_materialized_carry(self):
        landed, replays = [], []
        boom = [True]

        def land(i, h):
            if i == 1 and boom[0]:
                boom[0] = False
                raise TransientDeviceError("drain hiccup")
            landed.append(i)

        with telemetry.capture() as ev:
            stats = execute_pipeline(
                5, lambda i: i, land, drain_site="t.drain",
                replay=lambda lo, hi: replays.append((lo, hi)),
                window=2,
            )
        # launches 0,1 -> land 0 -> launch 2 -> land 1 FAILS with
        # items 1,2 in flight: the window is discarded and the caller
        # replays [materialized+1 .. last launched] = [1, 2]
        assert replays == [(1, 2)]
        assert landed == [0, 3, 4]
        assert stats.replays == 1 and stats.replayed == 2
        kinds = [e["event"] for e in ev]
        assert kinds.count("pipeline_replay") == 1

    def test_transient_launch_discards_unlanded_window(self):
        replays = []
        boom = [True]

        def launch(i):
            if i == 1 and boom[0]:
                boom[0] = False
                raise TransientDeviceError("launch hiccup")
            return i

        stats = execute_pipeline(
            3, launch, lambda i, h: None, drain_site="t.drain",
            replay=lambda lo, hi: replays.append((lo, hi)),
            window=4,
        )
        # item 0 was launched but NOT yet materialized when launch(1)
        # failed — it is part of the poisoned window and replays too
        assert replays == [(0, 1)]
        assert stats.replayed == 2

    def test_commit_runs_on_caller_thread_with_pulled_value(self):
        # the guarded pull runs on the watchdog worker (a deadline is
        # set), but commit — where effects live — must run on the
        # caller thread, only after the pull returned
        main = threading.get_ident()
        land_threads, commits = [], []

        def land(i, h):
            land_threads.append(threading.get_ident())
            return h * 10

        def commit(i, pulled):
            commits.append((i, pulled, threading.get_ident()))

        execute_pipeline(
            3, lambda i: i, land, commit=commit,
            drain_site="t.drain", window=2, watchdog_default_s=30.0,
        )
        assert [(i, p) for i, p, _ in commits] == [(0, 0), (1, 10), (2, 20)]
        assert all(t == main for _, _, t in commits)
        assert all(t != main for t in land_threads)

    def test_transient_commit_replays_own_item(self):
        # the replay anchor advances only after commit returns: a
        # transient mid-commit replays the SAME item from the pre-item
        # carry — it is neither skipped nor applied twice
        commits, replays = [], []
        boom = [True]

        def commit(i, pulled):
            if i == 1 and boom[0]:
                boom[0] = False
                raise TransientDeviceError("commit hiccup")
            commits.append(i)

        stats = execute_pipeline(
            5, lambda i: i, lambda i, h: h, commit=commit,
            drain_site="t.drain",
            replay=lambda lo, hi: replays.append((lo, hi)), window=2,
        )
        assert replays == [(1, 2)]
        assert commits == [0, 3, 4]
        assert stats.replays == 1 and stats.replayed == 2

    def test_deadline_abandoned_land_commits_nothing(self):
        # the watchdog ABANDONS its worker on deadline: the stalled
        # pull eventually finishes in the background, but its item was
        # already replayed — commit must never run for it, or the
        # item's effects would double-apply
        commits, replays = [], []
        slow = [True]

        def land(i, h):
            if i == 1 and slow[0]:
                slow[0] = False
                time.sleep(0.3)  # blocks past the drain deadline
            return h

        stats = execute_pipeline(
            4, lambda i: i, land,
            commit=lambda i, pulled: commits.append(i),
            drain_site="t.drain",
            replay=lambda lo, hi: replays.append((lo, hi)),
            window=2, watchdog_default_s=0.1,
        )
        time.sleep(0.4)  # let the abandoned worker finish its pull
        assert replays == [(1, 2)]
        assert commits == [0, 3]  # item 1 committed by nobody but replay
        assert stats.replays == 1

    def test_transient_without_replay_propagates(self):
        def land(i, h):
            raise TransientDeviceError("no replay path")

        with pytest.raises(TransientDeviceError):
            execute_pipeline(
                3, lambda i: i, land, drain_site="t.drain", window=2,
            )

    def test_fatal_launch_drains_completed_work_then_raises(self):
        landed = []

        def launch(i):
            if i == 3:
                raise RuntimeError("simulated device loss")
            return i

        with pytest.raises(RuntimeError, match="device loss"):
            execute_pipeline(
                6, launch, lambda i, h: landed.append(i),
                drain_site="t.drain", window=2,
            )
        # everything launched before the fatal error still lands —
        # the durable caller's snapshots become resume points
        assert landed == [0, 1, 2]

    def test_fatal_drain_error_wins_over_best_effort(self):
        def land(i, h):
            raise ValueError(f"bad land {i}")

        with pytest.raises(ValueError, match="bad land 0"):
            execute_pipeline(
                4, lambda i: i, land, drain_site="t.drain", window=2,
            )

    def test_empty_input(self):
        stats = execute_pipeline(
            0, lambda i: i, lambda i, h: None, drain_site="t.drain",
        )
        assert stats.launched == 0 and stats.landed == 0

    def test_drain_emits_stage_and_span(self):
        with telemetry.capture() as ev:
            execute_pipeline(
                2, lambda i: i, lambda i, h: None,
                drain_site="t.drain", window=1,
            )
        stages = [
            e for e in ev
            if e["event"] == "stream_stage"
            and e.get("stage") == "pipeline_drain"
        ]
        spans = [
            e for e in ev
            if e["event"] == "span"
            and e.get("name") == "stream.pipeline.drain"
        ]
        assert len(stages) == 2 and len(spans) == 2
        assert all(s["site"] == "t.drain" for s in stages)


class TestSnapshotWriter:
    def test_jobs_run_fifo_and_flush_is_a_barrier(self):
        done = []
        w = SnapshotWriter(name="t", maxsize=4)
        for i in range(6):
            w.submit(lambda i=i: done.append(i))
        w.flush()
        assert done == list(range(6))
        assert w.pending == 0
        w.close()

    def test_worker_adopts_telemetry_sinks(self):
        with telemetry.capture() as ev:
            w = SnapshotWriter(name="t")
            w.submit(lambda: telemetry.record("from_writer", ok=True))
            w.flush()
            w.close()
        assert any(e["event"] == "from_writer" for e in ev)

    def test_worker_shares_fault_budgets(self):
        # the plan list is SHARED (not copied): budget consumed on the
        # writer thread is visible to the caller — one budget, two
        # threads, exactly like an inline write
        with faults.transient_errors(1, sites=("t.site",)):
            w = SnapshotWriter(name="t")
            hits = []

            def job():
                try:
                    faults.maybe_fail("t.site")
                except TransientDeviceError:
                    hits.append(1)

            w.submit(job)
            w.flush()
            # budget of 1 was consumed by the writer thread
            faults.maybe_fail("t.site")  # must NOT raise
            w.close()
        assert hits == [1]

    def test_job_error_held_and_reraised_on_flush(self):
        w = SnapshotWriter(name="t")
        w.submit(lambda: (_ for _ in ()).throw(OSError("disk on fire")))
        with pytest.raises(OSError, match="disk on fire"):
            w.flush()
        # the error does not re-raise twice
        w.flush()
        w.close()

    def test_submit_after_close_raises(self):
        w = SnapshotWriter(name="t")
        w.close()
        with pytest.raises(RuntimeError, match="closed"):
            w.submit(lambda: None)

    def test_close_noflush_abandons_queued_jobs(self):
        # flush=False is the fatal-unwind path: queued jobs must NOT
        # run (the STOP marker may not queue FIFO behind them) and
        # close must not block on a full queue
        gate = threading.Event()
        ran = []
        w = SnapshotWriter(name="t", maxsize=2)
        w.submit(gate.wait)  # occupies the worker
        w.submit(lambda: ran.append(1))
        w.submit(lambda: ran.append(2))  # queue now full

        def release():
            time.sleep(0.05)
            gate.set()

        threading.Thread(target=release).start()  # lint: thread-context-adoption-ok (test timer thread: only sets an Event, records nothing)
        w.close(flush=False)
        assert ran == []
        assert w.pending == 0

    def test_backpressure_blocks_submit(self):
        gate = threading.Event()
        w = SnapshotWriter(name="t", maxsize=1)
        w.submit(gate.wait)  # occupies the worker
        w.submit(lambda: None)  # fills the queue
        t0 = time.perf_counter()

        def release():
            time.sleep(0.05)
            gate.set()

        threading.Thread(target=release).start()  # lint: thread-context-adoption-ok (test timer thread: only sets an Event, records nothing)
        w.submit(lambda: None)  # must block until the worker drains
        assert time.perf_counter() - t0 >= 0.04
        w.close()
