"""Mesh-sharded SpatialKNN vs the single-device model.

Runs on the virtual 8-device CPU mesh (conftest) — the same evidence
standard as tests/test_dist_join.py. Reference analog: SpatialKNN is the
reference's showcase distributed model (`models/knn/SpatialKNN.scala:
202-235`); here the per-iteration pair batch shards over the mesh.
"""

import numpy as np
import pytest

from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.functions.formats import st_point
from mosaic_tpu.models.knn import SpatialKNN
from mosaic_tpu.parallel.dist_join import make_mesh

RES = 7
BBOX = (-74.05, 40.60, -73.85, 40.78)


def _points(n, seed):
    rng = np.random.default_rng(seed)
    xy = rng.uniform((BBOX[0], BBOX[1]), (BBOX[2], BBOX[3]), (n, 2))
    return st_point(xy[:, 0], xy[:, 1]), xy


@pytest.mark.parametrize("n_devices", [2, 8])
def test_mesh_knn_equals_single_device(devices, n_devices):
    h3 = H3IndexSystem()
    lm, _ = _points(9, seed=1)  # 9 landmarks: pair batches hit padding
    cd, _ = _points(57, seed=2)
    args = dict(index=h3, resolution=RES, k_neighbours=4, max_iterations=8)
    r1 = SpatialKNN(**args).transform(lm, cd)
    rm = SpatialKNN(mesh=make_mesh(n_devices), **args).transform(lm, cd)
    np.testing.assert_array_equal(rm.landmark_id, r1.landmark_id)
    np.testing.assert_array_equal(rm.candidate_id, r1.candidate_id)
    np.testing.assert_array_equal(rm.rank, r1.rank)
    np.testing.assert_allclose(rm.distance, r1.distance, rtol=0, atol=1e-12)
    assert rm.metrics["match_count"] == r1.metrics["match_count"]


def test_knn_cache_stats_and_clear(devices):
    from mosaic_tpu.parallel.dist_knn import (
        clear_knn_caches, knn_cache_stats,
    )
    from mosaic_tpu.runtime import telemetry

    h3 = H3IndexSystem()
    lm, _ = _points(5, seed=3)
    cd, _ = _points(33, seed=4)
    clear_knn_caches()
    with telemetry.capture() as events:
        SpatialKNN(
            index=h3, resolution=RES, k_neighbours=3, max_iterations=6,
            mesh=make_mesh(2),
        ).transform(lm, cd)
        stats = knn_cache_stats()
    dist = stats["sharded_distance"]
    assert dist["currsize"] == 1  # one mesh -> one cached program
    assert dist["maxsize"] == 8   # bounded (was maxsize=None)
    assert dist["hits"] >= 1      # ring iterations share the program
    assert any(e["event"] == "knn_cache_stats" for e in events)

    with telemetry.capture() as events:
        pre = clear_knn_caches()
    assert pre["sharded_distance"]["currsize"] == 1
    assert knn_cache_stats(emit=False)["sharded_distance"]["currsize"] == 0
    assert any(e["event"] == "knn_caches_cleared" for e in events)


def test_mesh_knn_matches_bruteforce(devices):
    h3 = H3IndexSystem()
    lm, lxy = _points(7, seed=5)
    cd, cxy = _points(64, seed=6)
    k = 3
    r = SpatialKNN(
        index=h3, resolution=RES, k_neighbours=k, max_iterations=12,
        approximate=False, mesh=make_mesh(8),
    ).transform(lm, cd)
    d = np.linalg.norm(lxy[:, None, :] - cxy[None, :, :], axis=2)
    for i in range(7):
        want = np.argsort(d[i], kind="stable")[:k]
        got = r.candidate_id[r.landmark_id == i]
        order = np.argsort(r.rank[r.landmark_id == i])
        np.testing.assert_array_equal(got[order], want)
