"""Ship2Ship-transfer workload analog: buffered AIS tracks -> overlay join.

Reference analog: `notebooks/examples/python/Ship2ShipTransfers/` — vessel
ping linestrings are buffered (ST_Buffer), indexed, and candidate vessel
pairs whose buffered corridors intersect are detected with the
cell-indexed join. Here: synthetic tracks -> st_buffer -> intersects_join,
verified against the dense oracle matrix.
"""

import numpy as np

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.functions import geometry as F
from mosaic_tpu.sql.overlay import intersects_join

from fixtures import oracle_pairs


def _tracks(n, seed):
    """n jittered great-circle-ish linestrings around the North Sea."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.uniform(2.0, 4.0)
        y = rng.uniform(51.0, 53.0)
        hdg = rng.uniform(0, 2 * np.pi)
        pts = []
        for k in range(6):
            pts.append(f"{x:.6f} {y:.6f}")
            x += 0.08 * np.cos(hdg) + rng.normal(0, 0.01)
            y += 0.08 * np.sin(hdg) + rng.normal(0, 0.01)
        out.append("LINESTRING (" + ", ".join(pts) + ")")
    return out


def test_ship2ship_corridor_join():
    tracks_a = _tracks(8, seed=3)
    tracks_b = _tracks(8, seed=9)
    # ~500 m corridors in degree units; packed input keeps st_buffer's
    # output packed (no WKT round trip)
    buf_a = F.st_buffer(wkt.from_wkt(tracks_a), 0.005)
    buf_b = F.st_buffer(wkt.from_wkt(tracks_b), 0.005)

    got = intersects_join(buf_a, buf_b, H3IndexSystem(), 7)
    want = oracle_pairs(buf_a, buf_b)
    np.testing.assert_array_equal(got, want)
    assert want.shape[0] > 0  # the region is dense enough to overlap


def test_buffered_track_area_positive():
    buf = F.st_buffer(wkt.from_wkt(_tracks(3, seed=1)), 0.01)
    areas = F.st_area(buf, backend="oracle")
    assert (areas > 0).all()
    # corridor area ~ 2 * r * length (+ caps); sanity-bound it
    lengths = F.st_length(wkt.from_wkt(_tracks(3, seed=1)), backend="oracle")
    lo = 2 * 0.01 * lengths
    assert (areas > 0.9 * lo).all() and (areas < 2.0 * lo + 0.01).all()
