"""docs/QUICKSTART.md is executable documentation: every fenced python
block runs here, in order, in one shared namespace — the doc cannot
drift from the library. (Reference analog: the QuickstartNotebook is the
reference's living example of the same workflow.)"""

import re
from pathlib import Path

import pytest

DOC = Path(__file__).parent.parent / "docs" / "QUICKSTART.md"


def _blocks():
    text = DOC.read_text()
    return re.findall(r"```python\n(.*?)```", text, re.DOTALL)


def test_quickstart_blocks_execute():
    blocks = _blocks()
    assert len(blocks) >= 6
    ns: dict = {}
    for i, src in enumerate(blocks):
        try:
            exec(compile(src, f"{DOC.name}[block {i}]", "exec"), ns)
        except Exception as e:  # pragma: no cover - failure reporting
            pytest.fail(f"QUICKSTART block {i} failed: {e}\n---\n{src}")
