"""WKT1 CRS parsing (.prj sidecars) — `core/crs_wkt.py`.

Reference analog: proj4j resolves arbitrary CRS text for
`MosaicGeometry.transformCRSXY` (`core/geometry/MosaicGeometry.scala:
102-128`); here WKT lowers to a PROJ string for the native CRS engine.
"""

import numpy as np
import pytest

from mosaic_tpu.core.crs import to_wgs84
from mosaic_tpu.core.crs_proj import crs_from_wgs84, crs_to_wgs84, lookup
from mosaic_tpu.core.crs_wkt import (
    parse_crs_wkt,
    register_prj_text,
    srid_of_wkt,
    wkt_to_proj_string,
)

BNG = (
    'PROJCS["OSGB 1936 / British National Grid",GEOGCS["OSGB 1936",'
    'DATUM["OSGB_1936",SPHEROID["Airy 1830",6377563.396,299.3249646],'
    "TOWGS84[446.448,-125.157,542.06,0.15,0.247,0.842,-20.489]],"
    'PRIMEM["Greenwich",0],UNIT["degree",0.0174532925199433]],'
    'PROJECTION["Transverse_Mercator"],'
    'PARAMETER["latitude_of_origin",49],PARAMETER["central_meridian",-2],'
    'PARAMETER["scale_factor",0.9996012717],'
    'PARAMETER["false_easting",400000],PARAMETER["false_northing",-100000],'
    'UNIT["metre",1],AUTHORITY["EPSG","27700"]]'
)

TX_FEET = (
    'PROJCS["NAD_1983_StatePlane_Texas_Central_FIPS_4203_Feet",'
    'GEOGCS["GCS_North_American_1983",DATUM["D_North_American_1983",'
    'SPHEROID["GRS_1980",6378137.0,298.257222101]],PRIMEM["Greenwich",0.0],'
    'UNIT["Degree",0.0174532925199433]],'
    'PROJECTION["Lambert_Conformal_Conic"],'
    'PARAMETER["False_Easting",2296583.333333333],'
    'PARAMETER["False_Northing",9842500.0],'
    'PARAMETER["Central_Meridian",-100.333333333333],'
    'PARAMETER["Standard_Parallel_1",30.1166666666667],'
    'PARAMETER["Standard_Parallel_2",31.8833333333333],'
    'PARAMETER["Latitude_Of_Origin",29.6666666666667],'
    'UNIT["Foot_US",0.3048006096012192]]'
)

WEB_MERC = (
    'PROJCS["WGS_1984_Web_Mercator_Auxiliary_Sphere",GEOGCS["GCS_WGS_1984",'
    'DATUM["D_WGS_1984",SPHEROID["WGS_1984",6378137.0,298.257223563]],'
    'PRIMEM["Greenwich",0.0],UNIT["Degree",0.0174532925199433]],'
    'PROJECTION["Mercator_Auxiliary_Sphere"],PARAMETER["False_Easting",0.0],'
    'PARAMETER["False_Northing",0.0],PARAMETER["Central_Meridian",0.0],'
    'PARAMETER["Standard_Parallel_1",0.0],'
    'PARAMETER["Auxiliary_Sphere_Type",0.0],UNIT["Meter",1.0]]'
)


def test_bng_wkt_matches_builtin_27700():
    assert srid_of_wkt(BNG) == 27700
    crs = parse_crs_wkt(BNG)
    pt = np.array([[529090.0, 181680.0]])  # central London
    a = np.asarray(crs_to_wgs84(crs, pt))
    b = np.asarray(to_wgs84(pt, 27700))
    assert np.abs(a - b).max() < 2e-6  # ~0.2 m: same datum shift + tmerc


def test_esri_feet_state_plane_registers_synthetic():
    srid = register_prj_text(TX_FEET)
    assert lookup(srid) is not None
    crs = lookup(srid)
    xy = np.asarray(crs_from_wgs84(crs, np.array([[-97.74, 30.27]])))
    back = np.asarray(crs_to_wgs84(crs, xy))
    np.testing.assert_allclose(back, [[-97.74, 30.27]], atol=1e-8)
    assert 2.8e6 < xy[0, 0] < 3.4e6  # Austin easting lands in US feet
    # same WKT -> same synthetic code (stable)
    assert register_prj_text(TX_FEET) == srid


def test_web_mercator_auxiliary_sphere_is_spherical():
    crs = parse_crs_wkt(WEB_MERC)
    xy = np.asarray(crs_from_wgs84(crs, np.array([[-74.0, 40.7]])))
    # decode through the builtin spherical 3857
    back = np.asarray(to_wgs84(xy, 3857))
    assert np.abs(back - [[-74.0, 40.7]]).max() < 1e-6


def test_geogcs_only_is_longlat():
    s = wkt_to_proj_string(
        'GEOGCS["GCS_WGS_1984",DATUM["D_WGS_1984",SPHEROID["WGS_1984",'
        '6378137.0,298.257223563]],PRIMEM["Greenwich",0.0],'
        'UNIT["Degree",0.0174532925199433]]'
    )
    assert s.startswith("+proj=longlat")


def test_unknown_projection_raises():
    bad = BNG.replace("Transverse_Mercator", "Space_Oblique_Mercator")
    with pytest.raises(ValueError, match="unsupported PROJECTION"):
        wkt_to_proj_string(bad)


def test_prj_sidecar_drives_shapefile_srid(tmp_path):
    from mosaic_tpu.core.geometry import wkt as wktmod
    from mosaic_tpu.readers.vector import (
        VectorTable,
        read_shapefile,
        write_shapefile,
    )

    col = wktmod.from_wkt(["POINT (529090 181680)"])
    t = VectorTable(geometry=col, columns={})
    p = tmp_path / "uk.shp"
    write_shapefile(str(p), t, srid=27700)
    r = read_shapefile(str(p))
    assert int(r.geometry.srid[0]) == 27700


GRADS_POLAR = (
    'PROJCS["South Pole Stereo (grads)",GEOGCS["GCS_Sphere_Grads",'
    'DATUM["D_Sphere",SPHEROID["Sphere",6371000.0,0.0]],'
    'PRIMEM["Greenwich",0.0],UNIT["Grad",0.015707963267948967]],'
    'PROJECTION["Polar_Stereographic"],'
    'PARAMETER["Central_Meridian",0.0],'
    'PARAMETER["Standard_Parallel_1",-80.0],'
    'PARAMETER["False_Easting",0.0],PARAMETER["False_Northing",0.0],'
    'UNIT["Metre",1.0]]'
)


def test_polar_stereographic_pole_in_grads_units():
    """The injected pole must be expressed in the CRS's angular unit
    BEFORE the unit scaling: a raw 90.0 in a grads .prj used to scale to
    81 deg and place the projection center off the pole."""
    s = wkt_to_proj_string(GRADS_POLAR)
    assert "+proj=stere" in s
    params = dict(
        p[1:].split("=") for p in s.split() if p.startswith("+") and "=" in p
    )
    # lat_0 lands at the true pole in degrees (-80 grads -> south)
    np.testing.assert_allclose(float(params["lat_0"]), -90.0, atol=1e-12)
    # the standard parallel scales grads -> degrees: -80 grads = -72 deg
    np.testing.assert_allclose(float(params["lat_ts"]), -72.0, atol=1e-9)


def test_polar_stereographic_degree_pole_unchanged():
    """Degree-unit .prj keeps the existing behavior (regression guard)."""
    deg = GRADS_POLAR.replace(
        'UNIT["Grad",0.015707963267948967]',
        'UNIT["Degree",0.0174532925199433]',
    ).replace('PARAMETER["Standard_Parallel_1",-80.0]',
              'PARAMETER["Standard_Parallel_1",-71.0]')
    s = wkt_to_proj_string(deg)
    params = dict(
        p[1:].split("=") for p in s.split() if p.startswith("+") and "=" in p
    )
    np.testing.assert_allclose(float(params["lat_0"]), -90.0, atol=1e-12)
    np.testing.assert_allclose(float(params["lat_ts"]), -71.0, atol=1e-12)
