"""Services: SpatialKNN, analyzer, MosaicFrame, checkpoints, iteration.

KNN correctness oracle: brute-force pairwise distances in f64 numpy over
small synthetic landmark/candidate sets — the grid-ring result (exact mode)
must produce identical neighbour sets; approximate mode must produce k
matches with non-decreasing distances.
"""

import numpy as np
import pytest

from mosaic_tpu import MosaicContext
from mosaic_tpu import functions as F
from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.datasets import random_points, synthetic_zones
from mosaic_tpu.models import CheckpointManager, IterativeTransformer, SpatialKNN
from mosaic_tpu.sql.analyzer import MosaicAnalyzer, SampleStrategy
from mosaic_tpu.sql.frame import MosaicFrame


@pytest.fixture(autouse=True)
def _fresh_context():
    MosaicContext.reset()
    yield
    MosaicContext.reset()


# -------------------------------------------------------------- checkpoints


def test_checkpoint_manager(tmp_path):
    ck = CheckpointManager(str(tmp_path / "ck"))
    t1 = {"a": np.arange(3), "b": np.ones(3)}
    ck.append(t1)
    assert ck.load()["a"].tolist() == [0, 1, 2]
    ck.append({"a": np.arange(3, 5), "b": np.zeros(2)})
    assert ck.load()["a"].tolist() == [0, 1, 2, 3, 4]
    ck.overwrite({"a": np.array([9]), "b": np.array([9.0])})
    assert ck.load()["a"].tolist() == [9]
    ck.write_meta({"k": 5})
    assert ck.read_meta()["k"] == 5
    ck.delete()
    assert not (tmp_path / "ck").exists()


def test_iterative_transformer():
    steps = []

    def step(state, i):
        steps.append(i)
        return state + i

    it = IterativeTransformer(
        step, should_stop=lambda prev, cur: cur >= 6, max_iterations=10
    )
    out = it.iterate(0)
    assert out == 6  # 0+1+2+3
    assert it.iterations_run == 3


# ----------------------------------------------------------------- analyzer


def test_analyzer_resolution():
    idx = H3IndexSystem()
    zones = synthetic_zones(4, 4, bbox=(-74.05, 40.60, -73.85, 40.78))
    res = MosaicAnalyzer(idx).get_optimal_resolution(zones)
    assert res in idx.resolutions()
    # a typical zone should span roughly target_cells cells at that res
    from mosaic_tpu.core.geometry import oracle

    med_area = np.median(oracle.area(zones))
    ratio = med_area / idx.cell_area_approx(res)
    assert 4 <= ratio <= 1024  # within ~half/double of the 64-cell target band

    metrics = MosaicAnalyzer(idx).get_resolution_metrics(zones)
    assert res in metrics and "p50_cells" in metrics[res]
    s = SampleStrategy(fraction=0.5, limit=4)
    assert MosaicAnalyzer(idx).get_optimal_resolution(zones, sample=s) in idx.resolutions()


def test_analyzer_reference_golden_nyc():
    """The reference-recipe analyzer pinned to the resolution the
    reference's `MosaicAnalyzer.getOptimalResolution` yields on its own
    NYC taxi-zone fixture (hand-derived from `MosaicAnalyzer.scala:28-39`:
    surviving band rows are res 8/9/10 with p50 cells-per-geometry ratios
    1.91 / 13.3 / 93.4; the median-by-p50 row is resolution 9)."""
    import os

    import pytest

    fixture = "/root/reference/src/test/resources/NYC_Taxi_Zones.geojson"
    if not os.path.exists(fixture):
        pytest.skip("reference NYC fixture unavailable")
    from mosaic_tpu.readers.vector import read_geojson

    zones = read_geojson(fixture).geometry
    idx = H3IndexSystem()
    got = MosaicAnalyzer(idx).get_optimal_resolution_reference(zones)
    assert got == 9


# -------------------------------------------------------------- MosaicFrame


def test_mosaic_frame_join():
    zones = synthetic_zones(3, 3, bbox=(-74.05, 40.60, -73.85, 40.78))
    names = np.array([f"z{i}" for i in range(len(zones))], dtype=object)
    polys = MosaicFrame.from_geometry(zones, name=names, code=np.arange(len(zones)))
    pts = random_points(500, bbox=(-74.05, 40.60, -73.85, 40.78), seed=5)
    points = MosaicFrame.from_geometry(
        F.st_point(pts[:, 0], pts[:, 1]), pid=np.arange(500)
    )
    joined = polys.point_in_polygon_join(points, resolution=8)
    assert joined["polygon_row"].shape == (500,)
    hit = joined["polygon_row"] >= 0
    assert hit.mean() > 0.5
    # joined attributes line up with the matched polygon
    for i in np.nonzero(hit)[0][:20]:
        assert joined["polygon_name"][i] == f"z{joined['polygon_row'][i]}"
    # oracle check on a few points
    from mosaic_tpu.core.geometry import oracle

    for i in range(0, 100, 7):
        row = joined["polygon_row"][i]
        if row >= 0:
            assert oracle.point_in_polygon(zones, int(row), pts[i])


def test_mosaic_frame_utils():
    zones = synthetic_zones(2, 2, bbox=(-74.0, 40.6, -73.9, 40.7))
    f = MosaicFrame.from_geometry(zones, name=np.array(["a", "b", "c", "d"], dtype=object))
    assert len(f) == 4
    res = f.get_optimal_resolution(H3IndexSystem())
    fi = f.set_index_resolution(res, index=H3IndexSystem())
    assert fi.chips is not None and len(fi.chips) > 0
    s = f.prettified(2)
    assert "geometry" in s and "name" in s


# ---------------------------------------------------------------------- KNN


def _knn_oracle(land_pts, cand_pts, k):
    """Brute-force k nearest candidate ids per landmark (point-point)."""
    d = np.linalg.norm(land_pts[:, None, :] - cand_pts[None, :, :], axis=-1)
    order = np.argsort(d, axis=1, kind="stable")[:, :k]
    return order, np.take_along_axis(d, order, axis=1)


def test_spatial_knn_exact_points():
    rng = np.random.default_rng(11)
    bbox = (-74.05, 40.60, -73.85, 40.78)
    land_pts = random_points(12, bbox=bbox, seed=1)
    cand_pts = random_points(80, bbox=bbox, seed=2)
    land = F.st_point(land_pts[:, 0], land_pts[:, 1])
    cand = F.st_point(cand_pts[:, 0], cand_pts[:, 1])
    knn = SpatialKNN(
        index=H3IndexSystem(), resolution=8, k_neighbours=3,
        max_iterations=30, approximate=False,
    )
    res = knn.transform(land, cand)
    want_ids, want_d = _knn_oracle(land_pts, cand_pts, 3)
    assert res.metrics["complete_landmarks"] == 12
    for i in range(12):
        got = res.candidate_id[res.landmark_id == i]
        got_d = res.distance[res.landmark_id == i]
        assert got.shape == (3,)
        np.testing.assert_allclose(np.sort(got_d), got_d)  # ranked
        np.testing.assert_allclose(got_d, want_d[i], atol=1e-5)
        assert set(got) == set(want_ids[i])


def test_spatial_knn_polygons_and_checkpoint(tmp_path):
    bbox = (-74.05, 40.60, -73.85, 40.78)
    zones = synthetic_zones(4, 4, bbox=bbox)
    land_pts = random_points(5, bbox=bbox, seed=3)
    land = F.st_point(land_pts[:, 0], land_pts[:, 1])
    knn = SpatialKNN(
        index=H3IndexSystem(), resolution=8, k_neighbours=2,
        max_iterations=25, approximate=False,
        checkpoint_dir=str(tmp_path / "knn_ck"),
    )
    res = knn.transform(land, zones)
    assert res.metrics["complete_landmarks"] == 5
    # nearest polygon distance 0 when the point is inside a zone
    from mosaic_tpu.sql.join import pip_join

    inside = pip_join(land_pts, zones, H3IndexSystem(), 8)
    for i in range(5):
        d1 = res.distance[(res.landmark_id == i) & (res.rank == 1)]
        if inside[i] >= 0:
            assert d1[0] == pytest.approx(0.0, abs=1e-6)
    # checkpoint recorded iterations
    ck = CheckpointManager(str(tmp_path / "knn_ck"))
    log = ck.load()
    assert "iteration" in log and log["iteration"].max() >= 1
    assert ck.read_meta()["match_count"] == res.metrics["match_count"]


def test_spatial_knn_threshold_and_early_stop():
    bbox = (-74.05, 40.60, -73.85, 40.78)
    land = F.st_point(np.array([-74.0]), np.array([40.7]))
    cand_pts = random_points(50, bbox=bbox, seed=9)
    cand = F.st_point(cand_pts[:, 0], cand_pts[:, 1])
    knn = SpatialKNN(
        index=H3IndexSystem(), resolution=8, k_neighbours=50,
        max_iterations=6, early_stop_iterations=2,
        distance_threshold=0.01,
    )
    res = knn.transform(land, cand)
    assert (res.distance <= 0.01).all()
    d = np.linalg.norm(cand_pts - np.array([-74.0, 40.7]), axis=-1)
    assert res.metrics["match_count"] <= int((d <= 0.01).sum())


def test_binary_transformer_threads_right_side(tmp_path):
    """Reference: `models/core/BinaryTransformer.scala` — fixed right table
    joined against an evolving left state each iteration."""
    import numpy as np

    from mosaic_tpu.models import BinaryTransformer, CheckpointManager

    right = np.asarray([1.0, 2.0, 3.0])

    def join_step(left, r, i):
        return left + r.sum()  # each iteration folds the right side in

    ck = CheckpointManager(str(tmp_path / "bt"))
    bt = BinaryTransformer(
        join_step,
        should_stop=lambda prev, cur: cur >= 18,
        max_iterations=10,
        right=right,
        checkpoint=ck,
    )
    out = bt.transform(0.0)
    assert out == 18.0 and bt.iterations_run == 3


def test_r_bindings_generated_and_complete():
    """The generated R package must cover every registered name
    (reference analog: R/generate_R_bindings.R output)."""
    import os
    import re

    import mosaic_tpu

    path = os.path.join(
        os.path.dirname(os.path.dirname(__file__)), "R", "mosaicTpu", "R",
        "functions.R",
    )
    assert os.path.exists(path), "run tools/generate_r_bindings.py"
    src = open(path).read()
    exported = set(re.findall(r"^([A-Za-z_0-9]+) <- function", src, re.M))
    registered = set(mosaic_tpu.MosaicContext.build("H3").register())
    missing = registered - exported
    assert not missing, f"R bindings missing: {sorted(missing)}"
    stale = exported - registered - {"enableMosaic"}
    assert not stale, f"stale R bindings for removed names: {sorted(stale)}"
