"""Tessellation engine tests.

Mirrors the reference's MosaicExplode/MosaicFill behavior suites
(`expressions/index/MosaicExplodeBehaviors.scala`) with exact invariants:
area conservation, core-chip containment, centroid-rule polyfill, line
length conservation — across the index-system matrix (SURVEY.md §4).
"""

import numpy as np
import pytest

from mosaic_tpu.core import tessellate as tz
from mosaic_tpu.core.geometry import oracle, wkt
from mosaic_tpu.core.index import BNG, H3, CustomIndexSystem, GridConf

CUSTOM = CustomIndexSystem(
    GridConf(-180, 180, -90, 90, 2, 10.0, 10.0)
)  # reference test grid: CustomIndexSystem(GridConf(-180,180,-90,90,2,360,180))

POLY = "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))"
POLY_HOLE = "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1), (5 5, 5 8, 8 8, 8 5, 5 5))"
MULTIPOLY = "MULTIPOLYGON (((0 0, 6 0, 6 6, 0 6, 0 0)), ((20 20, 26 20, 26 27, 20 27, 20 20)))"
LINE = "LINESTRING (0 0, 9 4, 14 -3, 21 8)"


def chip_areas(table: tz.ChipTable) -> np.ndarray:
    return oracle.area(table.chips)


class TestPolygonTessellation:
    @pytest.mark.parametrize("res", [2, 3])
    @pytest.mark.parametrize("w", [POLY, POLY_HOLE, MULTIPOLY])
    def test_area_conserved_custom(self, w, res):
        col = wkt.from_wkt([w])
        table = tz.tessellate(col, CUSTOM, res)
        assert len(table) > 0
        total = chip_areas(table).sum()
        np.testing.assert_allclose(total, oracle.area(col)[0], rtol=1e-9)

    def test_core_and_border_present(self):
        col = wkt.from_wkt([POLY])
        table = tz.tessellate(col, CUSTOM, 3)
        assert table.core_count() > 0
        assert (~table.is_core).sum() > 0
        # no duplicate cells per geometry
        assert len(np.unique(table.cell_id)) == len(table)

    def test_core_chips_fully_inside(self):
        col = wkt.from_wkt([POLY_HOLE])
        table = tz.tessellate(col, CUSTOM, 3)
        rng = np.random.default_rng(1)
        bb = table.chips.bounds()
        for i in np.nonzero(table.is_core)[0]:
            pts = np.column_stack(
                [
                    rng.uniform(bb[i, 0], bb[i, 2], 64),
                    rng.uniform(bb[i, 1], bb[i, 3], 64),
                ]
            )
            inside = oracle.contains_points(col, 0, pts)
            assert inside.all(), f"core chip {i} leaks outside the polygon"

    def test_border_chips_subset_of_cell_and_geom(self):
        col = wkt.from_wkt([POLY])
        table = tz.tessellate(col, CUSTOM, 3)
        border = np.nonzero(~table.is_core)[0]
        assert border.size
        for i in border[:8]:
            # border chip area strictly less than the cell area
            cell_area = CUSTOM.cell_area_approx(3)
            assert chip_areas(table)[i] < cell_area + 1e-9

    def test_keep_core_geoms_false(self):
        col = wkt.from_wkt([POLY])
        t1 = tz.tessellate(col, CUSTOM, 3, keep_core_geoms=False)
        assert not t1.has_geom[t1.is_core].any()
        assert t1.has_geom[~t1.is_core].all()

    def test_hole_respected(self):
        col = wkt.from_wkt([POLY_HOLE])
        table = tz.tessellate(col, CUSTOM, 4)
        # a cell entirely inside the hole must not appear
        centers = np.asarray(CUSTOM.cell_center(table.cell_id))
        hole_interior = (
            (centers[:, 0] > 5.6)
            & (centers[:, 0] < 7.4)
            & (centers[:, 1] > 5.6)
            & (centers[:, 1] < 7.4)
            & table.is_core
        )
        assert not hole_interior.any()

    def test_multi_geometry_ids(self):
        col = wkt.from_wkt([POLY, MULTIPOLY])
        table = tz.tessellate(col, CUSTOM, 3)
        assert set(np.unique(table.geom_id)) == {0, 1}
        a = chip_areas(table)
        np.testing.assert_allclose(
            [a[table.geom_id == 0].sum(), a[table.geom_id == 1].sum()],
            oracle.area(col),
            rtol=1e-9,
        )


class TestPolygonH3BNG:
    def test_area_conserved_h3(self):
        w = "POLYGON ((-73.98 40.75, -73.94 40.75, -73.94 40.78, -73.98 40.78, -73.98 40.75))"
        col = wkt.from_wkt([w])
        table = tz.tessellate(col, H3, 9)
        assert table.core_count() > 0
        total = chip_areas(table).sum()
        # H3 hexagons in lat/lng are near- but not exactly convex: loose tol
        np.testing.assert_allclose(total, oracle.area(col)[0], rtol=1e-3)

    def test_area_conserved_bng(self):
        w = "POLYGON ((216000 771000, 219500 771400, 219000 774800, 216200 774000, 216000 771000))"
        col = wkt.from_wkt([w], srid=27700)
        table = tz.tessellate(col, BNG, 4)
        assert table.core_count() > 0
        np.testing.assert_allclose(
            chip_areas(table).sum(), oracle.area(col)[0], rtol=1e-9
        )


class TestBatchClipper:
    def test_comb_ring_buffer_growth(self):
        # regression: a concave "comb" ring crossing one clip half-plane in
        # many excursions overflows any small static output buffer — the
        # batched Sutherland-Hodgman must grow to the true output size
        teeth = 12
        xs, ys = [], []
        for t in range(teeth):
            x0 = t / teeth
            x1 = (t + 0.45) / teeth
            xs += [x0, x0, x1, x1]
            ys += [0.0, 1.0, 1.0, 0.0]
        ring = np.column_stack([np.asarray(xs), np.asarray(ys)])
        cell = np.array([[-1.0, 0.4], [2.0, 0.4], [2.0, 0.6], [-1.0, 0.6]])
        cells = cell[None, :, :]
        klen = np.asarray([4])
        out, olen = tz.clip_rings_convex_batch(ring, cells, klen)
        assert olen[0] >= 3
        # parity with the scalar clipper's area
        ref = tz.clip_ring_convex(ring, cell)
        from mosaic_tpu.core.types import ring_signed_area

        np.testing.assert_allclose(
            abs(ring_signed_area(out[0, : olen[0]])),
            abs(ring_signed_area(ref)),
            rtol=1e-9,
        )

    def test_batch_matches_scalar_on_hex_windows(self):
        rng = np.random.default_rng(5)
        ang = np.sort(rng.uniform(0, 2 * np.pi, 11))
        ring = np.column_stack([np.cos(ang), np.sin(ang)]) * rng.uniform(
            0.4, 1.2, 11
        )[:, None]
        hexa = np.column_stack(
            [np.cos(np.arange(6) * np.pi / 3), np.sin(np.arange(6) * np.pi / 3)]
        )
        windows = [hexa * s + o for s, o in [(0.5, 0.2), (1.0, -0.3), (0.25, 0.0)]]
        cells = np.stack(windows)
        klen = np.asarray([6, 6, 6])
        out, olen = tz.clip_rings_convex_batch(ring, cells, klen)
        from mosaic_tpu.core.types import ring_signed_area

        for t, w in enumerate(windows):
            ref = tz.clip_ring_convex(ring, w)
            a_ref = abs(ring_signed_area(ref)) if ref.shape[0] >= 3 else 0.0
            a_new = (
                abs(ring_signed_area(out[t, : olen[t]])) if olen[t] >= 3 else 0.0
            )
            np.testing.assert_allclose(a_new, a_ref, rtol=1e-9, atol=1e-12)


class TestLinePointChips:
    def test_line_length_conserved(self):
        col = wkt.from_wkt([LINE])
        table = tz.tessellate(col, CUSTOM, 3)
        assert not table.is_core.any()
        np.testing.assert_allclose(
            oracle.length(table.chips).sum(), oracle.length(col)[0], rtol=1e-9
        )

    def test_multiline(self):
        col = wkt.from_wkt(["MULTILINESTRING ((0 0, 9 4), (11 11, 14 -3))"])
        table = tz.tessellate(col, CUSTOM, 3)
        np.testing.assert_allclose(
            oracle.length(table.chips).sum(), oracle.length(col)[0], rtol=1e-9
        )

    def test_point_chip(self):
        col = wkt.from_wkt(["POINT (3 4)", "MULTIPOINT ((1 1), (15 15))"])
        table = tz.tessellate(col, CUSTOM, 3)
        assert len(table) == 3
        expected = np.asarray(
            CUSTOM.point_to_cell(np.array([[3.0, 4], [1, 1], [15, 15]]), 3)
        )
        np.testing.assert_array_equal(np.sort(table.cell_id), np.sort(expected))
        assert not table.is_core.any()


class TestPolyfill:
    @pytest.mark.parametrize("index,res,w", [
        (CUSTOM, 3, POLY),
        (CUSTOM, 4, POLY_HOLE),
        (H3, 8, "POLYGON ((-73.98 40.75, -73.94 40.75, -73.94 40.78, -73.98 40.78, -73.98 40.75))"),
    ])
    def test_centroid_rule(self, index, res, w):
        col = wkt.from_wkt([w])
        cells, offs = tz.polyfill(col, index, res)
        assert offs[-1] == cells.size and cells.size > 0
        centers = np.asarray(index.cell_center(cells), dtype=np.float64)
        inside = oracle.contains_points(col, 0, centers)
        assert inside.all()

    def test_polyfill_matches_tessellation_cover(self):
        col = wkt.from_wkt([POLY])
        cells, _ = tz.polyfill(col, CUSTOM, 3)
        table = tz.tessellate(col, CUSTOM, 3)
        # every polyfill cell appears in the tessellation cover
        assert np.isin(cells, table.cell_id).all()
        # every core cell's center is inside => core ⊆ polyfill
        core = table.cell_id[table.is_core]
        assert np.isin(core, cells).all()
