"""KNN-as-a-service contract (PR 19): served KNN answers are
bit-identical to the engine-less frontend, the batch `SpatialKNN`
model, and the brute-force f64 host oracle; KNN requests co-batch with
PIP traffic under one admission/deadline/shed budget; the Voronoi
convex fast path is exact; a hot swap mid-flight serves the old index
to completion — `mosaic_tpu/knn/` + the serve integration."""

import time

import numpy as np
import pytest

from mosaic_tpu import dispatch as _dispatch, functions as F
from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.knn import (
    KNNFrontend,
    brute_force_knn,
    build_knn_index,
    decode_knn,
)
from mosaic_tpu.runtime import faults
from mosaic_tpu.runtime.errors import Overloaded
from mosaic_tpu.serve import BucketLadder, ServeEngine
from mosaic_tpu.sql.join import build_chip_index

BBOX = (-25.0, -25.0, 35.0, 20.0)
RES = 3
#: small ladders so bucket boundaries are cheap to straddle in tests
ROWS = BucketLadder(8, 512)
PAIRS = BucketLadder(64, 4096)

PIP_ZONES = [
    "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))",
    "POLYGON ((-20 -20, -5 -20, -5 -5, -20 -5, -20 -20))",
    "POLYGON ((20 -10, 30 -10, 30 5, 20 5, 20 -10))",
]


def square_wkts(rng, n, side=(0.5, 1.5)):
    cx = rng.uniform(BBOX[0], BBOX[2], n)
    cy = rng.uniform(BBOX[1], BBOX[3], n)
    s = rng.uniform(*side, n)
    return [
        f"POLYGON(({x} {y}, {x + w} {y}, {x + w} {y + w},"
        f" {x} {y + w}, {x} {y}))"
        for x, y, w in zip(cx, cy, s)
    ], cx, cy


@pytest.fixture(scope="module")
def grid():
    return CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))


@pytest.fixture(scope="module")
def pip_index(grid):
    col = wkt.from_wkt(PIP_ZONES)
    return build_chip_index(tessellate(col, grid, RES, keep_core_geoms=False))


@pytest.fixture(scope="module")
def knn_problem(grid):
    """Dense convex candidates + a query sampler staying strictly inside
    the candidate bbox (the shift contract the bit-identity argument
    rests on)."""
    rng = np.random.default_rng(11)
    polys, cx, cy = square_wkts(rng, 100)
    cand = F.st_geomfromwkt(np.array(polys))
    kx = build_knn_index(cand, index_system=grid, resolution=RES)
    lo = np.array([cx.min(), cy.min()])
    hi = np.array([cx.max(), cy.max()])

    def qpts(n, seed):
        r = np.random.default_rng(seed)
        return lo + r.uniform(0.1, 0.9, (n, 2)) * (hi - lo)

    return cand, kx, qpts


@pytest.fixture(scope="module")
def frontend(knn_problem):
    _, kx, _ = knn_problem
    fe = KNNFrontend(kx, lane="ring", row_ladder=ROWS, pair_ladder=PAIRS)
    rep = fe.warmup()
    assert rep["signatures"] == len(ROWS.buckets) + len(PAIRS.buckets)
    return fe


@pytest.fixture(scope="module")
def engine(pip_index, grid, frontend):
    """One warmed mixed-traffic engine shared by the whole module (the
    pre-warmed frontend is adopted as-is, so engine warmup only adds the
    PIP rungs)."""
    eng = ServeEngine(
        pip_index, grid, RES, ladder=BucketLadder(64, 1024), bounds=BBOX,
        max_wait_s=0.05, knn=frontend, default_deadline_s=120.0,
    )
    eng.warmup()
    yield eng
    eng.close()


def oracle(kx, q, k):
    return brute_force_knn(q, kx, k)


class TestServedBitIdentity:
    def test_cobatched_equals_solo_equals_batch_equals_oracle(
        self, engine, frontend, knn_problem, grid
    ):
        """Concurrent KNN requests whose sizes straddle the row-bucket
        boundary, co-batched into ONE mixed batch, answer exactly the
        bits of (a) the engine-less frontend, (b) the batch `SpatialKNN`
        model run exact, and (c) the brute-force f64 host oracle —
        neighbour ranks AND distance bits."""
        from mosaic_tpu.models import SpatialKNN

        cand, kx, qpts = knn_problem
        k = 3
        sizes = (7, 8, 9)  # straddles the 8-row rung
        qs = [qpts(n, seed=40 + n) for n in sizes]
        futs = [engine.submit_knn(q, k) for q in qs]
        answers = [f.result(timeout=120) for f in futs]
        assert engine.metrics()["cold_compiles"] == 0

        allq = np.concatenate(qs)
        # (b) batch model, exact mode, early stopping disabled
        m = SpatialKNN(
            index=grid, resolution=RES, k_neighbours=k, max_iterations=60,
            early_stop_iterations=100, approximate=False,
        )
        res = m.transform(F.st_point(allq[:, 0], allq[:, 1]), cand)
        bids = np.full((allq.shape[0], k), -1, np.int64)
        bdist = np.full((allq.shape[0], k), np.inf)
        for li, ci, d, r in zip(
            res.landmark_id, res.candidate_id, res.distance, res.rank
        ):
            bids[li, r - 1] = ci
            bdist[li, r - 1] = d
        # (c) oracle
        oids, odist = oracle(kx, allq, k)
        np.testing.assert_array_equal(bids, oids)
        assert np.array_equal(bdist, odist)

        off = 0
        for q, a in zip(qs, answers):
            n = q.shape[0]
            # (a) engine-less frontend, solo dispatch
            out, _ = frontend.dispatch(q, k)
            sids, sdist = decode_knn(np.asarray(out), k)
            np.testing.assert_array_equal(a.ids, sids)
            assert np.array_equal(a.distance, sdist)
            np.testing.assert_array_equal(a.ids, oids[off : off + n])
            assert np.array_equal(a.distance, odist[off : off + n])
            off += n
        assert engine.metrics()["cold_compiles"] == 0

    def test_mixed_batch_leaves_pip_answers_bit_identical(
        self, engine, knn_problem
    ):
        """A KNN batchmate cannot perturb PIP answers: PIP rows co-batched
        with KNN traffic return exactly the solo bits."""
        _, kx, qpts = knn_problem
        rng = np.random.default_rng(4)
        ppts = rng.uniform(BBOX[:2], BBOX[2:], (90, 2))
        fp = engine.submit(ppts)
        fk = engine.submit_knn(qpts(5, seed=77), 2)
        pip_rows = np.asarray(fp.result(timeout=120))
        a = fk.result(timeout=120)
        solo = np.asarray(engine.join(ppts, timeout=120))
        np.testing.assert_array_equal(pip_rows, solo)
        oids, odist = oracle(kx, qpts(5, seed=77), 2)
        np.testing.assert_array_equal(a.ids, oids)
        assert np.array_equal(a.distance, odist)


class TestVoronoiLane:
    def test_voronoi_equals_ring_on_convex_fixture(
        self, frontend, knn_problem
    ):
        """The Voronoi one-shot cover is EXACT: same pair programs, same
        merge — identical ids and distance bits to ring expansion on the
        all-convex fixture, with the one-dispatch lane actually taken."""
        _, kx, qpts = knn_problem
        fv = KNNFrontend(
            kx, lane="voronoi", row_ladder=ROWS, pair_ladder=PAIRS
        )
        fv.warmup()
        q = qpts(11, seed=9)
        out_r, _ = frontend.dispatch(q, 4)
        out_v, _ = fv.dispatch(q, 4)
        np.testing.assert_array_equal(np.asarray(out_v), np.asarray(out_r))
        assert fv.stats["lane_voronoi"] == 11

    def test_voronoi_equals_ring_on_mixed_fixture(self, grid):
        """Concave candidates break the convex-walk guarantee for some
        queries — those fall back to ring expansion per query, and the
        answers stay bit-identical to the pure ring lane."""
        rng = np.random.default_rng(5)
        polys, _, _ = square_wkts(rng, 40)
        # L-shaped (concave) candidates interleaved with the squares
        for i in range(12):
            x = float(rng.uniform(BBOX[0], BBOX[2] - 3))
            y = float(rng.uniform(BBOX[1], BBOX[3] - 3))
            polys.append(
                f"POLYGON(({x} {y}, {x + 2} {y}, {x + 2} {y + 0.6},"
                f" {x + 0.6} {y + 0.6}, {x + 0.6} {y + 2},"
                f" {x} {y + 2}, {x} {y}))"
            )
        cand = F.st_geomfromwkt(np.array(polys))
        kxm = build_knn_index(cand, index_system=grid, resolution=RES)
        fr = KNNFrontend(kxm, lane="ring", row_ladder=ROWS,
                         pair_ladder=PAIRS)
        fv = KNNFrontend(kxm, lane="voronoi", row_ladder=ROWS,
                         pair_ladder=PAIRS)
        fr.warmup()
        fv.warmup()
        q = np.stack([
            np.random.default_rng(8).uniform(BBOX[0] + 5, BBOX[2] - 5, 9),
            np.random.default_rng(9).uniform(BBOX[1] + 5, BBOX[3] - 5, 9),
        ], axis=1)
        out_r, _ = fr.dispatch(q, 3)
        out_v, _ = fv.dispatch(q, 3)
        np.testing.assert_array_equal(np.asarray(out_v), np.asarray(out_r))


class TestDeadlinesAndQuarantine:
    def test_stalled_knn_sheds_only_the_late_request(
        self, engine, knn_problem
    ):
        """A stall inside the KNN dispatch makes the tight-deadline KNN
        request late; it is shed (typed Overloaded) while its slack PIP
        batchmate keeps its exact result."""
        _, kx, qpts = knn_problem
        rng = np.random.default_rng(6)
        ppts = rng.uniform(BBOX[:2], BBOX[2:], (40, 2))
        shed_before = engine.metrics()["shed_deadline"]
        with faults.stalls(0.8, n=1, sites=("knn.distance",)):
            f_knn = engine.submit_knn(qpts(4, seed=3), 2, deadline_s=0.4)
            f_pip = engine.submit(ppts, deadline_s=60.0)
            with pytest.raises(Overloaded) as exc:
                f_knn.result(timeout=120)
            assert exc.value.reason == "deadline"
            pip_rows = np.asarray(f_pip.result(timeout=120))
        solo = np.asarray(engine.join(ppts, timeout=120))
        np.testing.assert_array_equal(pip_rows, solo)
        assert engine.metrics()["shed_deadline"] == shed_before + 1

    def test_poisoned_rows_quarantined_batchmates_exact(
        self, engine, knn_problem
    ):
        """Non-finite / out-of-domain query rows answer the sentinel
        (ids=-1, distance=inf); the request's clean rows and its
        batchmates answer exactly."""
        _, kx, qpts = knn_problem
        qb = qpts(6, seed=12)
        qb[1] = (np.nan, 3.0)
        qb[4] = (1e9, -1e9)
        clean = qpts(5, seed=13)
        fb = engine.submit_knn(qb, 3)
        fc = engine.submit_knn(clean, 3)
        ab, ac = fb.result(timeout=120), fc.result(timeout=120)
        assert np.all(ab.ids[[1, 4]] == -1)
        assert np.all(np.isinf(ab.distance[[1, 4]]))
        good = [0, 2, 3, 5]
        oids, odist = oracle(kx, qb[good], 3)
        np.testing.assert_array_equal(ab.ids[good], oids)
        assert np.array_equal(ab.distance[good], odist)
        oids, odist = oracle(kx, clean, 3)
        np.testing.assert_array_equal(ac.ids, oids)
        assert np.array_equal(ac.distance, odist)


class TestSwapAndKnobs:
    def test_hot_swap_mid_flight_serves_old_index_to_completion(
        self, pip_index, grid, knn_problem
    ):
        """A KNN request in flight when `hot_swap(knn=...)` lands answers
        from the OLD index (the dispatch snapshot); the next request
        answers from the new one."""
        _, kx, qpts = knn_problem
        rng = np.random.default_rng(21)
        polys, cx, cy = square_wkts(rng, 50)
        kx2 = build_knn_index(
            F.st_geomfromwkt(np.array(polys)), index_system=grid,
            resolution=RES,
        )
        fe2 = KNNFrontend(kx2, lane="ring", row_ladder=ROWS,
                          pair_ladder=PAIRS)
        fe2.warmup()
        fe1 = KNNFrontend(kx, lane="ring", row_ladder=ROWS,
                          pair_ladder=PAIRS)
        fe1.warmup()
        q = qpts(5, seed=33)
        with ServeEngine(
            pip_index, grid, RES, ladder=BucketLadder(64, 256),
            bounds=BBOX, max_wait_s=0.01, knn=fe1,
            default_deadline_s=120.0,
        ) as eng:
            eng.warmup()
            with faults.stalls(1.0, n=1, sites=("knn.expand",)):
                fut = eng.submit_knn(q, 2)
                time.sleep(0.15)  # let the batch enter dispatch
                eng.hot_swap(knn=fe2)
                old = fut.result(timeout=120)
            oids, odist = oracle(kx, q, 2)
            np.testing.assert_array_equal(old.ids, oids)
            assert np.array_equal(old.distance, odist)
            new = eng.join_knn(q, 2, timeout=120)
            oids2, odist2 = oracle(kx2, q, 2)
            np.testing.assert_array_equal(new.ids, oids2)
            assert np.array_equal(new.distance, odist2)
            # the two indexes genuinely disagree — the swap was observable
            assert not np.array_equal(old.distance, new.distance)

    def test_knn_lane_knob_precedence(
        self, pip_index, grid, knn_problem, monkeypatch
    ):
        """`knn_lane` resolves explicit > env > profile > default, like
        every other serve knob."""
        from mosaic_tpu.tune.recommend import TuningProfile

        _, kx, _ = knn_problem
        prof = TuningProfile(knn_lane="voronoi")

        def mk(**kw):
            eng = ServeEngine(
                pip_index, grid, RES, ladder=BucketLadder(64, 256),
                bounds=BBOX, knn=kx, **kw,
            )
            lane = eng.knn.lane
            eng.close()
            return lane

        assert mk() == "ring"  # default
        assert mk(profile=prof) == "voronoi"
        monkeypatch.setenv("MOSAIC_TUNE_KNN_LANE", "ring")
        assert mk(profile=prof) == "ring"  # env beats profile
        assert mk(profile=prof, knn_lane="voronoi") == "voronoi"  # explicit

    def test_engine_without_knn_rejects_knn_requests(
        self, pip_index, grid
    ):
        with ServeEngine(
            pip_index, grid, RES, ladder=BucketLadder(64, 256),
            bounds=BBOX,
        ) as eng:
            with pytest.raises(RuntimeError, match="no KNN frontend"):
                eng.submit_knn(np.zeros((2, 2)), 2)


class TestBatchModelCache:
    def test_pair_distance_program_is_registry_governed(self):
        """The batch model's pairwise-distance program lives in the
        dispatch cache registry (satellite of PR 19): visible in
        `cache_stats()`, cleared by `clear_caches()` — no private
        per-instance dict."""
        from mosaic_tpu.models.knn import _pair_distance_prog

        _pair_distance_prog()
        stats = _dispatch.cache_stats()
        assert stats["knn_pair_distance"]["currsize"] == 1
        _dispatch.clear_caches(names=["knn_pair_distance"])
        assert (
            _dispatch.cache_stats()["knn_pair_distance"]["currsize"] == 0
        )


class TestTuneRouting:
    def test_convex_share_routes_voronoi_with_machine_rationale(self):
        from mosaic_tpu.tune.profiler import WorkloadProfile
        from mosaic_tpu.tune.recommend import recommend

        prof = WorkloadProfile(
            kind="points", n_sampled=100, n_total=1000,
            class_shares={"light": 0.2, "heavy": 0.1, "convex": 0.7},
        )
        rec = recommend(prof, priors={})
        assert rec.knn_lane == "voronoi"
        (entry,) = [r for r in rec.rationale if r["knob"] == "knn_lane"]
        assert set(entry) == {"knob", "value", "rule", "evidence"}
        assert entry["rule"] == "convex-share-voronoi-lane"
        assert entry["evidence"]["threshold"] == pytest.approx(0.5)

    def test_measured_regression_keeps_ring_lane(self):
        from mosaic_tpu.tune.profiler import WorkloadProfile
        from mosaic_tpu.tune.recommend import recommend

        prof = WorkloadProfile(
            kind="points", n_sampled=100, n_total=1000,
            class_shares={"light": 0.1, "heavy": 0.1, "convex": 0.8},
        )
        priors = {"artifacts": {"KNN_r19.json": {
            "detail": {"voronoi_speedup_vs_ring": 0.7},
        }}}
        rec = recommend(prof, priors=priors)
        assert rec.knn_lane == "ring"
        (entry,) = [r for r in rec.rationale if r["knob"] == "knn_lane"]
        assert entry["evidence"]["voronoi_speedup_vs_ring"] == 0.7

    def test_committed_artifact_loads_as_prior(self):
        from mosaic_tpu.tune.recommend import load_priors

        priors = load_priors()
        knn = [a for a in priors["artifacts"] if a.startswith("KNN_")]
        assert knn, "KNN_r19.json must be committed and loadable"

