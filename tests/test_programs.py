"""AOT program-store contract (PR 16): serialized-executable
persistence with the checkpoint discipline — atomic payload-then-sidecar
writes, checksum + environment-fingerprint validation with typed
refusals, zero-compile reload, self-healing re-export, and bit-identical
answers under every failure path — `mosaic_tpu/dispatch/programs.py`."""

import json
import os

import numpy as np
import pytest

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.runtime import telemetry
from mosaic_tpu.serve import BucketLadder
from mosaic_tpu.dispatch import (
    DispatchCore,
    ProgramFingerprintMismatch,
    ProgramStore,
    ProgramStoreCorrupt,
    backend_fingerprint,
    program_key,
    resolve_program_store,
)
from mosaic_tpu.sql.join import build_chip_index, pip_join

BBOX = (-25.0, -25.0, 35.0, 20.0)
RES = 3


@pytest.fixture(scope="module")
def grid():
    return CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))


@pytest.fixture(scope="module")
def index(grid):
    col = wkt.from_wkt(
        [
            "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))",
            "POLYGON ((-20 -20, -5 -20, -5 -5, -20 -5, -20 -20))",
            "POLYGON ((20 -10, 30 -10, 30 5, 20 5, 20 -10))",
        ]
    )
    return build_chip_index(tessellate(col, grid, RES, keep_core_geoms=False))


LADDER = BucketLadder(64, 256)  # 3 rungs x 2 programs = 6 store entries


def make_core(index, grid, store):
    return DispatchCore(
        index, grid, RES, ladder=LADDER, program_store=store,
    )


def run_core(core, pts):
    padded, n = core.ladder.pad(pts)
    return np.asarray(core.execute_padded(padded))[:n]


@pytest.fixture()
def pts():
    rng = np.random.default_rng(11)
    return rng.uniform(BBOX[:2], BBOX[2:], (100, 2))


# ---------------------------------------------------------------- store

class TestStoreDiscipline:
    def test_roundtrip_and_keys(self, tmp_path):
        store = ProgramStore(str(tmp_path))
        store.save("abc123", b"payload-bytes", meta={"kind": "cells"})
        assert store.load("abc123") == b"payload-bytes"
        assert store.keys() == ["abc123"]

    def test_missing_is_clean_miss(self, tmp_path):
        assert ProgramStore(str(tmp_path)).load("nope") is None
        assert ProgramStore(str(tmp_path / "absent")).keys() == []

    def test_orphan_payload_is_clean_miss(self, tmp_path):
        """A payload without its sidecar is the kill-mid-export remnant:
        invisible to keys() and a miss on load — never half a program."""
        store = ProgramStore(str(tmp_path))
        (tmp_path / "prog-dead.bin").write_bytes(b"partial")
        assert store.load("dead") is None
        assert store.keys() == []

    def test_no_temp_files_survive_save(self, tmp_path):
        store = ProgramStore(str(tmp_path))
        store.save("k", b"x" * 64)
        assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]

    def test_corrupt_payload_typed_refusal(self, tmp_path):
        store = ProgramStore(str(tmp_path))
        store.save("k", b"payload")
        (tmp_path / "prog-k.bin").write_bytes(b"tampered")
        with telemetry.capture() as events:
            with pytest.raises(ProgramStoreCorrupt, match="checksum"):
                store.load("k")
        assert any(
            e.get("event") == "program_store_corrupt_skipped" for e in events
        )

    def test_corrupt_sidecar_typed_refusal(self, tmp_path):
        store = ProgramStore(str(tmp_path))
        store.save("k", b"payload")
        (tmp_path / "prog-k.json").write_text("{not json")
        with pytest.raises(ProgramStoreCorrupt, match="sidecar"):
            store.load("k")

    def test_unknown_version_typed_refusal(self, tmp_path):
        store = ProgramStore(str(tmp_path))
        path = tmp_path / "prog-k.json"
        store.save("k", b"payload")
        sidecar = json.loads(path.read_text())
        sidecar["version"] = 999
        path.write_text(json.dumps(sidecar))
        with pytest.raises(ProgramStoreCorrupt, match="version"):
            store.load("k")

    def test_env_fingerprint_mismatch_typed_refusal(self, tmp_path):
        store = ProgramStore(str(tmp_path))
        path = tmp_path / "prog-k.json"
        store.save("k", b"payload")
        sidecar = json.loads(path.read_text())
        sidecar["env"]["jax"] = "0.0.0-other"
        path.write_text(json.dumps(sidecar))
        with telemetry.capture() as events:
            with pytest.raises(ProgramFingerprintMismatch):
                store.load("k")
        assert any(
            e.get("event") == "program_store_mismatch" for e in events
        )

    def test_program_key_separates_statics(self):
        a = program_key("fp", "join", bucket=64, probe="scatter")
        b = program_key("fp", "join", bucket=128, probe="scatter")
        c = program_key("fp", "cells", bucket=64, probe="scatter")
        d = program_key("fp2", "join", bucket=64, probe="scatter")
        assert len({a, b, c, d}) == 4
        assert a == program_key("fp", "join", probe="scatter", bucket=64)

    def test_backend_fingerprint_shape(self):
        fp = backend_fingerprint()
        assert set(fp) == {"jax", "platform", "device_kind", "device_count"}

    def test_resolve_precedence(self, tmp_path, monkeypatch):
        explicit = ProgramStore(str(tmp_path))
        assert resolve_program_store(explicit) is explicit
        assert resolve_program_store(str(tmp_path)).root == str(tmp_path)
        monkeypatch.setenv("MOSAIC_PROGRAM_STORE", str(tmp_path / "env"))
        assert resolve_program_store(None).root == str(tmp_path / "env")
        monkeypatch.setenv("MOSAIC_PROGRAM_STORE", "")
        assert resolve_program_store(None) is None


# ------------------------------------------------------------- core AOT

class TestCoreAOT:
    def test_export_then_reload_bit_identical(
        self, index, grid, tmp_path, pts
    ):
        """First core exports every rung; a second core warms purely by
        loading, introduces no new executables, and answers exactly the
        batch-path reference."""
        store = str(tmp_path)
        c1 = make_core(index, grid, store)
        w1 = c1.warmup()
        assert w1["aot"] == {"loaded": 0, "exported": 6, "fallback": 0}
        assert len(ProgramStore(store).keys()) == 6

        c2 = make_core(index, grid, store)
        w2 = c2.warmup()
        assert w2["aot"] == {"loaded": 6, "exported": 0, "fallback": 0}
        assert c2.cold_compiles == 0

        ref = np.asarray(
            pip_join(pts, None, grid, RES, chip_index=index, recheck=False)
        )
        np.testing.assert_array_equal(run_core(c1, pts), ref)
        np.testing.assert_array_equal(run_core(c2, pts), ref)

    def test_corrupt_entry_self_heals(self, index, grid, tmp_path, pts):
        """One flipped payload byte: the next core records the typed
        skip, recompiles that program, re-exports it, and the store is
        clean again — answers bit-identical throughout."""
        store = str(tmp_path)
        make_core(index, grid, store).warmup()
        victim = sorted(tmp_path.glob("prog-*.bin"))[0]
        blob = bytearray(victim.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        victim.write_bytes(bytes(blob))

        with telemetry.capture() as events:
            c = make_core(index, grid, store)
            w = c.warmup()
        assert w["aot"]["loaded"] == 5 and w["aot"]["exported"] == 1
        assert any(
            e.get("event") == "program_store_corrupt_skipped" for e in events
        )
        ref = np.asarray(
            pip_join(pts, None, grid, RES, chip_index=index, recheck=False)
        )
        np.testing.assert_array_equal(run_core(c, pts), ref)

        healed = make_core(index, grid, store).warmup()
        assert healed["aot"] == {"loaded": 6, "exported": 0, "fallback": 0}

    def test_fingerprint_mismatch_falls_back(
        self, index, grid, tmp_path, pts
    ):
        """A sidecar stamped with a foreign environment is REFUSED (not
        loaded — a wrong program could crash or mis-answer) and replaced
        by a fresh compile + export."""
        store = str(tmp_path)
        make_core(index, grid, store).warmup()
        sidecar = sorted(tmp_path.glob("prog-*.json"))[0]
        doc = json.loads(sidecar.read_text())
        doc["env"]["device_count"] = 4096
        sidecar.write_text(json.dumps(doc))

        with telemetry.capture() as events:
            c = make_core(index, grid, store)
            w = c.warmup()
        assert w["aot"]["exported"] == 1
        assert any(
            e.get("event") == "program_store_mismatch" for e in events
        )
        ref = np.asarray(
            pip_join(pts, None, grid, RES, chip_index=index, recheck=False)
        )
        np.testing.assert_array_equal(run_core(c, pts), ref)

    def test_orphan_payload_reexports(self, index, grid, tmp_path):
        """Deleting a sidecar (the state a kill between payload and
        sidecar leaves) is a clean miss: the program recompiles and the
        sidecar is restored."""
        store = str(tmp_path)
        make_core(index, grid, store).warmup()
        sorted(tmp_path.glob("prog-*.json"))[0].unlink()
        w = make_core(index, grid, store).warmup()
        assert w["aot"]["loaded"] == 5 and w["aot"]["exported"] == 1
        assert len(list(tmp_path.glob("prog-*.json"))) == 6

    def test_no_store_no_aot(self, index, grid, monkeypatch):
        monkeypatch.delenv("MOSAIC_PROGRAM_STORE", raising=False)
        core = DispatchCore(index, grid, RES, ladder=LADDER)
        assert core._programs is None
        w = core.warmup()
        assert "aot" not in w


# --------------------------------------------------- epochal provenance

class TestEpochPrograms:
    """ISSUE 18 regression: the store key must fold in the index's
    EPOCH identity, not just its cell fingerprint — two epochs can
    cover the exact same cells with different chip geometry, and a
    stale program answering for the wrong epoch is silent corruption."""

    ZONES = [
        "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))",
        "POLYGON ((-20 -20, -5 -20, -5 -5, -20 -5, -20 -20))",
        "POLYGON ((20 -10, 30 -10, 30 5, 20 5, 20 -10))",
    ]
    #: zone 0 with one vertex nudged INSIDE its cells: the covered cell
    #: set is unchanged, the chip geometry is not
    ZONE0_NUDGED = "POLYGON ((1 1, 13 2.001, 12 11, 6 14, 2 9, 1 1))"

    def _epochal(self, grid):
        from mosaic_tpu.core.geometry import wkt as _wkt
        from mosaic_tpu.index import EpochalIndex

        ep = EpochalIndex(
            _wkt.from_wkt(self.ZONES), grid, RES, keep_core_geoms=False
        )
        ep.publish()
        return ep

    def test_new_epoch_same_cells_never_loads_stale(
        self, grid, tmp_path
    ):
        """Stale direction: a geometry edit that keeps the cell set
        identical still changes the program identity — the new epoch
        must export fresh programs, never load epoch-0's — and warmup
        GCs the superseded epoch's entries."""
        from mosaic_tpu.core.geometry import wkt as _wkt
        from mosaic_tpu.runtime import checkpoint

        ep = self._epochal(grid)
        idx0 = ep.index
        store = str(tmp_path)
        w0 = make_core(idx0, grid, store).warmup()
        assert w0["aot"] == {"loaded": 0, "exported": 6, "fallback": 0}
        assert w0["aot_gc"] == 0

        ep.apply(upsert=_wkt.from_wkt([self.ZONE0_NUDGED]), ids=[0])
        ep.publish()
        idx1 = ep.index
        # the collision this regression pins: same cells, new epoch
        np.testing.assert_array_equal(
            np.asarray(idx0.cells), np.asarray(idx1.cells)
        )
        assert checkpoint.index_identity(idx0) != \
            checkpoint.index_identity(idx1)

        with telemetry.capture() as events:
            w1 = make_core(idx1, grid, store).warmup()
        assert w1["aot"] == {"loaded": 0, "exported": 6, "fallback": 0}
        assert w1["aot_gc"] == 6  # epoch-0 ladder dropped
        assert len(ProgramStore(store).keys()) == 6
        assert any(
            e.get("event") == "program_store_gc" for e in events
        )

    def test_same_epoch_reload_is_stable(self, grid, tmp_path):
        """Stability direction: re-warming the SAME epoch is a pure
        load — no re-export, no GC thrash."""
        ep = self._epochal(grid)
        store = str(tmp_path)
        make_core(ep.index, grid, store).warmup()
        w = make_core(ep.index, grid, store).warmup()
        assert w["aot"] == {"loaded": 6, "exported": 0, "fallback": 0}
        assert w["aot_gc"] == 0
        assert len(ProgramStore(store).keys()) == 6

    def test_gc_spares_other_series_and_unstamped(self, grid, tmp_path):
        """gc_superseded only touches entries of the SAME series with an
        OLDER epoch: plain (unstamped) indexes and foreign series
        survive an epoch advance untouched."""
        store = ProgramStore(str(tmp_path))
        store.save("plain", b"x", meta={"kind": "cells"})
        store.save("other", b"y", meta={
            "index_series": "someoneelse", "index_epoch": 0,
        })
        store.save("mine-old", b"z", meta={
            "index_series": "s1", "index_epoch": 0,
        })
        store.save("mine-new", b"w", meta={
            "index_series": "s1", "index_epoch": 3,
        })
        assert store.gc_superseded("s1", 3) == 1
        assert store.keys() == ["mine-new", "other", "plain"]
