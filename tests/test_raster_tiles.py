"""Tile pipeline + GeoTIFF round-trip edge cases.

Covers the raster engine's staging layer: every `_DTYPES` entry through
the writer/reader pair, NaN nodata masking, multi-band band-sequential
layout, ladder snapping with pad+mask for non-divisible shapes, the
device/host pixel-center parity that the zonal oracles depend on, the
``MOSAIC_RASTER_TILE`` knob, and the typed decode-error surface.
"""

import numpy as np
import pytest

from mosaic_tpu.raster import (
    Raster,
    plan_tiles,
    read_raster,
    stack_tiles,
    tile_centers,
    write_geotiff,
)
from mosaic_tpu.raster import tiles as tiles_mod
from mosaic_tpu.raster import zonal as zonal_mod
from mosaic_tpu.runtime.errors import RasterDecodeError, is_transient


def _mk(bands=1, h=10, w=12, dtype=np.float32, nodata=-9.0, seed=3):
    rng = np.random.default_rng(seed)
    data = rng.uniform(1, 100, (bands, h, w)).astype(dtype)
    return Raster(
        data=data,
        gt=(-74.05, 0.01, 0.0, 40.78, 0.0, -0.01),
        srid=4326,
        nodata=nodata,
    )


# ---------------------------------------------------------------- round-trip


@pytest.mark.parametrize(
    "dtype",
    [np.uint8, np.uint16, np.uint32, np.int8, np.int16, np.int32,
     np.float32, np.float64],
)
def test_roundtrip_every_dtype(tmp_path, dtype):
    # full _DTYPES coverage (test_raster.py samples 5 of the 8)
    r = _mk(dtype=dtype, nodata=None)
    p = tmp_path / "t.tif"
    write_geotiff(str(p), r)
    back = read_raster(str(p))
    assert back.data.dtype == np.dtype(dtype)
    np.testing.assert_array_equal(back.data, r.data)


def test_roundtrip_nan_nodata(tmp_path):
    r = _mk(dtype=np.float32, nodata=np.nan)
    r.data[0, 2:5, 3:7] = np.nan
    p = tmp_path / "nan.tif"
    write_geotiff(str(p), r)
    back = read_raster(str(p))
    assert np.isnan(back.nodata)
    m = back.band(1).mask
    # v != NaN is vacuously True — the mask must come from isnan
    assert not m[2, 3] and m[0, 0]
    assert m.sum() == r.data.size - 12
    np.testing.assert_array_equal(
        back.data[0][m], r.data[0][~np.isnan(r.data[0])]
    )


def test_roundtrip_multiband_band_sequential(tmp_path):
    r = _mk(bands=4, h=17, w=23, dtype=np.int32, nodata=None)
    p = tmp_path / "mb.tif"
    write_geotiff(str(p), r)
    back = read_raster(str(p))
    assert back.num_bands == 4
    # planar config 2: any interleave bug scrambles bands, not pixels
    np.testing.assert_array_equal(back.data, r.data)


# ------------------------------------------------------------------- planning


def test_plan_snaps_to_ladder():
    r = _mk(h=75, w=90)
    plan = plan_tiles(r, (33, 100))
    # ladder is 32,64,128,...: 33 -> 64, 100 -> 128
    assert plan.shape == (64, 128)
    assert plan.requested == (33, 100)
    assert plan.ntiles == 2 * 1
    assert plan.pixels == 75 * 90
    assert plan.padded_pixels == 2 * 64 * 128


def test_plan_origin_order_row_major():
    r = _mk(h=70, w=70)
    plan = plan_tiles(r, (32, 32))
    assert plan.shape == (32, 32) and plan.ntiles == 3 * 3
    expect = [
        (y, x) for y in (0, 32, 64) for x in (0, 32, 64)
    ]
    np.testing.assert_array_equal(plan.origins, np.array(expect))


def test_stack_tiles_pad_and_mask():
    # 75x90 with 32x32 tiles: both axes non-divisible -> edge padding
    r = _mk(h=75, w=90, nodata=-9.0)
    r.data[0, :3, :4] = -9.0
    plan = plan_tiles(r, (32, 32))
    vals, mask = stack_tiles(r, plan)
    assert vals.shape == mask.shape == (plan.ntiles, 32, 32)
    # total valid == in-bounds minus nodata
    assert mask.sum() == 75 * 90 - 12
    # pad region of the last tile (origin (64, 64)) is masked out
    last = plan.ntiles - 1
    assert not mask[last, 75 - 64 :, :].any()
    assert not mask[last, :, 90 - 64 :].any()
    # masked-out values are zeroed (keeps NaN/nodata out of folds)
    assert (vals[~mask] == 0).all()
    # reassembly: every valid pixel round-trips exactly
    recon = np.zeros((75, 90))
    got = np.zeros((75, 90), dtype=bool)
    for i, (y0, x0) in enumerate(plan.origins):
        sub = vals[i][mask[i]]
        yy, xx = np.nonzero(mask[i])
        recon[y0 + yy, x0 + xx] = sub
        got[y0 + yy, x0 + xx] = True
    band = r.band(1)
    np.testing.assert_array_equal(got, band.mask)
    np.testing.assert_array_equal(recon[got], band.values[band.mask])


def test_stack_tiles_nan_nodata_zeroed():
    r = _mk(dtype=np.float64, nodata=np.nan)
    r.data[0, 1, 1] = np.nan
    plan = plan_tiles(r, (32, 32))
    vals, mask = stack_tiles(r, plan)
    assert not np.isnan(vals).any()
    assert not mask[0, 1, 1]


# ----------------------------------------------------------- center parity


def test_tile_centers_device_host_bit_identical():
    r = _mk(h=75, w=90)
    r.gt = (100.0, 2.0, 0.5, 50.0, -0.25, -3.0)  # skewed: exercises rx/ry
    plan = plan_tiles(r, (32, 32))
    for t in range(plan.ntiles):
        dev = np.asarray(
            tile_centers(
                np.asarray(plan.gt), plan.origins[t],
                th=plan.shape[0], tw=plan.shape[1],
            )
        )
        host = zonal_mod.host_tile_centers(plan, t)
        # bit-identical, not approx: the zonal oracle contract depends
        # on device and host agreeing on the affine evaluation exactly
        np.testing.assert_array_equal(dev, host)


def test_tile_centers_match_raster_to_world():
    r = _mk(h=40, w=40)
    plan = plan_tiles(r, (32, 32))
    dev = np.asarray(
        tile_centers(np.asarray(plan.gt), plan.origins[3], th=32, tw=32)
    )
    # origin (32, 32), first center = pixel (col 32.5, row 32.5)
    wx, wy = r.raster_to_world(32.5, 32.5)
    np.testing.assert_allclose(dev[0], [wx, wy], rtol=0, atol=0)


# -------------------------------------------------------------------- knob


def test_tile_knob(monkeypatch):
    monkeypatch.delenv("MOSAIC_RASTER_TILE", raising=False)
    assert tiles_mod.default_tile_shape() == tiles_mod.DEFAULT_TILE
    monkeypatch.setenv("MOSAIC_RASTER_TILE", "512x128")
    assert tiles_mod.default_tile_shape() == (512, 128)
    r = _mk(h=75, w=90)
    assert plan_tiles(r).shape == (512, 128)
    monkeypatch.setenv("MOSAIC_RASTER_TILE", "banana")
    with pytest.raises(ValueError, match="MOSAIC_RASTER_TILE"):
        tiles_mod.default_tile_shape()
    monkeypatch.setenv("MOSAIC_RASTER_TILE", "0x64")
    with pytest.raises(ValueError, match="MOSAIC_RASTER_TILE"):
        tiles_mod.default_tile_shape()


# ------------------------------------------------------------ decode errors


def test_decode_error_not_a_tiff(tmp_path):
    p = tmp_path / "junk.tif"
    p.write_bytes(b"this is not a tiff at all, sorry")
    with pytest.raises(RasterDecodeError) as ei:
        read_raster(str(p))
    err = ei.value
    assert err.rc == -2 and err.path == str(p)
    assert "not a TIFF" in str(err)
    assert f"native rc {err.rc}" in str(err)


def test_decode_error_missing_file(tmp_path):
    p = str(tmp_path / "nope.tif")
    with pytest.raises(RasterDecodeError) as ei:
        read_raster(p)
    assert ei.value.rc == -10  # fopen failure


def test_decode_error_truncated(tmp_path):
    # valid header, then cut the file mid-IFD
    src = tmp_path / "ok.tif"
    write_geotiff(str(src), _mk(nodata=None))
    raw = src.read_bytes()
    cut = tmp_path / "cut.tif"
    cut.write_bytes(raw[: len(raw) // 2])
    with pytest.raises(RasterDecodeError) as ei:
        read_raster(str(cut))
    assert ei.value.rc < 0


def test_decode_error_never_transient(tmp_path):
    # a corrupt file stays corrupt: retry loops must not spin on it,
    # even when the native message happens to contain a transient marker
    p = tmp_path / "junk.tif"
    p.write_bytes(b"MM garbage")
    with pytest.raises(RasterDecodeError) as ei:
        read_raster(str(p))
    assert not is_transient(ei.value)
    assert not is_transient(
        RasterDecodeError("decode timeout mid-read", path="x", rc=-11)
    )
