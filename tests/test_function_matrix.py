"""Full function-matrix sweep: every registered name x backends x grids.

Reference analog: `MosaicSpatialQueryTest.scala:18-126` runs each behavior
across geometry APIs (ESRI/JTS) x index systems (H3/BNG/Custom) x execution
modes (codegen/interpreted). Here:

- every name registered by `MosaicContext.register()` must have a spec
  (the completeness test fails when a new function lands without one);
- backend-dual functions run through BOTH the device (jnp) and oracle
  (host numpy) backends and must agree;
- grid functions run across H3 / BNG / CUSTOM index systems on
  reference-fixture-derived inputs (NYC taxi zones; translated+scaled into
  the BNG domain the way the reference pre-scales its EPSG:27700 fixtures,
  `test/package.scala:300-333`);
- results are snapshotted as scalar digests in
  `tests/goldens/function_matrix.json` (regenerate by deleting entries and
  running with MOSAIC_UPDATE_GOLDENS=1).
"""

import json
import os

import numpy as np
import pytest

import mosaic_tpu
from mosaic_tpu import expr as E
from mosaic_tpu import functions as F
from mosaic_tpu.core.index import CustomIndexSystem, GridConf, H3, BNG
from mosaic_tpu.core.geometry import wkt as W
from mosaic_tpu.raster import Raster

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "goldens", "function_matrix.json")
NYC_FIXTURE = "/root/reference/src/test/resources/NYC_Taxi_Zones.geojson"

CUSTOM = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 360, 180))

GRIDS = {"H3": (H3, 8), "BNG": (BNG, 3), "CUSTOM": (CUSTOM, 3)}


def _digest(x):
    """Deterministic scalar-ish digest of any function result."""
    if isinstance(x, Raster):
        return _digest(x.data)
    if isinstance(x, (list, tuple)):
        return [_digest(v) for v in x[:4]] + [len(x)]
    if isinstance(x, dict):
        # keys as str: goldens survive the JSON round trip
        return {str(k): _digest(v) for k, v in sorted(x.items())[:6]}
    if hasattr(x, "num_geometries"):  # PackedGeometry
        xy = np.asarray(x.xy, dtype=np.float64)
        return [
            int(x.num_geometries),
            round(float(xy.sum()), 4) if xy.size else 0.0,
        ]
    if hasattr(x, "cell_id"):  # ChipTable
        return [
            len(x),
            int(np.asarray(x.is_core).sum()),
            int(np.bitwise_xor.reduce(np.asarray(x.cell_id))),
        ]
    arr = np.asarray(x)
    if arr.dtype == object or arr.dtype.kind in "US":
        return [arr.shape[0] if arr.ndim else 1, str(arr.reshape(-1)[:2])]
    if arr.dtype.kind == "b":
        return [list(arr.shape), int(arr.sum())]
    if arr.dtype.kind in "iu":
        return [list(arr.shape), int(np.bitwise_xor.reduce(arr.reshape(-1))) if arr.size else 0]
    s = float(np.nansum(np.asarray(arr, dtype=np.float64)))
    return [list(arr.shape), round(s, 4)]


@pytest.fixture(scope="module")
def env():
    """Inputs per grid system, derived from the reference NYC fixture."""
    try:
        from mosaic_tpu.readers.vector import read_geojson

        nyc = read_geojson(NYC_FIXTURE).geometry.slice(0, 6)
    except Exception:
        nyc = W.from_wkt(
            [
                "POLYGON ((-74.02 40.70, -73.96 40.70, -73.96 40.76, -74.02 40.76, -74.02 40.70))",
                "POLYGON ((-73.96 40.70, -73.90 40.70, -73.90 40.76, -73.96 40.76, -73.96 40.70))",
            ]
        )
    out = {}
    rng = np.random.default_rng(7)
    b = nyc.bounds()
    bbox = (
        float(np.nanmin(b[:, 0])),
        float(np.nanmin(b[:, 1])),
        float(np.nanmax(b[:, 2])),
        float(np.nanmax(b[:, 3])),
    )
    pts = np.column_stack(
        [rng.uniform(bbox[0], bbox[2], 200), rng.uniform(bbox[1], bbox[3], 200)]
    )
    # BNG needs EPSG:27700-domain coordinates: translate+scale the NYC
    # shapes into the 0..700km x 0..1300km easting/northing plane (the
    # reference pre-scales its fixtures the same way)
    def to_bng(col):
        t = F.st_translate(col, -bbox[0], -bbox[1])
        return F.st_scale(
            t, 400_000.0 / (bbox[2] - bbox[0]), 400_000.0 / (bbox[3] - bbox[1])
        )

    bng_geom = to_bng(nyc)
    bng_pts = np.column_stack(
        [
            (pts[:, 0] - bbox[0]) * 400_000.0 / (bbox[2] - bbox[0]),
            (pts[:, 1] - bbox[1]) * 400_000.0 / (bbox[3] - bbox[1]),
        ]
    )
    out["H3"] = dict(geom=nyc, pts=pts)
    out["CUSTOM"] = dict(geom=nyc, pts=pts)
    out["BNG"] = dict(geom=bng_geom, pts=bng_pts)
    data = np.arange(2 * 10 * 12, dtype=np.float32).reshape(2, 10, 12)
    data[:, :2, :2] = -9.0
    out["raster"] = Raster(
        data=data,
        gt=(bbox[0], 0.01, 0.0, bbox[3], 0.0, -0.01),
        srid=4326,
        nodata=-9.0,
    )
    return out


# ---------------------------------------------------------------- geometry
BACKEND_DUAL = [
    "st_area", "st_length", "st_perimeter",
    "st_xmin", "st_xmax", "st_ymin", "st_ymax", "st_centroid3D",
]


@pytest.mark.parametrize("name", BACKEND_DUAL)
def test_backend_parity(name, env):
    """device (jnp), oracle (host) and the independent C++ second engine
    must agree (the analog of the reference's codegen-vs-interpreted AND
    JTS-vs-ESRI double equality)."""
    g = env["H3"]["geom"]
    fn = getattr(F, name)
    dev = np.asarray(fn(g, backend="device"), dtype=np.float64)
    orc = np.asarray(fn(g, backend="oracle"), dtype=np.float64)
    nat = np.asarray(fn(g, backend="native"), dtype=np.float64)
    np.testing.assert_allclose(dev, orc, rtol=2e-5, atol=1e-7)
    np.testing.assert_allclose(nat, orc, rtol=1e-11, atol=1e-12)


def _geom_specs(e):
    g = e["H3"]["geom"]
    g2 = F.st_translate(g, 0.01, 0.01)
    pts = e["H3"]["pts"]
    pt_col = F.st_point(pts[:8, 0], pts[:8, 1])
    return {
        "st_area": lambda: F.st_area(g),
        "st_length": lambda: F.st_length(g),
        "st_perimeter": lambda: F.st_perimeter(g),
        "st_centroid": lambda: F.st_centroid(g),
        "st_centroid2D": lambda: F.st_centroid2D(g),
        "st_centroid2d": lambda: F.st_centroid2d(g),
        # Z-bearing fixture: the NYC shapes are 2D, which would leave the
        # z column all-NaN and invisible to the nansum digest
        "st_centroid3D": lambda: F.st_centroid3D(
            W.from_wkt(
                ["POINT Z (1 2 3)", "LINESTRING Z (0 0 1, 2 0 5)"]
            )
        ),
        "st_centroid3d": lambda: F.st_centroid3d(g),
        "st_envelope": lambda: F.st_envelope(g),
        "st_buffer": lambda: F.st_area(F.st_buffer(g.slice(0, 2), 0.005)),
        "st_bufferloop": lambda: F.st_area(F.st_bufferloop(g.slice(0, 2), 0.002, 0.005)),
        "st_convexhull": lambda: F.st_area(F.st_convexhull(g)),
        "st_simplify": lambda: F.st_numpoints(F.st_simplify(g, 0.001)),
        "st_intersection": lambda: F.st_area(F.st_intersection(g, g2)),
        "st_union": lambda: F.st_area(F.st_union(g.slice(0, 2), g2.slice(0, 2))),
        "st_difference": lambda: F.st_area(F.st_difference(g, g2)),
        "st_symdifference": lambda: F.st_area(F.st_symdifference(g.slice(0, 2), g2.slice(0, 2))),
        "st_unaryunion": lambda: F.st_area(F.st_unaryunion(g)),
        "st_dump": lambda: F.st_dump(g),
        "flatten_polygons": lambda: F.flatten_polygons(g),
        "st_contains": lambda: F.st_contains(g, F.st_centroid(g)),
        "st_intersects": lambda: F.st_intersects(g, g2),
        "st_distance": lambda: F.st_distance(g.slice(0, 2), g2.slice(0, 2)),
        "st_geometrytype": lambda: F.st_geometrytype(g),
        "st_isvalid": lambda: F.st_isvalid(g),
        "st_numpoints": lambda: F.st_numpoints(g),
        "st_x": lambda: F.st_x(pt_col),
        "st_y": lambda: F.st_y(pt_col),
        "st_xmin": lambda: F.st_xmin(g),
        "st_xmax": lambda: F.st_xmax(g),
        "st_ymin": lambda: F.st_ymin(g),
        "st_ymax": lambda: F.st_ymax(g),
        "st_zmin": lambda: F.st_zmin(W.from_wkt(["POINT Z (1 2 3)"])),
        "st_zmax": lambda: F.st_zmax(W.from_wkt(["POINT Z (1 2 3)"])),
        "st_rotate": lambda: F.st_centroid(F.st_rotate(g, 0.5)),
        "st_scale": lambda: F.st_area(F.st_scale(g, 2.0, 3.0)),
        "st_translate": lambda: F.st_centroid(F.st_translate(g, 1.0, 2.0)),
        "st_srid": lambda: F.st_srid(g),
        "st_setsrid": lambda: F.st_srid(F.st_setsrid(g, 3857)),
        "st_transform": lambda: F.st_centroid(F.st_transform(F.st_setsrid(g, 4326), 32618)),
        "st_updatesrid": lambda: F.st_centroid(F.st_updatesrid(g, 4326, 3857)),
        "st_hasvalidcoordinates": lambda: F.st_hasvalidcoordinates(g, "EPSG:4326"),
    }


def _format_specs(e):
    g = e["H3"]["geom"].slice(0, 3)
    simple = W.from_wkt(
        ["POLYGON ((1 1, 4 1, 4 4, 1 4, 1 1))", "POINT (2 3)", "LINESTRING (0 0, 2 2)"]
    )
    pts = e["H3"]["pts"]
    return {
        "convert_to": lambda: F.convert_to(simple, "wkt"),
        "convert_to_wkt": lambda: F.convert_to_wkt(simple),
        "convert_to_wkb": lambda: [len(b) for b in F.convert_to_wkb(simple)],
        "convert_to_hex": lambda: F.convert_to_hex(simple),
        "convert_to_geojson": lambda: F.convert_to_geojson(simple),
        "convert_to_coords": lambda: F.convert_to_coords(simple),
        "as_hex": lambda: F.as_hex(simple),
        "as_json": lambda: F.as_json(simple),
        "st_astext": lambda: F.st_astext(simple),
        "st_aswkt": lambda: F.st_aswkt(simple),
        "st_asbinary": lambda: [len(b) for b in F.st_asbinary(simple)],
        "st_aswkb": lambda: [len(b) for b in F.st_aswkb(simple)],
        "st_asgeojson": lambda: F.st_asgeojson(simple),
        "st_geomfromwkt": lambda: F.st_geomfromwkt(F.st_aswkt(g)),
        "st_geomfromwkb": lambda: F.st_geomfromwkb(F.st_aswkb(g)),
        "st_geomfromgeojson": lambda: F.st_geomfromgeojson(F.st_asgeojson(g)),
        "st_point": lambda: F.st_point(pts[:5, 0], pts[:5, 1]),
        "st_makeline": lambda: F.st_makeline([pts[:4], pts[4:9]]),
        "st_makepolygon": lambda: F.st_area(
            F.st_makepolygon(W.from_wkt(["LINESTRING (0 0, 4 0, 4 4, 0 4, 0 0)"]))
        ),
        "st_polygon": lambda: F.st_area(
            F.st_polygon(W.from_wkt(["LINESTRING (0 0, 4 0, 4 4, 0 4, 0 0)"]))
        ),
        "st_union_agg": lambda: F.st_area(
            F.st_union_agg(simple.slice(0, 1), groups=np.asarray([0]))
        ),
        "try_sql": lambda: F.try_sql(
            lambda w: float(F.st_area(W.from_wkt([w]))[0]), F.st_aswkt(simple)
        ),
        "try_sql_columnar": lambda: F.try_sql_columnar(
            lambda ws: [float(a) for a in F.st_area(W.from_wkt(list(ws)))],
            F.st_aswkt(simple),
        ),
    }


def _grid_specs(e, grid_name):
    idx, res = GRIDS[grid_name]
    g = e[grid_name]["geom"]
    pts = e[grid_name]["pts"]
    cells = F.grid_pointascellid(F.st_point(pts[:, 0], pts[:, 1]), res, index=idx)
    c8 = np.asarray(cells)[:8]
    return {
        "grid_longlatascellid": lambda: F.grid_longlatascellid(
            pts[:, 0], pts[:, 1], res, index=idx
        ),
        "grid_pointascellid": lambda: cells,
        "grid_polyfill": lambda: [len(c) for c in F.grid_polyfill(g, res, index=idx)],
        "grid_tessellate": lambda: F.grid_tessellate(g, res, index=idx),
        "grid_tessellateexplode": lambda: F.grid_tessellateexplode(g, res, index=idx),
        "grid_boundary": lambda: F.grid_boundary(c8[:2], index=idx),
        "grid_boundaryaswkb": lambda: [
            len(b) for b in F.grid_boundaryaswkb(c8[:2], index=idx)
        ],
        # legacy v0.2 aliases (MosaicContext.scala:419-424): must resolve
        # to the same callables and results as their grid_ targets
        "polyfill": lambda: [len(c) for c in F.polyfill(g, res, index=idx)],
        "mosaicfill": lambda: F.mosaicfill(g, res, index=idx),
        "mosaic_explode": lambda: F.mosaic_explode(g, res, index=idx),
        "grid_tessellateaslong": lambda: F.grid_tessellateaslong(
            g, res, index=idx
        ),
        "point_index_geom": lambda: F.point_index_geom(
            F.st_point(pts[:, 0], pts[:, 1]), res, index=idx
        ),
        "point_index_lonlat": lambda: F.point_index_lonlat(
            pts[:, 0], pts[:, 1], res, index=idx
        ),
        "index_geometry": lambda: [
            len(b) for b in F.index_geometry(c8[:2], index=idx)
        ],
        "grid_cellkring": lambda: F.grid_cellkring(c8, 2, index=idx),
        "grid_cellkloop": lambda: F.grid_cellkloop(c8, 2, index=idx),
        "grid_cellkringexplode": lambda: F.grid_cellkringexplode(c8[:3], 1, index=idx),
        "grid_cellkloopexplode": lambda: F.grid_cellkloopexplode(c8[:3], 1, index=idx),
        "grid_geometrykring": lambda: [
            len(c) for c in F.grid_geometrykring(g.slice(0, 2), res, 1, index=idx)
        ],
        "grid_geometrykloop": lambda: [
            len(c) for c in F.grid_geometrykloop(g.slice(0, 2), res, 1, index=idx)
        ],
        "grid_geometrykringexplode": lambda: F.grid_geometrykringexplode(
            g.slice(0, 2), res, 1, index=idx
        ),
        "grid_geometrykloopexplode": lambda: F.grid_geometrykloopexplode(
            g.slice(0, 2), res, 1, index=idx
        ),
        "grid_distance": lambda: F.grid_distance(c8, c8[::-1].copy(), index=idx),
        "grid_cell_center": lambda: F.grid_cell_center(c8, index=idx),
        "grid_format_cellid": lambda: F.grid_format_cellid(c8[:4], index=idx),
        "grid_parse_cellid": lambda: F.grid_parse_cellid(
            F.grid_format_cellid(c8[:4], index=idx), index=idx
        ),
        "grid_resolution": lambda: F.grid_resolution(c8, index=idx),
        "grid_is_valid_cellid": lambda: F.grid_is_valid_cellid(c8, index=idx),
    }


def _raster_specs(e):
    r = e["raster"]
    col = [r]
    return {
        "rst_metadata": lambda: F.rst_metadata(col),
        "rst_bandmetadata": lambda: F.rst_bandmetadata(col, 1),
        "rst_georeference": lambda: F.rst_georeference(col),
        "rst_height": lambda: F.rst_height(col),
        "rst_width": lambda: F.rst_width(col),
        "rst_numbands": lambda: F.rst_numbands(col),
        "rst_srid": lambda: F.rst_srid(col),
        "rst_memsize": lambda: F.rst_memsize(col),
        "rst_isempty": lambda: F.rst_isempty(col),
        "rst_subdatasets": lambda: F.rst_subdatasets(col),
        "rst_summary": lambda: F.rst_summary(col),
        "rst_scalex": lambda: F.rst_scalex(col),
        "rst_scaley": lambda: F.rst_scaley(col),
        "rst_skewx": lambda: F.rst_skewx(col),
        "rst_skewy": lambda: F.rst_skewy(col),
        "rst_upperleftx": lambda: F.rst_upperleftx(col),
        "rst_upperlefty": lambda: F.rst_upperlefty(col),
        "rst_pixelwidth": lambda: F.rst_pixelwidth(col),
        "rst_pixelheight": lambda: F.rst_pixelheight(col),
        "rst_mapbands": lambda: F.rst_mapbands(
            col, E.band(1).mask_where(E.band(2) > 0.0)
        ),
        "rst_ndvi": lambda: F.rst_ndvi(col),
        "rst_rotation": lambda: F.rst_rotation(col),
        "rst_rastertoworldcoord": lambda: F.rst_rastertoworldcoord(col, 2, 3),
        "rst_rastertoworldcoordx": lambda: F.rst_rastertoworldcoordx(col, 2, 3),
        "rst_rastertoworldcoordy": lambda: F.rst_rastertoworldcoordy(col, 2, 3),
        "rst_worldtorastercoord": lambda: F.rst_worldtorastercoord(
            col, float(r.gt[0]) + 0.03, float(r.gt[3]) - 0.03
        ),
        "rst_worldtorastercoordx": lambda: F.rst_worldtorastercoordx(
            col, float(r.gt[0]) + 0.03, float(r.gt[3]) - 0.03
        ),
        "rst_worldtorastercoordy": lambda: F.rst_worldtorastercoordy(
            col, float(r.gt[0]) + 0.03, float(r.gt[3]) - 0.03
        ),
        "rst_retile": lambda: [t.data.shape for t in F.rst_retile(col, 6, 5)],
        "rst_rastertogridavg": lambda: _grid_digest(F.rst_rastertogridavg(col, 5)),
        "rst_rastertogridmin": lambda: _grid_digest(F.rst_rastertogridmin(col, 5)),
        "rst_rastertogridmax": lambda: _grid_digest(F.rst_rastertogridmax(col, 5)),
        "rst_rastertogridmedian": lambda: _grid_digest(F.rst_rastertogridmedian(col, 5)),
        "rst_rastertogridcount": lambda: _grid_digest(F.rst_rastertogridcount(col, 5)),
    }


def _agg_specs(e):
    idx, res = GRIDS["CUSTOM"]
    g = e["H3"]["geom"].slice(0, 2)
    ta = F.grid_tessellate(g, res, index=idx)
    tb = F.grid_tessellate(F.st_translate(g, 0.005, 0.005), res, index=idx)
    # join chips on shared cells (tiny two-row worked example)
    common = np.intersect1d(ta.cell_id, tb.cell_id)[:4]
    ia = [int(np.nonzero(ta.cell_id == c)[0][0]) for c in common]
    ib = [int(np.nonzero(tb.cell_id == c)[0][0]) for c in common]
    a_chips = ta.chips.take(ia)
    b_chips = tb.chips.take(ib)
    a_core = ta.is_core[ia]
    b_core = tb.is_core[ib]
    return {
        "st_intersection_aggregate": lambda: F.st_area(
            F.st_intersection_aggregate(
                idx, common, a_core, b_core, a_chips, b_chips,
                groups=np.zeros(len(common), dtype=np.int64),
            )
        ),
        "st_intersects_aggregate": lambda: F.st_intersects_aggregate(
            common, a_core, b_core, a_chips, b_chips,
            groups=np.zeros(len(common), dtype=np.int64),
        ),
        # pair-level overlay measures (digest the folded area and value
        # lanes; the trailing row stays out via an uncapped stream)
        "st_intersection_area": lambda: F.st_intersection_area(
            g, F.st_translate(g, 0.005, 0.005), idx, res
        ).area,
        "st_overlap_fraction": lambda: F.st_overlap_fraction(
            g, F.st_translate(g, 0.005, 0.005), idx, res
        ).value,
    }


def _grid_digest(mapping):
    return _digest(mapping)


def _all_specs(e):
    specs = {}
    specs.update(_geom_specs(e))
    specs.update(_format_specs(e))
    specs.update(_grid_specs(e, "H3"))  # canonical grid for the spec map
    specs.update(_raster_specs(e))
    specs.update(_agg_specs(e))
    return specs


def test_every_registered_name_has_a_spec(env):
    ctx = mosaic_tpu.MosaicContext.build("H3")
    registered = set(ctx.register())
    specs = set(_all_specs(env))
    missing = sorted(registered - specs)
    assert not missing, f"functions without a matrix spec: {missing}"


def _load_goldens():
    if os.path.exists(GOLDEN_PATH):
        with open(GOLDEN_PATH) as f:
            return json.load(f)
    return {}


@pytest.fixture(scope="module")
def goldens():
    g = _load_goldens()
    yield g
    if g.pop("_dirty", False):
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as f:
            json.dump(g, f, indent=1, sort_keys=True)


def _check_golden(goldens, key, value):
    dig = _digest(value)
    if key not in goldens or os.environ.get("MOSAIC_UPDATE_GOLDENS"):
        goldens[key] = dig
        goldens["_dirty"] = True
        return
    assert goldens[key] == dig, f"golden drift for {key}: {goldens[key]} != {dig}"


# the committed goldens were recorded against the reference checkout's
# real fixtures (NYC taxi zones + MODIS tile); without /root/reference
# the env fixture falls back to synthetic data, so every golden-keyed
# sweep drifts by construction — an environment gap, not a regression
# (PR 3 triage; regenerate with MOSAIC_UPDATE_GOLDENS=1 on a machine
# with the reference checkout to make these strict again)
_GOLDENS_NEED_REFERENCE = pytest.mark.xfail(
    condition=not os.path.exists(NYC_FIXTURE),
    reason="goldens recorded from the reference NYC/MODIS fixtures; "
    "this environment has no /root/reference checkout, so the env "
    "fixture's synthetic fallback data cannot match them",
    strict=False,
)


@_GOLDENS_NEED_REFERENCE
@pytest.mark.parametrize("grid", ["H3", "BNG", "CUSTOM"])
def test_grid_matrix(grid, env, goldens):
    """Every grid_ function runs on every index system; snapshot goldens."""
    specs = _grid_specs(env, grid)
    for name, fn in sorted(specs.items()):
        result = fn()
        _check_golden(goldens, f"{grid}/{name}", result)


@_GOLDENS_NEED_REFERENCE
def test_geometry_and_format_sweep(env, goldens):
    for name, fn in sorted({**_geom_specs(env), **_format_specs(env)}.items()):
        _check_golden(goldens, f"geom/{name}", fn())


@_GOLDENS_NEED_REFERENCE
def test_raster_and_agg_sweep(env, goldens):
    for name, fn in sorted({**_raster_specs(env), **_agg_specs(env)}.items()):
        _check_golden(goldens, f"rst/{name}", fn())


_COLLECTION_WKT = (
    "GEOMETRYCOLLECTION (POINT (-73.98 40.73), "
    "POLYGON ((-74.02 40.70, -73.96 40.70, -73.96 40.76, -74.02 40.76, "
    "-74.02 40.70)), LINESTRING (-74.0 40.7, -73.9 40.8))"
)


def test_geometry_collection_fixture(goldens):
    """Collection inputs flow through the whole function surface with the
    reference's first-polygonal semantics (MosaicGeometryJTS.scala:179-192):
    the polygon member survives, so measures/flatten/tessellate all work."""
    from mosaic_tpu.core.index import H3

    col = F.st_geomfromwkt([_COLLECTION_WKT])
    _check_golden(goldens, "geom/collection_area", F.st_area(col))
    _check_golden(goldens, "geom/collection_flatten", F.flatten_polygons(col))
    _check_golden(
        goldens, "geom/collection_tessellate",
        F.grid_tessellate(col, 7, index=H3),
    )
    # the three codecs agree on the coerced result
    via_wkb = F.st_geomfromwkb(F.st_aswkb(col))
    via_gj = F.st_geomfromgeojson(F.st_asgeojson(col))
    np.testing.assert_allclose(
        np.asarray(col.xy), np.asarray(via_wkb.xy), atol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(col.xy), np.asarray(via_gj.xy), atol=1e-9
    )
