"""Function DSL surface tests.

Mirrors the reference's matrix harness idea
(`MosaicSpatialQueryTest.scala:18-126`): every behavior runs across backends
(device jit vs host f64 oracle — the analog of codegen vs interpreted) and,
for grid functions, across the three index systems (H3, BNG, CUSTOM).
"""

import numpy as np
import pytest

from mosaic_tpu import MosaicContext
from mosaic_tpu import functions as F
from mosaic_tpu.core.index.bng import BNGIndexSystem
from mosaic_tpu.core.index.custom import CustomIndexSystem, GridConf
from mosaic_tpu.core.index.h3 import H3IndexSystem

BACKENDS = ["device", "oracle"]
SQUARE = "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"
HOLED = "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (2 2, 4 2, 4 4, 2 4, 2 2))"
LINE = "LINESTRING (0 0, 3 4)"
POINT = "POINT (1 2)"
WKTS = [SQUARE, HOLED, LINE, POINT]


def _indexes():
    return [
        H3IndexSystem(),
        BNGIndexSystem(),
        CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 360, 180)),
    ]


@pytest.fixture(autouse=True)
def _fresh_context():
    MosaicContext.reset()
    yield
    MosaicContext.reset()


# ----------------------------------------------------------------- measures


@pytest.mark.parametrize("backend", BACKENDS)
def test_measures_matrix(backend):
    area = F.st_area(WKTS, backend=backend)
    np.testing.assert_allclose(area, [16.0, 96.0, 0.0, 0.0], atol=1e-6)
    ln = F.st_length(WKTS, backend=backend)
    np.testing.assert_allclose(ln[2], 5.0, atol=1e-6)
    assert ln[0] == pytest.approx(16.0, abs=1e-6)


@pytest.mark.parametrize("backend", BACKENDS)
def test_centroid_and_bounds_matrix(backend):
    c = F.st_centroid([SQUARE], backend=backend)
    assert c[0].startswith("POINT")
    assert "2" in c[0]
    assert F.st_xmin([SQUARE], backend=backend)[0] == pytest.approx(0.0)
    assert F.st_xmax([SQUARE], backend=backend)[0] == pytest.approx(4.0)
    assert F.st_ymax([HOLED], backend=backend)[0] == pytest.approx(10.0)


def test_accessors():
    assert F.st_geometrytype(WKTS) == [
        "POLYGON", "POLYGON", "LINESTRING", "POINT",
    ]
    np.testing.assert_array_equal(F.st_numpoints([SQUARE, LINE]), [5, 2])
    assert F.st_x([POINT])[0] == 1.0 and F.st_y([POINT])[0] == 2.0
    assert F.st_isvalid(WKTS).all()
    assert not F.st_isvalid(["POLYGON ((0 0, 1 0, 0 0))"])[0]


def test_envelope_format_preserved():
    out = F.st_envelope([F.convert_to_wkb([HOLED])[0]])
    assert isinstance(out[0], bytes)  # WKB in -> WKB out
    assert F.st_area(out)[0] == pytest.approx(100.0)


# --------------------------------------------------------------- predicates


@pytest.mark.parametrize("backend", BACKENDS)
def test_predicates_matrix(backend):
    a = [SQUARE, SQUARE, HOLED]
    b = ["POINT (1 1)", "POINT (9 9)", "POINT (3 3)"]  # 3,3 is in the hole
    got = F.st_contains(a, b, backend=backend)
    np.testing.assert_array_equal(got, [True, False, False])
    inter = F.st_intersects(
        [SQUARE, SQUARE],
        ["POLYGON ((2 2, 6 2, 6 6, 2 6, 2 2))", "POLYGON ((9 9, 11 9, 11 11, 9 11, 9 9))"],
        backend=backend,
    )
    np.testing.assert_array_equal(inter, [True, False])


@pytest.mark.parametrize("backend", BACKENDS)
def test_distance_matrix(backend):
    d = F.st_distance(
        [SQUARE, SQUARE, SQUARE],
        ["POINT (7 4)", "POINT (2 2)", "POLYGON ((6 0, 8 0, 8 2, 6 2, 6 0))"],
        backend=backend,
    )
    np.testing.assert_allclose(d, [3.0, 0.0, 2.0], atol=1e-5)


# ------------------------------------------------------- host engine ops


def test_boolean_ops_and_buffer():
    inter = F.st_intersection([SQUARE], ["POLYGON ((2 2, 6 2, 6 6, 2 6, 2 2))"])
    assert F.st_area(inter, backend="oracle")[0] == pytest.approx(4.0)
    uni = F.st_union([SQUARE], ["POLYGON ((2 2, 6 2, 6 6, 2 6, 2 2))"])
    assert F.st_area(uni, backend="oracle")[0] == pytest.approx(28.0)
    buf = F.st_buffer([POINT], 1.0, quad_segs=64)
    assert F.st_area(buf, backend="oracle")[0] == pytest.approx(np.pi, rel=1e-3)
    loop = F.st_bufferloop([POINT], 0.5, 1.0)
    assert F.st_area(loop, backend="oracle")[0] == pytest.approx(
        np.pi * 0.75, rel=1e-2
    )
    hull = F.st_convexhull(["MULTIPOINT ((0 0), (2 0), (2 2), (0 2), (1 1))"])
    assert F.st_area(hull, backend="oracle")[0] == pytest.approx(4.0)


def test_dump():
    rows, parts = F.st_dump(["MULTIPOLYGON (((0 0, 1 0, 1 1, 0 1, 0 0)), ((5 5, 6 5, 6 6, 5 6, 5 5)))", POINT])
    np.testing.assert_array_equal(rows, [0, 0, 1])
    assert F.st_geometrytype(parts) == ["POLYGON", "POLYGON", "POINT"]


# ------------------------------------------------------------ affine / CRS


def test_affine_and_crs_functions():
    moved = F.st_translate([POINT], 1, 1)
    assert moved[0] == "POINT (2 3)"
    assert F.st_srid([POINT])[0] == 4326
    relab = F.st_setsrid([POINT], 27700)
    # setsrid keeps coordinates; srid readback needs packed form
    packed = F.convert_to_coords(relab)
    assert F.st_srid(packed)[0] == 4326  # WKT round-trip drops srid label
    bng = F.st_transform(F.st_geomfromwkt(["POINT (-0.1195 51.5033)"]), 27700)
    xy = bng.geom_xy(0)
    assert 500000 < xy[0, 0] < 560000
    ok = F.st_hasvalidcoordinates(["POINT (-0.5 51.6)"], "EPSG:27700", "bounds")
    assert ok[0]
    bad = F.st_hasvalidcoordinates(["POINT (-20 10)"], "EPSG:27700", "bounds")
    assert not bad[0]


# ----------------------------------------------------------------- formats


def test_conversions_roundtrip():
    wkb = F.convert_to_wkb(WKTS)
    hexes = F.convert_to_hex(WKTS)
    gj = F.convert_to_geojson(WKTS)
    back = F.convert_to_wkt(F.st_geomfromwkb(wkb))
    assert back[0].startswith("POLYGON")
    assert F.st_area(F.st_geomfromwkb(hexes), backend="oracle")[0] == 16.0
    assert F.st_area(F.st_geomfromgeojson(gj), backend="oracle")[1] == 96.0
    assert F.as_json(WKTS)[0].startswith("{")


def test_constructors():
    pts = F.st_point([1.0, 2.0], [3.0, 4.0])
    assert F.st_x(pts).tolist() == [1.0, 2.0]
    line = F.st_makeline([np.array([[0, 0], [1, 1], [2, 0]])])
    np.testing.assert_array_equal(F.st_numpoints(line), [3])
    poly = F.st_makepolygon(["LINESTRING (0 0, 1 0, 1 1, 0 1, 0 0)"])
    assert F.st_area(poly, backend="oracle")[0] == pytest.approx(1.0)


# -------------------------------------------------------------------- grid


@pytest.mark.parametrize("idx", _indexes(), ids=lambda i: i.name)
def test_grid_matrix(idx):
    res = 7 if idx.name == "H3" else (4 if idx.name == "BNG" else 3)
    lon, lat = np.array([-0.12, -1.5]), np.array([51.5, 52.7])
    if idx.name == "BNG":
        from mosaic_tpu.core import crs

        xy = crs.from_wgs84(np.stack([lon, lat], -1), 27700)
        lon, lat = xy[:, 0], xy[:, 1]
    cells = np.asarray(F.grid_longlatascellid(lon, lat, res, index=idx))
    assert cells.shape == (2,)
    assert np.asarray(F.grid_is_valid_cellid(cells, index=idx)).all()
    assert (np.asarray(F.grid_resolution(cells, index=idx)) == res).all()
    # boundary contains the generating point
    wkts = F.grid_boundary(cells, fmt="wkt", index=idx)
    got = F.st_contains(wkts, F.st_point(lon, lat), backend="oracle")
    assert got.all()
    # strings round-trip
    strs = F.grid_format_cellid(cells, index=idx)
    np.testing.assert_array_equal(F.grid_parse_cellid(strs, index=idx), cells)
    # krings
    ring = F.grid_cellkring(cells, 1, index=idx)
    loop = F.grid_cellkloop(cells, 1, index=idx)
    assert (ring >= -1).all() and ring.shape[0] == 2
    rows, vals = F.grid_cellkringexplode(cells, 1, index=idx)
    assert set(np.unique(rows)) <= {0, 1}
    d = F.grid_distance(cells, cells, index=idx)
    np.testing.assert_array_equal(d, [0, 0])
    # kloop cells are at distance exactly 1
    first_loop = loop[0][loop[0] >= 0]
    dd = F.grid_distance(
        np.full(first_loop.shape, cells[0]), first_loop, index=idx
    )
    np.testing.assert_array_equal(dd, np.ones_like(dd))


@pytest.mark.parametrize("idx", _indexes(), ids=lambda i: i.name)
def test_grid_tessellate_and_kring_matrix(idx):
    res = 7 if idx.name == "H3" else (3 if idx.name == "BNG" else 4)
    if idx.name == "BNG":
        wkt = "POLYGON ((400000 200000, 440000 200000, 440000 240000, 400000 240000, 400000 200000))"
    else:
        wkt = "POLYGON ((-0.2 51.4, 0.1 51.4, 0.1 51.6, -0.2 51.6, -0.2 51.4))"
    table = F.grid_tessellateexplode([wkt], res, index=idx)
    assert len(table) > 0
    cells, offs = F.grid_polyfill([wkt], res, index=idx)
    assert offs[-1] == cells.shape[0]
    kr = F.grid_geometrykring([wkt], res, 1, index=idx)
    kl = F.grid_geometrykloop([wkt], res, 1, index=idx)
    assert kr[0].size > kl[0].size > 0
    assert np.intersect1d(kl[0], np.unique(table.cell_id)).size == 0
    rows, vals = F.grid_geometrykringexplode([wkt], res, 1, index=idx)
    assert vals.size == kr[0].size


def test_grid_pointascellid_matches_longlat():
    a = F.grid_pointascellid(["POINT (-0.12 51.5)"], 9)
    b = np.asarray(F.grid_longlatascellid(np.array([-0.12]), np.array([51.5]), 9))
    np.testing.assert_array_equal(a, b)


# --------------------------------------------------------------- aggregates


def test_union_agg_groups():
    col = [
        "POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))",
        "POLYGON ((1 0, 3 0, 3 2, 1 2, 1 0))",
        "POLYGON ((10 10, 11 10, 11 11, 10 11, 10 10))",
    ]
    out = F.st_union_agg(col, groups=[0, 0, 1])
    areas = F.st_area(out, backend="oracle")
    np.testing.assert_allclose(areas, [6.0, 1.0], atol=1e-9)


def test_intersection_aggregate_two_squares():
    idx = H3IndexSystem()
    a = ["POLYGON ((-0.2 51.4, 0.1 51.4, 0.1 51.6, -0.2 51.6, -0.2 51.4))"]
    b = ["POLYGON ((-0.05 51.5, 0.25 51.5, 0.25 51.7, -0.05 51.7, -0.05 51.5))"]
    ta = F.grid_tessellate(a, 7, index=idx)
    tb = F.grid_tessellate(b, 7, index=idx)
    # equi-join the two chip tables on cell id
    import numpy as _np

    ia = {int(c): i for i, c in enumerate(ta.cell_id)}
    rows = [(ia[int(c)], j) for j, c in enumerate(tb.cell_id) if int(c) in ia]
    ra = [r[0] for r in rows]
    rb = [r[1] for r in rows]
    got = F.st_intersection_aggregate(
        idx,
        ta.cell_id[ra],
        ta.is_core[ra],
        tb.is_core[rb],
        ta.chips.take(ra),
        tb.chips.take(rb),
    )
    want = F.st_area(F.st_intersection(a, b), backend="oracle")[0]
    area = F.st_area(got, backend="oracle")[0]
    assert area == pytest.approx(want, rel=2e-2)
    flags = F.st_intersects_aggregate(
        ta.cell_id[ra], ta.is_core[ra], tb.is_core[rb],
        ta.chips.take(ra), tb.chips.take(rb),
    )
    assert flags[0]


def test_try_sql():
    res, err = F.try_sql(lambda w: F.st_area([w], backend="oracle")[0], [SQUARE, "NOT A WKT"])
    assert res[0] == 16.0 and res[1] is None
    assert err[0] is None and "Error" in (err[1] or "Error")


def test_try_sql_columnar_bisects_bad_rows():
    """Clean path is one vectorized call; bad rows isolate by bisection
    with the same None + error-message contract as try_sql."""
    calls = []

    def area_col(wkts):
        calls.append(len(wkts))
        return [float(a) for a in F.st_area(list(wkts), backend="oracle")]

    # all-clean: exactly one columnar call
    res, err = F.try_sql_columnar(area_col, [SQUARE, SQUARE, SQUARE])
    assert res == [16.0, 16.0, 16.0] and err == [None] * 3
    assert calls == [3]

    # two bad rows among six: every good row still evaluated, both bad
    # rows carry messages, and the call count stays logarithmic (< n+1)
    calls.clear()
    col = [SQUARE, "NOT A WKT", SQUARE, SQUARE, "POLYGON((", SQUARE]
    res, err = F.try_sql_columnar(area_col, col)
    assert [r == 16.0 for r in res] == [True, False, True, True, False, True]
    assert err[1] is not None and err[4] is not None
    assert sum(e is None for e in err) == 4

    # the bisection win shows at scale: one bad row in 4096 isolates in
    # O(log n) columnar calls, nowhere near the 4096 of per-row try_sql
    calls.clear()
    big = [SQUARE] * 4096
    big[1777] = "NOT A WKT"
    res, err = F.try_sql_columnar(area_col, big)
    assert res[1776] == 16.0 and res[1777] is None and err[1777]
    assert len(calls) <= 30

    # empty column: no calls, empty outputs
    calls.clear()
    assert F.try_sql_columnar(area_col, []) == ([], [])
    assert calls == []

    # a lazy fn defers its failure to iteration: still isolated per-row
    res, err = F.try_sql_columnar(
        lambda ws: (float(a) for a in F.st_area(list(ws), backend="oracle")),
        [SQUARE, "NOT A WKT"],
    )
    assert res == [16.0, None] and err[0] is None and err[1]

    # wrong-length output is an error, not silent row misalignment (a
    # fixed-length fn recovers by bisection down to rows where its length
    # happens to be right; an always-empty fn errors on every row)
    res, err = F.try_sql_columnar(lambda ws: [], [SQUARE, SQUARE])
    assert res == [None, None]
    assert all("columnar fn returned" in e for e in err)


def test_context_registry():
    ctx = MosaicContext.build("BNG", geometry_backend="oracle")
    assert ctx.index_system.name == "BNG"
    reg = ctx.register()
    assert "st_area" in reg and "grid_tessellate" in reg
    assert reg["st_area"]([SQUARE])[0] == pytest.approx(16.0)
    ns = ctx.functions
    assert ns.st_length([LINE])[0] == pytest.approx(5.0)
