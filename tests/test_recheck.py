"""Epsilon-band borderline recheck (SURVEY §7 precision strategy).

Reference contract: JTS evaluates `contains` in exact f64 arithmetic
(`core/geometry/MosaicGeometryJTS.scala:61-101`); the TPU fast path runs
f32. These tests pin the three layers that close the gap:

1. the cell-rounding margin (`IndexSystem.point_to_cell_margin`) flags
   EVERY point whose f32 cell differs from the f64 cell, with 2x headroom
   on the calibrated constant `sql.join.CELL_MARGIN_K`;
2. the runner-up cell (`point_to_cell_alt`) + the vertex/invalid flags
   cover the true cell for every flagged point (so only genuine result
   ties escalate to the host oracle);
3. end to end, `pip_join(recheck=True)` with f32 cell assignment equals
   the exact f64 host join everywhere.
"""

import json
import os

import jax.numpy as jnp
import numpy as np

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import BNG, H3
from mosaic_tpu.runtime import telemetry
from mosaic_tpu.sql.join import (
    CELL_MARGIN_K,
    EDGE_BAND_K,
    build_chip_index,
    host_join,
    pip_join,
)
from mosaic_tpu.core.tessellate import tessellate

EPS32 = float(np.finfo(np.float32).eps)
GOLDEN = os.path.join(
    os.path.dirname(__file__), "goldens", "recheck_margins.json"
)


def _global_points(n, seed=3):
    rng = np.random.default_rng(seed)
    lng = rng.uniform(-180, 180, n)
    lat = np.degrees(np.arcsin(rng.uniform(-0.999, 0.999, n)))
    return np.stack([lng, lat], -1)


def test_margin_covers_all_f32_disagreements():
    """Every point whose f32 cell differs from f64 must sit inside the
    epsilon band, with >= 2x headroom below CELL_MARGIN_K."""
    pts = _global_points(150_000)
    res = 9
    c64 = np.asarray(H3.point_to_cell(pts, res))  # host f64 path
    f32 = jnp.asarray(pts, dtype=jnp.float32)
    c32, m = H3.point_to_cell_margin(f32, res)
    c32, m = np.asarray(c32), np.asarray(m)
    dis = c32 != c64
    assert dis.any(), "sanity: f32 must disagree somewhere at res 9"
    worst = m[dis, 0].max() / EPS32
    assert worst <= CELL_MARGIN_K / 2, (
        f"disagreeing point at margin {worst:.2f}·eps — above the "
        f"calibrated headroom ({CELL_MARGIN_K}/2)"
    )
    # the band must stay a small minority of points (recheck cost bound)
    band = (m[:, 0] < CELL_MARGIN_K * EPS32).mean()
    assert band < 0.08, f"cell band too wide: {band:.3f}"


def test_alt_cell_covers_flagged_points():
    """For flagged points the true f64 cell is the primary or the runner-
    up — except near cell corners (margin 2 flags) or where no valid
    alternate exists (alt == -1): those escalate to the host."""
    pts = _global_points(150_000, seed=11)
    res = 9
    c64 = np.asarray(H3.point_to_cell(pts, res))
    f32 = jnp.asarray(pts, dtype=jnp.float32)
    c32, m = H3.point_to_cell_margin(f32, res)
    alt = np.asarray(H3.point_to_cell_alt(f32, res))
    c32, m = np.asarray(c32), np.asarray(m)
    km = CELL_MARGIN_K * EPS32
    flagged = m[:, 0] < km
    vertex = m[:, 1] < km
    dis = c32 != c64
    covered = ~dis | (flagged & ((alt == c64) | vertex | (alt == -1)))
    bad = np.nonzero(~covered)[0]
    assert bad.size == 0, (
        f"{bad.size} disagreements escape the band/alt/vertex cover, "
        f"e.g. point {pts[bad[0]] if bad.size else None}"
    )
    # escalation set (host recheck upper bound) stays tiny
    esc = (flagged & (vertex | (alt == -1))).mean()
    assert esc < 0.005, f"direct-host escalation too wide: {esc:.4f}"


def test_alt_cell_is_a_neighbor():
    """The runner-up is a distinct cell, and (away from face-overage
    geometry, where grid adjacency itself warps) a k-ring-1 neighbor."""
    pts = _global_points(20_000, seed=5)
    res = 7
    f32 = jnp.asarray(pts, dtype=jnp.float32)
    c32 = np.asarray(H3.point_to_cell(f32, res))
    alt = np.asarray(H3.point_to_cell_alt(f32, res))
    ok = alt >= 0
    assert (alt[ok] != c32[ok]).all()
    rings = np.asarray(H3.k_ring(jnp.asarray(c32[ok]), 1))
    neighbor_frac = (rings == alt[ok, None]).any(axis=1).mean()
    assert neighbor_frac > 0.999


def _nyc_zones():
    return wkt.from_wkt(
        [
            "POLYGON ((-74.02 40.70, -73.96 40.70, -73.96 40.76, "
            "-74.02 40.76, -74.02 40.70))",
            "POLYGON ((-73.96 40.70, -73.90 40.70, -73.90 40.76, "
            "-73.96 40.76, -73.96 40.70))",
            "POLYGON ((-74.00 40.77, -73.92 40.77, -73.92 40.80, "
            "-74.00 40.80, -74.00 40.77), (-73.97 40.78, -73.97 40.79, "
            "-73.95 40.79, -73.95 40.78, -73.97 40.78))",
        ]
    )


def test_pip_join_recheck_matches_host_oracle_exactly():
    """f32 cells + f32 probe + recheck == the exact f64 host join,
    row for row (the VERDICT r4 'discrepancies drop to 0' bar)."""
    col = _nyc_zones()
    res = 9
    rng = np.random.default_rng(2)
    pts = np.column_stack(
        [rng.uniform(-74.05, -73.87, 60_000), rng.uniform(40.68, 40.82, 60_000)]
    )
    table = tessellate(col, H3, res, keep_core_geoms=False)
    idx = build_chip_index(table)
    got = pip_join(
        pts, None, H3, res, chip_index=idx,
        recheck=True, cell_dtype=jnp.float32,
    )
    want = host_join(pts, idx.host, H3, res)
    np.testing.assert_array_equal(got, want)


def test_pip_join_recheck_off_still_close():
    """Without recheck the f32 path may differ only inside the band."""
    col = _nyc_zones()
    res = 9
    rng = np.random.default_rng(4)
    pts = np.column_stack(
        [rng.uniform(-74.05, -73.87, 40_000), rng.uniform(40.68, 40.82, 40_000)]
    )
    table = tessellate(col, H3, res, keep_core_geoms=False)
    idx = build_chip_index(table)
    got = pip_join(
        pts, None, H3, res, chip_index=idx,
        recheck=False, cell_dtype=jnp.float32,
    )
    want = host_join(pts, idx.host, H3, res)
    assert (got != want).mean() < 0.005


def test_recheck_config_flag_routes_default(monkeypatch):
    import mosaic_tpu.context as ctx

    col = _nyc_zones()
    res = 8
    rng = np.random.default_rng(6)
    pts = np.column_stack(
        [rng.uniform(-74.05, -73.87, 5_000), rng.uniform(40.68, 40.82, 5_000)]
    )
    table = tessellate(col, H3, res, keep_core_geoms=False)
    idx = build_chip_index(table)
    cfg = ctx.current_config()
    monkeypatch.setattr(
        ctx, "current_config",
        lambda: type(cfg)(**{**cfg.__dict__, "exact_recheck": True}),
    )
    got = pip_join(pts, None, H3, res, chip_index=idx, cell_dtype=jnp.float32)
    want = host_join(pts, idx.host, H3, res)
    np.testing.assert_array_equal(got, want)


def test_host_companion_round_trip():
    """HostRecheck survives an npz round-trip (bench index cache)."""
    import io

    from mosaic_tpu.sql.join import HostRecheck

    col = _nyc_zones()
    idx = build_chip_index(tessellate(col, H3, 8, keep_core_geoms=False))
    buf = io.BytesIO()
    np.savez(buf, **idx.host.save_arrays())
    buf.seek(0)
    back = HostRecheck.from_arrays(np.load(buf))
    assert back.coord_scale == idx.host.coord_scale
    np.testing.assert_array_equal(back.cells, idx.host.cells)
    np.testing.assert_array_equal(back.cell_edges, idx.host.cell_edges)


def test_bng_margin_flags_boundary_points():
    cells, m = BNG.point_to_cell_margin(
        np.array([[100000.0, 200000.0], [123456.7, 254321.9]]), 4
    )
    assert m.shape == (2, 2)
    # first point sits ON a binning boundary: zero margin
    assert m[0, 0] < 1e-12
    assert m[1, 0] > 1e-6


def test_recheck_requires_host_companion():
    import dataclasses as dc

    import pytest

    col = _nyc_zones()
    idx = build_chip_index(tessellate(col, H3, 8, keep_core_geoms=False))
    stripped = dc.replace(idx)  # fresh instance without the attribute
    rng = np.random.default_rng(1)
    pts = np.column_stack(
        [rng.uniform(-74.0, -73.9, 100), rng.uniform(40.7, 40.8, 100)]
    )
    with pytest.raises(ValueError, match="host companion"):
        pip_join(pts, None, H3, 8, chip_index=stripped, recheck=True)


def test_margin_golden_two_x_headroom():
    """The committed calibration sweep (`tools/calibrate_margins.py`)
    pins the measured drift ceiling; the shipped band constants must keep
    >= 2x headroom over it, and the golden must be regenerated whenever
    the defaults change (the tool records them)."""
    with open(GOLDEN) as f:
        g = json.load(f)
    assert g["defaults"] == {
        "CELL_MARGIN_K": CELL_MARGIN_K,
        "EDGE_BAND_K": EDGE_BAND_K,
    }, "constants changed: rerun tools/calibrate_margins.py"
    cell_max = g["cell_margin"]["max_observed_k"]
    edge_max = g["edge_band"]["max_observed_k"]
    assert cell_max > 0, "sweep found no cell disagreements — no signal"
    assert edge_max > 0, "sweep found no edge disagreements — no signal"
    assert 2 * cell_max <= CELL_MARGIN_K, (
        f"cell drift {cell_max}·eps leaves <2x headroom under "
        f"CELL_MARGIN_K={CELL_MARGIN_K}"
    )
    assert 2 * edge_max <= EDGE_BAND_K, (
        f"edge drift {edge_max}·eps·scale leaves <2x headroom under "
        f"EDGE_BAND_K={EDGE_BAND_K}"
    )


def test_margin_golden_matches_fresh_measurement():
    """A fresh (smaller) drift measurement stays under the golden's 2x-
    headroom ceiling — catches silent drift in the cell pipeline."""
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
    )
    from calibrate_margins import global_points, measure_cell_drift

    r = measure_cell_drift(H3, global_points(40_000, seed=21), 9)
    assert 2 * r["max_observed_k"] <= CELL_MARGIN_K


def test_recheck_runs_one_narrow_compacted_rejoin():
    """The recheck issue-path must be ONE band-compacted narrow re-join —
    never a full-width pass: exactly one `recheck_narrow` event per
    batch, its compacted cap strictly below the batch width, its caps
    sized to the band, and the result still exactly equal to f64."""
    col = _nyc_zones()
    res = 9
    rng = np.random.default_rng(13)
    pts = np.column_stack(
        [rng.uniform(-74.05, -73.87, 30_000),
         rng.uniform(40.68, 40.82, 30_000)]
    )
    table = tessellate(col, H3, res, keep_core_geoms=False)
    idx = build_chip_index(table)
    with telemetry.capture() as events:
        got = pip_join(
            pts, None, H3, res, chip_index=idx,
            recheck=True, cell_dtype=jnp.float32,
        )
    want = host_join(pts, idx.host, H3, res)
    np.testing.assert_array_equal(got, want)
    narrow = [e for e in events if e["event"] == "recheck_narrow"]
    assert len(narrow) == 1, narrow
    e = narrow[0]
    assert e["mode"] == "alt_rejoin"
    assert 0 < e["band"] <= e["cap"] < e["n"] == pts.shape[0]
    # the re-join is sized to the band, not the batch
    assert e["caps"][0] <= e["cap"]
    assert e["ties"] >= 0 and e["seconds"] >= 0


def test_recheck_narrow_respects_margin_override():
    """cell_margin_k=0 disables the cell band entirely (no narrow event);
    a wider band flags more points than the default."""
    col = _nyc_zones()
    res = 9
    rng = np.random.default_rng(8)
    pts = np.column_stack(
        [rng.uniform(-74.05, -73.87, 8_000),
         rng.uniform(40.68, 40.82, 8_000)]
    )
    idx = build_chip_index(tessellate(col, H3, res, keep_core_geoms=False))
    with telemetry.capture() as ev0:
        pip_join(
            pts, None, H3, res, chip_index=idx, recheck=True,
            cell_dtype=jnp.float32, cell_margin_k=0.0,
        )
    assert not [e for e in ev0 if e["event"] == "recheck_narrow"]
    with telemetry.capture() as ev_def:
        pip_join(
            pts, None, H3, res, chip_index=idx, recheck=True,
            cell_dtype=jnp.float32,
        )
    with telemetry.capture() as ev_wide:
        pip_join(
            pts, None, H3, res, chip_index=idx, recheck=True,
            cell_dtype=jnp.float32, cell_margin_k=4 * CELL_MARGIN_K,
        )
    band_def = [e for e in ev_def if e["event"] == "recheck_narrow"]
    band_wide = [e for e in ev_wide if e["event"] == "recheck_narrow"]
    assert band_def and band_wide
    assert band_wide[0]["band"] > band_def[0]["band"]


def test_pip_join_recheck_bng_no_alt_fallback():
    """BNG has margins but no alternate-rounding: the whole flagged band
    escalates to the host oracle — still exactly equal to f64."""
    from mosaic_tpu.core.tessellate import tessellate

    col = wkt.from_wkt([
        "POLYGON ((400000 200000, 440000 200000, 440000 240000, "
        "400000 240000, 400000 200000))",
        "POLYGON ((440000 200000, 480000 200000, 480000 240000, "
        "440000 240000, 440000 200000))",
    ])
    idx = build_chip_index(tessellate(col, BNG, 3, keep_core_geoms=False))
    rng = np.random.default_rng(4)
    pts = np.column_stack(
        [rng.uniform(395000, 485000, 20000), rng.uniform(195000, 245000, 20000)]
    )
    got = pip_join(
        pts, None, BNG, 3, chip_index=idx,
        recheck=True, cell_dtype=jnp.float32,
    )
    truth = host_join(pts, idx.host, BNG, 3)
    np.testing.assert_array_equal(got, truth)
