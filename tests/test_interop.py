"""Arrow / pandas interop boundary (SURVEY §7.6: mapInArrow analog)."""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

from mosaic_tpu import functions as F
from mosaic_tpu.interop import (
    from_arrow,
    from_pandas,
    map_in_arrow,
    to_arrow,
    to_pandas,
)
from mosaic_tpu.readers.vector import VectorTable, read_geojson

NYC = "/root/reference/src/test/resources/NYC_Taxi_Zones.geojson"


@pytest.fixture(scope="module")
def zones():
    try:
        t = read_geojson(NYC)
        if len(t):
            return t
    except Exception:
        pass
    from mosaic_tpu.core.geometry import wkt

    return VectorTable(
        geometry=wkt.from_wkt(
            ["POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))", "POINT (5 5)"]
        ),
        columns={"name": np.asarray(["a", "b"], dtype=object)},
    )


@pytest.mark.parametrize("fmt", ["wkb", "wkt"])
def test_arrow_roundtrip(zones, fmt):
    tbl = to_arrow(zones, geometry_format=fmt)
    assert tbl.num_rows == len(zones)
    back = from_arrow(tbl)
    a0 = np.asarray(F.st_area(zones.geometry))
    a1 = np.asarray(F.st_area(back.geometry))
    np.testing.assert_allclose(a0, a1, rtol=1e-12)
    for k, v in zones.columns.items():
        assert back.columns[k].tolist() == v.tolist()


def test_map_in_arrow_batch_pipeline(zones):
    """The exact mapInArrow contract: iterator of RecordBatches in,
    iterator of RecordBatches out — here computing per-zone H3 cover
    counts as a new attribute column."""
    from mosaic_tpu.core.index import H3

    def add_cells(vt):
        _, off = F.grid_polyfill(vt.geometry, 7, index=H3)
        cols = dict(vt.columns)
        cols["n_cells"] = np.diff(np.asarray(off))
        return VectorTable(geometry=vt.geometry, columns=cols)

    src = to_arrow(zones)
    batches = src.to_batches(max_chunksize=8)  # multiple batches
    out = list(map_in_arrow(add_cells)(batches))
    assert sum(b.num_rows for b in out) == len(zones)
    merged = pa.Table.from_batches(out)
    n = np.asarray(merged.column("n_cells").to_pylist())
    assert (n >= 0).all() and n.sum() > 0


def test_pandas_roundtrip(zones):
    df = to_pandas(zones)
    assert "geometry" in df.columns and len(df) == len(zones)
    back = from_pandas(df)
    np.testing.assert_allclose(
        np.asarray(F.st_area(zones.geometry)),
        np.asarray(F.st_area(back.geometry)),
        rtol=1e-12,
    )


def test_from_arrow_detects_geometry_column():
    from mosaic_tpu.core.geometry import wkb, wkt

    g = wkt.from_wkt(["POINT (1 2)"])
    tbl = pa.Table.from_arrays(
        [pa.array([7]), pa.array(wkb.to_wkb(g), type=pa.binary())],
        names=["id", "blob"],
    )
    vt = from_arrow(tbl)  # binary column auto-detected
    assert vt.geometry.geom_xy(0).tolist() == [[1.0, 2.0]]
    assert vt.columns["id"].tolist() == [7]
    with pytest.raises(ValueError, match="no geometry column"):
        from_arrow(pa.Table.from_arrays([pa.array([1])], names=["x"]))
