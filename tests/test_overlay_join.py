"""Polygon-polygon intersects overlay join vs the dense oracle.

Reference analog: the BNG overlay workload
(`notebooks/examples/python/BritishNationalGrid.py`) — the cell-indexed
join must reproduce exactly the pairs the O(L*R) dense `st_intersects`
matrix reports, across H3 and BNG index systems.
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index.bng import BNGIndexSystem
from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.sql.overlay import intersects_join

from fixtures import oracle_pairs as _oracle_pairs


def _squares(n, size, offx, offy, scale=1.0):
    out = []
    for i in range(n):
        x0 = offx + (i % 3) * scale
        y0 = offy + (i // 3) * scale
        out.append(
            f"POLYGON (({x0} {y0}, {x0 + size} {y0}, {x0 + size} {y0 + size},"
            f" {x0} {y0 + size}, {x0} {y0}))"
        )
    return out




@pytest.mark.parametrize("grid", ["h3", "bng"])
def test_overlay_matches_dense_oracle(grid):
    if grid == "h3":
        idx, res = H3IndexSystem(), 7
        left = wkt.from_wkt(_squares(6, 0.08, -0.02, 51.48, 0.06))
        right = wkt.from_wkt(_squares(6, 0.08, 0.01, 51.50, 0.05))
    else:
        idx, res = BNGIndexSystem(), 4
        # offsets deliberately not multiples of the cell size: a zero-area
        # touch exactly on an axis-aligned grid line tessellates into
        # disjoint cell sets (documented degenerate case in overlay.py)
        left = wkt.from_wkt(_squares(6, 4030, 530000, 180000, 3070))
        right = wkt.from_wkt(_squares(6, 4030, 531517, 181533, 2531))

    got = intersects_join(left, right, idx, res)
    want = _oracle_pairs(left, right)
    np.testing.assert_array_equal(got, want)
    assert got.shape[0] > 0  # the layout guarantees overlaps


def test_overlay_disjoint_tables():
    idx = H3IndexSystem()
    left = wkt.from_wkt(_squares(3, 0.01, 0.0, 51.0, 0.05))
    right = wkt.from_wkt(_squares(3, 0.01, 3.0, 52.0, 0.05))
    got = intersects_join(left, right, idx, 7)
    assert got.shape == (0, 2)


def test_overlay_core_shortcut_counts():
    """A small square fully inside a big one: every shared cell with a core
    chip must be accepted without predicates, and the pair reported once."""
    idx = H3IndexSystem()
    big = wkt.from_wkt(_squares(1, 0.5, 0.0, 51.0))
    small = wkt.from_wkt(_squares(1, 0.05, 0.2, 51.2))
    got = intersects_join(big, small, idx, 7)
    np.testing.assert_array_equal(got, [[0, 0]])


def test_multi_cell_pair_emitted_once():
    """Regression: a geometry pair sharing N cells must appear ONCE in
    `candidate_pairs` (the raw chip-row stream emits it N times), and a
    core chip in ANY shared cell must win over border-only cells."""
    from mosaic_tpu.core.index import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.overlay import candidate_pairs, chip_candidate_rows

    grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
    res = 3  # 1.25-degree cells
    # geometry 0: big square spanning a 4x4 cell patch (core chips inside);
    # geometry 1: thin all-border sliver sharing cells with the big square
    left = wkt.from_wkt([
        "POLYGON ((0.2 0.2, 4.8 0.2, 4.8 4.8, 0.2 4.8, 0.2 0.2))",
        "POLYGON ((0.1 5.1, 4.9 5.1, 4.9 5.4, 0.1 5.4, 0.1 5.1))",
    ])
    right = wkt.from_wkt([
        "POLYGON ((0.4 0.4, 4.6 0.4, 4.6 5.6, 0.4 5.6, 0.4 0.4))",
    ])
    lt = tessellate(left, grid, res)
    rt = tessellate(right, grid, res)

    lrows, rrows = chip_candidate_rows(lt, rt)
    raw = np.stack(
        [np.asarray(lt.geom_id)[lrows], np.asarray(rt.geom_id)[rrows]],
        axis=-1,
    )
    # the raw stream really does repeat both pairs across shared cells —
    # without that, this test would not pin the dedup at all
    assert np.count_nonzero((raw == [0, 0]).all(axis=1)) > 1
    assert np.count_nonzero((raw == [1, 0]).all(axis=1)) > 1

    lgeom, rgeom, sure = candidate_pairs(lt, rt)
    pairs = np.stack([lgeom, rgeom], axis=-1)
    np.testing.assert_array_equal(pairs, [[0, 0], [1, 0]])
    # core-beats-border: the big pair shares cells with core chips on
    # both sides; the sliver pair is border-only everywhere
    assert bool(sure[0]) and not bool(sure[1])


def test_frame_level_overlay():
    from mosaic_tpu.sql.frame import MosaicFrame

    left = MosaicFrame.from_geometry(
        wkt.from_wkt(_squares(4, 0.08, -0.02, 51.48, 0.06))
    )
    right = MosaicFrame.from_geometry(
        wkt.from_wkt(_squares(4, 0.08, 0.011, 51.503, 0.053))
    )
    pairs = left.intersects_join(right, index=H3IndexSystem(), resolution=7)
    want = _oracle_pairs(left.geometry, right.geometry)
    np.testing.assert_array_equal(pairs, want)
