"""The lint gate runs inside the suite so every environment enforces it
(reference analog: the scalastyle gate wired into the Maven build)."""

import subprocess
import sys
import os


def test_lint_gate_clean():
    root = os.path.dirname(os.path.dirname(__file__))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "lint.py")],
        capture_output=True,
        text=True,
    )
    assert r.returncode == 0, f"lint findings:\n{r.stdout}"
