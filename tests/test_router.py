"""Multi-tenant router contract (PR 16): per-tenant engines with hard
admission isolation (one tenant's overload cannot occupy another's
quota), bounded residency with LRU evict + transparent AOT-backed
revival, fault sites on the shared machinery, swap-under-load
bit-identity, and the `_CoreCache` stats surface —
`mosaic_tpu/serve/router.py` + `mosaic_tpu/dispatch/core.py`."""

import threading
import time

import numpy as np
import pytest

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.dispatch import BucketLadder, cache_stats, cache_view
from mosaic_tpu.dispatch.core import _CoreCache
from mosaic_tpu.runtime import faults
from mosaic_tpu.runtime.errors import Overloaded, TransientDeviceError
from mosaic_tpu.serve import ServeRouter, resolve_max_resident
from mosaic_tpu.sql.join import build_chip_index, pip_join

BBOX = (-25.0, -25.0, 35.0, 20.0)
RES = 3


@pytest.fixture(scope="module")
def grid():
    return CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))


def _index(grid, wkts):
    col = wkt.from_wkt(wkts)
    return build_chip_index(tessellate(col, grid, RES, keep_core_geoms=False))


@pytest.fixture(scope="module")
def index_a(grid):
    return _index(grid, [
        "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))",
        "POLYGON ((-20 -20, -5 -20, -5 -5, -20 -5, -20 -20))",
        "POLYGON ((20 -10, 30 -10, 30 5, 20 5, 20 -10))",
    ])


@pytest.fixture(scope="module")
def index_b(grid):
    # deliberately DIFFERENT coverage so swapped answers are
    # distinguishable from index_a's
    return _index(grid, [
        "POLYGON ((-24 -24, 34 -24, 34 19, -24 19, -24 -24))",
    ])


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    """One AOT program store shared by every router in this module:
    after the first tenant exports, every revival is a pure load."""
    return str(tmp_path_factory.mktemp("programs"))


def make_router(grid, store, **kw):
    kw.setdefault("program_store", store)
    kw.setdefault("engine_defaults", {
        "ladder": BucketLadder(64, 256),
        "bounds": BBOX,
        "max_wait_s": 0.01,
    })
    return ServeRouter(grid, **kw)


def rand_points(rng, n):
    return rng.uniform(BBOX[:2], BBOX[2:], (n, 2))


def ref_join(pts, grid, index):
    return np.asarray(
        pip_join(pts, None, grid, RES, chip_index=index, recheck=False)
    )


def settle(futures):
    """Drain a list of futures, swallowing sheds (the flood tests only
    care that they resolved, not how)."""
    for f in futures:
        try:
            f.result(timeout=10)
        except Overloaded:
            pass


class TestRouterBasics:
    def test_unknown_tenant_is_keyerror(self, grid, store):
        with make_router(grid, store) as router:
            with pytest.raises(KeyError, match="unknown tenant"):
                router.submit("ghost", np.zeros((4, 2)))
            with pytest.raises(KeyError):
                router.evict("ghost")
            with pytest.raises(KeyError):
                router.swap("ghost")

    def test_duplicate_tenant_rejected(self, grid, store, index_a):
        with make_router(grid, store) as router:
            router.add_tenant("acme", index_a, RES, warm=False)
            with pytest.raises(ValueError, match="already registered"):
                router.add_tenant("acme", index_a, RES, warm=False)

    def test_resolve_max_resident_precedence(self, monkeypatch):
        monkeypatch.delenv("MOSAIC_SERVE_TENANTS", raising=False)
        assert resolve_max_resident(None) == 4
        assert resolve_max_resident(2) == 2
        monkeypatch.setenv("MOSAIC_SERVE_TENANTS", "7")
        assert resolve_max_resident(None) == 7
        assert resolve_max_resident(1) == 1  # explicit beats env
        with pytest.raises(ValueError, match=">= 1"):
            resolve_max_resident(0)

    def test_closed_router_refuses(self, grid, store, index_a):
        router = make_router(grid, store)
        router.add_tenant("acme", index_a, RES, warm=False)
        router.close()
        with pytest.raises(RuntimeError, match="closed"):
            router.submit("acme", np.zeros((4, 2)))


class TestResidencyAndRevival:
    def test_lru_evict_and_transparent_revive(
        self, grid, store, index_a
    ):
        """max_resident=1: registering B evicts A; submitting to A
        revives it (evicting B) and answers bit-identically — eviction
        is invisible to correctness."""
        rng = np.random.default_rng(5)
        pts = rand_points(rng, 100)
        ref = ref_join(pts, grid, index_a)
        with make_router(grid, store, max_resident=1) as router:
            router.add_tenant("a", index_a, RES)
            router.add_tenant("b", index_a, RES)
            m = router.metrics()
            assert m["resident"] == 1 and m["evictions"] == 1
            assert not m["tenants"]["a"]["resident"]
            assert m["tenants"]["b"]["resident"]

            np.testing.assert_array_equal(router.join("a", pts), ref)
            m = router.metrics()
            assert m["tenants"]["a"]["resident"]
            assert not m["tenants"]["b"]["resident"]
            assert m["tenants"]["a"]["revivals"] == 2
            assert m["evictions"] == 2

    def test_revival_warms_from_store_not_compiler(
        self, grid, store, index_a
    ):
        """With the program store bound, a revival's warmup is an AOT
        load: zero exports, zero backend compiles (the reason bounded
        residency is cheap enough to be viable)."""
        with make_router(grid, store, max_resident=1) as router:
            router.add_tenant("a", index_a, RES)  # exports on first ever run
            stats = router.add_tenant("b", index_a, RES)  # same tessellation
            assert stats["aot"]["exported"] == 0
            assert stats["aot"]["loaded"] > 0
            assert stats.get("backend_compiles") in (0, None)

    def test_explicit_evict_keeps_registration(
        self, grid, store, index_a
    ):
        rng = np.random.default_rng(6)
        pts = rand_points(rng, 64)
        with make_router(grid, store) as router:
            router.add_tenant("a", index_a, RES)
            router.evict("a")
            assert not router.metrics()["tenants"]["a"]["resident"]
            # last-known metrics survive eviction
            assert "shed" in router.metrics()["tenants"]["a"]
            np.testing.assert_array_equal(
                router.join("a", pts), ref_join(pts, grid, index_a)
            )


class TestIsolation:
    def test_aggressor_flood_cannot_touch_victim(
        self, grid, store, index_a
    ):
        """The acceptance pin: tenant A at a many-times-over flood of
        its own tiny quota while tenant B serves sequentially — B must
        see ZERO shed (admission or deadline) and every B answer must be
        exact. Isolation is structural (separate queues), not a
        scheduling outcome."""
        rng = np.random.default_rng(7)
        flood_pts = rand_points(rng, 200)
        victim_pts = rand_points(rng, 100)
        ref = ref_join(victim_pts, grid, index_a)
        with make_router(grid, store, max_resident=2) as router:
            router.add_tenant("aggressor", index_a, RES, queue_capacity=2)
            router.add_tenant("victim", index_a, RES, queue_capacity=32)

            futures, stop = [], threading.Event()

            def flood():
                while not stop.is_set():
                    try:
                        futures.append(
                            router.submit(
                                "aggressor", flood_pts, deadline_s=0.05
                            )
                        )
                    except Overloaded:
                        pass

            th = threading.Thread(target=flood, daemon=True)  # lint: thread-context-adoption-ok (flood thread asserts only router-side counters; no telemetry/fault context needed)
            th.start()
            try:
                for _ in range(15):
                    np.testing.assert_array_equal(
                        router.join("victim", victim_pts), ref
                    )
            finally:
                stop.set()
                th.join(timeout=10)
            settle(futures)

            m = router.metrics()["tenants"]
            assert m["aggressor"]["shed_admit_router"] > 0
            assert m["victim"]["shed_admit_router"] == 0
            assert m["victim"]["shed"] == 0
            assert m["victim"]["cold_compiles"] == 0

    def test_simultaneous_overload_accounts_per_tenant(
        self, grid, store, index_a
    ):
        """Both tenants overload at once: each tenant's sheds land in
        its own ledger, matching what its own caller observed — no
        cross-tenant attribution."""
        rng = np.random.default_rng(8)
        pts = rand_points(rng, 200)
        observed = {"x": 0, "y": 0}
        reasons = set()
        with make_router(grid, store, max_resident=2) as router:
            for name in observed:
                router.add_tenant(name, index_a, RES, queue_capacity=2)

            def flood(name):
                futures = []
                for _ in range(40):
                    try:
                        futures.append(
                            router.submit(name, pts, deadline_s=0.05)
                        )
                    except Overloaded as e:
                        observed[name] += 1
                        reasons.add(e.reason)
                settle(futures)

            threads = [
                threading.Thread(target=flood, args=(n,))  # lint: thread-context-adoption-ok (per-tenant flood asserts caller-observed counts only)
                for n in observed
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)

            m = router.metrics()["tenants"]
            for name, n_observed in observed.items():
                assert n_observed > 0, f"{name} never overloaded"
                assert m[name]["shed_admit_router"] == n_observed
            assert reasons <= {"queue_full", "deadline"}


class TestSwapUnderLoad:
    def test_swap_mid_stream_is_bit_exact(
        self, grid, store, index_a, index_b
    ):
        """hot_swap through the router while submits stream: every
        answer must exactly match one of the two index snapshots (never
        a torn mix), the post-swap answer must come from the new index,
        and the swap introduces zero cold compiles."""
        rng = np.random.default_rng(9)
        pts = rand_points(rng, 100)
        ref_a = ref_join(pts, grid, index_a)
        ref_b = ref_join(pts, grid, index_b)
        assert not np.array_equal(ref_a, ref_b)  # swap must be observable

        with make_router(grid, store) as router:
            router.add_tenant("t", index_a, RES, queue_capacity=128)
            futures, stop = [], threading.Event()

            def stream():
                while not stop.is_set():
                    try:
                        futures.append(router.submit("t", pts))
                    except Overloaded:
                        pass
                    time.sleep(0.002)

            th = threading.Thread(target=stream, daemon=True)  # lint: thread-context-adoption-ok (load generator; results compared on the caller thread)
            th.start()
            try:
                time.sleep(0.05)
                stats = router.swap("t", index_b)
            finally:
                stop.set()
                th.join(timeout=10)

            assert stats["buckets"] == 3  # new core warmed every rung
            results = []
            for f in futures:
                try:
                    results.append(np.asarray(f.result(timeout=10)))
                except Overloaded:
                    pass
            assert results, "stream produced no answers"
            for r in results:
                assert (
                    np.array_equal(r, ref_a) or np.array_equal(r, ref_b)
                ), "answer matches neither snapshot — torn swap"
            np.testing.assert_array_equal(router.join("t", pts), ref_b)
            assert router.metrics()["tenants"]["t"]["cold_compiles"] == 0


class TestFaultSites:
    def test_router_admit_site_injects(self, grid, store, index_a):
        with make_router(grid, store) as router:
            router.add_tenant("a", index_a, RES)
            pts = np.zeros((4, 2))
            with faults.transient_errors(1, sites=("router.admit",)):
                with pytest.raises(TransientDeviceError):
                    router.submit("a", pts)
            router.join("a", pts)  # budget consumed; serving resumes

    def test_router_evict_site_injects(self, grid, store, index_a):
        with make_router(grid, store) as router:
            router.add_tenant("a", index_a, RES)
            with faults.transient_errors(1, sites=("router.evict",)):
                with pytest.raises(TransientDeviceError):
                    router.evict("a")
            # the failed evict left the engine resident and serving
            assert router.metrics()["tenants"]["a"]["resident"]
            router.evict("a")
            assert not router.metrics()["tenants"]["a"]["resident"]

    def test_router_swap_site_failure_keeps_old_snapshot(
        self, grid, store, index_a, index_b
    ):
        """A fault at router.swap must leave the tenant serving the OLD
        index bit-identically — swap is all-or-nothing."""
        rng = np.random.default_rng(10)
        pts = rand_points(rng, 64)
        ref_a = ref_join(pts, grid, index_a)
        with make_router(grid, store) as router:
            router.add_tenant("a", index_a, RES)
            with faults.transient_errors(1, sites=("router.swap",)):
                with pytest.raises(TransientDeviceError):
                    router.swap("a", index_b)
            np.testing.assert_array_equal(router.join("a", pts), ref_a)


# --------------------------------------------------- _CoreCache surface

class _FakeCore:
    def __init__(self, warmed):
        self.warmed = warmed


class TestCoreCache:
    def test_cold_evicted_before_warm_regardless_of_recency(self):
        c = _CoreCache(maxsize=2)
        warm, cold = _FakeCore(True), _FakeCore(False)
        c.put("warm", warm)
        c.put("cold", cold)  # most recent, but never warmed
        c.put("new", _FakeCore(True))
        assert c.get("warm") is warm
        assert c.get("cold") is None
        assert c.extra_stats()["evictions"] == 1

    def test_lru_order_among_warm(self):
        c = _CoreCache(maxsize=2)
        a, b = _FakeCore(True), _FakeCore(True)
        c.put("a", a)
        c.put("b", b)
        c.get("a")  # refresh: b becomes the LRU
        c.put("c", _FakeCore(True))
        assert c.get("b") is None
        assert c.get("a") is a

    def test_lru_cache_protocol_and_extra_stats(self):
        c = _CoreCache(maxsize=4)
        c.put("k", _FakeCore(True))
        c.get("k")
        c.get("k")
        info = c.cache_info()
        assert (info.hits, info.misses) == (2, 1)
        assert (info.maxsize, info.currsize) == (4, 1)
        assert c.extra_stats() == {"evictions": 0, "occupancy": 0.25}
        c.cache_clear()
        info = c.cache_info()
        assert (info.hits, info.misses, info.currsize) == (0, 0, 0)

    def test_registered_in_dispatch_cache_registry(self):
        """The satellite pin: batch-core residency is visible through
        the SAME stats surface as every other dispatch cache, with the
        occupancy-aware extras merged in."""
        view = cache_view("batch_cores")
        for key in (
            "hits", "misses", "maxsize", "currsize",
            "evictions", "occupancy",
        ):
            assert key in view
        stats = cache_stats(emit=False)
        assert "evictions" in stats["batch_cores"]
        assert "occupancy" in stats["batch_cores"]
